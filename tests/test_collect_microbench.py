"""Unit tests for the MICROBENCH collector's merge-preserve contract.

Counterpart of the discipline in the reference's
release/microbenchmark/run_microbenchmark.py: every benchmark program is
a first-class section, and a refresh that regenerates only some sections
must never drop the others.  (Round-4 regression: a refresh that didn't
run rl_perf.py rewrote MICROBENCH.json and silently lost the `rl`
section.)
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmarks"))

from collect_microbench import SECTIONS, merge_preserve  # noqa: E402


def test_rl_is_a_first_class_section():
    assert "rl" in SECTIONS
    assert any("rl_perf.py" in a for a in SECTIONS["rl"]["cmd"])


def test_unknown_sections_survive_a_refresh():
    prev = {
        "generated": "old",
        "host": {"cpus": 1},
        "rl": [{"metric": "rl_ppo_cartpole", "env_steps_per_s": 363.1}],
        "envelope": {"tasks_1m": {"per_s": 2185}},
        "some_future_section": {"x": 1},
    }
    out = {"generated": "new", "host": {"cpus": 1},
           "core": [{"metric": "tasks_per_s"}]}
    merge_preserve(out, prev, regenerated={"core"})
    # un-regenerated sections carried over verbatim
    assert out["rl"] == prev["rl"]
    assert out["envelope"] == prev["envelope"]
    assert out["some_future_section"] == {"x": 1}
    # regenerated + metadata keys are NOT clobbered by the old file
    assert out["generated"] == "new"
    assert out["core"] == [{"metric": "tasks_per_s"}]


def test_regenerated_section_replaces_old_value():
    prev = {"rl": [{"env_steps_per_s": 1.0}]}
    out = {"rl": [{"env_steps_per_s": 2.0}]}
    merge_preserve(out, prev, regenerated={"rl"})
    assert out["rl"] == [{"env_steps_per_s": 2.0}]


def test_partial_output_from_crashed_section_is_not_regenerated(tmp_path):
    """A benchmark that prints some rows then dies nonzero must not
    replace the previous complete numbers with a truncated set, and must
    not abort the rest of the sweep."""
    import collect_microbench as cm
    crash = tmp_path / "crash_bench.py"
    crash.write_text("print('{\"metric\": \"partial\"}')\n"
                     "raise SystemExit(1)\n")
    ok = tmp_path / "ok_bench.py"
    ok.write_text("print('{\"metric\": \"fresh\"}')\n")
    out_path = tmp_path / "mb.json"
    out_path.write_text(json.dumps(
        {"crashy": [{"metric": "complete"}], "other": 1}))
    old_sections = dict(SECTIONS)
    SECTIONS.clear()
    SECTIONS["crashy"] = dict(cmd=[sys.executable, str(crash)], timeout=30)
    SECTIONS["fine"] = dict(cmd=[sys.executable, str(ok)], timeout=30)
    try:
        sys.argv = ["collect_microbench.py", "-o", str(out_path)]
        cm.main()
    finally:
        SECTIONS.clear()
        SECTIONS.update(old_sections)
    data = json.loads(out_path.read_text())
    assert data["crashy"] == [{"metric": "complete"}]   # preserved
    assert data["fine"] == [{"metric": "fresh"}]        # sweep continued
    assert data["other"] == 1


def test_empty_rows_do_not_clobber_previous_numbers():
    """A section that exits 0 but prints no JSON must not be treated as
    regenerated — that would wipe good numbers with []."""
    prev = {"rl": [{"env_steps_per_s": 363.1}]}
    out = {}  # collector skipped adding 'rl' because rows was empty
    merge_preserve(out, prev, regenerated=set())
    assert out["rl"] == prev["rl"]


def test_only_flag_rejects_missing_script(tmp_path):
    """Explicitly requesting a section whose script doesn't exist is an
    error, not a silent no-op."""
    def script_of(spec):
        return next((a for a in spec["cmd"] if a.endswith(".py")), None)
    missing = [n for n, s in SECTIONS.items()
               if script_of(s) and not os.path.exists(script_of(s))]
    if not missing:
        return  # all scripts exist now; the guard is covered by review
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "collect_microbench.py"),
         "-o", str(tmp_path / "mb.json"), "--only", missing[0]],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode != 0
    assert "does not exist" in proc.stderr


def test_only_flag_rejects_unknown_section(tmp_path):
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "collect_microbench.py"),
         "-o", str(tmp_path / "mb.json"), "--only", "nonexistent"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode != 0
    assert "unknown sections" in proc.stderr


def test_only_refresh_preserves_other_sections_end_to_end(tmp_path):
    """Drive the real CLI with --only over a missing-script section: the
    run regenerates nothing, so every pre-existing section must survive."""
    out_path = tmp_path / "mb.json"
    seed = {"generated": "old", "rl": [{"env_steps_per_s": 363.1}],
            "envelope": {"ok": True}}
    out_path.write_text(json.dumps(seed))
    # 'vision' resolves to benchmarks/vision_perf.py; run from a cwd where
    # the script path exists or not — the collector skips missing scripts
    # and must preserve.  Use --only with an empty list: regenerates
    # nothing at all.
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "collect_microbench.py"),
         "-o", str(out_path), "--only"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr[-2000:]
    data = json.loads(out_path.read_text())
    assert data["rl"] == seed["rl"]
    assert data["envelope"] == seed["envelope"]
    assert data["generated"] != "old"
