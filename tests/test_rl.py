"""RL library tests (model: reference rllib/tests + per-algo tests)."""

import numpy as np
import pytest

from ray_tpu.rl import (CartPoleEnv, PendulumEnv, PrioritizedReplayBuffer,
                        ReplayBuffer, SampleBatch, VectorEnv, compute_gae)
from ray_tpu.rl.sample_batch import (ACTION_LOGP, ACTIONS, ADVANTAGES, EPS_ID,
                                     OBS, REWARDS, TERMINATEDS, TRUNCATEDS,
                                     VALUE_TARGETS, VF_PREDS)


def test_cartpole_env_api():
    env = CartPoleEnv()
    obs, info = env.reset(seed=0)
    assert obs.shape == (4,)
    total = 0
    for _ in range(10):
        obs, r, term, trunc, _ = env.step(env.action_space.sample(
            np.random.default_rng(0)))
        total += r
        if term or trunc:
            break
    assert total > 0


def test_vector_env_autoreset():
    vec = VectorEnv("CartPole-v1", 3, seed=0)
    obs = vec.reset()
    assert obs.shape == (3, 4)
    for _ in range(300):
        obs, r, terms, truncs, infos = vec.step([1, 1, 1])
    assert obs.shape == (3, 4)   # auto-reset keeps batch alive


def test_sample_batch_ops():
    b1 = SampleBatch({"a": np.arange(5), "b": np.ones(5)})
    b2 = SampleBatch({"a": np.arange(3), "b": np.zeros(3)})
    cat = SampleBatch.concat_samples([b1, b2])
    assert cat.count == 8
    mbs = list(cat.minibatches(4, epochs=2, seed=0))
    assert len(mbs) == 4 and all(m.count == 4 for m in mbs)


def test_gae_simple():
    batch = SampleBatch({
        REWARDS: np.array([1.0, 1.0, 1.0], np.float32),
        VF_PREDS: np.array([0.5, 0.5, 0.5], np.float32),
        TERMINATEDS: np.array([False, False, True]),
    })
    out = compute_gae(batch, gamma=0.99, lam=0.95)
    assert ADVANTAGES in out and VALUE_TARGETS in out
    # terminal step: adv = r - v = 0.5
    np.testing.assert_allclose(out[ADVANTAGES][-1], 0.5, rtol=1e-5)
    assert out[ADVANTAGES][0] > out[ADVANTAGES][-1]


def test_vtrace_on_policy_reduces_to_returns():
    """With target==behavior and rho/c uncapped effect absent, vs should
    equal discounted returns when values are zero."""
    import jax.numpy as jnp

    from ray_tpu.rl import vtrace
    T, B = 4, 2
    logp = jnp.zeros((T, B))
    rewards = jnp.ones((T, B))
    values = jnp.zeros((T, B))
    boot = jnp.zeros(B)
    discounts = jnp.full((T, B), 0.9)
    vs, pg_adv = vtrace(logp, logp, rewards, values, boot, discounts)
    expected_v0 = 1 + 0.9 * (1 + 0.9 * (1 + 0.9 * 1))
    np.testing.assert_allclose(np.asarray(vs)[0], expected_v0, rtol=1e-5)


def test_replay_buffers():
    buf = ReplayBuffer(100, seed=0)
    buf.add(SampleBatch({"x": np.arange(150)}))
    assert len(buf) == 100
    s = buf.sample(32)
    assert s.count == 32

    pbuf = PrioritizedReplayBuffer(64, seed=0)
    pbuf.add(SampleBatch({"x": np.arange(10)}))
    s = pbuf.sample(8)
    assert "weights" in s and "batch_indexes" in s
    pbuf.update_priorities(s["batch_indexes"], np.full(8, 5.0))
    s2 = pbuf.sample(8)
    assert s2.count == 8


def test_rollout_worker_local():
    from ray_tpu.rl.rollout_worker import RolloutWorker
    w = RolloutWorker("CartPole-v1", num_envs=2,
                      rollout_fragment_length=50, seed=0)
    batch = w.sample()
    assert batch.count == 100
    assert ADVANTAGES in batch and ACTION_LOGP in batch
    tm = w.sample_time_major()
    assert tm[OBS].shape == (50, 2, 4)
    assert tm["bootstrap_obs"].shape == (2, 4)
    metrics = w.get_metrics()
    assert isinstance(metrics, list)


def test_ppo_cartpole_reaches_tuned_target(ray_start_regular):
    """PPO reaches the reference's TUNED bar: episode_reward_mean >= 150
    within 100k env steps (rllib/tuned_examples/ppo/cartpole-ppo.yaml:4-7).
    The benchmarks/rl_perf.py config hits it in ~18k steps uncontended;
    the full 100k budget absorbs shared-box nondeterminism."""
    from ray_tpu.rl import PPOConfig
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=4,
                      rollout_fragment_length=125)
            .training(train_batch_size=1000, sgd_minibatch_size=250,
                      num_sgd_iter=8, lr=3e-4, entropy_coeff=0.01,
                      gamma=0.99)
            .debugging(seed=0)
            .build())
    try:
        best = -np.inf
        result = {"timesteps_total": 0}
        while result["timesteps_total"] < 100_000:
            result = algo.train()
            best = max(best, result["episode_reward_mean"])
            if best >= 150:
                break
        assert best >= 150, \
            f"tuned target missed: best={best} " \
            f"steps={result['timesteps_total']}"
        ckpt = algo.save()
        algo.restore(ckpt)
    finally:
        algo.stop()


def test_impala_cartpole_runs(ray_start_regular):
    from ray_tpu.rl import ImpalaConfig
    algo = (ImpalaConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=25)
            .training(batches_per_step=4, lr=5e-4)
            .debugging(seed=0)
            .build())
    try:
        r1 = algo.train()
        r2 = algo.train()
        assert r2["timesteps_total"] > r1["timesteps_total"] > 0
        assert "total_loss" in r2["info"]
    finally:
        algo.stop()


def test_worker_set_fault_tolerance(ray_start_regular):
    import ray_tpu
    from ray_tpu.rl.worker_set import WorkerSet
    ws = WorkerSet("CartPole-v1", num_workers=2,
                   worker_kwargs=dict(num_envs=1,
                                      rollout_fragment_length=10,
                                      gamma=0.99, lam=0.95,
                                      hidden=(32,), seed=0))
    try:
        out = ws.foreach_worker("sample")
        assert len(out) == 2
        ray_tpu.kill(ws.workers[0])
        out = ws.foreach_worker("sample", timeout=30.0)
        assert ws.num_restarts >= 1
        out = ws.foreach_worker("sample")
        assert len(out) == 2
    finally:
        ws.stop()


def test_qpolicy_epsilon_greedy():
    from ray_tpu.rl import QPolicy
    from ray_tpu.rl.env import Box, Discrete
    import numpy as np
    obs_space = Box(low=-1, high=1, shape=(4,))
    pol = QPolicy(obs_space, Discrete(2), hidden=(16,), seed=0, epsilon=1.0)
    obs = np.zeros((64, 4), np.float32)
    a, logp, q = pol.compute_actions(obs)
    assert a.shape == (64,) and set(np.unique(a)) <= {0, 1}
    # epsilon=1 -> both actions appear; epsilon=0 -> deterministic
    assert len(np.unique(a)) == 2
    pol.set_epsilon(0.0)
    a2, _, _ = pol.compute_actions(obs)
    assert len(np.unique(a2)) == 1
    with pytest.raises(ValueError):
        QPolicy(obs_space, Box(low=-1, high=1, shape=(1,)))


def test_rollout_worker_sample_transitions():
    from ray_tpu.rl import RolloutWorker
    w = RolloutWorker("CartPole-v1", num_envs=2, rollout_fragment_length=8,
                      policy="q", seed=0)
    batch = w.sample_transitions()
    import numpy as np
    from ray_tpu.rl import sample_batch as SB
    assert batch.count == 16
    assert batch[SB.NEXT_OBS].shape == batch[SB.OBS].shape
    # rows are aligned: next_obs of a non-terminal row differs from obs
    assert not np.allclose(batch[SB.OBS], batch[SB.NEXT_OBS])


def test_dqn_cartpole_learns(ray_start_regular):
    """DQN improves CartPole reward (tuned-example analog of
    /root/reference/rllib/tuned_examples/dqn/cartpole-dqn.yaml)."""
    from ray_tpu.rl import DQNConfig
    algo = (DQNConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=64)
            .training(lr=5e-4, train_batch_size=64, buffer_size=20000,
                      learning_starts=500, target_update_freq=256,
                      n_updates_per_iter=128, hidden=(64, 64),
                      epsilon_timesteps=2500)
            .debugging(seed=0)
            .build())
    try:
        first = None
        best = -1.0
        for _ in range(22):
            result = algo.train()
            r = result["episode_reward_mean"]
            import math
            if first is None and not math.isnan(r):
                first = r
            if not math.isnan(r):
                best = max(best, r)
        assert first is not None, "no episodes completed"
        assert best >= max(first + 15.0, 35.0), (first, best)
        assert result["info"]["buffer_size"] > 500
    finally:
        algo.stop()


def test_sac_policy_bounds_and_stochasticity():
    from ray_tpu.rl import SACPolicy
    from ray_tpu.rl.env import Box
    obs_space = Box(low=-1, high=1, shape=(3,))
    act_space = Box(low=-2.0, high=2.0, shape=(1,))
    pol = SACPolicy(obs_space, act_space, hidden=(16,), seed=0)
    obs = np.zeros((64, 3), np.float32)
    a, logp, _ = pol.compute_actions(obs)
    assert a.shape == (64, 1)
    assert np.all(a >= -2.0) and np.all(a <= 2.0)
    assert np.std(a) > 1e-3          # stochastic
    a2, _, _ = pol.compute_actions(obs, explore=False)
    assert np.allclose(a2, a2[0])    # deterministic mean action
    with pytest.raises(ValueError):
        from ray_tpu.rl.env import Discrete
        SACPolicy(obs_space, Discrete(2))


def test_sac_pendulum_improves(ray_start_regular):
    """SAC on Pendulum: entropy-tuned updates run and returns improve
    (tuned-example analog of rllib/tuned_examples/sac/pendulum-sac.yaml)."""
    import math

    from ray_tpu.rl import SACConfig
    # fragment 128 (not 64) and 44 iters: ~11k env steps total.  The
    # original 32x64-step budget (~4k steps) never cleared the +250
    # bar under current jax numerics — returns plateaued around -1300
    # with healthy entropy/alpha/Q dynamics, i.e. learning was real
    # but data-starved.  This budget reaches ~-860 (margin ~290) in
    # ~30s on an idle box.
    algo = (SACConfig()
            .environment("Pendulum-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=1,
                      rollout_fragment_length=128)
            .training(lr=1e-3, train_batch_size=128, buffer_size=50000,
                      learning_starts=500, n_updates_per_iter=128,
                      hidden=(64, 64))
            .debugging(seed=0)
            .build())
    try:
        rewards = []
        for _ in range(44):
            result = algo.train()
            r = result["episode_reward_mean"]
            if not math.isnan(r):
                rewards.append(r)
        assert rewards, "no episodes completed"
        # Pendulum random policy ~= -1200..-1600; learning pushes it up
        assert max(rewards[-8:]) > rewards[0] + 250, rewards
        assert np.isfinite(result["info"]["critic_loss"])
        assert result["info"]["alpha"] > 0
    finally:
        algo.stop()
