"""SPMD pipeline parallelism tests (8-dev CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import GPT, get_config
from ray_tpu.parallel import MeshConfig, build_mesh
from ray_tpu.parallel.pipeline import pipelined_lm_forward, spmd_pipeline


def test_spmd_pipeline_matches_sequential():
    mesh = build_mesh(MeshConfig(stage=4, data=2))
    n_stages, d = 4, 16
    key = jax.random.PRNGKey(0)
    ws = jax.random.normal(key, (n_stages, d, d)) / np.sqrt(d)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, d))

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    ref = x
    for i in range(n_stages):
        ref = stage_fn(ws[i], ref)

    out = jax.jit(lambda ws_, x_: spmd_pipeline(
        stage_fn, ws_, x_, mesh=mesh, n_microbatches=4))(ws, x)
    np.testing.assert_allclose(out, ref, atol=1e-5)

    # gradients flow through the pipelined loop (backward pipeline)
    g_ref = jax.grad(lambda w: sum(
        [jnp.sum(jnp.tanh(jnp.tanh(jnp.tanh(jnp.tanh(x @ w[0]) @ w[1])
                                   @ w[2]) @ w[3]))]))(ws)
    g = jax.grad(lambda w: jnp.sum(spmd_pipeline(
        stage_fn, w, x, mesh=mesh, n_microbatches=4)))(ws)
    np.testing.assert_allclose(g, g_ref, atol=1e-4)


def test_pipelined_gpt_matches_plain_forward():
    mesh = build_mesh(MeshConfig(stage=2, data=2, tensor=2))
    cfg = get_config("tiny", max_seq_len=32)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 16)),
        jnp.int32)
    model = GPT(cfg)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    ref = model.apply(variables, tokens)
    out = jax.jit(lambda v, t: pipelined_lm_forward(
        cfg, mesh, v, t, n_microbatches=4))(variables, tokens)
    np.testing.assert_allclose(out, ref, atol=2e-4)


def test_pipeline_rejects_bad_shapes():
    mesh = build_mesh(MeshConfig(stage=2, data=4))
    cfg = get_config("tiny", max_seq_len=32, n_layers=3)
    with pytest.raises(ValueError):
        pipelined_lm_forward(cfg, mesh, {"params": {}},
                             jnp.zeros((4, 8), jnp.int32), n_microbatches=2)
