"""raylint: the tier-1 gate plus red/green coverage per checker.

``test_tier1_gate_package_clean_and_fast`` IS the gate: it runs every
checker over the installed package and fails on any unallowlisted
violation, so a new violation anywhere in the tree fails the suite with
the checker's message — no new CI plumbing (docs/static_analysis.md).

The red/green tests build throwaway mini-packages (named ``ray_tpu`` so
the hardcoded plane/config module paths resolve) reproducing the
HISTORICAL bug each checker encodes — the inline-resolved-reply
deadlock (collective transport), the nested-``asyncio.run`` warmup bug,
the http_proxy executor-hop double-root, config-knob typos/rot, and
hot-path kill-switch reads — then assert the fixed shape passes.

The runtime sanitizers get direct unit coverage: a seeded A->B / B->A
lock inversion must raise naming BOTH acquisition sites, and the shm
ring protocol checker must catch a second writer and an out-of-order
ack on a real store segment.
"""

import os
import subprocess
import sys
import textwrap
import time

import pytest

from ray_tpu._private.analysis import core
from ray_tpu._private.analysis.checkers import (async_hygiene,
                                                config_knobs,
                                                executor_context,
                                                inline_handlers,
                                                killswitch)


def _mk_index(tmp_path, files):
    """Write a throwaway package named ray_tpu and index it (pure AST —
    nothing is imported, so stubs don't need to work)."""
    root = tmp_path / "ray_tpu"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return core.ProjectIndex(str(root))


def _rules(violations):
    return sorted(v.rule for v in violations)


# --------------------------------------------------------------- the gate
def test_tier1_gate_package_clean_and_fast():
    """The whole package lints clean through the default baseline, and
    fast enough to ride tier-1 (<10s is the CLI contract; typical ~2s).
    Any new violation fails HERE with the checker's full message."""
    t0 = time.monotonic()
    violations = core.run_lint()
    dt = time.monotonic() - t0
    assert not violations, "raylint violations:\n" + "\n".join(
        v.render() for v in violations)
    assert dt < 10.0, f"lint took {dt:.1f}s (budget 10s)"


# ------------------------------------------------- inline-handler purity
def test_inline_handler_checker_catches_blocking_fast_method(tmp_path):
    """The PR 6 deadlock shape: a handler registered as a fast method
    resolves its reply through a wait (ServeBoard.wait_clear) — i.e.
    blocks the connection's reader thread."""
    idx = _mk_index(tmp_path, {"fastmod.py": '''
        import threading
        from ray_tpu._private import rpc

        class Board:
            def __init__(self):
                self._ev = threading.Event()

            def wait_clear(self):
                self._ev.wait(5.0)

        class Server:
            def __init__(self):
                self._board = Board()
                self._srv = rpc.Server(self._handle,
                                       fast_methods={"take"})

            def _handle(self, conn, method, payload):
                if method == "take":
                    return self._serve_take(payload)
                raise KeyError(method)

            def _serve_take(self, p):
                self._board.wait_clear()
                return p
    '''})
    vs = inline_handlers.check(idx)
    assert any(v.rule == "inline-handler-purity"
               and "take" in v.message and "wait" in v.message
               for v in vs), vs


def test_inline_handler_checker_passes_buffer_and_notify(tmp_path):
    """The sanctioned fast-handler shape: buffer + return a Deferred
    resolved elsewhere — nothing blocking on the reader."""
    idx = _mk_index(tmp_path, {"fastmod.py": '''
        from ray_tpu._private import rpc

        class Server:
            def __init__(self):
                self._buf = []
                self._srv = rpc.Server(self._handle,
                                       fast_methods={"take"})

            def _handle(self, conn, method, payload):
                if method == "take":
                    return self._serve_take(payload)
                raise KeyError(method)

            def _serve_take(self, p):
                d = rpc.Deferred()
                self._buf.append((p, d))
                return d
    '''})
    assert inline_handlers.check(idx) == []


def test_inline_handler_checker_predicate_registration(tmp_path):
    """Predicate-style fast_methods (worker_main's shape): every string
    the predicate compares against ``method`` counts as fast and must
    resolve to a handler."""
    idx = _mk_index(tmp_path, {"wm.py": '''
        import time
        from ray_tpu._private import rpc

        class W:
            def __init__(self):
                def fast(method, payload):
                    if method == "actor_task":
                        return True
                    return False
                self._srv = rpc.Server(self._handle, fast_methods=fast)

            def _handle(self, conn, method, p):
                if method == "actor_task":
                    return self._run_actor_task(p)
                raise KeyError(method)

            def _run_actor_task(self, p):
                time.sleep(0.5)
                return p
    '''})
    vs = inline_handlers.check(idx)
    assert any("actor_task" in v.message and "time.sleep" in v.message
               for v in vs), vs


# ------------------------------------------------------ async-def hygiene
def test_async_checker_catches_blocking_and_nested_loop(tmp_path):
    """The warmup incident: blocking sleep and asyncio.run inside an
    async def (both freeze/blow up the serving loop)."""
    idx = _mk_index(tmp_path, {"serve/replica.py": '''
        import asyncio
        import time

        class R:
            async def handle(self, req):
                time.sleep(0.1)
                asyncio.run(self._other())
                return req

            async def _other(self):
                return 1
    '''})
    vs = async_hygiene.check(idx)
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 2 and "time.sleep" in msgs \
        and "nested event loop" in msgs, vs


def test_async_checker_passes_awaited_and_executor_shapes(tmp_path):
    """await asyncio.sleep / run_in_executor-shipped blocking work is
    the sanctioned pattern; a sync helper's sleep is not the loop's."""
    idx = _mk_index(tmp_path, {"serve/replica.py": '''
        import asyncio
        import time

        def _blocking_pull():
            time.sleep(0.1)

        class R:
            async def handle(self, req, loop):
                await asyncio.sleep(0.01)
                await loop.run_in_executor(None, _blocking_pull)
                return req
    '''})
    assert async_hygiene.check(idx) == []


# -------------------------------------------------- executor-hop context
_TRACING_STUB = '''
    def current_context():
        return None

    def bind_ctx(ctx, fn, *args, **kwargs):
        return fn
'''


def test_executor_hop_checker_catches_unbound_context_reader(tmp_path):
    """The http_proxy double-root bug: an executor hop (and a Thread)
    whose target reads the trace context without bind_ctx."""
    idx = _mk_index(tmp_path, {
        "util/tracing/tracing_helper.py": _TRACING_STUB,
        "serve/proxy.py": '''
        import threading
        from ray_tpu.util.tracing import tracing_helper

        class P:
            def _route(self):
                return tracing_helper.current_context()

            async def handle(self, loop):
                return await loop.run_in_executor(None, self._route)

            def spawn(self):
                threading.Thread(target=self._route).start()
    '''})
    vs = executor_context.check(idx)
    assert len(vs) == 2 and all(
        v.rule == "executor-hop-context" and "current_context" in v.message
        for v in vs), vs


def test_executor_hop_checker_passes_bind_ctx(tmp_path):
    idx = _mk_index(tmp_path, {
        "util/tracing/tracing_helper.py": _TRACING_STUB,
        "serve/proxy.py": '''
        from ray_tpu.util.tracing import tracing_helper

        class P:
            def _route(self):
                return tracing_helper.current_context()

            async def handle(self, loop, ctx):
                return await loop.run_in_executor(
                    None, tracing_helper.bind_ctx(ctx, self._route))
    '''})
    assert executor_context.check(idx) == []


# ------------------------------------------------------------ config-knob
_CONFIG_STUB = '''
    def _declare(name, type_, default, doc=""):
        pass

    _declare("used_knob", int, 1)
    _declare("dead_knob", int, 2)

    class Config:
        pass

    CONFIG = Config()
'''


def test_config_checker_catches_typo_and_dead_knob(tmp_path):
    idx = _mk_index(tmp_path, {
        "_private/config.py": _CONFIG_STUB,
        "user.py": '''
        from ray_tpu._private.config import CONFIG

        def f():
            return CONFIG.used_knob + CONFIG.hartbeat_ms
    '''})
    vs = config_knobs.check(idx)
    assert len(vs) == 2, vs
    typo = next(v for v in vs if "hartbeat_ms" in v.message)
    assert typo.symbol == "f" and "AttributeError" in typo.message
    dead = next(v for v in vs if "dead_knob" in v.message)
    assert dead.symbol == "dead_knob" and dead.path.endswith("config.py")


def test_config_checker_green_when_all_read_and_declared(tmp_path):
    idx = _mk_index(tmp_path, {
        "_private/config.py": _CONFIG_STUB,
        "user.py": '''
        from ray_tpu._private.config import CONFIG

        def f():
            return CONFIG.used_knob + getattr(CONFIG, "dead_knob")
    '''})
    assert config_knobs.check(idx) == []


# ------------------------------------------------------------ kill-switch
_RTM_STUB = '''
    def enabled():
        return True

    def counter(name, description=""):
        return None
'''


def test_killswitch_checker_catches_hot_read_and_dup_registration(
        tmp_path):
    idx = _mk_index(tmp_path, {
        "_private/runtime_metrics.py": _RTM_STUB,
        "a.py": '''
        from ray_tpu._private import runtime_metrics as rtm

        C1 = rtm.counter("ray_tpu_x_total", "x")

        def hot_path():
            if rtm.enabled():
                C1.inc()
    ''',
        "b.py": '''
        from ray_tpu._private import runtime_metrics as rtm

        C2 = rtm.counter("ray_tpu_x_total", "different description")
        D = rtm.counter("unprefixed_total", "bad namespace")
    '''})
    vs = killswitch.check(idx)
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 3, vs
    assert "generation()-keyed" in msgs
    assert "registered more than once" in msgs
    assert "lacks the ray_tpu_ prefix" in msgs


def test_killswitch_checker_passes_generation_cache(tmp_path):
    idx = _mk_index(tmp_path, {
        "_private/runtime_metrics.py": _RTM_STUB,
        "_private/config.py": _CONFIG_STUB,
        "a.py": '''
        from ray_tpu._private import runtime_metrics as rtm
        from ray_tpu._private.config import CONFIG

        C1 = rtm.counter("ray_tpu_x_total", "x")
        _cache = (-1, False)

        def _on():
            global _cache
            gen = CONFIG.generation()
            if _cache[0] != gen:
                _cache = (gen, rtm.enabled())
            return _cache[1]

        def hot_path():
            if _on():
                C1.inc()
    '''})
    assert killswitch.check(idx) == []


# ------------------------------------------------- suppression machinery
def test_inline_disable_requires_justification(tmp_path):
    files = {"serve/r.py": '''
        import time

        class R:
            async def handle(self):
                time.sleep(0.1)  # raylint: disable=async-blocking
    '''}
    root = tmp_path / "a"
    idx = _mk_index(root, files)
    vs = core.run_lint(index=idx, baseline=None)
    assert _rules(vs) == ["allowlist-format"], vs

    files = {"serve/r.py": files["serve/r.py"].replace(
        "disable=async-blocking",
        "disable=async-blocking -- simulated think time in a test stub")}
    idx = _mk_index(tmp_path / "b", files)
    assert core.run_lint(index=idx, baseline=None) == []


def test_baseline_suppresses_and_stale_entries_fail(tmp_path):
    idx = _mk_index(tmp_path, {"serve/r.py": '''
        import time

        class R:
            async def handle(self):
                time.sleep(0.1)
    '''})
    raw = core.run_lint(index=idx, baseline=None)
    assert _rules(raw) == ["async-blocking"]
    key = raw[0].key

    baseline = tmp_path / "allow.txt"
    baseline.write_text(f"{key} -- stub think time, not a real loop\n")
    assert core.run_lint(index=idx, baseline=str(baseline)) == []

    # an entry without justification is itself a violation
    baseline.write_text(f"{key}\n")
    vs = core.run_lint(index=idx, baseline=str(baseline))
    assert "allowlist-format" in _rules(vs), vs

    # a stale entry (matching nothing) fails: the baseline only shrinks
    baseline.write_text(
        f"{key} -- stub think time, not a real loop\n"
        f"async-blocking ray_tpu/gone.py::R.handle -- was removed\n")
    vs = core.run_lint(index=idx, baseline=str(baseline))
    assert _rules(vs) == ["stale-allowlist"], vs

    # ...but only against a FULL run: under --rule filtering, other
    # rules' entries legitimately match nothing this pass
    vs = core.run_lint(index=idx, baseline=str(baseline),
                       rules=["config-knob"])
    assert vs == [], vs


# ------------------------------------------------- lock-order sanitizer
def test_lock_sanitizer_catches_seeded_inversion():
    """A->B then B->A across two lock classes raises at the SECOND
    acquisition pattern — no actual deadlock needed — and the report
    names both acquisition sites."""
    from ray_tpu._private.analysis import lock_sanitizer as ls
    ls.reset()
    try:
        a = ls._DebugLock("siteA.py:10")
        b = ls._DebugLock("siteB.py:20")
        with a:
            with b:      # records A -> B
                pass
        b.acquire()
        with pytest.raises(ls.LockOrderError) as ei:
            a.acquire()  # B -> A: inversion
        msg = str(ei.value)
        assert "siteA.py:10" in msg and "siteB.py:20" in msg, msg
        # both acquire windows are named (this test file's lines)
        assert msg.count("test_static_analysis.py") >= 2, msg
        b.release()
    finally:
        ls.reset()


def test_lock_sanitizer_rlock_condition_wait_stays_truthful():
    """Condition.wait on a wrapped RLock releases/re-acquires through
    the wrapper (recursion count preserved), so held-state survives the
    wait and nested with-blocks keep working."""
    import threading

    from ray_tpu._private.analysis import lock_sanitizer as ls
    ls.reset()
    try:
        lk = ls._DebugRLock("siteR.py:1")
        cv = threading.Condition(lk)
        hits = []

        def waiter():
            with cv:
                with lk:          # nested: recursion depth 2
                    pass
                cv.wait(5.0)
                hits.append("woke")

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.1)
        with cv:
            cv.notify_all()
        t.join(10)
        assert hits == ["woke"]
        assert not ls._held_snapshot(t.ident), "held-stack leaked"
    finally:
        ls.reset()


def test_lock_sanitizer_cross_thread_release_leaves_no_phantom():
    """A plain Lock acquired on thread A and released on thread B (the
    completion-gate pattern, legal for Lock) must drop A's stack entry
    — a phantom there would spray false order edges from everything A
    acquires afterwards."""
    import threading

    from ray_tpu._private.analysis import lock_sanitizer as ls
    ls.reset()
    try:
        gate = ls._DebugLock("siteGate.py:1")
        gate.acquire()
        releaser = threading.Thread(target=gate.release)
        releaser.start()
        releaser.join(10)
        assert not ls._held_snapshot(), \
            "cross-thread release left a phantom held entry"
        # and no bogus edges from the phantom
        other = ls._DebugLock("siteOther.py:2")
        with other:
            pass
        assert not any("siteGate" in a for a, _b in ls.edges()), \
            ls.edges()
    finally:
        ls.reset()


def test_lock_sanitizer_install_gates_on_env_and_module(tmp_path,
                                                        monkeypatch):
    """install() wraps only locks created by instrumented files while
    the env gate is on; everything else gets real primitives."""
    import threading

    from ray_tpu._private.analysis import lock_sanitizer as ls
    old_prefixes = ls._prefixes
    ls.install()
    try:
        monkeypatch.setenv("RAY_TPU_DEBUG_LOCKS", "1")
        ls._prefixes = (str(tmp_path),)
        # a lock created from THIS (uninstrumented) file stays real
        assert not isinstance(threading.Lock(), ls._DebugLock)
        # code whose compile filename sits under the prefix is wrapped
        code = compile("import threading\nL = threading.Lock()\n",
                       str(tmp_path / "mod.py"), "exec")
        ns = {}
        exec(code, ns)
        assert isinstance(ns["L"], ls._DebugLock)
        # gate off: same site gets a real lock again
        monkeypatch.setenv("RAY_TPU_DEBUG_LOCKS", "0")
        ns2 = {}
        exec(code, ns2)
        assert not isinstance(ns2["L"], ls._DebugLock)
    finally:
        ls._prefixes = old_prefixes


# -------------------------------------------- channel protocol sanitizer
@pytest.fixture
def debug_channel_store(tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_DEBUG_CHANNELS", "1")
    from ray_tpu.runtime.object_store import SharedMemoryStore
    store = SharedMemoryStore.create_segment(
        str(tmp_path / "chan_store"), 4 * 1024 * 1024)
    yield store
    store.close()
    store.unlink()


def test_channel_checker_catches_second_writer_and_bad_ack(
        debug_channel_store):
    from ray_tpu._private.analysis.channel_check import \
        ChannelProtocolError
    from ray_tpu.experimental.channel import (Channel, ChannelReader,
                                              ChannelWriter,
                                              channel_object_id)
    store = debug_channel_store
    ch = Channel.create(store, channel_object_id(b"debug-ring"),
                        nslots=4, nreaders=1, capacity=4096)
    assert ch._debug, "debug gate did not reach the channel"
    w, r = ChannelWriter(ch), ChannelReader(ch, 0)
    # normal traffic stays green around the ring (slot reuse included)
    for i in range(10):
        w.write(i)
        assert r.read(timeout=5.0) == i
    # a SECOND writer instance on the same ring trips the claim word
    w2 = ChannelWriter(ch)
    with pytest.raises(ChannelProtocolError, match="second writer"):
        w2.write("intruder")
    # out-of-order ack: consume two items zero-copy, ack the second
    w.write("x")
    w.write("y")
    _view1, _f1, ack1 = r.read_zc(timeout=5.0)
    _view2, _f2, ack2 = r.read_zc(timeout=5.0)
    with pytest.raises(ChannelProtocolError, match="out-of-order"):
        ack2()
    ack1()
    ack2()  # in order now: fine
    ch.close()


# ------------------------------------------------------------------- CLI
def test_cli_lint_clean_tree_exits_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts", "lint"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_lint_violation_exits_nonzero(tmp_path):
    root = tmp_path / "ray_tpu"
    (root / "serve").mkdir(parents=True)
    (root / "serve" / "bad.py").write_text(textwrap.dedent('''
        import time

        async def handle():
            time.sleep(1)
    '''))
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts", "lint",
         "--root", str(root)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "async-blocking" in proc.stdout
