"""Memory monitor + OOM worker-killing tests (cf. reference
python/ray/tests/test_memory_pressure.py and worker_killing_policy tests).

Uses the memory_monitor_test_usage_path fault-injection seam instead of
actually exhausting host memory."""

import time

import pytest

import ray_tpu
from ray_tpu._private.memory_monitor import pick_oom_victim
from ray_tpu.exceptions import OutOfMemoryError


def test_pick_oom_victim_retriable_lifo():
    # (worker_id, is_actor, started_at, is_active)
    workers = [
        ("task-old", False, 10.0, True),
        ("task-new", False, 20.0, True),
        ("actor-new", True, 30.0, True),
        ("idle", False, 40.0, False),
    ]
    # newest *task* first, even though the actor started later
    assert pick_oom_victim(workers) == "task-new"
    # actors are last-resort victims
    assert pick_oom_victim([w for w in workers
                            if not w[0].startswith("task")]) == "actor-new"
    # nothing active -> nothing to kill
    assert pick_oom_victim([("idle", False, 1.0, False)]) is None


def test_oom_kill_retries_then_succeeds(tmp_path):
    """A task whose worker is OOM-killed retries on its OOM budget and
    succeeds once memory pressure clears."""
    usage = tmp_path / "usage.txt"
    usage.write_text("0.10")
    marker = tmp_path / "runs.txt"
    ray_tpu.init(
        num_cpus=2, object_store_memory=64 * 1024 * 1024,
        system_config={
            "memory_monitor_test_usage_path": str(usage),
            "memory_monitor_refresh_ms": 100,
            "memory_usage_threshold": 0.9,
        })

    @ray_tpu.remote(num_cpus=1, max_retries=0)
    def slow():
        with open(marker, "a") as f:
            f.write("x")
        time.sleep(3.0)
        return "done"

    ref = slow.remote()
    # wait until the task is actually running, then inject memory pressure
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not marker.exists():
        time.sleep(0.05)
    assert marker.exists()
    usage.write_text("0.99")
    time.sleep(0.6)   # monitor fires (>= one refresh period)
    usage.write_text("0.10")
    # the retry (on the OOM budget — max_retries=0!) must succeed
    assert ray_tpu.get(ref, timeout=120) == "done"
    assert marker.read_text().count("x") >= 2
    ray_tpu.shutdown()


def test_oom_budget_exhausted_raises(tmp_path):
    """Permanent memory pressure exhausts task_oom_retries and surfaces
    OutOfMemoryError (not WorkerCrashedError)."""
    usage = tmp_path / "usage.txt"
    usage.write_text("0.10")
    marker = tmp_path / "runs.txt"
    ray_tpu.init(
        num_cpus=2, object_store_memory=64 * 1024 * 1024,
        system_config={
            "memory_monitor_test_usage_path": str(usage),
            "memory_monitor_refresh_ms": 100,
            "memory_usage_threshold": 0.9,
            "task_oom_retries": 1,
        })

    @ray_tpu.remote(num_cpus=1, max_retries=0)
    def hog():
        with open(marker, "a") as f:
            f.write("x")
        time.sleep(30.0)
        return "never"

    ref = hog.remote()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not marker.exists():
        time.sleep(0.05)
    usage.write_text("0.99")  # pressure never clears
    with pytest.raises(OutOfMemoryError):
        ray_tpu.get(ref, timeout=120)
    ray_tpu.shutdown()
