"""Host (DCN) collective data-plane tests (docs/collective.md).

Multi-process groups over the real runtime: numerical correctness for
every ReduceOp against numpy at 2-4 ranks (odd world sizes, non-
divisible tensor lengths), the small-vs-large algorithm switch, the
same-node shm path moving ZERO collective bytes over TCP (telemetry-
asserted), the transfer-plane broadcast route, a rank dying
mid-allreduce surfacing a timely error on survivors, and the two
init/rendezvous races of ISSUE 6 (red before the fixes, green after).
"""

import threading
import time

import numpy as np
import pytest

import ray_tpu


# cluster shared by every in-runtime test below: per-group knobs travel
# as CONFIG overrides applied inside each rank actor, so one cluster
# serves shm/tcp/hier/store configurations alike
@pytest.fixture(scope="module")
def col_cluster():
    ray_tpu.init(num_cpus=8, object_store_memory=512 * 1024 * 1024)
    yield ray_tpu
    ray_tpu.shutdown()


def test_broadcast_store_route_multinode():
    """A multi-node group broadcasting >= the size threshold rides the
    object-transfer plane: the source puts the tensor once and remote
    ranks pull it (telemetry-marked on every rank).  Runs FIRST in this
    module, before the shared single-node cluster spins up."""
    from ray_tpu.cluster_utils import Cluster
    cluster = Cluster(head_resources={"CPU": 4, "nodeA": 2},
                      object_store_memory=256 * 1024 * 1024)
    try:
        cluster.add_node(resources={"CPU": 4, "nodeB": 2},
                         object_store_memory=256 * 1024 * 1024)
        ray_tpu.init(address=cluster.address)
        cfg = dict(_FAST_CFG, collective_bcast_store_min_bytes=256 * 1024)
        name = "bcast-store-mn"
        ranks = []
        for i in range(4):
            node_res = "nodeA" if i < 2 else "nodeB"
            ranks.append(Rank.options(resources={node_res: 1}).remote(
                4, i, name, cfg))
        nelems = 300001  # 1.2 MB float32 >= threshold
        outs = ray_tpu.get(
            [r.op.remote("broadcast", nelems, src=0) for r in ranks],
            timeout=240)
        xs = _inputs(4, nelems)
        for out in outs:
            np.testing.assert_allclose(out, xs[0], rtol=1e-6)
        for r in ranks:
            c = ray_tpu.get(
                r.metric.remote("ray_tpu_collective_bcast_store_total"),
                timeout=60)
            assert c is not None and c["{}"] >= 1.0
        # 2 nodes x 2 colocated ranks: the HIERARCHICAL allreduce
        # topology (intra-node shm reduce -> leader ring -> shm bcast)
        outs = ray_tpu.get(
            [r.op.remote("allreduce", 120001) for r in ranks],
            timeout=240)
        exp = _reduced(_inputs(4, 120001), "sum")
        for out in outs:
            np.testing.assert_allclose(out, exp, rtol=2e-5)
        labels = ray_tpu.get(ranks[0].op_labels.remote(), timeout=60)
        assert "allreduce/hier" in labels
        ray_tpu.get([r.destroy.remote() for r in ranks], timeout=60)
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        cluster.shutdown()


@ray_tpu.remote
class Rank:
    def __init__(self, world, rank, name, cfg=None):
        from ray_tpu._private.config import CONFIG
        from ray_tpu.util import collective as col
        CONFIG.update(cfg or {})
        self.col = col
        self.name = name
        self.rank = rank
        self.world = world
        col.init_collective_group(world, rank, group_name=name)

    def op(self, opname, nelems, dtype="float32", reduce_op="sum",
           src=0, dst=0, quantize=None):
        rng = np.random.RandomState(1000 + self.rank)
        x = rng.uniform(1.0, 2.0, nelems).astype(dtype)
        if opname == "allreduce":
            return self.col.allreduce(x, self.name, reduce_op,
                                      quantize=quantize)
        if opname == "reducescatter":
            return self.col.reducescatter(x, self.name, reduce_op)
        if opname == "allgather":
            return self.col.allgather(x, self.name)
        if opname == "broadcast":
            return self.col.broadcast(x, src, self.name)
        if opname == "reduce":
            return self.col.reduce(x, dst, self.name, reduce_op)
        raise ValueError(opname)

    def barrier(self):
        self.col.barrier(self.name)
        return True

    def async_overlap(self, nelems, nops, quantize=None):
        """Issue nops async allreduces, compute while they fly, fence
        with wait_all; returns per-op sums for correctness checks."""
        rng = np.random.RandomState(1000 + self.rank)
        xs = [rng.uniform(1.0, 2.0, nelems).astype("float32")
              for _ in range(nops)]
        hs = [self.col.allreduce_async(x, self.name, quantize=quantize)
              for x in xs]
        acc = 0.0  # synthetic backward: keeps the caller thread busy
        for _ in range(50):
            acc += float(np.sqrt(np.arange(20000,
                                           dtype=np.float64)).sum())
        res = self.col.wait_all(hs, timeout=120)
        return [float(r.sum()) for r in res], acc > 0

    def stub_ici(self, slice_ranks, nelems):
        """Install a fake in-graph slice reducer: it computes the exact
        slice sum from the test's deterministic per-rank inputs, so the
        schedule's host stages can be asserted skipped without jax."""
        from ray_tpu.util.collective.collective import _get
        g = _get(self.name)
        calls = []

        def fake(flat):
            calls.append(flat.size)
            return np.sum([np.random.RandomState(1000 + r)
                           .uniform(1.0, 2.0, nelems).astype("float32")
                           for r in slice_ranks], axis=0)

        g._ici_reduce = fake
        self._ici_calls = calls
        return True

    def ici_calls(self):
        return list(getattr(self, "_ici_calls", []))

    def metric(self, name):
        from ray_tpu._private import runtime_metrics as rtm
        rec = rtm.snapshot().get(name)
        if rec is None:
            return None
        return rec["values"]

    def op_labels(self):
        vals = self.metric("ray_tpu_collective_op_ms") or {}
        import json
        return sorted(json.loads(k)["op"] for k in vals)

    def destroy(self):
        self.col.destroy_collective_group(self.name)
        return True


def _inputs(world, nelems, dtype="float32"):
    return [np.random.RandomState(1000 + r).uniform(1.0, 2.0, nelems)
            .astype(dtype) for r in range(world)]


def _reduced(xs, reduce_op):
    red = {"sum": np.add, "product": np.multiply, "min": np.minimum,
           "max": np.maximum}[reduce_op]
    acc = xs[0].copy()
    for x in xs[1:]:
        acc = red(acc, x)
    return acc


def _chunk_bounds(nelem, m):
    base, rem = divmod(nelem, m)
    bounds, off = [], 0
    for k in range(m):
        sz = base + (1 if k < rem else 0)
        bounds.append((off, off + sz))
        off += sz
    return bounds


# tiny thresholds so modest tensors exercise the segmented ring and the
# rd/ring switch without multi-MB traffic per op
_FAST_CFG = {
    "collective_chunk_bytes": 64 * 1024,
    "collective_small_max_bytes": 1024,
    "collective_inflight_segments": 3,
}


def _spawn(world, name, cfg):
    return [Rank.remote(world, r, name, cfg) for r in range(world)]


def _teardown(ranks):
    ray_tpu.get([r.destroy.remote() for r in ranks], timeout=60)
    for r in ranks:
        ray_tpu.kill(r)


@pytest.mark.parametrize("world", [3])
def test_collective_numerics(col_cluster, world):
    """Every op x every ReduceOp vs numpy, small (recursive-doubling)
    and large (segmented ring / flat-arena shm) payloads, odd world
    size and non-divisible lengths included.  world=3 (odd) is the
    interesting case — even worlds are exercised by the zero-TCP (4),
    death/stale (2) and multinode (4) tests, keeping tier-1 wall cost
    down."""
    name = f"num-{world}"
    ranks = _spawn(world, name, _FAST_CFG)
    try:
        for reduce_op in ("sum", "product", "min", "max"):
            # every ReduceOp on the small (rd) path; the two
            # interesting ufunc shapes (accumulating / comparing) on
            # the large path — tier-1 wall budget
            sizes = (7, 100001) if reduce_op in ("sum", "max") else (7,)
            for nelems in sizes:
                xs = _inputs(world, nelems)
                exp = _reduced(xs, reduce_op)
                outs = ray_tpu.get(
                    [r.op.remote("allreduce", nelems,
                                 reduce_op=reduce_op) for r in ranks],
                    timeout=180)
                for out in outs:
                    np.testing.assert_allclose(out, exp, rtol=2e-5)
        # reducescatter: rank r owns chunk r of the reduced tensor
        nelems = 90001
        xs = _inputs(world, nelems)
        exp = _reduced(xs, "sum")
        outs = ray_tpu.get(
            [r.op.remote("reducescatter", nelems) for r in ranks],
            timeout=180)
        for r, (a, b) in enumerate(_chunk_bounds(nelems, world)):
            np.testing.assert_allclose(outs[r], exp[a:b], rtol=2e-5)
        # allgather
        outs = ray_tpu.get(
            [r.op.remote("allgather", 50001) for r in ranks],
            timeout=180)
        xs = _inputs(world, 50001)
        for parts in outs:
            assert len(parts) == world
            for r, part in enumerate(parts):
                np.testing.assert_allclose(part, xs[r], rtol=1e-6)
        # ring broadcast from a non-zero source + chunked star reduce
        outs = ray_tpu.get(
            [r.op.remote("broadcast", 70001, src=world - 1)
             for r in ranks], timeout=180)
        xs = _inputs(world, 70001)
        for out in outs:
            np.testing.assert_allclose(out, xs[world - 1], rtol=1e-6)
        outs = ray_tpu.get(
            [r.op.remote("reduce", 60001, dst=1) for r in ranks],
            timeout=180)
        np.testing.assert_allclose(outs[1], _reduced(_inputs(world, 60001),
                                                     "sum"), rtol=2e-5)
        # both algorithm regimes actually ran (small -> recursive
        # doubling; large -> flat shm arena on this single-node group)
        labels = ray_tpu.get(ranks[0].op_labels.remote(), timeout=60)
        assert "allreduce/rd" in labels
        assert any(lbl in labels
                   for lbl in ("allreduce/ring", "allreduce/hier",
                               "allreduce/flatshm"))
    finally:
        _teardown(ranks)
    # the segmented shm RING allreduce path, explicitly (the flat
    # arena normally shadows it on single-node groups)
    ranks = _spawn(world, f"numring-{world}",
                   dict(_FAST_CFG, collective_flat_shm=False,
                        collective_hierarchical=False))
    try:
        nelems = 100001
        outs = ray_tpu.get(
            [r.op.remote("allreduce", nelems, reduce_op="max")
             for r in ranks], timeout=180)
        exp = _reduced(_inputs(world, nelems), "max")
        for out in outs:
            np.testing.assert_allclose(out, exp, rtol=2e-5)
        labels = ray_tpu.get(ranks[0].op_labels.remote(), timeout=60)
        assert "allreduce/ring" in labels
    finally:
        _teardown(ranks)


def test_collective_same_node_zero_tcp_bytes(col_cluster):
    """A same-node-only group exchanges every segment over shm: the TCP
    byte counter stays at exactly zero on every rank while the shm
    counter moves (the ISSUE 6 acceptance assertion) — and a broadcast
    over the store-route size threshold still takes the ring (the
    transfer-plane route is gated to multi-node groups)."""
    name = "shm-only"
    ranks = _spawn(4, name, dict(_FAST_CFG, collective_shm_enabled=True,
                                 collective_quant_min_bytes=2048,
                                 collective_bcast_store_min_bytes=256 *
                                 1024))
    try:
        ray_tpu.get([r.op.remote("allreduce", 5) for r in ranks],
                    timeout=120)
        ray_tpu.get([r.op.remote("allreduce", 200001) for r in ranks],
                    timeout=180)
        # the quantized path must ALSO stay on shm links same-node
        ray_tpu.get([r.op.remote("allreduce", 200001, quantize="int8")
                     for r in ranks], timeout=180)
        ray_tpu.get([r.op.remote("allgather", 40001) for r in ranks],
                    timeout=180)
        # 1.2 MB >= the store threshold, but single-node -> ring
        outs = ray_tpu.get([r.op.remote("broadcast", 300001, src=2)
                            for r in ranks], timeout=180)
        xs = _inputs(4, 300001)
        for out in outs:
            np.testing.assert_allclose(out, xs[2], rtol=1e-6)
        for r in ranks:
            tcp = ray_tpu.get(
                r.metric.remote("ray_tpu_collective_tcp_bytes_total"),
                timeout=60)
            shm = ray_tpu.get(
                r.metric.remote("ray_tpu_collective_shm_bytes_total"),
                timeout=60)
            bc = ray_tpu.get(
                r.metric.remote("ray_tpu_collective_bcast_store_total"),
                timeout=60)
            assert tcp is None or tcp["{}"] == 0.0, \
                f"same-node group moved {tcp} TCP bytes"
            assert shm is not None and shm["{}"] > 0.0
            assert bc is None or bc["{}"] == 0.0  # ring, not store
            wire = ray_tpu.get(
                r.metric.remote("ray_tpu_collective_wire_bytes"),
                timeout=60)
            assert wire is not None and \
                wire.get('{"codec": "int8"}', 0.0) > 0.0
    finally:
        _teardown(ranks)


def test_rank_death_mid_allreduce_surfaces_error(col_cluster):
    """A rank dying mid-op must fail the survivors promptly (broken
    connection / op deadline), never hang them.  Doubles as the TCP
    transport check: with shm disabled the pre-kill op moves real
    bytes through the pull links (guards the byte counter against
    rotting into an always-zero stub)."""
    name = "death"
    cfg = dict(_FAST_CFG, collective_shm_enabled=False,
               collective_op_timeout_s=30.0)
    ranks = _spawn(2, name, cfg)
    outs = ray_tpu.get([r.op.remote("allreduce", 120001) for r in ranks],
                       timeout=180)
    exp = _reduced(_inputs(2, 120001), "sum")
    for out in outs:
        np.testing.assert_allclose(out, exp, rtol=2e-5)
    tcp = ray_tpu.get(
        ranks[0].metric.remote("ray_tpu_collective_tcp_bytes_total"),
        timeout=60)
    assert tcp is not None and tcp["{}"] > 0.0
    ref = ranks[0].op.remote("allreduce", 500001)
    time.sleep(1.0)  # rank 0 is now parked inside the op
    ray_tpu.kill(ranks[1])
    t0 = time.monotonic()
    with pytest.raises(Exception):
        ray_tpu.get(ref, timeout=60)
    # timely: bounded by the op timeout (x the suite's timeout scale),
    # reached far earlier via the dead peer's broken connection
    assert time.monotonic() - t0 < 150
    # forensics (docs/observability.md): the survivor emitted a
    # COLLECTIVE_RANK_DEATH event, and the killed rank's worker has a
    # driver-retrievable dossier naming it
    from ray_tpu.experimental import state
    deadline = time.monotonic() + 60
    deaths, exits, dossier = [], [], None
    dead_aid = ranks[1]._actor_id.hex()
    while time.monotonic() < deadline:
        deaths = state.list_cluster_events(type="COLLECTIVE_RANK_DEATH")
        exits = state.list_cluster_events(type="WORKER_EXIT",
                                          actor_id=dead_aid)
        if exits:
            dossier = state.get_dossier(exits[0]["worker_id"])
        if deaths and exits and dossier is not None:
            break
        time.sleep(0.5)
    assert deaths, "no COLLECTIVE_RANK_DEATH event reached the GCS"
    assert exits, "no WORKER_EXIT event for the killed rank"
    assert dossier is not None, "no dossier for the killed rank's worker"
    assert dossier["actor_id"] == dead_aid
    ray_tpu.get(ranks[0].destroy.remote(), timeout=60)
    ray_tpu.kill(ranks[0])


def test_stale_rendezvous_keys_ignored(col_cluster):
    """Re-creating a group under a previously-used name must not
    rendezvous against a dead incarnation's keys: rank 0 sweeps the
    prefix and publishes a fresh nonce that namespaces every address
    key (ISSUE 6 satellite, red before the nonce scheme)."""
    from ray_tpu.runtime.core_worker import get_global_worker
    gcs = get_global_worker().gcs
    name = "stale-rdv"
    # plant a dead incarnation: legacy-style un-namespaced keys AND a
    # stale nonce pointing at an unreachable address
    gcs.kv_put(f"collective/{name}/0", b'["127.0.0.1", 1]')
    gcs.kv_put(f"collective/{name}/nonce", b"deadbeefcafe")
    gcs.kv_put(f"collective/{name}/deadbeefcafe/0",
               b'["127.0.0.1", 1, "no-such-node"]')
    gcs.kv_put(f"collective/{name}/deadbeefcafe/1",
               b'["127.0.0.1", 2, "no-such-node"]')
    ranks = _spawn(2, name, _FAST_CFG)
    try:
        outs = ray_tpu.get([r.op.remote("allreduce", 64) for r in ranks],
                           timeout=120)
        exp = _reduced(_inputs(2, 64), "sum")
        for out in outs:
            np.testing.assert_allclose(out, exp, rtol=1e-6)
        # the fresh incarnation replaced the planted nonce
        assert gcs.kv_get(f"collective/{name}/nonce") != b"deadbeefcafe"
    finally:
        _teardown(ranks)
    # destroy swept the incarnation's keys (rank 0 prefix sweep)
    time.sleep(0.2)
    assert gcs.kv_get(f"collective/{name}/nonce") is None


def test_rerendezvous_after_rank_death_fresh_incarnation(col_cluster):
    """ISSUE 15 satellite: a gang killed mid-life (no destroy — its
    complete key set survives in the GCS under its nonce) and
    re-created under the SAME name must rendezvous a fresh incarnation:
    the dead incarnation's keys never satisfy the new join (rank 0
    confirms the nonce over RPC), the nonce rotates, the stale prefix
    is swept, and the reborn group's ops are numerically correct."""
    from ray_tpu.runtime.core_worker import get_global_worker
    gcs = get_global_worker().gcs
    name = "reborn"
    ranks = _spawn(2, name, _FAST_CFG)
    outs = ray_tpu.get([r.op.remote("allreduce", 64) for r in ranks],
                       timeout=120)
    exp = _reduced(_inputs(2, 64), "sum")
    for out in outs:
        np.testing.assert_allclose(out, exp, rtol=1e-6)
    old_nonce = gcs.kv_get(f"collective/{name}/nonce")
    assert old_nonce
    # ungraceful gang death (rank/slice kill): no destroy runs, the
    # dead incarnation's complete, valid-looking key set stays behind
    for r in ranks:
        ray_tpu.kill(r)
    time.sleep(0.5)
    old = old_nonce.decode()
    assert gcs.kv_get(f"collective/{name}/{old}/0") is not None
    ranks2 = _spawn(2, name, _FAST_CFG)
    try:
        outs = ray_tpu.get(
            [r.op.remote("allreduce", 2048) for r in ranks2], timeout=120)
        exp = _reduced(_inputs(2, 2048), "sum")
        for out in outs:
            np.testing.assert_allclose(out, exp, rtol=1e-6)
        new_nonce = gcs.kv_get(f"collective/{name}/nonce")
        assert new_nonce and new_nonce != old_nonce
        # the fresh rank 0 swept the dead incarnation's prefix
        assert gcs.kv_get(f"collective/{name}/{old}/0") is None
    finally:
        _teardown(ranks2)


def test_init_group_race_holds_slot(monkeypatch):
    """Two threads racing init_collective_group on one name: exactly ONE
    _Group is constructed (the loser fails the duplicate check without
    leaking an rpc.Server), red before the sentinel-slot fix."""
    from ray_tpu.util.collective import collective as colmod

    built = []
    gate = threading.Event()

    class SlowGroup:
        def __init__(self, name, world, rank, timeout):
            gate.wait(5.0)  # hold construction open across the race
            built.append(self)
            self.name = name

        def destroy(self):
            pass

    monkeypatch.setattr(colmod, "_Group", SlowGroup)
    errs, oks = [], []

    def init(rank):
        try:
            colmod.init_collective_group(2, rank, group_name="race-g")
            oks.append(rank)
        except RuntimeError as e:
            errs.append(str(e))

    t1 = threading.Thread(target=init, args=(0,))
    t2 = threading.Thread(target=init, args=(1,))
    t1.start()
    t2.start()
    time.sleep(0.3)   # both threads are past the duplicate check now
    gate.set()
    t1.join(10)
    t2.join(10)
    assert len(oks) == 1 and len(errs) == 1, (oks, errs)
    assert "already initialized" in errs[0]
    assert len(built) == 1  # the loser never constructed (no leak)
    assert colmod.is_group_initialized("race-g")
    colmod.destroy_collective_group("race-g")
    assert not colmod.is_group_initialized("race-g")


def test_init_group_failure_releases_slot(monkeypatch):
    from ray_tpu.util.collective import collective as colmod

    class BoomGroup:
        def __init__(self, *a, **kw):
            raise ConnectionError("rendezvous down")

    monkeypatch.setattr(colmod, "_Group", BoomGroup)
    with pytest.raises(ConnectionError):
        colmod.init_collective_group(2, 0, group_name="boom-g")
    # the pending sentinel was rolled back: the name is reusable
    assert not colmod.is_group_initialized("boom-g")

    class OkGroup:
        def __init__(self, *a, **kw):
            pass

        def destroy(self):
            pass

    monkeypatch.setattr(colmod, "_Group", OkGroup)
    colmod.init_collective_group(2, 0, group_name="boom-g")
    assert colmod.is_group_initialized("boom-g")
    colmod.destroy_collective_group("boom-g")


def test_mailbox_hygiene():
    """_Mailbox satellite: O(1) deque pops, and messages for ops older
    than the group's current sequence are dropped instead of queuing
    forever under a (src, tag) key a future op might reuse."""
    from ray_tpu.util.collective.collective import _Mailbox

    mb = _Mailbox()
    mb.put(1, "7:rs0:0", "a")
    mb.put(1, "7:rs0:0", "b")  # FIFO per key
    assert mb.get(1, "7:rs0:0", 1.0) == "a"
    assert mb.get(1, "7:rs0:0", 1.0) == "b"

    # a recv that timed out leaves nothing to poison op 8: the late
    # message for op 7 is dropped on arrival once the floor advanced
    with pytest.raises(TimeoutError):
        mb.get(1, "7:ag0:0", 0.01)
    mb.expire_below(8)
    mb.put(1, "7:ag0:0", "late")     # stale: dropped
    with pytest.raises(TimeoutError):
        mb.get(1, "7:ag0:0", 0.01)
    # queued-but-unconsumed stale messages are swept by the advance too
    mb.put(2, "7:x:0", "stale-queued")
    mb.expire_below(9)
    with pytest.raises(TimeoutError):
        mb.get(2, "7:x:0", 0.01)
    # current-op and unsequenced (p2p) messages are never dropped
    mb.put(1, "9:rs0:0", "current")
    assert mb.get(1, "9:rs0:0", 1.0) == "current"
    mb.put(3, "p2p", "user")
    assert mb.get(3, "p2p", 1.0) == "user"


def test_serve_board_sweep_and_drain():
    from ray_tpu.util.collective.transport import ServeBoard

    b = ServeBoard()
    arr = np.arange(4, dtype=np.float32)
    # publish-then-take resolves immediately
    b.publish(1, "5:rs0:0", arr)
    d = b.take(1, "5:rs0:0")
    assert d._result is not d._UNSET
    # take-then-publish parks, publish resolves
    d2 = b.take(2, "5:rs0:4")
    assert d2._result is d2._UNSET
    b.publish(2, "5:rs0:4", arr)
    assert d2._result is not d2._UNSET
    # a parked take for an expired op fails instead of parking forever
    d3 = b.take(1, "4:ag0:0")
    b.sweep_below(5)
    ok, value = d3._result[0], d3._result[1]
    assert ok is False
    # wait_clear returns once nothing references op buffers (the two
    # resolved deferreds above were never bound to a connection, so
    # their frames count as drained-on-resolve... bind-less resolve
    # defers the send to _bind; undrained tracks on_sent which only
    # fires post-send — emulate by closing)
    b.close()
    b.wait_clear(time.monotonic() + 1.0)


def test_sync_gradients_rides_host_allreduce(col_cluster):
    """JaxTrainer gang gradient sync goes through the new DCN
    allreduce: two workers (separate JAX runtimes) average a gradient
    pytree via ray_tpu.train.sync_gradients."""
    from ray_tpu.air import ScalingConfig, session
    from ray_tpu.train import JaxConfig, JaxTrainer

    def loop(config):
        import numpy as np
        from ray_tpu.train import sync_gradients
        rank = session.get_world_rank()
        grads = {"w": np.full((8, 4), float(rank + 1), np.float32),
                 "b": np.full((4,), 10.0 * (rank + 1), np.float32)}
        synced = sync_gradients(grads)
        session.report({
            "w0": float(synced["w"][0, 0]),
            "b0": float(synced["b"][0]),
        })

    trainer = JaxTrainer(
        loop, jax_config=JaxConfig(init_distributed=False),
        scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is None, result.error
    # mean of (1, 2) and (10, 20)
    assert abs(result.metrics["w0"] - 1.5) < 1e-6
    assert abs(result.metrics["b0"] - 15.0) < 1e-6


def _quant_bound(world, exp):
    """docs/collective.md numerics contract: <= world hops, each
    perturbing at most blockmax/254; positive [1,2] inputs keep every
    running blockmax under the final reduced max."""
    return world * np.abs(exp).max() / 254.0 + 1e-6


@pytest.mark.parametrize("world", [2, 3])
def test_quantized_allreduce_numerics(col_cluster, world):
    """int8-quantized allreduce vs the numpy fp32 reference: every
    ReduceOp at even AND odd world sizes on a length divisible by
    neither the world nor the codec block, error within the documented
    bound — and quantize=None on the same group stays byte-for-byte
    identical to the plain fp32 plane."""
    name = f"quant-{world}"
    cfg = dict(_FAST_CFG, collective_quant_min_bytes=2048,
               collective_flat_shm=False)
    ranks = _spawn(world, name, cfg)
    nelems = 30001  # 30001 % world != 0, % 256 != 0
    try:
        for reduce_op in ("sum", "product", "min", "max"):
            xs = _inputs(world, nelems)
            exp = _reduced(xs, reduce_op)
            outs = ray_tpu.get(
                [r.op.remote("allreduce", nelems, reduce_op=reduce_op,
                             quantize="int8") for r in ranks],
                timeout=180)
            bound = _quant_bound(world, exp)
            if reduce_op == "product":
                # one hop's rounding error multiplies through the
                # remaining partial products
                bound *= 2.0
            for out in outs:
                err = np.abs(out - exp).max()
                assert err <= bound, (reduce_op, err, bound)
        # exactness: quantize=None must match the untouched fp32 plane
        # bit-for-bit (same deterministic schedule, same bytes)
        a = ray_tpu.get([r.op.remote("allreduce", nelems)
                         for r in ranks], timeout=180)
        b = ray_tpu.get([r.op.remote("allreduce", nelems, quantize=None)
                         for r in ranks], timeout=180)
        for x, y in zip(a, b):
            assert np.array_equal(x, y)
        labels = ray_tpu.get(ranks[0].op_labels.remote(), timeout=60)
        assert "allreduce/ring-int8" in labels, labels
    finally:
        _teardown(ranks)


def test_allreduce_async_overlap(col_cluster):
    """The chained-completion API: allreduce_async returns immediately,
    ops complete in enqueue order on every rank while the caller
    computes, wait_all fences, and the overlap telemetry records how
    much ring time the compute hid."""
    world, nops, nelems = 3, 4, 30001
    ranks = _spawn(world, "async-ov", _FAST_CFG)
    try:
        outs = ray_tpu.get(
            [r.async_overlap.remote(nelems, nops) for r in ranks],
            timeout=180)
        # op i reduces the i-th fresh draw from each rank's rng stream
        draws = [np.random.RandomState(1000 + r)
                 .uniform(1.0, 2.0, nops * nelems).astype("float32")
                 .reshape(nops, nelems) for r in range(world)]
        for sums, computed in outs:
            assert computed
            assert len(sums) == nops
            for i in range(nops):
                exp = float(np.sum([d[i] for d in draws]))
                assert abs(sums[i] - exp) / abs(exp) < 1e-5
        hid = ray_tpu.get(ranks[0].metric.remote(
            "ray_tpu_collective_overlap_hidden_ms"), timeout=60)
        wait = ray_tpu.get(ranks[0].metric.remote(
            "ray_tpu_collective_overlap_wait_ms"), timeout=60)
        assert hid is not None and hid["{}"]["count"] == nops
        assert wait is not None and wait["{}"]["count"] == nops
    finally:
        _teardown(ranks)


def test_topology_schedule_slices(col_cluster):
    """Ranks labeled with tpu_slice_name group by slice: allreduce
    takes the slice-aware schedule (op label 'topo'), results match
    numpy for fp32 and stay in bound quantized; registering an
    in-graph (ICI) reducer on a multi-rank slice folds its host stages
    into one call per op."""
    world, nelems = 3, 70001
    name = "topo-sched"
    ranks = []
    for r in range(world):
        cfg = dict(_FAST_CFG, collective_flat_shm=False,
                   collective_quant_min_bytes=2048,
                   tpu_slice_name="sliceA" if r < 2 else "sliceB")
        ranks.append(Rank.remote(world, r, name, cfg))
    try:
        exp = _reduced(_inputs(world, nelems), "sum")
        outs = ray_tpu.get([r.op.remote("allreduce", nelems)
                            for r in ranks], timeout=180)
        for out in outs:
            np.testing.assert_allclose(out, exp, rtol=2e-5)
        labels = ray_tpu.get(ranks[0].op_labels.remote(), timeout=60)
        assert "allreduce/topo" in labels, labels
        # quantized variant rides the same schedule
        outs = ray_tpu.get(
            [r.op.remote("allreduce", nelems, quantize="int8")
             for r in ranks], timeout=180)
        for out in outs:
            assert np.abs(out - exp).max() <= _quant_bound(world, exp)
        labels = ray_tpu.get(ranks[0].op_labels.remote(), timeout=60)
        assert "allreduce/topo-int8" in labels, labels
        # ICI hook: slice A's ranks get a stub in-graph reducer that
        # returns the exact slice sum — SUM ops must route through it
        # (one call per op) and still produce the global sum
        ray_tpu.get([r.stub_ici.remote([0, 1], nelems)
                     for r in ranks[:2]], timeout=60)
        outs = ray_tpu.get([r.op.remote("allreduce", nelems)
                            for r in ranks], timeout=180)
        for out in outs:
            np.testing.assert_allclose(out, exp, rtol=2e-5)
        for r in ranks[:2]:
            calls = ray_tpu.get(r.ici_calls.remote(), timeout=60)
            assert calls == [nelems], calls
    finally:
        _teardown(ranks)


def test_sync_gradients_quantized_and_async(col_cluster):
    """sync_gradients e2e over a 2-worker gang: an SGD run whose
    gradient sync rides quantize="int8" diverges from the fp32 run by
    <= 0.1% on the loss curve, and the async_op=True chained form
    (issue -> compute -> wait fence) matches the sync form."""
    from ray_tpu.air import ScalingConfig, session
    from ray_tpu.train import JaxConfig, JaxTrainer

    def loop(config):
        import numpy as np
        from ray_tpu._private.config import CONFIG
        from ray_tpu.train import sync_gradients
        CONFIG.update({"collective_small_max_bytes": 1024,
                       "collective_quant_min_bytes": 2048,
                       "collective_chunk_bytes": 64 * 1024})
        rank = session.get_world_rank()
        rng = np.random.RandomState(77 + rank)
        dim, n = 4096, 32  # 16 KB grads: over the quantization floor
        X = rng.randn(n, dim).astype(np.float32)
        w_true = np.random.RandomState(7).randn(dim).astype(np.float32)
        y = X @ w_true + 0.01 * rng.randn(n).astype(np.float32)

        def grad_loss(w):
            r = X @ w - y
            return {"w": (2.0 / n) * (X.T @ r)}, float((r * r).mean())

        div = 0.0
        w_fp = np.zeros(dim, np.float32)
        w_q = np.zeros(dim, np.float32)
        for step in range(8):
            g_fp, l_fp = grad_loss(w_fp)
            g_q, l_q = grad_loss(w_q)
            if step:
                div = max(div, abs(l_q - l_fp) / max(abs(l_fp), 1e-9))
            # async chained form for fp32 (overlap exercised e2e),
            # sync quantized form for the int8 trajectory
            pend = sync_gradients(g_fp, async_op=True)
            sq = sync_gradients(g_q, quantize="int8")
            sf = pend.wait()
            w_fp = w_fp - 0.05 * sf["w"]
            w_q = w_q - 0.05 * sq["w"]
        session.report({"div": div, "final_loss": l_fp})

    trainer = JaxTrainer(
        loop, jax_config=JaxConfig(init_distributed=False),
        scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["div"] <= 1e-3, result.metrics
