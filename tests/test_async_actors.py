"""Async actor + concurrency group tests (cf. reference
python/ray/tests/test_async_actor*.py and test_concurrency_group.py)."""

import time

import pytest

import ray_tpu


def test_async_method_basic(ray_start_regular):
    @ray_tpu.remote
    class A:
        async def add(self, x, y):
            import asyncio
            await asyncio.sleep(0.01)
            return x + y

    a = A.remote()
    assert ray_tpu.get(a.add.remote(2, 3), timeout=60) == 5
    assert ray_tpu.get([a.add.remote(i, i) for i in range(10)],
                       timeout=60) == [2 * i for i in range(10)]


def test_async_methods_interleave(ray_start_regular):
    """max_concurrency coroutines overlap at await points: 6 calls that
    each sleep 0.5s finish in ~0.5s wall, not ~3s."""
    @ray_tpu.remote
    class Sleeper:
        async def nap(self):
            import asyncio
            t0 = time.monotonic()
            await asyncio.sleep(0.5)
            return time.monotonic() - t0

    s = Sleeper.remote()
    ray_tpu.get(s.nap.remote(), timeout=60)  # warm up (worker spawn)
    t0 = time.monotonic()
    ray_tpu.get([s.nap.remote() for _ in range(6)], timeout=60)
    elapsed = time.monotonic() - t0
    assert elapsed < 2.0, f"async calls serialized: {elapsed:.2f}s"


def test_async_actor_sync_methods_and_state(ray_start_regular):
    """Sync methods run on the loop thread too — state is single-threaded
    even with thousands of concurrent async calls in flight."""
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        async def bump_async(self):
            self.n += 1
            return self.n

        def bump_sync(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    refs = [c.bump_async.remote() for _ in range(20)]
    refs += [c.bump_sync.remote() for _ in range(20)]
    values = ray_tpu.get(refs, timeout=60)
    assert sorted(values) == list(range(1, 41))  # no lost updates


def test_async_actor_max_concurrency_cap(ray_start_regular):
    """An explicit max_concurrency bounds coroutine overlap."""
    @ray_tpu.remote(max_concurrency=2)
    class Gate:
        def __init__(self):
            self.active = 0
            self.peak = 0

        async def enter(self):
            import asyncio
            self.active += 1
            self.peak = max(self.peak, self.active)
            await asyncio.sleep(0.2)
            self.active -= 1
            return self.peak

    g = Gate.remote()
    ray_tpu.get([g.enter.remote() for _ in range(6)], timeout=60)
    assert ray_tpu.get(g.enter.remote(), timeout=60) <= 2


def test_concurrency_groups(ray_start_regular):
    """Named groups get independent caps (reference concurrency groups)."""
    @ray_tpu.remote(concurrency_groups={"io": 2, "compute": 1})
    class Worker:
        def __init__(self):
            self.peaks = {"io": 0, "compute": 0}
            self.active = {"io": 0, "compute": 0}

        @ray_tpu.method(concurrency_group="io")
        async def io_call(self):
            import asyncio
            self.active["io"] += 1
            self.peaks["io"] = max(self.peaks["io"], self.active["io"])
            await asyncio.sleep(0.2)
            self.active["io"] -= 1

        @ray_tpu.method(concurrency_group="compute")
        async def compute_call(self):
            import asyncio
            self.active["compute"] += 1
            self.peaks["compute"] = max(self.peaks["compute"],
                                        self.active["compute"])
            await asyncio.sleep(0.2)
            self.active["compute"] -= 1

        async def peaks_seen(self):
            return self.peaks

    w = Worker.remote()
    refs = [w.io_call.remote() for _ in range(6)]
    refs += [w.compute_call.remote() for _ in range(3)]
    ray_tpu.get(refs, timeout=60)
    peaks = ray_tpu.get(w.peaks_seen.remote(), timeout=60)
    assert peaks["io"] <= 2
    assert peaks["compute"] == 1


def test_concurrency_group_call_override(ray_start_regular):
    """.options(concurrency_group=...) reroutes a single call."""
    @ray_tpu.remote(concurrency_groups={"solo": 1})
    class W:
        def __init__(self):
            self.order = []

        async def tag(self, label):
            import asyncio
            self.order.append(label)
            await asyncio.sleep(0.05)
            return label

        async def get_order(self):
            return list(self.order)

    w = W.remote()
    assert ray_tpu.get(
        w.tag.options(concurrency_group="solo").remote("a"),
        timeout=60) == "a"
    assert ray_tpu.get(w.tag.remote("b"), timeout=60) == "b"
    assert ray_tpu.get(w.get_order.remote(), timeout=60) == ["a", "b"]


def test_threaded_actor_groups(ray_start_regular):
    """Concurrency groups also apply to non-async (threaded) actors."""
    @ray_tpu.remote(max_concurrency=4, concurrency_groups={"slow": 1})
    class T:
        @ray_tpu.method(concurrency_group="slow")
        def slow(self):
            time.sleep(0.2)
            return "slow"

        def fast(self):
            return "fast"

    t = T.remote()
    ray_tpu.get(t.fast.remote(), timeout=60)  # warm up (worker spawn)
    t0 = time.monotonic()
    slow_refs = [t.slow.remote() for _ in range(3)]
    assert ray_tpu.get(t.fast.remote(), timeout=60) == "fast"
    fast_elapsed = time.monotonic() - t0
    assert ray_tpu.get(slow_refs, timeout=60) == ["slow"] * 3
    slow_elapsed = time.monotonic() - t0
    # the slow group serializes (1 at a time); fast wasn't stuck behind it
    assert slow_elapsed >= 0.6
    assert fast_elapsed < 0.6


def test_async_actor_exception_propagates(ray_start_regular):
    @ray_tpu.remote
    class Boom:
        async def go(self):
            import asyncio
            await asyncio.sleep(0.01)
            raise ValueError("async boom")

        async def ok(self):
            return 1

    b = Boom.remote()
    with pytest.raises(ray_tpu.exceptions.TaskError):
        ray_tpu.get(b.go.remote(), timeout=60)
    # the actor survives a failed call
    assert ray_tpu.get(b.ok.remote(), timeout=60) == 1


def test_method_num_returns_declaration(ray_start_regular):
    """@ray_tpu.method(num_returns=N) declared on the class takes effect
    through the handle (harvested into method options at creation)."""
    @ray_tpu.remote
    class Splitter:
        @ray_tpu.method(num_returns=2)
        def split(self):
            return "a", "b"

        def one(self):
            return "single"

    s = Splitter.remote()
    r1, r2 = s.split.remote()
    assert ray_tpu.get([r1, r2], timeout=60) == ["a", "b"]
    assert ray_tpu.get(s.one.remote(), timeout=60) == "single"
    # per-call override still wins
    ref = s.split.options(num_returns=1).remote()
    assert ray_tpu.get(ref, timeout=60) == ("a", "b")
