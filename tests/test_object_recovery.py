"""Lineage reconstruction + object spilling tests (cf. reference
python/ray/tests/test_reconstruction.py and test_object_spilling.py)."""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import ObjectLostError


def _worker():
    from ray_tpu.runtime.core_worker import get_global_worker
    return get_global_worker()


@pytest.fixture(autouse=True)
def _shutdown_after_test():
    """Tests here call ray_tpu.shutdown() at the end of their own
    bodies — so one test failing mid-body used to leave its cluster
    live, and every later test in the file inited on top of it and
    failed on unrelated asserts (the PR 5..11 A/B pollution: one real
    failure cascaded into three).  shutdown() is idempotent; always
    run it."""
    yield
    ray_tpu.shutdown()

# every shm object in these tests is > inline_object_max_bytes (100 KiB)
BIG = 256 * 1024 // 8  # float64 elements -> 2 MiB... keep sizes explicit


def _wait_dead_nodes(expected_alive: int, timeout: float = 30.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len([n for n in ray_tpu.nodes() if n["alive"]]) == expected_alive:
            return
        time.sleep(0.2)
    raise TimeoutError("node death not detected")


def test_reconstruct_after_node_death(ray_start_cluster, tmp_path):
    """Losing the only node holding a task's shm output triggers lineage
    re-execution on `get` (reference ObjectRecoveryManager semantics)."""
    cluster = ray_start_cluster
    node2 = cluster.add_node(resources={"CPU": 2, "producer": 2})
    cluster.wait_for_nodes(2)
    ray_tpu.init(num_cpus=1, address=cluster.address)
    marker = str(tmp_path / "runs.txt")

    @ray_tpu.remote(resources={"producer": 1}, num_cpus=1)
    def produce():
        with open(marker, "a") as f:
            f.write("x")
        return np.arange(300_000, dtype=np.float64)  # ~2.3 MiB, shm

    ref = produce.remote()
    first = ray_tpu.get(ref, timeout=60)
    assert float(first[-1]) == 299_999.0
    assert open(marker).read() == "x"

    cluster.remove_node(node2)
    cluster.add_node(resources={"CPU": 2, "producer": 2})
    # the driver's in-process value cache would serve the old copy; drop it
    # so the get exercises the owner's location fetch + recovery path
    _worker()._memory_cache.clear()
    value = ray_tpu.get(ref, timeout=120)
    assert float(value[-1]) == 299_999.0
    assert open(marker).read().count("x") >= 2  # task really re-ran
    ray_tpu.shutdown()


def test_depth2_chain_reconstruction(ray_start_cluster, tmp_path):
    """Recovering an object whose recompute needs another lost object:
    the resubmitted consumer's argument fetch recursively reconstructs
    the producer (depth-2 lineage)."""
    cluster = ray_start_cluster
    node2 = cluster.add_node(resources={"CPU": 2, "producer": 2})
    cluster.wait_for_nodes(2)
    ray_tpu.init(num_cpus=1, address=cluster.address)
    marker = str(tmp_path / "runs.txt")

    @ray_tpu.remote(resources={"producer": 1}, num_cpus=1)
    def produce():
        with open(marker, "a") as f:
            f.write("p")
        return np.ones(300_000, dtype=np.float64)

    @ray_tpu.remote(resources={"producer": 1}, num_cpus=1)
    def double(x):
        with open(marker, "a") as f:
            f.write("d")
        return x * 2.0

    x_ref = produce.remote()
    y_ref = double.remote(x_ref)
    assert float(ray_tpu.get(y_ref, timeout=60)[0]) == 2.0
    assert sorted(open(marker).read()) == ["d", "p"]

    cluster.remove_node(node2)
    cluster.add_node(resources={"CPU": 2, "producer": 2})
    _worker()._memory_cache.clear()
    value = ray_tpu.get(y_ref, timeout=180)
    assert float(value[0]) == 2.0
    assert float(value.sum()) == 600_000.0
    runs = open(marker).read()
    assert runs.count("d") >= 2 and runs.count("p") >= 2
    ray_tpu.shutdown()


def test_unreconstructable_raises_object_lost(ray_start_cluster):
    """max_retries=0 means no lineage budget: losing the copy surfaces
    ObjectLostError instead of hanging (VERDICT round-1 weak #3)."""
    cluster = ray_start_cluster
    node2 = cluster.add_node(resources={"CPU": 2, "producer": 2})
    cluster.wait_for_nodes(2)
    ray_tpu.init(num_cpus=1, address=cluster.address)

    @ray_tpu.remote(resources={"producer": 1}, num_cpus=1, max_retries=0)
    def produce():
        return np.zeros(300_000, dtype=np.float64)

    ref = produce.remote()
    ray_tpu.get(ref, timeout=60)
    cluster.remove_node(node2)
    _wait_dead_nodes(expected_alive=1)
    _worker()._memory_cache.clear()
    with pytest.raises(ObjectLostError):
        ray_tpu.get(ref, timeout=60)
    ray_tpu.shutdown()


def _replicated_big_object(cluster, tmp_path, elems=2 * 1024 * 1024):
    """Produce a shm object on a 'src' node and read it from a 'dst' node
    so the owner's location set holds two live copies (the borrower's
    published pull / the dst raylet's argument prefetch both report their
    copy back).  Returns (ref, marker_path)."""
    marker = str(tmp_path / "producer_runs.txt")

    @ray_tpu.remote(resources={"src": 1}, num_cpus=1)
    def produce():
        with open(marker, "a") as f:
            f.write("x")
        return np.arange(elems, dtype=np.float64)  # 16 MiB shm object

    @ray_tpu.remote(resources={"dst": 1}, num_cpus=1)
    def consume(x):
        return float(x[-1])

    ref = produce.remote()
    assert ray_tpu.get(consume.remote(ref), timeout=120) == float(elems - 1)
    w = _worker()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        with w._owned_lock:
            locs = set(w._owned[ref.id].locations)
        if len(locs) >= 2:
            return ref, marker
        time.sleep(0.1)
    raise TimeoutError(f"object never replicated: locations={locs}")


def test_striped_pull_completes_after_source_eviction(
        ray_start_cluster, tmp_path, monkeypatch):
    """Freeing one source's copy mid-striped-pull doesn't fail (or
    restart) the transfer: the 'absent' answer is authoritative for that
    source only, its outstanding chunk ranges re-queue onto the survivor,
    and the object is never re-produced through lineage (the producer
    runs exactly once) — docs/object_transfer.md failover protocol."""
    # 128 KiB chunks: the 16 MiB pull moves in 128 chunks, so the
    # mid-transfer free lands while ranges are genuinely outstanding
    monkeypatch.setenv("RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES", "131072")
    import threading

    from ray_tpu._private import rpc

    cluster = ray_start_cluster
    node_src = cluster.add_node(resources={"CPU": 2, "src": 2})
    node_dst = cluster.add_node(resources={"CPU": 2, "dst": 2})
    cluster.wait_for_nodes(3)
    ray_tpu.init(num_cpus=1, address=cluster.address)
    ref, marker = _replicated_big_object(cluster, tmp_path)

    def free_on_dst():
        time.sleep(0.03)  # let the driver's pull get chunks in flight
        conn = rpc.connect(node_dst.address, timeout=5.0)
        try:
            conn.call("free_objects",
                      {"object_ids": [ref.id.binary()]}, timeout=10)
        finally:
            conn.close()

    _worker()._memory_cache.clear()
    t = threading.Thread(target=free_on_dst, daemon=True)
    t.start()
    value = ray_tpu.get(ref, timeout=120)
    t.join(timeout=30)
    assert value.shape == (2 * 1024 * 1024,)
    assert float(value[0]) == 0.0
    assert float(value[-1]) == float(2 * 1024 * 1024 - 1)
    # the transfer completed from the surviving copy — no lineage
    # re-execution, i.e. the pull was never restarted from scratch
    assert open(marker).read() == "x"
    # src still holds its copy (only dst's was freed)
    assert node_src.node_id in _worker()._owned[ref.id].locations
    ray_tpu.shutdown()


def test_prefetch_pin_released_when_task_never_dispatches(
        ray_start_cluster, monkeypatch, tmp_path):
    """A lease request's argument prefetch pins the pulled copy so
    eviction can't undo the transfer before the task runs — but a task
    that never dispatches (cancelled / blocked past its lease) must not
    leak that pin: the TTL reaper drops it, and the task still runs
    correctly afterwards (docs/object_transfer.md prefetch contract)."""
    monkeypatch.setenv("RAY_TPU_PREFETCH_PIN_TTL_S", "3.0")
    from ray_tpu._private import rpc
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    cluster = ray_start_cluster
    node2 = cluster.add_node(resources={"CPU": 1})
    cluster.wait_for_nodes(2)
    ray_tpu.init(num_cpus=1, address=cluster.address)
    pin_to_node2 = NodeAffinitySchedulingStrategy(node2.node_id)
    release = str(tmp_path / "release.flag")
    started = str(tmp_path / "blocker_started.flag")

    @ray_tpu.remote(num_cpus=1, scheduling_strategy=pin_to_node2)
    def blocker():
        open(started, "w").close()
        while not os.path.exists(release):
            time.sleep(0.05)
        return "done"

    @ray_tpu.remote(num_cpus=1, scheduling_strategy=pin_to_node2)
    def consume(x):
        return float(x.sum())

    blocker_ref = blocker.remote()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and not os.path.exists(started):
        time.sleep(0.05)
    assert os.path.exists(started)  # node2's only CPU is now occupied
    big = ray_tpu.put(np.ones(1024 * 1024, dtype=np.float64))  # 8 MiB
    target_ref = consume.remote(big)  # parks behind the blocker

    def pins_on_node2() -> int:
        conn = rpc.connect(node2.address, timeout=5.0)
        try:
            out = conn.call("object_pins",
                            {"object_ids": [big.id.binary()]}, timeout=10)
        finally:
            conn.close()
        return int(out.get(big.id.hex(), 0))

    # prefetch fired on lease arrival: the argument lands in node2's shm,
    # pinned, while the task is still parked behind the blocker
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and pins_on_node2() <= 0:
        time.sleep(0.1)
    assert pins_on_node2() >= 1, "argument was never prefetched + pinned"

    # the task never dispatches; the pin must drop after the TTL instead
    # of keeping the bytes unevictable forever
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and pins_on_node2() > 0:
        time.sleep(0.2)
    assert pins_on_node2() == 0, "prefetch pin leaked past its TTL"

    # end-to-end sanity: unblocking dispatches the task, whose fetch is a
    # local hit (the prefetched copy is unpinned, not deleted)
    open(release, "w").close()
    assert ray_tpu.get(blocker_ref, timeout=120) == "done"
    assert ray_tpu.get(target_ref, timeout=120) == float(1024 * 1024)
    ray_tpu.shutdown()


def test_spill_and_restore_roundtrip():
    """A working set ~3x the store capacity round-trips through disk spill
    (reference LocalObjectManager + external_storage semantics)."""
    store_mem = 48 * 1024 * 1024
    ray_tpu.init(num_cpus=2, object_store_memory=store_mem)
    obj_elems = 1024 * 1024  # 8 MiB each
    n_objects = 18           # 144 MiB total = 3x the store
    refs = [ray_tpu.put(np.full(obj_elems, i, dtype=np.float64))
            for i in range(n_objects)]
    # store never overcommits: spilling kept usage under capacity
    stats = _worker().store.stats()
    assert stats["bytes_in_use"] <= stats["capacity"]
    for i, ref in enumerate(refs):
        value = ray_tpu.get(ref, timeout=120)
        assert value.shape == (obj_elems,)
        assert float(value[0]) == float(i)
        assert float(value[-1]) == float(i)
        del value
    ray_tpu.shutdown()


def test_spilled_chunk_served_despite_unsealed_local_create():
    """A chunk request for a locally-spilled object must serve from the
    spill file even while an UNSEALED create for the same oid sits in the
    shared store (a pull's destination buffer, which only seals after
    this very reply): answering absent there is what drops a node with a
    perfectly recoverable copy from the owner's location set."""
    from ray_tpu._private import rpc

    store_mem = 48 * 1024 * 1024
    ray_tpu.init(num_cpus=2, object_store_memory=store_mem)
    w = _worker()
    refs = [ray_tpu.put(np.full(1024 * 1024, i, dtype=np.float64))
            for i in range(10)]  # 80 MiB: the oldest objects spill
    deadline = time.monotonic() + 30
    spilled = None
    while spilled is None and time.monotonic() < deadline:
        for r in refs:
            if not w.store.contains(r.id):
                spilled = r
                break
        time.sleep(0.1)
    assert spilled is not None, "nothing spilled"
    with w._owned_lock:
        size = w._owned[spilled.id].size
    # Wait for the raylet's hysteresis spill scan to settle the store:
    # right after the puts, each put's request_spill freed only its own
    # slack, so usage sits at ~capacity and the unsealed 8 MiB create
    # below would fail with ObjectStoreFullError before the race is
    # even staged (the scan drains to 90% of the threshold within a few
    # 200 ms ticks).
    deadline = time.monotonic() + 30
    buf = None
    while buf is None:
        st = w.store.stats()
        if st["bytes_in_use"] + size <= st["capacity"]:
            try:
                # stage the race: the pull engine has allocated (not yet
                # sealed) the destination for this object in shared store
                buf = w.store.create(spilled.id, size, allow_evict=False)
                break
            except ray_tpu.exceptions.ObjectStoreFullError:
                pass  # fragmented free space: let the scan spill more
        if time.monotonic() > deadline:
            raise AssertionError(
                f"store never settled below capacity for an {size}-byte "
                f"unsealed create: {w.store.stats()}")
        time.sleep(0.2)
    try:
        conn = rpc.connect(tuple(w.raylet_addr), timeout=5)
        try:
            res = conn.call("fetch_object_chunk",
                            {"object_id": spilled.id.binary(), "offset": 0,
                             "length": size, "timeout": 0.0}, timeout=30)
        finally:
            conn.close()
        assert res is not None, "raylet answered authoritative absent"
        assert res["total"] == size and len(res["data"]) == size
    finally:
        buf.release()
        w.store.abort(spilled.id)
    ray_tpu.shutdown()


def test_spill_files_deleted_on_free():
    """Refcount hitting zero deletes spilled files, not just shm copies."""
    store_mem = 48 * 1024 * 1024
    ray_tpu.init(num_cpus=2, object_store_memory=store_mem)
    session_dir = _worker().session_dir
    refs = [ray_tpu.put(np.full(1024 * 1024, i, dtype=np.float64))
            for i in range(18)]

    def spill_dir_bytes() -> int:
        total = 0
        for root, _dirs, files in os.walk(session_dir):
            if "spill_" not in root:
                continue
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(root, f))
                except OSError:
                    pass
        return total

    assert spill_dir_bytes() > 0  # pressure forced spills
    del refs
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and spill_dir_bytes() > 0:
        time.sleep(0.2)
    assert spill_dir_bytes() == 0
    ray_tpu.shutdown()


def test_lineage_budget_evicts_specs():
    """lineage_max_bytes caps pinned task specs FIFO: old completed tasks
    lose reconstructability instead of growing the ledger unboundedly."""
    ray_tpu.init(num_cpus=2, system_config={"lineage_max_bytes": 2000})

    @ray_tpu.remote(num_cpus=1)
    def f(i):
        return np.zeros(50_000) + i  # shm object -> lineage stays pinned

    refs = [f.remote(i) for i in range(12)]
    ray_tpu.get(refs, timeout=120)
    w = _worker()
    with w._owned_lock:
        assert w._lineage_bytes <= 2000
        specs = [w._owned[r.id].task_spec for r in refs
                 if r.id in w._owned]
    assert any(s is None for s in specs)      # oldest evicted
    assert any(s is not None for s in specs)  # newest retained
    ray_tpu.shutdown()


def test_task_output_spills_under_pressure():
    """Task return values (worker-side puts) also spill instead of failing
    or silently evicting primaries."""
    ray_tpu.init(num_cpus=2, object_store_memory=48 * 1024 * 1024)

    @ray_tpu.remote(num_cpus=1)
    def produce(i):
        return np.full(1024 * 1024, i, dtype=np.float64)

    refs = [produce.remote(i) for i in range(18)]
    values = ray_tpu.get(refs, timeout=300)
    for i, v in enumerate(values):
        assert float(v[0]) == float(i)
    ray_tpu.shutdown()


def test_spill_survives_unstable_storage():
    """The unstable-storage fault seam drops every other spill write; the
    spill loop retries and the working set still round-trips (reference
    unstable external-storage fake semantics)."""
    ray_tpu.init(num_cpus=2, object_store_memory=48 * 1024 * 1024,
                 system_config={"object_spill_fault": "unstable"})
    refs = [ray_tpu.put(np.full(1024 * 1024, i, dtype=np.float64))
            for i in range(12)]
    for i, ref in enumerate(refs):
        v = ray_tpu.get(ref, timeout=120)
        assert float(v[0]) == float(i)
        del v
    ray_tpu.shutdown()
