"""Streaming ObjectRef generators: per-yield delivery with backpressure.

Covers the subsystem docs/streaming_generators.md describes: strict
index-order consumption over out-of-order item arrival, the
backpressure bound (never more than ``generator_backpressure_num_objects``
unconsumed items in flight), mid-stream worker death + replay, async
iteration from async actors, ``ray.wait`` on item refs, cancellation on
generator drop, and the satellite fixes (ActorMethod string
num_returns normalization; the get_deserialized pin leak).  Transport-
sensitive suites run twice — fuzz off and with ``rpc_fuzz_ms`` schedule
fuzz (same pattern as tests/test_rpc.py) — because the item-report path
must not depend on frames landing in a convenient order.
"""

import asyncio
import os
import time

import pytest

import ray_tpu
from ray_tpu._private import serialization as ser
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu.runtime import core_worker as cw


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture(params=[0.0, 2.0], ids=["nofuzz", "fuzz"])
def fuzz(request):
    """Fuzz > 0 forces the driver-side report_generator_item handler off
    the inline fast path onto the pooled dispatcher and jitters its
    interleaving with completions."""
    CONFIG.set("rpc_fuzz_ms", request.param)
    yield request.param
    CONFIG.set("rpc_fuzz_ms", 0.0)


@ray_tpu.remote
def _yield_n(n, work_s=0.0):
    for i in range(n):
        if work_s:
            time.sleep(work_s)
        yield i * 10


def test_streaming_ordering_and_completion(cluster, fuzz):
    """Items surface strictly by yield index; the first ref is
    observable before completion; completed() resolves to the full
    generator of item refs."""
    gen = _yield_n.options(num_returns="streaming").remote(30, 0.005)
    vals = [ray_tpu.get(r, timeout=60) for r in gen]
    assert vals == [i * 10 for i in range(30)]
    done = ray_tpu.get(gen.completed(), timeout=60)
    assert len(done) == 30
    assert isinstance(done, ray_tpu.ObjectRefGenerator)


def test_first_item_before_completion(cluster, fuzz):
    """The streaming contract itself: next() returns while the task is
    still producing (dynamic can't — its refs appear at completion)."""
    gen = _yield_n.options(num_returns="streaming").remote(40, 0.02)
    first = next(gen)
    assert ray_tpu.get(first, timeout=60) == 0
    # the task still has most of its 40 * 20ms of work left: the
    # completion sentinel must not be resolved yet
    st = gen._state
    assert st.total is None, "first item only arrived at completion"
    rest = [ray_tpu.get(r, timeout=60) for r in gen]
    assert rest == [i * 10 for i in range(1, 40)]


def test_out_of_order_item_arrival(cluster, fuzz):
    """Owner-side table: reports may land in any index order (retries,
    fuzzed dispatch); the consumer still sees items strictly by index."""
    w = cw.get_global_worker()
    task_id = TaskID.from_random()
    tb = task_id.binary()
    state = w._register_stream(tb, -1)
    slot0 = ObjectID.for_task_return(task_id, 0)
    with w._owned_lock:
        w._owned[slot0] = cw._OwnedObject()
    gen = cw.StreamingObjectRefGenerator(
        w, state, cw.ObjectRef(slot0, w.address, w))

    def report(idx, value):
        head, views = ser.serialize(value)
        return w._rpc_report_generator_item(
            {"task_id": tb, "index": idx,
             "data": ser.to_flat_bytes(head, views)})

    report(2, "v2")
    report(0, "v0")
    assert ray_tpu.get(next(gen), timeout=30) == "v0"
    report(1, "v1")
    w._stream_finished(tb, failed=False, total=3)
    assert [ray_tpu.get(r, timeout=30) for r in gen] == ["v1", "v2"]
    # duplicate replay of a consumed index acks immediately, no re-adopt
    assert report(1, "v1") == {"consumed": 3}


def test_backpressure_bound(cluster, fuzz):
    """With generator_backpressure_num_objects=N the producer pauses
    until consumption: unconsumed in-flight items never exceed N."""
    CONFIG.set("generator_backpressure_num_objects", 2)
    try:
        gen = _yield_n.options(num_returns="streaming").remote(15)
        time.sleep(1.0)   # producer runs ahead as far as it is allowed
        vals = []
        for r in gen:
            time.sleep(0.03)    # slow consumer
            vals.append(ray_tpu.get(r, timeout=60))
    finally:
        CONFIG.set("generator_backpressure_num_objects", -1)
    assert vals == [i * 10 for i in range(15)]
    assert gen._state.max_unconsumed <= 2, (
        f"{gen._state.max_unconsumed} unconsumed items were in flight; "
        "the backpressure window is 2")


def test_worker_death_midstream_replays_unconsumed(cluster, fuzz,
                                                   tmp_path):
    """A worker dying mid-stream: the task retries and replays its
    items; already-consumed indexes ack immediately and the consumer
    sees every item exactly once."""
    flag = str(tmp_path / "died_once")

    @ray_tpu.remote(max_retries=2)
    def dies_once(path, n):
        for i in range(n):
            if i == 3 and not os.path.exists(path):
                open(path, "w").close()
                os._exit(1)
            yield i

    gen = dies_once.options(num_returns="streaming").remote(flag, 6)
    vals = [ray_tpu.get(r, timeout=120) for r in gen]
    assert vals == list(range(6))
    assert os.path.exists(flag), "task never went through the death path"


def test_async_iteration_from_async_actor(cluster, fuzz):
    @ray_tpu.remote
    class AsyncGen:
        async def countdown(self, n):
            for i in range(n):
                await asyncio.sleep(0.01)
                yield n - i

    a = AsyncGen.remote()
    gen = a.countdown.options(num_returns="streaming").remote(5)

    async def collect():
        out = []
        async for ref in gen:
            out.append(ray_tpu.get(ref, timeout=60))
        return out

    assert asyncio.run(collect()) == [5, 4, 3, 2, 1]


def test_wait_on_generator_item_refs(cluster, fuzz):
    """Item refs are first-class owned objects: ray.wait mixes them with
    the (pending) completion sentinel correctly."""
    gen = _yield_n.options(num_returns="streaming").remote(20, 0.02)
    r0, r1 = next(gen), next(gen)
    ready, rest = ray_tpu.wait([r0, r1, gen.completed()], num_returns=2,
                               timeout=30)
    assert set(ready) == {r0, r1}
    assert rest == [gen.completed()]
    for _ in gen:
        pass
    ready, rest = ray_tpu.wait([gen.completed()], timeout=60)
    assert ready and not rest


def test_stream_error_after_items(cluster, fuzz):
    """A generator raising mid-stream: the consumer drains the arrived
    prefix, then the error surfaces on the next next()."""
    @ray_tpu.remote
    def explodes(n):
        for i in range(n):
            yield i
        raise ValueError("boom after yields")

    gen = explodes.options(num_returns="streaming").remote(3)
    vals = [ray_tpu.get(next(gen), timeout=60) for _ in range(3)]
    assert vals == [0, 1, 2]
    with pytest.raises(Exception, match="boom after yields"):
        next(gen)


def test_generator_drop_cancels_producer(cluster, fuzz, tmp_path):
    """Dropping the generator cancels the stream: parked reports resolve
    with a cancel verdict and the producer stops instead of yielding all
    N items into the void."""
    path = str(tmp_path / "progress")
    CONFIG.set("generator_backpressure_num_objects", 1)
    try:
        @ray_tpu.remote
        def counts(p, n):
            for i in range(n):
                with open(p, "a") as f:
                    f.write("x")
                yield i

        gen = counts.options(num_returns="streaming").remote(path, 200)
        ray_tpu.get(next(gen), timeout=60)
        ray_tpu.get(next(gen), timeout=60)
        gen.close()
        deadline = time.monotonic() + 30
        size = None
        while time.monotonic() < deadline:
            time.sleep(0.5)
            new = os.path.getsize(path) if os.path.exists(path) else 0
            if size == new:
                break       # producer stopped making progress
            size = new
        assert size is not None and size < 50, (
            f"producer yielded {size}/200 items after cancellation")
    finally:
        CONFIG.set("generator_backpressure_num_objects", -1)


# --------------------------------------------------------------------------
# satellites
# --------------------------------------------------------------------------
def test_actor_method_num_returns_normalized(cluster):
    """Satellite: ActorMethod shares RemoteFunction's num_returns
    normalization — "dynamic" works on actor methods (no silent
    fall-through to int-only selection) and junk values fail loudly."""
    @ray_tpu.remote
    class Gen:
        def count(self, n):
            for i in range(n):
                yield i + 100

    a = Gen.remote()
    dyn_ref = a.count.options(num_returns="dynamic").remote(3)
    assert isinstance(dyn_ref, ray_tpu.ObjectRef)
    refs = ray_tpu.get(dyn_ref, timeout=60)
    assert isinstance(refs, ray_tpu.ObjectRefGenerator)
    assert [ray_tpu.get(r, timeout=60) for r in refs] == [100, 101, 102]
    with pytest.raises(TypeError):
        a.count.options(num_returns="bogus")
    with pytest.raises(TypeError):
        ray_tpu.remote(num_returns="bogus")(lambda: None)


def test_get_deserialized_releases_pin_for_view_free_payload(tmp_path):
    """Satellite: the object_store.py:293 pin leak — payloads with no
    zero-copy views (non-numpy) release their pin inside
    get_deserialized; numpy payloads stay pinned for their views."""
    np = pytest.importorskip("numpy")
    from ray_tpu.runtime.object_store import SharedMemoryStore

    store = SharedMemoryStore.create_segment(
        str(tmp_path / "seg"), 8 * 1024 * 1024)
    try:
        def pins_of(oid):
            return {o.hex(): p for o, _s, _l, p in store.list_objects()
                    }.get(oid.hex(), 0)

        plain = ObjectID.for_task_return(TaskID.from_random(), 1)
        head, views = ser.serialize(list(range(5000)))
        store.put_serialized(plain, head, views)
        base = pins_of(plain)
        found, value = store.get_deserialized(plain)
        assert found and value == list(range(5000))
        assert pins_of(plain) == base, "view-free payload leaked its pin"

        arr_oid = ObjectID.for_task_return(TaskID.from_random(), 1)
        arr = np.arange(10000, dtype=np.float64)
        head, views = ser.serialize(arr)
        store.put_serialized(arr_oid, head, views)
        base = pins_of(arr_oid)
        found, out = store.get_deserialized(arr_oid)
        assert found and (out == arr).all()
        assert pins_of(arr_oid) == base + 1, \
            "numpy payload must stay pinned while its views are live"
    finally:
        store.close()
        store.unlink()
