"""Preprocessors, predictors, and the ResNet vision path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu import data as rdata
from ray_tpu.data.preprocessors import (BatchMapper, Chain, Concatenator,
                                        LabelEncoder, MinMaxScaler,
                                        OneHotEncoder, SimpleImputer,
                                        StandardScaler)


@pytest.fixture
def numeric_ds(ray_start_regular):
    rows = [{"a": float(i), "b": float(i % 3), "c": ["x", "y"][i % 2]}
            for i in range(40)]
    return rdata.from_items(rows, parallelism=4)


def test_standard_scaler(numeric_ds):
    sc = StandardScaler(["a"])
    out = sc.fit_transform(numeric_ds)
    a = np.array([r["a"] for r in out.take_all()])
    np.testing.assert_allclose(a.mean(), 0.0, atol=1e-6)
    np.testing.assert_allclose(a.std(), 1.0, atol=1e-6)
    # fitted stats are correct against numpy
    mean, std = sc.stats_["a"]
    np.testing.assert_allclose(mean, np.arange(40).mean())
    np.testing.assert_allclose(std, np.arange(40).std(), rtol=1e-6)


def test_minmax_label_onehot(numeric_ds):
    out = MinMaxScaler(["a"]).fit_transform(numeric_ds)
    a = np.array([r["a"] for r in out.take_all()])
    assert a.min() == 0.0 and a.max() == 1.0

    le = LabelEncoder("c").fit(numeric_ds)
    assert le.classes_ == ["x", "y"]
    codes = {r["c"] for r in le.transform(numeric_ds).take_all()}
    assert codes == {0, 1}

    oh = OneHotEncoder(["c"]).fit(numeric_ds)
    row = oh.transform(numeric_ds).take(1)[0]
    assert row["c_x"] + row["c_y"] == 1 and "c" not in row


def test_imputer_and_chain(ray_start_regular):
    rows = [{"v": float(i) if i % 4 else float("nan")} for i in range(20)]
    ds = rdata.from_items(rows, parallelism=2)
    imp = SimpleImputer(["v"], strategy="mean").fit(ds)
    vals = np.array([r["v"] for r in imp.transform(ds).take_all()])
    assert not np.isnan(vals).any()

    chain = Chain(SimpleImputer(["v"], strategy="constant", fill_value=0.0),
                  StandardScaler(["v"]),
                  BatchMapper(lambda b: {**b, "v2": b["v"] * 2}))
    out = chain.fit_transform(ds).take_all()
    assert all(abs(r["v2"] - 2 * r["v"]) < 1e-9 for r in out)


def test_concatenator(ray_start_regular):
    ds = rdata.from_items([{"a": 1.0, "b": 2.0} for _ in range(8)],
                          parallelism=2)
    out = Concatenator(["a", "b"]).transform(ds).take(1)[0]
    np.testing.assert_allclose(out["features"], [1.0, 2.0])


def test_unfit_preprocessor_raises(numeric_ds):
    with pytest.raises(RuntimeError):
        StandardScaler(["a"]).transform(numeric_ds)


def test_batch_predictor_end_to_end(ray_start_regular):
    """Checkpoint -> BatchPredictor -> scored dataset (actor pool)."""
    import flax.linen as nn

    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.train.predictor import BatchPredictor, JaxPredictor

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(x)

    model = Tiny()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4)))["params"]
    ckpt = Checkpoint.from_dict({"params": jax.tree.map(np.asarray, params),
                                 "model": model})

    ds = rdata.from_items(
        [{"features": np.arange(4, dtype=np.float32) + i} for i in range(32)],
        parallelism=4)
    scored = BatchPredictor.from_checkpoint(ckpt, JaxPredictor).predict(
        ds, batch_size=8)
    rows = scored.take_all()
    assert len(rows) == 32 and rows[0]["predictions"].shape == (2,)
    # matches local apply
    local = model.apply({"params": params},
                        jnp.asarray(rows[0]["features"]))
    # worker processes may run a lower default matmul precision
    np.testing.assert_allclose(rows[0]["predictions"], local, rtol=1e-2)


def test_resnet_trains_cifar_shapes():
    """ResNet-18 (CIFAR stem) loss decreases under make_vision_train."""
    from ray_tpu.models import ResNet18
    from ray_tpu.parallel import MeshConfig, build_mesh
    from ray_tpu.train.step import OptimizerConfig, make_vision_train

    mesh = build_mesh(MeshConfig(data=-1))
    model = ResNet18(num_classes=10, small_inputs=True, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    batch = {"image": jnp.asarray(rng.normal(size=(16, 32, 32, 3)),
                                  jnp.float32),
             "label": jnp.asarray(rng.integers(0, 10, (16,)), jnp.int32)}
    init_fn, step_fn, _, _ = make_vision_train(
        model, mesh, OptimizerConfig(learning_rate=1e-3, warmup_steps=1,
                                     decay_steps=100, weight_decay=1e-4),
        example_batch=batch)
    state = init_fn(jax.random.PRNGKey(0), batch)
    losses = []
    for _ in range(6):
        state, m = step_fn(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()
    # batch_stats were updated away from init
    bs = jax.tree.leaves(state.batch_stats)
    assert any(float(jnp.abs(x).sum()) > 0 for x in bs)


def test_gbdt_trainer_end_to_end(ray_start_regular):
    """GBDTTrainer fits on a Dataset (sklearn backend) and its checkpoint
    scores through SklearnPredictor/BatchPredictor."""
    from ray_tpu.train import BatchPredictor, GBDTTrainer, SklearnPredictor

    rng = np.random.default_rng(0)
    rows = []
    for _ in range(200):
        a, b = rng.normal(), rng.normal()
        rows.append({"a": a, "b": b, "y": int(a + b > 0)})
    ds = rdata.from_items(rows, parallelism=4)
    train_ds, val_ds = ds.train_test_split(0.25)

    trainer = GBDTTrainer(label_column="y",
                          params={"max_iter": 40},
                          objective="classification",
                          datasets={"train": train_ds, "valid": val_ds})
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["backend"] == "sklearn"
    assert result.metrics["valid-score"] > 0.8, result.metrics

    scored = BatchPredictor.from_checkpoint(
        result.checkpoint, SklearnPredictor).predict(
        ds.drop_columns(["y"]), batch_size=64)
    out = scored.take_all()
    assert len(out) == 200 and set(r["predictions"] for r in out) <= {0, 1}
    acc = np.mean([r["predictions"] == (1 if r["a"] + r["b"] > 0 else 0)
                   for r in out])
    assert acc > 0.85, acc
