"""Multi-replica LLM serving + queue-metric autoscaling (VERDICT r4 #8).

Two tiny-engine LLM replicas behind one handle: the controller must
scale 1 -> 2 under sustained queue depth, requests must interleave
across BOTH replicas, and the deployment must drain back to 1 when the
load stops.  CPU-sized mechanics test — the chip-backed single replica
stays the perf row (benchmarks/serve_llm.py).  Matches the reference's
serve/_private/autoscaling_policy.py behavior and the BASELINE.md
"pod-slice autoscaling" serve north star.
"""

import threading
import time

import ray_tpu
from ray_tpu.serve.controller import REPLICA_PREFIX, SERVE_NAMESPACE


def _replica_tags(status):
    return list(status["replicas"])


def test_llm_scales_up_then_down(ray_start_regular):
    from ray_tpu import serve

    serve.start()
    app = serve.llm.build_app(
        preset="tiny", num_slots=2, block_size=4,
        max_concurrent_queries=16,
        warmup_prompt_lens=[2],      # compile at replica init, not under
                                     # load (health grace covers startup)
        autoscaling_config={
            "min_replicas": 1, "max_replicas": 2,
            "target_num_ongoing_requests_per_replica": 2.0,
            "upscale_delay_s": 0.5, "downscale_delay_s": 1.5,
        })
    handle = serve.run(app, name="llm-auto")
    name = "llm-auto"           # serve.run registers under the app name
    try:
        # warm the single replica
        ray_tpu.get(handle.remote({"prompt": [1, 2], "max_new_tokens": 2}),
                    timeout=300)

        stop = threading.Event()
        errors = []

        def load():
            # open loop at constant depth 6 (> 2 * target_ongoing): each
            # completed request is replaced immediately, so ongoing never
            # dips between batches and the controller sees steady demand.
            # Any failed request is a bug — scale-down must DRAIN, never
            # kill a replica with our requests on it.
            pending = [handle.remote({"prompt": [3, 4],
                                      "max_new_tokens": 24})
                       for _ in range(6)]
            while not stop.is_set():
                try:
                    done, pending = ray_tpu.wait(pending, num_returns=1,
                                                 timeout=300)
                    ray_tpu.get(done, timeout=60)
                except Exception as e:   # noqa: BLE001
                    if not stop.is_set():
                        errors.append(e)
                        return
                pending.append(handle.remote({"prompt": [3, 4],
                                              "max_new_tokens": 24}))
            try:
                ray_tpu.get(pending, timeout=300)
            except Exception:
                pass      # tail of the load; engine may be shutting down

        t = threading.Thread(target=load, daemon=True)
        t.start()

        # controller observes ongoing > target -> scales to 2
        deadline = time.monotonic() + 120
        scaled = False
        while time.monotonic() < deadline:
            st = serve.status()[name]
            if st["target_replicas"] == 2 and len(st["replicas"]) == 2:
                scaled = True
                break
            time.sleep(0.3)
        assert scaled, f"never scaled up: {serve.status()[name]}"
        assert not errors, errors

        # both replicas serve: drive more load, then read each replica's
        # engine stats directly
        deadline = time.monotonic() + 120
        interleaved = False
        while time.monotonic() < deadline and not interleaved:
            time.sleep(1.0)
            st = serve.status()[name]
            tags = _replica_tags(st)
            if len(tags) < 2:
                continue
            counts = []
            for tag in tags:
                try:
                    a = ray_tpu.get_actor(REPLICA_PREFIX + tag,
                                          namespace=SERVE_NAMESPACE)
                    stats = ray_tpu.get(
                        a.handle_request.remote("stats", (), {}),
                        timeout=60)
                    counts.append(stats["requests_completed"])
                except Exception:
                    counts.append(0)
            interleaved = sum(1 for c in counts if c > 0) >= 2
        assert interleaved, f"load never interleaved: {counts}"

        # stop the load: drains back to min_replicas=1
        stop.set()
        t.join(timeout=120)
        deadline = time.monotonic() + 120
        drained = False
        while time.monotonic() < deadline:
            st = serve.status()[name]
            if st["target_replicas"] == 1 and len(st["replicas"]) == 1:
                drained = True
                break
            time.sleep(0.3)
        assert drained, f"never scaled down: {serve.status()[name]}"
        # the retired replica must have been drained, not shot: no load
        # request may have died across the whole 1 -> 2 -> 1 cycle
        assert not errors, errors
    finally:
        serve.shutdown()
