"""Pluggable storage seam tests (cf. reference test_object_spilling.py's
unstable-storage cases and air/_internal remote_storage tests)."""
import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import storage


@pytest.fixture(autouse=True)
def _clean_mock():
    storage.MemoryStorage.clear()
    yield
    storage.MemoryStorage.clear()


def test_file_and_mock_roundtrip(tmp_path):
    for base in (f"file://{tmp_path}/x", "mock://ns/x"):
        uri = storage.join_uri(base, "a", "b.bin")
        assert not storage.exists(uri)
        storage.write_bytes(uri, b"hello world")
        assert storage.exists(uri)
        assert storage.read_bytes(uri) == b"hello world"
        assert storage.read_bytes(uri, offset=6) == b"world"
        assert storage.read_bytes(uri, offset=0, length=5) == b"hello"
        assert storage.list_prefix(base) == ["a/b.bin"]
        assert storage.delete_uri(uri)
        assert not storage.exists(uri)
        with pytest.raises(FileNotFoundError):
            storage.read_bytes(uri)


def test_upload_download_dir(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "top.txt").write_bytes(b"t")
    (src / "sub" / "leaf.txt").write_bytes(b"l")
    assert storage.upload_dir(str(src), "mock://exp/run1") == 2
    assert storage.list_prefix("mock://exp/run1") == \
        ["sub/leaf.txt", "top.txt"]
    dest = tmp_path / "dest"
    assert storage.download_dir("mock://exp/run1", str(dest)) == 2
    assert (dest / "top.txt").read_bytes() == b"t"
    assert (dest / "sub" / "leaf.txt").read_bytes() == b"l"


def test_flaky_storage_is_deterministic():
    flaky = storage.FlakyStorage(storage.MemoryStorage(), failure_rate=0.3)
    outcomes = []
    for i in range(100):
        try:
            flaky.write_bytes(f"k{i}", b"v")
            outcomes.append(True)
        except OSError:
            outcomes.append(False)
    assert outcomes.count(False) == 30  # exactly the configured rate
    # reads unaffected unless fail_reads
    assert flaky.read_bytes("k0") == b"v"


def test_unknown_scheme_rejected():
    with pytest.raises(ValueError, match="unsupported storage scheme"):
        storage.read_bytes("s3://nope/x")


def test_spill_through_mock_uri():
    """Objects spill to mock:// storage inside the raylet and round-trip
    (the spill consumer of the seam; reference external_storage.py:72)."""
    ray_tpu.init(system_config={
        "object_store_memory_bytes": 32 * 1024 * 1024,
        "object_spill_uri": "mock://spill_test",
    })
    try:
        refs = [ray_tpu.put(np.full((1 << 20,), i, dtype=np.uint8))
                for i in range(80)]  # 80 MB >> 32 MB store
        for i, r in enumerate(refs):
            assert ray_tpu.get(r)[0] == i
    finally:
        ray_tpu.shutdown()


def test_spill_survives_flaky_backend():
    """30% of spill writes fail; the scan retries and the working set
    still round-trips (reference UnstableFileStorage chaos case)."""
    ray_tpu.init(system_config={
        "object_store_memory_bytes": 32 * 1024 * 1024,
        "object_spill_failure_rate": 0.3,
    })
    try:
        refs = [ray_tpu.put(np.full((1 << 20,), i, dtype=np.uint8))
                for i in range(80)]
        for i, r in enumerate(refs):
            assert ray_tpu.get(r)[0] == i
    finally:
        ray_tpu.shutdown()


def test_tune_sync_to_mock_and_restore(ray_start_regular):
    """A Tune run syncs its experiment to mock:// storage; after the local
    staging dir is wiped, Tuner.restore resumes errored trials from the
    synced checkpoint (the Tune consumer of the seam; reference
    tune/syncer.py:185 + Tuner.restore)."""
    import shutil
    from ray_tpu.air import Checkpoint, RunConfig, session
    from ray_tpu.tune import TuneConfig, Tuner

    def flaky(config):
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["i"] + 1 if ckpt else 0
        for i in range(start, 4):
            if i == 2 and start == 0:
                raise RuntimeError("interrupted")
            session.report({"i": i},
                           checkpoint=Checkpoint.from_dict({"i": i}))

    run_cfg = RunConfig(name="exp_sync", storage_path="mock://tune_exps")
    grid = Tuner(flaky, param_space={},
                 tune_config=TuneConfig(metric="i", mode="max"),
                 run_config=run_cfg).fit()
    assert len(grid.errors) == 1  # first run dies at i==2

    # everything needed to resume lives under the URI
    synced = storage.list_prefix("mock://tune_exps/exp_sync")
    assert "experiment_state.json" in synced
    assert any(s.endswith("checkpoint.pkl") for s in synced)

    # wipe local staging: restore must come from the mock store alone
    import tempfile
    shutil.rmtree(os.path.join(tempfile.gettempdir(),
                               "ray_tpu_tune_staging", "exp_sync"),
                  ignore_errors=True)

    grid2 = Tuner.restore("mock://tune_exps/exp_sync", flaky,
                          tune_config=TuneConfig(metric="i", mode="max"),
                          resume_errored=True).fit()
    assert not grid2.errors
    # resumed from the synced i=1 checkpoint (start=2), not from scratch
    assert grid2.get_best_result().metrics["i"] == 3


def test_data_read_write_uri(ray_start_regular, tmp_path):
    """data.write_*/read_* against storage URIs (the Data consumer of the
    seam; reference read_api.py:429 read_parquet(filesystem=...))."""
    from ray_tpu import data

    ds = data.range(100, parallelism=4)
    out_uri = f"file://{tmp_path}/ds_out"
    ds.write_parquet(out_uri)
    back = data.read_parquet(out_uri)
    assert back.count() == 100
    assert sorted(r["id"] for r in back.take_all()) == list(range(100))

    csv_uri = f"file://{tmp_path}/ds_csv"
    ds.write_csv(csv_uri)
    back_csv = data.read_csv(csv_uri)
    assert back_csv.count() == 100


def test_disk_full_fails_spills_gracefully(tmp_path):
    """With the filesystem monitor reporting a full disk, spilling stops
    (objects stay in shm) and a put that needs fallback allocation
    raises OutOfDiskError instead of hanging (reference
    file_system_monitor.h + OutOfDiskError)."""
    usage_file = tmp_path / "usage"
    usage_file.write_text("0.99")   # injected: disk is 99% full
    ray_tpu.init(system_config={
        "object_store_memory_bytes": 24 * 1024 * 1024,
        "fs_monitor_test_usage_path": str(usage_file),
    })
    try:
        from ray_tpu.exceptions import OutOfDiskError
        refs = []
        with pytest.raises(OutOfDiskError, match="out of disk"):
            for i in range(40):   # 40 MB >> 24 MB store, spilling refused
                refs.append(ray_tpu.put(
                    np.full((1 << 20,), i, dtype=np.uint8)))
        # what made it into shm is still readable
        assert ray_tpu.get(refs[0])[0] == 0
        # freeing space re-enables spilling: the same overflow now works
        usage_file.write_text("0.2")
        import time
        time.sleep(1.2)  # monitor check interval
        more = [ray_tpu.put(np.full((1 << 20,), 7, dtype=np.uint8))
                for _ in range(30)]
        assert all(ray_tpu.get(m)[0] == 7 for m in more)
    finally:
        ray_tpu.shutdown()
