"""Mesh/sharding-rule tests and end-to-end sharded training on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.models import GPT, get_config
from ray_tpu.parallel import MeshConfig, build_mesh
from ray_tpu.parallel.sharding import (LOGICAL_RULES, logical_spec,
                                       logical_pspec_to_mesh)
from ray_tpu.train.step import OptimizerConfig, make_sharded_train


def test_mesh_config_resolution():
    assert MeshConfig(data=-1).resolve(8) == (1, 8, 1, 1, 1)
    assert MeshConfig(data=-1, fsdp=2, tensor=2).resolve(8) == (1, 2, 2, 1, 2)
    assert MeshConfig(data=2, fsdp=2, context=2, tensor=1).resolve(8) == \
        (1, 2, 2, 2, 1)
    assert MeshConfig(stage=2, data=-1).resolve(8) == (2, 4, 1, 1, 1)
    with pytest.raises(ValueError):
        MeshConfig(data=3).resolve(8)
    with pytest.raises(ValueError):
        MeshConfig(data=-1, fsdp=-1).resolve(8)


def test_logical_spec_prunes_size1_axes():
    mesh = build_mesh(MeshConfig(data=4, fsdp=2))  # context/tensor size 1
    spec = logical_spec(("batch", "seq", "embed"), mesh)
    assert spec == P(("data", "fsdp"), None, "fsdp")
    # without a mesh, no pruning
    assert logical_spec(("seq",)) == P("context")


def test_logical_pspec_translation():
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    s = logical_pspec_to_mesh(P(None, "embed", "heads"), mesh)
    assert s.spec == P(None, "fsdp", "tensor")
    s2 = logical_pspec_to_mesh(None, mesh)
    assert s2.spec == P()


@pytest.mark.parametrize("mesh_cfg,attn", [
    (MeshConfig(data=-1), "xla"),                          # pure DP
    (MeshConfig(data=2, fsdp=2, tensor=2), "xla"),         # DP+FSDP+TP
    (MeshConfig(data=2, fsdp=2, context=2), "ring"),       # DP+FSDP+CP(ring)
    (MeshConfig(data=2, fsdp=2, context=2), "ulysses"),    # DP+FSDP+CP(a2a)
])
def test_sharded_training_loss_decreases(mesh_cfg, attn):
    mesh = build_mesh(mesh_cfg)
    cfg = get_config("tiny", max_seq_len=64, attention_impl=attn)
    model = GPT(cfg, mesh=mesh)
    rng = np.random.default_rng(0)
    batch = {"tokens": jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 65)),
                                   jnp.int32)}
    init_fn, step_fn, state_sh, _ = make_sharded_train(
        model, mesh, OptimizerConfig(learning_rate=1e-3, warmup_steps=1,
                                     decay_steps=100),
        example_batch=batch)
    state = init_fn(jax.random.PRNGKey(0), batch)

    # parameters are born sharded as the rules dictate
    wq = state.params["blocks"]["attn"]["wq"]["kernel"].value
    if mesh.shape["fsdp"] > 1:
        flat_axes = [a for ax in wq.sharding.spec if ax is not None
                     for a in (ax if isinstance(ax, tuple) else (ax,))]
        assert "fsdp" in flat_axes, wq.sharding.spec

    losses = []
    for _ in range(8):
        state, m = step_fn(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


def test_model_forward_deterministic_across_shardings():
    """Same seed -> same logits whether run replicated or TP-sharded."""
    cfg = get_config("tiny", max_seq_len=32)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (8, 16)), jnp.int32)

    model_plain = GPT(cfg)
    vars_plain = model_plain.init(jax.random.PRNGKey(7), tokens)
    out_plain = model_plain.apply(vars_plain, tokens)

    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    model_mesh = GPT(cfg, mesh=mesh)
    vars_mesh = model_mesh.init(jax.random.PRNGKey(7), tokens)
    out_mesh = jax.jit(model_mesh.apply)(vars_mesh, tokens)
    np.testing.assert_allclose(out_plain, out_mesh, atol=2e-4)


def test_decode_cache_matches_full_forward():
    cfg = get_config("tiny", max_seq_len=32, scan_layers=True)
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, cfg.vocab_size, (1, 10)), jnp.int32)
    model = GPT(cfg)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    full_logits = model.apply(variables, tokens)

    decode_model = GPT(cfg, decode=True)
    dvars = decode_model.init(jax.random.PRNGKey(0), tokens[:, :1])
    cache = dvars["cache"]
    outs = []
    for i in range(tokens.shape[1]):
        logits, mut = decode_model.apply(
            {"params": variables["params"], "cache": cache},
            tokens[:, i:i + 1],
            jnp.full((1, 1), i, jnp.int32),
            mutable=["cache"])
        cache = mut["cache"]
        outs.append(logits[:, 0])
    dec = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(dec, full_logits, atol=1e-3)


def test_moe_training_loss_decreases():
    """Tiny MoE model trains end-to-end with expert parallelism + aux loss."""
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    cfg = get_config("tiny-moe", max_seq_len=64)
    model = GPT(cfg, mesh=mesh)
    rng = np.random.default_rng(0)
    batch = {"tokens": jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 65)),
                                   jnp.int32)}
    init_fn, step_fn, _, _ = make_sharded_train(
        model, mesh, OptimizerConfig(learning_rate=1e-3, warmup_steps=1,
                                     decay_steps=100),
        example_batch=batch)
    state = init_fn(jax.random.PRNGKey(0), batch)
    # expert weights exist, carry the expert dim, and shard over data axes
    moe_w = state.params["blocks"]["moe"]["w_gate"].value
    assert moe_w.shape[1] == cfg.moe_experts  # [layers, E, D, F] under scan
    losses, aux = [], []
    for _ in range(8):
        state, m = step_fn(state, batch)
        losses.append(float(m["loss"]))
        aux.append(float(m["moe_aux_loss"]))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all() and np.isfinite(aux).all()


def test_generation_greedy_matches_full_forward():
    """Greedy KV-cache generation equals argmax over repeated full
    forwards (decode-path correctness end-to-end)."""
    from ray_tpu.models import Generator, get_config

    cfg = get_config("tiny", max_seq_len=64)
    model_full = GPT(cfg)
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(1, cfg.vocab_size, (2, 8)),
        jnp.int32)
    variables = model_full.init(jax.random.PRNGKey(0), tokens)

    gen = Generator(cfg, variables["params"])
    out = gen.generate(tokens, max_new_tokens=6, temperature=0.0)
    assert out.shape == (2, 6)

    # reference: greedy via full re-forward each step
    cur = tokens
    for i in range(6):
        logits = model_full.apply(variables, cur)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        np.testing.assert_array_equal(np.asarray(out[:, i]), np.asarray(nxt))
        cur = jnp.concatenate([cur, nxt[:, None]], axis=1)


def test_generation_samplers_and_eos():
    from ray_tpu.models import Generator, get_config, sample_logits

    cfg = get_config("tiny", max_seq_len=64)
    model = GPT(cfg)
    tokens = jnp.ones((1, 4), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    gen = Generator(cfg, variables["params"])

    out = gen.generate(tokens, max_new_tokens=8, temperature=0.8,
                       top_k=16, top_p=0.9, rng=jax.random.PRNGKey(1))
    assert out.shape[1] <= 8 and out.dtype == jnp.int32

    # eos padding: force eos to be whatever the first sampled token is
    first = int(out[0, 0])
    out2 = gen.generate(tokens, max_new_tokens=8, temperature=0.8,
                        top_k=16, top_p=0.9, eos_id=first,
                        rng=jax.random.PRNGKey(1))
    assert int(out2[0, 0]) == first and out2.shape[1] <= 8

    # sampler math: top-k=1 equals greedy
    logits = jax.random.normal(jax.random.PRNGKey(2), (3, 50))
    a = sample_logits(jax.random.PRNGKey(3), logits, temperature=1.0,
                      top_k=1)
    np.testing.assert_array_equal(np.asarray(a),
                                  np.asarray(jnp.argmax(logits, -1)))


def test_partial_remat_split_stack():
    """cfg.remat_layers splits the stack into a rematted head and a
    plain tail (two scan scopes); the forward math is unchanged vs the
    single-stack model and a train step runs."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import GPT, get_config
    from ray_tpu.train.step import OptimizerConfig, make_sharded_train
    from ray_tpu.parallel import MeshConfig, build_mesh

    cfg = get_config("tiny", max_seq_len=64, remat=True,
                     remat_policy="nothing", remat_layers=1)
    model = GPT(cfg)
    tokens = jnp.arange(2 * 32, dtype=jnp.int32).reshape(2, 32) % 256
    variables = model.init(jax.random.PRNGKey(0), tokens)
    assert "blocks_tail" in variables["params"], \
        "partial remat must create the plain tail scope"
    logits = model.apply(variables, tokens)
    assert jnp.isfinite(logits).all()

    mesh = build_mesh(MeshConfig(data=-1))
    m_model = GPT(cfg, mesh=mesh)
    n_dev = len(jax.devices())
    batch = {"tokens": jnp.arange(n_dev * 33, dtype=jnp.int32
                                  ).reshape(n_dev, 33) % 256}
    init_fn, step_fn, _, _ = make_sharded_train(
        m_model, mesh, OptimizerConfig(warmup_steps=1, decay_steps=10),
        example_batch=batch)
    state = init_fn(jax.random.PRNGKey(0), batch)
    state, metrics = step_fn(state, batch)
    assert bool(jnp.isfinite(metrics["loss"]))


def test_cast_params_once_identical_loss():
    """The hoisted f32->bf16 cast changes scheduling, not numerics: the
    loss equals the uncast path bit-for-bit (flax promotes to the same
    bf16 values inside each Dense)."""
    import functools

    import jax
    import jax.numpy as jnp

    from ray_tpu.models import GPT, get_config
    from ray_tpu.train.step import lm_loss_fn

    cfg = get_config("tiny", max_seq_len=64, dtype=jnp.bfloat16)
    model = GPT(cfg)
    tokens = (jnp.arange(2 * 33, dtype=jnp.int32).reshape(2, 33) * 7) % 256
    params = model.init(jax.random.PRNGKey(0),
                        tokens[:, :-1])["params"]
    batch = {"tokens": tokens}
    base, _ = lm_loss_fn(model.apply, params, batch)
    cast, _ = lm_loss_fn(model.apply, params, batch,
                         param_cast=jnp.bfloat16)
    assert float(base) == float(cast)
