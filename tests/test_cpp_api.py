"""C++ language binding: codec, cpp tasks from Python, native C++ driver.

Covers the analog of the reference's C++ user API (cpp/include/ray/api.h,
cpp/src/ray/runtime) and cross-language calls (python/ray/cross_language.py):
csrc/{pycodec,rpcnet,cpp_worker,cpp_api} built to ray_tpu/_core/.
"""

import os
import pickle
import struct
import subprocess

import pytest

import ray_tpu

_CORE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "ray_tpu", "_core")


def _tool(name):
    path = os.path.join(_CORE, name)
    if not os.path.exists(path):
        pytest.skip(f"{name} not built (make -C csrc)")
    return path


def test_pycodec_roundtrip_all_protocols():
    """C++ pickle codec loads protocols 2-5 and emits pickles Python
    loads back unchanged, over the control-plane value set."""
    tool = _tool("pycodec_tool")
    cases = [
        None, True, False, 0, 255, 256, -1, -129, 2**31 - 1, -2**31,
        2**31, 2**62, -2**62, 3.14159, -0.0,
        "", "hello", "über ✓", "x" * 300,
        b"", b"bytes\x00\xff", b"y" * 70000,
        [1, [2, [3, "deep"]]], (), (1,), (1, 2, 3, 4, "five"),
        {"a": 1, "b": [2, 3], "c": {"d": b"x"}},
        {"task_id": b"\x01" * 16, "fn_key": "cpp:Add", "args": b"blob",
         "num_returns": 1, "owner_addr": ["127.0.0.1", 1234]},
        ["dup", "dup", {"dup": "dup"}],  # exercises memo opcodes
    ]
    shared = [1, 2]  # memoized-before-populated container, referenced twice
    cases.append((shared, shared, {"k": shared}))
    blobs = b""
    for proto in (2, 3, 4, 5):
        for c in cases:
            p = pickle.dumps(c, protocol=proto)
            blobs += struct.pack("<I", len(p)) + p
    out = subprocess.run([tool], input=blobs, capture_output=True,
                         timeout=60).stdout
    off = 0

    def block():
        nonlocal off
        (n,) = struct.unpack_from("<I", out, off)
        off += 4
        b = out[off:off + n]
        off += n
        return b

    for proto in (2, 3, 4, 5):
        for c in cases:
            enc, rep = block(), block()
            assert enc, f"p{proto} {c!r}: {rep.decode()}"
            back = pickle.loads(enc)
            if isinstance(c, tuple):
                back = tuple(back) if isinstance(back, list) else back
            assert back == c, f"p{proto}: {back!r} != {c!r}"


def test_pycodec_exception_bridge():
    """Exception instances decode to an inspectable form and re-encode to
    a real Python exception (the cpp worker's error-reply path)."""
    tool = _tool("pycodec_tool")
    blob = pickle.dumps(ValueError("boom message"), protocol=5)
    out = subprocess.run([tool],
                         input=struct.pack("<I", len(blob)) + blob,
                         capture_output=True, timeout=60).stdout
    (n,) = struct.unpack_from("<I", out, 0)
    back = pickle.loads(out[4:4 + n])
    assert isinstance(back, ValueError) and back.args == ("boom message",)


def test_cpp_tasks_from_python(ray_start_regular):
    """cross_language.cpp_function: Python driver, C++ execution."""
    _tool("cpp_worker")
    add = ray_tpu.cpp_function("Add")
    assert ray_tpu.get(add.remote(1, 2, 3), timeout=120) == 6
    assert abs(ray_tpu.get(add.remote(1.5, 2.25), timeout=120) - 3.75) \
        < 1e-9
    assert ray_tpu.get(ray_tpu.cpp_function("Concat").remote("a", "b"),
                       timeout=120) == "ab"
    assert ray_tpu.get(ray_tpu.cpp_function("Fib").remote(50),
                       timeout=120) == 12586269025
    # arbitrary primitives round-trip through the cpp side
    assert ray_tpu.get(
        ray_tpu.cpp_function("Echo").remote(None, True, b"\x00\xff",
                                            {"k": [1, 2]}),
        timeout=120) == [None, True, b"\x00\xff", {"k": [1, 2]}]
    # multiple returns
    lo, hi = ray_tpu.get(
        list(ray_tpu.cpp_function("MinMax", num_returns=2)
             .remote(5, 1, 9, 3)), timeout=120)
    assert (lo, hi) == (1, 9)


def test_cpp_task_errors_surface(ray_start_regular):
    """A throwing cpp task raises TaskError at the Python caller with the
    native message; unknown names fail cleanly, not hang."""
    _tool("cpp_worker")
    with pytest.raises(ray_tpu.exceptions.TaskError, match="kaboom"):
        ray_tpu.get(ray_tpu.cpp_function("Fail").remote("kaboom"),
                    timeout=120)
    with pytest.raises(ray_tpu.exceptions.TaskError,
                       match="no cpp function registered"):
        ray_tpu.get(ray_tpu.cpp_function("NoSuch").remote(1), timeout=120)
    # invalid args rejected client-side before submission
    with pytest.raises(TypeError):
        ray_tpu.cpp_function("Add").remote(object())


def test_cpp_and_python_pools_are_disjoint(ray_start_regular):
    """language=cpp leases never reuse python workers or vice versa —
    asserted on actual process identity, not just task results."""
    _tool("cpp_worker")

    @ray_tpu.remote
    def py_pid():
        return os.getpid()

    cpp_pids, py_pids = set(), set()
    for _ in range(3):
        py_pids.add(ray_tpu.get(py_pid.remote(), timeout=120))
        cpp_pids.add(ray_tpu.get(ray_tpu.cpp_function("Pid").remote(),
                                 timeout=120))
    assert not (cpp_pids & py_pids)
    for pid in cpp_pids:
        exe = os.readlink(f"/proc/{pid}/exe")
        assert exe.endswith("cpp_worker"), exe
    for pid in py_pids:
        exe = os.readlink(f"/proc/{pid}/exe")
        assert "python" in os.path.basename(exe), exe


def test_cpp_actor_lifecycle(ray_start_regular):
    """cpp_actor_class: construct with args, stateful ordered method
    calls, per-call errors that don't kill the actor, ray_tpu.kill."""
    _tool("cpp_worker")
    c = ray_tpu.cpp_actor_class("Counter").remote(100)
    assert ray_tpu.get(c.inc.remote(), timeout=120) == 101
    assert ray_tpu.get(c.inc.remote(5), timeout=120) == 106
    # pipelined burst executes in submission order (seq-ordered streams)
    vals = ray_tpu.get([c.inc.remote() for _ in range(20)], timeout=120)
    assert vals == list(range(107, 127))
    with pytest.raises(ray_tpu.exceptions.TaskError,
                       match="counter exploded"):
        ray_tpu.get(c.boom.remote(), timeout=120)
    assert ray_tpu.get(c.total.remote(), timeout=120) == 126  # still alive
    ray_tpu.kill(c)


def test_cpp_actor_state_isolated(ray_start_regular):
    """Two cpp actors of different classes hold independent native state;
    values of any primitive shape round-trip."""
    _tool("cpp_worker")
    kv = ray_tpu.cpp_actor_class("Kv").remote()
    ray_tpu.get(kv.put.remote("a", [1, 2, 3]), timeout=120)
    ray_tpu.get(kv.put.remote("b", {"x": b"bytes"}), timeout=120)
    assert ray_tpu.get(kv.get.remote("a"), timeout=120) == [1, 2, 3]
    assert ray_tpu.get(kv.get.remote("b"), timeout=120) == {"x": b"bytes"}
    assert ray_tpu.get(kv.size.remote(), timeout=120) == 2
    c = ray_tpu.cpp_actor_class("Counter").remote(0)
    assert ray_tpu.get(c.inc.remote(), timeout=120) == 1
    assert ray_tpu.get(kv.size.remote(), timeout=120) == 2


def test_cpp_ref_args_resolve_via_borrower_protocol(ray_start_regular):
    """ObjectRef args into cpp tasks/actors: the native worker polls the
    owner (get_object) and fetches located copies from raylets — same
    borrower protocol as Python workers.  Covers explicit refs, cpp->cpp
    chaining, python->cpp handoff, auto-promoted large args, and failed
    upstream dependencies."""
    _tool("cpp_worker")
    add = ray_tpu.cpp_function("Add")
    assert ray_tpu.get(add.remote(ray_tpu.put(40), ray_tpu.put(2)),
                       timeout=180) == 42
    mid = add.remote(1, 2)
    assert ray_tpu.get(add.remote(mid, 10), timeout=180) == 13

    @ray_tpu.remote
    def produce():
        return 5

    assert ray_tpu.get(add.remote(produce.remote(), 1), timeout=180) == 6
    # > max_direct_call_args_bytes: promoted to a store object client-side
    big = "a" * 500_000
    got = ray_tpu.get(ray_tpu.cpp_function("Concat").remote(big, "!"),
                      timeout=180)
    assert len(got) == 500_001 and got.endswith("!")
    # refs into actor methods
    kv = ray_tpu.cpp_actor_class("Kv").remote()
    ray_tpu.get(kv.put.remote("k", ray_tpu.put([1, 2, 3])), timeout=180)
    assert ray_tpu.get(kv.get.remote("k"), timeout=180) == [1, 2, 3]
    # failed upstream surfaces, doesn't hang
    bad = ray_tpu.cpp_function("Fail").remote("upstream-dead")
    with pytest.raises(ray_tpu.exceptions.TaskError):
        ray_tpu.get(add.remote(bad, 1), timeout=180)
    # refs into the CONSTRUCTOR resolve too (create_actor resolves the
    # markers before the factory runs) — not just method args
    c2 = ray_tpu.cpp_actor_class("Counter").remote(ray_tpu.put(100))
    assert ray_tpu.get(c2.inc.remote(), timeout=180) == 101


def test_cpp_large_results_ride_the_store(ray_start_regular):
    """Results above the inline threshold are sealed into the shm store
    by the native worker (cpp_store.h) and fetched like any store
    object; small results stay inline."""
    _tool("cpp_worker")
    blob = ray_tpu.get(ray_tpu.cpp_function("Blob").remote(4_000_000, "z"),
                       timeout=180)
    assert len(blob) == 4_000_000 and blob[:1] == b"z" and blob[-1:] == b"z"
    assert ray_tpu.get(ray_tpu.cpp_function("Blob").remote(10, "a"),
                       timeout=180) == b"a" * 10
    # big actor result through the same path; actor state unaffected
    c = ray_tpu.cpp_actor_class("Counter").remote(0)
    p = ray_tpu.get(c.payload.remote(1_500_000), timeout=180)
    assert len(p) == 1_500_000 and p[:1] == b"y"
    assert ray_tpu.get(c.inc.remote(), timeout=180) == 1


def test_cpp_actor_restart_after_worker_death(ray_start_regular):
    """The GCS restart FSM treats cpp actors like Python ones: killing
    the native worker process restarts the actor (fresh state, same
    handle) while max_restarts lasts."""
    import time
    _tool("cpp_worker")
    c = ray_tpu.cpp_actor_class("Counter", max_restarts=2).remote(0)
    assert ray_tpu.get(c.inc.remote(), timeout=120) == 1
    # the actor names its OWN process — no /proc guessing that could hit
    # another session's worker
    pid = ray_tpu.get(c.pid.remote(), timeout=120)
    assert os.readlink(f"/proc/{pid}/exe").endswith("cpp_worker")
    os.kill(pid, 9)

    deadline = time.monotonic() + 120
    while True:
        try:
            # idempotent probe: a timed-out-but-executed attempt can't
            # skew the asserted state the way a retried inc() would
            total = ray_tpu.get(c.total.remote(), timeout=30)
            break
        except (ray_tpu.exceptions.RayTpuError, ConnectionError):
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)
    assert total == 0  # fresh instance from the factory args
    assert ray_tpu.get(c.pid.remote(), timeout=120) != pid
    assert ray_tpu.get(c.inc.remote(), timeout=120) == 1


def test_cpp_native_driver(ray_start_cluster):
    """The C++ user API binary joins the cluster as a driver: registers a
    job, leases a cpp worker via the standard lease protocol, runs tasks,
    sees failures (cpp_api.h — reference cpp/include/ray/api.h analog)."""
    demo = _tool("cpp_driver_demo")
    cluster = ray_start_cluster
    cluster.wait_for_nodes(1)
    node = cluster.head_node
    proc = subprocess.run(
        [demo,
         "--raylet-host", node.address[0],
         "--raylet-port", str(node.address[1]),
         "--gcs-host", cluster.gcs_address[0],
         "--gcs-port", str(cluster.gcs_address[1])],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "CPP_DRIVER_OK" in proc.stdout
    # the job the cpp driver registered reached the GCS and finished
    from ray_tpu.runtime.gcs import GcsClient
    client = GcsClient(cluster.gcs_address)
    try:
        jobs = client.call("list_jobs")
        cpp_jobs = [j for j in jobs if j.get("entrypoint") == "cpp-driver"]
        assert cpp_jobs and cpp_jobs[0]["state"] == "FINISHED"
    finally:
        client.close()
