"""Path partitioning (hive/dir layouts, pruning) + TFRecord round-trip.

Reference analogs: python/ray/data/datasource/partitioning.py and
tfrecords_datasource.py.  Pruning is verified structurally: excluded
partitions' files are never opened (a poison file in the pruned
partition would fail the read if touched).
"""

import os
import struct

import numpy as np
import pytest

import ray_tpu
from ray_tpu.data.partitioning import (Partitioning, PathPartitionFilter,
                                       PathPartitionParser)


def _hive_tree(tmp_path, fmt="parquet"):
    """year=2023..2024 / month=01..02 parquet/csv files, 3 rows each."""
    import pandas as pd
    n = 0
    for year in (2023, 2024):
        for month in ("01", "02"):
            d = tmp_path / f"year={year}" / f"month={month}"
            d.mkdir(parents=True)
            df = pd.DataFrame({"v": [n, n + 1, n + 2]})
            if fmt == "parquet":
                df.to_parquet(d / "part.parquet")
            else:
                df.to_csv(d / "part.csv", index=False)
            n += 3
    return str(tmp_path)


def test_parser_hive_and_dir():
    p = PathPartitionParser(Partitioning("hive", base_dir="/lake"))
    assert p("/lake/year=2024/month=06/f.parquet") == {
        "year": "2024", "month": "06"}
    d = PathPartitionParser(Partitioning("dir", base_dir="/lake",
                                         field_names=["year", "month"]))
    assert d("/lake/2024/06/f.parquet") == {"year": "2024", "month": "06"}
    with pytest.raises(ValueError):
        Partitioning("dir")          # dir style needs field_names
    with pytest.raises(ValueError):
        Partitioning("zebra")


def test_parser_base_dir_anchored_at_component_boundary():
    # base "data" must not match inside "/mydata/": only a whole path
    # component splits, so the hive pairs under the real "data" dir win
    p = PathPartitionParser(Partitioning("hive", base_dir="data"))
    assert p("/mydata/data/year=2024/f.parquet") == {"year": "2024"}
    # no component-anchored occurrence at all -> no split, fall through
    # to scanning the whole path for hive pairs
    assert p("/mydata/year=2024/f.parquet") == {"year": "2024"}
    # dir style: a substring match would shift every positional field
    d = PathPartitionParser(Partitioning("dir", base_dir="lake",
                                         field_names=["year"]))
    assert d("/datalake/lake/2024/f.csv") == {"year": "2024"}


def test_read_parquet_hive_pruning(ray_start_regular, tmp_path):
    base = _hive_tree(tmp_path)
    # a poison file inside the pruned partition: opening it would raise,
    # so passing proves pruning happened on PATHS, not post-read
    poison = os.path.join(base, "year=2023", "month=01", "bad.parquet")
    os.rename(os.path.join(base, "year=2023", "month=01", "part.parquet"),
              poison + ".real")
    with open(poison, "wb") as f:
        f.write(b"this is not parquet")
    os.rename(poison + ".real",
              os.path.join(base, "year=2023", "month=01", "part.parquet"))

    import ray_tpu.data as data
    flt = PathPartitionFilter.of(
        lambda v: v.get("year") == "2024", base_dir=base)
    ds = data.read_parquet(base, partition_filter=flt)
    rows = ds.take_all()
    assert len(rows) == 6                       # only year=2024 rows
    assert {r["year"] for r in rows} == {"2024"}      # enrichment
    assert {r["month"] for r in rows} == {"01", "02"}
    assert sorted(r["v"] for r in rows) == [6, 7, 8, 9, 10, 11]


def test_read_csv_partition_columns(ray_start_regular, tmp_path):
    base = _hive_tree(tmp_path, fmt="csv")
    import ray_tpu.data as data
    ds = data.read_csv(base, partitioning=Partitioning("hive",
                                                       base_dir=base))
    rows = ds.take_all()
    assert len(rows) == 12
    assert {(r["year"], r["month"]) for r in rows} == {
        ("2023", "01"), ("2023", "02"), ("2024", "01"), ("2024", "02")}


def test_tfrecords_roundtrip(ray_start_regular, tmp_path):
    """write_tfrecords -> read_tfrecords preserves bytes/str/int/float
    scalars and lists (tf.train.Example without a tensorflow dep)."""
    import ray_tpu.data as data
    rows = [
        {"i": 7, "f": 1.5, "s": "hello", "b": b"\x00\xff",
         "vec": [1.0, 2.0, 3.0], "ids": [4, 5, 6]},
        {"i": -3, "f": -0.25, "s": "über", "b": b"", "vec": [9.0],
         "ids": [0]},
    ]
    ds = data.from_items(rows)
    out = ds.write_tfrecords(str(tmp_path / "out"))
    assert out and all(p.endswith(".tfrecords") for p in out)

    back = data.read_tfrecords(str(tmp_path / "out")).take_all()
    assert len(back) == 2
    by_i = {r["i"]: r for r in back}
    assert by_i[7]["s"] == b"hello"       # strings ride BytesList
    assert by_i[7]["b"] == b"\x00\xff"
    assert by_i[7]["vec"] == [1.0, 2.0, 3.0]
    assert by_i[7]["ids"] == [4, 5, 6]
    assert by_i[-3]["i"] == -3            # zigzag-free signed int64
    assert by_i[-3]["f"] == -0.25
    assert by_i[-3]["vec"] == 9.0         # singleton unwraps


def test_tfrecords_crc_guard(tmp_path):
    """A corrupted record fails loudly, not with garbage rows."""
    from ray_tpu.data.tfrecords import (read_tfrecord_file,
                                        write_tfrecord_file)
    path = str(tmp_path / "x.tfrecords")
    write_tfrecord_file(path, [{"a": 1}])
    blob = bytearray(open(path, "rb").read())
    blob[-5] ^= 0xFF                      # flip a payload byte
    with open(path, "wb") as f:
        f.write(blob)
    with pytest.raises(ValueError, match="crc"):
        read_tfrecord_file(path)


def test_tfrecords_numpy_features(ray_start_regular, tmp_path):
    """numpy arrays/scalars in rows encode as packed lists."""
    import ray_tpu.data as data
    ds = data.from_items([{"x": np.arange(4, dtype=np.int64),
                           "y": np.float32(2.5)}])
    ds.write_tfrecords(str(tmp_path / "np"))
    back = data.read_tfrecords(str(tmp_path / "np")).take_all()
    assert back[0]["x"] == [0, 1, 2, 3]
    assert back[0]["y"] == 2.5


def test_read_mongo_requires_pymongo():
    import ray_tpu.data as data
    try:
        import pymongo  # noqa: F401
        pytest.skip("pymongo present; gate not exercisable")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="pymongo"):
        data.read_mongo("mongodb://x", "db", "coll")
