"""Tests for the TPE searcher, HyperBand scheduler, callbacks, and
ExperimentAnalysis (model: reference tune/tests/test_trial_scheduler.py,
test_searchers.py, test_callbacks.py, test_experiment_analysis.py)."""

import json
import os

from ray_tpu.air import RunConfig, session
from ray_tpu.tune import (Callback, CSVLoggerCallback, ExperimentAnalysis,
                          HyperBandScheduler, JsonLoggerCallback,
                          TPESearcher, TuneBOHB, TuneConfig, Tuner,
                          grid_search, uniform)


def test_tpe_searcher_biases_toward_optimum():
    space = {"x": uniform(-1.0, 1.0)}
    s = TPESearcher(space, metric="score", mode="max", n_initial=6,
                    n_candidates=16, seed=0)
    for i in range(20):
        cfg = s.suggest(f"t{i}")
        s.on_trial_complete(f"t{i}", {"score": -abs(cfg["x"] - 0.5)})
    later = [s.suggest(f"u{i}")["x"] for i in range(10)]
    # suggestions concentrate near the optimum at 0.5
    assert sum(abs(x - 0.5) for x in later) / len(later) < 0.4
    assert TuneBOHB is TPESearcher


def test_tpe_categorical_dims():
    from ray_tpu.tune import choice
    space = {"c": choice(["good", "bad"])}
    s = TPESearcher(space, metric="score", mode="max", n_initial=6, seed=1)
    for i in range(20):
        cfg = s.suggest(f"t{i}")
        s.on_trial_complete(
            f"t{i}", {"score": 1.0 if cfg["c"] == "good" else 0.0})
    later = [s.suggest(f"u{i}")["c"] for i in range(12)]
    assert later.count("good") > later.count("bad")


def test_hyperband_bracket_unit():
    sched = HyperBandScheduler(metric="score", mode="max", grace_period=1,
                               reduction_factor=2, max_t=8)

    class T:
        def __init__(self, tid):
            self.trial_id = tid
            self.status = "RUNNING"

    class R:
        trials = []

    a, b = T("a"), T("b")
    # both trials join the bracket at creation (on_trial_add)
    sched.on_trial_add(R, a)
    sched.on_trial_add(R, b)
    # first to hit the rung waits for its peer
    d1 = sched.on_trial_result(R, a, {"training_iteration": 1, "score": 1.0})
    assert d1 == "PAUSE"
    # when b reports the rung, the rung completes: b (better) advances
    d2 = sched.on_trial_result(R, b, {"training_iteration": 1, "score": 5.0})
    assert d2 == "CONTINUE"
    bracket = sched._bracket_of[a.trial_id]
    assert a.trial_id in bracket.done
    assert bracket is sched._bracket_of[b.trial_id]


def test_hyperband_integration(ray_start_regular):
    def trainable(config):
        for i in range(8):
            session.report({"score": config["q"] * (i + 1)})

    tuner = Tuner(
        trainable,
        param_space={"q": grid_search([1.0, 4.0, 8.0, 16.0])},
        tune_config=TuneConfig(
            metric="score", mode="max", max_concurrent_trials=4,
            scheduler=HyperBandScheduler(metric="score", mode="max",
                                         grace_period=2,
                                         reduction_factor=2, max_t=8)))
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.metrics["config"]["q"] == 16.0


def test_callbacks_and_analysis(ray_start_regular, tmp_path):
    events = []

    class Probe(Callback):
        def on_trial_start(self, iteration, trials, trial):
            events.append(("start", trial.trial_id))

        def on_trial_result(self, iteration, trials, trial, result):
            events.append(("result", trial.trial_id))

        def on_trial_complete(self, iteration, trials, trial):
            events.append(("complete", trial.trial_id))

        def on_experiment_end(self, trials):
            events.append(("end", None))

    def trainable(config):
        for i in range(2):
            session.report({"score": config["lr"] * (i + 1)})

    tuner = Tuner(
        trainable,
        param_space={"lr": grid_search([1.0, 2.0])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(
            name="cbexp", storage_path=str(tmp_path),
            callbacks=[Probe(), JsonLoggerCallback(),
                       CSVLoggerCallback()]))
    grid = tuner.fit()
    assert not grid.errors
    kinds = [k for k, _ in events]
    assert kinds.count("start") >= 2
    assert kinds.count("complete") == 2
    assert kinds[-1] == "end"
    assert "result" in kinds

    exp_dir = os.path.join(str(tmp_path), "cbexp")
    # logger callbacks wrote per-trial files
    trial_dirs = [d for d in os.listdir(exp_dir) if d.startswith("trial_")]
    assert trial_dirs
    for d in trial_dirs:
        assert os.path.exists(os.path.join(exp_dir, d, "results.json"))
        assert os.path.exists(os.path.join(exp_dir, d, "progress.csv"))

    # analysis over the written experiment
    ana = ExperimentAnalysis(exp_dir, default_metric="score",
                             default_mode="max")
    assert len(ana.trial_ids) == 2
    best_cfg = ana.get_best_config()
    assert best_cfg["lr"] == 2.0
    last = ana.get_last_results()
    assert all(r["score"] > 0 for r in last.values())


def test_tpe_tuner_integration(ray_start_regular):
    def trainable(config):
        session.report({"score": -(config["x"] - 0.3) ** 2})

    tuner = Tuner(
        trainable,
        param_space={"x": uniform(-1.0, 1.0)},
        tune_config=TuneConfig(
            metric="score", mode="max", num_samples=12,
            search_alg=TPESearcher({"x": uniform(-1.0, 1.0)},
                                   metric="score", mode="max",
                                   n_initial=4, seed=0)))
    grid = tuner.fit()
    best = grid.get_best_result()
    assert abs(best.metrics["config"]["x"] - 0.3) < 0.6
