"""Encoder-family model tests: ViT, BERT (MLM), T5 — forward shapes,
masking semantics, and sharded training on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import (BERT, T5, ViT, get_config, get_vit_config,
                            masked_batch, mlm_loss_fn, seq2seq_loss_fn,
                            t5_init_inputs)
from ray_tpu.models.t5 import greedy_decode
from ray_tpu.parallel import MeshConfig, build_mesh
from ray_tpu.train.step import OptimizerConfig, make_sharded_train, \
    make_vision_train


def test_vit_forward_shapes():
    cfg = get_vit_config("vit-tiny-test")
    model = ViT(cfg)
    imgs = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), imgs)
    logits = model.apply(variables, imgs)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


def test_vit_trains_sharded():
    cfg = get_vit_config("vit-tiny-test")
    mesh = build_mesh(MeshConfig(data=-1))
    model = ViT(cfg, mesh=mesh)
    rng = np.random.default_rng(0)
    batch = {"image": jnp.asarray(rng.normal(size=(8, 32, 32, 3)),
                                  jnp.float32),
             "label": jnp.asarray(rng.integers(0, 10, 8), jnp.int32)}
    init_fn, step_fn, _, _ = make_vision_train(
        model, mesh, OptimizerConfig(warmup_steps=1, decay_steps=10),
        example_batch=batch)
    state = init_fn(jax.random.PRNGKey(0), batch)
    losses = []
    for _ in range(5):
        state, metrics = step_fn(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]          # memorizes one batch
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0


def test_bert_mask_changes_output():
    cfg = get_config("tiny", max_seq_len=32)
    model = BERT(cfg)
    toks = jnp.asarray(np.random.default_rng(0).integers(1, 256, (2, 16)),
                       jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), toks)
    full = model.apply(variables, toks)
    assert full.shape == (2, 16, 256)
    # masking the second half must change the first half's logits
    mask = np.ones((2, 16), np.int32)
    mask[:, 8:] = 0
    part = model.apply(variables, toks, jnp.asarray(mask))
    assert not np.allclose(np.asarray(full)[:, :8],
                           np.asarray(part)[:, :8], atol=1e-5)


def test_masked_batch_corruption():
    toks = np.random.default_rng(0).integers(5, 250, (4, 64))
    out = masked_batch(toks, 256, mask_token=3, mask_prob=0.25, seed=1)
    sel = out["labels"] != -100
    assert 0.05 < sel.mean() < 0.5
    # labels hold the originals at selected positions
    np.testing.assert_array_equal(out["labels"][sel], toks[sel])
    # most selected positions got the mask token
    assert (out["tokens"][sel] == 3).mean() > 0.5
    # unselected positions untouched
    np.testing.assert_array_equal(out["tokens"][~sel], toks[~sel])


def test_bert_mlm_trains_sharded():
    cfg = get_config("tiny", max_seq_len=32)
    mesh = build_mesh(MeshConfig(data=-1))
    model = BERT(cfg, mesh=mesh)
    rng = np.random.default_rng(0)
    toks = rng.integers(5, 250, (8, 32))
    mb = masked_batch(toks, cfg.vocab_size, mask_token=3, seed=0)
    batch = {"tokens": jnp.asarray(mb["tokens"], jnp.int32),
             "labels": jnp.asarray(mb["labels"], jnp.int32)}
    init_fn, step_fn, _, _ = make_sharded_train(
        model, mesh, OptimizerConfig(warmup_steps=1, decay_steps=20),
        loss_fn=mlm_loss_fn, example_batch=batch,
        init_inputs=lambda b: (b["tokens"],))
    state = init_fn(jax.random.PRNGKey(0), batch)
    losses = []
    for _ in range(6):
        state, metrics = step_fn(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert float(metrics["masked_tokens"]) > 0


def test_t5_forward_and_train():
    cfg = get_config("tiny", max_seq_len=32)
    mesh = build_mesh(MeshConfig(data=-1))
    model = T5(cfg, mesh=mesh)
    rng = np.random.default_rng(0)
    batch = {"enc_tokens": jnp.asarray(rng.integers(1, 256, (8, 12)),
                                       jnp.int32),
             "dec_tokens": jnp.asarray(rng.integers(1, 256, (8, 9)),
                                       jnp.int32)}
    init_fn, step_fn, _, _ = make_sharded_train(
        model, mesh, OptimizerConfig(warmup_steps=1, decay_steps=20),
        loss_fn=seq2seq_loss_fn, example_batch=batch,
        init_inputs=t5_init_inputs)
    state = init_fn(jax.random.PRNGKey(0), batch)
    losses = []
    for _ in range(6):
        state, metrics = step_fn(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


def test_t5_enc_mask_respected():
    cfg = get_config("tiny", max_seq_len=32)
    model = T5(cfg)
    rng = np.random.default_rng(1)
    enc = jnp.asarray(rng.integers(1, 256, (2, 10)), jnp.int32)
    dec = jnp.asarray(rng.integers(1, 256, (2, 6)), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), enc, dec)
    full = model.apply(variables, enc, dec)
    mask = np.ones((2, 10), np.int32)
    mask[:, 5:] = 0
    part = model.apply(variables, enc, dec, jnp.asarray(mask))
    assert full.shape == (2, 6, 256)
    assert not np.allclose(np.asarray(full), np.asarray(part), atol=1e-5)


def test_t5_greedy_decode():
    cfg = get_config("tiny", max_seq_len=32)
    model = T5(cfg)
    enc = jnp.asarray(np.random.default_rng(2).integers(1, 256, (2, 8)),
                      jnp.int32)
    dec = jnp.zeros((2, 4), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), enc, dec)
    out = greedy_decode(model, variables, enc, max_len=5, bos_id=1)
    assert out.shape == (2, 5)
    assert out.dtype == jnp.int32


def test_attention_mask_op():
    from ray_tpu.ops.attention import xla_attention
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, 4, 2, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 6, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 6, 2, 8)), jnp.float32)
    full = xla_attention(q, k, v, causal=False)
    mask = jnp.asarray([[True] * 3 + [False] * 3])
    part = xla_attention(q, k, v, causal=False, mask=mask)
    # masked result equals attention over only the first 3 keys
    ref = xla_attention(q, k[:, :3], v[:, :3], causal=False)
    np.testing.assert_allclose(np.asarray(part), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(np.asarray(full), np.asarray(part))
