"""Randomized chaos tests: unplanned node loss under live load (cf.
reference chaos_test suite + NodeKiller, _private/test_utils.py:1301).

Runs under BOTH runtime sanitizers (docs/static_analysis.md): the
lock-order sanitizer and the shm-ring protocol checker, in this driver
process and — via the inherited env — in every daemon/worker the
cluster fixtures spawn.  Chaos exercises the widest concurrent surface
in the tree, so a lock-order inversion or ring-protocol break anywhere
in the kill/recovery paths fails loudly here instead of deadlocking
one run in a thousand."""

import threading
import time

import numpy as np

import pytest

from conftest import debug_sanitizers_enabled

import ray_tpu
from ray_tpu._private.chaos import NodeKiller


@pytest.fixture(scope="module", autouse=True)
def _debug_sanitizers():
    with debug_sanitizers_enabled():
        yield


def test_tasks_survive_random_node_kills(ray_start_cluster):
    """A task wave keeps completing correctly while random worker nodes
    die mid-run and replacements join: retries + lineage reconstruction
    under chaos, not scripted removal."""
    cluster = ray_start_cluster
    head_id = cluster.head_node.node_id
    for _ in range(2):
        cluster.add_node(resources={"CPU": 2})
    cluster.wait_for_nodes(3)
    ray_tpu.init(num_cpus=1, address=cluster.address)

    @ray_tpu.remote(num_cpus=1, max_retries=8)
    def work(i):
        time.sleep(0.1)
        return np.full(40_000, i, dtype=np.float64)  # shm-sized output

    killer = NodeKiller(cluster.gcs_address,
                        protected_node_ids=[head_id],
                        interval_s=3.0, max_kills=2, seed=7).start()
    try:
        refs = [work.remote(i) for i in range(60)]
        # add replacement capacity while the killer is active
        time.sleep(4.0)
        cluster.add_node(resources={"CPU": 2})
        values = ray_tpu.get(refs, timeout=300)
    finally:
        killer.stop()
    assert len(killer.kills) >= 1, "chaos never fired"
    for i, v in enumerate(values):
        assert float(v[0]) == float(i)
    ray_tpu.shutdown()


def test_actor_survives_chaos_with_restarts(ray_start_cluster):
    """A restartable actor pinned off-head keeps serving across a chaos
    kill of its node (state resets, availability recovers)."""
    cluster = ray_start_cluster
    head_id = cluster.head_node.node_id
    cluster.add_node(resources={"CPU": 2, "pin": 2})
    cluster.wait_for_nodes(2)
    ray_tpu.init(num_cpus=1, address=cluster.address)

    @ray_tpu.remote(resources={"pin": 1}, max_restarts=4)
    class Echo:
        def ping(self, x):
            return x

    e = Echo.remote()
    assert ray_tpu.get(e.ping.remote(1), timeout=60) == 1
    killer = NodeKiller(cluster.gcs_address, protected_node_ids=[head_id],
                        interval_s=3600, seed=3)
    assert killer.kill_one() is not None
    cluster.add_node(resources={"CPU": 2, "pin": 2})
    deadline = time.monotonic() + 120
    while True:
        try:
            assert ray_tpu.get(e.ping.remote(2), timeout=60) == 2
            break
        except ray_tpu.exceptions.RayTpuError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)
    ray_tpu.shutdown()


def test_shuffle_survives_node_kills_mid_transfer(ray_start_cluster,
                                                  monkeypatch):
    """A multi-node random_shuffle completes correctly while the NodeKiller
    fires every few seconds: blocks are mid-chunked-transfer when their
    nodes die (tiny transfer chunks force multi-chunk pulls), so recovery
    exercises _restore_one/_try_reconstruct under real racing (reference
    chaos shuffle runs, test_utils.py:1301 NodeKillerActor)."""
    # 128 KiB chunks: a 1 MiB block moves in 8 chunks per pull
    monkeypatch.setenv("RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES", "131072")
    cluster = ray_start_cluster
    head_id = cluster.head_node.node_id
    for _ in range(2):
        cluster.add_node(resources={"CPU": 2})
    cluster.wait_for_nodes(3)
    ray_tpu.init(num_cpus=1, address=cluster.address)
    from ray_tpu import data

    n = 1 << 18   # 256k rows -> 8 blocks x ~256 KiB
    killer = NodeKiller(cluster.gcs_address, protected_node_ids=[head_id],
                        interval_s=4.0, max_kills=2, seed=11).start()
    try:
        shuffled = data.range(n, parallelism=8).random_shuffle(seed=5)
        # replacement capacity joins while the killer is live
        time.sleep(2.0)
        cluster.add_node(resources={"CPU": 2})
        total = shuffled.count()
        # correctness, not just liveness: every row exactly once
        parts = shuffled.map_batches(
            lambda b: {"s": np.asarray([b["id"].sum()], dtype=np.int64)})
        checksum = sum(int(r["s"]) for r in parts.take_all())
    finally:
        killer.stop()
    assert len(killer.kills) >= 1, "chaos never fired"
    assert total == n
    assert checksum == n * (n - 1) // 2
    ray_tpu.shutdown()


def test_striped_pull_fails_over_when_source_node_killed(
        ray_start_cluster, tmp_path, monkeypatch):
    """SIGKILLing one of two source nodes mid-striped-pull re-queues only
    that source's outstanding chunk ranges onto the survivor: the pull
    completes with correct bytes and the producer is never re-executed
    (the transfer failed over, it didn't restart through lineage
    reconstruction) — docs/object_transfer.md striping/failover."""
    # 128 KiB chunks: 16 MiB moves in 128 chunks, so the kill lands while
    # both sources still hold outstanding ranges
    monkeypatch.setenv("RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES", "131072")
    import threading

    cluster = ray_start_cluster
    cluster.add_node(resources={"CPU": 2, "src": 2})
    node_dst = cluster.add_node(resources={"CPU": 2, "dst": 2})
    cluster.wait_for_nodes(3)
    ray_tpu.init(num_cpus=1, address=cluster.address)
    marker = str(tmp_path / "producer_runs.txt")
    n = 2 * 1024 * 1024

    @ray_tpu.remote(resources={"src": 1}, num_cpus=1)
    def produce():
        with open(marker, "a") as f:
            f.write("x")
        return np.arange(n, dtype=np.float64)

    @ray_tpu.remote(resources={"dst": 1}, num_cpus=1)
    def consume(x):
        return float(x[-1])

    ref = produce.remote()
    assert ray_tpu.get(consume.remote(ref), timeout=120) == float(n - 1)
    # wait for the dst copy to be reported back to the owner so the
    # driver's pull genuinely stripes across two sources
    from ray_tpu.runtime.core_worker import get_global_worker
    w = get_global_worker()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        with w._owned_lock:
            locs = set(w._owned[ref.id].locations)
        if len(locs) >= 2:
            break
        time.sleep(0.1)
    assert len(locs) >= 2, f"object never replicated: {locs}"

    # state-based kill trigger (was a fixed 30 ms sleep, load-flaky:
    # on a busy box the sleep could outlive the whole pull, so no
    # failover ever happened and the TRANSFER_FAILOVER assert fired).
    # The driver's per-chunk RTT histogram counts every chunk the pull
    # lands, so fire once a few chunks of this pull have moved — the
    # kill is then guaranteed mid-transfer with ~most of the 128 chunk
    # ranges still outstanding, however loaded the box is.
    from ray_tpu._private import runtime_metrics as rtm

    def _chunks_landed():
        rec = rtm.snapshot().get("ray_tpu_pull_chunk_rtt_ms")
        return rec["values"]["{}"]["count"] if rec else 0.0

    chunks_before = _chunks_landed()

    def kill_dst():
        d = time.monotonic() + 20
        while (time.monotonic() < d
               and _chunks_landed() < chunks_before + 4):
            time.sleep(0.002)
        cluster.remove_node(node_dst)  # SIGKILL

    w._memory_cache.clear()
    t = threading.Thread(target=kill_dst, daemon=True)
    t.start()
    value = ray_tpu.get(ref, timeout=120)
    t.join(timeout=30)
    assert value.shape == (n,)
    assert float(value[0]) == 0.0
    assert float(value[-1]) == float(n - 1)
    assert bool((value[:: n // 64] ==
                 np.arange(n, dtype=np.float64)[:: n // 64]).all())
    # failover, not lineage re-execution: the producer ran exactly once
    assert open(marker).read() == "x"
    # forensics (docs/observability.md): the failover left a typed
    # event, and the dead node has a driver-retrievable dossier naming
    # it with >=1 event explaining the death
    from ray_tpu.experimental import state
    deadline = time.monotonic() + 60
    failovers, dossier = [], None
    while time.monotonic() < deadline:
        failovers = state.list_cluster_events(type="TRANSFER_FAILOVER")
        dossier = state.get_dossier(node_dst.node_id)
        if failovers and dossier is not None:
            break
        time.sleep(0.5)
    assert failovers, "no TRANSFER_FAILOVER event reached the GCS"
    assert dossier is not None, "no dossier for the killed node"
    assert dossier["kind"] == "node"
    assert dossier["node_id"] == node_dst.node_id
    assert any(e.get("type") == "NODE_DEAD" or "heartbeat"
               in str(dossier.get("reason", ""))
               for e in [dossier] + list(dossier.get("events") or [])), \
        dossier
    dead_events = state.list_cluster_events(type="NODE_DEAD",
                                            node_id=node_dst.node_id)
    assert dead_events, "no NODE_DEAD event for the killed node"
    # the recovery-SLO auditor folded the same events into its transfer
    # ledger: every TRANSFER_FAILOVER counted, broken down by outcome
    rstats = state.recovery_stats()
    assert rstats["transfer_failovers"] >= len(failovers)
    assert sum(rstats["transfer_by_outcome"].values()) == \
        rstats["transfer_failovers"]
    ray_tpu.shutdown()


def test_replica_kill_heal_episode_audited():
    """Serve-pool chaos for the auditor's third episode kind: kill a
    serving replica under steady load — the controller's REPLICA_RETIRED
    ("unhealthy") opens the heal episode — then push the load past the
    autoscaling target so the next AUTOSCALE target change closes it.
    Pool-heal latency is derived entirely from the serve controller's
    own event stream and cross-checked here against the raw event
    timestamps the auditor folded."""
    import threading

    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu.experimental import state
    from ray_tpu.serve.controller import REPLICA_PREFIX, SERVE_NAMESPACE

    name = "heal-gate"
    stop = threading.Event()
    rt.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        serve.start()

        @serve.deployment(max_concurrent_queries=8, autoscaling_config={
            "min_replicas": 1, "max_replicas": 3,
            "target_num_ongoing_requests_per_replica": 2.0,
            "upscale_delay_s": 0.5,
            # a scale-DOWN would close the heal episode with the wrong
            # target change: park downscaling outside the test window
            "downscale_delay_s": 600.0})
        def slow(x):
            time.sleep(0.25)
            return x

        handle = serve.run(slow.bind(), name=name)
        assert rt.get(handle.remote(0), timeout=120) == 0

        depth = [4]   # open-loop depth; the second wave raises it to 8

        def load():
            pending = [handle.remote(i) for i in range(depth[0])]
            while not stop.is_set():
                try:
                    done, pending = rt.wait(pending, num_returns=1,
                                            timeout=120)
                    rt.get(done, timeout=60)
                except Exception:
                    pass   # a request died with the killed replica
                while len(pending) < depth[0] and not stop.is_set():
                    pending.append(handle.remote(0))
            try:
                rt.get(pending, timeout=120)
            except Exception:
                pass

        t = threading.Thread(target=load, daemon=True)
        t.start()

        # wave 1: depth 4 over target_ongoing 2.0 -> the controller
        # scales 1 -> 2 (this AUTOSCALE precedes the chaos, so it must
        # NOT close anything — no heal episode is open yet)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            st = serve.status()[name]
            if st["target_replicas"] == 2 and len(st["replicas"]) == 2:
                break
            time.sleep(0.3)
        else:
            raise AssertionError(f"never scaled to 2: {serve.status()}")

        # chaos: SIGKILL one serving replica mid-load
        victim_tag = list(st["replicas"])[0]
        rt.kill(rt.get_actor(REPLICA_PREFIX + victim_tag,
                             namespace=SERVE_NAMESPACE))
        retired = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            evs = [e for e in state.list_cluster_events(
                       type="REPLICA_RETIRED")
                   if e.get("replica") == victim_tag]
            if evs:
                retired = evs[-1]
                break
            time.sleep(0.3)
        assert retired is not None, \
            "controller never retired the dead replica"
        assert retired["reason"] == "unhealthy"
        assert retired["severity"] == "WARNING"

        # wave 2: depth 8 over 2 serving -> desired ceil(8/2)=4 capped
        # at max_replicas=3 -> AUTOSCALE 2 -> 3 heals the pool and
        # closes the episode
        depth[0] = 8
        autoscale = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            evs = [e for e in state.list_cluster_events(type="AUTOSCALE")
                   if e.get("deployment") == name
                   and e.get("new_target") == 3]
            if evs:
                autoscale = evs[-1]
                break
            time.sleep(0.3)
        assert autoscale is not None, \
            "load surge never drove a target change"
        stop.set()
        t.join(timeout=120)

        # the auditor's heal episode tells the same story as the raw
        # REPLICA_RETIRED/AUTOSCALE pair it folded
        ep = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            eps = [e for e in state.list_recovery_episodes(
                       kind="heal", include_open=False)
                   if e.get("deployment") == name]
            if eps:
                ep = eps[-1]
                break
            time.sleep(0.3)
        assert ep is not None, "auditor never closed the heal episode"
        assert ep["opening_type"] == "REPLICA_RETIRED"
        assert ep["closing_type"] == "AUTOSCALE"
        assert ep["replica"] == victim_tag and ep["retired"] == 1
        assert ep["reason"] == "unhealthy"
        assert ep["old_target"] == 2 and ep["new_target"] == 3
        assert abs(ep["latency_s"]
                   - (autoscale["ts"] - retired["ts"])) < 0.05
        # default pool-heal SLO (recovery_slo_heal_s): 90 s
        assert ep["slo_s"] == 90.0
        assert ep["violation"] == (ep["latency_s"] > 90.0)

        from conftest import record_recovery_row
        record_recovery_row({
            "name": "heal", "latency_s": ep["latency_s"],
            "retired": ep["retired"], "slo_s": ep["slo_s"],
            "violation": ep["violation"],
            "reference": "tests/test_chaos.py::"
                         "test_replica_kill_heal_episode_audited"})
    finally:
        stop.set()
        try:
            serve.shutdown()
        except Exception:
            pass
        rt.shutdown()


def test_impala_podracer_survives_rollout_actor_kill():
    """Podracer fleet chaos (docs/rl_podracer.md failure semantics): kill
    one free-running rollout actor mid-IMPALA-run.  The learner must
    never stall — every train() during the outage keeps consuming the
    surviving actors' streams and advancing timesteps — while a
    replacement rendezvouses on a side thread, pulls current weights
    multi-source, and rejoins the fleet.  The RL_ACTOR_LOST/JOINED event
    pair is folded by the recovery auditor into an rl_actor episode
    whose latency matches the raw event timestamps."""
    import ray_tpu as rt
    from ray_tpu.experimental import state
    from ray_tpu.rl.impala import ImpalaConfig

    rt.init(num_cpus=8, object_store_memory=256 * 1024 * 1024)
    algo = None
    try:
        algo = (ImpalaConfig().environment("CartPole-v1")
                .rollouts(num_rollout_workers=3,
                          rollout_fragment_length=25)
                .training(batches_per_step=4)
                .debugging(seed=0)
                .podracer()
                .build())
        ex = algo.podracer
        r = algo.train()
        assert r["timesteps_total"] > 0

        rt.kill(ex._slots[1]["actor"])

        # the learner never stalls: with 2 surviving free-running
        # streams every iteration of the outage window still advances
        # timesteps (a stall would TimeoutError inside train())
        ts_prev = r["timesteps_total"]
        deadline = time.monotonic() + 180
        while (ex.telemetry["replacements"] < 1
               and time.monotonic() < deadline):
            r = algo.train()
            assert r["timesteps_total"] > ts_prev, \
                "learner stalled during actor outage"
            ts_prev = r["timesteps_total"]
        assert ex.telemetry["replacements"] >= 1, \
            "replacement actor never joined"
        # steady-state windows stayed submission-free through the chaos
        assert ex.telemetry["classic_submits_steady"] == 0

        lost = None
        joined = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not (lost and joined):
            lost = next((e for e in state.list_cluster_events(
                type="RL_ACTOR_LOST") if e.get("run_id") == ex.run_id
                and e.get("slot") == 1), None)
            joined = next((e for e in state.list_cluster_events(
                type="RL_ACTOR_JOINED") if e.get("run_id") == ex.run_id
                and e.get("slot") == 1), None)
            time.sleep(0.3)
        assert lost is not None, "RL_ACTOR_LOST never reached the GCS"
        assert joined is not None, "RL_ACTOR_JOINED never reached the GCS"
        # the rejoin rendezvous pulled CURRENT weights (not version 1:
        # the learner kept publishing throughout the outage)
        assert joined["weight_version"] > 1
        assert joined.get("weight_pull_ms", 0) >= 0

        ep = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and ep is None:
            eps = [e for e in state.list_recovery_episodes(
                       kind="rl_actor", include_open=False)
                   if e.get("key") == f"{ex.run_id}/1"]
            if eps:
                ep = eps[-1]
            else:
                time.sleep(0.3)
        assert ep is not None, "auditor never closed the rl_actor episode"
        assert ep["opening_type"] == "RL_ACTOR_LOST"
        assert ep["closing_type"] == "RL_ACTOR_JOINED"
        assert abs(ep["latency_s"] - (joined["ts"] - lost["ts"])) < 0.05
        assert ep["weight_version"] == joined["weight_version"]
        # default rl_actor SLO (recovery_slo_rl_actor_s): 60 s
        assert ep["slo_s"] == 60.0
        assert ep["violation"] == (ep["latency_s"] > 60.0)

        # post-rejoin the full fleet trains on
        r = algo.train()
        assert r["timesteps_total"] > ts_prev

        from conftest import record_recovery_row
        record_recovery_row({
            "name": "rl_actor_rejoin", "latency_s": ep["latency_s"],
            "weight_version": ep["weight_version"],
            "slo_s": ep["slo_s"], "violation": ep["violation"],
            "reference": "tests/test_chaos.py::"
                         "test_impala_podracer_survives_rollout_actor_kill"})
    finally:
        if algo is not None:
            algo.stop()
        rt.shutdown()


def test_disagg_serving_survives_replica_chaos():
    """Disaggregated LLM serving under replica chaos (docs/
    serve_disagg.md failure semantics): while 8 streams run against a
    2-prefill + 2-decode app, one PREFILL replica and one BUSY DECODE
    replica are killed mid-flight.  Every stream must complete with its
    full token count — prefill deaths re-route/re-prefill, decode
    deaths surface a mid-stream retry, and the controller respawns
    both pools back to target."""
    import asyncio

    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu.serve.controller import REPLICA_PREFIX, SERVE_NAMESPACE

    rt.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    try:
        serve.start()
        # 2 decode slots for ~4 streams per replica: the queued streams
        # keep every decode replica's num_ongoing > 0 for the whole
        # first wave.  With 4 slots the tiny engine generates eagerly
        # and can finish ALL streams server-side before a loaded driver
        # reaches token 2 of stream 0 — the kill then hits an idle
        # replica, no stream observes a retry, and the assert below
        # fires (the load-flake this shape deflakes).
        serve.run(serve.llm.build_app(
            preset="tiny", disaggregated=True, num_replicas=2,
            prefill_replicas=2, num_slots=2, block_size=4, page_size=8,
            max_concurrent_queries=32))
        handle = serve.llm.disagg_handle("tiny")

        async def one(i):
            toks, summary, retries = [], None, 0
            async for item in handle.stream(
                    {"prompt": [i + 1, i + 2, i + 3],
                     "max_new_tokens": 16, "temperature": 0.0}):
                if "token" in item:
                    toks.append(item["token"])
                elif "retry" in item:
                    retries = item["retry"]
                else:
                    summary = item
            return toks, summary, retries

        killed_actor_ids = []
        chaos = {"fired": False}
        stop = threading.Event()

        def _watch_and_kill():
            # state-based trigger: fire the moment ANY decode replica
            # reports an in-flight stream (server-side num_ongoing),
            # instead of waiting for a client-side token count — under
            # load the tiny engine can finish every stream server-side
            # before a starved driver coroutine sees token 2, and a
            # count-triggered kill then hits only idle replicas (the
            # load-flake this watcher deflakes).  Killing a decode
            # replica WHILE it owns a stream guarantees some stream
            # observes the death and retries.
            deadline = time.monotonic() + 60
            while not stop.is_set() and time.monotonic() < deadline:
                try:
                    st = serve.status()
                    for tag in st["llm-tiny-decode"]["replicas"]:
                        a = rt.get_actor(REPLICA_PREFIX + tag,
                                         namespace=SERVE_NAMESPACE)
                        if rt.get(a.get_metrics.remote(),
                                  timeout=30)["num_ongoing"] <= 0:
                            continue
                        # this decode replica is mid-stream: kill it...
                        killed_actor_ids.append(a._actor_id.hex())
                        rt.kill(a)
                        # ... and one prefill replica (any)
                        ptag = st["llm-tiny-prefill"]["replicas"][0]
                        pa = rt.get_actor(REPLICA_PREFIX + ptag,
                                          namespace=SERVE_NAMESPACE)
                        killed_actor_ids.append(pa._actor_id.hex())
                        rt.kill(pa)
                        chaos["fired"] = True
                        return
                except Exception:
                    # startup races (replica not registered yet) just
                    # mean "look again"
                    pass
                time.sleep(0.05)

        watcher = threading.Thread(target=_watch_and_kill, daemon=True)
        watcher.start()

        async def main():
            return await asyncio.gather(*[one(i) for i in range(8)])

        try:
            outs = asyncio.run(asyncio.wait_for(main(), timeout=300))
        finally:
            stop.set()
            watcher.join(timeout=60)
        assert chaos["fired"], "chaos never fired"
        for i, (toks, summary, _) in enumerate(outs):
            assert len(toks) == 16, (i, len(toks))
            assert summary is not None and \
                summary["finish_reason"] == "length"
        # at least one stream crossed a decode death and retried
        assert any(r >= 1 for _, _, r in outs), \
            "no stream observed the decode kill"
        # the controller heals both pools back to target
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            st = serve.status()
            if (len(st["llm-tiny-prefill"]["replicas"]) == 2
                    and len(st["llm-tiny-decode"]["replicas"]) == 2):
                break
            time.sleep(0.5)
        else:
            raise AssertionError(f"pools never healed: {serve.status()}")
        # forensics: each killed replica's worker left a WORKER_EXIT
        # event naming it, and its dossier is driver-retrievable with
        # >=1 event explaining the death (docs/observability.md)
        from ray_tpu.experimental import state
        assert killed_actor_ids
        for aid in killed_actor_ids:
            # generous: worker-death detection -> emit -> periodic
            # flush -> GCS apply is a multi-hop chain that a loaded
            # 1-CPU box stretches well past the old 60s
            deadline = time.monotonic() + 180
            exits, dossier = [], None
            while time.monotonic() < deadline:
                exits = state.list_cluster_events(type="WORKER_EXIT",
                                                  actor_id=aid)
                if exits:
                    dossier = state.get_dossier(exits[0]["worker_id"])
                if exits and dossier is not None:
                    break
                time.sleep(0.5)
            assert exits, f"no WORKER_EXIT event for actor {aid[:8]}"
            assert dossier is not None, \
                f"no dossier for actor {aid[:8]}'s worker"
            assert dossier["worker_id"] == exits[0]["worker_id"]
            assert dossier["actor_id"] == aid
            assert dossier.get("reason"), dossier
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        rt.shutdown()


def test_shuffle_with_unstable_slow_spill_storage(monkeypatch):
    """A shuffle whose working set overflows the store completes with 30%
    of spill writes failing and injected spill latency underneath
    (reference UnstableFileStorage/SlowFileStorage chaos cases,
    external_storage.py:587/608)."""
    import ray_tpu as rt
    rt.init(num_cpus=4, system_config={
        "object_store_memory_bytes": 24 * 1024 * 1024,
        "object_spill_failure_rate": 0.3,
        "object_spill_slow_ms": 20.0,
    })
    try:
        from ray_tpu import data
        n = 1 << 19   # ~4 MiB x 12 blocks round-tripping through spill
        ds = data.range(n, parallelism=12).random_shuffle(seed=3)
        assert ds.count() == n
        parts = ds.map_batches(
            lambda b: {"s": np.asarray([b["id"].sum()], dtype=np.int64)})
        checksum = sum(int(r["s"]) for r in parts.take_all())
        assert checksum == n * (n - 1) // 2
    finally:
        rt.shutdown()
