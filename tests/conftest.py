"""Test harness configuration.

Forces JAX onto the host platform with 8 virtual devices BEFORE jax is
imported anywhere, so every sharding/collective test runs against a simulated
8-chip mesh (SURVEY.md §4: the CPU-device-simulation analog of the reference's
fake-GPU yamls).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The environment's sitecustomize force-registers an `axon` TPU backend and
# overrides jax_platforms programmatically; put it back to host CPU for tests.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def ray_start_regular():
    """Start a fresh single-node ray_tpu instance for the test (head + 1 node)."""
    import ray_tpu
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    """Multi-daemon simulated cluster (cf. reference cluster_utils.Cluster)."""
    from ray_tpu.cluster_utils import Cluster
    cluster = Cluster()
    yield cluster
    cluster.shutdown()
