"""Test harness configuration.

Forces JAX onto the host platform with 8 virtual devices BEFORE jax is
imported anywhere, so every sharding/collective test runs against a simulated
8-chip mesh (SURVEY.md §4: the CPU-device-simulation analog of the reference's
fake-GPU yamls).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# Load calibration: this box runs the whole cluster under test on one
# core, so heartbeat/startup threads starve for seconds under a full
# suite.  The scale multiplies the liveness-patience flags
# (config._SCALED_FLAGS) in every daemon (env-inherited) AND the
# explicit get/wait timeouts tests pass (shim below).
os.environ.setdefault("RAY_TPU_TIMEOUT_SCALE", "4.0")
_TIMEOUT_SCALE = float(os.environ["RAY_TPU_TIMEOUT_SCALE"])

import contextlib  # noqa: E402

import jax  # noqa: E402

# The environment's sitecustomize force-registers an `axon` TPU backend and
# overrides jax_platforms programmatically; put it back to host CPU for tests.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _scale_test_timeouts():
    """Multiply explicit ray_tpu.get/wait timeouts by the load scale —
    test constants are written for an idle box."""
    import ray_tpu
    real_get, real_wait = ray_tpu.get, ray_tpu.wait

    def get(refs, *, timeout=None, **kw):
        if timeout is not None:
            timeout = timeout * _TIMEOUT_SCALE
        return real_get(refs, timeout=timeout, **kw)

    def wait(refs, **kw):
        if kw.get("timeout") is not None:
            kw["timeout"] = kw["timeout"] * _TIMEOUT_SCALE
        return real_wait(refs, **kw)

    ray_tpu.get = get
    ray_tpu.wait = wait
    yield
    ray_tpu.get = real_get
    ray_tpu.wait = real_wait


@pytest.fixture
def ray_start_regular():
    """Start a fresh single-node ray_tpu instance for the test (head + 1 node)."""
    import ray_tpu
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    """Multi-daemon simulated cluster (cf. reference cluster_utils.Cluster)."""
    from ray_tpu.cluster_utils import Cluster
    cluster = Cluster()
    yield cluster
    cluster.shutdown()


def record_recovery_row(row):
    """Under ``MICROBENCH_RECORD=1`` the chaos gates double as the data
    source for MICROBENCH.json's ``recovery`` section: the drain /
    failover / heal latencies they already assert against the
    recovery-SLO auditor ARE the numbers the bench table should cite,
    so recording them here keeps bench and gate from drifting.  Same
    merge-by-row-name idiom as benchmarks/scale_envelope.py — a partial
    re-run must not drop sibling rows, and collect_microbench's
    merge_preserve carries the whole section across refreshes."""
    import json
    if os.environ.get("MICROBENCH_RECORD") != "1":
        return
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "MICROBENCH.json")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {}
    sec = doc.setdefault("recovery", {})
    merged = {r.get("name"): r for r in sec.get("episodes", [])}
    merged[row.get("name")] = row
    sec["episodes"] = list(merged.values())
    sec["source"] = ("tests/test_preemption.py + tests/test_chaos.py "
                     "under MICROBENCH_RECORD=1: recovery-SLO auditor "
                     "episodes from injected chaos")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)


@contextlib.contextmanager
def debug_sanitizers_enabled():
    """Run a block under BOTH runtime sanitizers
    (docs/static_analysis.md): the lock-order sanitizer and the
    shm-ring protocol checker, in this process and — via the inherited
    env — in every daemon/worker spawned inside the block.  Env is
    restored afterwards so the rest of a tier-1 run stays
    uninstrumented.  The chaos and compiled-DAG suites wrap their whole
    module in this via an autouse fixture."""
    from ray_tpu._private.analysis import lock_sanitizer
    keys = ("RAY_TPU_DEBUG_LOCKS", "RAY_TPU_DEBUG_CHANNELS")
    old = {k: os.environ.get(k) for k in keys}
    for k in keys:
        os.environ[k] = "1"
    lock_sanitizer.install()
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
