"""Tests for ray_tpu.util: placement groups, scheduling strategies,
ActorPool, Queue, collective ring algorithms, metrics.

Modeled on the reference's python/ray/tests/test_placement_group*.py,
test_actor_pool.py, test_queue.py, util/collective/tests.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import (ActorPool, PlacementGroup, Queue,
                          NodeAffinitySchedulingStrategy,
                          PlacementGroupSchedulingStrategy, placement_group,
                          placement_group_table, remove_placement_group)


# ---------------------------------------------------------------- fixtures
@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0

    def add(self, k):
        self.n += k
        return self.n

    def node_id(self):
        import ray_tpu
        return ray_tpu.context()["node_id"]


@ray_tpu.remote
def where_am_i():
    return ray_tpu.context()["node_id"]


# --------------------------------------------------------- placement groups
def test_placement_group_create_and_remove(ray_start_regular):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(30)
    table = placement_group_table(pg)
    info = table[pg.id.hex()]
    assert info["state"] == "CREATED"
    assert len(info["placement"]) == 2
    remove_placement_group(pg)
    table = placement_group_table(pg)
    assert not table or table[pg.id.hex()] is None


def test_placement_group_task_scheduling(ray_start_regular):
    pg = placement_group([{"CPU": 2}], strategy="PACK")
    assert pg.wait(30)
    strategy = PlacementGroupSchedulingStrategy(
        placement_group=pg, placement_group_bundle_index=0)
    ref = where_am_i.options(scheduling_strategy=strategy).remote()
    node = ray_tpu.get(ref, timeout=60)
    info = placement_group_table(pg)[pg.id.hex()]
    assert node == info["placement"][0]
    remove_placement_group(pg)


def test_placement_group_actor(ray_start_regular):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(30)
    strategy = PlacementGroupSchedulingStrategy(
        placement_group=pg, placement_group_bundle_index=0)
    c = Counter.options(scheduling_strategy=strategy).remote()
    assert ray_tpu.get(c.add.remote(5), timeout=60) == 5
    node = ray_tpu.get(c.node_id.remote(), timeout=60)
    info = placement_group_table(pg)[pg.id.hex()]
    assert node == info["placement"][0]
    ray_tpu.kill(c)
    remove_placement_group(pg)


def test_placement_group_infeasible_pending(ray_start_regular):
    # way more CPU than the single test node has
    pg = placement_group([{"CPU": 512}])
    assert not pg.wait(1.0)
    info = placement_group_table(pg)[pg.id.hex()]
    assert info["state"] == "PENDING"
    remove_placement_group(pg)


def test_placement_group_strict_spread_infeasible(ray_start_regular):
    # single node -> STRICT_SPREAD of 2 bundles can't be placed
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert not pg.wait(1.0)
    remove_placement_group(pg)


def test_node_affinity_strategy(ray_start_regular):
    my_node = ray_tpu.nodes()[0]["node_id"]
    strategy = NodeAffinitySchedulingStrategy(node_id=my_node, soft=False)
    node = ray_tpu.get(
        where_am_i.options(scheduling_strategy=strategy).remote(),
        timeout=60)
    assert node == my_node


# ------------------------------------------------------------- actor pool
def test_actor_pool_map(ray_start_regular):
    @ray_tpu.remote
    class Doubler:
        def double(self, v):
            return v * 2

    pool = ActorPool([Doubler.remote() for _ in range(2)])
    results = list(pool.map(lambda a, v: a.double.remote(v), range(6)))
    assert results == [0, 2, 4, 6, 8, 10]


def test_actor_pool_map_unordered(ray_start_regular):
    @ray_tpu.remote
    class Doubler:
        def double(self, v):
            return v * 2

    pool = ActorPool([Doubler.remote() for _ in range(2)])
    results = sorted(pool.map_unordered(
        lambda a, v: a.double.remote(v), range(6)))
    assert results == [0, 2, 4, 6, 8, 10]


# ------------------------------------------------------------------ queue
def test_queue_basic(ray_start_regular):
    q = Queue(maxsize=3)
    q.put(1)
    q.put(2)
    assert q.qsize() == 2
    assert q.get() == 1
    assert q.get() == 2
    assert q.empty()
    with pytest.raises(ray_tpu.util.Empty):
        q.get(block=False)
    q.put_nowait_batch([1, 2, 3])
    with pytest.raises(ray_tpu.util.Full):
        q.put_nowait(4)
    assert q.get_nowait_batch(3) == [1, 2, 3]
    q.shutdown()


def test_queue_across_tasks(ray_start_regular):
    q = Queue()

    @ray_tpu.remote
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return n

    ray_tpu.get(producer.remote(q, 5), timeout=60)
    assert sorted(q.get() for _ in range(5)) == list(range(5))
    q.shutdown()


# ------------------------------------------------------------- collective
def test_collective_ring_allreduce(ray_start_regular):
    """4 actors run a ring allreduce over the host (DCN) backend."""

    @ray_tpu.remote
    class Rank:
        def __init__(self, world, rank):
            from ray_tpu.util import collective as col
            self.col = col
            col.init_collective_group(world, rank, group_name="test-ar")
            self.rank = rank

        def allreduce(self):
            x = np.full((32,), float(self.rank + 1), np.float32)
            out = self.col.allreduce(x, group_name="test-ar")
            return out

        def allgather(self):
            x = np.full((4,), float(self.rank), np.float32)
            return self.col.allgather(x, group_name="test-ar")

        def broadcast(self):
            x = np.full((8,), float(self.rank), np.float32)
            return self.col.broadcast(x, src_rank=2, group_name="test-ar")

        def destroy(self):
            self.col.destroy_collective_group("test-ar")

    world = 4
    ranks = [Rank.remote(world, r) for r in range(world)]
    outs = ray_tpu.get([r.allreduce.remote() for r in ranks], timeout=120)
    expected = np.full((32,), float(sum(range(1, world + 1))), np.float32)
    for out in outs:
        np.testing.assert_allclose(out, expected)
    gathers = ray_tpu.get([r.allgather.remote() for r in ranks], timeout=120)
    for parts in gathers:
        assert len(parts) == world
        for r, part in enumerate(parts):
            np.testing.assert_allclose(part, np.full((4,), float(r)))
    bcasts = ray_tpu.get([r.broadcast.remote() for r in ranks], timeout=120)
    for out in bcasts:
        np.testing.assert_allclose(out, np.full((8,), 2.0))
    ray_tpu.get([r.destroy.remote() for r in ranks], timeout=60)
    for r in ranks:
        ray_tpu.kill(r)


def test_ici_collectives_on_mesh():
    """In-graph collectives over the 8-device virtual mesh."""
    import jax
    from ray_tpu.parallel import build_mesh, MeshConfig
    from ray_tpu.util.collective import ici

    mesh = build_mesh(MeshConfig(data=8))
    x = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    xs = ici.device_put_sharded(x, mesh, "data")
    out = ici.all_gather(xs, mesh, "data")
    np.testing.assert_allclose(np.asarray(out), x)
    rs = ici.reduce_scatter(xs, mesh, "data")
    np.testing.assert_allclose(
        np.asarray(rs).reshape(-1), x.sum(axis=0))
    pp = ici.ppermute(xs, mesh, "data", shift=1)
    np.testing.assert_allclose(np.asarray(pp), np.roll(x, 1, axis=0))


# ---------------------------------------------------------------- metrics
def test_metrics_counter_gauge(ray_start_regular):
    from ray_tpu.util import metrics

    c = metrics.Counter("test_requests", "reqs", tag_keys=("route",))
    c.inc(2.0, tags={"route": "a"})
    c.inc(3.0, tags={"route": "a"})
    c.flush()
    g = metrics.Gauge("test_temp", "temp")
    g.set(42.0)
    g.flush()
    snap = metrics.query_metrics()
    counters = [v for k, v in snap.items() if k.startswith("test_requests")]
    assert counters and list(counters[0]["values"].values()) == [5.0]
    gauges = [v for k, v in snap.items() if k.startswith("test_temp")]
    assert gauges and list(gauges[0]["values"].values()) == [42.0]
