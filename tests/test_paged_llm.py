"""Paged KV-cache serving: block tables, pool recycling, prefill-ahead.

VERDICT round-4 task #1: replace the dense per-slot ``[max_seq]`` KV rows
with paged allocation (ops/paged_attention.py + llm_engine paged mode).
The bar: slot decode matches lone generation at mixed offsets, pages
recycle safely across requests, and queued requests get their first
token from the slotless prefill stage (the TTFT knob) instead of
waiting for slot turnover.  CPU-sized; real-chip numbers live in
benchmarks/serve_llm.py --paged.
"""

import threading
import time

import pytest


def _tiny():
    import jax
    import jax.numpy as jnp
    from ray_tpu.models.configs import get_config
    from ray_tpu.models.gpt import GPT

    cfg = get_config("tiny")
    model = GPT(cfg, decode=True)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 1), jnp.int32))["params"]
    return cfg, params


@pytest.fixture(scope="module")
def tiny_parts():
    return _tiny()


def _lone_expect(cfg, params, prompts, n=8):
    import jax.numpy as jnp
    from ray_tpu.models.generate import Generator

    lone = Generator(cfg, params)
    return [
        [int(t) for t in lone.generate(jnp.asarray([p], jnp.int32),
                                       max_new_tokens=n,
                                       temperature=0.0)[0]]
        for p in prompts
    ]


def _submit_all(eng, prompts, n=8, timeout=240):
    results = [None] * len(prompts)
    threads = []
    for i, p in enumerate(prompts):
        def go(i=i, p=p):
            results[i] = eng.submit(p, max_new_tokens=n, temperature=0.0)
        t = threading.Thread(target=go)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=timeout)
    return results


def test_paged_model_matches_dense_at_mixed_offsets(tiny_parts):
    """Model-level: paged prefill + per-page decode reproduces the dense
    decode path exactly with rows at different offsets and disjoint
    (deliberately shuffled) physical pages."""
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu.models.generate import init_decode_cache
    from ray_tpu.models.gpt import GPT

    cfg, params = tiny_parts
    ps = 16
    max_pages = cfg.max_seq_len // ps
    paged = GPT(cfg, decode=True, paged_pages=32, page_size=ps)
    cache = init_decode_cache(paged, 1)

    prompts = [[1, 2, 3], [7, 8, 9, 10, 11]]
    expect = _lone_expect(cfg, params, prompts)

    # non-contiguous, interleaved physical pages
    bt = np.zeros((2, max_pages), np.int32)
    bt[0] = (np.arange(max_pages) * 2 + 1) % 31 + 1
    bt[1] = (np.arange(max_pages) * 2 + 2) % 31 + 1
    assert len(set(bt[0]) & set(bt[1])) == 0
    bt = jnp.asarray(bt)

    bucket = 8
    toks = np.zeros((2, bucket), np.int32)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
    positions = jnp.broadcast_to(jnp.arange(bucket), (2, bucket))
    logits, mut = paged.apply({"params": params, "cache": cache},
                              jnp.asarray(toks), positions,
                              block_tables=bt, mutable=["cache"])
    cache = mut["cache"]
    out = [[int(jnp.argmax(logits[i, len(p) - 1]))]
           for i, p in enumerate(prompts)]
    tok = jnp.asarray([o[0] for o in out], jnp.int32)
    pos = jnp.asarray([len(p) for p in prompts], jnp.int32)
    for _ in range(7):
        logits, mut = paged.apply({"params": params, "cache": cache},
                                  tok[:, None], pos[:, None],
                                  block_tables=bt, mutable=["cache"])
        cache = mut["cache"]
        tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        for i in range(2):
            out[i].append(int(tok[i]))
        pos = pos + 1
    assert out == expect


def test_paged_engine_matches_lone_generation(tiny_parts):
    """Engine-level (the VERDICT bar): greedy decode through the paged
    engine — slotless prefill, install, per-row tables — equals each
    prompt generated alone, with more requests than decode slots."""
    from ray_tpu.serve.llm_engine import LLMEngine

    cfg, params = tiny_parts
    prompts = [[1, 2, 3], [7, 8, 9, 10, 11], [50, 60], [5] * 9]
    expect = _lone_expect(cfg, params, prompts)
    eng = LLMEngine(cfg, params, num_slots=2, block_size=4, paged=True,
                    page_size=16, kv_pool_pages=1 + 8)
    try:
        results = _submit_all(eng, prompts)
        for i in range(len(prompts)):
            assert results[i] is not None
            assert results[i].tokens == expect[i], (
                f"paged decode diverged for prompt {i}")
            assert results[i].prompt_len == len(prompts[i])
    finally:
        eng.close()


def test_page_recycling_stays_exact(tiny_parts):
    """Pool smaller than the workload: pages must recycle through the
    redirect fence across ~4x pool turnover with every output still
    exactly the lone generation (a page recycled one dispatch too early
    would corrupt a live row's KV and diverge)."""
    from ray_tpu.serve.llm_engine import LLMEngine

    cfg, params = tiny_parts
    prompts = [[i + 1, i + 2, i + 3] for i in range(16)]
    expect = _lone_expect(cfg, params, prompts, n=6)
    # 4 usable pages, 1 page per request -> at most 4 in flight, 16 total
    eng = LLMEngine(cfg, params, num_slots=2, block_size=4, paged=True,
                    page_size=16, kv_pool_pages=1 + 4)
    try:
        results = _submit_all(eng, prompts, n=6)
        for i in range(16):
            assert results[i] is not None, f"request {i} hung"
            assert results[i].tokens == expect[i], (
                f"page recycling corrupted request {i}")
    finally:
        eng.close()


def test_prefill_ahead_ttft_decoupled_from_slot_wait(tiny_parts):
    """With one busy decode slot, queued requests still get their first
    token from the slotless prefill stage: TTFT well under the full
    latency (which includes waiting for the slot)."""
    from ray_tpu.serve.llm_engine import LLMEngine

    cfg, params = tiny_parts
    eng = LLMEngine(cfg, params, num_slots=1, block_size=4, paged=True,
                    page_size=16, kv_pool_pages=1 + 8)
    try:
        eng.warmup(prompt_lens=[3])
        firsts_seen = []
        results = {}
        lock = threading.Lock()

        def go(rid, n):
            r = eng.submit([rid + 1, rid + 2, rid + 3], max_new_tokens=n,
                           temperature=0.0,
                           on_token=(lambda t, rid=rid: firsts_seen.append(
                               (rid, time.monotonic()))))
            with lock:
                results[rid] = r

        threads = [threading.Thread(target=go, args=(0, 40))]
        threads[0].start()
        time.sleep(0.3)        # let request 0 occupy the only slot
        for rid in range(1, 4):
            th = threading.Thread(target=go, args=(rid, 8))
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=240)
        assert sorted(results) == [0, 1, 2, 3]
        for rid in range(1, 4):
            r = results[rid]
            assert len(r.tokens) == 8
            # first token arrived from prefill-ahead, long before the
            # slot freed: TTFT must undercut the queued request's
            # end-to-end latency decisively
            assert r.time_to_first_token_s < r.latency_s / 2, (
                rid, r.time_to_first_token_s, r.latency_s)
    finally:
        eng.close()


def test_paged_eos_streaming_and_oversized(tiny_parts):
    """eos stops a paged row; on_token streams in order; a request that
    can never fit the pool fails alone without wedging the loop."""
    from ray_tpu.serve.llm_engine import LLMEngine

    cfg, params = tiny_parts
    eng = LLMEngine(cfg, params, num_slots=2, block_size=4, paged=True,
                    page_size=16, kv_pool_pages=1 + 6, max_prompt_len=60)
    try:
        seen = []
        probe = eng.submit([3, 4, 5], max_new_tokens=4, temperature=0.0,
                           on_token=seen.append)
        assert seen == probe.tokens
        eos = probe.tokens[0]
        r = eng.submit([3, 4, 5], max_new_tokens=64, temperature=0.0,
                       eos_id=eos)
        assert r.finish_reason == "eos"
        assert r.tokens == [eos]
        # needs ceil(min(60+128, max_seq 128)/16) = 8 pages > pool's 6
        with pytest.raises(ValueError):
            eng.submit([9] * 60, max_new_tokens=128)
        # engine still serves afterwards
        r2 = eng.submit([3, 4, 5], max_new_tokens=4, temperature=0.0)
        assert r2.tokens == probe.tokens
    finally:
        eng.close()
