"""Scripted-peer unit tier for the lease submitter (no live cluster).

VERDICT round-4 task #9: the reference tests its scheduler/transfer
logic against mocks (src/mock/ray/**, cluster_task_manager_test.cc,
pull_manager_test.cc) while our scheduling edge cases previously needed
whole live clusters.  This file drives the REAL client-side lease state
machine (core_worker._lease_request_loop / _lease_worker_loop /
_lease_with_spillback / _retry_or_fail_dead_worker) against scripted
fake raylets and fake workers — deterministic peers that redirect,
grant, die mid-pipeline, or error on cue — reaching orderings the live
cluster tests can't schedule deliberately.

The harness: ``ScriptedOwner`` inherits the full submitter machinery
from CoreWorker but constructs only its state and overrides the result
sinks; ``FakePeer`` is an rpc.Server whose handler runs a per-method
script.  Everything here completes in seconds.
"""

import threading
import time


from ray_tpu._private import rpc
from ray_tpu._private.ids import JobID
from ray_tpu.runtime import core_worker as cw


class FakePeer:
    """Scriptable raylet/worker: handler methods come from a dict of
    callables; every call is recorded."""

    def __init__(self, script):
        self.script = dict(script)
        self.calls = []
        self.lock = threading.Lock()
        self.server = rpc.Server(self._handle)
        self.address = self.server.address

    def _handle(self, conn, method, payload):
        with self.lock:
            self.calls.append((method, payload))
        fn = self.script.get(method)
        if fn is None:
            raise rpc.RpcError(f"unscripted method {method}")
        return fn(conn, payload)

    def called(self, method):
        with self.lock:
            return [p for m, p in self.calls if m == method]

    def close(self):
        self.server.stop() if hasattr(self.server, "stop") else None


class ScriptedOwner(cw.CoreWorker):
    """The real lease submitter over scripted peers: state constructed
    directly, result sinks recorded instead of resolving objects."""

    def __init__(self, raylet_addr):
        # deliberately NOT calling super().__init__ — only the submitter
        # machinery's state exists (one shared helper with the real
        # CoreWorker, so new submitter fields can't drift from this
        # tier); anything else raising AttributeError is a seam this
        # test file must think about explicitly
        self._init_submitter_state()
        self._raylet = rpc.connect(raylet_addr)
        self.job_id = JobID.from_random()
        self.replies = []
        self.errors = []
        self.done = threading.Condition()

    # ------------------------------------------------- recorded sinks
    def _on_task_reply(self, spec, reply):
        with self.done:
            self.replies.append((spec["name"], reply))
            self.done.notify_all()

    def _store_task_error(self, spec, error, error_code=None):
        with self.done:
            self.errors.append((spec["name"], error))
            self.done.notify_all()

    def _lease_was_oom_killed(self, lease):
        return False

    # ------------------------------------------------------- helpers
    def push(self, name, key="k", retries=0):
        spec = {"task_id": name.encode().ljust(16, b"0"), "name": name}
        self._enqueue_task(key, {"CPU": 1}, spec, retries)

    def wait_done(self, n, timeout=30):
        deadline = time.monotonic() + timeout
        with self.done:
            while len(self.replies) + len(self.errors) < n:
                left = deadline - time.monotonic()
                assert left > 0, (
                    f"timeout: {len(self.replies)} replies "
                    f"{len(self.errors)} errors, wanted {n}")
                self.done.wait(left)

    def close(self):
        self._shutdown.set()
        try:
            self._raylet.close()
        except Exception:
            pass


def ok_worker():
    """Worker that acks every push_tasks frame with per-spec results."""
    def push_tasks(conn, p):
        return {"results": [{"ok": {"results": [{"name": s["name"]}]}}
                            for s in p["specs"]]}
    return FakePeer({"push_tasks": push_tasks})


def granting_raylet(worker, grants=None, returns=None):
    """Raylet that leases the given worker and records returns."""
    n = [0]

    def lease_worker(conn, p):
        n[0] += 1
        if grants is not None and n[0] > grants:
            raise rpc.RpcError("resources unavailable")
        return {"lease_id": f"l{n[0]}", "worker_id": f"w{n[0]}",
                "address": list(worker.address)}

    return FakePeer({"lease_worker": lease_worker,
                     "return_worker": lambda conn, p: {"ok": True}})


def test_grant_execute_return():
    """Baseline: lease(s), pipeline tasks, drain — and EVERY granted
    lease is returned to the granting raylet (the queue-pressure loop
    may take several leases; none may leak)."""
    w = ok_worker()
    r = granting_raylet(w)
    o = ScriptedOwner(r.address)
    try:
        for i in range(5):
            o.push(f"t{i}")
        o.wait_done(5)
        assert sorted(n for n, _ in o.replies) == [f"t{i}" for i in range(5)]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            granted = {f"l{i + 1}"
                       for i in range(len(r.called("lease_worker")))}
            returned = {p["lease_id"] for p in r.called("return_worker")}
            if granted and granted == returned:
                break
            time.sleep(0.01)
        assert granted == returned, f"leaked leases: {granted - returned}"
    finally:
        o.close()


def test_spillback_chain_lands_on_third_raylet():
    """Local raylet redirects to B, B redirects to C, C grants: the task
    runs on C's worker and the lease is RETURNED TO C (granting_addr
    tracking), never to the local raylet."""
    w = ok_worker()
    c = granting_raylet(w)
    b = FakePeer({"lease_worker":
                  lambda conn, p: {"retry_at": list(c.address)}})
    a = FakePeer({"lease_worker":
                  lambda conn, p: {"retry_at": list(b.address)},
                  "return_worker": lambda conn, p: {"ok": True}})
    o = ScriptedOwner(a.address)
    try:
        o.push("t0")
        o.wait_done(1)
        assert [n for n, _ in o.replies] == ["t0"]
        deadline = time.monotonic() + 10
        while not c.called("return_worker") and time.monotonic() < deadline:
            time.sleep(0.01)
        assert c.called("return_worker"), "lease returned to wrong raylet"
        assert not a.called("return_worker")
        # each hop carried an incremented spillback counter
        assert [p["spillback"] for p in a.called("lease_worker")] == [0]
        assert [p["spillback"] for p in b.called("lease_worker")] == [1]
        assert [p["spillback"] for p in c.called("lease_worker")] == [2]
    finally:
        o.close()


def test_spillback_loop_bounded_then_recovers():
    """Two raylets redirecting at each other forever: the submitter must
    bound the chase (no infinite redirect), keep the task queued, and
    complete it the moment a grant appears."""
    w = ok_worker()
    state = {"grant": False}

    def lease_a(conn, p):
        if state["grant"]:
            return {"lease_id": "l1", "worker_id": "w1",
                    "address": list(w.address)}
        return {"retry_at": list(b.address)}

    a = FakePeer({"lease_worker": lease_a,
                  "return_worker": lambda conn, p: {"ok": True}})
    b = FakePeer({"lease_worker":
                  lambda conn, p: {"retry_at": list(a.address)}})
    o = ScriptedOwner(a.address)
    try:
        o.push("t0")
        time.sleep(1.0)          # several bounded chases + retry sleeps
        assert o.replies == [] and o.errors == []   # still queued, not lost
        state["grant"] = True
        o.wait_done(1, timeout=30)
        assert [n for n, _ in o.replies] == ["t0"]
    finally:
        o.close()


def test_worker_death_charges_only_oldest_push():
    """Worker accepts a pipeline of pushes then dies before replying:
    only the oldest (the one actually executing) is charged a retry;
    the younger in-flight pushes requeue for free and complete on the
    next lease.  A task with no retries left fails exactly once."""
    first = ok_worker()

    def dying_push(conn, p):
        # die with the whole pipeline unacked
        conn.close()
        raise rpc.RpcError("unreachable")  # conn gone; never delivered

    dead = FakePeer({"push_tasks": dying_push})
    leases = [dead, first]

    def lease_worker(conn, p):
        peer = leases.pop(0) if leases else first
        return {"lease_id": f"l{id(peer) % 97}", "worker_id": "w",
                "address": list(peer.address)}

    r = FakePeer({"lease_worker": lease_worker,
                  "return_worker": lambda conn, p: {"ok": True}})
    o = ScriptedOwner(r.address)
    try:
        # oldest task has a retry budget: it must survive the death
        o.push("t0", retries=1)
        o.push("t1", retries=0)
        o.push("t2", retries=0)
        o.wait_done(3, timeout=30)
        assert sorted(n for n, _ in o.replies) == ["t0", "t1", "t2"]
        assert o.errors == []
    finally:
        o.close()


def test_worker_death_no_retries_fails_only_executing_task():
    """Same death, but the executing task has retries=0: it fails; the
    younger pipelined tasks still requeue and complete (they never ran,
    so they are not charged)."""
    first = ok_worker()

    def dying_push(conn, p):
        conn.close()
        raise rpc.RpcError("unreachable")

    dead = FakePeer({"push_tasks": dying_push})
    leases = [dead, first]
    r = FakePeer({"lease_worker": lambda conn, p: {
        "lease_id": "l", "worker_id": "w",
        "address": list((leases.pop(0) if leases else first).address)},
        "return_worker": lambda conn, p: {"ok": True}})
    o = ScriptedOwner(r.address)
    try:
        o.push("t0", retries=0)
        o.push("t1", retries=0)
        o.push("t2", retries=0)
        o.wait_done(3, timeout=30)
        assert [n for n, _ in o.errors] == ["t0"]
        assert sorted(n for n, _ in o.replies) == ["t1", "t2"]
    finally:
        o.close()


def test_remote_error_keeps_lease_serving():
    """A task failing on the worker (per-spec err entry in the batch
    ack) must not kill the lease: subsequent pipelined tasks keep
    flowing on the same connection, and the failed task is charged no
    worker-death retry."""
    def push_tasks(conn, p):
        out = []
        for s in p["specs"]:
            if s["name"] == "bad":
                out.append({"err": "user exception"})
            else:
                out.append({"ok": {"results": [{"name": s["name"]}]}})
        return {"results": out}

    w = FakePeer({"push_tasks": push_tasks})
    r = granting_raylet(w)
    o = ScriptedOwner(r.address)
    try:
        o.push("t0")
        o.push("bad", retries=3)   # retries must NOT be consumed
        o.push("t1")
        o.wait_done(3)
        assert [n_ for n_, _ in o.errors] == ["bad"]
        assert sorted(n_ for n_, _ in o.replies) == ["t0", "t1"]
        # no task was treated as a worker death: each pushed exactly once
        # (queue pressure may open a second lease; that's fine)
        pushed = [s["name"] for p in w.called("push_tasks")
                  for s in p["specs"]]
        assert sorted(pushed) == ["bad", "t0", "t1"]
    finally:
        o.close()


def test_frame_remote_error_fails_whole_batch():
    """A dispatch-level RemoteError on a push_tasks frame (handler blew
    up before producing per-spec results) fails every spec of THAT frame
    without being charged as a worker death, and the lease keeps
    serving later frames."""
    n = [0]

    def push_tasks(conn, p):
        n[0] += 1
        if n[0] == 1:
            raise rpc.RpcError("frame dispatch exploded")
        return {"results": [{"ok": {"results": [{"name": s["name"]}]}}
                            for s in p["specs"]]}

    w = FakePeer({"push_tasks": push_tasks})
    r = granting_raylet(w)
    o = ScriptedOwner(r.address)
    try:
        o.push("t0", retries=3)
        o.wait_done(1)
        assert [n_ for n_, _ in o.errors] == ["t0"]  # retries NOT consumed
        o.push("t1")
        o.wait_done(2)
        assert [n_ for n_, _ in o.replies] == ["t1"]
    finally:
        o.close()


def test_raylet_dies_mid_lease_fails_queue():
    """The local raylet drops the connection during the lease request
    and the owner holds no other leases: queued tasks must fail with a
    clear 'raylet unreachable' error instead of spinning forever."""
    def drop(conn, p):
        conn.close()
        raise rpc.RpcError("never delivered")

    r = FakePeer({"lease_worker": drop})
    o = ScriptedOwner(r.address)
    try:
        o.push("t0")
        o.wait_done(1, timeout=30)
        assert [n for n, _ in o.errors] == ["t0"]
        assert "unreachable" in str(o.errors[0][1])
    finally:
        o.close()


def test_lease_returned_when_queue_cancelled_before_grant():
    """Cancel race: the queue empties while the lease request is in
    flight — the grant lands on an empty queue and must be returned
    immediately (no leaked lease, no push ever sent)."""
    w = ok_worker()
    granted = threading.Event()
    release = threading.Event()

    def slow_lease(conn, p):
        granted.set()
        release.wait(10)
        return {"lease_id": "l1", "worker_id": "w1",
                "address": list(w.address)}

    r = FakePeer({"lease_worker": slow_lease,
                  "return_worker": lambda conn, p: {"ok": True}})
    o = ScriptedOwner(r.address)
    try:
        o.push("t0")
        assert granted.wait(10)
        # cancel: drain the queue while the raylet is still deciding
        with o._sched_lock:
            for st in o._sched.values():
                st["queue"].clear()
        release.set()
        deadline = time.monotonic() + 10
        while not r.called("return_worker") and time.monotonic() < deadline:
            time.sleep(0.01)
        assert r.called("return_worker"), "cancelled grant leaked"
        assert not w.called("push_tasks")
    finally:
        o.close()
