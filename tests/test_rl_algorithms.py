"""Tests for the wider algorithm families (model: reference
rllib/algorithms/*/tests): PG/A2C/A3C, APPO, SimpleQ, DDPG/TD3, offline
(BC/MARWIL/CQL + estimators), ES/ARS, and the registry."""

import math

import numpy as np
import pytest


def _train_n(algo, n):
    results = []
    try:
        for _ in range(n):
            results.append(algo.train())
    finally:
        algo.stop()
    return results


def test_registry_lookup():
    from ray_tpu.rl import get_algorithm_class
    from ray_tpu.rl.ppo import PPO
    assert get_algorithm_class("PPO") is PPO
    algo_cls, cfg_cls = get_algorithm_class("td3", return_config=True)
    assert algo_cls.__name__ == "TD3"
    assert cfg_cls().twin_q is True
    with pytest.raises(ValueError):
        get_algorithm_class("nope")


def test_pg_cartpole_runs(ray_start_regular):
    from ray_tpu.rl import PGConfig
    algo = (PGConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=1, num_envs_per_worker=2,
                      rollout_fragment_length=100)
            .training(train_batch_size=200, hidden=(32, 32))
            .debugging(seed=0)
            .build())
    results = _train_n(algo, 3)
    assert results[-1]["timesteps_total"] >= 600
    assert math.isfinite(results[-1]["info"]["policy_loss"])


def test_a2c_cartpole_runs(ray_start_regular):
    from ray_tpu.rl import A2CConfig
    algo = (A2CConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=1,
                      rollout_fragment_length=50)
            .training(train_batch_size=100, hidden=(32, 32))
            .debugging(seed=0)
            .build())
    results = _train_n(algo, 3)
    assert results[-1]["timesteps_total"] > 0
    assert math.isfinite(results[-1]["info"]["total_loss"])


def test_a3c_async_updates(ray_start_regular):
    from ray_tpu.rl import A3CConfig
    algo = (A3CConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=1,
                      rollout_fragment_length=25)
            .training(batches_per_step=4, hidden=(32, 32))
            .debugging(seed=0)
            .build())
    results = _train_n(algo, 2)
    assert results[-1]["info"]["batches_processed"] >= 1
    assert results[-1]["timesteps_total"] > 0


def test_appo_cartpole_runs(ray_start_regular):
    from ray_tpu.rl import APPOConfig
    algo = (APPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=1,
                      rollout_fragment_length=25)
            .training(batches_per_step=4, hidden=(32, 32),
                      target_update_frequency=2)
            .debugging(seed=0)
            .build())
    results = _train_n(algo, 2)
    info = results[-1]["info"]
    assert math.isfinite(info["total_loss"])
    assert info["mean_ratio"] > 0
    assert results[-1]["timesteps_total"] > 0


def test_simple_q_is_dqn_without_extensions(ray_start_regular):
    from ray_tpu.rl import SimpleQConfig
    cfg = (SimpleQConfig()
           .environment("CartPole-v1")
           .rollouts(num_rollout_workers=1, num_envs_per_worker=2,
                     rollout_fragment_length=32)
           .training(learning_starts=64, train_batch_size=32,
                     n_updates_per_iter=8, hidden=(32, 32))
           .debugging(seed=0))
    assert cfg.double_q is False and cfg.dueling is False
    algo = cfg.build()
    results = _train_n(algo, 3)
    assert results[-1]["info"]["buffer_size"] > 0


def test_ddpg_policy_noise_and_bounds():
    from ray_tpu.rl import DDPGPolicy
    from ray_tpu.rl.env import Box, Discrete
    obs_space = Box(low=-1, high=1, shape=(3,))
    act_space = Box(low=-2.0, high=2.0, shape=(1,))
    pol = DDPGPolicy(obs_space, act_space, hidden=(16,), seed=0,
                     exploration_noise=0.3)
    obs = np.zeros((32, 3), np.float32)
    a, _, _ = pol.compute_actions(obs)
    assert a.shape == (32, 1)
    assert np.all(a >= -2.0) and np.all(a <= 2.0)
    assert np.std(a) > 1e-4              # noise applied
    a2, _, _ = pol.compute_actions(obs, explore=False)
    assert np.allclose(a2, a2[0])        # deterministic
    with pytest.raises(ValueError):
        DDPGPolicy(obs_space, Discrete(2))


def test_td3_pendulum_runs(ray_start_regular):
    from ray_tpu.rl import TD3Config
    algo = (TD3Config()
            .environment("Pendulum-v1")
            .rollouts(num_rollout_workers=1, num_envs_per_worker=1,
                      rollout_fragment_length=64)
            .training(learning_starts=128, train_batch_size=64,
                      n_updates_per_iter=16, hidden=(32, 32))
            .debugging(seed=0)
            .build())
    results = _train_n(algo, 4)
    info = results[-1]["info"]
    assert info["buffer_size"] >= 128
    assert math.isfinite(info["critic_loss"])
    # delayed policy updates: actor loss becomes nonzero once updating
    assert "actor_loss" in info


def test_offline_json_roundtrip(tmp_path):
    from ray_tpu.rl import JsonReader, JsonWriter, SampleBatch
    w = JsonWriter(str(tmp_path / "data"))
    batch = SampleBatch({"obs": np.random.randn(10, 4).astype(np.float32),
                         "actions": np.arange(10)})
    w.write(batch)
    w.write(batch)
    w.close()
    out = JsonReader(str(tmp_path / "data")).read_all()
    assert out.count == 20
    np.testing.assert_allclose(out["obs"][:10], batch["obs"], rtol=1e-6)


def test_bc_learns_dataset_policy(ray_start_regular, tmp_path):
    from ray_tpu.rl import BCConfig, collect_dataset
    path = collect_dataset("CartPole-v1", str(tmp_path / "ds"),
                           n_steps=600, seed=0)
    cfg = (BCConfig()
           .environment("CartPole-v1")
           .training(num_sgd_iter=3, sgd_minibatch_size=64, hidden=(32, 32),
                     lr=1e-3)
           .debugging(seed=0))
    cfg.offline_data(input_path=path)
    algo = cfg.algo_class(cfg)
    r1 = algo.train()
    r2 = algo.train()
    # log-likelihood of dataset actions should improve
    assert r2["info"]["logp"] > r1["info"]["logp"]
    assert "episode_reward_mean" in r2
    ckpt = algo.save()
    algo.restore(ckpt)


def test_marwil_advantage_weighting(ray_start_regular, tmp_path):
    from ray_tpu.rl import MARWILConfig, collect_dataset
    path = collect_dataset("CartPole-v1", str(tmp_path / "ds"),
                           n_steps=600, seed=1)
    cfg = (MARWILConfig()
           .environment("CartPole-v1")
           .training(num_sgd_iter=2, sgd_minibatch_size=64, hidden=(32, 32),
                     beta=1.0)
           .debugging(seed=0))
    cfg.offline_data(input_path=path)
    algo = cfg.algo_class(cfg)
    result = algo.train()
    assert math.isfinite(result["info"]["policy_loss"])
    assert math.isfinite(result["info"]["vf_loss"])
    est = algo.estimate_off_policy()
    assert "v_target" in est and "v_behavior" in est
    assert math.isfinite(est["v_behavior"])


def test_cql_pendulum_runs(ray_start_regular, tmp_path):
    from ray_tpu.rl import CQLConfig, collect_dataset
    path = collect_dataset("Pendulum-v1", str(tmp_path / "ds"),
                           n_steps=400, seed=2)
    cfg = (CQLConfig()
           .environment("Pendulum-v1")
           .training(num_sgd_iter=8, train_batch_size=64, hidden=(32, 32),
                     num_actions=2)
           .debugging(seed=0))
    cfg.offline_data(input_path=path)
    algo = cfg.algo_class(cfg)
    result = algo.train()
    info = result["info"]
    assert math.isfinite(info["critic_loss"])
    # the conservative penalty is active (logsumexp > dataset Q)
    assert info["cql_loss"] > 0


def test_es_cartpole_improves(ray_start_regular):
    from ray_tpu.rl import ESConfig
    algo = (ESConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2)
            .training(candidates_per_iteration=8, noise_stdev=0.1,
                      step_size=0.1, hidden=(16,))
            .debugging(seed=0)
            .build())
    results = _train_n(algo, 4)
    last = results[-1]["info"]
    assert math.isfinite(last["reward_mean_candidates"])
    assert last["reward_best_candidate"] >= last["reward_mean_candidates"]
    assert results[-1]["timesteps_total"] > 0


def test_ars_top_k_update(ray_start_regular):
    from ray_tpu.rl import ARSConfig
    algo = (ARSConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2)
            .training(candidates_per_iteration=8, top_k=2,
                      noise_stdev=0.1, step_size=0.1, hidden=(16,))
            .debugging(seed=0)
            .build())
    results = _train_n(algo, 2)
    assert math.isfinite(results[-1]["info"]["sigma_r"])
    assert math.isfinite(results[-1]["info"]["grad_norm"])


def test_cql_full_state_checkpoint_roundtrip(ray_start_regular, tmp_path):
    """save/restore must round-trip the FULL training state — critics,
    targets, optimizer moments — not just the actor (a resumed run with
    fresh critics silently degrades; cf. reference full-state policy
    checkpoints)."""
    import jax
    import numpy as np

    from ray_tpu.rl import CQLConfig, collect_dataset
    path = collect_dataset("Pendulum-v1", str(tmp_path / "ds"),
                           n_steps=300, seed=3)
    cfg = (CQLConfig()
           .environment("Pendulum-v1")
           .training(num_sgd_iter=4, train_batch_size=64, hidden=(16, 16),
                     num_actions=2)
           .debugging(seed=0))
    cfg.offline_data(input_path=path)
    algo = cfg.algo_class(cfg)
    algo.train()
    ckpt = algo.save()
    saved = jax.tree.map(np.asarray, algo.state)
    algo.train()  # mutate every component of the state
    algo.restore(ckpt)
    restored = jax.tree.map(np.asarray, algo.state)
    flat_saved, _ = jax.tree_util.tree_flatten(saved)
    flat_restored, _ = jax.tree_util.tree_flatten(restored)
    assert len(flat_saved) == len(flat_restored)
    for a, b in zip(flat_saved, flat_restored):
        np.testing.assert_array_equal(a, b)
