"""Podracer RL data plane tests (docs/rl_podracer.md).

Covers the three legs of the executor — streaming fragment ingestion,
store-routed weight broadcast, compiled-DAG learner — plus the
pickle-5 out-of-band SampleBatch contract and the rl_actor recovery
episode the auditor derives from RL_ACTOR_LOST/JOINED events.
"""

import time

import numpy as np
import pytest

from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.sample_batch import SampleBatch


# --------------------------------------------------------- weight codec

def test_weight_codec_roundtrip_raw_and_int8():
    """encode/decode is exact in raw mode and within the Int8Codec
    block-scale bound when quantized; non-float leaves always ride raw."""
    from ray_tpu.rl.podracer.weights import decode_weights, encode_weights
    tree = {"a": {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
                  "b": np.zeros(3, np.float32)},
            "step": np.array(7)}

    out = decode_weights(encode_weights(tree, quantize=False))
    np.testing.assert_array_equal(out["a"]["w"], tree["a"]["w"])
    assert out["step"] == 7

    q = encode_weights(tree, quantize=True)
    assert q["codec"] == "int8"
    outq = decode_weights(q)
    assert outq["a"]["w"].shape == (3, 4)
    assert outq["a"]["w"].dtype == np.float32
    # block-scaled int8: error bounded by blockmax/254
    bound = np.abs(tree["a"]["w"]).max() / 254 + 1e-7
    assert np.abs(outq["a"]["w"] - tree["a"]["w"]).max() <= bound
    # integer leaf survives exactly even under quantize
    assert outq["step"] == 7


def test_weight_publisher_follower_version_skip(ray_start_regular):
    """The follower adopts the NEWEST version in one pull when multiple
    publishes happened since its last poll (the version-skip rule), and
    a poll with nothing new returns None."""
    from ray_tpu.rl.podracer.weights import WeightFollower, WeightPublisher
    pub = WeightPublisher("skiptest")
    fol = WeightFollower("skiptest")
    try:
        assert fol.poll() is None          # nothing published yet

        tree = {"w": np.ones((4, 4), np.float32)}
        pub.publish(tree)
        got, ver = fol.poll()
        assert ver == 1
        np.testing.assert_array_equal(got["w"], tree["w"])
        assert fol.poll() is None          # same version: no re-pull

        # three publishes back to back: one poll lands on v4, skipping 2
        for k in range(2, 5):
            pub.publish({"w": np.full((4, 4), float(k), np.float32)})
        got, ver = fol.poll()
        assert ver == 4
        np.testing.assert_array_equal(got["w"], np.full((4, 4), 4.0))
        assert fol.versions_skipped == 2
    finally:
        pub.clear()


# --------------------------------------- SampleBatch pickle-5 contract

def test_sample_batch_ships_columns_out_of_band():
    """Every column of a SampleBatch rides pickle-5 out-of-band —
    including columns built from non-contiguous inputs, which __init__
    must coerce to C-contiguous (a strided view would otherwise fall
    back to an in-band copy)."""
    from ray_tpu._private import serialization as ser
    base = np.arange(1 << 14, dtype=np.float32).reshape(128, 128)
    batch = SampleBatch({
        SB.OBS: base,
        SB.REWARDS: base.T,                    # transposed: not contiguous
        SB.ACTIONS: np.arange(256, dtype=np.float32)[::2],  # strided
    })
    for col in batch.values():
        assert col.flags.c_contiguous
    payload = sum(col.nbytes for col in batch.values())
    head, views = ser.serialize(batch)
    assert sum(len(v) for v in views) >= payload   # out-of-band, no copy
    out = ser.deserialize(ser.to_flat_bytes(head, views))
    np.testing.assert_array_equal(out[SB.REWARDS], base.T)
    np.testing.assert_array_equal(out[SB.ACTIONS],
                                  np.arange(256, dtype=np.float32)[::2])


def test_sample_batch_store_roundtrip_pins_shm(ray_start_regular):
    """A large SampleBatch put+get maps straight out of the shared-memory
    store: the driver holds shm pins while the value is live (the
    ray_tpu_shm_pins gauge counts them) and the columns round-trip."""
    import ray_tpu
    from ray_tpu._private import runtime_metrics as rtm
    from ray_tpu.runtime import core_worker as cw

    batch = SampleBatch({
        SB.OBS: np.arange(1 << 18, dtype=np.float32).reshape(1024, 256),
        SB.REWARDS: np.ones(1024, np.float32),
    })
    ref = ray_tpu.put(batch)
    out = ray_tpu.get(ref, timeout=30)
    worker = cw.get_global_worker()
    assert sum(worker._pins.values()) >= 1
    snap = rtm.snapshot()
    gauge = snap.get("ray_tpu_shm_pins")
    assert gauge is not None and sum(gauge["values"].values()) >= 1
    np.testing.assert_array_equal(out[SB.OBS], batch[SB.OBS])


# ------------------------------------------------------ executor e2e

def test_impala_podracer_zero_submissions_steady_state(ray_start_podracer):
    """IMPALA on the podracer plane: timesteps advance, losses flow, the
    fleet adopts published weight versions, and — the tentpole contract —
    the driver submits ZERO classic actor tasks per steady-state learner
    step (the inner loop runs entirely over the compiled DAG's channels;
    strict_zero_submit raises inside train() if that regresses)."""
    from ray_tpu.rl.impala import ImpalaConfig
    algo = (ImpalaConfig().environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=25)
            .training(batches_per_step=4)
            .debugging(seed=0)
            .podracer())
    algo = algo.build()
    try:
        ts = []
        for _ in range(3):
            r = algo.train()
            ts.append(r["timesteps_total"])
        assert ts[0] > 0 and ts[2] > ts[1] > ts[0]
        assert "total_loss" in r["info"]
        ex = algo.podracer
        assert ex.telemetry["classic_submits_steady"] == 0
        assert ex.telemetry["learner_steps"] >= 12
        # the learner published at least one version past the initial
        # bootstrap and the whole fleet adopted it
        assert r["info"]["weight_version"] >= 2
        assert len(ex.telemetry["weight_adoption_s"]) >= 1
        assert all(s >= 0 for s in ex.telemetry["weight_adoption_s"])
    finally:
        algo.stop()


def test_ppo_podracer_checkpoint_roundtrip(ray_start_podracer):
    """PPO rides the same executor; a full save/restore preserves the
    optimizer + counters and training resumes (timesteps keep growing)."""
    from ray_tpu.rl.ppo import PPOConfig
    algo = (PPOConfig().environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=50)
            .training(train_batch_size=200, sgd_minibatch_size=100)
            .debugging(seed=0)
            .podracer()
            .build())
    try:
        r1 = algo.train()
        assert r1["timesteps_total"] > 0
        assert algo.podracer.telemetry["classic_submits_steady"] == 0
        ckpt = algo.save()
        algo.restore(ckpt)
        r2 = algo.train()
        assert r2["timesteps_total"] > r1["timesteps_total"]
    finally:
        algo.stop()


# ----------------------------------------------------- recovery audit

def _ev(etype, ts, **fields):
    return dict(type=etype, ts=ts, **fields)


def test_auditor_rl_actor_episode():
    """RL_ACTOR_LOST -> RL_ACTOR_JOINED closes an rl_actor episode keyed
    by run/slot whose latency is the event-timestamp delta, judged
    against recovery_slo_rl_actor_s and carrying the rejoin's weight
    version + pull latency."""
    from ray_tpu._private.metrics_history import RecoveryAuditor

    a = RecoveryAuditor()
    t0 = 5000.0
    a.observe([
        _ev("RL_ACTOR_LOST", t0, run_id="podracer-impala-abc", slot=1,
            reason="ConnectionError('stream')"),
        _ev("RL_ACTOR_JOINED", t0 + 3.5, run_id="podracer-impala-abc",
            slot=1, weight_version=42, weight_pull_ms=12.5),
    ])
    eps = a.list(kind="rl_actor")
    assert len(eps) == 1
    ep = eps[0]
    assert not ep["open"]
    assert ep["key"] == "podracer-impala-abc/1"
    assert ep["latency_s"] == 3.5
    assert ep["opening_type"] == "RL_ACTOR_LOST"
    assert ep["closing_type"] == "RL_ACTOR_JOINED"
    assert ep["weight_version"] == 42
    assert ep["weight_pull_ms"] == 12.5
    assert ep["slo_s"] == 60.0 and not ep["violation"]

    # a different slot is a different episode; blowing the SLO flags it
    a.observe([
        _ev("RL_ACTOR_LOST", t0 + 10, run_id="podracer-impala-abc",
            slot=2, reason="killed"),
        _ev("RL_ACTOR_JOINED", t0 + 80, run_id="podracer-impala-abc",
            slot=2, weight_version=50),
    ])
    ep2 = a.list(kind="rl_actor")[-1]
    assert ep2["key"].endswith("/2")
    assert ep2["latency_s"] == 70.0 and ep2["violation"]


@pytest.fixture
def ray_start_podracer():
    """Podracer fleets need headroom beyond ray_start_regular's 4 CPUs:
    1 learner + 2 rollout actors + replacement slack."""
    import ray_tpu
    ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()
