"""Cluster launcher e2e (model: reference test_autoscaler.py launcher
cases + test_cli.py): `up` a multi-node cluster from YAML via the local
provider + LocalCommandRunner, run a job on it, tear it down."""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from ray_tpu.autoscaler.cluster_launcher import (ClusterConfigError,
                                                 create_or_update_cluster,
                                                 exec_cluster,
                                                 load_cluster_state,
                                                 submit_job,
                                                 teardown_cluster,
                                                 validate_cluster_config)

PY = sys.executable


def _local_yaml(tmp_path, workers=2):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = f"""
cluster_name: launcher-e2e
provider:
  type: local
env:
  PYTHONPATH: {repo}
available_node_types:
  head:
    resources: {{"CPU": 2}}
    hosts_per_node: 1
  cpu_worker:
    resources: {{"CPU": 2}}
    hosts_per_node: 1
    min_workers: {workers}
    max_workers: {workers}
head_node_type: head
head_start_ray_commands:
  - {PY} -m ray_tpu.scripts start --head --port={{port}} --num-cpus 2
worker_start_ray_commands:
  - {PY} -m ray_tpu.scripts start --address={{head_address}} --num-cpus 2
"""
    path = tmp_path / "cluster.yaml"
    path.write_text(cfg)
    return str(path)


@pytest.fixture
def state_dir(tmp_path, monkeypatch):
    d = tmp_path / "cluster_state"
    monkeypatch.setenv("RAY_TPU_CLUSTER_STATE_DIR", str(d))
    return d


def test_validate_cluster_config_errors():
    with pytest.raises(ClusterConfigError, match="cluster_name"):
        validate_cluster_config({"provider": {"type": "local"},
                                 "available_node_types": {"a": {}},
                                 "head_node_type": "a"})
    with pytest.raises(ClusterConfigError, match="head_node_type"):
        validate_cluster_config({"cluster_name": "x",
                                 "provider": {"type": "local"},
                                 "available_node_types": {"a": {}},
                                 "head_node_type": "nope"})
    with pytest.raises(ClusterConfigError, match="min_workers"):
        validate_cluster_config({"cluster_name": "x",
                                 "provider": {"type": "local"},
                                 "available_node_types": {
                                     "a": {"min_workers": 3,
                                           "max_workers": 1}},
                                 "head_node_type": "a"})


def test_tpu_yaml_dry_run_plan(capsys, state_dir):
    """`ray-tpu up examples/cluster.yaml --dry-run` prints the gcloud/SSH
    plan for a v4-32 slice without executing anything."""
    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "cluster.yaml")
    lines = []
    create_or_update_cluster(path, dry_run=True, _print=lines.append)
    plan = "\n".join(lines)
    assert "DRY RUN" in plan
    assert "gcloud compute tpus tpu-vm create" in plan
    assert "--accelerator-type v4-32" in plan
    # 4 hosts of the slice each get their start command over gcloud ssh
    assert plan.count("--worker=") >= 5  # 1 head host + 4 slice hosts
    assert "start --address=" in plan
    # nothing was persisted: a dry run leaves no cluster state
    assert load_cluster_state("tpu-demo") is None


def test_launcher_up_job_down(tmp_path, state_dir):
    """The full operator loop: up -> nodes registered -> exec + submit a
    real driver -> down kills exactly this cluster's sessions."""
    yaml_path = _local_yaml(tmp_path, workers=2)
    state = create_or_update_cluster(yaml_path, _print=lambda *a: None)
    try:
        assert state["head_address"]
        assert len(state["workers"]) == 2
        # state survives to a fresh process (down/exec read it from disk)
        assert load_cluster_state("launcher-e2e")["head_address"] == \
            state["head_address"]

        # the cluster is real: a driver sees head + 2 worker nodes
        driver = tmp_path / "driver.py"
        driver.write_text(textwrap.dedent("""
            import os, ray_tpu
            ray_tpu.init(address=os.environ["RAY_TPU_ADDRESS"])
            import time
            deadline = time.time() + 30
            while time.time() < deadline:
                nodes = [n for n in ray_tpu.nodes() if n["alive"]]
                if len(nodes) >= 3:
                    break
                time.sleep(0.5)
            assert len(nodes) >= 3, nodes

            @ray_tpu.remote
            def whoami():
                return ray_tpu.get_runtime_context().node_id
            spots = set(ray_tpu.get([whoami.remote() for _ in range(12)]))
            print("NODES-SEEN", len(nodes), "TASK-NODES", len(spots))
            ray_tpu.shutdown()
        """))
        rc, out = submit_job(yaml_path, str(driver), _print=lambda *a: None)
        assert rc == 0, out
        assert "NODES-SEEN 3" in out

        rc, out = exec_cluster(yaml_path, "echo cluster-says-hi",
                               _print=lambda *a: None)
        assert rc == 0 and "cluster-says-hi" in out
    finally:
        teardown_cluster(yaml_path, _print=lambda *a: None)

    # every session this cluster started is dead; state file removed
    assert load_cluster_state("launcher-e2e") is None
    for node in [state["head"]] + state["workers"]:
        for sess in node["session_dirs"]:
            pids = json.load(open(os.path.join(sess, "pids.json")))
            for pid in pids:
                alive = subprocess.run(["kill", "-0", str(pid)],
                                       capture_output=True).returncode == 0
                assert not alive, f"pid {pid} of {sess} survived teardown"
