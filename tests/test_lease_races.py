"""Lease timeout/grant race + pull admission tests (VERDICT round-1 weak
items #4/#6; cf. reference lease-leak tests and PullManager quota)."""

import threading
import time

import numpy as np

import ray_tpu


def test_lease_timeout_grant_races_leak_nothing():
    """Hammer the raylet with far more lease demand than capacity under a
    tiny lease timeout: timed-out requests and racing grants must all
    either serve a task or return their resources — afterwards the node
    reports full availability again (no leaked leases)."""
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024,
                 system_config={"worker_lease_timeout_s": 0.4})

    @ray_tpu.remote(num_cpus=1)
    def slow(i):
        time.sleep(0.25)
        return i

    # several waves from several threads: lease requests pile up far past
    # what 2 slots can grant inside 0.4s, forcing the timeout/abandoned-
    # grant dance over and over
    results = []
    lock = threading.Lock()

    def wave(base):
        refs = [slow.remote(base + i) for i in range(10)]
        values = ray_tpu.get(refs, timeout=600)
        with lock:
            results.extend(values)

    threads = [threading.Thread(target=wave, args=(base,))
               for base in (0, 10, 20, 30)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(results) == list(range(40))

    # every lease returned: the node's available CPU recovers to its total
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        avail = ray_tpu.available_resources().get("CPU", 0)
        if avail >= 2.0:
            break
        time.sleep(0.2)
    assert ray_tpu.available_resources().get("CPU", 0) >= 2.0, \
        "leaked lease: CPU never returned to the pool"
    ray_tpu.shutdown()


def test_concurrent_large_pulls_respect_admission_cap(ray_start_cluster):
    """Parallel gets of large remote objects ride the pull byte budget:
    with a cap smaller than the combined size they still all complete
    (queued FIFO), and the budget drains back to zero."""
    cluster = ray_start_cluster
    cluster.add_node(resources={"CPU": 2, "producer": 4})
    cluster.wait_for_nodes(2)
    ray_tpu.init(num_cpus=1, address=cluster.address,
                 system_config={
                     "pull_memory_cap_bytes": 8 * 1024 * 1024,
                     "object_transfer_chunk_bytes": 1024 * 1024,
                 })

    @ray_tpu.remote(resources={"producer": 1}, num_cpus=1)
    def produce(i):
        return np.full(512 * 1024, i, dtype=np.float64)  # 4 MiB each

    refs = [produce.remote(i) for i in range(6)]  # 24 MiB total, cap 8 MiB
    ray_tpu.wait(refs, num_returns=len(refs), timeout=120)

    from ray_tpu.runtime.core_worker import get_global_worker
    w = get_global_worker()
    values = [None] * len(refs)

    def fetch(idx):
        values[idx] = ray_tpu.get(refs[idx], timeout=120)

    threads = [threading.Thread(target=fetch, args=(i,))
               for i in range(len(refs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, v in enumerate(values):
        assert v is not None and float(v[0]) == float(i)
    assert w._pull_budget.used == 0  # fully drained after the pulls
    ray_tpu.shutdown()


def test_pull_budget_fifo_and_oversize_unit():
    """_PullBudget unit semantics: strict FIFO (a fitting small request
    can't starve a queued large one), oversize requests clamp to the cap
    and run alone, accounting drains to zero."""
    from ray_tpu.runtime.core_worker import _PullBudget

    b = _PullBudget(100)
    assert b.acquire(60, None)

    got = []
    t = threading.Thread(
        target=lambda: got.append(b.acquire(200, time.monotonic() + 10)))
    t.start()
    # deterministic: wait until the ticket is actually enqueued (a fixed
    # sleep can't distinguish 'blocked waiting' from 'not yet started')
    deadline = time.monotonic() + 10
    while not b._waiters and time.monotonic() < deadline:
        time.sleep(0.01)
    assert b._waiters
    assert got == []  # oversize waits for exclusivity (used > 0)
    # a small request that WOULD fit must queue behind the large head
    assert b.acquire(30, time.monotonic() + 0.3) is False
    b.release(60)
    t.join(timeout=10)
    assert got == [True]  # clamped to cap, admitted alone
    assert b.used == 100
    b.release(200)  # symmetric clamp
    assert b.used == 0
    assert b.acquire(30, time.monotonic() + 1)
    b.release(30)
    assert b.used == 0
