"""Correctness tests for attention kernels and fused layers (CPU, 8-dev mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from ray_tpu.ops.attention import attention, xla_attention
from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.ops.layers import apply_rope, rms_norm, rope_frequencies
from ray_tpu.ops.losses import softmax_cross_entropy
from ray_tpu.ops.ring_attention import ring_attention


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_xla_fwd_bwd(causal):
    key = jax.random.PRNGKey(0)
    B, S, H, D = 2, 128, 2, 32
    q, k, v = [jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3)]
    o_ref = xla_attention(q, k, v, causal=causal)
    o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    np.testing.assert_allclose(o, o_ref, atol=2e-5)

    g_ref = jax.grad(lambda *a: (xla_attention(*a, causal=causal) ** 2).sum(),
                     (0, 1, 2))(q, k, v)
    g = jax.grad(lambda *a: (flash_attention(*a, causal=causal, block_q=32,
                                             block_k=32) ** 2).sum(),
                 (0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(a, b, atol=2e-4)


def test_gqa_repeat_kv():
    key = jax.random.PRNGKey(1)
    B, S, H, KvH, D = 1, 64, 8, 2, 16
    q = jax.random.normal(key, (B, S, H, D))
    k = jax.random.normal(key, (B, S, KvH, D))
    v = jax.random.normal(key, (B, S, KvH, D))
    out = attention(q, k, v, impl="xla")
    assert out.shape == (B, S, H, D)
    # flash path handles GQA by expansion in ops.attention
    out2 = attention(q, k, v, impl="flash")
    np.testing.assert_allclose(out, out2, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "context"))
    key = jax.random.PRNGKey(2)
    B, S, H, D = 2, 256, 2, 16
    q, k, v = [jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3)]
    ref = xla_attention(q, k, v, causal=causal)
    out = jax.jit(lambda *a: ring_attention(
        *a, mesh=mesh, causal=causal, batch_axes=("data",)))(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5)
    # gradients flow through the ring (scan + ppermute autodiff)
    g_ref = jax.grad(lambda *a: (xla_attention(*a, causal=causal) ** 2).sum())(
        q, k, v)
    g = jax.grad(lambda *a: (ring_attention(
        *a, mesh=mesh, causal=causal, batch_axes=("data",)) ** 2).sum())(
        q, k, v)
    np.testing.assert_allclose(g, g_ref, atol=2e-4)


def test_rms_norm_and_rope():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
    w = jnp.ones((16,))
    y = rms_norm(x, w)
    norms = jnp.sqrt(jnp.mean(y ** 2, axis=-1))
    np.testing.assert_allclose(norms, jnp.ones_like(norms), atol=1e-3)

    cos, sin = rope_frequencies(16, 32)
    q = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 2, 16))
    q_rot = apply_rope(q, cos, sin)
    # norms are preserved by rotation
    np.testing.assert_allclose(
        jnp.linalg.norm(q_rot, axis=-1), jnp.linalg.norm(q, axis=-1),
        atol=1e-4)
    # position 0 is identity
    np.testing.assert_allclose(q_rot[:, 0], q[:, 0], atol=1e-5)
    # explicit positions match implicit arange
    pos = jnp.broadcast_to(jnp.arange(8), (1, 8))
    np.testing.assert_allclose(apply_rope(q, cos, sin, pos), q_rot, atol=1e-6)


def test_cross_entropy_matches_manual():
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 16))
    labels = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 16)
    loss, denom = softmax_cross_entropy(logits, labels)
    manual = -jnp.mean(jnp.take_along_axis(
        jax.nn.log_softmax(logits, -1), labels[..., None], -1))
    np.testing.assert_allclose(loss, manual, rtol=1e-5)
    assert denom == 32

    mask = jnp.zeros((4, 8)).at[:, :4].set(1.0)
    loss_m, denom_m = softmax_cross_entropy(logits, labels, mask)
    assert denom_m == 16
    manual_m = -jnp.sum(jnp.take_along_axis(
        jax.nn.log_softmax(logits, -1), labels[..., None], -1)[..., 0] * mask) / 16
    np.testing.assert_allclose(loss_m, manual_m, rtol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_dense(causal):
    from ray_tpu.ops.ulysses import ulysses_attention
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "context"))
    key = jax.random.PRNGKey(3)
    B, S, H, D = 2, 256, 4, 16   # H divisible by context axis (4)
    q, k, v = [jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3)]
    ref = xla_attention(q, k, v, causal=causal)
    out = jax.jit(lambda *a: ulysses_attention(
        *a, mesh=mesh, causal=causal, impl="xla",
        batch_axes=("data",)))(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5)
    g_ref = jax.grad(lambda *a: (xla_attention(*a, causal=causal) ** 2).sum())(
        q, k, v)
    g = jax.grad(lambda *a: (ulysses_attention(
        *a, mesh=mesh, causal=causal, impl="xla",
        batch_axes=("data",)) ** 2).sum())(q, k, v)
    np.testing.assert_allclose(g, g_ref, atol=2e-4)


def test_moe_layer_routes_and_balances():
    from ray_tpu.ops.moe import MoEMLP
    layer = MoEMLP(n_experts=4, d_ff=64, top_k=2, capacity_factor=2.0,
                   dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 16), jnp.float32)
    variables = layer.init(jax.random.PRNGKey(1), x)
    y, state = layer.apply(variables, x, mutable=["intermediates"])
    assert y.shape == x.shape
    assert jnp.all(jnp.isfinite(y))
    (aux,) = state["intermediates"]["moe_aux_loss"]
    # Switch aux loss is exactly coef at perfect balance, >= coef otherwise
    assert float(aux) >= layer.aux_loss_coef * 0.99
    # with generous capacity, every token is dispatched: output != 0
    assert float(jnp.mean(jnp.abs(y))) > 0.0
    # gradients flow to expert weights and the router
    g = jax.grad(lambda v: (layer.apply(v, x,
                                        mutable=["intermediates"])[0] ** 2
                            ).sum())(variables)
    gnorm = jax.tree.reduce(lambda a, b: a + float(jnp.sum(jnp.abs(b))),
                            g["params"], 0.0)
    assert gnorm > 0.0


def test_chunked_lm_loss_matches_dense():
    """chunked projection head == materialized logits + CE, values and
    gradients (the memory-lean path must be numerically identical)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu.ops.losses import chunked_lm_loss, softmax_cross_entropy

    rng = np.random.default_rng(0)
    B, S, D, V = 2, 48, 16, 64          # S not a multiple of chunk_size
    hidden = jnp.asarray(rng.normal(size=(B, S, D)), jnp.float32)
    W = jnp.asarray(rng.normal(size=(D, V)) * 0.1, jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
    mask = jnp.asarray(rng.integers(0, 2, (B, S)), jnp.float32)

    def dense(h, w):
        return softmax_cross_entropy(
            jnp.einsum("bsd,dv->bsv", h, w), labels, mask, z_loss=1e-4)[0]

    def chunked(h, w):
        return chunked_lm_loss(h, w, labels, mask, z_loss=1e-4,
                               chunk_size=32)[0]

    ld, gd = jax.value_and_grad(dense, argnums=(0, 1))(hidden, W)
    lc, gc = jax.value_and_grad(chunked, argnums=(0, 1))(hidden, W)
    np.testing.assert_allclose(float(ld), float(lc), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gd[0]), np.asarray(gc[0]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gd[1]), np.asarray(gc[1]),
                               rtol=1e-4, atol=1e-6)
    # tied-embedding orientation
    lt = chunked_lm_loss(hidden, W.T, labels, mask, z_loss=1e-4,
                         chunk_size=32, transpose_weight=True)[0]
    np.testing.assert_allclose(float(ld), float(lt), rtol=1e-5)


def test_lm_loss_chunked_fn_trains():
    """The chunked head plugs into make_sharded_train and the loss
    tracks the dense head's trajectory."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu.models import GPT, get_config
    from ray_tpu.parallel import MeshConfig, build_mesh
    from ray_tpu.train.step import (OptimizerConfig, lm_loss_chunked_fn,
                                    make_sharded_train)

    cfg = get_config("tiny", max_seq_len=64)
    mesh = build_mesh(MeshConfig(data=-1))
    rng = np.random.default_rng(0)
    batch = {"tokens": jnp.asarray(
        rng.integers(0, cfg.vocab_size, (8, 65)), jnp.int32)}
    losses = {}
    for name, loss_fn in (("dense", None), ("chunked", lm_loss_chunked_fn)):
        model = GPT(cfg, mesh=mesh)
        kwargs = {} if loss_fn is None else {"loss_fn": loss_fn}
        init_fn, step_fn, _, _ = make_sharded_train(
            model, mesh, OptimizerConfig(warmup_steps=1, decay_steps=20),
            example_batch=batch, **kwargs)
        state = init_fn(jax.random.PRNGKey(0), batch)
        for _ in range(3):
            state, m = step_fn(state, batch)
        losses[name] = float(m["loss"])
    # same init/data/optimizer: trajectories must agree closely
    np.testing.assert_allclose(losses["dense"], losses["chunked"],
                               rtol=1e-3)
