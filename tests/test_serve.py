"""Serve library tests: deployments, routing, batching, autoscaling wiring,
composition graphs, HTTP proxy.

Modeled on reference python/ray/serve/tests/ (test_api.py, test_batching.py,
test_deployment_graph.py).
"""

import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_instance():
    ray_tpu.init(num_cpus=8, object_store_memory=128 * 1024 * 1024)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_function_deployment(serve_instance):
    @serve.deployment
    def echo(x):
        return {"echo": x}

    handle = serve.run(echo.bind())
    assert ray_tpu.get(handle.remote("hi")) == {"echo": "hi"}


def test_class_deployment_and_methods(serve_instance):
    @serve.deployment
    class Counter:
        def __init__(self, start):
            self.count = start

        def __call__(self, inc):
            self.count += inc
            return self.count

        def value(self):
            return self.count

    handle = serve.run(Counter.bind(10))
    assert ray_tpu.get(handle.remote(5)) == 15
    assert ray_tpu.get(handle.value.remote()) == 15


def test_multiple_replicas_all_serve(serve_instance):
    @serve.deployment(num_replicas=2)
    class WhoAmI:
        def __init__(self):
            import os
            self.pid = os.getpid()

        def __call__(self, _):
            return self.pid

    handle = serve.run(WhoAmI.bind())
    pids = {ray_tpu.get(handle.remote(None)) for _ in range(20)}
    assert len(pids) == 2, f"expected both replicas hit, got {pids}"


def test_redeploy_updates_version(serve_instance):
    @serve.deployment
    def v(_):
        return 1

    handle = serve.run(v.bind())
    assert ray_tpu.get(handle.remote(None)) == 1

    @serve.deployment(name="v")
    def v2(_):
        return 2

    handle = serve.run(v2.bind())
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if ray_tpu.get(handle.remote(None)) == 2:
            break
        time.sleep(0.2)
    assert ray_tpu.get(handle.remote(None)) == 2


def test_user_config_reconfigure(serve_instance):
    @serve.deployment(user_config={"threshold": 5})
    class Thresholder:
        def __init__(self):
            self.threshold = 0

        def reconfigure(self, config):
            self.threshold = config["threshold"]

        def __call__(self, _):
            return self.threshold

    handle = serve.run(Thresholder.bind())
    assert ray_tpu.get(handle.remote(None)) == 5


def test_composition_graph(serve_instance):
    @serve.deployment
    class Adder:
        def __init__(self, increment):
            self.increment = increment

        def __call__(self, x):
            return x + self.increment

    @serve.deployment
    class Combiner:
        def __init__(self, adder):
            self.adder = adder

        def __call__(self, x):
            doubled = ray_tpu.get(self.adder.remote(x))
            return doubled * 10

    handle = serve.run(Combiner.bind(Adder.bind(3)))
    assert ray_tpu.get(handle.remote(4)) == 70


def test_batching(serve_instance):
    @serve.deployment(max_concurrent_queries=8)
    class Batched:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        def __call__(self, xs):
            # returns batch size per element so the test can observe coalescing
            return [len(xs)] * len(xs)

    handle = serve.run(Batched.bind())
    refs = [handle.remote(i) for i in range(4)]
    sizes = ray_tpu.get(refs)
    assert max(sizes) > 1, f"no batching observed: {sizes}"


def test_status_and_delete(serve_instance):
    @serve.deployment
    def f(_):
        return "ok"

    serve.run(f.bind())
    st = serve.status()
    assert st["f"]["status"] == "HEALTHY"
    assert st["f"]["running_replicas"] == 1
    serve.delete("f")
    assert "f" not in serve.status()


def test_http_proxy(serve_instance):
    import json
    import urllib.request

    @serve.deployment
    def hello(payload):
        return {"got": payload}

    serve.run(hello.bind(),
              http_options=serve.HTTPOptions(port=18231))
    deadline = time.monotonic() + 10
    body = json.dumps({"a": 1}).encode()
    last = None
    while time.monotonic() < deadline:
        try:
            req = urllib.request.Request(
                "http://127.0.0.1:18231/hello", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5) as resp:
                assert json.loads(resp.read()) == {"got": {"a": 1}}
            return
        except Exception as e:  # noqa: BLE001
            last = e
            time.sleep(0.3)
    raise AssertionError(f"http proxy never served: {last}")


SCHEMA_APP_MODULE = "serve_schema_test_app"


def test_schema_roundtrip_and_apply(serve_instance, tmp_path, monkeypatch):
    """ServeApplicationSchema: dict roundtrip, import-path apply with
    overrides, and the controller's KV status snapshot."""
    import sys
    import textwrap

    from ray_tpu.serve.schema import ServeApplicationSchema

    mod = tmp_path / f"{SCHEMA_APP_MODULE}.py"
    mod.write_text(textwrap.dedent("""
        from ray_tpu import serve

        @serve.deployment
        def shout(s: str) -> str:
            return s.upper()

        app = shout.bind()
    """))
    monkeypatch.syspath_prepend(str(tmp_path))
    sys.modules.pop(SCHEMA_APP_MODULE, None)

    d = {"import_path": f"{SCHEMA_APP_MODULE}:app",
         "deployments": [{"name": "shout", "num_replicas": 2}]}
    schema = ServeApplicationSchema.from_dict(d)
    assert schema.to_dict()["import_path"] == f"{SCHEMA_APP_MODULE}:app"

    handle = schema.apply()
    assert ray_tpu.get(handle.remote("hi"), timeout=30) == "HI"
    st = serve.status()
    assert st["shout"]["target_replicas"] == 2

    # controller publishes status into GCS KV for non-driver readers
    import json

    from ray_tpu.experimental import internal_kv
    for _ in range(40):
        raw = internal_kv._internal_kv_get("serve:status")
        if raw and json.loads(raw).get("shout", {}).get(
                "running_replicas") == 2:
            break
        time.sleep(0.25)
    assert raw is not None
    assert json.loads(raw)["shout"]["status"] == "HEALTHY"


def test_llm_generation_deployment(serve_instance):
    """LLM serving composition: a deployment holding a Generator serves
    batched generate calls (the reference Serve LLM benchmark shape)."""
    from ray_tpu.models import Generator, get_config

    @serve.deployment(num_replicas=1, max_concurrent_queries=8)
    class TinyLLM:
        def __init__(self):
            import os
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            import jax
            jax.config.update("jax_platforms", "cpu")  # fast CI replicas
            from ray_tpu.models import GPT
            cfg = get_config("tiny", max_seq_len=64)
            model = GPT(cfg)
            variables = model.init(jax.random.PRNGKey(0),
                                   __import__("jax.numpy", fromlist=["x"]
                                              ).ones((1, 4), dtype="int32"))
            self.gen = Generator(cfg, variables["params"])

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
        async def generate(self, prompts):
            import numpy as np
            # prompts: list of token lists (equal length in this test)
            batch = np.asarray(prompts, np.int32)
            out = self.gen.generate(batch, max_new_tokens=4, temperature=0.0)
            return [row.tolist() for row in np.asarray(out)]

    handle = serve.run(TinyLLM.bind())
    prompts = [[1, 2, 3, 4], [5, 6, 7, 8], [1, 2, 3, 4]]
    refs = [handle.generate.remote(p) for p in prompts]
    outs = [ray_tpu.get(r, timeout=90) for r in refs]
    assert all(len(o) == 4 for o in outs)
    # identical prompts -> identical greedy generations
    assert outs[0] == outs[2]
