"""DAG authoring + durable Workflow tests.

Modeled on reference python/ray/dag/tests and python/ray/workflow/tests
(test_basic_workflows.py, test_recovery.py).
"""

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode


@pytest.fixture
def wf(tmp_path, ray_start_regular):
    workflow.init(str(tmp_path / "wfs"))
    yield ray_start_regular


def test_function_dag_execute(ray_start_regular):
    @ray_tpu.remote
    def a(x):
        return x + 1

    @ray_tpu.remote
    def b(x, y):
        return x * y

    dag = b.bind(a.bind(1), a.bind(2))
    assert ray_tpu.get(dag.execute()) == 6


def test_input_node(ray_start_regular):
    @ray_tpu.remote
    def double(x):
        return 2 * x

    with InputNode() as inp:
        dag = double.bind(inp)
    assert ray_tpu.get(dag.execute(21)) == 42


def test_diamond_executes_shared_node_once(ray_start_regular):
    @ray_tpu.remote
    def source():
        import os
        return os.getpid(), id(object())

    @ray_tpu.remote
    def left(s):
        return s

    @ray_tpu.remote
    def right(s):
        return s

    @ray_tpu.remote
    def join(l, r):
        return l == r

    shared = source.bind()
    dag = join.bind(left.bind(shared), right.bind(shared))
    assert ray_tpu.get(dag.execute()) is True


def test_actor_dag(ray_start_regular):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.v = start

        def add(self, x):
            self.v += x
            return self.v

    node = Counter.bind(10)
    dag = node.add.bind(5)
    assert ray_tpu.get(dag.execute()) == 15


def test_workflow_run_and_output(wf):
    @ray_tpu.remote
    def add(x, y):
        return x + y

    dag = add.bind(add.bind(1, 2), 3)
    result = workflow.run(dag, workflow_id="w1")
    assert result == 6
    assert workflow.get_status("w1") == "SUCCESS"
    assert workflow.get_output("w1") == 6
    assert ("w1", "SUCCESS") in workflow.list_all()


def test_workflow_resume_skips_completed_steps(wf, tmp_path):
    marker = tmp_path / "ran_times"
    marker.write_text("")

    @ray_tpu.remote
    def expensive(path):
        with open(path, "a") as f:
            f.write("x")
        return 10

    @ray_tpu.remote
    def flaky(x, path):
        import os
        if not os.path.exists(path + ".ok"):
            raise RuntimeError("injected failure")
        return x * 2

    dag = flaky.bind(expensive.bind(str(marker)), str(marker))
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="w2")
    assert workflow.get_status("w2") == "FAILED"
    assert marker.read_text() == "x"  # expensive ran once

    # heal the failure, resume: expensive must NOT re-run
    (tmp_path / "ran_times.ok").write_text("")
    result = workflow.resume("w2")
    assert result == 20
    assert marker.read_text() == "x"
    assert workflow.get_status("w2") == "SUCCESS"


def test_workflow_run_async(wf):
    @ray_tpu.remote
    def slow_add(x, y):
        import time
        time.sleep(0.2)
        return x + y

    wid, fut = workflow.run_async(slow_add.bind(20, 22), workflow_id="w3")
    assert fut.result(timeout=30) == 42
    assert workflow.get_status("w3") == "SUCCESS"


def test_workflow_delete(wf):
    @ray_tpu.remote
    def one():
        return 1

    workflow.run(one.bind(), workflow_id="w4")
    workflow.delete("w4")
    assert workflow.get_status("w4") is None


def test_workflow_step_retries_with_backoff(wf, tmp_path):
    """workflow.options(max_retries=N): a flaky step re-submits with
    backoff and the workflow still succeeds (reference step options)."""
    marker = str(tmp_path / "attempts.txt")

    @ray_tpu.remote
    def flaky():
        with open(marker, "a") as f:
            f.write("x")
        if len(open(marker).read()) < 3:
            raise RuntimeError("transient")
        return "recovered"

    dag = flaky.options(
        **workflow.options(max_retries=5, retry_backoff_s=0.05)).bind()
    assert workflow.run(dag, workflow_id="w-retry") == "recovered"
    assert open(marker).read().count("x") == 3


def test_workflow_catch_exceptions(wf):
    """catch_exceptions resolves the step to (result, err) instead of
    failing the workflow."""
    @ray_tpu.remote
    def boom():
        raise ValueError("nope")

    @ray_tpu.remote
    def handle(pair):
        result, err = pair
        return "fallback" if err is not None else result

    dag = handle.bind(
        boom.options(**workflow.options(catch_exceptions=True)).bind())
    assert workflow.run(dag, workflow_id="w-catch") == "fallback"


def test_workflow_wait_for_event(wf, tmp_path):
    """An event step completes when its listener observes the event, and
    the payload checkpoints (resume does not re-wait)."""
    flag = tmp_path / "flag.txt"

    class FileEvent(workflow.EventListener):
        def __init__(self, path):
            self.path = path

        def poll_for_event(self):
            try:
                with open(self.path) as f:
                    return f.read() or None
            except FileNotFoundError:
                return None

    @ray_tpu.remote
    def after(event):
        return f"got:{event}"

    dag = after.bind(workflow.wait_for_event(
        FileEvent, str(flag), poll_interval_s=0.05, timeout_s=30))
    wid, fut = workflow.run_async(dag, workflow_id="w-event")
    import time as _t
    _t.sleep(0.5)
    assert workflow.get_status("w-event") == "RUNNING"
    flag.write_text("fired")
    assert fut.result(timeout=60) == "got:fired"
    # the event is checkpointed: resume replays without re-waiting even
    # though the flag file is gone
    flag.unlink()
    assert workflow.resume("w-event") == "got:fired"


def test_virtual_actor_state_persists(wf, tmp_path):
    """Virtual actor state survives across handles and 'process restarts'
    (a fresh handle over the same storage sees the mutations)."""
    @workflow.virtual_actor
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def add(self, k):
            self.n += k
            return self.n

        def get(self):
            return self.n

    c = Counter.get_or_create("acct-1", 10)
    assert c.add.run(5) == 15
    assert c.add.run(1) == 16
    # a fresh handle (new driver analog) sees the durable state
    again = workflow.get_virtual_actor(Counter, "acct-1")
    assert again.get.run() == 16
    # get_or_create on an existing id must NOT reinitialize
    third = Counter.get_or_create("acct-1", 999)
    assert third.get.run() == 16
