"""DAG authoring + durable Workflow tests.

Modeled on reference python/ray/dag/tests and python/ray/workflow/tests
(test_basic_workflows.py, test_recovery.py).
"""

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode


@pytest.fixture
def wf(tmp_path, ray_start_regular):
    workflow.init(str(tmp_path / "wfs"))
    yield ray_start_regular


def test_function_dag_execute(ray_start_regular):
    @ray_tpu.remote
    def a(x):
        return x + 1

    @ray_tpu.remote
    def b(x, y):
        return x * y

    dag = b.bind(a.bind(1), a.bind(2))
    assert ray_tpu.get(dag.execute()) == 6


def test_input_node(ray_start_regular):
    @ray_tpu.remote
    def double(x):
        return 2 * x

    with InputNode() as inp:
        dag = double.bind(inp)
    assert ray_tpu.get(dag.execute(21)) == 42


def test_diamond_executes_shared_node_once(ray_start_regular):
    @ray_tpu.remote
    def source():
        import os
        return os.getpid(), id(object())

    @ray_tpu.remote
    def left(s):
        return s

    @ray_tpu.remote
    def right(s):
        return s

    @ray_tpu.remote
    def join(l, r):
        return l == r

    shared = source.bind()
    dag = join.bind(left.bind(shared), right.bind(shared))
    assert ray_tpu.get(dag.execute()) is True


def test_actor_dag(ray_start_regular):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.v = start

        def add(self, x):
            self.v += x
            return self.v

    node = Counter.bind(10)
    dag = node.add.bind(5)
    assert ray_tpu.get(dag.execute()) == 15


def test_workflow_run_and_output(wf):
    @ray_tpu.remote
    def add(x, y):
        return x + y

    dag = add.bind(add.bind(1, 2), 3)
    result = workflow.run(dag, workflow_id="w1")
    assert result == 6
    assert workflow.get_status("w1") == "SUCCESS"
    assert workflow.get_output("w1") == 6
    assert ("w1", "SUCCESS") in workflow.list_all()


def test_workflow_resume_skips_completed_steps(wf, tmp_path):
    marker = tmp_path / "ran_times"
    marker.write_text("")

    @ray_tpu.remote
    def expensive(path):
        with open(path, "a") as f:
            f.write("x")
        return 10

    @ray_tpu.remote
    def flaky(x, path):
        import os
        if not os.path.exists(path + ".ok"):
            raise RuntimeError("injected failure")
        return x * 2

    dag = flaky.bind(expensive.bind(str(marker)), str(marker))
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="w2")
    assert workflow.get_status("w2") == "FAILED"
    assert marker.read_text() == "x"  # expensive ran once

    # heal the failure, resume: expensive must NOT re-run
    (tmp_path / "ran_times.ok").write_text("")
    result = workflow.resume("w2")
    assert result == 20
    assert marker.read_text() == "x"
    assert workflow.get_status("w2") == "SUCCESS"


def test_workflow_run_async(wf):
    @ray_tpu.remote
    def slow_add(x, y):
        import time
        time.sleep(0.2)
        return x + y

    wid, fut = workflow.run_async(slow_add.bind(20, 22), workflow_id="w3")
    assert fut.result(timeout=30) == 42
    assert workflow.get_status("w3") == "SUCCESS"


def test_workflow_delete(wf):
    @ray_tpu.remote
    def one():
        return 1

    workflow.run(one.bind(), workflow_id="w4")
    workflow.delete("w4")
    assert workflow.get_status("w4") is None
