"""Scaled-down core-scalability envelope (cf. reference
release/benchmarks/README.md:9-31: 10k+ tasks, 10k+ actors, 1k+ PGs on
64-node clusters).  Counts here are sized for a 1-core CI box but exercise
the same structures: the lease scheduler under a deep task queue, the
actor directory under bulk registration, and the PG manager's 2-phase
bundle reservation at the hundreds scale.  RAY_TPU_TEST_SCALE multiplies
the counts on bigger machines."""

import os

import pytest

import ray_tpu

SCALE = float(os.environ.get("RAY_TPU_TEST_SCALE", "1"))


@pytest.mark.slow
def test_10k_queued_tasks_drain():
    """10k trivial tasks queued through a handful of workers: the per-key
    lease queue and reply plumbing survive depth, no task lost."""
    n = int(10_000 * SCALE)
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)

    @ray_tpu.remote(num_cpus=1)
    def inc(i):
        return i + 1

    refs = [inc.remote(i) for i in range(n)]
    values = ray_tpu.get(refs, timeout=1800)
    assert values == list(range(1, n + 1))
    ray_tpu.shutdown()


@pytest.mark.slow
def test_many_actors_register_and_respond():
    """Bulk actor creation against the GCS FSM + worker pool.  Fractional
    CPUs let actors pack far beyond core count; each still gets a real
    worker process, so the count stays process-bounded on tiny boxes."""
    n = int(60 * SCALE)
    ray_tpu.init(num_cpus=8, object_store_memory=256 * 1024 * 1024,
                 system_config={"worker_start_timeout_s": 300.0})

    @ray_tpu.remote(num_cpus=0.01)
    class A:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    # waves of 15: a 1-core CI box can't fork+import 60 interpreters at
    # once inside the start timeout; the structures under test (GCS actor
    # FSM, worker pool, directory) still reach the full count
    actors = []
    wave = 15
    for base in range(0, n, wave):
        batch = [A.remote(i) for i in range(base, min(base + wave, n))]
        ray_tpu.get([a.who.remote() for a in batch], timeout=1800)
        actors.extend(batch)
    assert ray_tpu.get([a.who.remote() for a in actors],
                       timeout=1800) == list(range(n))
    for a in actors:
        ray_tpu.kill(a)
    ray_tpu.shutdown()


@pytest.mark.slow
def test_thousand_object_args_one_task():
    """1k ObjectRef args into a single task: argument staging resolves
    them all and pins them for the task's lifetime (reference 10k+ args,
    release/benchmarks/README.md:27; benchmarks/scale_envelope.py runs
    the full 10k)."""
    n = int(1_000 * SCALE)
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)

    @ray_tpu.remote
    def consume(*args):
        return len(args), sum(args)

    refs = [ray_tpu.put(i) for i in range(n)]
    count, total = ray_tpu.get(consume.remote(*refs), timeout=1800)
    assert count == n and total == n * (n - 1) // 2
    ray_tpu.shutdown()


@pytest.mark.slow
def test_256_returns_one_task():
    """Hundreds of return slots from one task: per-slot ownership entries
    and the multi-return seal path (reference 3k+ returns,
    release/benchmarks/README.md:28; the bench script runs 1k)."""
    n = int(256 * SCALE)
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)

    @ray_tpu.remote(num_returns=n)
    def produce():
        return tuple(range(n))

    assert ray_tpu.get(list(produce.remote()),
                       timeout=1800) == list(range(n))
    ray_tpu.shutdown()


@pytest.mark.slow
def test_hundred_placement_groups():
    """100+ simultaneous placement groups: 2-phase reservation, bundle
    pools, and clean removal at the reference's envelope dimension."""
    n = int(100 * SCALE)
    ray_tpu.init(num_cpus=8, object_store_memory=128 * 1024 * 1024)
    from ray_tpu.util.placement_group import placement_group, \
        remove_placement_group

    pgs = [placement_group([{"CPU": 0.01}]) for _ in range(n)]
    ray_tpu.get([pg.ready() for pg in pgs], timeout=600)

    @ray_tpu.remote(num_cpus=0.01)
    def where():
        return 1

    # schedule one task into a sample of the groups
    from ray_tpu.util.scheduling_strategies import \
        PlacementGroupSchedulingStrategy
    refs = [where.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg)).remote() for pg in pgs[:10]]
    assert ray_tpu.get(refs, timeout=600) == [1] * 10
    for pg in pgs:
        remove_placement_group(pg)
    ray_tpu.shutdown()
