"""Multi-node simulated cluster + failure tests (cf. reference
python/ray/tests/test_failure*.py, test_component_failures*.py)."""

import time

import pytest

import ray_tpu


def test_two_node_cluster_spreads_tasks(ray_start_cluster):
    cluster = ray_start_cluster
    # head has 1 CPU; second node adds 2
    cluster.add_node(resources={"CPU": 2})
    cluster.wait_for_nodes(2)
    ray_tpu.init(address=cluster.address)
    assert ray_tpu.cluster_resources()["CPU"] >= 3.0

    @ray_tpu.remote
    def whoami():
        import os
        return os.getpid()

    pids = set(ray_tpu.get([whoami.remote() for _ in range(8)], timeout=60))
    assert len(pids) >= 1  # tasks ran somewhere
    ray_tpu.shutdown()


def test_node_death_detected(ray_start_cluster):
    cluster = ray_start_cluster
    node2 = cluster.add_node(resources={"CPU": 2, "spot": 1})
    cluster.wait_for_nodes(2)
    ray_tpu.init(address=cluster.address)
    assert len([n for n in ray_tpu.nodes() if n["alive"]]) == 2
    cluster.remove_node(node2)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        alive = [n for n in ray_tpu.nodes() if n["alive"]]
        if len(alive) == 1:
            break
        time.sleep(0.2)
    assert len([n for n in ray_tpu.nodes() if n["alive"]]) == 1
    ray_tpu.shutdown()


def test_actor_restarts_after_node_death(ray_start_cluster):
    cluster = ray_start_cluster
    node2 = cluster.add_node(resources={"CPU": 2, "pin": 1})
    cluster.wait_for_nodes(2)
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote
    class A:
        def where(self):
            import os
            return os.getpid()

    # pin the actor to node2 via its custom resource, allow restart
    a = A.options(max_restarts=1,
                  resources={"pin": 1}).remote()
    pid1 = ray_tpu.get(a.where.remote(), timeout=60)
    # take node2 down; restart must land on the remaining feasible... there is
    # none with "pin", so instead verify the actor is reported unavailable,
    # then add a new pin node and watch it come back.
    cluster.remove_node(node2)
    cluster.add_node(resources={"CPU": 2, "pin": 1})
    deadline = time.monotonic() + 90
    while True:
        try:
            pid2 = ray_tpu.get(a.where.remote(), timeout=60)
            break
        except ray_tpu.exceptions.RayTpuError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)
    assert pid2 != pid1
    ray_tpu.shutdown()
