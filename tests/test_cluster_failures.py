"""Multi-node simulated cluster + failure tests (cf. reference
python/ray/tests/test_failure*.py, test_component_failures*.py)."""

import time

import pytest

import ray_tpu


def test_two_node_cluster_spreads_tasks(ray_start_cluster):
    cluster = ray_start_cluster
    # head has 1 CPU; second node adds 2
    cluster.add_node(resources={"CPU": 2})
    cluster.wait_for_nodes(2)
    ray_tpu.init(address=cluster.address)
    assert ray_tpu.cluster_resources()["CPU"] >= 3.0

    @ray_tpu.remote
    def whoami():
        import os
        return os.getpid()

    pids = set(ray_tpu.get([whoami.remote() for _ in range(8)], timeout=60))
    assert len(pids) >= 1  # tasks ran somewhere
    ray_tpu.shutdown()


def test_node_death_detected(ray_start_cluster):
    cluster = ray_start_cluster
    node2 = cluster.add_node(resources={"CPU": 2, "spot": 1})
    cluster.wait_for_nodes(2)
    ray_tpu.init(address=cluster.address)
    assert len([n for n in ray_tpu.nodes() if n["alive"]]) == 2
    cluster.remove_node(node2)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        alive = [n for n in ray_tpu.nodes() if n["alive"]]
        if len(alive) == 1:
            break
        time.sleep(0.2)
    assert len([n for n in ray_tpu.nodes() if n["alive"]]) == 1
    ray_tpu.shutdown()


def test_actor_restarts_after_node_death(ray_start_cluster):
    cluster = ray_start_cluster
    node2 = cluster.add_node(resources={"CPU": 2, "pin": 1})
    cluster.wait_for_nodes(2)
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote
    class A:
        def where(self):
            import os
            return os.getpid()

    # pin the actor to node2 via its custom resource, allow restart
    a = A.options(max_restarts=1,
                  resources={"pin": 1}).remote()
    pid1 = ray_tpu.get(a.where.remote(), timeout=60)
    # take node2 down; restart must land on the remaining feasible... there is
    # none with "pin", so instead verify the actor is reported unavailable,
    # then add a new pin node and watch it come back.
    cluster.remove_node(node2)
    cluster.add_node(resources={"CPU": 2, "pin": 1})
    deadline = time.monotonic() + 90
    while True:
        try:
            pid2 = ray_tpu.get(a.where.remote(), timeout=60)
            break
        except ray_tpu.exceptions.RayTpuError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)
    assert pid2 != pid1
    ray_tpu.shutdown()


def test_stranded_bundle_reservation_reconciled(ray_start_cluster):
    """ISSUE 15 satellite: a raylet holding a bundle reservation the
    GCS no longer knows about (placement group removed / rescheduled
    while the return_bundle RPC was lost) must release it via the
    heartbeat-carried bundle reconciliation — no permanently stranded
    resources."""
    from ray_tpu._private import rpc

    cluster = ray_start_cluster
    node2 = cluster.add_node(resources={"CPU": 4})
    cluster.wait_for_nodes(2)
    ray_tpu.init(num_cpus=1, address=cluster.address)
    conn = rpc.connect(node2.address)
    try:
        # orphan reservation: a pg id the GCS never heard of (models a
        # removed group whose return_bundle never arrived)
        r = conn.call("reserve_bundle",
                      {"pg_id": "feedfacefeedface", "index": 0,
                       "resources": {"CPU": 2}})
        assert r["ok"]
        info = conn.call("node_info", {})
        assert info["bundles"] == ["feedfacefeedface:0"]
        assert info["available"]["CPU"] == 2.0
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            info = conn.call("node_info", {})
            if not info["bundles"]:
                break
            time.sleep(0.3)
        assert info["bundles"] == []
        assert info["available"]["CPU"] == 4.0
    finally:
        conn.close()
    ray_tpu.shutdown()


def test_placement_group_reschedules_off_dead_node(ray_start_cluster):
    """A member node dying while holding bundles sends the group back
    to PENDING and fully re-reserves it on surviving/replacement nodes
    — with no tpu-slice/bundle reservation left behind on survivors
    beyond the re-placed set."""
    from ray_tpu._private import rpc
    from ray_tpu.util.placement_group import (placement_group,
                                              placement_group_table)

    cluster = ray_start_cluster
    node2 = cluster.add_node(resources={"CPU": 4})
    node3 = cluster.add_node(resources={"CPU": 4})
    cluster.wait_for_nodes(3)
    ray_tpu.init(num_cpus=1, address=cluster.address)
    pg = placement_group([{"CPU": 3}, {"CPU": 3}],
                         strategy="STRICT_SPREAD")
    assert pg.wait(60)
    tbl = placement_group_table(pg)[pg.id.hex()]
    victim_hex = tbl["placement"][1]
    victim = node2 if node2.node_id == victim_hex else node3
    survivor = node3 if victim is node2 else node2
    cluster.remove_node(victim)
    cluster.add_node(resources={"CPU": 4})
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        tbl = placement_group_table(pg)[pg.id.hex()]
        if tbl["state"] == "CREATED" and \
                victim_hex not in (tbl["placement"] or []):
            break
        time.sleep(0.3)
    assert tbl["state"] == "CREATED"
    assert victim_hex not in tbl["placement"]
    # the survivor holds exactly the bundles of the NEW placement —
    # nothing stranded from the broken incarnation
    expect = {f"{pg.id.hex()}:{i}" for i, nid in
              enumerate(tbl["placement"]) if nid == survivor.node_id}
    conn = rpc.connect(survivor.address)
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            held = set(conn.call("node_info", {})["bundles"])
            if held == expect:
                break
            time.sleep(0.3)
        assert held == expect
    finally:
        conn.close()
    ray_tpu.shutdown()


def test_chunked_object_transfer_across_nodes(ray_start_cluster):
    """A multi-chunk object produced on one node is pulled by another with
    bounded per-message frames (reference chunked ObjectManager::Push)."""
    import numpy as np

    cluster = ray_start_cluster
    cluster.add_node(resources={"CPU": 2, "producer": 1})
    cluster.wait_for_nodes(2)
    ray_tpu.init(num_cpus=1,
                 address=cluster.address,
                 system_config={"object_transfer_chunk_bytes": 256 * 1024})

    @ray_tpu.remote(resources={"producer": 1}, num_cpus=1)
    def produce():
        # ~4 MiB -> 16 chunks at the configured 256 KiB
        return np.arange(1024 * 1024, dtype=np.float32)

    ref = produce.remote()
    value = ray_tpu.get(ref, timeout=120)
    assert value.shape == (1024 * 1024,)
    assert float(value[-1]) == 1024 * 1024 - 1
    # pull again via a consumer task pinned to the head (cross-node arg)
    @ray_tpu.remote(num_cpus=1)
    def consume(arr):
        return float(arr.sum())

    total = ray_tpu.get(consume.remote(ref), timeout=120)
    assert total == float(np.arange(1024 * 1024, dtype=np.float32).sum())
    ray_tpu.shutdown()
