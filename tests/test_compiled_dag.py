"""Compiled DAG tests: compile validation, channel slot reuse, fan-out,
error propagation, backpressure, worker death + recompile, asyncio.

Cf. reference python/ray/dag/tests/experimental/test_accelerated_dag.py;
the subsystem under test is docs/compiled_dag.md
(dag/compiled_dag.py + experimental/channel.py + the actor-side loop in
runtime/worker_main.py).

The channel-layer tests at the bottom run against their own standalone
shm segment (no cluster) and the compile-validation cases share one
cluster spin-up — tier-1 wall time on this 1-core box is budgeted."""

import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.dag import InputNode
from ray_tpu.exceptions import (ChannelClosedError, ChannelTimeoutError,
                                DAGCompileError, DAGUnavailableError)

_TIMEOUT_SCALE = float(os.environ.get("RAY_TPU_TIMEOUT_SCALE", "1.0"))
GET_T = 60.0 * _TIMEOUT_SCALE


@pytest.fixture(scope="module", autouse=True)
def _debug_sanitizers():
    """Run the whole compiled-DAG suite under the lock-order sanitizer
    and the shm-ring protocol checker (docs/static_analysis.md) — the
    ring protocol and the driver/actor locking here are exactly what
    those sanitizers exist to police."""
    from conftest import debug_sanitizers_enabled
    with debug_sanitizers_enabled():
        yield


@ray_tpu.remote
class Adder:
    def __init__(self, inc=1):
        self.inc = inc

    def add(self, x):
        return x + self.inc

    def add2(self, x, y):
        return x + y + self.inc

    def boom(self, x):
        if x == 13:
            raise ValueError("unlucky number")
        return x

    def slow(self, x):
        time.sleep(0.25)
        return x

    def die(self, x):
        if x == "die":
            import os
            os._exit(1)
        return x


def _chain(n_stages=3):
    """3-stage compiled chain over fresh ClassNode actors."""
    with InputNode() as inp:
        node = inp
        for i in range(n_stages):
            node = Adder.bind(10 ** i).add.bind(node)
    return node


# ------------------------------------------------------------- validation
def test_compile_validation_errors(ray_start_regular):
    """Every rejection path of experimental_compile, on one cluster."""
    # no InputNode reachable
    with pytest.raises(DAGCompileError, match="InputNode"):
        Adder.bind().add.bind(5).experimental_compile()

    # task (function) nodes: as root and mid-graph
    @ray_tpu.remote
    def f(x):
        return x

    with pytest.raises(DAGCompileError, match="actor method"):
        f.bind(1).experimental_compile()
    with InputNode() as inp:
        dag = Adder.bind().add.bind(f.bind(inp))
    with pytest.raises(DAGCompileError, match="actor-method only"):
        dag.experimental_compile()

    # more than one InputNode
    i1, i2 = InputNode(), InputNode()
    with pytest.raises(DAGCompileError, match="single InputNode"):
        Adder.bind().add2.bind(i1, i2).experimental_compile()

    # the output node must be an actor method call
    with pytest.raises(DAGCompileError, match="actor method"):
        InputNode().experimental_compile()

    # cycles (hand-mutated; the bind API cannot author one)
    with InputNode() as inp:
        a = Adder.bind().add.bind(inp)
    a._bound_args = (a,)
    with pytest.raises(DAGCompileError, match="cycle"):
        a.experimental_compile()

    # binding a dead actor's method
    h = Adder.remote()
    ray_tpu.get(h.add.remote(1))          # ensure alive, then kill
    ray_tpu.kill(h)
    time.sleep(0.5)
    with InputNode() as inp:
        dead_dag = h.add.bind(inp)
    with pytest.raises(DAGCompileError, match="not alive"):
        dead_dag.experimental_compile()


# ------------------------------------------------------------- execution
def test_basic_chain_live_handles_and_single_get(ray_start_regular):
    """Chain result correctness; live-handle binding shares the actor
    with the classic path; a ref's value may be taken exactly once."""
    cdag = _chain().experimental_compile()
    try:
        ref = cdag.execute(5)
        assert ref.get(timeout=GET_T) == 5 + 111
        with pytest.raises(ValueError, match="already retrieved"):
            ref.get(timeout=GET_T)
    finally:
        cdag.teardown()

    h = Adder.remote(7)
    with InputNode() as inp:
        cdag = h.add.bind(inp).experimental_compile()
    try:
        assert cdag.execute(1).get(timeout=GET_T) == 8
        # the classic path still works on the same live actor
        assert ray_tpu.get(h.add.remote(2)) == 9
    finally:
        cdag.teardown()


def test_repeated_execution_reuses_slots_no_shm_growth(ray_start_regular):
    """1k executes ride the preallocated rings: the store's
    bytes_in_use must not move (the acceptance criterion's leak bar)."""
    from ray_tpu.runtime.core_worker import get_global_worker
    cdag = _chain().experimental_compile(max_inflight=4)
    try:
        for i in range(20):       # settle caches/leases
            cdag.execute(i).get(timeout=GET_T)
        store = get_global_worker().store
        before = store.stats()["bytes_in_use"]
        for i in range(1000):
            assert cdag.execute(i).get(timeout=GET_T) == i + 111
        after = store.stats()["bytes_in_use"]
        assert after == before, (before, after)
    finally:
        cdag.teardown()


def test_multi_reader_fanout_and_join(ray_start_regular):
    """One producer channel consumed by two downstream actors (reader-
    release refcounts) plus a two-input join stage."""
    with InputNode() as inp:
        shared = Adder.bind(1).add.bind(inp)        # x + 1
        left = Adder.bind(10).add.bind(shared)      # x + 11
        right = Adder.bind(100).add.bind(shared)    # x + 101
        dag = Adder.bind(0).add2.bind(left, right)  # 2x + 112
    cdag = dag.experimental_compile()
    try:
        for x in (0, 5, 42):
            assert cdag.execute(x).get(timeout=GET_T) == 2 * x + 112
    finally:
        cdag.teardown()


def test_exception_propagation_and_recovery(ray_start_regular):
    """A user exception becomes an error item: downstream stages forward
    it, get() raises it, and the DAG keeps executing afterwards."""
    with InputNode() as inp:
        dag = Adder.bind(100).add.bind(
            Adder.bind(0).boom.bind(Adder.bind(1).add.bind(inp)))
    cdag = dag.experimental_compile()
    try:
        assert cdag.execute(0).get(timeout=GET_T) == 101
        with pytest.raises(exc.TaskError, match="unlucky"):
            cdag.execute(12).get(timeout=GET_T)     # 12+1 == 13 -> boom
        assert cdag.execute(1).get(timeout=GET_T) == 102
    finally:
        cdag.teardown()


def test_max_inflight_backpressure_bound(ray_start_regular):
    """The submit window blocks at max_inflight: the N+1th execute waits
    for a completed execution to drain before its input is admitted.

    The stage duration rides inside the input item so the window phase
    can use a 1 s stage — wide enough that a CPU-starved in-suite run
    cannot push legitimate (non-blocking) submit cost past the
    regression signal, which costs a full stage."""

    @ray_tpu.remote
    class Sleeper:
        def nap(self, item):
            time.sleep(item[0])
            return item[1]

    with InputNode() as inp:
        dag = Sleeper.bind().nap.bind(inp)
    cdag = dag.experimental_compile(max_inflight=2)
    try:
        stage = 1.0
        t_start = time.monotonic()
        r0, r1 = cdag.execute((stage, 0)), cdag.execute((stage, 1))
        submit_two = time.monotonic() - t_start
        r2 = cdag.execute((stage, 2))
        admitted = time.monotonic() - t_start
        # if submits blocked on completion, execute((stage, 1)) alone
        # would have cost >= one full stage
        assert submit_two < 0.6 * stage, submit_two
        # r2 cannot be admitted before r0's full stage ran and drained;
        # starvation only ever pushes this wait UP, never down
        assert admitted >= 0.9 * stage, admitted
        assert [r.get(timeout=GET_T)
                for r in (r0, r1, r2)] == [0, 1, 2]
        # execute(timeout=) surfaces a held-full window as GetTimeoutError
        refs = [cdag.execute((0.5, i)) for i in (3, 4)]
        with pytest.raises(exc.GetTimeoutError):
            cdag.execute((0.5, 99), timeout=0.05)
        assert [r.get(timeout=GET_T) for r in refs] == [3, 4]
    finally:
        cdag.teardown()


def test_actor_death_unavailable_then_recompile(ray_start_regular):
    """Mid-execution worker death poisons the graph: the blocked get()
    raises DAGUnavailableError, later executes fail fast, and a fresh
    experimental_compile() restores service on new actors."""
    with InputNode() as inp:
        dag = Adder.bind(100).add.bind(Adder.bind().die.bind(inp))
    cdag = dag.experimental_compile()
    try:
        assert cdag.execute(1).get(timeout=GET_T) == 101
        with pytest.raises(DAGUnavailableError):
            cdag.execute("die").get(timeout=GET_T)
        with pytest.raises(DAGUnavailableError):
            cdag.execute(2)
    finally:
        cdag.teardown()
    cdag2 = dag.experimental_compile()
    try:
        assert cdag2.execute(3).get(timeout=GET_T) == 103
    finally:
        cdag2.teardown()


def test_teardown_then_execute_raises(ray_start_regular):
    cdag = _chain(1).experimental_compile(max_inflight=2)
    assert cdag.execute(0).get(timeout=GET_T) == 1
    pending = cdag.execute(1)          # outstanding ref across teardown
    cdag.teardown()
    cdag.teardown()                    # idempotent
    with pytest.raises(DAGUnavailableError, match="torn down"):
        cdag.execute(2)
    # an outstanding ref must fail cleanly too, not touch freed channels
    with pytest.raises(DAGUnavailableError, match="torn down"):
        pending.get(timeout=GET_T)


def test_async_await_and_async_actor_method(ray_start_regular):
    """``await ref`` resolves compiled results from asyncio, including a
    graph whose stage is a coroutine method (executed on the actor's
    event loop by the resident DAG loop)."""
    import asyncio

    @ray_tpu.remote
    class AsyncAdder:
        async def add(self, x):
            await asyncio.sleep(0.001)
            return x + 1

    with InputNode() as inp:
        cdag = AsyncAdder.bind().add.bind(inp).experimental_compile(
            max_inflight=4)
    try:
        async def run():
            refs = [cdag.execute(i) for i in range(4)]
            return [await r for r in refs]

        assert asyncio.run(run()) == [1, 2, 3, 4]
    finally:
        cdag.teardown()


def test_serialization_edge_paths(ray_start_regular):
    """An oversized input fails cleanly (the claimed window slot rolls
    back, drain accounting stays aligned) and a non-serializable stage
    result becomes an error item — the DAG keeps executing after both."""

    @ray_tpu.remote
    class Edge:
        def maybe_bad(self, x):
            return threading.Lock() if x == "bad" else x

    with InputNode() as inp:
        cdag = Edge.bind().maybe_bad.bind(inp).experimental_compile(
            buffer_size_bytes=64 * 1024)
    try:
        assert cdag.execute(1).get(timeout=GET_T) == 1
        with pytest.raises(ValueError, match="capacity"):
            cdag.execute(b"x" * (128 * 1024))
        assert cdag.execute(2).get(timeout=GET_T) == 2
        with pytest.raises(exc.TaskError):
            cdag.execute("bad").get(timeout=GET_T)
        assert cdag.execute(3).get(timeout=GET_T) == 3
    finally:
        cdag.teardown()


# ------------------------------------------------------------- channels
@pytest.fixture
def standalone_store(tmp_path):
    """A private shm segment — the channel layer needs no cluster."""
    from ray_tpu.runtime.object_store import SharedMemoryStore
    path = str(tmp_path / "chan_store")
    store = SharedMemoryStore.create_segment(path, 8 * 1024 * 1024)
    yield store
    store.close()
    store.unlink()


def test_channel_ring_reuse_error_bit_and_poison(standalone_store):
    """Unit-level: the shm channel ring reuses its slots, blocks the
    writer at capacity, carries the error bit, and poison wakes blocked
    peers."""
    from ray_tpu._private import serialization as ser
    from ray_tpu.experimental.channel import (Channel, ChannelReader,
                                              ChannelWriter, FLAG_ERROR,
                                              channel_object_id)

    store = standalone_store
    ch = Channel.create(store, channel_object_id(b"test-ring"),
                        nslots=2, nreaders=1, capacity=4096)
    w, r = ChannelWriter(ch), ChannelReader(ch, 0)
    before = store.stats()["bytes_in_use"]
    for i in range(50):                # 25 laps around the 2-slot ring
        w.write(i)
        assert r.read(timeout=5.0) == i
    assert store.stats()["bytes_in_use"] == before
    # writer blocks once the ring is full of unconsumed items
    w.write("a")
    w.write("b")
    with pytest.raises(ChannelTimeoutError):
        w.write("c", timeout=0.1)
    # error payloads round-trip via the flag + re-raise on deserialize
    assert r.read(timeout=5.0) == "a"
    w.write_error(RuntimeError("boom"), timeout=5.0)
    assert r.read(timeout=5.0) == "b"
    payload, flags = r.read_raw(timeout=5.0)
    assert flags & FLAG_ERROR
    with pytest.raises(RuntimeError, match="boom"):
        ser.deserialize(payload)
    # an oversized payload is rejected up front
    with pytest.raises(ValueError, match="capacity"):
        w.write(b"x" * 8192)
    # poison wakes a blocked reader
    t = threading.Thread(target=ch.poison)
    t.start()
    with pytest.raises(ChannelClosedError):
        r.read(timeout=5.0)
    t.join()
    ch.close()
    assert ch.delete()                 # pin released: backing object freed
    assert store.stats()["bytes_in_use"] < before


def test_channel_multi_reader_acks(standalone_store):
    """Per-reader ack words: the slowest reader gates slot reuse."""
    from ray_tpu.experimental.channel import (Channel, ChannelReader,
                                              ChannelWriter,
                                              channel_object_id)

    ch = Channel.create(standalone_store, channel_object_id(b"test-mr"),
                        nslots=1, nreaders=2, capacity=1024)
    try:
        w = ChannelWriter(ch)
        r0, r1 = ChannelReader(ch, 0), ChannelReader(ch, 1)
        w.write("x")
        assert r0.read(timeout=5.0) == "x"
        # reader 1 hasn't consumed item 0: the 1-slot ring is still full
        with pytest.raises(ChannelTimeoutError):
            w.write("y", timeout=0.1)
        assert r1.read(timeout=5.0) == "x"
        w.write("y", timeout=5.0)      # slot released by the last reader
        assert r0.read(timeout=5.0) == "y"
        assert r1.read(timeout=5.0) == "y"
    finally:
        ch.close()
        ch.delete()
