"""RL wave 2 tests: bandits, CRR, Ape-X DQN, Decision Transformer,
multi-agent (model: reference rllib/algorithms/*/tests +
rllib/tests/test_multi_agent_env.py)."""

import math

import numpy as np
import pytest


def test_linucb_beats_random():
    from ray_tpu.rl import BanditConfig, LinearDiscreteEnv
    cfg = (BanditConfig()
           .environment(lambda: LinearDiscreteEnv(n_arms=4, dim=6, seed=3))
           .training(steps_per_iteration=200)
           .debugging(seed=0))
    algo = cfg.algo_class(cfg)
    first = algo.train()
    for _ in range(4):
        last = algo.train()
    # regret shrinks as the posteriors tighten
    assert last["mean_regret"] < first["mean_regret"]
    ckpt = algo.save()
    algo.restore(ckpt)
    algo.stop()


def test_lints_runs():
    from ray_tpu.rl import BanditLinTSConfig, LinearDiscreteEnv
    cfg = (BanditLinTSConfig()
           .environment(lambda: LinearDiscreteEnv(n_arms=3, dim=4, seed=1))
           .training(steps_per_iteration=100)
           .debugging(seed=0))
    algo = cfg.algo_class(cfg)
    r = algo.train()
    assert math.isfinite(r["episode_reward_mean"])
    assert r["timesteps_total"] == 100
    algo.stop()


def test_crr_pendulum_runs(ray_start_regular, tmp_path):
    from ray_tpu.rl import CRRConfig, collect_dataset
    path = collect_dataset("Pendulum-v1", str(tmp_path / "ds"),
                           n_steps=400, seed=5)
    cfg = (CRRConfig()
           .environment("Pendulum-v1")
           .training(num_sgd_iter=8, train_batch_size=64, hidden=(32, 32),
                     n_action_samples=2)
           .debugging(seed=0))
    cfg.offline_data(input_path=path)
    algo = cfg.algo_class(cfg)
    r = algo.train()
    info = r["info"]
    assert math.isfinite(info["critic_loss"])
    assert math.isfinite(info["actor_loss"])
    assert info["mean_weight"] > 0          # exp-advantage weights active


def test_apex_dqn_cartpole_runs(ray_start_regular):
    from ray_tpu.rl import ApexDQNConfig
    algo = (ApexDQNConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=1,
                      rollout_fragment_length=32)
            .training(learning_starts=64, train_batch_size=32,
                      n_updates_per_iter=16, hidden=(32, 32))
            .debugging(seed=0)
            .build())
    try:
        got_updates = False
        for _ in range(6):
            r = algo.train()
            info = r["info"]
            if "loss" in info:
                got_updates = True
        # per-worker epsilon ladder is strictly decreasing
        eps = info["epsilons"]
        assert len(eps) == 2 and eps[0] > eps[1]
        assert got_updates, info
        assert r["timesteps_total"] > 0
    finally:
        algo.stop()


def test_dt_learns_dataset_actions(ray_start_regular, tmp_path):
    from ray_tpu.rl import DTConfig, collect_dataset
    path = collect_dataset("CartPole-v1", str(tmp_path / "ds"),
                           n_steps=600, seed=7)
    cfg = (DTConfig()
           .environment("CartPole-v1")
           .training(num_sgd_iter=12, train_batch_size=16, context_len=8,
                     d_model=32, n_layers=2, n_heads=2)
           .debugging(seed=0))
    cfg.offline_data(input_path=path)
    algo = cfg.algo_class(cfg)
    r1 = algo.train()
    r2 = algo.train()
    # sequence-model fit improves on the dataset
    assert r2["info"]["loss"] < r1["info"]["loss"]
    assert 0.0 <= r2["info"]["action_accuracy"] <= 1.0
    assert math.isfinite(r2["episode_reward_mean"])
    ckpt = algo.save()
    algo.restore(ckpt)


def test_multi_agent_env_api():
    from ray_tpu.rl import MultiAgentCartPole
    env = MultiAgentCartPole(num_agents=3)
    obs, _ = env.reset(seed=0)
    assert set(obs) == {"agent_0", "agent_1", "agent_2"}
    obs, rews, terms, truncs, _ = env.step(
        {aid: 1 for aid in env.agent_ids})
    assert "__all__" in terms
    assert all(isinstance(r, float) for r in rews.values())
    env.close()


def test_multi_agent_ppo_shared_policy(ray_start_regular):
    from ray_tpu.rl import MultiAgentCartPole, MultiAgentPPOConfig
    cfg = (MultiAgentPPOConfig()
           .environment(lambda: MultiAgentCartPole(num_agents=2,
                                                   max_steps=100))
           .rollouts(num_rollout_workers=2)
           .training(num_sgd_iter=4, sgd_minibatch_size=64,
                     episodes_per_sample=2, hidden=(32, 32))
           .debugging(seed=0))
    algo = cfg.algo_class(cfg)
    try:
        r = algo.train()
        assert "shared" in r["info"]           # default mapping fn
        assert math.isfinite(r["info"]["shared"]["total_loss"])
        assert r["timesteps_total"] > 0
        ckpt = algo.save()
        algo.restore(ckpt)
    finally:
        algo.stop()


def test_multi_agent_ppo_per_agent_policies(ray_start_regular):
    from ray_tpu.rl import MultiAgentCartPole, MultiAgentPPOConfig
    cfg = (MultiAgentPPOConfig()
           .environment(lambda: MultiAgentCartPole(num_agents=2,
                                                   max_steps=80))
           .rollouts(num_rollout_workers=1)
           .training(num_sgd_iter=2, sgd_minibatch_size=32,
                     episodes_per_sample=1, hidden=(32,))
           .debugging(seed=0))
    cfg.multi_agent(policy_mapping_fn=lambda aid: aid)   # one per agent
    algo = cfg.algo_class(cfg)
    try:
        r = algo.train()
        assert set(r["info"]) == {"agent_0", "agent_1"}
    finally:
        algo.stop()


def test_registry_covers_new_families():
    from ray_tpu.rl import get_algorithm_class
    for name in ("apexdqn", "crr", "dt", "bandit-lin-ucb", "banditlints"):
        assert get_algorithm_class(name) is not None


def test_r2d2_policy_carry_management():
    from ray_tpu.rl import R2D2Policy
    from ray_tpu.rl.env import Box, Discrete
    import numpy as np
    pol = R2D2Policy(Box(low=-1, high=1, shape=(4,)), Discrete(2),
                     hidden=(8,), lstm_size=8, num_envs=3, seed=0,
                     epsilon=0.0)
    obs = np.random.default_rng(0).normal(
        size=(3, 4)).astype(np.float32)
    a1, _, q1 = pol.compute_actions(obs)
    assert a1.shape == (3,)
    c_before = np.asarray(pol.carry[0]).copy()
    pol.compute_actions(obs)
    assert not np.allclose(np.asarray(pol.carry[0]), c_before)  # evolves
    pol.reset_carry(np.array([1, 0, 0]))
    assert np.allclose(np.asarray(pol.carry[0])[0], 0.0)        # env0 zeroed
    assert not np.allclose(np.asarray(pol.carry[0])[1], 0.0)


def test_r2d2_sequence_sampling():
    from ray_tpu.rl import RolloutWorker
    w = RolloutWorker("CartPole-v1", num_envs=2, rollout_fragment_length=12,
                      policy="r2d2", hidden=(8,),
                      policy_kwargs={"lstm_size": 8}, seed=0)
    batch = w.sample_sequences()
    assert batch["obs"].shape == (2, 12, 4)
    assert batch["seq_valid"].shape == (2, 12)
    # valid mask is monotone non-increasing per sequence
    import numpy as np
    v = batch["seq_valid"]
    assert np.all(np.diff(v, axis=1) <= 0)


def test_r2d2_cartpole_runs(ray_start_regular):
    from ray_tpu.rl import R2D2Config
    import math
    algo = (R2D2Config()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=1, num_envs_per_worker=2,
                      rollout_fragment_length=16)
            .training(learning_starts=4, train_batch_size=8, burn_in=2,
                      n_updates_per_iter=8, hidden=(16,), lstm_size=16)
            .debugging(seed=0)
            .build())
    try:
        got = False
        for _ in range(4):
            r = algo.train()
            if "loss" in r["info"]:
                got = True
        assert got, r["info"]
        assert math.isfinite(r["info"]["loss"])
        assert r["info"]["trained_steps"] > 0
        assert r["timesteps_total"] > 0
    finally:
        algo.stop()


def test_two_step_game_env():
    from ray_tpu.rl import TwoStepGame
    env = TwoStepGame()
    obs, _ = env.reset()
    assert set(obs) == {"agent_0", "agent_1"}
    # branch B, coordinated action 1 -> team reward 8
    env.step({"agent_0": 1, "agent_1": 0})
    _, rews, terms, _, _ = env.step({"agent_0": 1, "agent_1": 1})
    assert sum(rews.values()) == 8.0
    assert terms["__all__"]


def test_qmix_learns_coordination():
    """QMIX's monotonic mixer discovers the coordinated payoff 8 in the
    two-step game (the reference's canonical QMIX check,
    rllib/examples/two_step_game.py); independent greedy gets only 7."""
    from ray_tpu.rl import QMixConfig, TwoStepGame
    cfg = (QMixConfig().environment(TwoStepGame)
           .training(episodes_per_iter=40, n_updates_per_iter=24,
                     learning_starts=32, target_update_freq=60,
                     epsilon_timesteps=1200)
           .debugging(seed=0))
    algo = cfg.algo_class(cfg)
    try:
        for _ in range(30):
            r = algo.train()
        ev = algo.evaluate(episodes=10)
        assert ev >= 7.0, (ev, r["episode_reward_mean"])
        assert math.isfinite(r["info"]["loss"])
        ckpt = algo.save()
        algo.restore(ckpt)
    finally:
        algo.stop()


def test_tictactoe_env():
    from ray_tpu.rl import TicTacToe
    env = TicTacToe()
    env.reset()
    assert len(env.legal_actions()) == 9
    env.step(0); env.step(3); env.step(1); env.step(4)
    w, done = env.step(2)          # X completes the top row
    assert (w, done) == (1, True)
    assert env.observation().shape == (18,)


def test_mcts_finds_winning_move():
    """With uniform priors, PUCT search must find a one-move win."""
    from ray_tpu.rl import MCTS, TicTacToe
    import numpy as np
    env = TicTacToe()
    env.reset()
    # X on 0,1; O on 3,4 — X to move, 2 wins immediately
    env.board[[0, 1]] = 1
    env.board[[3, 4]] = -1
    env.player = 1
    mcts = MCTS(lambda obs: (np.full(9, 1 / 9), 0.0),
                num_simulations=80, rng=np.random.default_rng(0))
    pi = mcts.run(env, add_noise=False)
    assert int(np.argmax(pi)) == 2, pi


def test_alpha_zero_self_play_distills():
    """Self-play training improves the RAW network policy vs random
    (search-free probe; the search alone already plays well)."""
    from ray_tpu.rl import AlphaZeroConfig, get_algorithm_class
    assert get_algorithm_class("alphazero") is not None
    cfg = (AlphaZeroConfig()
           .training(episodes_per_iter=10, num_simulations=32,
                     num_sgd_iter=12, train_batch_size=64)
           .environment()
           .debugging(seed=0))
    algo = cfg.algo_class(cfg)
    before = algo.play_vs_random(games=30, use_search=False)
    for _ in range(8):
        r = algo.train()
    after = algo.play_vs_random(games=30, use_search=False)
    score_b = before["win_rate"] + 0.5 * before["draw_rate"]
    score_a = after["win_rate"] + 0.5 * after["draw_rate"]
    assert score_a > score_b, (before, after)
    assert math.isfinite(r["info"]["loss"])
    # with search the agent dominates a random opponent
    search_eval = algo.play_vs_random(games=10)
    assert search_eval["win_rate"] + search_eval["draw_rate"] >= 0.8, \
        search_eval


def test_cooperative_nav_env():
    from ray_tpu.rl import CooperativeNav
    env = CooperativeNav(num_agents=2, max_steps=5)
    obs, _ = env.reset(seed=0)
    assert set(obs) == {"agent_0", "agent_1"}
    for _ in range(5):
        obs, rews, terms, truncs, _ = env.step(
            {a: np.zeros(2) for a in env.agent_ids})
    assert truncs["__all__"]             # time-limit truncation
    assert all(r <= 0 for r in rews.values())   # -distance reward


def test_maddpg_learns_cooperative_nav():
    """Centralized critics + decentralized actors improve landmark
    coverage (cf. reference rllib/algorithms/maddpg)."""
    from ray_tpu.rl import MADDPGConfig, CooperativeNav, get_algorithm_class
    assert get_algorithm_class("maddpg") is not None
    cfg = (MADDPGConfig()
           .environment(lambda: CooperativeNav(num_agents=2, max_steps=25))
           .training(steps_per_iter=250, n_updates_per_iter=24,
                     learning_starts=300, train_batch_size=128,
                     exploration_noise=0.2, hidden=(64, 64))
           .debugging(seed=0))
    algo = cfg.algo_class(cfg)
    try:
        before = algo.evaluate(episodes=5)
        for _ in range(20):
            r = algo.train()
        after = algo.evaluate(episodes=5)
        assert after > before + 1.0, (before, after)
        assert math.isfinite(r["info"]["critic_loss"])
        ckpt = algo.save()
        algo.restore(ckpt)
    finally:
        algo.stop()


def test_maml_adaptation_gain():
    """Meta-training makes one inner SGD step on a new sinusoid task pay
    off: post-adaptation query MSE beats pre-adaptation, and both beat
    the untrained init by a wide margin (cf. reference
    rllib/algorithms/maml; Finn et al. sinusoid benchmark). The inner
    loop is differentiated through (second-order) inside one jitted,
    task-vmapped meta-step."""
    from ray_tpu.rl import MAMLConfig, get_algorithm_class
    assert get_algorithm_class("maml") is not None
    cfg = (MAMLConfig().environment()
           .training(meta_updates_per_iter=100, meta_batch_size=16)
           .debugging(seed=0))
    algo = cfg.algo_class(cfg)
    e0 = algo.evaluate()
    for _ in range(5):
        r = algo.train()
    assert r["post_adapt_mse"] < r["pre_adapt_mse"], r
    assert r["post_adapt_mse"] < 0.65 * e0["post_adapt_mse"], (e0, r)
    ckpt = algo.save()
    algo.restore(ckpt)


def test_maml_first_order_runs():
    from ray_tpu.rl import MAMLConfig
    cfg = (MAMLConfig().environment()
           .training(meta_updates_per_iter=20, meta_batch_size=8,
                     first_order=True, inner_steps=2)
           .debugging(seed=1))
    algo = cfg.algo_class(cfg)
    r = algo.train()
    assert math.isfinite(r["info"]["meta_loss"])
    assert r["timesteps_total"] == 20 * 8 * 20


def test_interest_evolution_env():
    from ray_tpu.rl import InterestEvolutionEnv
    env = InterestEvolutionEnv(seed=0)
    obs = env.reset(seed=0)
    assert obs["docs"].shape == (10, 8)
    probs = env.choice_probs(np.array([0, 1, 2]))
    assert len(probs) == 4                  # slate + no-click
    assert abs(probs.sum() - 1.0) < 1e-6
    obs, r, done, clicked = env.step(np.array([0, 1, 2]))
    assert r >= 0.0 and clicked >= -1


def test_slateq_improves_engagement():
    """Decomposed slate Q-learning lifts engagement over the untrained
    policy (cf. reference rllib/algorithms/slateq)."""
    from ray_tpu.rl import (InterestEvolutionEnv, SlateQConfig,
                            get_algorithm_class)
    assert get_algorithm_class("slateq") is not None
    cfg = (SlateQConfig()
           .environment(lambda: InterestEvolutionEnv(seed=1))
           .training(steps_per_iter=300, n_updates_per_iter=24,
                     learning_starts=400, epsilon_timesteps=3000)
           .debugging(seed=0))
    algo = cfg.algo_class(cfg)
    try:
        before = algo.evaluate(episodes=10)
        for _ in range(12):
            r = algo.train()
        after = algo.evaluate(episodes=10)
        assert after > before, (before, after)
        assert math.isfinite(r["info"]["loss"])
        ckpt = algo.save()
        algo.restore(ckpt)
    finally:
        algo.stop()


def test_dreamer_world_model_learns():
    """The RSSM world model's reconstruction+reward+KL loss drops as real
    experience accumulates, and the imagination actor-critic updates run
    (cf. reference rllib/algorithms/dreamer — control-level learning
    needs far more steps than a unit test; the model-learning signal is
    the testable core)."""
    from ray_tpu.rl import DreamerConfig, get_algorithm_class
    assert get_algorithm_class("dreamer") is not None
    cfg = (DreamerConfig().environment("Pendulum-v1")
           .training(steps_per_iter=400, n_updates_per_iter=10,
                     learning_starts=8, seq_len=25)
           .debugging(seed=0))
    algo = cfg.algo_class(cfg)
    try:
        first, best = None, float("inf")
        for _ in range(7):
            r = algo.train()
            ml = r["info"].get("model_loss")
            if ml is not None:
                if first is None:
                    first = ml
                best = min(best, ml)
        assert first is not None
        assert best < 0.6 * first, (first, best)
        assert math.isfinite(r["info"]["actor_loss"])
        assert math.isfinite(r["info"]["critic_loss"])
        ckpt = algo.save()
        algo.restore(ckpt)
    finally:
        algo.stop()


def test_mbmpo_ensemble_learns_dynamics():
    """MBMPO: the dynamics ensemble fits real transitions (loss drops
    steeply) and the vmapped MAML-over-models meta-step produces finite
    second-order updates (cf. reference rllib/algorithms/mbmpo)."""
    import math

    from ray_tpu.rl import MBMPOConfig, get_algorithm_class

    assert get_algorithm_class("MBMPO") is not None
    cfg = (MBMPOConfig().environment("Pendulum-v1")
           .training(hidden=(32, 32))
           .debugging(seed=0))
    cfg.ensemble_size = 3
    cfg.model_train_steps = 80
    cfg.meta_updates_per_iter = 3
    cfg.real_steps_per_iter = 400
    cfg.horizon = 10
    cfg.n_imagined = 8
    algo = cfg.algo_class(cfg)
    first = algo.train()["info"]
    # the model loss is stochastic iteration-to-iteration (fresh real
    # rollouts enter the buffer); assert on the BEST of a few iters like
    # the MBPO test above, not on one draw
    best = first["model_loss"]
    last = first
    for _ in range(4):
        last = algo.train()["info"]
        best = min(best, last["model_loss"])
        if best < first["model_loss"] * 0.7:
            break
    assert math.isfinite(last["meta_loss"])
    assert math.isfinite(last["imagined_return"])
    assert best < first["model_loss"] * 0.7, (first, best, last)
    algo.stop()


def test_alpha_star_league_beats_random():
    """AlphaStar league play: mains + exploiters train via PFSP matchups,
    snapshots populate the league and the payoff matrix, and the main
    agent's greedy policy improves against a uniform-random player
    (cf. reference rllib/algorithms/alpha_star)."""
    from ray_tpu.rl import AlphaStarConfig, get_algorithm_class

    assert get_algorithm_class("AlphaStar") is not None
    cfg = AlphaStarConfig().debugging(seed=0)
    cfg.games_per_iter = 96
    cfg.snapshot_interval = 4
    algo = cfg.algo_class(cfg)
    base = algo.eval_vs_random(n_games=200)
    for _ in range(8):
        res = algo.train()
    trained = algo.eval_vs_random(n_games=200)
    assert trained > base, (base, trained)
    assert trained >= 0.52, trained
    assert len(algo.league) >= 3           # snapshots were frozen
    assert any("exploiter" in a for a, _b in algo.payoff)  # PFSP ran
    info = res["info"]
    assert all(0.0 <= info[f"{n}_win_rate"] <= 1.0
               for n in algo.learners)
    # checkpoint round-trips the whole league
    ckpt = algo.save()
    algo2 = cfg.algo_class(cfg)
    algo2.restore(ckpt)
    assert len(algo2.league) == len(algo.league)
    assert algo2.eval_vs_random(n_games=100) >= 0.45
