"""AIR glue + Train library tests (cf. reference python/ray/train/tests &
air/tests — model: SURVEY.md §4 tier 2)."""

import numpy as np
import pytest

from ray_tpu.air import (Checkpoint, CheckpointConfig, FailureConfig,
                         RunConfig, ScalingConfig, session)


def test_checkpoint_dict_roundtrip():
    ckpt = Checkpoint.from_dict({"step": 3, "w": np.arange(4)})
    d = ckpt.to_dict()
    assert d["step"] == 3
    np.testing.assert_array_equal(d["w"], np.arange(4))
    blob = ckpt.to_bytes()
    d2 = Checkpoint.from_bytes(blob).to_dict()
    assert d2["step"] == 3


def test_checkpoint_directory_roundtrip(tmp_path):
    ckpt = Checkpoint.from_dict({"x": 1})
    out = ckpt.to_directory(str(tmp_path / "c1"))
    restored = Checkpoint.from_directory(out)
    assert restored.to_dict() == {"x": 1}


def test_checkpoint_jax_roundtrip():
    import jax.numpy as jnp
    state = {"params": {"w": jnp.ones((4, 4)), "b": jnp.zeros(4)},
             "step": jnp.asarray(7)}
    ckpt = Checkpoint.from_jax(state, metrics={"loss": 0.5})
    restored = ckpt.to_jax()
    leaves = sorted(str(k) for k in restored)
    assert leaves
    flat = restored["params"] if "params" in restored else restored
    assert np.asarray(flat["w"]).shape == (4, 4)
    assert ckpt.metrics()["loss"] == 0.5


def test_checkpoint_jax_sharded_restore():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec
    from jax.experimental import mesh_utils

    mesh = Mesh(mesh_utils.create_device_mesh((8,)), ("data",))
    sh = NamedSharding(mesh, PartitionSpec("data"))
    x = jax.device_put(jnp.arange(16.0), sh)
    ckpt = Checkpoint.from_jax({"x": x})
    restored = ckpt.to_jax(shardings={"x": sh})
    rx = restored["x"]
    np.testing.assert_allclose(np.asarray(rx), np.arange(16.0))
    assert rx.sharding.is_equivalent_to(sh, rx.ndim)


def test_scaling_config_resources():
    sc = ScalingConfig(num_workers=2, use_tpu=True, devices_per_worker=4)
    res = sc.worker_resources()
    assert res["TPU"] == 4.0 and res["CPU"] == 1.0
    assert len(sc.as_placement_group_bundles()) == 2


def test_session_report_and_poll():
    s = session.init_session(world_rank=0, world_size=2)
    try:
        import threading
        def loop():
            session.report({"loss": 1.0})
            session.report({"loss": 0.5})
        t = threading.Thread(target=loop)
        t.start()
        m1, _ = s.next_result(timeout=5)
        m2, _ = s.next_result(timeout=5)
        t.join(5)
        assert m1["loss"] == 1.0 and m2["loss"] == 0.5
        assert m2["training_iteration"] == 2
        assert session.get_world_size() == 2
    finally:
        session.shutdown_session()


def test_jax_trainer_single_worker_mesh(ray_start_regular):
    """End-to-end: JaxTrainer runs a pjit step over a 2x4 mesh (8 virtual
    devices), reports metrics + a checkpoint, fit() returns them."""
    from ray_tpu.train import JaxTrainer, get_mesh

    def loop(config):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = get_mesh()
        assert dict(mesh.shape) == {"data": 2, "fsdp": 4}
        w = jnp.ones((8, 8))
        x = jax.device_put(
            jnp.ones((8, 8)),
            NamedSharding(mesh, PartitionSpec(("data", "fsdp"), None)))

        @jax.jit
        def step(w, x):
            return (x @ w).mean()

        for i in range(config["steps"]):
            val = float(step(w, x))
            session.report({"loss": val},
                           checkpoint=Checkpoint.from_dict({"i": i}))

    trainer = JaxTrainer(
        loop, train_loop_config={"steps": 3},
        scaling_config=ScalingConfig(num_workers=1,
                                     mesh_shape={"data": 2, "fsdp": 4}),
        run_config=RunConfig(
            checkpoint_config=CheckpointConfig(num_to_keep=2)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["loss"] == 8.0
    assert result.metrics["training_iteration"] == 3
    assert result.checkpoint is not None
    assert result.checkpoint.to_dict()["i"] == 2
    assert len(result.best_checkpoints) == 2


def test_trainer_failure_propagates(ray_start_regular):
    from ray_tpu.train import JaxTrainer, TrainingFailedError

    def bad_loop(config):
        raise ValueError("boom in train loop")

    trainer = JaxTrainer(bad_loop,
                         scaling_config=ScalingConfig(num_workers=1))
    result = trainer.fit()
    assert result.error is not None
    assert "boom in train loop" in str(result.error)


def test_trainer_stop_criterion(ray_start_regular):
    from ray_tpu.train import JaxTrainer

    def loop(config):
        for i in range(100):
            session.report({"score": i})

    trainer = JaxTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(stop={"score": 5}))
    result = trainer.fit()
    assert result.metrics["score"] == 5


def test_multi_process_jax_distributed_mesh(ray_start_regular):
    """THE multi-host bootstrap path, executed for real: two separate
    worker PROCESSES call jax.distributed.initialize through JaxConfig
    (worker_group.setup_jax_distributed), form one global CPU mesh from
    their local devices, and run a pjit step whose gradient reduction
    crosses the process boundary (gloo collectives — the CPU stand-in
    for ICI/DCN).  Reference analog: torch TCP rendezvous
    (python/ray/train/torch/config.py:29)."""
    from ray_tpu.train import JaxConfig, JaxTrainer

    def loop(config):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ray_tpu.train import get_mesh

        assert jax.process_count() == 2
        n = jax.device_count()
        assert n == 2 * jax.local_device_count() and n >= 4
        mesh = get_mesh({"data": -1})
        sh = NamedSharding(mesh, P("data"))
        full = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
        x = jax.make_array_from_callback((n, 4), sh,
                                         lambda idx: full[idx])
        w = jnp.ones((4,), jnp.float32)

        @jax.jit
        def step(w, x):
            def loss_fn(w):
                # mean over the GLOBAL batch: the grad all-reduce must
                # cross the process boundary
                return jnp.mean((x @ w) ** 2)
            loss, g = jax.value_and_grad(loss_fn)(w)
            return loss, w - 0.1 * g

        loss, w2 = step(w, x)
        expect = float(np.mean((full @ np.ones(4)) ** 2))
        session.report({
            "loss": float(loss),
            "expect": expect,
            "w0": float(w2[0]),
            "devices": n,
            "procs": jax.process_count(),
        })

    trainer = JaxTrainer(
        loop, jax_config=JaxConfig(platform="cpu"),
        scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is None, result.error
    m = result.metrics
    assert m["procs"] == 2 and m["devices"] >= 4
    # the global-mean loss matches the host-side computation exactly:
    # every shard (both processes) contributed to the reduction
    assert abs(m["loss"] - m["expect"]) / m["expect"] < 1e-5


def test_multi_worker_group(ray_start_regular):
    """Two worker actors, no jax.distributed (each its own runtime) — the
    group mechanics: rank-0 metrics stream, both loops complete."""
    from ray_tpu.train import JaxConfig, JaxTrainer

    def loop(config):
        session.report({"rank": session.get_world_rank(),
                        "ws": session.get_world_size()})

    trainer = JaxTrainer(
        loop, jax_config=JaxConfig(init_distributed=False),
        scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["rank"] == 0
    assert result.metrics["ws"] == 2


def test_huggingface_trainer(ray_start_regular, tmp_path):
    """HuggingFaceTrainer runs a real transformers.Trainer in a Train
    worker, forwarding its logs as session reports (cf. reference
    train/huggingface/huggingface_trainer.py)."""
    from ray_tpu.air import RunConfig, ScalingConfig
    from ray_tpu.train import HuggingFaceTrainer

    def trainer_init(train_ds, eval_ds, **config):
        import torch
        import transformers

        class Tiny(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.lin = torch.nn.Linear(4, 2)

            def forward(self, x=None, labels=None, **kw):
                logits = self.lin(x)
                loss = torch.nn.functional.cross_entropy(logits, labels)
                return {"loss": loss, "logits": logits}

        class Ds(torch.utils.data.Dataset):
            def __len__(self):
                return 32

            def __getitem__(self, i):
                g = torch.Generator().manual_seed(i)
                x = torch.randn(4, generator=g)
                return {"x": x, "labels": int(x.sum() > 0)}

        args = transformers.TrainingArguments(
            output_dir=config["out"], num_train_epochs=2,
            per_device_train_batch_size=8, logging_steps=2,
            save_strategy="no", report_to=[], disable_tqdm=True,
            use_cpu=True)
        return transformers.Trainer(model=Tiny(), args=args,
                                    train_dataset=Ds())

    trainer = HuggingFaceTrainer(
        trainer_init,
        trainer_init_config={"out": str(tmp_path / "hf")},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="hfexp", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics.get("done") is True
    assert "train_loss" in result.metrics or "loss" in result.metrics
