"""Slice-preemption chaos gate (docs/fault_tolerance.md).

The survival story ISSUE 15 composes out of the existing planes, asserted
end to end on a multi-node simulated cluster:

* **graceful drain** — `drain_node` emits NODE_PREEMPTING with a grace
  deadline, the raylet stops granting leases and evacuates every primary
  object copy to survivors over the transfer plane; after the node is
  SIGKILLed, owners recover every object WITHOUT re-executing lineage
  (zero lost objects, verified by execution counters and the striped-pull
  telemetry of the recovery gets);
* **gang recovery** — a 2-"slice" training run survives a mid-run slice
  kill, graceful and ungraceful: the trainer detects rank death (event
  plane or poll failure), re-forms the gang on replacement capacity and
  resumes from the latest checkpoint — lost work <= one checkpoint
  interval, time-to-failover asserted from the recovery-SLO auditor's
  failover episode (NODE_PREEMPTING/NODE_DEAD -> TRAIN_GANG_RECOVERY),
  cross-checked against the raw event timestamps it folded;
* **lineage hardening** — cascading loss (an object whose args also
  died) reconstructs transitively; exhausted lineage raises
  ObjectLostError naming the dead node's dossier; the per-object
  reconstruction budget converges a flapping cluster to a clean error.

Like the chaos suite, the whole module runs under BOTH runtime
sanitizers (docs/static_analysis.md) via the shared conftest fixture:
the drain/evacuation/recovery paths are the newest wide-concurrency
surface in the tree.
"""

import time

import numpy as np

import pytest

from conftest import debug_sanitizers_enabled

import ray_tpu


@pytest.fixture(scope="module", autouse=True)
def _debug_sanitizers():
    with debug_sanitizers_enabled():
        yield


def _wait_event(gcs, etype, timeout=60.0, **match):
    """Newest event of ``etype`` whose fields contain ``match``."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        evs = gcs.call("list_cluster_events", {"type": etype})
        for ev in reversed(evs or []):
            if all(ev.get(k) == v for k, v in match.items()):
                return ev
        time.sleep(0.3)
    return None


def _driver_gcs():
    from ray_tpu.runtime.core_worker import get_global_worker
    return get_global_worker().gcs


def _wait_episode(gcs, kind, timeout=60.0, **match):
    """Newest CLOSED recovery episode of ``kind`` whose fields contain
    ``match`` — the auditor's derived view of the same chaos the raw
    event asserts above it already pinned down."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        eps = gcs.call("list_recovery_episodes",
                       {"kind": kind, "include_open": False})
        for ep in reversed(eps or []):
            if all(ep.get(k) == v for k, v in match.items()):
                return ep
        time.sleep(0.3)
    return None


# --------------------------------------------------------------- drain
def test_graceful_drain_evacuates_objects(ray_start_cluster):
    """The tentpole's zero-loss leg: produce primary copies on a node,
    drain it (CLI path), SIGKILL it, and get() every object back with
    the producers having executed exactly once — the copies moved to
    survivors during the grace window and the recovery gets pulled them
    through the striped-pull engine (multi-source registration), not
    through lineage re-execution."""
    cluster = ray_start_cluster
    victim = cluster.add_node(resources={"CPU": 2, "prod": 4})
    # a non-head survivor: evacuation round-robins over BOTH survivors,
    # so part of the recovery set must come back over the wire (head-
    # landed copies are local-shm hits for the driver)
    cluster.add_node(resources={"CPU": 2})
    cluster.wait_for_nodes(3)
    # small chunks: the recovery pulls are multi-chunk, so the pull
    # engine records its striping fan-out (ray_tpu_pull_sources)
    ray_tpu.init(num_cpus=1, address=cluster.address,
                 system_config={"object_transfer_chunk_bytes": 128 * 1024})

    @ray_tpu.remote(resources={"prod": 1}, num_cpus=1, max_retries=4)
    def produce(i):
        import os
        from ray_tpu.runtime.core_worker import get_global_worker
        w = get_global_worker()
        w.gcs.kv_put(f"exec/{i}/{os.getpid()}_{time.time_ns()}", b"1")
        return np.full(100_000, i, dtype=np.float64)  # ~800 KiB

    n = 4
    refs = [produce.remote(i) for i in range(n)]
    ready, _ = ray_tpu.wait(refs, num_returns=n, timeout=120)
    assert len(ready) == n
    gcs = _driver_gcs()
    execs_before = len(gcs.kv_keys("exec/"))
    assert execs_before == n

    # drain through the CLI surface (`ray-tpu drain <prefix>`)
    from ray_tpu.scripts.scripts import build_parser
    args = build_parser().parse_args(
        ["drain", victim.node_id[:12], "--grace", "5",
         "--reason", "chaos-gate"])
    args.fn(args)

    pre = _wait_event(gcs, "NODE_PREEMPTING", node_id=victim.node_id)
    assert pre is not None and pre["grace_s"] == 5.0
    drained = _wait_event(gcs, "NODE_DRAINED", timeout=90,
                          node_id=victim.node_id)
    assert drained is not None, "drain never completed"
    assert drained["evacuated"] == n and drained["failed"] == 0
    assert drained["bytes"] >= n * 100_000 * 8
    # exactly one canonical NODE_PREEMPTING in the table per drain
    pres = gcs.call("list_cluster_events", {"type": "NODE_PREEMPTING"})
    assert len([e for e in pres
                if e.get("node_id") == victim.node_id]) == 1
    # per-object evacuation breadcrumbs name their landing node (they
    # ride the raylet recorder's flusher — poll past its 500 ms cadence)
    deadline = time.monotonic() + 60
    evacs = []
    while time.monotonic() < deadline:
        evacs = gcs.call("list_cluster_events",
                         {"type": "OBJECT_EVACUATED", "severity": "DEBUG"})
        if len(evacs) >= n:
            break
        time.sleep(0.3)
    survivors = {n["node_id"] for n in gcs.call("list_nodes")
                 if n["node_id"] != victim.node_id}
    assert len(evacs) == n
    assert all(e["target_node_id"] in survivors for e in evacs)

    # the recovery-SLO auditor folded that event stream into ONE drain
    # episode whose numbers match the event-timestamp ground truth —
    # drain latency, evacuation ledger and the grace-window SLO verdict
    ep = _wait_episode(gcs, "drain", node_id=victim.node_id)
    assert ep is not None, "auditor never closed the drain episode"
    assert ep["opening_type"] == "NODE_PREEMPTING"
    assert ep["closing_type"] == "NODE_DRAINED"
    assert abs(ep["latency_s"] - (drained["ts"] - pre["ts"])) < 0.05
    assert ep["evacuated"] == n and ep["failed"] == 0
    assert ep["evacuated_bytes"] == drained["bytes"]
    # no explicit drain SLO configured: the advertised 5 s grace window
    # IS the budget, and the drain finished inside it
    assert ep["slo_s"] == 5.0
    assert ep["violation"] == (ep["latency_s"] > 5.0)
    from conftest import record_recovery_row
    record_recovery_row({
        "name": "drain", "latency_s": ep["latency_s"],
        "evacuated": ep["evacuated"],
        "evacuated_bytes": ep["evacuated_bytes"],
        "slo_s": ep["slo_s"], "violation": ep["violation"],
        "reference": "tests/test_preemption.py::"
                     "test_graceful_drain_evacuates_objects"})

    # the preemption lands: SIGKILL, no cleanup
    cluster.remove_node(victim)

    from ray_tpu._private import runtime_metrics as rtm

    def _hist_count(name):
        rec = rtm.snapshot().get(name)
        if not rec:
            return 0
        return sum(v["count"] for v in rec["values"].values())

    pulls_before = _hist_count("ray_tpu_pull_sources")
    values = ray_tpu.get(refs, timeout=180)
    for i, v in enumerate(values):
        assert v.shape == (100_000,) and float(v[0]) == float(i)
    # zero lost objects: the producers never re-executed
    assert len(gcs.kv_keys("exec/")) == execs_before
    # and the wire-recovered share came through the striped-pull engine
    # off the evacuated copies (head-landed copies are local-shm hits —
    # round-robin put half the set on the non-head survivor)
    assert _hist_count("ray_tpu_pull_sources") >= pulls_before + 1
    ray_tpu.shutdown()


def test_draining_node_refuses_new_leases(ray_start_cluster):
    """Lease-side drain semantics: after drain_node, tasks that could
    only run on the draining node fail over (redirect or queue
    elsewhere) instead of landing new work on a doomed raylet."""
    from ray_tpu._private import rpc

    cluster = ray_start_cluster
    victim = cluster.add_node(resources={"CPU": 2, "pin": 1})
    cluster.wait_for_nodes(2)
    ray_tpu.init(num_cpus=1, address=cluster.address)
    gcs = _driver_gcs()

    conn = rpc.connect(victim.address)
    try:
        # sanity: the raylet grants leases while healthy
        grant = conn.call("lease_worker",
                          {"resources": {"CPU": 1}, "key": "pre-drain"},
                          timeout=120)
        assert "lease_id" in grant
        conn.call("return_worker", {"lease_id": grant["lease_id"],
                                    "worker_id": grant["worker_id"],
                                    "key": "pre-drain"})

        assert gcs.call("drain_node", {"node_id": victim.node_id,
                                       "grace_s": 60.0,
                                       "reason": "lease test"})["ok"]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            nodes = {n["node_id"]: n for n in gcs.call("list_nodes")}
            if nodes[victim.node_id].get("draining"):
                break
            time.sleep(0.2)
        assert nodes[victim.node_id].get("draining")

        # a generic lease is redirected to the surviving head node...
        r = conn.call("lease_worker",
                      {"resources": {"CPU": 1}, "key": "post-drain"},
                      timeout=60)
        assert tuple(r.get("retry_at", ())) == cluster.head_node.address
        # ...a lease nothing else can serve is refused cleanly, not
        # granted onto the doomed node...
        with pytest.raises(rpc.RemoteError, match="draining"):
            conn.call("lease_worker",
                      {"resources": {"pin": 1}, "key": "pinned"},
                      timeout=60)
        # ...and a BUNDLE lease gets the clean error too, never a
        # retry_at (the placement-group client path treats the reply as
        # a final grant and cannot follow redirects)
        assert conn.call("reserve_bundle",
                         {"pg_id": "ab" * 8, "index": 0,
                          "resources": {"CPU": 1}})["ok"]
        with pytest.raises(rpc.RemoteError, match="draining"):
            conn.call("lease_worker",
                      {"resources": {"CPU": 1}, "key": "bk",
                       "bundle": ["ab" * 8, 0]}, timeout=60)
    finally:
        conn.close()
    ray_tpu.shutdown()


# ------------------------------------------------------- gang recovery
def _make_gang_loop():
    """Per-rank loop: N short steps, checkpoint every K; every executed
    step leaves one KV breadcrumb so the driver can count re-executed
    (lost) work exactly.  Built as a closure so cloudpickle ships it by
    value (a tests-module function would pickle by reference, which
    workers cannot import)."""

    def gang_loop(config):
        import os
        import time as _t
        from ray_tpu.air import session
        from ray_tpu.air.checkpoint import Checkpoint
        from ray_tpu.runtime.core_worker import get_global_worker
        gcs = get_global_worker().gcs
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["step"] + 1 if ckpt is not None else 0
        rank = session.get_world_rank()
        n, k = config["steps"], config["ckpt_interval"]
        for step in range(start, n):
            _t.sleep(config["step_s"])
            gcs.kv_put(f"gang-steps/{rank}/{step}/{os.getpid()}", b"1")
            session.report(
                {"step": step},
                checkpoint=Checkpoint.from_dict({"step": step})
                if (step + 1) % k == 0 else None)

    return gang_loop


def _run_gang_with_kill(cluster, graceful: bool):
    """Shared driver for the two slice-kill legs: 2 ranks on 2 "slice"
    nodes, kill one mid-run, assert recovery + bounded lost work +
    event-plane forensics."""
    import threading

    from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train.base_trainer import DataParallelTrainer

    victim = cluster.add_node(resources={"CPU": 2, "slice": 2})
    cluster.add_node(resources={"CPU": 2, "slice": 2})
    cluster.wait_for_nodes(3)
    ray_tpu.init(num_cpus=0, address=cluster.address)
    gcs = _driver_gcs()

    steps, interval = 12, 3
    name = "gate-graceful" if graceful else "gate-ungraceful"
    trainer = DataParallelTrainer(
        _make_gang_loop(),
        train_loop_config={"steps": steps, "ckpt_interval": interval,
                           "step_s": 0.4},
        scaling_config=ScalingConfig(
            num_workers=2, resources_per_worker={"CPU": 1, "slice": 1}),
        run_config=RunConfig(name=name,
                             failure_config=FailureConfig(max_failures=3)))

    def _preempt():
        time.sleep(5.0)
        if graceful:
            # preemption NOTICE: drain first, kill at the deadline
            gcs.call("drain_node", {"node_id": victim.node_id,
                                    "grace_s": 4.0,
                                    "reason": "spot preemption"})
            time.sleep(4.0)
        cluster.remove_node(victim)   # SIGKILL
        # replacement slice joins (the autoscaler path is exercised in
        # test_autoscaler; here capacity arrives like a fresh provider
        # launch)
        cluster.add_node(resources={"CPU": 2, "slice": 2})

    killer = threading.Thread(target=_preempt, daemon=True)
    killer.start()
    result = trainer.fit()
    killer.join(timeout=30)
    assert result.error is None, f"training did not recover: {result.error}"
    assert result.metrics.get("step") == steps - 1

    # every step completed on both ranks, and lost (re-executed) work is
    # bounded by one checkpoint interval per rank
    for rank in range(2):
        executed = {}
        for key in gcs.kv_keys(f"gang-steps/{rank}/"):
            step = int(key.split("/")[2])
            executed[step] = executed.get(step, 0) + 1
        assert set(executed) == set(range(steps)), \
            f"rank {rank} missed steps: {sorted(set(range(steps)) - set(executed))}"
        re_executed = sum(c - 1 for c in executed.values())
        assert re_executed <= interval, \
            f"rank {rank} lost {re_executed} steps > interval {interval}"

    # event-plane forensics: the death/preemption event and the recovery
    # event exist, and time-to-failover is sane
    first_type = "NODE_PREEMPTING" if graceful else "NODE_DEAD"
    fail_ev = _wait_event(gcs, first_type, timeout=60,
                          node_id=victim.node_id)
    assert fail_ev is not None
    rec_ev = _wait_event(gcs, "TRAIN_GANG_RECOVERY", timeout=60,
                         experiment=name)
    assert rec_ev is not None
    ttf = rec_ev["ts"] - fail_ev["ts"]
    assert 0 <= ttf < 120, f"time-to-failover {ttf:.1f}s out of bounds"
    if graceful:
        # the event watch failed over proactively off the preemption
        # notice: recovery references the event plane, not a poll error
        assert "event plane" in rec_ev.get("reason", "") or ttf < 60

    # the auditor's failover episode derived the same story: anchored
    # at the FIRST failure event (the preemption NOTICE on the graceful
    # leg, the death on the ungraceful one), closed by the gang
    # recovery, time-to-failover matching the hand-subtracted event
    # timestamps and lost work counted in re-executed steps
    ep = _wait_episode(gcs, "failover", experiment=name)
    assert ep is not None, "auditor never closed the failover episode"
    assert ep["opening_type"] == first_type
    assert ep["node_id"] == victim.node_id
    assert ep["closing_type"] == "TRAIN_GANG_RECOVERY"
    assert abs(ep["latency_s"] - ttf) < 0.05, (ep["latency_s"], ttf)
    assert ep["lost_steps"] == int(rec_ev.get("lost_steps") or 0)
    assert 0 <= ep["lost_steps"] <= interval
    # default failover SLO is 120 s; the ttf bound above means no breach
    assert ep["slo_s"] == 120.0 and not ep["violation"]

    # `ray-tpu doctor` names the episode: the closed-episodes finding
    # cites the slowest recovery, which is this failover
    from ray_tpu._private.metrics_history import format_doctor_report
    report = gcs.call("doctor_report", {})
    text = format_doctor_report(report)
    assert ep["id"] in text, text
    assert any(f["category"] == "recovery"
               for f in report["findings"])

    from conftest import record_recovery_row
    record_recovery_row({
        "name": f"failover_{'graceful' if graceful else 'ungraceful'}",
        "time_to_failover_s": ep["latency_s"],
        "lost_steps": ep["lost_steps"], "opened_by": ep["opening_type"],
        "slo_s": ep["slo_s"], "violation": ep["violation"],
        "reference": "tests/test_preemption.py::_run_gang_with_kill"})

    if graceful:
        # one-shot forensics: `ray-tpu debug-bundle` exports every
        # plane of THIS incident as one tarball — the events, the
        # failover episode, the doctor verdict naming it and a
        # non-empty metrics-history window, all correlated
        _assert_debug_bundle(gcs, ep)
    ray_tpu.shutdown()
    return fail_ev, rec_ev


def _assert_debug_bundle(gcs, ep):
    import json
    import os
    import tarfile
    import tempfile

    from ray_tpu.experimental import state

    # the history plane fills from the periodic runtime-metrics flush;
    # wait until at least one series landed so the bundle's window has
    # real points in it
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if gcs.call("metrics_history_stats", {}).get("series", 0) > 0:
            break
        time.sleep(0.5)

    path = os.path.join(tempfile.mkdtemp(), "bundle.tar.gz")
    try:
        manifest = state.collect_debug_bundle(path)
        with tarfile.open(path) as tar:
            names = tar.getnames()
            members = {}
            for want in ("events.json", "recovery_episodes.json",
                         "metrics_history.json",
                         "metrics_history_stats.json", "dossiers.json",
                         "doctor.json", "doctor.txt"):
                assert f"debug-bundle/{want}" in names, names
                blob = tar.extractfile(f"debug-bundle/{want}").read()
                members[want] = (blob.decode() if want.endswith(".txt")
                                 else json.loads(blob))
        assert set(manifest["members"]) == {
            n[len("debug-bundle/"):] for n in names}
        # correlated content, not just file presence: the bundle's
        # planes all tell this incident's story
        assert any(e.get("type") == "TRAIN_GANG_RECOVERY"
                   for e in members["events.json"])
        assert any(b.get("id") == ep["id"]
                   for b in members["recovery_episodes.json"])
        assert any(d.get("dossier_id") == ep["node_id"]
                   for d in members["dossiers.json"]
                   if isinstance(d, dict)), \
            "bundle carries no dossier for the dead node"
        assert ep["id"] in members["doctor.txt"]
        assert members["metrics_history_stats.json"]["series"] > 0
        assert len(members["metrics_history.json"]) > 0
    finally:
        try:
            os.remove(path)
            os.rmdir(os.path.dirname(path))
        except OSError:
            pass


def test_training_survives_graceful_slice_preemption(ray_start_cluster):
    """A 2-slice training run rides out a drained-then-killed slice:
    the gang watch picks the NODE_PREEMPTING event up DURING the grace
    window, the gang re-forms on the replacement slice and resumes from
    the latest checkpoint."""
    _run_gang_with_kill(ray_start_cluster, graceful=True)


def test_training_survives_ungraceful_slice_kill(ray_start_cluster):
    """Same run, no notice: the slice is SIGKILLed mid-step.  Recovery
    rides checkpoint + actor-death detection; lost work stays bounded
    by the checkpoint interval."""
    _run_gang_with_kill(ray_start_cluster, graceful=False)


# --------------------------------------------------- lineage hardening
def test_cascading_loss_reconstructs_transitively(ray_start_cluster):
    """g(f()) where BOTH outputs lived only on the dead node: recovering
    g must first reconstruct f (its lost argument) — the cascade the
    tentpole stresses."""
    cluster = ray_start_cluster
    victim = cluster.add_node(resources={"CPU": 2, "prod": 4})
    cluster.wait_for_nodes(2)
    ray_tpu.init(num_cpus=1, address=cluster.address,
                 system_config={"evacuation_enabled": False})

    @ray_tpu.remote(resources={"prod": 1}, num_cpus=1, max_retries=4)
    def f(i):
        return np.full(50_000, i, dtype=np.float64)

    @ray_tpu.remote(resources={"prod": 1}, num_cpus=1, max_retries=4)
    def g(x):
        return x * 2

    gr = g.remote(f.remote(21))
    ray_tpu.wait([gr], timeout=120)
    cluster.remove_node(victim)
    cluster.add_node(resources={"CPU": 2, "prod": 4})
    v = ray_tpu.get(gr, timeout=180)
    assert float(v[0]) == 42.0
    ray_tpu.shutdown()


def test_exhausted_lineage_names_node_dossier(ray_start_cluster):
    """max_retries=0: when the only copy dies, ObjectLostError carries
    the dead node's dossier id and debug_dossier() resolves it."""
    cluster = ray_start_cluster
    victim = cluster.add_node(resources={"CPU": 2, "prod": 2})
    cluster.wait_for_nodes(2)
    ray_tpu.init(num_cpus=1, address=cluster.address,
                 system_config={"evacuation_enabled": False})

    @ray_tpu.remote(resources={"prod": 1}, num_cpus=1, max_retries=0)
    def h():
        return np.ones(50_000)

    ref = h.remote()
    ray_tpu.wait([ref], timeout=120)
    cluster.remove_node(victim)
    with pytest.raises(ray_tpu.exceptions.ObjectLostError) as ei:
        ray_tpu.get(ref, timeout=180)
    err = ei.value
    assert err.dossier_id == victim.node_id
    # the GCS assembled a node dossier at death: the error resolves it
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        text = err.debug_dossier()
        if text.startswith("==="):
            break
        time.sleep(0.5)
    assert victim.node_id[:12] in text
    ray_tpu.shutdown()


def test_reconstruction_budget_bounds_resubmits(ray_start_cluster):
    """object_reconstruct_max_attempts=0 turns reconstruction off even
    with task retries left: a flapping node can never drive unbounded
    resubmit loops — the budget converges to ObjectLostError."""
    cluster = ray_start_cluster
    victim = cluster.add_node(resources={"CPU": 2, "prod": 2})
    cluster.wait_for_nodes(2)
    ray_tpu.init(num_cpus=1, address=cluster.address,
                 system_config={"evacuation_enabled": False,
                                "object_reconstruct_max_attempts": 0})

    @ray_tpu.remote(resources={"prod": 1}, num_cpus=1, max_retries=8)
    def h():
        return np.ones(50_000)

    ref = h.remote()
    ray_tpu.wait([ref], timeout=120)
    cluster.remove_node(victim)
    cluster.add_node(resources={"CPU": 2, "prod": 2})
    with pytest.raises(ray_tpu.exceptions.ObjectLostError):
        ray_tpu.get(ref, timeout=180)
    ray_tpu.shutdown()
