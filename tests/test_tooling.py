"""L5 tooling: dashboard HTTP API, job submission, CLI, log-to-driver,
usage stats (SURVEY.md §2.5)."""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def test_dashboard_endpoints(ray_start_regular):
    import ray_tpu
    from ray_tpu.dashboard import start_dashboard
    from ray_tpu.util.metrics import Counter

    host, port = ray_tpu.context()["gcs_address"].rsplit(":", 1)
    head = start_dashboard((host, int(port)), port=0)
    try:
        base = f"http://{head.host}:{head.port}"
        assert _get_json(base + "/api/version")["version"]
        nodes = _get_json(base + "/api/nodes")["nodes"]
        assert len(nodes) == 1 and nodes[0]["alive"]

        status = _get_json(base + "/api/cluster_status")
        assert status["alive_nodes"] == 1
        assert status["total_resources"]["CPU"] == 4

        # run a task so the task table has rows
        @ray_tpu.remote
        def noop():
            return 1
        ray_tpu.get(noop.remote())
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if _get_json(base + "/api/tasks?limit=10")["tasks"]:
                break
            time.sleep(0.3)
        assert _get_json(base + "/api/tasks")["tasks"]

        c = Counter("dash_test_counter", description="testing")
        c.inc(3)
        c.flush()
        time.sleep(0.2)
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "dash_test_counter" in text
        assert "ray_tpu_cluster_nodes 1" in text

        # "/" serves the live HTML UI to browsers, JSON to API clients
        with urllib.request.urlopen(base + "/", timeout=10) as r:
            html = r.read().decode()
        assert "<!doctype html>" in html.lower()
        assert "ray_tpu dashboard" in html
        # the page is live: it polls every view without reload and can
        # tail job logs (reference SPA pages list, dashboard/client/src)
        assert "setInterval(refresh" in html
        # Metrics charts + Timeline swimlanes (reference embeds Grafana
        # / chrome-trace externally; here they're in-page SVG).  The
        # timeline renderer consumes start_time/end_time/worker_id off
        # the task rows — pin that contract on real data.
        assert "renderMetrics" in html and "renderTimeline" in html
        assert "sampleMetrics" in html
        # start_time rides the executing worker's RUNNING event, which
        # flushes on its own clock: wait for a row that has it
        deadline = time.monotonic() + 10
        row = None
        while time.monotonic() < deadline:
            rows = _get_json(base + "/api/tasks?limit=50")["tasks"]
            row = next((t for t in rows if t.get("start_time")), None)
            if row is not None:
                break
            time.sleep(0.3)
        assert row is not None, "no task row gained start_time"
        for key in ("end_time", "worker_id", "state"):
            assert key in row, (key, sorted(row))
        for tab_name in ("Nodes", "Actors", "Tasks", "Jobs", "Serve"):
            assert f'"{tab_name}"' in html
        assert "tailJob" in html
        req = urllib.request.Request(base + "/",
                                     headers={"Accept": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            import json as _json
            assert "routes" in _json.loads(r.read())
    finally:
        head.stop()


def test_dashboard_job_log_tail(ray_start_regular):
    """The offset-based log endpoint returns only the delta, so the live
    page can stream a running job's logs."""
    import json as _json

    import ray_tpu
    from ray_tpu.dashboard import start_dashboard
    from ray_tpu.job_submission import JobSubmissionClient

    host, port = ray_tpu.context()["gcs_address"].rsplit(":", 1)
    head = start_dashboard((host, int(port)), port=0)
    try:
        base = f"http://{head.host}:{head.port}"
        client = JobSubmissionClient()
        sid = client.submit_job(
            entrypoint="python -c \"import time\n"
                       "for i in range(6):\n"
                       "    print('tick', i, flush=True)\n"
                       "    time.sleep(0.5)\"")
        deadline = time.monotonic() + 60
        got, offset = "", 0
        while time.monotonic() < deadline and "tick 5" not in got:
            with urllib.request.urlopen(
                    f"{base}/api/jobs/{sid}/logs?offset={offset}",
                    timeout=10) as r:
                d = _json.loads(r.read())
            assert offset == 0 or "tick 0" not in d["text"], \
                "offset fetch must return only the delta"
            got += d["text"]
            offset = d["offset"]
            time.sleep(0.4)
        assert all(f"tick {i}" in got for i in range(6)), got
        # plain fetch still returns the whole text
        with urllib.request.urlopen(f"{base}/api/jobs/{sid}/logs",
                                    timeout=10) as r:
            assert "tick 0" in r.read().decode()
    finally:
        head.stop()


def test_job_submission(ray_start_regular):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    sid = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('hello from job')\"")
    status = client.wait_until_finished(sid, timeout=120)
    assert status == JobStatus.SUCCEEDED
    assert "hello from job" in client.get_job_logs(sid)
    info = client.get_job_info(sid)
    assert info.driver_exit_code == 0

    jobs = client.list_jobs()
    assert any(j.submission_id == sid for j in jobs)


def test_job_submission_with_cluster_driver(ray_start_regular):
    """The submitted script connects back to this cluster and runs tasks."""
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    script = ("import ray_tpu; ray_tpu.init(); "
              "f = ray_tpu.remote(lambda: 40 + 2); "
              "print('answer =', ray_tpu.get(f.remote()))")
    client = JobSubmissionClient()
    sid = client.submit_job(
        entrypoint=f"{sys.executable} -c \"{script}\"")
    assert client.wait_until_finished(sid, timeout=180) == \
        JobStatus.SUCCEEDED
    assert "answer = 42" in client.get_job_logs(sid)


def test_job_stop(ray_start_regular):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    sid = client.submit_job(
        entrypoint=f"{sys.executable} -c \"import time; time.sleep(600)\"")
    deadline = time.monotonic() + 60
    while client.get_job_status(sid) == JobStatus.PENDING and \
            time.monotonic() < deadline:
        time.sleep(0.2)
    assert client.stop_job(sid)
    assert client.wait_until_finished(sid, timeout=60) == JobStatus.STOPPED


def test_log_to_driver(ray_start_regular, capfd):
    import ray_tpu

    @ray_tpu.remote
    def shout():
        print("LOUD-AND-CLEAR", flush=True)
        return 1

    assert ray_tpu.get(shout.remote()) == 1
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        out = capfd.readouterr().out
        if "LOUD-AND-CLEAR" in out:
            return
        time.sleep(0.5)
    raise AssertionError("worker stdout never reached the driver")


def test_usage_stats(tmp_path):
    from ray_tpu._private.usage.usage_lib import (record_usage_report,
                                                  usage_stats_enabled)

    assert usage_stats_enabled()
    path = record_usage_report(str(tmp_path))
    payload = json.loads(open(path).read())
    assert payload["source"] == "ray_tpu"
    assert payload["version"]
    os.environ["RAY_TPU_USAGE_STATS_ENABLED"] = "0"
    try:
        assert record_usage_report(str(tmp_path)) == ""
    finally:
        del os.environ["RAY_TPU_USAGE_STATS_ENABLED"]


@pytest.mark.slow
def test_cli_start_status_stop():
    """Full head lifecycle through the CLI (reference `ray start/stop`)."""
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    run = lambda *cmd, **kw: subprocess.run(  # noqa: E731
        [sys.executable, "-m", "ray_tpu.scripts", *cmd],
        env=env, capture_output=True, text=True, timeout=180, **kw)

    out = run("start", "--head", "--num-cpus", "2")
    assert out.returncode == 0, out.stderr
    assert "GCS listening" in out.stdout
    addr = [ln for ln in out.stdout.splitlines()
            if "ray_tpu.init" in ln][0].split('"')[1]
    try:
        st = run("status", "--address", addr)
        assert st.returncode == 0, st.stderr
        assert "Nodes: 1 alive" in st.stdout

        ls = run("list", "nodes", "--address", addr)
        assert ls.returncode == 0, ls.stderr
        assert ls.stdout.strip()
    finally:
        sp = run("stop")
        assert sp.returncode == 0, sp.stderr


def test_microbenchmark_suite_runs():
    """ray_perf analog reports the reference's metric names
    (BASELINE.md microbenchmark section)."""
    from ray_tpu._private.ray_perf import main
    results = main(min_time=0.05)
    names = {r["name"] for r in results}
    assert "single client get calls (Plasma Store)" in names
    assert "1:1 actor calls sync" in names
    assert "multi client tasks async" in names
    assert all(r["ops_per_s"] > 0 for r in results)


def test_cli_stack_dumps_all_processes(ray_start_regular):
    """`ray-tpu stack` signals every session process and prints their
    thread stacks (py-spy / `ray stack` analog)."""
    import subprocess
    import sys
    import time

    import ray_tpu

    @ray_tpu.remote
    def busy():
        time.sleep(15)

    ref = busy.remote()  # noqa: F841 - keep a worker running
    time.sleep(2)
    from ray_tpu.runtime.core_worker import get_global_worker
    sd = get_global_worker().session_dir
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.scripts", "stack",
         "--session-dir", sd],
        capture_output=True, text=True, timeout=120)
    assert "signalled" in out.stdout, out.stdout[:500] + out.stderr[:500]
    assert "Thread" in out.stdout  # faulthandler stack frames present
    assert "_recv_exact" in out.stdout or "threading.py" in out.stdout


def test_component_events_and_profiling(ray_start_regular):
    """Structured events flow to the GCS ring + dashboard endpoint, and
    every process kind answers on-demand flame sampling (reference
    event_logger.py + reporter_agent.py:253)."""
    import json as _json

    import ray_tpu
    from ray_tpu.dashboard import start_dashboard
    from ray_tpu.runtime.core_worker import get_global_worker

    worker = get_global_worker()
    gcs = worker.gcs
    gcs.call("report_event", {"severity": "WARNING", "source": "test",
                              "label": "UNIT", "message": "hello events",
                              "fields": {"k": 1}})
    events = gcs.call("list_events", {"limit": 10})
    assert any(e["label"] == "UNIT" and e["fields"]["k"] == 1
               for e in events)
    only_err = gcs.call("list_events", {"severity": "ERROR", "limit": 10})
    assert all(e["severity"] == "ERROR" for e in only_err)

    # profile the GCS process (folded keys are line-stable `name (file)`;
    # leaf line detail rides the reserved entry)
    from ray_tpu._private.profiler import split_leaf_detail
    counts = gcs.call("profile", {"duration": 0.3}, timeout=40)
    clean, _detail = split_leaf_detail(counts)
    assert clean and all(isinstance(v, int) for v in clean.values())

    # profile a worker through its raylet (spin one up with a task)
    @ray_tpu.remote
    def spin():
        import time
        t0 = time.monotonic()
        while time.monotonic() - t0 < 4:
            sum(range(1000))
        return 1

    ref = spin.remote()
    import time
    time.sleep(1.0)
    nodes = gcs.call("list_nodes")
    from ray_tpu._private import rpc
    conn = rpc.connect(tuple(nodes[0]["address"]), timeout=5.0)
    try:
        wcounts = conn.call("profile", {"duration": 0.5, "worker_id": ""},
                            timeout=40)  # the raylet itself
        assert wcounts
    finally:
        conn.close()
    assert ray_tpu.get(ref, timeout=60) == 1

    # the dashboard exposes both
    host, port = ray_tpu.context()["gcs_address"].rsplit(":", 1)
    head = start_dashboard((host, int(port)), port=0)
    try:
        base = f"http://{head.host}:{head.port}"
        with urllib.request.urlopen(base + "/api/events", timeout=10) as r:
            evs = _json.loads(r.read())["events"]
        # typed cluster-event rows (docs/observability.md): the legacy
        # report_event's label became the event type
        assert any(e["type"] == "UNIT" for e in evs)
        with urllib.request.urlopen(
                base + "/api/events?type=UNIT", timeout=10) as r:
            filtered = _json.loads(r.read())["events"]
        assert filtered and all(e["type"] == "UNIT" for e in filtered)
        with urllib.request.urlopen(
                base + "/api/profile?duration=0.3&format=top",
                timeout=60) as r:
            assert "samples" in r.read().decode()
    finally:
        head.stop()
