"""Training performance plane (_private/step_stats.py,
docs/observability.md): step clock + goodput ledger units, the GCS step
table's straggler detection and retention, profiler line-stable keys,
gang profile merging, the daemon-spawn connect retry, and the 2/4-rank
gang end-to-end paths (timeline slices, training_summary, chaos
straggler)."""

import json
import threading
import time

import pytest

from ray_tpu._private import step_stats as sst
from ray_tpu._private.config import CONFIG


def _wait_for(pred, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------- units
def test_step_clock_and_goodput_ledger():
    """Phases cut by the clock land in the step; out-of-step phases in
    the ledger; the summary's buckets + MFU arithmetic are exact."""
    run = sst.start_run("unit-run", group="g", rank=0, world=1,
                        flops_per_token=1000.0, peak_flops=1e6)
    assert run is not None
    clock = sst.step_clock()
    for _ in range(4):
        clock.begin()
        with clock.phase("data_wait"):
            time.sleep(0.001)
        with clock.phase("host_dispatch"):
            time.sleep(0.003)
        clock.end(tokens=50)
    # a checkpoint between steps counts in the ledger, not a step
    sst.record_phase("checkpoint", 25.0)
    summary = sst.end_run(run)
    assert summary["steps"] == 4 and summary["tokens"] == 200
    assert summary["phase_ms"]["checkpoint"] == 25.0
    assert summary["phase_ms"]["host_dispatch"] >= 4 * 3.0
    assert summary["productive_ms"] > 0
    assert 0.0 < summary["goodput"] <= 1.0
    # mfu = fpt * tokens / productive_s / peak, exactly
    expect = 1000.0 * 200 / (summary["productive_ms"] / 1e3) / 1e6
    assert summary["mfu"] == pytest.approx(expect, rel=1e-3)
    # ledger time buckets cover the wall clock (idle absorbs the rest)
    parts = (summary["init_ms"] + summary["compile_ms"]
             + summary["productive_ms"] + summary["idle_ms"])
    assert parts <= summary["wall_ms"] * 1.01 + 26.0


def test_kill_switch_hands_out_noop_clock(monkeypatch):
    monkeypatch.setenv("RAY_TPU_STEP_STATS", "0")
    assert not sst.enabled()
    assert sst.start_run("killed") is None
    clock = sst.step_clock()
    assert clock is sst.NOOP_CLOCK
    clock.begin()
    with clock.phase("host_dispatch"):
        pass
    assert clock.end() is None
    sst.record_phase("checkpoint", 1.0)   # cheap no-op, not a crash
    monkeypatch.delenv("RAY_TPU_STEP_STATS")
    assert sst.enabled()


def test_begin_auto_finalizes_open_step():
    """A loop that only calls begin() still records every step."""
    run = sst.start_run("unit-auto")
    clock = sst.step_clock()
    for _ in range(3):
        clock.begin()
        with clock.phase("host_dispatch"):
            pass
    summary = sst.end_run(run)   # end_run closes the last open step
    assert summary["steps"] == 3


def test_step_report_sink_batches_and_survives_outage():
    """Reports buffer off the step path and a sink failure re-queues
    bounded instead of dropping or growing without bound."""
    shipped = []
    fail = {"on": True}

    def sink(reports):
        if fail["on"]:
            raise ConnectionError("gcs away")
        shipped.extend(reports)

    run = sst.start_run("unit-sink", sink=sink, meta={"pid": 1})
    clock = sst.step_clock()
    for _ in range(5):
        clock.begin()
        clock.end()
    run.flush()         # sink down: re-queued
    assert not shipped
    fail["on"] = False
    summary = sst.end_run(run)   # close flushes + pushes the summary
    steps = [r for r in shipped if "step" in r]
    assert len(steps) == 5
    assert steps[0]["meta"]["pid"] == 1      # rank meta rides the first
    assert all("meta" not in r for r in steps[1:])
    assert any("summary" in r for r in shipped)
    assert summary["steps"] == 5


# ------------------------------------------------------- GCS step table
def _reports(run, step, ms_by_rank, world=None, phases=None):
    world = world or len(ms_by_rank)
    out = []
    for rank, ms in ms_by_rank.items():
        ph = dict(phases[rank]) if phases else {"host_dispatch": ms}
        out.append({"run": run, "group": "gg", "rank": rank,
                    "world": world, "step": step, "ts": time.time(),
                    "step_ms": ms, "phases": ph,
                    **({"meta": {"pid": rank}} if step == 0 else {})})
    return out


def test_straggler_detection_edge_triggers_and_names_phase():
    events = []
    tbl = sst.GcsStepStatsTable(
        emit=lambda sev, src, label, msg, **f:
        events.append((sev, label, f)))
    # step 0: healthy; steps 1-3: rank 2 +100ms in host_dispatch
    tbl.put(_reports("ru", 0, {0: 10.0, 1: 11.0, 2: 10.0, 3: 10.5}))
    for step in range(1, 4):
        tbl.put(_reports(
            "ru", step, {0: 10.0, 1: 11.0, 2: 110.0, 3: 10.5},
            phases={0: {"data_wait": 2.0, "host_dispatch": 8.0},
                    1: {"data_wait": 2.0, "host_dispatch": 9.0},
                    2: {"data_wait": 2.0, "host_dispatch": 108.0},
                    3: {"data_wait": 2.0, "host_dispatch": 8.5}}))
    strag = [e for e in events if e[1] == "TRAIN_STRAGGLER"]
    # edge-triggered: THREE straggling steps -> ONE event
    assert len(strag) == 1
    sev, _, fields = strag[0]
    assert sev == "WARNING"
    assert fields["rank"] == 2 and fields["run"] == "ru"
    assert fields["phase"] == "host_dispatch"
    assert fields["overshoot_ms"] > 50
    # recovery re-arms the trigger
    tbl.put(_reports("ru", 4, {0: 10.0, 1: 11.0, 2: 10.0, 3: 10.5}))
    tbl.put(_reports("ru", 5, {0: 10.0, 1: 11.0, 2: 120.0, 3: 10.5}))
    strag = [e for e in events if e[1] == "TRAIN_STRAGGLER"]
    assert len(strag) == 2
    # the run row names the live straggler set
    runs = tbl.list_runs()
    assert runs[0]["straggling"] == {2: True}
    assert runs[0]["skew"], "per-step skew must be recorded"


def test_two_rank_gang_records_skew_but_never_flags():
    events = []
    tbl = sst.GcsStepStatsTable(
        emit=lambda *a, **f: events.append(a))
    for step in range(3):
        tbl.put(_reports("r2", step, {0: 10.0, 1: 150.0}))
    assert not events, "2-rank gangs can't name a straggler"
    assert tbl.list_runs()[0]["skew"][0]["skew_ms"] >= 69.0


def test_step_table_retention_bounds():
    tbl = sst.GcsStepStatsTable(max_runs=3, max_steps=8)
    for r in range(6):
        for step in range(20):
            tbl.put(_reports(f"run{r}", step, {0: 1.0, 1: 1.0}))
    st = tbl.stats()
    assert st["runs"] <= 3
    assert st["steps_retained"] <= 3 * 8
    # oldest runs evicted first
    kept = {row["run"] for row in tbl.list_runs()}
    assert kept == {"run3", "run4", "run5"}
    # per-run steps keep the newest tail
    steps = tbl.steps("run5")
    assert len(steps) <= 8
    assert steps[-1]["step"] == 19
    # summaries survive and aggregate
    tbl.put([{"run": "run5", "rank": 0, "world": 2,
              "summary": {"goodput": 0.5, "mfu": 0.25, "tokens": 10,
                          "steps": 20, "tokens_per_s": 100.0}}])
    s = tbl.summary("run5")
    assert s["aggregate"]["mfu"] == 0.25


# ------------------------------------------------------- profiler plane
def test_profiler_keys_line_stable_with_leaf_detail():
    """Folded keys carry `co_name (file)` only — a hot line shifting by
    one line can't split counts across captures; the line numbers live
    in the reserved leaf-detail entry and the top_summary column."""
    from ray_tpu._private import profiler

    stop = threading.Event()

    def busy():
        while not stop.is_set():
            sum(range(500))

    t = threading.Thread(target=busy, daemon=True)
    t.start()
    try:
        counts = profiler.sample_folded(0.3, interval_s=0.005)
    finally:
        stop.set()
        t.join()
    clean, detail = profiler.split_leaf_detail(counts)
    assert clean, "sampler saw no stacks"
    for key in clean:
        for frame in key.split(";"):
            assert frame.endswith(")") and ":" not in \
                frame[frame.rfind("("):], f"line number leaked: {frame}"
    busy_leaves = [k.rsplit(";", 1)[-1] for k in clean
                   if "busy" in k]
    assert busy_leaves
    lines = detail.get(busy_leaves[0])
    assert lines and any(":" in ln for ln in lines), \
        "leaf line detail missing"
    top = profiler.top_summary(counts)
    assert "[" in top and ":" in top, "top_summary lost the line column"
    # folded_text never renders the reserved entry
    assert profiler.LEAF_LINES_KEY not in profiler.folded_text(counts)


def test_merged_profile_trace_keys_ranks_and_correlates_steps():
    from ray_tpu._private.profiler import LEAF_LINES_KEY

    per_rank = {
        0: {"main (a.py);hot (b.py)": 10,
            LEAF_LINES_KEY: {"hot (b.py)": {"b.py:7": 10}}},
        1: {"main (a.py);cold (c.py)": 4},
    }
    t0 = 1000.0
    task_rows = [{"task_id": "step-runx-r1", "events": [
        {"state": "STEP", "ts": t0 + 0.5, "dur_ms": 100.0, "step": 3,
         "trace_id": "step-runx:3", "phases": {"host_dispatch": 90.0}},
        {"state": "RUNNING", "ts": t0},   # non-STEP events are ignored
    ]}]
    steps = sst.step_trace_events(task_rows, window=(t0, t0 + 10))
    assert len(steps) == 1 and steps[0]["pid"] == "rank 1"
    assert steps[0]["args"]["trace_id"] == "step-runx:3"
    trace = sst.merged_profile_trace(per_rank, interval_s=0.01,
                                     t_start=t0, step_events=steps)
    pids = {ev["pid"] for ev in trace}
    assert pids == {"rank 0", "rank 1"}
    hot = next(ev for ev in trace if ev["name"] == "hot (b.py)")
    assert hot["dur"] == pytest.approx(10 * 0.01 * 1e6)
    assert hot["args"]["top_line"] == "b.py:7"
    assert hot["ts"] >= t0 * 1e6


# ------------------------------------------------- daemon connect retry
def test_gcs_client_retries_initial_connect():
    """The startup-race deflake: a client (raylet at spawn) created
    BEFORE the GCS accepts connections retries with backoff inside
    daemon_connect_retry_s instead of dying on the first refusal."""
    import socket
    from ray_tpu.runtime.gcs import GcsClient, GcsServer

    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    holder = {}

    def later():
        time.sleep(0.7)
        holder["server"] = GcsServer("127.0.0.1", port)

    t = threading.Thread(target=later, daemon=True)
    t.start()
    client = GcsClient(("127.0.0.1", port), connect_retry=True)
    try:
        assert client.call("list_nodes", timeout=10) == []
    finally:
        client.close()
        t.join(timeout=10)
        if "server" in holder:
            holder["server"].stop()
    # interactive clients keep fail-fast semantics: no retry by default
    # (fresh port: the stopped server's listener may linger on the old)
    s2 = socket.socket()
    s2.bind(("127.0.0.1", 0))
    dead_port = s2.getsockname()[1]
    s2.close()
    t0 = time.monotonic()
    with pytest.raises((ConnectionError, OSError)):
        GcsClient(("127.0.0.1", dead_port))
    assert time.monotonic() - t0 < 5.0, "default client must not retry"


# ------------------------------------------------------------ end to end
def test_gang_training_produces_slices_summary_and_matching_mfu(
        ray_start_regular):
    """THE acceptance path: a 2-rank gang drives the step clock; the
    run lands per-step phase slices in the timeline, a
    training_summary() whose MFU matches the loop's own bench-style
    computation within 2%, and a step-table row carrying rank RPC
    metadata for gang profiling."""
    from ray_tpu.air import RunConfig, ScalingConfig, session
    from ray_tpu.experimental import state
    from ray_tpu.train import JaxConfig, JaxTrainer

    def loop(config):
        import time as _t
        from ray_tpu import train

        train.set_model_info(flops_per_token=1e6, peak_flops=1e9,
                             tokens_per_step=128)
        clock = train.step_clock()
        steps = 6
        t0 = _t.perf_counter()
        for _ in range(steps):
            clock.begin()
            with clock.phase("data_wait"):
                _t.sleep(0.002)
            with clock.phase("host_dispatch"):
                _t.sleep(0.01)
            clock.end()
        dt = _t.perf_counter() - t0
        # bench.py's hand computation of the same run
        bench_mfu = 1e6 * (128 * steps / dt) / 1e9
        session.report({"bench_mfu": bench_mfu,
                        "rank": session.get_world_rank()})

    trainer = JaxTrainer(
        loop,
        jax_config=JaxConfig(init_distributed=False,
                             host_collective=False),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="stepstats-e2e"))
    result = trainer.fit()
    assert result.error is None, result.error
    bench_mfu = result.metrics["bench_mfu"]

    # the goodput ledger reached the GCS (end_run flushes before the
    # worker reports done, but ride out a slow box)
    def _summary_ready():
        s = state.training_summary("stepstats-e2e")
        return s and len(s.get("ranks") or {}) == 2
    _wait_for(_summary_ready, msg="training summary with both ranks")
    s = state.training_summary("stepstats-e2e")
    assert s["world"] == 2
    led0 = s["ranks"].get(0) or s["ranks"].get("0")
    assert led0["steps"] == 6
    assert led0["mfu"] == pytest.approx(bench_mfu, rel=0.02), \
        f"ledger mfu {led0['mfu']} vs bench {bench_mfu}"
    assert 0 < led0["goodput"] <= 1.0
    assert led0["phase_ms"]["host_dispatch"] >= 6 * 10.0

    # step-table run row: both ranks with RPC metadata (profile --group)
    table = state.list_step_stats("stepstats-e2e")
    row = next(r for r in table["runs"]
               if r["group"] == "stepstats-e2e")
    assert row["world"] == 2 and row["steps_seen"] >= 6
    metas = row["ranks"]
    assert len(metas) == 2
    assert all(m.get("address") and m.get("worker_id")
               for m in metas.values())
    assert table.get("steps"), "per-step cross-rank records missing"
    assert row["skew"], "cross-rank skew not computed"

    # per-step phase slices in the Chrome trace (task events flush on
    # their own 500ms cadence)
    def _slices():
        evs = state.timeline()
        return any(e["cat"] == "train_step" for e in evs) and \
            any(e["cat"] == "train_phase"
                and e["name"] == "host_dispatch" for e in evs)
    _wait_for(_slices, msg="STEP timeline slices")
    evs = state.timeline()
    step_slices = [e for e in evs if e["cat"] == "train_step"]
    assert any(e["args"].get("trace_id", "").startswith("step-")
               for e in step_slices)


def test_chaos_pinned_rank_names_itself_as_straggler(ray_start_regular):
    """Chaos: pin one rank of a 4-rank gang with an injected per-step
    sleep — a TRAIN_STRAGGLER event must name that rank and the slow
    phase, and the step table stays inside its retention bounds."""
    from ray_tpu.air import RunConfig, ScalingConfig, session
    from ray_tpu.experimental import state
    from ray_tpu.train import JaxConfig, JaxTrainer

    def loop(config):
        import time as _t
        from ray_tpu import train

        rank = session.get_world_rank()
        clock = train.step_clock()
        for _ in range(5):
            clock.begin()
            with clock.phase("data_wait"):
                _t.sleep(0.001)
            with clock.phase("host_dispatch"):
                _t.sleep(0.005 + (0.1 if rank == 3 else 0.0))
            clock.end()
        session.report({"rank": rank})

    trainer = JaxTrainer(
        loop,
        jax_config=JaxConfig(init_distributed=False,
                             host_collective=False),
        scaling_config=ScalingConfig(num_workers=4),
        run_config=RunConfig(name="stepstats-chaos"))
    result = trainer.fit()
    assert result.error is None, result.error

    def _event():
        return state.list_cluster_events(type="TRAIN_STRAGGLER")
    _wait_for(lambda: _event(), msg="TRAIN_STRAGGLER event")
    evs = _event()
    ours = [e for e in evs if e.get("group") == "stepstats-chaos"
            or "stepstats-chaos" in str(e.get("run", ""))
            or e.get("rank") == 3]
    assert ours, f"no straggler event for this run in {evs}"
    ev = ours[-1]
    assert ev["rank"] == 3, f"wrong rank named: {ev}"
    assert ev["phase"] == "host_dispatch", f"wrong phase named: {ev}"
    assert ev["severity"] == "WARNING"
    assert ev["overshoot_ms"] >= 50
    # only the pinned rank is flagged, and retention invariants hold
    table = state.list_step_stats("stepstats-chaos")
    row = next(r for r in table["runs"]
               if r["group"] == "stepstats-chaos")
    assert set(row["straggling"]) <= {3, "3"}
    st = table["stats"]
    assert st["steps_retained"] <= st["max_runs"] * st["max_steps"]
    assert st["runs"] <= st["max_runs"]
