"""Ray-Client-mode tests: remote driver over the client server."""

import subprocess
import sys

import pytest


CLIENT_DRIVER = """
import ray_tpu

# decorated BEFORE init: client dispatch must happen at call time
@ray_tpu.remote
def double(x):
    return 2 * x

@ray_tpu.remote
def poke(acc, v):
    # acc arrives as a real server-side actor handle
    import ray_tpu as rt
    return rt.get(acc.add.remote(v))

@ray_tpu.remote
class Acc:
    def __init__(self, start):
        self.n = start
    def add(self, v):
        self.n += v
        return self.n

ray_tpu.init(address="client://127.0.0.1:__PORT__")
assert ray_tpu.is_initialized()
ref = ray_tpu.put(21)
assert ray_tpu.get(double.remote(ref)) == 42
refs = [double.remote(i) for i in range(4)]
ready, pending = ray_tpu.wait(refs, num_returns=4, timeout=30)
assert len(ready) == 4 and not pending
assert ray_tpu.get(refs) == [0, 2, 4, 6]

a = Acc.remote(10)
assert ray_tpu.get(a.add.remote(5)) == 15
# a client ref passed into an actor call resolves server-side
assert ray_tpu.get(a.add.remote(ref)) == 36

# a ref nested two containers deep still resolves
@ray_tpu.remote
def deep(d):
    import ray_tpu as rt
    return rt.get(d["xs"][0]) + 1

assert ray_tpu.get(deep.remote({"xs": [ref]})) == 22
# actor handles ship into tasks as wire tags
assert ray_tpu.get(poke.remote(a, 4)) == 40
assert len(ray_tpu.nodes()) >= 1
ray_tpu.kill(a)
import pytest_unused  # noqa
"""
CLIENT_DRIVER = CLIENT_DRIVER.replace("import pytest_unused  # noqa",
                                      "ray_tpu.shutdown()\nprint('CLIENT-OK')")


@pytest.fixture
def client_server():
    import ray_tpu
    from ray_tpu.util.client.server import ClientServer
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    server = ClientServer(host="127.0.0.1", port=0)
    yield server
    server.stop()
    ray_tpu.shutdown()


def test_client_driver_end_to_end(client_server):
    out = subprocess.run(
        [sys.executable, "-c",
         CLIENT_DRIVER.replace("__PORT__", str(client_server.address[1]))],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "CLIENT-OK" in out.stdout


def test_client_refs_release_on_disconnect(client_server):
    from ray_tpu.util import client as client_mod
    ctx = client_mod.ClientContext(client_server.address)
    ref = ctx.put({"k": 1})
    assert ctx.get(ref) == {"k": 1}
    assert any(s["refs"] for s in client_server._sessions.values())
    ctx.disconnect()   # clean bye: released immediately, no grace wait
    import time
    for _ in range(50):
        if not client_server._sessions:
            break
        time.sleep(0.1)
    assert not client_server._sessions  # session dropped with the bye


def test_client_reconnect_keeps_refs(client_server):
    """An abrupt connection drop (network blip, not a clean disconnect)
    reconnects transparently: the session's refs survive the grace
    window and in-flight RPC retries are deduped server-side (reference
    test_client_reconnect.py)."""
    import time

    from ray_tpu.util import client as client_mod
    ctx = client_mod.ClientContext(client_server.address)
    try:
        ref = ctx.put({"v": 41})
        # simulate the network dropping the server side of the conn
        sess = client_server._sessions[ctx.session_id]
        sess["conn"].close()
        time.sleep(0.3)
        # same context keeps working, and the pre-drop ref still resolves
        assert ctx.get(ref) == {"v": 41}
        ref2 = ctx.put(7)
        assert ctx.get(ref2) == 7
        assert client_server._sessions[ctx.session_id]["conn"] is not None
    finally:
        ctx.disconnect()


def test_client_large_object_roundtrip(client_server):
    """A multi-MB payload streams through the client path both ways."""
    import numpy as np

    from ray_tpu.util import client as client_mod
    ctx = client_mod.ClientContext(client_server.address)
    try:
        arr = np.arange(4 << 20, dtype=np.uint8)   # 4 MiB
        ref = ctx.put(arr)
        back = ctx.get(ref)
        assert back.shape == arr.shape and back[-1] == arr[-1]
        assert (back[::65536] == arr[::65536]).all()
    finally:
        ctx.disconnect()


def test_client_dynamic_num_returns(client_server):
    """num_returns="dynamic" through the remote driver: the generator's
    refs arrive as client refs resolvable over the same connection."""
    from ray_tpu.util import client as client_mod
    ctx = client_mod.ClientContext(client_server.address)
    try:
        def gen(n):
            for i in range(n):
                yield i * 11

        remote_gen = ctx.remote(gen, num_returns="dynamic")
        g = ctx.get(remote_gen.remote(3), timeout=60)
        assert len(g) == 3
        assert ctx.get(list(g), timeout=60) == [0, 11, 22]
    finally:
        ctx.disconnect()
