"""State API + internal KV + task events (SURVEY.md §2.3 state API row,
§5 tracing: reference python/ray/experimental/state/api.py,
_private/state.py:829 timeline)."""

import time

import pytest


def _wait_for(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {msg}")


def test_list_tasks_and_timeline(ray_start_regular):
    import ray_tpu
    from ray_tpu.experimental.state import (list_tasks, summarize_tasks,
                                            timeline)

    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get([add.remote(i, i) for i in range(4)]) == \
        [0, 2, 4, 6]

    def _done_with_running():
        tasks = [t for t in list_tasks(name="add")
                 if t["state"] == "FINISHED"
                 and any(ev["state"] == "RUNNING" for ev in t["events"])]
        return len(tasks) >= 4

    # worker-side RUNNING events flush on their own clock; wait for both
    _wait_for(_done_with_running,
              msg="4 finished add tasks (with RUNNING spans) in task table")
    tasks = list_tasks(name="add")
    assert all(t["name"] == "add" for t in tasks)
    done = [t for t in tasks if t["state"] == "FINISHED"]
    assert {"SUBMITTED", "RUNNING", "FINISHED"} <= {
        ev["state"] for t in done for ev in t["events"]}

    summary = summarize_tasks()
    assert summary["cluster"]["summary"]["add"]["FINISHED"] >= 4

    spans = timeline()
    assert any(e["name"] == "add" and e["ph"] == "X" and e["dur"] >= 0
               for e in spans)


def test_timeline_queue_wait_slices(ray_start_regular):
    """The enriched timeline carries SUBMITTED->RUNNING queue-wait
    slices next to each task's execution span."""
    import ray_tpu
    from ray_tpu.experimental.state import timeline

    @ray_tpu.remote
    def queued_task():
        return 1

    assert ray_tpu.get([queued_task.remote() for _ in range(3)]) == [1] * 3

    def _has_queue_slices():
        ev = timeline()
        waits = [e for e in ev if e["cat"] == "queue_wait"
                 and e["name"].startswith("queued_task")]
        runs = [e for e in ev if e["cat"] == "task"
                and e["name"] == "queued_task"]
        return len(waits) >= 1 and len(runs) >= 3

    _wait_for(_has_queue_slices, msg="queue-wait slices in timeline")
    ev = timeline()
    wait = next(e for e in ev if e["cat"] == "queue_wait"
                and e["name"].startswith("queued_task"))
    run = next(e for e in ev if e["cat"] == "task"
               and e["name"] == "queued_task"
               and e["args"]["task_id"] == wait["args"]["task_id"])
    assert wait["ph"] == "X" and wait["dur"] >= 0
    # the queued slice ends where the running span starts
    assert wait["ts"] + wait["dur"] == pytest.approx(run["ts"], abs=1.0)


def test_timeline_stream_item_instants(ray_start_regular):
    """Streaming generators leave one instant per reported yield on the
    executing worker's timeline row."""
    import ray_tpu
    from ray_tpu.experimental.state import timeline

    @ray_tpu.remote
    def gen(n):
        for i in range(n):
            yield i

    g = gen.options(num_returns="streaming").remote(4)
    assert [ray_tpu.get(r) for r in g] == [0, 1, 2, 3]

    def _has_instants():
        items = [e for e in timeline() if e["cat"] == "stream_item"]
        return len(items) >= 4

    _wait_for(_has_instants, msg="stream item instants in timeline")
    items = sorted((e for e in timeline() if e["cat"] == "stream_item"),
                   key=lambda e: e["args"]["index"])
    assert [e["args"]["index"] for e in items[:4]] == [0, 1, 2, 3]
    assert all(e["ph"] == "i" for e in items)
    run = next(e for e in timeline() if e["cat"] == "task"
               and e["name"] == "gen")
    # instants sit on the same worker row as the task span
    assert all(e["tid"] == run["tid"] for e in items)


def test_timeline_trace_id_correlation(ray_start_regular):
    """A span() on the driver propagates its trace_id through the
    submitted task into the timeline, so user spans and tasks correlate
    in Perfetto."""
    import ray_tpu
    from ray_tpu.experimental.state import timeline
    from ray_tpu.util.tracing.tracing_helper import (get_trace_context,
                                                     span)

    @ray_tpu.remote
    def traced_task():
        return 1

    with span("driver-work"):
        driver_trace = get_trace_context()["trace_id"]
        assert ray_tpu.get(traced_task.remote()) == 1

    def _correlated():
        ev = timeline()
        task = [e for e in ev if e["name"] == "traced_task"
                and e["cat"] == "task"
                and e["args"].get("trace_id") == driver_trace]
        spans = [e for e in ev if e["name"] == "span:driver-work"
                 and e["args"].get("trace_id") == driver_trace]
        return task and spans

    _wait_for(_correlated, msg="trace-correlated task + span in timeline")


def test_failed_task_state(ray_start_regular):
    import ray_tpu
    from ray_tpu.experimental.state import list_tasks

    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("nope")

    with pytest.raises(ray_tpu.exceptions.TaskError):
        ray_tpu.get(boom.remote())
    _wait_for(lambda: any(t["state"] == "FAILED"
                          for t in list_tasks(name="boom")),
              msg="FAILED boom task")


def test_list_actors_workers_objects(ray_start_regular):
    import ray_tpu
    from ray_tpu.experimental.state import (list_actors, list_objects,
                                            list_workers, memory_summary,
                                            summarize_objects)

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote()) == 1

    actors = list_actors(state="ALIVE")
    assert len(actors) == 1

    workers = list_workers()
    assert any(w["alive"] and w["actor_id"] for w in workers)

    big = ray_tpu.put(b"x" * 512 * 1024)  # above inline threshold
    objs = list_objects()
    assert any(o["object_id"] == big.hex() for o in objs)
    assert summarize_objects()["cluster"]["total_objects"] >= 1
    assert "OBJECT_ID" in memory_summary()
    del big


def test_internal_kv(ray_start_regular):
    from ray_tpu.experimental import internal_kv as kv

    assert kv._internal_kv_initialized()
    assert kv._internal_kv_put("k1", b"v1") is False  # fresh key
    assert kv._internal_kv_put("k1", b"v2") is True   # existed
    assert kv._internal_kv_get("k1") == b"v2"
    assert kv._internal_kv_put("k1", b"v3", overwrite=False) is True
    assert kv._internal_kv_get("k1") == b"v2"
    assert kv._internal_kv_exists("k1")
    assert "k1" in kv._internal_kv_list("k")
    assert kv._internal_kv_del("k1")
    assert not kv._internal_kv_exists("k1")
    assert kv._internal_kv_get("k1") is None


def test_trace_context_propagates_into_tasks(ray_start_regular):
    """Auto span injection: a task submitted inside a driver span joins
    the driver's trace (reference _inject_tracing_into_function)."""
    import ray_tpu
    from ray_tpu.util.tracing.tracing_helper import (get_trace_context,
                                                     span)

    @ray_tpu.remote
    def inner_trace():
        from ray_tpu.util.tracing.tracing_helper import get_trace_context
        return get_trace_context().get("trace_id")

    with span("driver-section"):
        driver_trace = get_trace_context()["trace_id"]
        task_trace = ray_tpu.get(inner_trace.remote(), timeout=60)
    assert task_trace == driver_trace

    @ray_tpu.remote
    class A:
        def trace(self):
            from ray_tpu.util.tracing.tracing_helper import \
                get_trace_context
            return get_trace_context().get("trace_id")

    a = A.remote()
    with span("actor-section"):
        driver_trace = get_trace_context()["trace_id"]
        actor_trace = ray_tpu.get(a.trace.remote(), timeout=60)
    assert actor_trace == driver_trace
