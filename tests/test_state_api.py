"""State API + internal KV + task events (SURVEY.md §2.3 state API row,
§5 tracing: reference python/ray/experimental/state/api.py,
_private/state.py:829 timeline)."""

import time

import pytest


def _wait_for(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {msg}")


def test_list_tasks_and_timeline(ray_start_regular):
    import ray_tpu
    from ray_tpu.experimental.state import (list_tasks, summarize_tasks,
                                            timeline)

    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get([add.remote(i, i) for i in range(4)]) == \
        [0, 2, 4, 6]

    def _done_with_running():
        tasks = [t for t in list_tasks(name="add")
                 if t["state"] == "FINISHED"
                 and any(ev["state"] == "RUNNING" for ev in t["events"])]
        return len(tasks) >= 4

    # worker-side RUNNING events flush on their own clock; wait for both
    _wait_for(_done_with_running,
              msg="4 finished add tasks (with RUNNING spans) in task table")
    tasks = list_tasks(name="add")
    assert all(t["name"] == "add" for t in tasks)
    done = [t for t in tasks if t["state"] == "FINISHED"]
    assert {"SUBMITTED", "RUNNING", "FINISHED"} <= {
        ev["state"] for t in done for ev in t["events"]}

    summary = summarize_tasks()
    assert summary["cluster"]["summary"]["add"]["FINISHED"] >= 4

    spans = timeline()
    assert any(e["name"] == "add" and e["ph"] == "X" and e["dur"] >= 0
               for e in spans)


def test_failed_task_state(ray_start_regular):
    import ray_tpu
    from ray_tpu.experimental.state import list_tasks

    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("nope")

    with pytest.raises(ray_tpu.exceptions.TaskError):
        ray_tpu.get(boom.remote())
    _wait_for(lambda: any(t["state"] == "FAILED"
                          for t in list_tasks(name="boom")),
              msg="FAILED boom task")


def test_list_actors_workers_objects(ray_start_regular):
    import ray_tpu
    from ray_tpu.experimental.state import (list_actors, list_objects,
                                            list_workers, memory_summary,
                                            summarize_objects)

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote()) == 1

    actors = list_actors(state="ALIVE")
    assert len(actors) == 1

    workers = list_workers()
    assert any(w["alive"] and w["actor_id"] for w in workers)

    big = ray_tpu.put(b"x" * 512 * 1024)  # above inline threshold
    objs = list_objects()
    assert any(o["object_id"] == big.hex() for o in objs)
    assert summarize_objects()["cluster"]["total_objects"] >= 1
    assert "OBJECT_ID" in memory_summary()
    del big


def test_internal_kv(ray_start_regular):
    from ray_tpu.experimental import internal_kv as kv

    assert kv._internal_kv_initialized()
    assert kv._internal_kv_put("k1", b"v1") is False  # fresh key
    assert kv._internal_kv_put("k1", b"v2") is True   # existed
    assert kv._internal_kv_get("k1") == b"v2"
    assert kv._internal_kv_put("k1", b"v3", overwrite=False) is True
    assert kv._internal_kv_get("k1") == b"v2"
    assert kv._internal_kv_exists("k1")
    assert "k1" in kv._internal_kv_list("k")
    assert kv._internal_kv_del("k1")
    assert not kv._internal_kv_exists("k1")
    assert kv._internal_kv_get("k1") is None


def test_trace_context_propagates_into_tasks(ray_start_regular):
    """Auto span injection: a task submitted inside a driver span joins
    the driver's trace (reference _inject_tracing_into_function)."""
    import ray_tpu
    from ray_tpu.util.tracing.tracing_helper import (get_trace_context,
                                                     span)

    @ray_tpu.remote
    def inner_trace():
        from ray_tpu.util.tracing.tracing_helper import get_trace_context
        return get_trace_context().get("trace_id")

    with span("driver-section"):
        driver_trace = get_trace_context()["trace_id"]
        task_trace = ray_tpu.get(inner_trace.remote(), timeout=60)
    assert task_trace == driver_trace

    @ray_tpu.remote
    class A:
        def trace(self):
            from ray_tpu.util.tracing.tracing_helper import \
                get_trace_context
            return get_trace_context().get("trace_id")

    a = A.remote()
    with span("actor-section"):
        driver_trace = get_trace_context()["trace_id"]
        actor_trace = ray_tpu.get(a.trace.remote(), timeout=60)
    assert actor_trace == driver_trace
