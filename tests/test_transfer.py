"""Unit tests for the bulk data plane (ray_tpu/_private/transfer.py):
pipelined windowed pulls, multi-source striping with per-source failover,
shm-direct landing and budget admission — driven with fake stores and
fake raylet connections so every failure is injected deterministically
(the cluster-level versions live in tests/test_object_recovery.py)."""

import threading
import time
from concurrent.futures import Future

import pytest

from ray_tpu._private import transfer
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import ObjectID

CHUNK = 64  # config patched per-test: tiny chunks, many of them


@pytest.fixture(autouse=True)
def _small_chunks(monkeypatch):
    monkeypatch.setenv("RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES", str(CHUNK))
    monkeypatch.setenv("RAY_TPU_OBJECT_PULL_WINDOW", "4")
    monkeypatch.setenv("RAY_TPU_OBJECT_PULL_MAX_SOURCES", "4")
    yield


class FakeStore:
    """Minimal SharedMemoryStore double: create/seal/get/release/abort
    over heap bytearrays, with pin counting."""

    def __init__(self, full=False):
        self.unsealed = {}
        self.sealed = {}
        self.pins = {}
        self.full = full

    def create(self, oid, size, meta=0, allow_evict=True):
        from ray_tpu.exceptions import ObjectStoreFullError
        if self.full:
            raise ObjectStoreFullError("full")
        ob = oid.binary()
        if ob in self.unsealed or ob in self.sealed:
            raise FileExistsError(oid)
        buf = bytearray(size)
        self.unsealed[ob] = (buf, meta)
        return memoryview(buf)

    def seal(self, oid):
        ob = oid.binary()
        if ob not in self.unsealed:
            raise KeyError(oid)
        self.sealed[ob] = self.unsealed.pop(ob)

    def abort(self, oid):
        self.unsealed.pop(oid.binary(), None)

    def get(self, oid, timeout=0.0):
        rec = self.sealed.get(oid.binary())
        if rec is None:
            return None
        self.pins[oid.binary()] = self.pins.get(oid.binary(), 0) + 1
        return memoryview(rec[0]), rec[1]

    def release(self, oid):
        self.pins[oid.binary()] -= 1


class FakeSource:
    """One fake raylet serving fetch_object_chunk for a single payload.

    ``fail_after``/``absent_after``: after serving that many chunks the
    source starts raising ConnectionError / answering "no copy".
    Mirrors the real connection's buffer-sink contract: a served chunk
    lands directly in the sink-provided destination view (and the
    ``sunk`` counter lets tests assert the zero-copy path was taken)."""

    def __init__(self, payload, meta=7, fail_after=None, absent_after=None,
                 delay=0.0):
        self.payload = payload
        self.meta = meta
        self.fail_after = fail_after
        self.absent_after = absent_after
        self.delay = delay
        self.served = []       # offsets that returned data
        self.sunk = 0          # chunks landed via a buffer sink
        self.discarded = []    # msg_ids whose sinks were withdrawn
        self.closed = False
        self._lock = threading.Lock()
        self._ids = iter(range(1, 1 << 30))

    def call(self, method, p, timeout=None):
        return self.call_async(method, p).result(timeout)

    def call_async(self, method, p, buffer_sink=None):
        assert method == "fetch_object_chunk"
        fut = Future()
        fut._rpc_msg_id = next(self._ids)
        if self.delay:
            time.sleep(self.delay)
        with self._lock:
            n = len(self.served)
            if self.fail_after is not None and n >= self.fail_after:
                fut.set_exception(ConnectionError("source died"))
                return fut
            if self.absent_after is not None and n >= self.absent_after:
                fut.set_result(None)  # authoritative "no copy here"
                return fut
            off = int(p["offset"])
            data = bytes(self.payload[off:off + int(p["length"])])
            self.served.append(off)
        if buffer_sink is not None:
            dests = buffer_sink([len(data)])
            if dests is not None:
                dests[0][:] = data  # reader recv_into analog
                self.sunk += 1
                fut.set_result({"total": len(self.payload),
                                "meta": self.meta,
                                "data": dests[0].toreadonly()})
                return fut
        fut.set_result({"total": len(self.payload), "meta": self.meta,
                        "data": data})
        return fut

    def discard_sinks(self, msg_ids, timeout=2.0):
        self.discarded.extend(msg_ids)


def make_puller(sources, store=None, budget=None):
    store = store if store is not None else FakeStore()
    conns = {nh: src for nh, src in sources.items()}

    def resolve(nh):
        return (nh, 0) if nh in conns else None

    def get_conn(addr):
        src = conns[addr[0]]
        if src is None:
            raise ConnectionError("unreachable")
        return src

    return transfer.ObjectPuller(store, resolve, get_conn,
                                 budget=budget), store


def payload_of(n):
    return bytes(bytearray(i % 251 for i in range(n)))


def test_single_source_pipelined_pull_publishes_to_store():
    oid = ObjectID.from_random()
    data = payload_of(CHUNK * 10 + 13)
    src = FakeSource(data)
    puller, store = make_puller({"a": src})
    out = puller.pull(oid, ["a"])
    assert out.status == "ok"
    assert out.published
    assert bytes(out.data) == data
    assert out.bytes == len(data)
    assert out.meta == 7
    # shm-direct: the sealed store copy IS the returned buffer, pinned once
    assert oid.binary() in store.sealed
    assert store.pins[oid.binary()] == 1
    # every chunk fetched exactly once (no restart, no duplicates)
    assert sorted(src.served) == list(range(0, len(data), CHUNK))
    # zero-copy landing: every windowed chunk rode a buffer sink straight
    # into the destination (discovery's chunk 0 is the only copied one)
    assert src.sunk == len(src.served) - 1


def test_small_object_single_rtt_no_store_publish():
    oid = ObjectID.from_random()
    data = payload_of(CHUNK // 2)
    src = FakeSource(data)
    puller, store = make_puller({"a": src})
    out = puller.pull(oid, ["a"])
    assert out.status == "ok" and not out.published
    assert bytes(out.data) == data
    assert src.served == [0]
    assert not store.sealed  # get path: no local store churn

    # the prefetch path wants a local copy even for small objects
    oid2 = ObjectID.from_random()
    out2 = puller.pull(oid2, ["a"], publish_small=True)
    assert out2.status == "ok" and out2.published
    assert oid2.binary() in store.sealed


def test_striping_spreads_chunks_across_sources():
    oid = ObjectID.from_random()
    data = payload_of(CHUNK * 16)
    a, b = FakeSource(data), FakeSource(data)
    puller, store = make_puller({"a": a, "b": b})
    out = puller.pull(oid, ["a", "b"])
    assert out.status == "ok"
    assert bytes(out.data) == data
    assert out.nsources == 2
    assert a.served and b.served, "both sources must serve chunks"
    # dynamic striping: union covers every offset exactly once
    assert sorted(a.served + b.served) == list(range(0, len(data), CHUNK))


def test_source_death_mid_transfer_fails_over_without_restart():
    oid = ObjectID.from_random()
    data = payload_of(CHUNK * 20)
    dying = FakeSource(data, fail_after=3)
    # the survivor serves slowly so the dying source deterministically
    # reaches its failure point while ranges are still outstanding
    healthy = FakeSource(data, delay=0.01)
    puller, store = make_puller({"dying": dying, "healthy": healthy})
    out = puller.pull(oid, ["dying", "healthy"])
    assert out.status == "ok"
    assert bytes(out.data) == data
    assert out.transient  # a source died on transport
    # failover, not restart: offsets the dead source already delivered
    # were NOT fetched again from the survivor
    assert len(dying.served) == 3
    assert sorted(dying.served + healthy.served) == \
        list(range(0, len(data), CHUNK))


def test_eviction_on_one_source_completes_from_survivor():
    oid = ObjectID.from_random()
    data = payload_of(CHUNK * 12)
    evicted = FakeSource(data, absent_after=2)
    holder = FakeSource(data, delay=0.01)  # see death test: deterministic
    puller, store = make_puller({"evicted": evicted, "holder": holder})
    out = puller.pull(oid, ["evicted", "holder"])
    assert out.status == "ok"
    assert bytes(out.data) == data
    # the absent answer is authoritative for that source only
    assert "evicted" in out.absent
    assert sorted(evicted.served + holder.served) == \
        list(range(0, len(data), CHUNK))


def test_all_sources_absent_is_authoritative():
    oid = ObjectID.from_random()
    src = FakeSource(b"", absent_after=0)
    puller, _ = make_puller({"a": src})
    out = puller.pull(oid, ["a"])
    assert out.status == "absent"
    assert out.absent == {"a"}
    assert not out.transient


def test_all_sources_dead_is_transient_error():
    oid = ObjectID.from_random()
    src = FakeSource(payload_of(CHUNK * 4), fail_after=0)
    puller, _ = make_puller({"a": src})
    out = puller.pull(oid, ["a"])
    assert out.status == "error"
    assert out.transient


def test_mid_transfer_death_of_only_source_aborts_create():
    oid = ObjectID.from_random()
    src = FakeSource(payload_of(CHUNK * 8), fail_after=2)
    puller, store = make_puller({"a": src})
    out = puller.pull(oid, ["a"])
    assert out.status == "error" and out.transient
    # the partially-written create was aborted, not leaked
    assert oid.binary() not in store.unsealed
    assert oid.binary() not in store.sealed


def test_store_full_degrades_to_heap_buffer():
    oid = ObjectID.from_random()
    data = payload_of(CHUNK * 6)
    src = FakeSource(data)
    puller, store = make_puller({"a": src}, store=FakeStore(full=True))
    out = puller.pull(oid, ["a"])
    assert out.status == "ok" and not out.published
    assert bytes(out.data) == data


def test_budget_uncontended_keeps_first_chunk():
    oid = ObjectID.from_random()
    data = payload_of(CHUNK * 8)
    src = FakeSource(data)
    budget = transfer.PullBudget(10 * len(data))
    puller, _ = make_puller({"a": src}, budget=budget)
    out = puller.pull(oid, ["a"])
    assert out.status == "ok"
    # offset 0 fetched exactly once: the uncontended admit kept it
    assert src.served.count(0) == 1
    assert budget.used == 0  # released after the pull


def test_budget_contended_drops_first_chunk_and_waits_fifo():
    oid = ObjectID.from_random()
    data = payload_of(CHUNK * 8)
    src = FakeSource(data)
    budget = transfer.PullBudget(len(data) + 10)
    assert budget.acquire(len(data), None)  # hog the whole budget
    puller, _ = make_puller({"a": src}, budget=budget)
    done = {}

    def run():
        done["out"] = puller.pull(oid, ["a"])

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.3)
    assert "out" not in done, "pull must park while the budget is held"
    budget.release(len(data))
    t.join(timeout=10)
    out = done["out"]
    assert out.status == "ok"
    assert bytes(out.data) == data
    # parked waiters hold no payload bytes: offset 0 was re-fetched
    assert src.served.count(0) == 2


def test_pull_budget_oversized_object_admitted_alone():
    budget = transfer.PullBudget(100)
    assert budget.acquire(1000, None)   # capped at the whole budget
    assert not budget.acquire(1, time.monotonic() + 0.05)
    budget.release(1000)
    assert budget.acquire(1, None)


def test_conn_cache_reuses_and_replaces_closed():
    class FakeConn:
        def __init__(self):
            self.closed = False

        def close(self):
            self.closed = True

    made = []

    def fake_connect(addr, timeout=None):
        conn = FakeConn()
        made.append(conn)
        return conn

    cache = transfer.ConnCache()
    real_connect = transfer.rpc.connect
    transfer.rpc.connect = fake_connect
    try:
        c1 = cache.get(("h", 1))
        assert cache.get(("h", 1)) is c1   # pooled, not re-dialed
        c2 = cache.get(("h", 2))
        assert c2 is not c1
        c1.closed = True
        c3 = cache.get(("h", 1))           # dead conn replaced
        assert c3 is not c1 and not c3.closed
        cache.close()
        assert c2.closed and c3.closed
    finally:
        transfer.rpc.connect = real_connect


def test_concurrent_local_pull_waits_for_peer_seal():
    """Two concurrent pulls of the same object into one store: the loser
    of the create race waits for the winner's seal instead of
    transferring the same bytes twice."""
    oid = ObjectID.from_random()
    data = payload_of(CHUNK * 6)
    slow = FakeSource(data, delay=0.05)
    fast = FakeSource(data)
    store = FakeStore()
    p1, _ = make_puller({"a": slow}, store=store)
    p2, _ = make_puller({"a": fast}, store=store)
    outs = {}

    def run(name, puller):
        outs[name] = puller.pull(oid, ["a"])

    t1 = threading.Thread(target=run, args=("slow", p1))
    t1.start()
    time.sleep(0.1)  # slow's discovery (0.05s) done: it holds the create
    run("fast", p2)
    t1.join(timeout=30)
    assert outs["slow"].status == "ok"
    assert outs["fast"].status == "ok"
    assert bytes(outs["fast"].data) == data
    # the fast puller paid only the discovery probe — the body was never
    # transferred twice; it waited for the winner's seal
    assert fast.served == [0]
