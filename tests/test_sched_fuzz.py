"""Schedule fuzzing: the runtime survives perturbed RPC interleavings.

The reference stresses races with TSAN builds and schedule-fuzzing CI
jobs (SURVEY.md §5 race detection).  The single-language analog here:
``rpc_fuzz_ms`` jitters every RPC dispatch (rpc.py _maybe_fuzz), so
orderings that "usually" hold — replies before pushes, lease grants
before worker deaths, seal-before-fetch — get shuffled.  Any handler
that silently depended on timing fails loudly under this suite.
"""

import time

import pytest

import ray_tpu


@pytest.fixture
def fuzzed_cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024,
                 system_config={"rpc_fuzz_ms": 8.0})
    yield ray_tpu
    ray_tpu.shutdown()


def test_tasks_actors_objects_under_fuzz(fuzzed_cluster):
    """Core invariants hold when every RPC is jittered: task results
    are exact, actor call order per caller is preserved, concurrent
    waves complete, and store objects round-trip."""

    @ray_tpu.remote
    def sq(x):
        return x * x

    assert ray_tpu.get([sq.remote(i) for i in range(40)],
                       timeout=120) == [i * i for i in range(40)]

    @ray_tpu.remote(num_cpus=0)
    class Seq:
        def __init__(self):
            self.log = []

        def add(self, i):
            self.log.append(i)
            return len(self.log)

        def all(self):
            return self.log

    s = Seq.remote()
    refs = [s.add.remote(i) for i in range(30)]
    counts = ray_tpu.get(refs, timeout=120)
    # per-caller actor ordering survives the jitter: calls applied in
    # submission order despite shuffled transport timing
    assert counts == list(range(1, 31))
    assert ray_tpu.get(s.all.remote(), timeout=60) == list(range(30))

    big = ray_tpu.put(b"z" * 600_000)          # store path (not inline)
    assert len(ray_tpu.get(big, timeout=60)) == 600_000


def test_dependency_chains_under_fuzz(fuzzed_cluster):
    """Ref-arg staging and chained lineage under jittered grants."""

    @ray_tpu.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(15):
        ref = inc.remote(ref)
    assert ray_tpu.get(ref, timeout=120) == 16


def test_worker_death_under_fuzz(fuzzed_cluster):
    """Actor restart FSM with jittered death notifications."""
    @ray_tpu.remote(num_cpus=0, max_restarts=2)
    class C:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

        def die(self):
            import os
            os._exit(1)

    c = C.remote()
    assert ray_tpu.get(c.bump.remote(), timeout=60) == 1
    c.die.remote()
    deadline = time.monotonic() + 90
    val = None
    while time.monotonic() < deadline:
        try:
            val = ray_tpu.get(c.bump.remote(), timeout=30)
            break
        except ray_tpu.exceptions.RayTpuError:
            time.sleep(0.5)
    assert val == 1, "actor did not restart under fuzz"
