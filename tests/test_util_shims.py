"""Utility shims (SURVEY.md §2.3: multiprocessing/joblib shims, iter,
actor_group, check_serialize, rpdb, tracing)."""

import threading
import time

import pytest


def test_multiprocessing_pool(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(2) as pool:
        assert pool.map(lambda x: x * 2, range(10)) == \
            [0, 2, 4, 6, 8, 10, 12, 14, 16, 18]
        assert pool.starmap(lambda a, b: a + b, [(1, 2), (3, 4)]) == [3, 7]
        assert pool.apply(lambda a, b: a * b, (3, 4)) == 12
        r = pool.map_async(lambda x: x + 1, [1, 2, 3])
        r.wait(30)
        assert r.ready() and r.successful()
        assert r.get() == [2, 3, 4]
        assert list(pool.imap(lambda x: x * x, [1, 2, 3], chunksize=2)) == \
            [1, 4, 9]
        assert sorted(pool.imap_unordered(lambda x: x, [3, 1, 2])) == \
            [1, 2, 3]
    with pytest.raises(ValueError):
        pool.map(lambda x: x, [1])


def test_pool_imap_streams_lazily(ray_start_regular):
    """imap must not materialize the input (stdlib semantics)."""
    from ray_tpu.util.multiprocessing import Pool

    def gen():
        yield from range(10 ** 9)  # effectively infinite

    with Pool(2) as pool:
        it = pool.imap(lambda x: x * 2, gen(), chunksize=4)
        assert [next(it) for _ in range(6)] == [0, 2, 4, 6, 8, 10]


def test_pool_initializer_once_per_worker(ray_start_regular):
    import os

    from ray_tpu.util.multiprocessing import Pool

    def init_marker():
        os.environ["POOL_INIT_COUNT"] = str(
            int(os.environ.get("POOL_INIT_COUNT", "0")) + 1)

    def read_marker(_):
        return (os.getpid(), int(os.environ.get("POOL_INIT_COUNT", "0")))

    with Pool(2, initializer=init_marker) as pool:
        # many chunks per worker: initializer must still run once each
        out = pool.map(read_marker, range(16), chunksize=1)
    per_pid = {}
    for pid, count in out:
        per_pid.setdefault(pid, set()).add(count)
    for pid, counts in per_pid.items():
        assert counts == {1}, f"worker {pid} saw init counts {counts}"


def test_pool_error_propagation(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(2) as pool:
        r = pool.map_async(lambda x: 1 // x, [1, 0])
        r.wait(30)
        assert r.ready() and not r.successful()
        with pytest.raises(Exception):
            r.get()


def test_joblib_backend(ray_start_regular):
    import joblib

    from ray_tpu.util.joblib import register_ray
    register_ray()
    with joblib.parallel_backend("ray_tpu", n_jobs=2):
        out = joblib.Parallel()(joblib.delayed(pow)(i, 2) for i in range(6))
    assert out == [0, 1, 4, 9, 16, 25]


def test_parallel_iterator(ray_start_regular):
    from ray_tpu.util import iter as par_iter

    it = par_iter.from_range(8, num_shards=2)
    assert it.num_shards() == 2
    doubled = it.for_each(lambda x: x * 2)
    assert sorted(doubled.gather_sync()) == [0, 2, 4, 6, 8, 10, 12, 14]

    evens = par_iter.from_range(10, num_shards=2) \
        .filter(lambda x: x % 2 == 0)
    assert sorted(evens.gather_async()) == [0, 2, 4, 6, 8]

    batched = par_iter.from_items([1, 2, 3, 4], num_shards=1).batch(2)
    assert list(batched.gather_sync()) == [[1, 3], [2, 4]] or \
        list(batched.gather_sync()) == [[1, 2], [3, 4]]

    u = par_iter.from_range(3, 1).union(par_iter.from_range(3, 1))
    assert sorted(u.gather_sync()) == [0, 0, 1, 1, 2, 2]
    assert par_iter.from_range(100, 4).take(5) == [0, 4, 1, 5, 2] or \
        len(par_iter.from_range(100, 4).take(5)) == 5


def test_actor_group(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import ActorGroup

    class Member:
        def __init__(self, base):
            self.base = base

        def add(self, x):
            return self.base + x

    g = ActorGroup(Member, 3, 10)
    assert len(g) == 3
    assert g.execute("add", 5) == [15, 15, 15]
    refs = g.add.remote(1)
    assert ray_tpu.get(refs) == [11, 11, 11]
    g.shutdown()


def test_inspect_serializability():
    from ray_tpu.util import inspect_serializability

    ok, failures = inspect_serializability(lambda x: x + 1)
    assert ok and not failures

    lock = threading.Lock()

    def bad():
        return lock

    ok, failures = inspect_serializability(bad)
    assert not ok
    assert any("lock" in f.lower() or "closure" in f for f in failures)


def test_tracing_span(ray_start_regular):
    import ray_tpu
    from ray_tpu.experimental.state import list_tasks
    from ray_tpu.util.tracing import get_trace_context, span

    with span("prep"):
        ctx = get_trace_context()
        assert ctx.get("trace_id")
    assert get_trace_context() == {}

    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if any(t["name"] == "span:prep" and t["state"] == "FINISHED"
               for t in list_tasks()):
            break
        time.sleep(0.2)
    else:
        raise AssertionError("span:prep not in task table")


def test_rpdb_registration(ray_start_regular):
    """set_trace publishes host:port in KV; attach via raw socket."""
    import socket

    import ray_tpu
    from ray_tpu.util import rpdb

    @ray_tpu.remote
    def task_with_bp():
        rpdb.set_trace()
        return "resumed"

    ref = task_with_bp.remote()
    deadline = time.monotonic() + 30
    sessions = []
    while time.monotonic() < deadline and not sessions:
        sessions = rpdb.list_breakpoints()
        time.sleep(0.2)
    assert sessions, "breakpoint never registered"
    host, port = sessions[0][1].split(":")
    s = socket.create_connection((host, int(port)), timeout=10)
    f = s.makefile("rw", buffering=1)
    f.write("c\n")  # continue
    f.flush()
    assert ray_tpu.get(ref, timeout=60) == "resumed"
    s.close()


def test_dask_graph_scheduler(ray_start_regular):
    """ray_dask_get executes dask-format task graphs ({key: (fn, *args)},
    dask's documented spec — no dask import needed) as cluster tasks:
    dependency chaining, fan-in, nested specs, aliases, literals, and
    the nested-keys fetch convention."""
    from operator import add, mul

    from ray_tpu.util.dask import ray_dask_get

    dsk = {
        "x": 1,
        "y": 2,
        "z": (add, "x", "y"),                 # fan-in on two literals
        "w": (mul, "z", 10),
        "nested": (add, (mul, "x", 100), "y"),  # inline nested task
        "alias": "w",
        "lst": (sum, [1, 2, "x"]),            # list arg, key inside
    }
    assert ray_dask_get(dsk, "z") == 3
    assert ray_dask_get(dsk, "w") == 30
    assert ray_dask_get(dsk, "nested") == 102
    assert ray_dask_get(dsk, "alias") == 30
    assert ray_dask_get(dsk, "lst") == 4   # 1 + 2 + x(=1)
    # dask collections pass nested key lists
    assert ray_dask_get(dsk, [["x", "y"], ["w"]]) == [[1, 2], [30]]

    # cycles fail loudly
    import pytest
    with pytest.raises(ValueError, match="cycle"):
        ray_dask_get({"a": (add, "b", 1), "b": (add, "a", 1)}, "a")
