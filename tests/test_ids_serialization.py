"""Unit tests for IDs, serialization, config, and RPC plumbing."""

import numpy as np
import pytest

from ray_tpu._private import serialization as ser
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID


def test_id_roundtrip():
    t = TaskID.from_random()
    assert TaskID(t.binary()) == t
    assert TaskID.from_hex(t.hex()) == t
    assert t != TaskID.from_random()
    assert not t.is_nil() and TaskID.nil().is_nil()
    assert hash(JobID(t.binary())) != hash(ActorID(t.binary()))


def test_object_id_embeds_task_and_index():
    t = TaskID.from_random()
    o = ObjectID.for_task_return(t, 3)
    assert o.task_id() == t
    assert o.return_index() == 3
    assert not o.is_put()
    p = ObjectID.for_put(t, 7)
    assert p.is_put() and p.task_id() == t


def test_serialize_roundtrip_scalar_and_nested():
    for value in [42, "hello", {"a": [1, 2, (3, None)]}, b"\x00" * 100]:
        head, views = ser.serialize(value)
        flat = ser.to_flat_bytes(head, views)
        assert ser.deserialize(flat) == value


def test_serialize_numpy_zero_copy():
    arr = np.arange(1 << 16, dtype=np.float32).reshape(256, 256)
    head, views = ser.serialize({"w": arr, "tag": 1})
    assert sum(len(v) for v in views) >= arr.nbytes  # out-of-band
    flat = ser.to_flat_bytes(head, views)
    out = ser.deserialize(flat)
    np.testing.assert_array_equal(out["w"], arr)


def test_serialize_error_payload_raises_on_deserialize():
    err = ValueError("boom")
    head, views = ser.serialize(err, error_type=ser.ERROR_TASK)
    flat = ser.to_flat_bytes(head, views)
    assert ser.error_type_of(flat) == ser.ERROR_TASK
    with pytest.raises(ValueError, match="boom"):
        ser.deserialize(flat)


def test_config_defaults_and_overrides():
    assert CONFIG.inline_object_max_bytes == 100 * 1024
    CONFIG.set("inline_object_max_bytes", 1)
    try:
        assert CONFIG.inline_object_max_bytes == 1
    finally:
        CONFIG.set("inline_object_max_bytes", 100 * 1024)
    with pytest.raises(AttributeError):
        _ = CONFIG.not_a_flag
    assert "object_store_memory_bytes" in CONFIG.snapshot()


def test_rpc_call_push_and_error():
    from ray_tpu._private import rpc

    pushes = []

    def handler(conn, method, payload):
        if method == "echo":
            return payload
        if method == "fail":
            raise RuntimeError("nope")
        raise KeyError(method)

    server = rpc.Server(handler)
    try:
        conn = rpc.connect(server.address, push_handler=lambda m, p: pushes.append((m, p)))
        assert conn.call("echo", {"x": 1}) == {"x": 1}
        with pytest.raises(rpc.RemoteError):
            conn.call("fail")
        # server -> client push
        server.connections()[0].push("note", 7)
        import time
        for _ in range(100):
            if pushes:
                break
            time.sleep(0.01)
        assert pushes == [("note", 7)]
        conn.close()
    finally:
        server.stop()
