"""Sharded-training subsystem gate (docs/train_sharded.md).

Three contracts, asserted end to end:

* **golden layouts** — :func:`ray_tpu.train.sharded.layout.plan` maps a
  ShardingConfig to an EXACT PartitionSpec table per parameter /
  activation class (including the dp-only and pp-only degenerates).
  The tables are written out literally: any rule-table or pruning
  change must update this file consciously.
* **pipeline numerics** — a pp=2 MPMD pipeline seeded from one
  full-model init via ``split_params_by_stage`` reproduces the
  single-process GPT loss (measured bit-identical on the CPU backend;
  1e-6 is the documented tolerance), and its hot loop keeps the
  zero-classic-submission contract (telemetry-asserted inside
  ``run_step``).
* **gang chaos** — a 2-worker ShardedTrainer run survives a mid-run
  node preemption (graceful drain -> NODE_DRAINED -> SIGKILL, the spot
  termination shape): gang recovery resumes from the newest restorable
  sharded checkpoint and the per-(rank, step, pid) KV breadcrumbs bound
  re-executed work by the checkpoint interval (+1 interval when the
  newest shard set raced the evacuation sweep and restore fell back one
  chain entry).
"""

import collections
import threading
import time

import numpy as np

import pytest

import ray_tpu
from ray_tpu._private.jax_compat import PartitionSpec as P
from ray_tpu.train.sharded import layout
from ray_tpu.train.sharded.layout import (ShardingConfig, dryrun_plans,
                                          plan)


# ------------------------------------------------------------- golden layouts
def test_golden_fsdp_tp():
    """The headline bench layout: fsdp=2 x tp=2 on 4 devices."""
    p = plan(ShardingConfig(fsdp=2, tp=2), n_devices=4)
    assert p.mesh_shape == {"stage": 1, "data": 1, "fsdp": 2,
                            "context": 1, "tensor": 2}
    assert p.param_table() == {
        "token_embed": P("tensor", "fsdp"),
        "attn_qkv": P("fsdp", "tensor", None),
        "attn_kv": P("fsdp", "tensor", None),
        "attn_out": P("tensor", "fsdp"),
        "mlp_up": P("fsdp", "tensor"),
        "mlp_down": P("tensor", "fsdp"),
        "norm_scale": P(None),
        "lm_head": P("fsdp", "tensor"),
    }
    assert p.activation_table() == {
        "batch_tokens": P("fsdp", None),
        "hidden": P("fsdp", None, None),
        "logits": P("fsdp", None, "tensor"),
    }
    assert p.n_stages == 1 and p.devices_per_stage() == 4


def test_golden_full_stack():
    """All four in-mesh axes live: the tuple-axes ('data','fsdp') batch
    rule survives unpruned and context shards the sequence axis."""
    p = plan(ShardingConfig(dp=2, fsdp=2, cp=2, tp=2), n_devices=16)
    assert p.mesh_shape == {"stage": 1, "data": 2, "fsdp": 2,
                            "context": 2, "tensor": 2}
    t = p.activation_table()
    assert t["batch_tokens"] == P(("data", "fsdp"), None)
    assert t["hidden"] == P(("data", "fsdp"), "context", None)
    assert t["logits"] == P(("data", "fsdp"), "context", "tensor")
    assert p.param_table()["token_embed"] == P("tensor", "fsdp")


def test_golden_dp_only_degenerate():
    """Pure data parallelism: every param replicated, batch on 'data'."""
    p = plan(ShardingConfig(dp=8), n_devices=8)
    assert p.mesh_shape == {"stage": 1, "data": 8, "fsdp": 1,
                            "context": 1, "tensor": 1}
    for name, spec in p.param_table().items():
        assert all(ax is None for ax in spec), (name, spec)
    assert p.activation_table() == {
        "batch_tokens": P("data", None),
        "hidden": P("data", None, None),
        "logits": P("data", None, None),
    }


def test_golden_pp_only_degenerate():
    """pp-only MPMD: a 1-device mesh per stage, everything replicated —
    parallelism lives in the stage split, not the mesh."""
    p = plan(ShardingConfig(pp=2), n_devices=1)
    assert p.mesh_shape == {"stage": 1, "data": 1, "fsdp": 1,
                            "context": 1, "tensor": 1}
    assert p.n_stages == 2 and p.devices_per_stage(n_devices=2) == 1
    for table in (p.param_table(), p.activation_table()):
        for name, spec in table.items():
            assert all(ax is None for ax in spec), (name, spec)
    # remainder layers land on the EARLY stages (they also carry embed)
    assert p.layer_ranges(4) == [(0, 2), (2, 4)]
    assert p.layer_ranges(5) == [(0, 3), (3, 5)]
    with pytest.raises(ValueError):
        p.layer_ranges(1)


def test_spmd_pipeline_and_wildcard():
    """pp_style='spmd' makes pp a mesh axis; -1 absorbs the rest."""
    p = plan(ShardingConfig(dp=-1, pp=2, pp_style="spmd"), n_devices=8)
    assert p.mesh_shape == {"stage": 2, "data": 4, "fsdp": 1,
                            "context": 1, "tensor": 1}
    assert p.n_stages == 1  # spmd: no MPMD stage actors
    assert p.activation_table()["batch_tokens"] == P("data", None)


def test_config_validation():
    with pytest.raises(ValueError, match="at most one"):
        ShardingConfig(dp=-1, fsdp=-1)
    with pytest.raises(ValueError, match="pp_style"):
        ShardingConfig(pp_style="gpipe")
    with pytest.raises(ValueError, match="slices"):
        ShardingConfig(slices=0)
    with pytest.raises(ValueError, match="needs 4 devices"):
        plan(ShardingConfig(fsdp=2, tp=2), n_devices=8)
    with pytest.raises(ValueError, match="not divisible"):
        plan(ShardingConfig(dp=-1, tp=3), n_devices=8)
    with pytest.raises(ValueError, match="unknown mesh axis"):
        layout._shape_to_config({"rows": 2})


def test_mesh_authority_get_mesh():
    """get_mesh is THE mesh constructor (absorbed from jax_trainer):
    resolves through the planner, preserves the caller's axis subset,
    caches per loop thread."""
    from ray_tpu.train import jax_trainer

    assert jax_trainer.get_mesh is layout.get_mesh
    layout.set_loop_mesh_shape(None)
    try:
        m = layout.get_mesh({"data": 2, "fsdp": 4})
        assert m.axis_names == ("data", "fsdp")
        assert dict(m.shape) == {"data": 2, "fsdp": 4}
        assert layout.get_mesh({"data": 2, "fsdp": 4}) is m  # cached
        # the trainer-installed loop shape, wildcard resolved
        layout.set_loop_mesh_shape({"data": -1})
        m2 = layout.get_mesh()
        assert dict(m2.shape) == {"data": 8}
    finally:
        layout.set_loop_mesh_shape(None)


def test_dryrun_plans_accounting():
    """The MULTICHIP dryrun sweep: every named plan factors the device
    count exactly (per stage x stages)."""
    plans = dict(dryrun_plans(8))
    assert set(plans) == {"train", "pipeline_spmd", "moe_ep",
                          "hier_2slice"}
    for name, p in plans.items():
        total = p.devices_per_stage() * p.n_stages
        assert total == 8, (name, p.mesh_shape)
    assert plans["pipeline_spmd"].mesh_shape["stage"] == 2
    assert plans["hier_2slice"].config.slices == 2


# --------------------------------------------------------- pipeline numerics
def test_pipeline_matches_single_process(ray_start_regular):
    """A pp=2 pipeline seeded from ONE full-model init reproduces the
    single-process loss, then trains a step without a single classic
    task submission in the hot loop."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import GPT
    from ray_tpu.train.sharded.pipeline import (PipelineRunner,
                                                PipelineSpec,
                                                gpt_stage_specs, lm_loss,
                                                split_params_by_stage,
                                                synth_microbatches)

    spec = PipelineSpec(model="tiny", pp=2, microbatches=2,
                        microbatch_size=2, seq_len=16, steps=1, seed=3)
    cfg = spec.config()
    mbs = synth_microbatches(spec, cfg, 0)

    model = GPT(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.asarray(mbs[0]["tokens"]))
    params = nn.meta.unbox(variables["params"])
    ref = [float(lm_loss(model.apply({"params": params},
                                     jnp.asarray(mb["tokens"])),
                         jnp.asarray(mb["targets"])))
           for mb in mbs]

    stage_params = split_params_by_stage(params, gpt_stage_specs(cfg, 2))
    runner = PipelineRunner(spec, stage_params=stage_params)
    try:
        got = runner.forward_loss(mbs)
        # measured bit-identical on the CPU backend; 1e-6 is the
        # documented tolerance (docs/train_sharded.md)
        assert np.allclose(got, ref, rtol=0, atol=1e-6), (got, ref)
        out = runner.train(2)
        assert out["classic_submits_hot_loop"] in (None, 0.0)
        assert out["submissions_per_microbatch"] in (None, 0.0)
        assert np.isfinite(out["final_loss"])
        # the optimizer step actually moved the params
        p0 = runner.stage_params()
        assert any(
            not np.array_equal(a, b)
            for a, b in zip(jax.tree_util.tree_leaves(p0),
                            jax.tree_util.tree_leaves(stage_params)))
    finally:
        runner.shutdown()


def test_stage_split_covers_model():
    """split_params_by_stage partitions the full tree: stage scopes are
    disjoint and reassemble to every top-level scope exactly once."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import GPT, get_config
    from ray_tpu.train.sharded.pipeline import (gpt_stage_specs,
                                                split_params_by_stage)

    cfg = get_config("tiny")
    model = GPT(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    parts = split_params_by_stage(variables["params"],
                                  gpt_stage_specs(cfg, 2))
    assert "embed" in parts[0] and "embed" not in parts[1]
    assert "lm_head" in parts[1] and "lm_head" not in parts[0]
    n_layers = [jax.tree_util.tree_leaves(p["blocks"])[0].shape[0]
                for p in parts]
    assert sum(n_layers) == cfg.n_layers


# ----------------------------------------------------------------- gang chaos
def _wait_event(gcs, etype, timeout=60.0, **match):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        evs = gcs.call("list_cluster_events", {"type": etype})
        for ev in reversed(evs or []):
            if all(ev.get(k) == v for k, v in match.items()):
                return ev
        time.sleep(0.3)
    return None


def test_sharded_gang_survives_preemption(ray_start_cluster):
    """Chaos leg: drain+kill a gang node mid-run (the spot-termination
    shape: NODE_PREEMPTING grace, shard evacuation, SIGKILL at the
    NODE_DRAINED edge).  The trainer re-forms the gang on replacement
    capacity, restores the striped sharded checkpoint, and the KV
    breadcrumbs prove re-executed work stayed inside the bound."""
    from ray_tpu.air.config import FailureConfig, RunConfig
    from ray_tpu.runtime.core_worker import get_global_worker
    from ray_tpu.train.sharded import (ShardedRunConfig, ShardedTrainer,
                                       ShardingConfig)

    cluster = ray_start_cluster
    victim = cluster.add_node(resources={"CPU": 2, "slice": 2})
    cluster.add_node(resources={"CPU": 2, "slice": 2})
    cluster.wait_for_nodes(3)
    ray_tpu.init(num_cpus=0, address=cluster.address)
    gcs = get_global_worker().gcs

    tag = "t-sharded-chaos"
    interval = 2
    # fsdp x tp (the headline bench layout): batch shards over fsdp
    # only, so batch_per_worker=4 divides cleanly on the 8-device mesh
    run = ShardedRunConfig(
        sharding=ShardingConfig(fsdp=2, tp=4), model="tiny",
        num_workers=2, steps=10, batch_per_worker=4, seq_len=32,
        checkpoint_interval=interval, quantize="int8",
        async_grad_sync=True, step_sleep_s=0.5, kv_breadcrumbs=True)
    trainer = ShardedTrainer(
        run,
        run_config=RunConfig(name=tag,
                             failure_config=FailureConfig(max_failures=3)),
        resources_per_worker={"CPU": 1, "slice": 1}, tag=tag)

    state = {}

    def _preempt():
        # wait for the first post-checkpoint step (interval=2: step 1's
        # shards are in the KV), then drain the victim and SIGKILL at
        # the NODE_DRAINED edge — killing earlier loses the primaries
        # the survivors are supposed to inherit
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            steps = [int(k.split("/")[3])
                     for k in gcs.kv_keys(f"shardsteps/{tag}/")]
            if steps and max(steps) >= interval:
                break
            time.sleep(0.2)
        else:
            state["error"] = "never saw a post-checkpoint step"
            return
        gcs.call("drain_node", {"node_id": victim.node_id,
                                "grace_s": 30.0,
                                "reason": "chaos spot preemption"})
        if _wait_event(gcs, "NODE_DRAINED", timeout=90,
                       node_id=victim.node_id) is None:
            state["error"] = "drain never completed"
            return
        cluster.remove_node(victim)
        cluster.add_node(resources={"CPU": 2, "slice": 2})
        state["killed"] = True

    th = threading.Thread(target=_preempt, daemon=True)
    th.start()
    result = trainer.fit()
    th.join(timeout=300)
    assert state.get("killed"), state
    assert result.error is None, result.error
    assert result.metrics["step"] == run.steps - 1

    # exactly-once ledger from the per-(rank, step, pid) breadcrumbs
    per_rank = collections.defaultdict(list)
    pids = collections.defaultdict(set)
    for k in gcs.kv_keys(f"shardsteps/{tag}/"):
        _, _, rank, step_s, pid = k.split("/")
        per_rank[rank].append(int(step_s))
        pids[rank].add(pid)
    assert sorted(per_rank) == ["0", "1"]
    # the kill landed mid-run: at least one rank ran in two processes
    assert any(len(p) > 1 for p in pids.values()), dict(pids)
    for rank, steps in per_rank.items():
        counts = collections.Counter(steps)
        # every step executed at least once, none skipped
        assert sorted(counts) == list(range(run.steps)), (rank, counts)
        re_exec = sum(c - 1 for c in counts.values())
        # nominal bound: one checkpoint interval of lost work; +1
        # interval when the newest shard set raced the evacuation sweep
        # and restore fell back one chain entry (docs/train_sharded.md)
        assert re_exec <= 2 * interval, (rank, counts)
