"""Continuous-batching LLM engine + Serve LLM deployment.

The north-star serving path (BASELINE.md llama-3-8b row): requests are
admitted into free KV-cache slots mid-decode, so a slot-scheduled batch
must reproduce exactly what each request would generate alone
(greedy), interleave admissions, reuse slots, and ride a Serve replica.
CPU-sized model; the real-chip numbers live in benchmarks/serve_llm.py.
"""

import threading
import time

import pytest

import ray_tpu


def _tiny():
    import jax
    import jax.numpy as jnp
    from ray_tpu.models.configs import get_config
    from ray_tpu.models.gpt import GPT

    cfg = get_config("tiny")
    model = GPT(cfg, decode=True)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 1), jnp.int32))["params"]
    return cfg, params


@pytest.fixture(scope="module")
def tiny_engine_parts():
    return _tiny()


def test_slot_decode_matches_lone_generate(tiny_engine_parts):
    """Greedy decode through the slot engine == Generator.generate of the
    same prompt alone: the per-row position mask must make batch
    neighbors invisible."""
    import jax.numpy as jnp
    from ray_tpu.models.generate import Generator
    from ray_tpu.serve.llm_engine import LLMEngine

    cfg, params = tiny_engine_parts
    prompts = [[1, 2, 3], [7, 8, 9, 10, 11], [50, 60]]
    lone = Generator(cfg, params)
    expect = [
        [int(t) for t in lone.generate(jnp.asarray([p], jnp.int32),
                                       max_new_tokens=8,
                                       temperature=0.0)[0]]
        for p in prompts
    ]

    eng = LLMEngine(cfg, params, num_slots=4)
    try:
        results = [None] * len(prompts)
        threads = []
        for i, p in enumerate(prompts):
            def go(i=i, p=p):
                results[i] = eng.submit(p, max_new_tokens=8,
                                        temperature=0.0)
            t = threading.Thread(target=go)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=120)
        for i in range(len(prompts)):
            assert results[i] is not None
            assert results[i].tokens == expect[i], (
                f"slot decode diverged for prompt {i}")
            assert results[i].prompt_len == len(prompts[i])
    finally:
        eng.close()


def test_interleaved_admission_and_slot_reuse(tiny_engine_parts):
    """More requests than slots, submitted in two waves mid-decode: all
    complete, slots are reused, and occupancy shows real batching."""
    from ray_tpu.serve.llm_engine import LLMEngine

    cfg, params = tiny_engine_parts
    # block_size sized to the generations so occupancy measures overlap,
    # not block-tail junk
    eng = LLMEngine(cfg, params, num_slots=4, block_size=4)
    try:
        results = {}
        lock = threading.Lock()

        def go(rid, prompt, n):
            r = eng.submit(prompt, max_new_tokens=n, temperature=0.0)
            with lock:
                results[rid] = r

        threads = []
        # wave 1: 8 requests into 4 slots — the second 4 must wait for
        # evictions, proving admission happens mid-decode
        for i in range(8):
            t = threading.Thread(target=go,
                                 args=(i, [i + 1, i + 2], 6 + (i % 3)))
            t.start()
            threads.append(t)
        time.sleep(0.3)
        # wave 2 arrives while wave 1 decodes
        for i in range(8, 12):
            t = threading.Thread(target=go, args=(i, [i + 1], 4))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=180)
        assert sorted(results) == list(range(12))
        for i in range(8):
            assert len(results[i].tokens) == 6 + (i % 3)
            assert results[i].finish_reason == "length"
        for i in range(8, 12):
            assert len(results[i].tokens) == 4
        st = eng.stats.snapshot(eng.num_slots)
        assert st["requests_completed"] == 12
        assert st["prefills"] == 12
        # 12 requests through 4 slots: decode steps must have overlapped.
        # (Junk steps past eos / block tails count against occupancy, and
        # these generations are shorter than one block.)
        assert st["batch_occupancy"] > 0.25
    finally:
        eng.close()


def test_admission_wave_equals_cache_rows(tiny_engine_parts):
    """Regression: with num_slots=3 a 4-wide admission wave has the same
    leading shape as the 4-row global cache (3 slots + scratch) — the
    insert must still write the prompt K/V (axis by layout, not by shape
    mismatch), or every request decodes against a zeroed prompt."""
    import jax.numpy as jnp
    from ray_tpu.models.generate import Generator
    from ray_tpu.serve.llm_engine import LLMEngine

    cfg, params = tiny_engine_parts
    prompts = [[11, 12, 13], [21, 22], [31, 32, 33, 34]]
    lone = Generator(cfg, params)
    expect = [
        [int(t) for t in lone.generate(jnp.asarray([p], jnp.int32),
                                       max_new_tokens=6,
                                       temperature=0.0)[0]]
        for p in prompts
    ]
    eng = LLMEngine(cfg, params, num_slots=3, block_size=4)
    try:
        results = [None] * 3
        threads = []
        for i, p in enumerate(prompts):
            def go(i=i, p=p):
                results[i] = eng.submit(p, max_new_tokens=6,
                                        temperature=0.0)
            t = threading.Thread(target=go)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=120)
        for i in range(3):
            assert results[i] is not None
            assert results[i].tokens == expect[i]
    finally:
        eng.close()


def test_engine_eos_and_errors(tiny_engine_parts):
    """eos stops a row without touching its neighbors; an over-long
    prompt fails just that request."""
    from ray_tpu.serve.llm_engine import LLMEngine

    cfg, params = tiny_engine_parts
    eng = LLMEngine(cfg, params, num_slots=2, max_prompt_len=16)
    try:
        with pytest.raises(ValueError):
            eng.submit(list(range(17)), max_new_tokens=4)
        r = eng.submit([3, 4, 5], max_new_tokens=200)  # > max_seq_len cap
        assert r.finish_reason == "length"
        assert len(r.tokens) <= cfg.max_seq_len
        # pick the first greedily generated token as a fake eos: the
        # request must stop right there
        probe = eng.submit([3, 4, 5], max_new_tokens=4, temperature=0.0)
        eos = probe.tokens[0]
        r2 = eng.submit([3, 4, 5], max_new_tokens=64, temperature=0.0,
                        eos_id=eos)
        assert r2.finish_reason == "eos"
        assert r2.tokens == [eos]
    finally:
        eng.close()


def test_streaming_on_token(tiny_engine_parts):
    """on_token fires once per generated token, in order."""
    from ray_tpu.serve.llm_engine import LLMEngine

    cfg, params = tiny_engine_parts
    eng = LLMEngine(cfg, params, num_slots=2)
    try:
        seen = []
        r = eng.submit([9, 9, 9], max_new_tokens=5, temperature=0.0,
                       on_token=seen.append)
        assert seen == r.tokens
    finally:
        eng.close()


def _disagg_app(**kw):
    """2-pool tiny app with fast-compile shapes shared by the disagg
    tests; kwargs override decode-pool / shared engine settings."""
    from ray_tpu import serve

    base = dict(preset="tiny", disaggregated=True, num_replicas=2,
                prefill_replicas=2, num_slots=4, block_size=4,
                page_size=8, max_concurrent_queries=32)
    base.update(kw)
    return serve.llm.build_app(**base)


def _stream_all(handle, requests, timeout=300):
    """Drive N concurrent streams through a DisaggHandle; returns
    (tokens, summary, retries) per request, in order."""
    import asyncio

    async def one(req):
        toks, summary, retries = [], None, 0
        async for item in handle.stream(req):
            if "token" in item:
                toks.append(item["token"])
            elif "retry" in item:
                retries = item["retry"]
            else:
                summary = item
        return toks, summary, retries

    async def main():
        return await asyncio.gather(*[one(r) for r in requests])

    return asyncio.run(asyncio.wait_for(main(), timeout=timeout))


def test_disagg_streaming_smoke(ray_start_regular, tiny_engine_parts):
    """Tier-1 disaggregated smoke (docs/serve_disagg.md): 2 prefill + 2
    decode replicas, 32 concurrent streaming requests.  Greedy tokens
    must match lone generation EXACTLY across the export -> transfer ->
    import path, prefill replicas must never decode, decode replicas
    must never prefill."""
    import jax.numpy as jnp

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.models.generate import Generator
    from ray_tpu.serve.controller import REPLICA_PREFIX, SERVE_NAMESPACE

    cfg, params = tiny_engine_parts
    prompts = [[1, 2, 3], [7, 8, 9, 10, 11], [50, 60], [9] * 17]
    lone = Generator(cfg, params)
    expect = {
        tuple(p): [int(t) for t in lone.generate(
            jnp.asarray([p], jnp.int32), max_new_tokens=6,
            temperature=0.0)[0]]
        for p in prompts
    }

    serve.start()
    serve.run(_disagg_app())
    try:
        handle = serve.llm.disagg_handle("tiny")
        reqs = [{"prompt": prompts[i % len(prompts)],
                 "max_new_tokens": 6, "temperature": 0.0}
                for i in range(32)]
        outs = _stream_all(handle, reqs)
        for req, (toks, summary, _) in zip(reqs, outs):
            assert toks == expect[tuple(req["prompt"])], (req, toks)
            assert summary["finish_reason"] == "length"
            assert summary["num_tokens"] == 6
        # pool separation: every prefill came from the prefill pool,
        # every decode step from the decode pool
        st = serve.status()
        roles = {"prefill": [], "decode": []}
        for name, s in st.items():
            role = name.rsplit("-", 1)[-1]
            for tag in s["replicas"]:
                a = ray_tpu.get_actor(REPLICA_PREFIX + tag,
                                      namespace=SERVE_NAMESPACE)
                roles[role].append(ray_tpu.get(
                    a.handle_request.remote("stats", (), {}), timeout=60))
        assert sum(r["prefills"] for r in roles["prefill"]) == 32
        assert sum(r["exports"] for r in roles["prefill"]) == 32
        assert all(r["steps"] == 0 for r in roles["prefill"])
        assert sum(r["imports"] for r in roles["decode"]) == 32
        assert all(r["prefills"] == 0 for r in roles["decode"])
        # the decode pool saw BOTH replicas (queue-depth p2c routing)
        assert sum(1 for r in roles["decode"] if r["imports"] > 0) == 2
        # handoffs are visible as HANDOFF timeline slices on both the
        # exporting and importing replicas' rows (docs/serve_disagg.md)
        from ray_tpu.experimental.state.api import timeline
        deadline = time.monotonic() + 30
        stages = set()
        while time.monotonic() < deadline and \
                stages != {"export", "import"}:
            stages = {e["args"]["stage"] for e in timeline()
                      if e.get("cat") == "handoff"}
            time.sleep(0.5)
        assert stages == {"export", "import"}, stages
    finally:
        serve.shutdown()


def test_disagg_prefill_death_after_handoff(ray_start_regular):
    """A prefill replica dying AFTER its handoff was imported is
    invisible: the stream completes entirely from the KV object, with
    no retry."""
    import asyncio

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.controller import REPLICA_PREFIX, SERVE_NAMESPACE

    serve.start()
    serve.run(_disagg_app(prefill_replicas=1, num_replicas=1))
    try:
        handle = serve.llm.disagg_handle("tiny")

        async def run():
            toks, summary, retries = [], None, 0
            killed = False
            async for item in handle.stream(
                    {"prompt": [5, 6, 7], "max_new_tokens": 24,
                     "temperature": 0.0}):
                if "token" in item:
                    toks.append(item["token"])
                elif "retry" in item:
                    retries = item["retry"]
                else:
                    summary = item
                if len(toks) >= 3 and not killed:
                    # >= 2 decoded tokens: the handoff was imported;
                    # the prefill replica is now irrelevant
                    killed = True
                    st = serve.status()["llm-tiny-prefill"]
                    for tag in st["replicas"]:
                        a = ray_tpu.get_actor(REPLICA_PREFIX + tag,
                                              namespace=SERVE_NAMESPACE)
                        ray_tpu.kill(a)
            return toks, summary, retries, killed

        toks, summary, retries, killed = asyncio.run(
            asyncio.wait_for(run(), timeout=240))
        assert killed, "stream finished before the kill fired"
        assert retries == 0, "prefill death after handoff must be invisible"
        assert len(toks) == 24
        assert summary["finish_reason"] == "length"
    finally:
        serve.shutdown()


def test_disagg_decode_death_mid_stream(ray_start_regular):
    """Killing the decode replica mid-stream surfaces a retry marker
    and the stream still completes (re-prefill + resume: no duplicated
    tokens, greedy suffix identical)."""
    import asyncio

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.controller import REPLICA_PREFIX, SERVE_NAMESPACE

    serve.start()
    serve.run(_disagg_app(prefill_replicas=1, num_replicas=2))
    try:
        handle = serve.llm.disagg_handle("tiny")
        probe = _stream_all(handle, [{"prompt": [5, 6, 7],
                                      "max_new_tokens": 24,
                                      "temperature": 0.0}])[0][0]

        async def run():
            toks, summary, retries = [], None, 0
            killed = False
            async for item in handle.stream(
                    {"prompt": [5, 6, 7], "max_new_tokens": 24,
                     "temperature": 0.0}):
                if "token" in item:
                    toks.append(item["token"])
                elif "retry" in item:
                    retries = item["retry"]
                else:
                    summary = item
                if len(toks) >= 3 and not killed:
                    killed = True
                    # kill the decode replica serving THIS stream (the
                    # one with an ongoing request)
                    st = serve.status()["llm-tiny-decode"]
                    for tag in st["replicas"]:
                        a = ray_tpu.get_actor(REPLICA_PREFIX + tag,
                                              namespace=SERVE_NAMESPACE)
                        m = ray_tpu.get(a.get_metrics.remote(),
                                        timeout=30)
                        if m["num_ongoing"] > 0:
                            ray_tpu.kill(a)
            return toks, summary, retries, killed

        toks, summary, retries, killed = asyncio.run(
            asyncio.wait_for(run(), timeout=240))
        assert killed, "stream finished before the kill fired"
        assert retries >= 1, "decode death must surface a retry marker"
        assert toks == probe, (toks, probe)   # resumed, not restarted
        assert summary["finish_reason"] == "length"
    finally:
        serve.shutdown()


def test_disagg_pool_full_rejection_requeues(ray_start_regular):
    """Import admission under a pool sized for ONE resident request:
    the second import FIFO-waits in the engine (pages free as the
    first completes — no polling, no wedge), and the third hits the
    import_queue_max cap and is REJECTED (typed, synchronous), then
    re-queued by the decode replica's retry loop until the queue
    drains.  All three requests complete."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.controller import REPLICA_PREFIX, SERVE_NAMESPACE

    serve.start()
    # decode pool sized for exactly ONE request: prompt 3 + 96 new
    # tokens at page_size 8 -> 13 pages; pool = scratch + 13.  Wait
    # queue capped at ONE import, so a third concurrent request must
    # take the rejection path.
    serve.run(_disagg_app(prefill_replicas=1, num_replicas=1,
                          kv_pool_pages=14, import_queue_max=1,
                          prefill_server_kwargs={"kv_pool_pages": None,
                                                 "import_queue_max":
                                                     None}))
    try:
        handle = serve.llm.disagg_handle("tiny")
        req = {"prompt": [5, 6, 7], "max_new_tokens": 96,
               "temperature": 0.0}
        outs = [None, None, None]
        errs = []

        def drive(i, delay):
            try:
                time.sleep(delay)
                outs[i] = _stream_all(handle, [req])[0]
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=drive, args=(i, 0.8 * i))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not errs, errs
        assert all(o is not None and len(o[0]) == 96 for o in outs), \
            [(o and len(o[0])) for o in outs]
        # the third stream's import was queue-cap-rejected at least
        # once while the first held the pool and the second the queue
        st = serve.status()["llm-tiny-decode"]
        rejects = 0
        for tag in st["replicas"]:
            a = ray_tpu.get_actor(REPLICA_PREFIX + tag,
                                  namespace=SERVE_NAMESPACE)
            s = ray_tpu.get(a.handle_request.remote("stats", (), {}),
                            timeout=60)
            rejects += s["import_rejects"]
        assert rejects >= 1, "no import was ever queue-cap-rejected"
    finally:
        serve.shutdown()


def test_disagg_handoff_quantize_numerics_gate(tiny_engine_parts):
    """``serve_handoff_quantize`` ships the cross-host KV handoff as
    int8 wire blocks (util/collective/quant.Int8Codec, ~3.9x smaller)
    and dequantizes before import.  The gate: greedy tokens must STILL
    match lone generation EXACTLY — per-block scaling keeps the KV
    error ~0.4% of blockmax, far under what flips a tiny-model argmax —
    and the prefill pool must account the bytes it did NOT ship on
    ray_tpu_serve_handoff_saved_bytes."""
    import jax.numpy as jnp

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.models.generate import Generator

    cfg, params = tiny_engine_parts
    prompts = [[1, 2, 3], [7, 8, 9, 10, 11], [50, 60], [9] * 17]
    lone = Generator(cfg, params)
    expect = {
        tuple(p): [int(t) for t in lone.generate(
            jnp.asarray([p], jnp.int32), max_new_tokens=6,
            temperature=0.0)[0]]
        for p in prompts
    }

    # the knob rides system_config so replica processes inherit it
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024,
                 system_config={"serve_handoff_quantize": True})
    try:
        serve.start()
        serve.run(_disagg_app(prefill_replicas=1, num_replicas=1))
        handle = serve.llm.disagg_handle("tiny")
        reqs = [{"prompt": prompts[i % len(prompts)],
                 "max_new_tokens": 6, "temperature": 0.0}
                for i in range(8)]
        outs = _stream_all(handle, reqs)
        for req, (toks, summary, _) in zip(reqs, outs):
            assert toks == expect[tuple(req["prompt"])], (req, toks)
            assert summary["finish_reason"] == "length"
        # the quantized wire actually carried the handoffs: saved bytes
        # (raw - encoded) accumulate on the prefill replica and flush
        # to the cluster metric plane
        from ray_tpu.experimental.state.api import list_metrics
        deadline = time.monotonic() + 60
        saved = 0.0
        while time.monotonic() < deadline and saved <= 0:
            saved = sum(
                r.get("value", 0.0) for r in
                list_metrics("ray_tpu_serve_handoff_saved_bytes"))
            if saved <= 0:
                time.sleep(0.5)
        assert saved > 0, "no handoff bytes were saved (codec never ran)"
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()


@pytest.mark.slow
def test_serve_disagg_load_harness_1k():
    """The full >= 1k-connection closed-loop A/B (benchmarks/
    serve_disagg.py) with the MICROBENCH acceptance bars: p99 TTFT
    >= 2x better disaggregated, aggregate tokens/s within 10%, handoff
    p50 under one decode block's wall time, zero stream errors.
    ~10 min; tier-1 runs the fast smoke above instead."""
    from benchmarks.serve_disagg import run_ab

    rows = run_ab(connections=1000, new_tokens=96, duration_s=90.0)
    ab = rows[-1]
    assert ab["errors"] == 0
    assert ab["connections"] >= 1000
    assert ab["ttft_p99_ratio"] >= 2.0, ab
    assert ab["tokens_per_s_ratio"] >= 0.9, ab
    assert ab["handoff_total_p50_ms"] < ab["decode_block_wall_p50_ms"], ab


def test_serve_llm_deployment(ray_start_regular):
    """End-to-end: a Serve replica owning an engine serves ≥8 concurrent
    requests through the handle with interleaved admission."""
    from ray_tpu import serve

    serve.start()
    app = serve.llm.build_app(preset="tiny", num_slots=4,
                              max_concurrent_queries=32)
    handle = serve.run(app, name="llm")
    try:
        refs = [handle.remote({"prompt": [i + 1, i + 2],
                               "max_new_tokens": 5 + (i % 4)})
                for i in range(10)]
        outs = ray_tpu.get(refs, timeout=300)
        for i, out in enumerate(outs):
            assert len(out["tokens"]) == 5 + (i % 4)
            assert out["prompt_len"] == 2
            assert out["latency_s"] > 0
    finally:
        serve.shutdown()
