"""Continuous-batching LLM engine + Serve LLM deployment.

The north-star serving path (BASELINE.md llama-3-8b row): requests are
admitted into free KV-cache slots mid-decode, so a slot-scheduled batch
must reproduce exactly what each request would generate alone
(greedy), interleave admissions, reuse slots, and ride a Serve replica.
CPU-sized model; the real-chip numbers live in benchmarks/serve_llm.py.
"""

import threading
import time

import pytest

import ray_tpu


def _tiny():
    import jax
    import jax.numpy as jnp
    from ray_tpu.models.configs import get_config
    from ray_tpu.models.gpt import GPT

    cfg = get_config("tiny")
    model = GPT(cfg, decode=True)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 1), jnp.int32))["params"]
    return cfg, params


@pytest.fixture(scope="module")
def tiny_engine_parts():
    return _tiny()


def test_slot_decode_matches_lone_generate(tiny_engine_parts):
    """Greedy decode through the slot engine == Generator.generate of the
    same prompt alone: the per-row position mask must make batch
    neighbors invisible."""
    import jax.numpy as jnp
    from ray_tpu.models.generate import Generator
    from ray_tpu.serve.llm_engine import LLMEngine

    cfg, params = tiny_engine_parts
    prompts = [[1, 2, 3], [7, 8, 9, 10, 11], [50, 60]]
    lone = Generator(cfg, params)
    expect = [
        [int(t) for t in lone.generate(jnp.asarray([p], jnp.int32),
                                       max_new_tokens=8,
                                       temperature=0.0)[0]]
        for p in prompts
    ]

    eng = LLMEngine(cfg, params, num_slots=4)
    try:
        results = [None] * len(prompts)
        threads = []
        for i, p in enumerate(prompts):
            def go(i=i, p=p):
                results[i] = eng.submit(p, max_new_tokens=8,
                                        temperature=0.0)
            t = threading.Thread(target=go)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=120)
        for i in range(len(prompts)):
            assert results[i] is not None
            assert results[i].tokens == expect[i], (
                f"slot decode diverged for prompt {i}")
            assert results[i].prompt_len == len(prompts[i])
    finally:
        eng.close()


def test_interleaved_admission_and_slot_reuse(tiny_engine_parts):
    """More requests than slots, submitted in two waves mid-decode: all
    complete, slots are reused, and occupancy shows real batching."""
    from ray_tpu.serve.llm_engine import LLMEngine

    cfg, params = tiny_engine_parts
    # block_size sized to the generations so occupancy measures overlap,
    # not block-tail junk
    eng = LLMEngine(cfg, params, num_slots=4, block_size=4)
    try:
        results = {}
        lock = threading.Lock()

        def go(rid, prompt, n):
            r = eng.submit(prompt, max_new_tokens=n, temperature=0.0)
            with lock:
                results[rid] = r

        threads = []
        # wave 1: 8 requests into 4 slots — the second 4 must wait for
        # evictions, proving admission happens mid-decode
        for i in range(8):
            t = threading.Thread(target=go,
                                 args=(i, [i + 1, i + 2], 6 + (i % 3)))
            t.start()
            threads.append(t)
        time.sleep(0.3)
        # wave 2 arrives while wave 1 decodes
        for i in range(8, 12):
            t = threading.Thread(target=go, args=(i, [i + 1], 4))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=180)
        assert sorted(results) == list(range(12))
        for i in range(8):
            assert len(results[i].tokens) == 6 + (i % 3)
            assert results[i].finish_reason == "length"
        for i in range(8, 12):
            assert len(results[i].tokens) == 4
        st = eng.stats.snapshot(eng.num_slots)
        assert st["requests_completed"] == 12
        assert st["prefills"] == 12
        # 12 requests through 4 slots: decode steps must have overlapped.
        # (Junk steps past eos / block tails count against occupancy, and
        # these generations are shorter than one block.)
        assert st["batch_occupancy"] > 0.25
    finally:
        eng.close()


def test_admission_wave_equals_cache_rows(tiny_engine_parts):
    """Regression: with num_slots=3 a 4-wide admission wave has the same
    leading shape as the 4-row global cache (3 slots + scratch) — the
    insert must still write the prompt K/V (axis by layout, not by shape
    mismatch), or every request decodes against a zeroed prompt."""
    import jax.numpy as jnp
    from ray_tpu.models.generate import Generator
    from ray_tpu.serve.llm_engine import LLMEngine

    cfg, params = tiny_engine_parts
    prompts = [[11, 12, 13], [21, 22], [31, 32, 33, 34]]
    lone = Generator(cfg, params)
    expect = [
        [int(t) for t in lone.generate(jnp.asarray([p], jnp.int32),
                                       max_new_tokens=6,
                                       temperature=0.0)[0]]
        for p in prompts
    ]
    eng = LLMEngine(cfg, params, num_slots=3, block_size=4)
    try:
        results = [None] * 3
        threads = []
        for i, p in enumerate(prompts):
            def go(i=i, p=p):
                results[i] = eng.submit(p, max_new_tokens=6,
                                        temperature=0.0)
            t = threading.Thread(target=go)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=120)
        for i in range(3):
            assert results[i] is not None
            assert results[i].tokens == expect[i]
    finally:
        eng.close()


def test_engine_eos_and_errors(tiny_engine_parts):
    """eos stops a row without touching its neighbors; an over-long
    prompt fails just that request."""
    from ray_tpu.serve.llm_engine import LLMEngine

    cfg, params = tiny_engine_parts
    eng = LLMEngine(cfg, params, num_slots=2, max_prompt_len=16)
    try:
        with pytest.raises(ValueError):
            eng.submit(list(range(17)), max_new_tokens=4)
        r = eng.submit([3, 4, 5], max_new_tokens=200)  # > max_seq_len cap
        assert r.finish_reason == "length"
        assert len(r.tokens) <= cfg.max_seq_len
        # pick the first greedily generated token as a fake eos: the
        # request must stop right there
        probe = eng.submit([3, 4, 5], max_new_tokens=4, temperature=0.0)
        eos = probe.tokens[0]
        r2 = eng.submit([3, 4, 5], max_new_tokens=64, temperature=0.0,
                        eos_id=eos)
        assert r2.finish_reason == "eos"
        assert r2.tokens == [eos]
    finally:
        eng.close()


def test_streaming_on_token(tiny_engine_parts):
    """on_token fires once per generated token, in order."""
    from ray_tpu.serve.llm_engine import LLMEngine

    cfg, params = tiny_engine_parts
    eng = LLMEngine(cfg, params, num_slots=2)
    try:
        seen = []
        r = eng.submit([9, 9, 9], max_new_tokens=5, temperature=0.0,
                       on_token=seen.append)
        assert seen == r.tokens
    finally:
        eng.close()


def test_serve_llm_deployment(ray_start_regular):
    """End-to-end: a Serve replica owning an engine serves ≥8 concurrent
    requests through the handle with interleaved admission."""
    from ray_tpu import serve

    serve.start()
    app = serve.llm.build_app(preset="tiny", num_slots=4,
                              max_concurrent_queries=32)
    handle = serve.run(app, name="llm")
    try:
        refs = [handle.remote({"prompt": [i + 1, i + 2],
                               "max_new_tokens": 5 + (i % 4)})
                for i in range(10)]
        outs = ray_tpu.get(refs, timeout=300)
        for i, out in enumerate(outs):
            assert len(out["tokens"]) == 5 + (i % 4)
            assert out["prompt_len"] == 2
            assert out["latency_s"] > 0
    finally:
        serve.shutdown()
