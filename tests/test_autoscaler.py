"""Autoscaler tests (cf. reference python/ray/tests/test_resource_demand_scheduler.py
and test_autoscaler_fake_multinode.py)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (AutoscalerConfig, LoadMetrics, NodeTypeConfig,
                                ResourceDemandScheduler, StandardAutoscaler,
                                binpack_residual, load_config)
from ray_tpu.autoscaler.load_metrics import NodeView
from ray_tpu.autoscaler.node_provider import InMemoryNodeProvider
from ray_tpu.autoscaler.tpu_provider import TpuPodSliceProvider


def make_config(**kw):
    return load_config({
        "cluster_name": "t",
        "max_workers": kw.pop("max_workers", 8),
        "idle_timeout_s": kw.pop("idle_timeout_s", 300),
        "provider": {"type": "mem"},
        "available_node_types": kw.pop("types", {
            "cpu4": {"resources": {"CPU": 4}, "max_workers": 8},
        }),
        **kw,
    })


def view(node_id, resources, available=None, idle_s=0.0, labels=None):
    return NodeView(node_id=node_id, resources=dict(resources),
                    available=dict(available
                                   if available is not None else resources),
                    labels=labels or {}, alive=True, idle_s=idle_s)


def test_binpack_residual():
    free = [{"CPU": 4}, {"CPU": 2}]
    demands = [{"CPU": 2}] * 4
    assert binpack_residual(free, demands) == [{"CPU": 2}]
    assert binpack_residual([], [{"CPU": 1}]) == [{"CPU": 1}]
    # resource the capacity lacks entirely
    assert binpack_residual([{"CPU": 8}], [{"TPU": 1}]) == [{"TPU": 1}]


def test_demand_launches_best_fit_type():
    cfg = make_config(types={
        "cpu4": {"resources": {"CPU": 4}, "max_workers": 8},
        "tpu-host": {"resources": {"TPU": 4, "CPU": 8}, "max_workers": 8},
    })
    sched = ResourceDemandScheduler(cfg)
    # CPU-only demand should pick the CPU type, not burn a TPU host
    out = sched.get_nodes_to_launch([{"CPU": 4}] * 2, [], {})
    assert out == {"cpu4": 2}
    # TPU demand must pick the TPU type
    out = sched.get_nodes_to_launch([{"TPU": 4}], [], {})
    assert out == {"tpu-host": 1}


def test_existing_capacity_absorbs_demand():
    cfg = make_config()
    sched = ResourceDemandScheduler(cfg)
    out = sched.get_nodes_to_launch([{"CPU": 2}] * 2, [{"CPU": 4}], {})
    assert out == {}


def test_min_and_max_workers():
    cfg = make_config(types={
        "cpu4": {"resources": {"CPU": 4}, "min_workers": 2, "max_workers": 3},
    })
    sched = ResourceDemandScheduler(cfg)
    # min_workers honored with zero demand
    assert sched.get_nodes_to_launch([], [], {}) == {"cpu4": 2}
    # cap at per-type max_workers despite huge demand
    out = sched.get_nodes_to_launch([{"CPU": 4}] * 10, [], {"cpu4": 2})
    assert out == {"cpu4": 1}
    # global max_workers caps too
    cfg2 = make_config(max_workers=1)
    out = ResourceDemandScheduler(cfg2).get_nodes_to_launch(
        [{"CPU": 4}] * 10, [], {})
    assert out == {"cpu4": 1}


def test_tpu_slice_is_atomic_unit():
    """A v4-32-style slice (4 hosts x TPU:4) launches as ONE unit and its
    whole-slice resources satisfy a 16-chip demand."""
    cfg = make_config(types={
        "v4-32": {"resources": {"TPU": 4, "CPU": 8}, "hosts_per_node": 4,
                  "max_workers": 2},
    })
    sched = ResourceDemandScheduler(cfg)
    out = sched.get_nodes_to_launch([{"TPU": 4}] * 4, [], {})
    assert out == {"v4-32": 1}
    # 8 host-demands -> 2 slices
    out = sched.get_nodes_to_launch([{"TPU": 4}] * 8, [], {})
    assert out == {"v4-32": 2}


def test_infeasible_demand_does_not_spin():
    cfg = make_config()
    sched = ResourceDemandScheduler(cfg)
    assert sched.get_nodes_to_launch([{"GPU": 1}], [], {}) == {}


def test_idle_termination_respects_min_workers_and_slices():
    cfg = make_config(idle_timeout_s=10, types={
        "cpu4": {"resources": {"CPU": 4}, "min_workers": 1, "max_workers": 4},
    })
    provider = InMemoryNodeProvider({"type": "mem"})
    auto = StandardAutoscaler(cfg, provider)
    a = provider.create_node("cpu4", {}, {"CPU": 4}, 1, {})
    b = provider.create_node("cpu4", {}, {"CPU": 4}, 1, {})
    provider.mark_running(a.node_id)
    provider.mark_running(b.node_id)
    lm = LoadMetrics(nodes=[
        view("ra", {"CPU": 4}, idle_s=100,
             labels={"autoscaler-node-id": a.node_id}),
        view("rb", {"CPU": 4}, idle_s=100,
             labels={"autoscaler-node-id": b.node_id}),
    ])
    status = auto.update(lm)
    # exactly one terminated: min_workers=1 keeps the other
    assert len(status["terminated"]) == 1
    # a busy host keeps its whole slice alive
    c = provider.create_node("cpu4", {}, {"CPU": 4}, 2, {})
    provider.mark_running(c.node_id)
    lm2 = LoadMetrics(nodes=[
        view("rc0", {"CPU": 4}, idle_s=100,
             labels={"autoscaler-node-id": c.node_id}),
        view("rc1", {"CPU": 4}, idle_s=1,
             labels={"autoscaler-node-id": c.node_id}),
    ])
    status = auto.update(lm2)
    assert c.node_id not in status["terminated"]


def test_autoscaler_launches_for_pending_demand():
    cfg = make_config()
    provider = InMemoryNodeProvider({"type": "mem"})
    auto = StandardAutoscaler(cfg, provider)
    lm = LoadMetrics(nodes=[view("head", {"CPU": 1}, available={"CPU": 0})],
                     pending_demand=[{"CPU": 4}])
    status = auto.update(lm)
    assert len(status["launched"]) == 1
    # idempotent: pending launch counts against further demand
    status = auto.update(lm)
    assert status["launched"] == []


def test_batching_provider_one_patch_per_cycle():
    """kuberay-style integration: N scaling decisions in a cycle become
    ONE declarative patch an operator reconciles (reference
    batching_node_provider.py semantics)."""
    from ray_tpu.autoscaler.batching_node_provider import (
        InProcessOperator, KubeRayStyleProvider)
    from ray_tpu.autoscaler.node_provider import NodeRecord

    seq = [0]

    def spawn_host(node_type):
        seq[0] += 1
        return NodeRecord(node_id=f"w{seq[0]}", node_type=node_type,
                          state="running")

    op = InProcessOperator(spawn_host)
    provider = KubeRayStyleProvider({"type": "kuberay", "operator": op},
                                    "t")
    try:
        cfg = make_config(types={
            "cpu4": {"resources": {"CPU": 4}, "max_workers": 8}},
            upscaling_speed=99)  # let one tick stage all 3 launches
        auto = StandardAutoscaler(cfg, provider)
        # demand worth 3 nodes -> 3 create_node decisions, zero patches yet
        lm = LoadMetrics(nodes=[view("head", {"CPU": 1},
                                     available={"CPU": 0})],
                         pending_demand=[{"CPU": 4}] * 3)
        status = auto.update(lm)
        assert len(status["launched"]) == 3
        assert op.patch_count == 0  # mutations only staged so far
        # next cycle submits exactly one batched patch; operator
        # reconciles all 3 workers from it
        auto.update(LoadMetrics(nodes=[view("head", {"CPU": 1})]))
        assert op.patch_count == 1

        def all_up():
            return len(op.nodes()) == 3
        deadline = time.monotonic() + 10
        while not all_up() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert all_up()

        # scale down: idle workers leave via workers_to_delete, again one
        # patch for the whole decision set
        recs = provider.non_terminated_nodes()
        assert len(recs) == 3
        lm_idle = LoadMetrics(nodes=[
            view(f"r{r.node_id}", {"CPU": 4}, idle_s=10_000,
                 labels={"autoscaler-node-id": r.node_id}) for r in recs])
        patches_before = op.patch_count
        status = auto.update(lm_idle)
        assert len(status["terminated"]) == 3
        assert not provider.safe_to_scale  # deletes not reconciled yet
        provider.non_terminated_nodes()   # next cycle: submit
        assert op.patch_count == patches_before + 1
        deadline = time.monotonic() + 10
        while op.nodes() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert op.nodes() == {}
        provider.non_terminated_nodes()
        assert provider.safe_to_scale
    finally:
        op.stop()


def test_tpu_provider_dry_run_records_gcloud_calls():
    p = TpuPodSliceProvider({"type": "tpu", "project": "proj",
                             "zone": "us-central2-b", "dry_run": True})
    rec = p.create_node("v4-32", {"accelerator_type": "v4-32"},
                        {"TPU": 4}, 4, {})
    assert rec.state == "running"
    assert any("create" in c for c in p.calls[0])
    assert "--accelerator-type" in p.calls[0]
    p.terminate_node(rec.node_id)
    assert p.non_terminated_nodes() == []
    assert any("delete" in c for c in p.calls[1])
    # topology mismatch rejected (slice atomicity check)
    with pytest.raises(ValueError):
        p.create_node("v4-32", {"accelerator_type": "v4-32"},
                      {"TPU": 4}, 2, {})


def test_load_metrics_from_gcs_snapshot():
    lm = LoadMetrics.from_gcs_snapshot([
        {"node_id": "a", "resources": {"CPU": 4}, "available": {"CPU": 1},
         "labels": {}, "alive": True, "idle_s": 3.0,
         "load": [{"shape": {"CPU": 2}, "count": 3}]},
        {"node_id": "b", "resources": {"CPU": 4}, "available": {"CPU": 4},
         "labels": {}, "alive": False, "idle_s": 0.0, "load": []},
    ])
    assert len(lm.pending_demand) == 3
    assert len(lm.alive_nodes()) == 1
    assert lm.summary()["total"] == {"CPU": 4}


def test_event_driven_preemption_replacement():
    """ISSUE 15 satellite: the monitor consumes NODE_PREEMPTING events
    (the event plane, not polling) and requests a slice-atomic
    replacement through the provider WHILE the doomed unit is still
    draining; the unit's own NODE_DEAD must not double-replace, and
    idle terminations initiated by the autoscaler never trigger a
    replacement (their NODE_DEAD events are self-inflicted)."""
    from ray_tpu.cluster_utils import AutoscalingCluster
    cluster = AutoscalingCluster({
        "max_workers": 4,
        "idle_timeout_s": 3600,
        "available_node_types": {
            "cpu2": {"resources": {"CPU": 2}, "min_workers": 1,
                     "max_workers": 3},
        },
    }, head_resources={"CPU": 1})
    try:
        ray_tpu.init(address=cluster.address)
        provider = cluster.monitor.provider
        from ray_tpu.runtime.core_worker import get_global_worker
        gcs = get_global_worker().gcs
        # wait for the min_workers unit to register with the GCS
        deadline = time.monotonic() + 120
        unit = None
        while time.monotonic() < deadline:
            labeled = [n for n in gcs.call("list_nodes")
                       if n.get("alive") and (n.get("labels") or {})
                       .get("autoscaler-node-id")]
            if labeled:
                unit = labeled[0]["labels"]["autoscaler-node-id"]
                break
            time.sleep(0.5)
        assert unit, "min_workers unit never registered"

        drained = provider.inject_preemption(unit, grace_s=4.0)
        assert drained, "preemption notice reached no raylet"

        # the replacement launches off the event, during the grace
        # window (the preempted unit is typically still alive)
        deadline = time.monotonic() + 60
        repl = []
        while time.monotonic() < deadline:
            repl = [r for r in provider.non_terminated_nodes()
                    if r.node_id != unit]
            if repl:
                break
            time.sleep(0.3)
        assert repl, "no replacement unit launched from the event"

        evs = gcs.call("list_cluster_events", {"type": "NODE_PREEMPTING"})
        assert evs, "no NODE_PREEMPTING event recorded"

        # stability: once the unit dies, NODE_DEAD must not launch a
        # second replacement for the same unit
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if all(r.node_id != unit
                   for r in provider.non_terminated_nodes()):
                break
            time.sleep(0.5)
        for _ in range(6):   # several monitor ticks
            time.sleep(0.5)
        others = {r.node_id for r in provider.non_terminated_nodes()}
        assert len(others) <= 2, f"replacement storm: {others}"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


@pytest.mark.slow
def test_fake_multinode_scale_up_and_down():
    """End-to-end: queued tasks drive a real launch; idle node terminates.

    cf. reference python/ray/tests/test_autoscaler_fake_multinode.py.
    """
    from ray_tpu.cluster_utils import AutoscalingCluster
    cluster = AutoscalingCluster({
        "max_workers": 2,
        "idle_timeout_s": 5,
        "available_node_types": {
            "cpu4": {"resources": {"CPU": 4}, "max_workers": 2},
        },
    }, head_resources={"CPU": 0})
    try:
        ray_tpu.init(address=cluster.address)

        @ray_tpu.remote(num_cpus=3)
        def f():
            return 1

        # head has no CPU: this demand can only be served by a new node
        assert ray_tpu.get([f.remote() for _ in range(2)],
                           timeout=120) == [1, 1]
        records = cluster.monitor.provider.non_terminated_nodes()
        assert len(records) >= 1
        # after going idle, the worker node should be reclaimed
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if not cluster.monitor.provider.non_terminated_nodes():
                break
            time.sleep(0.5)
        assert cluster.monitor.provider.non_terminated_nodes() == []
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
