"""Actor tests (cf. reference python/ray/tests/test_actor*.py)."""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import (ActorDiedError, ActorUnavailableError,
                                TaskError)


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def inc(self, k=1):
        self.n += k
        return self.n

    def get(self):
        return self.n

    def fail(self):
        raise RuntimeError("actor method boom")

    def die(self):
        import os
        os._exit(1)


def test_actor_create_and_call(ray_start_regular):
    c = Counter.remote(5)
    assert ray_tpu.get(c.inc.remote()) == 6
    assert ray_tpu.get(c.inc.remote(10)) == 16


def test_actor_call_ordering(ray_start_regular):
    c = Counter.remote()
    refs = [c.inc.remote() for _ in range(40)]
    assert ray_tpu.get(refs) == list(range(1, 41))


def test_actor_method_error_keeps_actor_alive(ray_start_regular):
    c = Counter.remote()
    with pytest.raises(TaskError):
        ray_tpu.get(c.fail.remote())
    assert ray_tpu.get(c.get.remote()) == 0


def test_named_actor(ray_start_regular):
    Counter.options(name="shared").remote(7)
    h = ray_tpu.get_actor("shared")
    assert ray_tpu.get(h.get.remote()) == 7
    with pytest.raises(ValueError):
        ray_tpu.get_actor("nope")


def test_actor_handle_passing(ray_start_regular):
    c = Counter.remote()

    @ray_tpu.remote
    def bump(handle):
        return ray_tpu.get(handle.inc.remote())

    assert ray_tpu.get(bump.remote(c), timeout=60) == 1
    assert ray_tpu.get(c.get.remote()) == 1


def test_kill_actor(ray_start_regular):
    c = Counter.remote()
    ray_tpu.get(c.inc.remote())
    ray_tpu.kill(c)
    with pytest.raises((ActorDiedError, ActorUnavailableError)):
        ray_tpu.get(c.get.remote(), timeout=90)


def test_actor_restart(ray_start_regular):
    f = Counter.options(max_restarts=2).remote()
    assert ray_tpu.get(f.inc.remote()) == 1
    with pytest.raises((ActorDiedError, ActorUnavailableError, TaskError)):
        ray_tpu.get(f.die.remote(), timeout=60)
    # restarted actor: fresh state
    deadline = time.monotonic() + 60
    while True:
        try:
            assert ray_tpu.get(f.inc.remote(), timeout=60) == 1
            break
        except (ActorUnavailableError, ActorDiedError):
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)


def test_actor_no_restart_dies_for_good(ray_start_regular):
    f = Counter.options(max_restarts=0).remote()
    ray_tpu.get(f.inc.remote())
    f.die.remote()
    with pytest.raises((ActorDiedError, ActorUnavailableError)):
        ray_tpu.get(f.get.remote(), timeout=90)


def test_kill_racing_creation_releases_resources(ray_start_regular):
    """kill() before/while an actor's creation dispatch is in flight must
    not leak the worker or its resource slots (reference
    GcsActorManager::DestroyActor on PENDING_CREATION actors)."""
    import ray_tpu

    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    for _ in range(3):
        a = A.remote()
        ray_tpu.kill(a)  # racing creation: never awaited, never called
    # every CPU slot must be reusable: the fixture starts 4 CPUs
    gang = [A.remote() for _ in range(4)]
    assert ray_tpu.get([g.ping.remote() for g in gang], timeout=120) == \
        [1, 1, 1, 1]
    for g in gang:
        ray_tpu.kill(g)
