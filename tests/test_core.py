"""Core tasks/objects tests (cf. reference python/ray/tests/test_basic*.py)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import TaskError, WorkerCrashedError


def test_put_get_roundtrip(ray_start_regular):
    for value in [1, "s", {"a": [1, 2]}, np.arange(10)]:
        ref = ray_tpu.put(value)
        out = ray_tpu.get(ref)
        if isinstance(value, np.ndarray):
            np.testing.assert_array_equal(out, value)
        else:
            assert out == value


def test_large_object_through_shm(ray_start_regular):
    arr = np.random.default_rng(0).random(500_000)
    ref = ray_tpu.put(arr)
    np.testing.assert_array_equal(ray_tpu.get(ref), arr)


def test_simple_task(ray_start_regular):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_parallel_tasks_and_order(ray_start_regular):
    @ray_tpu.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(30)]
    assert ray_tpu.get(refs) == [i * i for i in range(30)]


def test_task_with_ref_args(ray_start_regular):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    x = ray_tpu.put(10)
    y = add.remote(x, 5)
    z = add.remote(y, x)   # task-output ref as arg
    assert ray_tpu.get(z) == 25


def test_large_task_result(ray_start_regular):
    @ray_tpu.remote
    def big():
        return np.ones(400_000)

    out = ray_tpu.get(big.remote())
    assert out.shape == (400_000,)
    assert float(out.sum()) == 400_000.0


def test_task_error_propagates(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(TaskError) as ei:
        ray_tpu.get(boom.remote())
    assert "kaboom" in str(ei.value)


def test_num_returns(ray_start_regular):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_wait_semantics(ray_start_regular):
    @ray_tpu.remote
    def fast():
        return 1

    @ray_tpu.remote
    def slow():
        time.sleep(10)
        return 2

    f, s = fast.remote(), slow.remote()
    ready, rest = ray_tpu.wait([s, f], num_returns=1, timeout=5)
    assert ready == [f] and rest == [s]


def test_get_timeout(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(30)

    with pytest.raises(ray_tpu.exceptions.GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.5)


def test_worker_crash_retry_then_error(ray_start_regular):
    @ray_tpu.remote(max_retries=0)
    def die():
        import os
        os._exit(1)

    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(die.remote(), timeout=30)


def test_worker_crash_retry_succeeds(ray_start_regular):
    # a task that dies on first execution and succeeds on retry, via a
    # sentinel file (the retried execution sees it)
    import tempfile
    marker = tempfile.mktemp()

    @ray_tpu.remote(max_retries=2)
    def flaky(path):
        import os
        if not os.path.exists(path):
            open(path, "w").close()
            os._exit(1)
        return "recovered"

    assert ray_tpu.get(flaky.remote(marker), timeout=60) == "recovered"


def test_nested_tasks(ray_start_regular):
    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 10

    assert ray_tpu.get(outer.remote(1), timeout=60) == 12


def test_cluster_resources(ray_start_regular):
    total = ray_tpu.cluster_resources()
    assert total.get("CPU") == 4.0


def test_dynamic_num_returns(ray_start_regular):
    """num_returns="dynamic": a generator task yields a variable number of
    objects; the caller gets an ObjectRefGenerator (reference
    ObjectRefGenerator, _raylet.pyx:169)."""
    import numpy as np

    import ray_tpu

    @ray_tpu.remote(num_returns="dynamic")
    def splits(n):
        for i in range(n):
            yield np.full((10,), i)

    gen_ref = ray_tpu.get(splits.remote(4), timeout=60)
    assert isinstance(gen_ref, ray_tpu.ObjectRefGenerator)
    assert len(gen_ref) == 4
    values = ray_tpu.get(list(gen_ref), timeout=60)
    for i, v in enumerate(values):
        assert v.shape == (10,) and v[0] == i

    # empty generator -> empty ref list
    empty = ray_tpu.get(splits.remote(0), timeout=60)
    assert len(empty) == 0

    # big yielded items travel through the object store, not inline
    @ray_tpu.remote(num_returns="dynamic")
    def big(n):
        for i in range(n):
            yield np.zeros(200_000, np.float64)   # 1.6 MB each

    refs = list(ray_tpu.get(big.remote(3), timeout=60))
    vals = ray_tpu.get(refs, timeout=60)
    assert all(v.nbytes == 1_600_000 for v in vals)

    # non-iterable return is a clear error
    @ray_tpu.remote(num_returns="dynamic")
    def notiter():
        return 42

    with pytest.raises(Exception, match="iterable"):
        ray_tpu.get(ray_tpu.get(notiter.remote(), timeout=60), timeout=60)


def test_task_error_propagates_root_not_wrapped(ray_start_regular):
    """A failure at the root of a task chain surfaces as ONE TaskError
    with the root cause — not re-wrapped per hop (TaskError.__reduce__
    keeps pickle round trips idempotent; downstream workers forward an
    upstream TaskError unchanged)."""
    import ray_tpu
    import pytest

    @ray_tpu.remote
    def boom():
        raise ValueError("root cause")

    @ray_tpu.remote
    def passthrough(x):
        return x

    ref = passthrough.remote(passthrough.remote(boom.remote()))
    with pytest.raises(ray_tpu.exceptions.TaskError) as ei:
        ray_tpu.get(ref, timeout=60)
    msg = str(ei.value)
    assert "root cause" in msg
    assert msg.count("failed:") == 1
    assert len(msg) < 2000
