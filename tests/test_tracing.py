"""Distributed request tracing plane (docs/observability.md).

Covers the tentpole's load-bearing claims: deterministic sampling (the
same trace id reaches the same verdict in every process), cross-process
context propagation (driver -> task -> nested task, async-actor
interleaving on one event loop, streaming per-yield spans), span-table
retention bounds, the serve SLO accounting + exemplar path, the
trace <-> crash-dossier cross-link, the kill switch, and the
end-to-end disaggregated-serve trace whose hop spans decompose TTFT.
"""

import os
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu._private.config import CONFIG
from ray_tpu.util.tracing import tracing_helper as trh


def _worker():
    from ray_tpu.runtime.core_worker import get_global_worker
    return get_global_worker()


def _wait_for(pred, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = pred()
        if out:
            return out
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def full_sampling(monkeypatch):
    """Force sample rate 1.0 so every trace records (propagation tests
    must not depend on a lucky draw).  Worker-side recording trusts the
    propagated ``sampled`` flag, so only the driver needs the rate."""
    monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE_RATE", "1.0")
    CONFIG.set("trace_sample_rate", 1.0)
    yield
    CONFIG.set("trace_sample_rate", 0.1)


def _flush_traces():
    trh.flush_now()


def _get_trace(w, trace_id, nspans=1, timeout=30.0):
    """Poll the GCS span table until the trace holds >= nspans spans
    (worker-side flushers tick at trace_flush_interval_ms)."""
    def _go():
        _flush_traces()
        t = w.gcs.call("get_trace", {"trace_id": trace_id})
        if t and len(t.get("spans") or []) >= nspans:
            return t
        time.sleep(0.3)
        return None
    return _wait_for(_go, timeout=timeout,
                     msg=f"trace {trace_id[:8]} with {nspans} spans")


# ------------------------------------------------------------ sampler unit
def test_sampler_deterministic_across_processes():
    """The sampling verdict is a pure function of the trace id: every
    process derives the same answer with no coordination."""
    CONFIG.set("trace_sample_rate", 0.5)
    try:
        ids = [trh.new_trace_id() for _ in range(64)]
        local = [trh.sampled(t) for t in ids]
        # decisions split (rate 0.5 over 64 draws: both outcomes present
        # with probability 1 - 2^-63)
        assert any(local) and not all(local)
        # same ids, fresh interpreter, same verdicts
        code = (
            "import json,sys\n"
            "from ray_tpu._private.config import CONFIG\n"
            "CONFIG.set('trace_sample_rate', 0.5)\n"
            "from ray_tpu.util.tracing import tracing_helper as trh\n"
            "ids = json.loads(sys.argv[1])\n"
            "print(json.dumps([trh.sampled(t) for t in ids]))\n")
        import json
        out = subprocess.run(
            [sys.executable, "-c", code, json.dumps(ids)],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert out.returncode == 0, out.stderr[-800:]
        assert json.loads(out.stdout.strip().splitlines()[-1]) == local
        # a root minted by the submission sampler always re-derives True
        CONFIG.set("trace_sample_rate", 0.25)
        for _ in range(32):
            ctx = trh.maybe_sample_root()
            if ctx is not None:
                assert trh.sampled(ctx["trace_id"])
    finally:
        CONFIG.set("trace_sample_rate", 0.1)


def test_ids_distinct_across_fork():
    """Workers fork from a warm zygote: the id generator must reseed in
    the child or two workers mint identical trace/span ids and merge
    unrelated requests into one trace."""
    if not hasattr(os, "fork"):
        pytest.skip("no fork on this platform")
    # draw once so the parent's generator state is warm pre-fork
    trh.new_trace_id()
    r, w = os.pipe()
    pid = os.fork()
    if pid == 0:  # child
        try:
            os.write(w, trh.new_trace_id().encode())
        finally:
            os._exit(0)
    os.close(w)
    child_id = b""
    while True:
        chunk = os.read(r, 64)
        if not chunk:
            break
        child_id += chunk
    os.close(r)
    os.waitpid(pid, 0)
    parent_id = trh.new_trace_id()
    assert len(child_id) == 32
    assert child_id.decode() != parent_id


def test_sampler_rate_bounds():
    CONFIG.set("trace_sample_rate", 0.0)
    try:
        assert all(trh.maybe_sample_root() is None for _ in range(64))
        assert not trh.sampled(trh.new_trace_id())
    finally:
        CONFIG.set("trace_sample_rate", 1.0)
    try:
        ctx = trh.maybe_sample_root()
        assert ctx is not None and ctx["sampled"]
    finally:
        CONFIG.set("trace_sample_rate", 0.1)


# -------------------------------------------------------- span table unit
def test_span_table_retention_bounds():
    """Count, byte and per-trace-span bounds all rotate oldest-first."""
    t = trh.GcsSpanTable(max_traces=16, max_bytes=64 * 1024)
    t.max_spans = 8

    def span(tid, i):
        return {"trace_id": tid, "span_id": f"s{i:04d}", "name": "x" * 50,
                "kind": "task", "start": time.time(), "dur_ms": 1.0,
                "status": "ok"}

    # trace-count bound (sharded: per-shard cap = max_traces/8 = 2)
    tids = [trh.new_trace_id() for _ in range(64)]
    for tid in tids:
        t.put([span(tid, 0)])
    stats = t.stats()
    assert stats["traces"] <= 16
    assert stats["traces_seen"] == 64
    assert stats["dropped_traces"] >= 48
    # per-trace span cap: first/last halves survive
    tid = trh.new_trace_id()
    t.put([span(tid, i) for i in range(40)])
    rec = t.get(tid)
    assert rec["truncated"] and len(rec["spans"]) == 8
    kept = {s["span_id"] for s in rec["spans"]}
    assert "s0000" in kept and "s0039" in kept
    # byte budget: a flood of fat spans cannot grow the table unbounded
    t2 = trh.GcsSpanTable(max_traces=10_000, max_bytes=32 * 1024)
    for i in range(200):
        tid = trh.new_trace_id()
        t2.put([dict(span(tid, 0), name="y" * 400)])
    assert t2.stats()["bytes"] <= 32 * 1024


def test_span_table_slo_index_and_exemplars():
    t = trh.GcsSpanTable(max_traces=64, max_bytes=1 << 20)
    for i in range(8):
        tid = trh.new_trace_id()
        t.put([{"trace_id": tid, "span_id": f"r{i}", "name": "req",
                "kind": "ingress", "start": time.time(), "dur_ms": 5.0,
                "status": "ok", "root": True, "route": "llm-a",
                "ttft_ms": 100.0 * (i + 1), "slo_ok": i < 6,
                "slo_violated": [] if i < 6 else ["ttft"]}])
    rows = t.list(slo_violations=True)
    assert len(rows) == 2
    stats = t.stats()["slo_by_route"]["llm-a"]
    assert stats == {
        "good": 6, "violation": 2,
        "ttft_violation": 2, "tpot_violation": 0,
        "exemplars": stats["exemplars"]}
    # exemplars are the worst TTFTs, descending
    ttfts = [e["ttft_ms"] for e in stats["exemplars"]]
    assert ttfts == sorted(ttfts, reverse=True)
    assert ttfts[0] == 800.0


# ------------------------------------------------------------- kill switch
def test_kill_switch_noop_path(monkeypatch):
    """RAY_TPU_TRACING=0: roots/samplers return None, configure refuses
    a buffer, record_span drops — one cached flag read per call."""
    monkeypatch.setenv("RAY_TPU_TRACING", "0")
    CONFIG.set("tracing_enabled", True)  # bump gen -> re-read env
    try:
        assert not trh.enabled()
        assert trh.serve_ingress_root("x") is None
        assert trh.maybe_sample_root() is None
        assert trh.configure(lambda spans: None) is None
        # finish_request on a None root is a no-op
        trh.finish_request(None, pool="p", ttft_s=1.0)
        # user span() keeps its task-event contract but records nothing
        with trh.span("off-span"):
            assert trh.get_trace_context().get("trace_id")
    finally:
        monkeypatch.delenv("RAY_TPU_TRACING")
        CONFIG.set("tracing_enabled", True)


# ------------------------------------------------- cross-process propagation
def test_cross_process_propagation_nested(ray_start_regular,
                                          full_sampling):
    """driver -> task -> nested task: one trace, parent/child linked
    through two process hops."""
    w = _worker()

    @ray_tpu.remote
    def inner():
        return 1

    @ray_tpu.remote
    def outer():
        import ray_tpu
        return ray_tpu.get(inner.remote())

    root = trh.serve_ingress_root("req", route="test")
    token = trh.install(root.ctx())
    try:
        assert ray_tpu.get(outer.remote(), timeout=120) == 1
    finally:
        trh.uninstall(token)
    trh.finish_request(root, pool="test", ttft_s=0.001)
    trace = _get_trace(w, root.trace_id, nspans=3)
    by_name = {s["name"]: s for s in trace["spans"]}
    assert by_name["task:outer"]["parent_id"] == root.span_id
    assert by_name["task:inner"]["parent_id"] == \
        by_name["task:outer"]["span_id"]
    # execution spans are stamped with the executing process, not ours
    assert by_name["task:outer"]["worker_id"] != w.worker_id.hex()
    assert trace["root"]["route"] == "test"


def test_async_actor_interleaved_contexts(ray_start_regular,
                                          full_sampling):
    """Two concurrent calls on ONE async actor, each under its own
    trace: the ContextVar keeps the identities apart while both
    coroutines interleave on the actor's single event loop."""
    w = _worker()

    @ray_tpu.remote
    class A:
        async def slow(self, ms):
            import asyncio
            from ray_tpu.util.tracing.tracing_helper import \
                get_trace_context
            before = get_trace_context().get("trace_id")
            await asyncio.sleep(ms / 1000.0)
            after = get_trace_context().get("trace_id")
            return before, after

    a = A.remote()
    ray_tpu.get(a.slow.remote(0), timeout=120)  # actor up

    roots = [trh.serve_ingress_root(f"req{i}") for i in range(2)]
    refs = []
    for i, root in enumerate(roots):
        token = trh.install(root.ctx())
        try:
            # both in flight together: 300ms + 150ms overlap on the loop
            refs.append(a.slow.remote(300 if i == 0 else 150))
        finally:
            trh.uninstall(token)
    outs = ray_tpu.get(refs, timeout=120)
    for root, (before, after) in zip(roots, outs):
        # each call saw ITS OWN trace id, before and after the await
        # that interleaved it with the other call
        assert before == root.trace_id, (before, root.trace_id)
        assert after == root.trace_id, (after, root.trace_id)


def test_streaming_per_yield_spans(ray_start_regular, full_sampling):
    """A sampled streaming task records per-yield marker spans (capped
    at trace_stream_span_items) inside the task's trace."""
    w = _worker()

    @ray_tpu.remote
    def gen():
        for i in range(40):
            yield i

    with trh.span("stream-driver"):
        tid = trh.get_trace_context()["trace_id"]
        out = [ray_tpu.get(r, timeout=60) for r in
               gen.options(num_returns="streaming").remote()]
    assert out == list(range(40))
    cap = CONFIG.trace_stream_span_items
    trace = _get_trace(w, tid, nspans=cap + 1)
    yields = sorted((s for s in trace["spans"]
                     if s["kind"] == "stream_item"),
                    key=lambda s: s.get("index", -1))
    assert len(yields) == cap  # capped, not one span per token
    assert [s["index"] for s in yields] == list(range(cap))
    # children of the executing task's span
    task_span = next(s for s in trace["spans"] if s["kind"] == "task")
    assert all(y["parent_id"] == task_span["span_id"] for y in yields)


def test_transfer_pull_span(ray_start_cluster, full_sampling):
    """A cross-node object fetch inside a sampled trace lands as a
    ``pull`` span (the transfer-plane hop of the trace)."""
    import numpy as np

    cluster = ray_start_cluster
    cluster.add_node(resources={"CPU": 2, "producer": 2})
    cluster.wait_for_nodes(2)
    ray_tpu.init(num_cpus=1, address=cluster.address)
    try:
        w = _worker()

        @ray_tpu.remote(resources={"producer": 1}, num_cpus=1)
        def produce():
            import numpy as np
            return np.arange(2_000_000, dtype=np.float64)  # 16 MiB

        ref = produce.remote()
        with trh.span("pull-driver"):
            tid = trh.get_trace_context()["trace_id"]
            value = ray_tpu.get(ref, timeout=120)
        assert float(value[-1]) == 1_999_999.0
        trace = _get_trace(w, tid, nspans=2)
        pulls = [s for s in trace["spans"] if s["kind"] == "pull"]
        assert pulls, [s["name"] for s in trace["spans"]]
        assert pulls[0]["attrs"]["bytes"] > 15_000_000
    finally:
        ray_tpu.shutdown()


# --------------------------------------------------- dossier cross-link
def test_trace_dossier_cross_link(ray_start_regular, full_sampling):
    """A root span closed with a dossier_id links both ways: the trace
    record carries the dossier id, the dossier gains the trace id."""
    w = _worker()
    w.gcs.call("put_dossier", {
        "dossier_id": "deadbeef00112233",
        "dossier": {"kind": "worker", "reason": "test-crash"}})
    root = trh.serve_ingress_root("doomed", route="llm-x")
    trh.finish_request(root, pool="decode", route="llm-x",
                       status=trh.ERROR, ttft_s=None,
                       error_type="ActorDiedError",
                       dossier_id="deadbeef00112233")
    trace = _get_trace(w, root.trace_id, nspans=1)
    assert trace["root"]["dossier_id"] == "deadbeef00112233"
    d = _wait_for(lambda: w.gcs.call(
        "get_dossier", {"dossier_id": "deadbeef"}),
        msg="dossier")
    assert d["trace_id"] == root.trace_id
    # and the violation listing carries the exemplar id
    rows = w.gcs.call("list_traces", {"status": "error"})
    assert any(r["trace_id"] == root.trace_id
               and r["dossier_id"] == "deadbeef00112233" for r in rows)


def test_death_mid_request_links_dossier(ray_start_regular,
                                         full_sampling):
    """An actor dying under a traced request closes the root with the
    failure and the crash dossier id the error carried."""
    w = _worker()

    @ray_tpu.remote(max_restarts=0)
    class Doomed:
        def boom(self):
            import os
            os._exit(1)

    a = Doomed.remote()
    root = trh.serve_ingress_root("dying-request", route="doomed")
    token = trh.install(root.ctx())
    try:
        # the exact surface depends on timing: ActorDiedError once the
        # GCS verdict lands, ActorUnavailableError when the conn breaks
        # with the call in flight — both carry the dossier ref
        with pytest.raises((ray_tpu.exceptions.ActorDiedError,
                            ray_tpu.exceptions.ActorUnavailableError)
                           ) as ei:
            ray_tpu.get(a.boom.remote(), timeout=120)
    finally:
        trh.uninstall(token)
    did = getattr(ei.value, "dossier_id", None)
    trh.finish_request(root, pool="serve", route="doomed",
                       status=trh.ERROR,
                       error_type=type(ei.value).__name__,
                       dossier_id=did)
    trace = _get_trace(w, root.trace_id, nspans=1)
    assert trace["root"]["status"] == "error"
    if did:  # dossier harvest is best-effort; the link must hold when
        assert trace["root"]["dossier_id"] == did  # it exists
        d = _wait_for(lambda: w.gcs.call("get_dossier",
                                         {"dossier_id": did}),
                      msg="dossier")
        assert d.get("trace_id") == root.trace_id


# ------------------------------------------------------------ serve + SLO
def test_serve_slo_accounting_and_summary(ray_start_regular,
                                          full_sampling):
    """Completed requests are classified against the TTFT target:
    violations publish counters + exemplar trace ids, and both the
    state API filter and metrics_summary surface them."""
    from ray_tpu.experimental import state

    w = _worker()
    CONFIG.set("serve_slo_ttft_ms", 50.0)
    try:
        good = trh.serve_ingress_root("fast", route="llm-fast")
        trh.finish_request(good, pool="decode", route="llm-fast",
                           ttft_s=0.005)
        slow = trh.serve_ingress_root("slow", route="llm-slow")
        trh.finish_request(slow, pool="decode", route="llm-slow",
                           ttft_s=0.500, tpot_s=0.001, num_tokens=8)
        _get_trace(w, slow.trace_id, nspans=1)
        rows = state.list_traces(slo_violations=True)
        assert [r["trace_id"] for r in rows] == [slow.trace_id]
        assert rows[0]["slo_violated"] == ["ttft"]
        assert rows[0]["ttft_ms"] == 500.0
        stats = state.trace_stats()
        ex = stats["slo_by_route"]["llm-slow"]["exemplars"]
        assert ex[0]["trace_id"] == slow.trace_id
        # counters flushed into the metrics namespace
        from ray_tpu._private import runtime_metrics as rtm
        rtm.flush_now()
        summary = _wait_for(
            lambda: (lambda s: s if "Request traces" in s else None)(
                state.metrics_summary()),
            msg="Request traces section")
        assert "llm-slow" in summary
        assert slow.trace_id[:16] in summary
    finally:
        CONFIG.set("serve_slo_ttft_ms", 2000.0)


@pytest.mark.usefixtures("full_sampling")
def test_disagg_request_trace_end_to_end(ray_start_regular):
    """Acceptance smoke (2 prefill + 2 decode replicas): one streamed
    request yields ONE retrievable trace whose spans cover
    ingress -> prefill -> handoff-pull -> decode with correct
    parent/child links, whose summed hop durations account for >= 90%
    of the measured TTFT, and an injected-slow request shows up under
    ``--slo-violations`` with its exemplar trace id."""
    import asyncio

    from ray_tpu import serve
    from ray_tpu.experimental import state

    sys.path.insert(0, os.path.dirname(__file__))
    from test_serve_llm import _disagg_app

    w = _worker()
    serve.start()
    serve.run(_disagg_app())
    try:
        handle = serve.llm.disagg_handle("tiny")

        async def one(req):
            toks, summary = [], None
            t0 = time.perf_counter()
            ttft = None
            async for item in handle.stream(req):
                if "token" in item:
                    if ttft is None:
                        ttft = time.perf_counter() - t0
                    toks.append(item["token"])
                elif "retry" not in item:
                    summary = item
            return toks, summary, ttft

        req = {"prompt": [1, 2, 3, 4, 5], "max_new_tokens": 6,
               "temperature": 0.0}
        toks, summary, ttft = asyncio.run(
            asyncio.wait_for(one(req), timeout=300))
        assert len(toks) == 6 and summary["finish_reason"] == "length"

        # exactly one ingress trace for the request
        rows = _wait_for(
            lambda: (_flush_traces() or
                     [r for r in state.list_traces(limit=200)
                      if r.get("pool") == "disagg"]) or None,
            msg="disagg trace row")
        assert len(rows) == 1

        # hop coverage: ingress -> prefill/decode client hops ->
        # replica exec + serve spans -> handoff legs.  Poll until every
        # expected hop flushed (replica-side buffers tick at
        # trace_flush_interval_ms, independently of the driver's)
        pref_serve = "serve:llm-tiny-prefill.prefill"
        dec_serve = "serve:llm-tiny-decode.decode"
        wanted = {"prefill", "decode", "handoff_pull", "import_wait",
                  "handoff_export", pref_serve, dec_serve}

        def _full_trace():
            t = _get_trace(w, rows[0]["trace_id"], nspans=1, timeout=60)
            names = {s["name"] for s in t["spans"]}
            if wanted - names:
                time.sleep(0.3)
                return None
            return t

        trace = _wait_for(_full_trace, timeout=60,
                          msg=f"hop spans {wanted}")
        by_name = {}
        for s in trace["spans"]:
            by_name.setdefault(s["name"], s)
        root = trace["root"]
        assert root["pool"] == "disagg" and root["ttft_ms"] is not None
        root_id = root["span_id"]
        assert by_name["prefill"]["parent_id"] == root_id
        assert by_name["decode"]["parent_id"] == root_id
        # client hop -> actor exec span -> replica serve span -> legs
        exec_pref = next(
            s for s in trace["spans"]
            if s["name"] == "task:handle_request"
            and s["parent_id"] == by_name["prefill"]["span_id"])
        assert by_name[pref_serve]["parent_id"] == exec_pref["span_id"]
        assert by_name["handoff_export"]["parent_id"] == \
            by_name[pref_serve]["span_id"]
        exec_dec = next(
            s for s in trace["spans"]
            if s["name"] == "task:handle_request_streaming")
        assert exec_dec["parent_id"] == by_name["decode"]["span_id"]
        assert by_name[dec_serve]["parent_id"] == exec_dec["span_id"]
        assert by_name["handoff_pull"]["parent_id"] == \
            by_name[dec_serve]["span_id"]
        assert by_name["import_wait"]["parent_id"] == \
            by_name[dec_serve]["span_id"]
        # prefill and decode execution ran on DIFFERENT replicas
        assert exec_pref["worker_id"] != exec_dec["worker_id"]

        # TTFT decomposition: the client-observed prefill hop IS the
        # time-to-first-token path (routing + queue + replica prefill +
        # reply); it must account for >= 90% of the measured TTFT
        assert ttft is not None
        assert by_name["prefill"]["dur_ms"] >= 0.9 * ttft * 1e3, (
            by_name["prefill"]["dur_ms"], ttft * 1e3)

        # injected-slow request: drop the TTFT budget below this
        # pipeline's floor, stream once more, and the violation listing
        # names the new trace
        CONFIG.set("serve_slo_ttft_ms", 0.01)
        try:
            asyncio.run(asyncio.wait_for(one(req), timeout=300))
        finally:
            CONFIG.set("serve_slo_ttft_ms", 2000.0)
        viol = _wait_for(
            lambda: (_flush_traces() or
                     state.list_traces(slo_violations=True,
                                      limit=50)) or None,
            msg="slo violation row")
        assert any(r.get("pool") == "disagg"
                   and "ttft" in (r["slo_violated"] or [])
                   for r in viol), viol
        # the exemplar id resolves to a real trace
        vid = next(r["trace_id"] for r in viol
                   if r.get("pool") == "disagg")
        assert state.get_trace(vid)["root"]["slo_ok"] is False
    finally:
        serve.shutdown()
