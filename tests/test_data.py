"""Data library tests (model: reference python/ray/data/tests)."""

import numpy as np
import pytest

from ray_tpu import data as rd
from ray_tpu.data import ActorPoolStrategy
from ray_tpu.data.block import BlockAccessor


def test_block_accessor_formats():
    import pandas as pd
    simple = BlockAccessor.for_block([{"a": 1}, {"a": 2}])
    assert simple.num_rows() == 2
    np.testing.assert_array_equal(simple.to_numpy()["a"], [1, 2])

    npb = BlockAccessor.for_block({"x": np.arange(4)})
    assert npb.num_rows() == 4
    assert npb.slice(1, 3)["x"].tolist() == [1, 2]

    df = BlockAccessor.for_block(pd.DataFrame({"c": [1, 2, 3]}))
    assert df.num_rows() == 3
    assert list(df.iter_rows()) == [{"c": 1}, {"c": 2}, {"c": 3}]


def test_range_count_take(ray_start_regular):
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    assert ds.take(3) == [{"id": 0}, {"id": 1}, {"id": 2}]
    assert ds.num_blocks() == 4


def test_map_batches_fusion(ray_start_regular):
    ds = rd.range(32, parallelism=2) \
        .map_batches(lambda b: {"id": b["id"] * 2}, batch_format="numpy") \
        .map_batches(lambda b: {"id": b["id"] + 1}, batch_format="numpy")
    rows = ds.take_all()
    assert rows[0] == {"id": 1} and rows[-1] == {"id": 63}


def test_map_filter_flat_map(ray_start_regular):
    ds = rd.from_items(list(range(10)), parallelism=2)
    doubled = ds.map(lambda x: x * 2)
    assert doubled.take_all() == [x * 2 for x in range(10)]
    evens = ds.filter(lambda x: x % 2 == 0)
    assert evens.take_all() == [0, 2, 4, 6, 8]
    repeated = ds.flat_map(lambda x: [x, x])
    assert repeated.count() == 20


def test_actor_pool_strategy(ray_start_regular):
    class AddConst:
        def __init__(self, c=100):
            self.c = c

        def __call__(self, batch):
            return {"id": batch["id"] + self.c}

    ds = rd.range(16, parallelism=2).map_batches(
        AddConst, batch_format="numpy",
        compute=ActorPoolStrategy(1, 2))
    rows = ds.take_all()
    assert rows[0]["id"] == 100


def test_repartition(ray_start_regular):
    ds = rd.range(20, parallelism=5).repartition(2)
    assert ds.num_blocks() in (2, 5)      # hint before exec
    refs = ds.get_internal_block_refs()
    assert len(refs) == 2
    assert ds.count() == 20


def test_random_shuffle(ray_start_regular):
    ds = rd.range(50, parallelism=4).random_shuffle(seed=42)
    rows = [r["id"] for r in ds.take_all()]
    assert sorted(rows) == list(range(50))
    assert rows != list(range(50))


def test_sort(ray_start_regular):
    import random
    items = list(range(40))
    random.Random(0).shuffle(items)
    ds = rd.from_items(items, parallelism=4).sort()
    assert ds.take_all() == sorted(items)
    ds_desc = rd.from_items(items, parallelism=4).sort(descending=True)
    assert ds_desc.take_all() == sorted(items, reverse=True)


def test_sort_by_key(ray_start_regular):
    rows = [{"k": i % 5, "v": i} for i in range(20)]
    ds = rd.from_items(rows, parallelism=3).sort(key="k")
    out = ds.take_all()
    assert [r["k"] for r in out] == sorted(r["k"] for r in rows)


def test_groupby_aggregate(ray_start_regular):
    rows = [{"g": i % 3, "v": i} for i in range(12)]
    ds = rd.from_items(rows, parallelism=3)
    sums = {r["g"]: r["sum(v)"]
            for r in ds.groupby("g").sum("v").take_all()}
    expect = {}
    for r in rows:
        expect[r["g"]] = expect.get(r["g"], 0) + r["v"]
    assert sums == expect
    means = ds.groupby("g").mean("v").take_all()
    assert len(means) == 3


def test_split_and_split_at_indices(ray_start_regular):
    ds = rd.range(30, parallelism=6)
    shards = ds.split(3)
    assert len(shards) == 3
    assert sum(s.count() for s in shards) == 30
    equal = ds.split(3, equal=True)
    assert [s.count() for s in equal] == [10, 10, 10]
    a, b = ds.split_at_indices([12])
    assert a.count() == 12 and b.count() == 18
    assert a.take_all()[-1] == {"id": 11}


def test_iter_batches(ray_start_regular):
    ds = rd.range(25, parallelism=3)
    batches = list(ds.iter_batches(batch_size=10, batch_format="numpy"))
    sizes = [len(b["id"]) for b in batches]
    assert sizes == [10, 10, 5]
    all_ids = np.concatenate([b["id"] for b in batches])
    np.testing.assert_array_equal(np.sort(all_ids), np.arange(25))


def test_iter_batches_shuffled(ray_start_regular):
    ds = rd.range(40, parallelism=2)
    batches = list(ds.iter_batches(batch_size=8, batch_format="numpy",
                                   local_shuffle_buffer_size=16,
                                   local_shuffle_seed=7))
    ids = np.concatenate([b["id"] for b in batches])
    assert sorted(ids.tolist()) == list(range(40))
    assert ids.tolist() != list(range(40))


def test_zip_union_limit(ray_start_regular):
    a = rd.range(8, parallelism=2)
    b = rd.range(8, parallelism=2).map_batches(
        lambda x: {"id2": x["id"] * 10}, batch_format="numpy")
    z = a.zip(b)
    rows = z.take_all()
    assert rows[3]["id"] == 3 and rows[3]["id2"] == 30
    u = a.union(a)
    assert u.count() == 16
    assert a.limit(3).count() == 3


def _roundtrip_retrying(fn, label):
    """Run one write+read roundtrip, retrying ONCE on TaskError only.

    This test is the suite's recurring one-per-full-run load flake: a
    TaskError out of a write/read task under full-suite contention that
    standalone runs, 25x module loops under a CPU burner, and the whole
    alphabetical tier-1 prefix under synthetic load all fail to
    reproduce — and the truncated pytest summary line is all any tier-1
    log ever kept of it.  Every infra budget on the path
    (raylet_rpc/fetch_fail/worker_lease/worker_start) is already
    RAY_TPU_TIMEOUT_SCALE-scaled, so a budget bump has nowhere left to
    go.  A single retry keeps the transient green while a deterministic
    write/read bug still fails both attempts; the full wrapped traceback
    is printed on the first hit so the next occurrence finally lands a
    root cause in the log.
    """
    import sys

    from ray_tpu import exceptions as rexc
    for attempt in range(2):
        try:
            return fn(attempt)
        except rexc.TaskError as e:
            print(f"\n[test_file_roundtrips:{label}] attempt {attempt} "
                  f"TaskError (load-flake forensics):\n"
                  f"{e.traceback_str or e}", file=sys.stderr, flush=True)
            if attempt == 1:
                raise


def test_file_roundtrips(ray_start_regular, tmp_path):
    ds = rd.range(12, parallelism=3)

    def pq(attempt):
        pq_dir = str(tmp_path / f"pq{attempt}")
        ds.write_parquet(pq_dir)
        back = rd.read_parquet(pq_dir)
        back.materialize()       # read tasks execute inside the retry
        return back

    back = _roundtrip_retrying(pq, "parquet")
    assert back.count() == 12
    assert sorted(r["id"] for r in back.take_all()) == list(range(12))

    def csv(attempt):
        csv_dir = str(tmp_path / f"csv{attempt}")
        ds.write_csv(csv_dir)
        return rd.read_csv(csv_dir).materialize()

    assert _roundtrip_retrying(csv, "csv").count() == 12

    def js(attempt):
        js_dir = str(tmp_path / f"js{attempt}")
        ds.write_json(js_dir)
        return rd.read_json(js_dir).materialize()

    assert _roundtrip_retrying(js, "json").count() == 12


def test_from_pandas_numpy(ray_start_regular):
    import pandas as pd
    df = pd.DataFrame({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    ds = rd.from_pandas(df)
    assert ds.count() == 3
    assert ds.to_pandas()["a"].tolist() == [1, 2, 3]

    nds = rd.from_numpy(np.arange(6).reshape(3, 2))
    arrs = nds.to_numpy()
    assert arrs["data"].shape == (3, 2)


def test_train_test_split(ray_start_regular):
    ds = rd.range(20, parallelism=2)
    train, test = ds.train_test_split(0.25)
    assert train.count() == 15 and test.count() == 5


def test_pipeline_window_repeat(ray_start_regular):
    ds = rd.range(20, parallelism=4)
    pipe = ds.window(blocks_per_window=2)
    windows = list(pipe.iter_datasets())
    assert len(windows) == 2
    assert pipe.count() == 20

    rep = ds.repeat(2)
    assert rep.count() == 40

    mapped = ds.window(blocks_per_window=2).map_batches(
        lambda b: {"id": b["id"] + 1}, batch_format="numpy")
    first = next(mapped.iter_rows())
    assert first == {"id": 1}


def test_dataset_feeds_trainer(ray_start_regular):
    """Dataset shard → session.get_dataset_shard → iter_batches inside a
    JaxTrainer loop (the AIR ingest path)."""
    from ray_tpu.air import ScalingConfig, session
    from ray_tpu.train import JaxTrainer

    ds = rd.range(32, parallelism=4)

    def loop(config):
        shard = session.get_dataset_shard("train")
        total = 0
        for batch in shard.iter_batches(batch_size=8, batch_format="numpy"):
            total += int(batch["id"].sum())
        session.report({"total": total})

    result = JaxTrainer(loop,
                        scaling_config=ScalingConfig(num_workers=1),
                        datasets={"train": ds}).fit()
    assert result.error is None
    assert result.metrics["total"] == sum(range(32))


def test_random_access_dataset(ray_start_regular):
    from ray_tpu import data as rdata
    rows = [{"id": i * 3, "value": f"v{i}"} for i in range(50)]
    import random
    random.Random(0).shuffle(rows)
    ds = rdata.from_items(rows, parallelism=4)
    rad = ds.to_random_access_dataset("id", num_workers=3)
    import ray_tpu as rt
    # generous timeout: the first get rides the 3 RAD workers' cold
    # start, which on a loaded 1-CPU box can far outlive the old 30s
    assert rt.get(rad.get_async(27), timeout=180)["value"] == "v9"
    assert rt.get(rad.get_async(28), timeout=180) is None  # absent key
    got = rad.multiget([0, 3, 146, 147, 99])
    assert [g["value"] if g else None for g in got] == \
        ["v0", "v1", None, "v49", "v33"]
    assert "50 rows" in rad.stats()


def test_read_images(ray_start_regular, tmp_path):
    from PIL import Image
    import numpy as np
    import ray_tpu.data as rdata
    for i in range(6):
        Image.fromarray(
            np.full((8, 8, 3), i * 20, np.uint8)).save(
            tmp_path / f"im{i}.png")
    ds = rdata.read_images(str(tmp_path), mode="RGB")
    assert ds.count() == 6
    batch = next(ds.iter_batches(batch_size=6, batch_format="numpy"))
    assert batch["image"].shape == (6, 8, 8, 3)
    assert len(batch["path"]) == 6


def test_from_torch_and_to_torch(ray_start_regular):
    import numpy as np
    import torch
    import ray_tpu.data as rdata

    class Sq(torch.utils.data.Dataset):
        def __len__(self):
            return 10

        def __getitem__(self, i):
            return {"x": float(i), "y": float(i * i)}

    ds = rdata.from_torch(Sq())
    assert ds.count() == 10
    got = sorted(r["y"] for r in ds.take_all())
    assert got == [float(i * i) for i in range(10)]

    tds = rdata.from_numpy(np.arange(12).reshape(12, 1)).to_torch(
        batch_size=4)
    batches = list(iter(tds))
    assert len(batches) == 3
    assert batches[0]["data"].shape == (4, 1)
    assert str(batches[0]["data"].dtype).startswith("torch")


def test_from_huggingface(ray_start_regular):
    import datasets as hfd
    import ray_tpu.data as rdata
    hf = hfd.Dataset.from_dict({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    ds = rdata.from_huggingface(hf)
    assert ds.count() == 3
    batch = next(ds.iter_batches(batch_size=3, batch_format="pandas"))
    assert list(batch["a"]) == [1, 2, 3]


def test_map_can_change_row_schema(ray_start_regular):
    """Dataset.map output blocks take the OUTPUT rows' schema (a map that
    renames/adds columns used to rebuild blocks with the input keys)."""
    import ray_tpu.data as rdata
    ds = rdata.range(30, parallelism=3)
    out = ds.map(lambda r: {"x": r["id"], "y": r["id"] * 2})
    rows = out.take(3)
    assert set(rows[0]) == {"x", "y"}
    assert out.count() == 30


def test_dataset_stats_per_stage(ray_start_regular):
    """ds.stats() reports per-stage blocks, driver/remote wall, CPU,
    rows, and bytes (reference DatasetStats, data/_internal/stats.py)."""
    from ray_tpu import data

    ds = data.range(400, parallelism=4).map(lambda r: {"id": r["id"] + 1})
    report = ds.stats()
    assert "Stage read->map" in report
    assert "remote wall time" in report
    assert "remote cpu time" in report
    assert "total=400" in report          # output rows across blocks
    assert "output size (bytes)" in report
    # a derived dataset keeps the whole chain in its report
    ds2 = ds.filter(lambda r: r["id"] % 2 == 0)
    report2 = ds2.stats()
    assert "Stage read->map" in report2 and "filter" in report2
