"""Runtime environments (SURVEY.md §2.3 runtime_env row: reference
python/ray/runtime_env/ + _private/runtime_env/packaging.py)."""

import os
import sys

import pytest


def test_runtime_env_validation(tmp_path):
    from ray_tpu.runtime_env import RuntimeEnv

    with pytest.raises(TypeError):
        RuntimeEnv(env_vars={"A": 1})
    with pytest.raises(ValueError):
        RuntimeEnv(working_dir=str(tmp_path / "nope"))
    with pytest.raises(TypeError):
        RuntimeEnv(pip="not-a-list")
    assert RuntimeEnv(pip=["b", "a"])["pip"] == ["a", "b"]
    with pytest.raises(ValueError):
        RuntimeEnv.from_dict({"bogus_field": 1})
    env = RuntimeEnv(env_vars={"A": "1"}, working_dir=str(tmp_path))
    assert env.to_dict()["env_vars"] == {"A": "1"}


def test_conda_spec_folds_into_pip(tmp_path):
    """conda environment.yml content routes through the venv isolation
    path: dependencies become pip requirements (reference conda plugin,
    _private/runtime_env/conda.py); named envs are rejected — no conda
    binary in hermetic images."""
    from ray_tpu.runtime_env import RuntimeEnv

    env = RuntimeEnv(conda={"dependencies": [
        "python=3.10", "pip", "left-pad=1.0", {"pip": ["right-pad==2.0"]}]})
    assert env["pip"] == ["left-pad==1.0", "right-pad==2.0"]
    assert "conda" not in env  # wire format stays pip-only

    # conda + pip merge, deduped
    env = RuntimeEnv(pip=["right-pad==2.0"],
                     conda={"dependencies": [{"pip": ["a==1"]}]})
    assert env["pip"] == ["a==1", "right-pad==2.0"]

    # environment.yml file path parses the same way
    yml = tmp_path / "environment.yml"
    yml.write_text("name: t\ndependencies:\n  - python=3.10\n"
                   "  - numpy>=1.20\n  - pip:\n    - req==1.0\n")
    env = RuntimeEnv(conda=str(yml))
    assert env["pip"] == ["numpy>=1.20", "req==1.0"]

    with pytest.raises(ValueError):
        RuntimeEnv(conda="some-named-env")
    with pytest.raises(TypeError):
        RuntimeEnv(conda=[1, 2])


def test_env_vars_in_task(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote(runtime_env={"env_vars": {"RTENV_PROBE": "tpu42"}})
    def read_env():
        return os.environ.get("RTENV_PROBE")

    @ray_tpu.remote
    def read_env_plain():
        return os.environ.get("RTENV_PROBE")

    assert ray_tpu.get(read_env.remote()) == "tpu42"
    # a different env hash must not reuse the env-carrying worker
    assert ray_tpu.get(read_env_plain.remote()) is None


def test_working_dir_and_py_modules(ray_start_regular, tmp_path):
    import ray_tpu

    wd = tmp_path / "app"
    wd.mkdir()
    (wd / "data.txt").write_text("payload-123")
    mod = tmp_path / "mymod_rtenv_test.py"
    mod.write_text("MAGIC = 777\n")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd),
                                 "py_modules": [str(mod)]})
    def probe():
        import mymod_rtenv_test
        with open("data.txt") as f:
            return f.read(), mymod_rtenv_test.MAGIC

    data, magic = ray_tpu.get(probe.remote())
    assert data == "payload-123"
    assert magic == 777
    assert "mymod_rtenv_test" not in sys.modules


def test_env_vars_in_actor(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_RTENV": "yes"}})
    class Probe:
        def read(self):
            return os.environ.get("ACTOR_RTENV")

    a = Probe.remote()
    assert ray_tpu.get(a.read.remote()) == "yes"


def _make_wheel(wheel_dir, name, version):
    """Minimal hand-built wheel (no build backend needed: zero egress)."""
    import os
    import zipfile
    os.makedirs(wheel_dir, exist_ok=True)
    whl = os.path.join(wheel_dir, f"{name}-{version}-py3-none-any.whl")
    dist = f"{name}-{version}.dist-info"
    files = {
        f"{name}/__init__.py": f'__version__ = "{version}"\n',
        f"{dist}/METADATA": (f"Metadata-Version: 2.1\nName: {name}\n"
                             f"Version: {version}\n"),
        f"{dist}/WHEEL": ("Wheel-Version: 1.0\nGenerator: test\n"
                          "Root-Is-Purelib: true\nTag: py3-none-any\n"),
    }
    record = "".join(f"{p},,\n" for p in files) + f"{dist}/RECORD,,\n"
    files[f"{dist}/RECORD"] = record
    with zipfile.ZipFile(whl, "w") as z:
        for path, content in files.items():
            z.writestr(path, content)
    return whl


def test_pip_runtime_env_conflicting_versions(tmp_path):
    """Two tasks pin conflicting versions of the same package and run
    concurrently, each inside its own cached venv (reference
    PipProcessor, _private/runtime_env/pip.py:75; local wheelhouse keeps
    the install zero-egress)."""
    import ray_tpu

    wheelhouse = str(tmp_path / "wheels")
    _make_wheel(wheelhouse, "conflictpkg", "1.0.0")
    _make_wheel(wheelhouse, "conflictpkg", "2.0.0")
    ray_tpu.init(num_cpus=2, system_config={
        "runtime_env_pip_find_links": wheelhouse,
        "runtime_env_cache_dir": str(tmp_path / "env_cache"),
    })
    try:
        @ray_tpu.remote
        def which_version():
            import conflictpkg
            return conflictpkg.__version__

        r1 = which_version.options(
            runtime_env={"pip": ["conflictpkg==1.0.0"]}).remote()
        r2 = which_version.options(
            runtime_env={"pip": ["conflictpkg==2.0.0"]}).remote()
        assert sorted(ray_tpu.get([r1, r2], timeout=240)) == \
            ["1.0.0", "2.0.0"]

        # the venvs are cached: a second round reuses them (fast path)
        import time
        t0 = time.monotonic()
        r3 = which_version.options(
            runtime_env={"pip": ["conflictpkg==1.0.0"]}).remote()
        assert ray_tpu.get(r3, timeout=60) == "1.0.0"
        assert time.monotonic() - t0 < 30
    finally:
        ray_tpu.shutdown()


def test_pip_runtime_env_bad_package_fails_cleanly(tmp_path):
    """An unresolvable pip requirement surfaces as a task error, not a
    hang (reference RuntimeEnvSetupError path)."""
    import pytest as _pytest

    import ray_tpu

    ray_tpu.init(num_cpus=1, system_config={
        "runtime_env_pip_find_links": str(tmp_path / "empty_wheels"),
        "runtime_env_cache_dir": str(tmp_path / "env_cache2"),
    })
    try:
        @ray_tpu.remote
        def f():
            return 1

        ref = f.options(
            runtime_env={"pip": ["no-such-package==9.9.9"]}).remote()
        with _pytest.raises(ray_tpu.exceptions.RayTpuError):
            ray_tpu.get(ref, timeout=120)
    finally:
        ray_tpu.shutdown()
