"""Runtime environments (SURVEY.md §2.3 runtime_env row: reference
python/ray/runtime_env/ + _private/runtime_env/packaging.py)."""

import os
import sys

import pytest


def test_runtime_env_validation(tmp_path):
    from ray_tpu.runtime_env import RuntimeEnv

    with pytest.raises(TypeError):
        RuntimeEnv(env_vars={"A": 1})
    with pytest.raises(ValueError):
        RuntimeEnv(working_dir=str(tmp_path / "nope"))
    with pytest.raises(TypeError):
        RuntimeEnv(pip="not-a-list")
    assert RuntimeEnv(pip=["b", "a"])["pip"] == ["a", "b"]
    with pytest.raises(ValueError):
        RuntimeEnv.from_dict({"bogus_field": 1})
    env = RuntimeEnv(env_vars={"A": "1"}, working_dir=str(tmp_path))
    assert env.to_dict()["env_vars"] == {"A": "1"}


def test_conda_spec_folds_into_pip(tmp_path):
    """conda environment.yml content routes through the venv isolation
    path: dependencies become pip requirements (reference conda plugin,
    _private/runtime_env/conda.py); named envs are rejected — no conda
    binary in hermetic images."""
    from ray_tpu.runtime_env import RuntimeEnv

    env = RuntimeEnv(conda={"dependencies": [
        "python=3.10", "pip", "left-pad=1.0", {"pip": ["right-pad==2.0"]}]})
    assert env["pip"] == ["left-pad==1.0", "right-pad==2.0"]
    assert "conda" not in env  # wire format stays pip-only

    # conda + pip merge, deduped
    env = RuntimeEnv(pip=["right-pad==2.0"],
                     conda={"dependencies": [{"pip": ["a==1"]}]})
    assert env["pip"] == ["a==1", "right-pad==2.0"]

    # environment.yml file path parses the same way
    yml = tmp_path / "environment.yml"
    yml.write_text("name: t\ndependencies:\n  - python=3.10\n"
                   "  - numpy>=1.20\n  - pip:\n    - req==1.0\n")
    env = RuntimeEnv(conda=str(yml))
    assert env["pip"] == ["numpy>=1.20", "req==1.0"]

    with pytest.raises(ValueError):
        RuntimeEnv(conda="some-named-env")
    with pytest.raises(TypeError):
        RuntimeEnv(conda=[1, 2])


def test_env_vars_in_task(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote(runtime_env={"env_vars": {"RTENV_PROBE": "tpu42"}})
    def read_env():
        return os.environ.get("RTENV_PROBE")

    @ray_tpu.remote
    def read_env_plain():
        return os.environ.get("RTENV_PROBE")

    assert ray_tpu.get(read_env.remote()) == "tpu42"
    # a different env hash must not reuse the env-carrying worker
    assert ray_tpu.get(read_env_plain.remote()) is None


def test_working_dir_and_py_modules(ray_start_regular, tmp_path):
    import ray_tpu

    wd = tmp_path / "app"
    wd.mkdir()
    (wd / "data.txt").write_text("payload-123")
    mod = tmp_path / "mymod_rtenv_test.py"
    mod.write_text("MAGIC = 777\n")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd),
                                 "py_modules": [str(mod)]})
    def probe():
        import mymod_rtenv_test
        with open("data.txt") as f:
            return f.read(), mymod_rtenv_test.MAGIC

    data, magic = ray_tpu.get(probe.remote())
    assert data == "payload-123"
    assert magic == 777
    assert "mymod_rtenv_test" not in sys.modules


def test_env_vars_in_actor(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_RTENV": "yes"}})
    class Probe:
        def read(self):
            return os.environ.get("ACTOR_RTENV")

    a = Probe.remote()
    assert ray_tpu.get(a.read.remote()) == "yes"


def _make_wheel(wheel_dir, name, version):
    """Minimal hand-built wheel (no build backend needed: zero egress)."""
    import os
    import zipfile
    os.makedirs(wheel_dir, exist_ok=True)
    whl = os.path.join(wheel_dir, f"{name}-{version}-py3-none-any.whl")
    dist = f"{name}-{version}.dist-info"
    files = {
        f"{name}/__init__.py": f'__version__ = "{version}"\n',
        f"{dist}/METADATA": (f"Metadata-Version: 2.1\nName: {name}\n"
                             f"Version: {version}\n"),
        f"{dist}/WHEEL": ("Wheel-Version: 1.0\nGenerator: test\n"
                          "Root-Is-Purelib: true\nTag: py3-none-any\n"),
    }
    record = "".join(f"{p},,\n" for p in files) + f"{dist}/RECORD,,\n"
    files[f"{dist}/RECORD"] = record
    with zipfile.ZipFile(whl, "w") as z:
        for path, content in files.items():
            z.writestr(path, content)
    return whl


def test_pip_runtime_env_conflicting_versions(tmp_path):
    """Two tasks pin conflicting versions of the same package and run
    concurrently, each inside its own cached venv (reference
    PipProcessor, _private/runtime_env/pip.py:75; local wheelhouse keeps
    the install zero-egress)."""
    import ray_tpu

    wheelhouse = str(tmp_path / "wheels")
    _make_wheel(wheelhouse, "conflictpkg", "1.0.0")
    _make_wheel(wheelhouse, "conflictpkg", "2.0.0")
    ray_tpu.init(num_cpus=2, system_config={
        "runtime_env_pip_find_links": wheelhouse,
        "runtime_env_cache_dir": str(tmp_path / "env_cache"),
    })
    try:
        @ray_tpu.remote
        def which_version():
            import conflictpkg
            return conflictpkg.__version__

        r1 = which_version.options(
            runtime_env={"pip": ["conflictpkg==1.0.0"]}).remote()
        r2 = which_version.options(
            runtime_env={"pip": ["conflictpkg==2.0.0"]}).remote()
        assert sorted(ray_tpu.get([r1, r2], timeout=240)) == \
            ["1.0.0", "2.0.0"]

        # the venvs are cached: a second round reuses them (fast path)
        import time
        t0 = time.monotonic()
        r3 = which_version.options(
            runtime_env={"pip": ["conflictpkg==1.0.0"]}).remote()
        assert ray_tpu.get(r3, timeout=60) == "1.0.0"
        assert time.monotonic() - t0 < 30
    finally:
        ray_tpu.shutdown()


def test_pip_runtime_env_bad_package_fails_cleanly(tmp_path):
    """An unresolvable pip requirement surfaces as a task error, not a
    hang (reference RuntimeEnvSetupError path)."""
    import pytest as _pytest

    import ray_tpu

    ray_tpu.init(num_cpus=1, system_config={
        "runtime_env_pip_find_links": str(tmp_path / "empty_wheels"),
        "runtime_env_cache_dir": str(tmp_path / "env_cache2"),
    })
    try:
        @ray_tpu.remote
        def f():
            return 1

        ref = f.options(
            runtime_env={"pip": ["no-such-package==9.9.9"]}).remote()
        with _pytest.raises(ray_tpu.exceptions.RayTpuError):
            ray_tpu.get(ref, timeout=120)
    finally:
        ray_tpu.shutdown()


def test_container_command_construction(tmp_path):
    """wrap_worker_command builds the reference-shaped podman/docker
    invocation: session + store mounts, host namespaces, critical env as
    explicit --env, run_options, --entrypoint python, image, worker
    args.  Pure construction — no container runtime needed."""
    import pytest

    from ray_tpu.runtime_env.container import (ContainerError, validate,
                                               wrap_worker_command)

    with pytest.raises(ContainerError):
        validate({})                       # no image
    with pytest.raises(ContainerError):
        validate({"image": "img", "run_options": "not-a-list"})

    fake = tmp_path / "fakedriver"
    fake.write_text("#!/bin/sh\n")
    fake.chmod(0o755)
    cmd = wrap_worker_command(
        {"image": "myimg:1", "driver": str(fake),
         "run_options": ["--memory=1g"]},
        ["/usr/bin/python3", "-m", "ray_tpu.runtime.worker_main",
         "--worker-id", "abc"],
        session_dir="/tmp/sess", store_path="/dev/shm/ray_tpu_store_x",
        env={"PYTHONPATH": "/repo", "RAY_TPU_SYSTEM_CONFIG": "{}",
             "IGNORED_KEY": "x"})
    assert cmd[0] == str(fake) and cmd[1] == "run"
    assert "-v" in cmd and "/tmp/sess:/tmp/sess" in cmd
    assert "/dev/shm:/dev/shm" in cmd
    for ns in ("--network=host", "--pid=host", "--ipc=host"):
        assert ns in cmd
    assert "PYTHONPATH=/repo" in cmd
    assert not any(c.startswith("IGNORED_KEY") for c in cmd)
    assert "--memory=1g" in cmd
    i = cmd.index("--entrypoint")
    assert cmd[i + 1] == "python" and cmd[i + 2] == "myimg:1"
    # host interpreter path is dropped; worker args survive
    assert "/usr/bin/python3" not in cmd
    assert cmd[-3:] == ["ray_tpu.runtime.worker_main",
                        "--worker-id", "abc"][-3:]

    with pytest.raises(ContainerError, match="not found"):
        wrap_worker_command({"image": "img", "driver": "no-such-runtime"},
                            ["python", "-m", "x"], session_dir="/t",
                            store_path="/dev/shm/s", env={})

    # user runtime_env env_vars ride into the container as --env too:
    # the raylet merges them into the spawn env, and the descriptor JSON
    # (RAY_TPU_RUNTIME_ENV) names which keys are the user's
    import json
    renv = json.dumps({"env_vars": {"MY_FLAG": "7", "OTHER": "y"}})
    cmd = wrap_worker_command(
        {"image": "myimg:1", "driver": str(fake)},
        ["/usr/bin/python3", "-m", "ray_tpu.runtime.worker_main"],
        session_dir="/tmp/sess", store_path="/dev/shm/ray_tpu_store_x",
        env={"RAY_TPU_RUNTIME_ENV": renv, "MY_FLAG": "7", "OTHER": "y",
             "HOST_SECRET": "nope"})
    assert "MY_FLAG=7" in cmd and "OTHER=y" in cmd
    assert not any(c.startswith("HOST_SECRET") for c in cmd)

    # blanking a var is a legitimate override of an image-baked value:
    # user env_vars forward even when empty
    renv = json.dumps({"env_vars": {"BLANKED": ""}})
    cmd = wrap_worker_command(
        {"image": "myimg:1", "driver": str(fake)},
        ["/usr/bin/python3", "-m", "ray_tpu.runtime.worker_main"],
        session_dir="/tmp/sess", store_path="/dev/shm/ray_tpu_store_x",
        env={"RAY_TPU_RUNTIME_ENV": renv, "BLANKED": ""})
    assert "BLANKED=" in cmd


def test_container_runtime_env_end_to_end(ray_start_regular, tmp_path):
    """A task with runtime_env={"container": ...} executes through the
    container driver: a recording fake driver proves the raylet wrapped
    the worker spawn (and passes execution through, standing in for a
    real podman on hosts that have one)."""
    import os

    import ray_tpu

    record = tmp_path / "invocations.log"
    fake = tmp_path / "fakepodman"
    # records its argv, then strips the container wrapping and execs the
    # worker with the image's entrypoint (host python stands in)
    fake.write_text(f"""#!/bin/bash
echo "$@" >> {record}
args=()
entry=python
seen_image=0
while [[ $# -gt 0 ]]; do
  case "$1" in
    run|--rm|--network=host|--pid=host|--ipc=host) shift ;;
    -v|--env) shift 2 ;;
    --entrypoint) entry="$2"; shift 2 ;;
    testimg:*) seen_image=1; shift ;;
    *) if [[ $seen_image == 1 ]]; then args+=("$1"); fi; shift ;;
  esac
done
exec "$entry" "${{args[@]}}"
""")
    fake.chmod(0o755)

    @ray_tpu.remote(runtime_env={"container": {"image": "testimg:9",
                                               "driver": str(fake)}})
    def inside():
        return os.getpid()

    pid = ray_tpu.get(inside.remote(), timeout=300)
    assert isinstance(pid, int)
    logged = record.read_text()
    assert "testimg:9" in logged
    assert "--network=host" in logged
    assert "worker_main" in logged
