"""Runtime environments (SURVEY.md §2.3 runtime_env row: reference
python/ray/runtime_env/ + _private/runtime_env/packaging.py)."""

import os
import sys

import pytest


def test_runtime_env_validation(tmp_path):
    from ray_tpu.runtime_env import RuntimeEnv

    with pytest.raises(TypeError):
        RuntimeEnv(env_vars={"A": 1})
    with pytest.raises(ValueError):
        RuntimeEnv(working_dir=str(tmp_path / "nope"))
    with pytest.raises(ValueError):
        RuntimeEnv(pip=["requests"])
    with pytest.raises(ValueError):
        RuntimeEnv.from_dict({"bogus_field": 1})
    env = RuntimeEnv(env_vars={"A": "1"}, working_dir=str(tmp_path))
    assert env.to_dict()["env_vars"] == {"A": "1"}


def test_env_vars_in_task(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote(runtime_env={"env_vars": {"RTENV_PROBE": "tpu42"}})
    def read_env():
        return os.environ.get("RTENV_PROBE")

    @ray_tpu.remote
    def read_env_plain():
        return os.environ.get("RTENV_PROBE")

    assert ray_tpu.get(read_env.remote()) == "tpu42"
    # a different env hash must not reuse the env-carrying worker
    assert ray_tpu.get(read_env_plain.remote()) is None


def test_working_dir_and_py_modules(ray_start_regular, tmp_path):
    import ray_tpu

    wd = tmp_path / "app"
    wd.mkdir()
    (wd / "data.txt").write_text("payload-123")
    mod = tmp_path / "mymod_rtenv_test.py"
    mod.write_text("MAGIC = 777\n")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd),
                                 "py_modules": [str(mod)]})
    def probe():
        import mymod_rtenv_test
        with open("data.txt") as f:
            return f.read(), mymod_rtenv_test.MAGIC

    data, magic = ray_tpu.get(probe.remote())
    assert data == "payload-123"
    assert magic == 777
    assert "mymod_rtenv_test" not in sys.modules


def test_env_vars_in_actor(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_RTENV": "yes"}})
    class Probe:
        def read(self):
            return os.environ.get("ACTOR_RTENV")

    a = Probe.remote()
    assert ray_tpu.get(a.read.remote()) == "yes"
