"""Tune library tests (model: reference python/ray/tune/tests)."""

import random

import pytest

from ray_tpu.air import Checkpoint, RunConfig, session
from ray_tpu.tune import (ASHAScheduler, BasicVariantGenerator,
                          ConcurrencyLimiter, HyperOptStyleSearch,
                          MedianStoppingRule, PopulationBasedTraining,
                          TuneConfig, Tuner, choice, grid_search, loguniform,
                          randint, uniform)
from ray_tpu.tune.sample import generate_variants


def test_generate_variants_grid_and_samples():
    space = {"lr": grid_search([0.1, 0.01]), "wd": uniform(0, 1),
             "layers": grid_search([2, 4]), "fixed": 7}
    variants = generate_variants(space, random.Random(0), num_samples=3)
    assert len(variants) == 12   # 2 x 2 grid x 3 samples
    lrs = {v["lr"] for v in variants}
    assert lrs == {0.1, 0.01}
    assert all(0 <= v["wd"] <= 1 and v["fixed"] == 7 for v in variants)


def test_generate_variants_nested():
    space = {"opt": {"lr": grid_search([1, 2]), "b1": 0.9},
             "n": randint(1, 10)}
    vs = generate_variants(space, random.Random(0))
    assert len(vs) == 2
    assert {v["opt"]["lr"] for v in vs} == {1, 2}
    assert all(v["opt"]["b1"] == 0.9 for v in vs)


def test_domains_sample_ranges():
    rng = random.Random(0)
    assert 1e-4 <= loguniform(1e-4, 1e-1).sample(rng) <= 1e-1
    assert choice(["a", "b"]).sample(rng) in ("a", "b")
    assert 0 <= randint(0, 5).sample(rng) < 5


def test_concurrency_limiter():
    base = BasicVariantGenerator({"x": uniform(0, 1)}, num_samples=5)
    lim = ConcurrencyLimiter(base, max_concurrent=2)
    a = lim.suggest("t1")
    b = lim.suggest("t2")
    assert a is not None and b is not None
    assert lim.suggest("t3") is None           # capped
    lim.on_trial_complete("t1", {"x": 1.0})
    assert lim.suggest("t3") is not None       # freed


def test_asha_stops_bad_trials():
    sched = ASHAScheduler(metric="score", mode="max", grace_period=1,
                          reduction_factor=2, max_t=100)

    class T:
        def __init__(self, tid):
            self.trial_id = tid

    class R:
        trials = []

    # descending scores: once the rung fills, worse-than-cutoff trials stop
    decisions = {}
    for i, score in enumerate([4.0, 3.0, 2.0, 1.0]):
        t = T(f"t{i}")
        decisions[i] = sched.on_trial_result(
            R, t, {"training_iteration": 1, "score": score})
    assert decisions[0] == "CONTINUE"      # rung not filled yet
    assert decisions[2] == "STOP"
    assert decisions[3] == "STOP"


def test_median_stopping():
    sched = MedianStoppingRule(metric="score", mode="max", grace_period=0,
                               min_samples_required=2)

    class T:
        def __init__(self, tid):
            self.trial_id = tid

    good1, good2, bad = T("g1"), T("g2"), T("b")
    for it in range(3):
        sched.on_trial_result(None, good1, {"training_iteration": it,
                                            "score": 10.0})
        sched.on_trial_result(None, good2, {"training_iteration": it,
                                            "score": 8.0})
    d = sched.on_trial_result(None, bad, {"training_iteration": 3,
                                          "score": 1.0})
    assert d == "STOP"


def test_hyperopt_style_search_learns():
    space = {"x": uniform(-1, 1)}
    s = HyperOptStyleSearch(space, metric="score", mode="max", n_initial=4,
                            seed=0)
    # feed observations: score = x (higher x better)
    for i in range(8):
        cfg = s.suggest(f"t{i}")
        s.on_trial_complete(f"t{i}", {"score": cfg["x"]})
    later = [s.suggest(f"u{i}")["x"] for i in range(10)]
    assert sum(later) / len(later) > 0   # biased toward good region


def test_tuner_grid_experiment(ray_start_regular):
    def trainable(config):
        for i in range(3):
            session.report({"score": config["lr"] * (i + 1)})

    tuner = Tuner(trainable,
                  param_space={"lr": grid_search([1.0, 2.0, 3.0])},
                  tune_config=TuneConfig(metric="score", mode="max"))
    grid = tuner.fit()
    assert len(grid) == 3
    best = grid.get_best_result()
    assert best.metrics["config"]["lr"] == 3.0
    assert best.metrics["score"] == 9.0
    assert not grid.errors


def test_tuner_with_checkpoints_and_failure(ray_start_regular):
    def flaky(config):
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["i"] + 1 if ckpt else 0
        for i in range(start, 4):
            if i == 2 and start == 0:
                raise RuntimeError("transient")
            session.report({"i": i},
                           checkpoint=Checkpoint.from_dict({"i": i}))

    from ray_tpu.air import FailureConfig
    tuner = Tuner(flaky, param_space={},
                  tune_config=TuneConfig(metric="i", mode="max"),
                  run_config=RunConfig(
                      failure_config=FailureConfig(max_failures=2)))
    grid = tuner.fit()
    assert not grid.errors
    # resumed from checkpoint i=1 and reached i=3
    assert grid.get_best_result().metrics["i"] == 3


def test_tuner_asha_integration(ray_start_regular):
    def trainable(config):
        for i in range(10):
            session.report({"score": config["q"] * (i + 1)})

    tuner = Tuner(
        trainable,
        param_space={"q": grid_search([1.0, 5.0, 10.0, 20.0])},
        tune_config=TuneConfig(
            metric="score", mode="max", max_concurrent_trials=4,
            scheduler=ASHAScheduler(metric="score", mode="max",
                                    grace_period=2, reduction_factor=2,
                                    max_t=10)))
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.metrics["config"]["q"] == 20.0
