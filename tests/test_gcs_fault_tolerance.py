"""GCS restart / fault-tolerance tests (cf. reference
python/ray/tests/test_gcs_fault_tolerance.py)."""

import time

import numpy as np

import ray_tpu


def test_gcs_restart_preserves_state(ray_start_cluster):
    """Kill + restart the GCS mid-run: a detached named actor is still
    resolvable and callable, KV entries survive, and nodes re-attach."""
    cluster = ray_start_cluster
    cluster.add_node(resources={"CPU": 2})
    cluster.wait_for_nodes(2)
    ray_tpu.init(num_cpus=1, address=cluster.address)

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.options(name="survivor", lifetime="detached").remote()
    assert ray_tpu.get(c.inc.remote(), timeout=60) == 1

    from ray_tpu.runtime.core_worker import get_global_worker
    w = get_global_worker()
    w.gcs.kv_put("ft:marker", b"before-restart")
    time.sleep(0.5)  # let the snapshot tick capture the latest state

    cluster.restart_gcs()

    # the restarted GCS replayed the actor table: resolve by name and call
    deadline = time.monotonic() + 60
    while True:
        try:
            h = ray_tpu.get_actor("survivor")
            assert ray_tpu.get(h.inc.remote(), timeout=60) == 2
            break
        except (ray_tpu.exceptions.RayTpuError, ValueError,
                ConnectionError):
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)
    assert w.gcs.kv_get("ft:marker") == b"before-restart"
    # both raylets re-attach via heartbeats; new leases still work
    cluster.wait_for_nodes(2, timeout=60)
    ray_tpu.shutdown()


def test_wal_survives_immediate_gcs_kill():
    """A mutation acknowledged an instant before SIGKILL is replayed from
    the write-ahead journal — the snapshot tick is disabled (1h interval)
    so only the per-mutation WAL can provide durability (reference writes
    through to the store client per mutation,
    store_client/redis_store_client.h:28)."""
    from ray_tpu._private.config import CONFIG
    from ray_tpu.cluster_utils import Cluster
    saved = CONFIG.copy_overrides()
    CONFIG.set("gcs_snapshot_interval_s", 3600.0)
    cluster = None
    try:
        cluster = Cluster()
        cluster.wait_for_nodes(1)
        ray_tpu.init(num_cpus=2, address=cluster.address)

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        c = Counter.options(name="wal-actor", lifetime="detached").remote()
        assert ray_tpu.get(c.inc.remote(), timeout=60) == 1

        from ray_tpu.runtime.core_worker import get_global_worker
        w = get_global_worker()
        w.gcs.kv_put("wal:marker", b"acked-then-killed")
        # no sleep: the kv_put reply means the WAL record is on disk;
        # restart_gcs SIGKILLs right away, so a snapshot can never run
        cluster.restart_gcs()

        deadline = time.monotonic() + 60
        while True:
            try:
                assert w.gcs.kv_get("wal:marker") == b"acked-then-killed"
                h = ray_tpu.get_actor("wal-actor")
                assert ray_tpu.get(h.inc.remote(), timeout=60) == 2
                break
            except (ray_tpu.exceptions.RayTpuError, ValueError,
                    ConnectionError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.5)
        ray_tpu.shutdown()
    finally:
        CONFIG.set_overrides(saved)
        if cluster is not None:
            cluster.shutdown()


def test_tasks_keep_working_after_gcs_restart(ray_start_cluster):
    """Task submission rides through a GCS restart: the driver's client
    reconnects and raylets keep serving leases."""
    cluster = ray_start_cluster
    cluster.wait_for_nodes(1)
    ray_tpu.init(num_cpus=2, address=cluster.address)

    @ray_tpu.remote
    def square(x):
        return x * x

    assert ray_tpu.get(square.remote(7), timeout=60) == 49
    time.sleep(0.5)
    cluster.restart_gcs()
    deadline = time.monotonic() + 60
    while True:
        try:
            assert ray_tpu.get(square.remote(9), timeout=60) == 81
            break
        except ray_tpu.exceptions.RayTpuError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)
    # shm objects and lineage were never GCS state: puts/gets unaffected
    ref = ray_tpu.put(np.arange(100_000, dtype=np.float64))
    assert float(ray_tpu.get(ref, timeout=60)[-1]) == 99_999.0
    ray_tpu.shutdown()
