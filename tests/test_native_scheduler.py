"""Native C++ cluster scheduler vs Python fallback: same semantics."""

import pytest

from ray_tpu._core.scheduler import (NativeClusterScheduler,
                                     PyClusterScheduler, native_available)

SCHEDULERS = [PyClusterScheduler]
if native_available():
    SCHEDULERS.append(NativeClusterScheduler)


@pytest.fixture(params=SCHEDULERS, ids=lambda c: c.__name__)
def sched(request):
    return request.param(spill_threshold=0.5, top_k=2)


def test_local_first_under_threshold(sched):
    sched.update_node("local", {"CPU": 8}, {"CPU": 8})
    sched.update_node("other", {"CPU": 8}, {"CPU": 8})
    # local stays preferred while post-placement utilization <= 0.5
    assert sched.best_node({"CPU": 2}, local_id="local") == "local"


def test_spills_when_local_hot(sched):
    sched.update_node("local", {"CPU": 8}, {"CPU": 2})   # 75% used
    sched.update_node("cold", {"CPU": 8}, {"CPU": 8})
    assert sched.best_node({"CPU": 1}, local_id="local") == "cold"


def test_infeasible_returns_none(sched):
    sched.update_node("a", {"CPU": 2}, {"CPU": 2})
    assert sched.best_node({"CPU": 4}) is None
    assert not sched.feasible_anywhere({"CPU": 4})
    assert sched.feasible_anywhere({"CPU": 2})


def test_feasible_anywhere_uses_total_not_available(sched):
    sched.update_node("a", {"CPU": 4}, {"CPU": 0})
    assert sched.best_node({"CPU": 1}) is None        # nothing available now
    assert sched.feasible_anywhere({"CPU": 1})        # but not infeasible


def test_custom_and_fractional_resources(sched):
    sched.update_node("t", {"CPU": 4, "TPU": 8, "slice": 1},
                      {"CPU": 3.5, "TPU": 8, "slice": 1})
    assert sched.best_node({"CPU": 0.5, "TPU": 4}) == "t"
    assert sched.best_node({"CPU": 3.75}) is None     # 3.75 > 3.5 available
    assert sched.best_node({"slice": 1, "CPU": 0.1}) == "t"


def test_dead_nodes_skipped(sched):
    sched.update_node("a", {"CPU": 4}, {"CPU": 4}, alive=False)
    sched.update_node("b", {"CPU": 4}, {"CPU": 1})
    assert sched.best_node({"CPU": 1}) == "b"
    sched.remove_node("b")
    assert sched.best_node({"CPU": 1}) is None
    assert sched.num_nodes() == 1


def test_top_k_rotation_spreads_ties(sched):
    sched.update_node("a", {"CPU": 8}, {"CPU": 8})
    sched.update_node("b", {"CPU": 8}, {"CPU": 8})
    picks = {sched.best_node({"CPU": 1}) for _ in range(8)}
    assert picks == {"a", "b"}   # top_k=2 rotates over equal candidates


def test_packing_prefers_fuller_node(sched):
    # hybrid under threshold packs: lowest post-placement utilization wins,
    # but among *under-threshold* nodes the scheduler is utilization-sorted;
    # the emptier node scores lower utilization and wins when no local given
    sched.update_node("busy", {"CPU": 10}, {"CPU": 3})
    sched.update_node("idle", {"CPU": 10}, {"CPU": 9})
    assert sched.best_node({"CPU": 1}) == "idle"
