"""Always-on runtime telemetry (_private/runtime_metrics.py): hot-path
instruments, flush-to-GCS, the kill switch, Prometheus conformance, and
the task-event table fixes that ride along (docs/observability.md)."""

import json
import threading
import time

import pytest


def _wait_for(pred, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {msg}")


# ------------------------------------------------------- instrument units
def test_hot_path_instruments():
    from ray_tpu._private import runtime_metrics as rtm

    c = rtm.counter("tm_unit_total", "count things")
    c.inc()
    c.inc(4)
    h = rtm.histogram("tm_unit_ms", "latency", boundaries=(1.0, 10.0))
    h.observe(0.5)
    h.observe(5.0)
    h.observe(500.0)   # overflow bucket
    t0 = rtm.now()
    h.observe_since(t0)  # ~0 ms -> first bucket
    f = rtm.histogram_family("tm_unit_fam", "per-method", tag_key="method")
    f.observe("alpha", 2.0)
    f.get("beta").observe(3.0)
    g = rtm.gauge("tm_unit_peak", watermark=True)
    g.set_max(7)
    g.set_max(2)   # must not lower the high-water mark
    rtm.gauge_callback("tm_unit_cb", "polled", lambda: 11.0)

    snap = rtm.snapshot()
    assert snap["tm_unit_total"]["values"]["{}"] == 5.0
    hist = snap["tm_unit_ms"]["values"]["{}"]
    assert hist["count"] == 4
    assert hist["buckets"]["+Inf"] == 1
    assert hist["sum"] == pytest.approx(505.5, abs=1.0)
    fam = snap["tm_unit_fam"]["values"]
    assert json.dumps({"method": "alpha"}) in fam
    assert fam[json.dumps({"method": "beta"})]["count"] == 1
    assert snap["tm_unit_peak"]["values"]["{}"] == 7
    assert snap["tm_unit_cb"]["values"]["{}"] == 11.0
    # a plain snapshot (debugging) must NOT consume the high-water
    # mark; only the flusher's reset_watermarks snapshot does
    assert rtm.snapshot()["tm_unit_peak"]["values"]["{}"] == 7
    assert rtm.snapshot(
        reset_watermarks=True)["tm_unit_peak"]["values"]["{}"] == 7
    assert rtm.snapshot()["tm_unit_peak"]["values"]["{}"] == 0.0


def test_histogram_family_label_cap():
    from ray_tpu._private import runtime_metrics as rtm

    f = rtm.HistogramFamily("tm_capfam", max_labels=4)
    for i in range(20):
        f.observe(f"label-{i}", 1.0)
    labels = f.labels()
    assert len(labels) <= 5  # 4 real + __other__ overflow
    assert "__other__" in labels


def test_kill_switch_returns_noops():
    from ray_tpu._private import runtime_metrics as rtm
    from ray_tpu._private.config import CONFIG

    CONFIG.set("telemetry_enabled", False)
    try:
        c = rtm.counter("tm_killed_total")
        h = rtm.histogram("tm_killed_ms")
        f = rtm.histogram_family("tm_killed_fam")
        g = rtm.gauge("tm_killed_gauge")
        rtm.gauge_callback("tm_killed_cb", "", lambda: 1.0)
        # all record calls are no-ops and nothing registers
        c.inc()
        h.observe(1.0)
        h.observe_since(rtm.now())
        f.observe("m", 1.0)
        f.get("m").observe(2.0)
        g.set(3.0)
        g.set_max(4.0)
        snap = rtm.snapshot()
        assert not any(k.startswith("tm_killed") for k in snap)
    finally:
        CONFIG.set("telemetry_enabled", True)


def test_concurrent_counter_is_approximately_lossless():
    """The lock-free record path may lose the odd update under races,
    but must stay in the right order of magnitude (monitoring data)."""
    from ray_tpu._private import runtime_metrics as rtm

    c = rtm.counter("tm_race_total")

    def worker():
        for _ in range(10000):
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value >= 10000  # at least one thread's worth survived fully


# ------------------------------------------------------ prometheus render
def test_prometheus_exposition_conformant():
    from ray_tpu._private.runtime_metrics import prometheus_exposition

    entries = [
        ("req_total", "w1", {"type": "counter", "description": "reqs",
                             "values": {"{}": 5.0}}),
        ("lat_ms", "w1", {
            "type": "histogram", "description": "latency",
            "values": {json.dumps({"method": "m"}): {
                "buckets": {"1.0": 2, "10.0": 3, "+Inf": 1},
                "sum": 40.0, "count": 6}}}),
    ]
    text = prometheus_exposition(entries)
    lines = text.splitlines()
    assert "# TYPE req_total counter" in lines
    assert 'req_total{worker="w1"} 5.0' in lines
    assert "# TYPE lat_ms histogram" in lines
    # cumulative buckets, +Inf present, bucket series on <name>_bucket
    assert 'lat_ms_bucket{le="1.0",method="m",worker="w1"} 2' in lines
    assert 'lat_ms_bucket{le="10.0",method="m",worker="w1"} 5' in lines
    assert 'lat_ms_bucket{le="+Inf",method="m",worker="w1"} 6' in lines
    assert 'lat_ms_count{method="m",worker="w1"} 6' in lines
    assert 'lat_ms_sum{method="m",worker="w1"} 40.0' in lines
    # no raw per-bucket samples on the bare histogram name
    assert not any(l.startswith("lat_ms{") for l in lines)


def test_user_histogram_conformant_via_exposition(ray_start_regular):
    """util.metrics.Histogram stores buckets+sum+count and renders as a
    conformant Prometheus histogram (the old format emitted raw bucket
    counts with an `le` tag on the bare metric name)."""
    from ray_tpu._private.runtime_metrics import prometheus_exposition
    from ray_tpu.util import metrics as um

    h = um.Histogram("tm_app_s", "app", boundaries=[0.1, 1.0],
                     tag_keys=("route",))
    for v in (0.05, 0.5, 7.0):
        h.observe(v, tags={"route": "r"})
    h.flush()

    snap = um.query_metrics("tm_app_s")
    assert snap, "histogram did not reach the GCS KV"
    key, data = next(iter(snap.items()))
    rec = next(iter(data["values"].values()))
    assert rec["count"] == 3 and rec["buckets"]["+Inf"] == 1
    text = prometheus_exposition(
        [("tm_app_s", key.split("/")[-1], data)])
    assert 'le="+Inf"' in text
    assert "tm_app_s_count" in text and "tm_app_s_sum" in text


# ----------------------------------------------------------- flush-to-GCS
def test_runtime_metrics_flush_to_gcs(ray_start_regular):
    """Hot-path instruments from every component land in the GCS KV
    metrics/ namespace and surface through list_metrics()."""
    import ray_tpu
    from ray_tpu._private import runtime_metrics as rtm
    from ray_tpu.experimental.state import list_metrics

    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get([f.remote(i) for i in range(3)]) == [1, 2, 3]
    rtm.flush_now()   # driver-side metrics, without waiting the interval

    def _published():
        names = {r["name"] for r in list_metrics(prefix="ray_tpu_")}
        if not {"ray_tpu_task_e2e_ms", "ray_tpu_rpc_dispatch_ms",
                "ray_tpu_lease_grant_ms"} <= names:
            return False
        # per-method dispatch rows (worker/raylet flush on their own
        # 2 s ticks, so the task-path methods can trail the first keys)
        methods = {r["tags"].get("method")
                   for r in list_metrics(prefix="ray_tpu_rpc_dispatch_ms")}
        return "push_tasks" in methods or "lease_worker" in methods

    _wait_for(_published, msg="runtime metrics in GCS KV")
    rows = {r["name"]: r for r in list_metrics(prefix="ray_tpu_")
            if not r["tags"]}
    e2e = rows["ray_tpu_task_e2e_ms"]
    assert e2e["count"] >= 3 and e2e["p95"] > 0


def test_metrics_summary_table(ray_start_regular):
    import ray_tpu
    from ray_tpu._private import runtime_metrics as rtm
    from ray_tpu.experimental.state import metrics_summary

    @ray_tpu.remote
    def f():
        return 0

    ray_tpu.get(f.remote())
    rtm.flush_now()
    _wait_for(lambda: "RPC dispatch latency" in metrics_summary(),
              msg="summary table with RPC section")
    text = metrics_summary()
    assert "P95" in text and "ray_tpu" in text


def test_gcs_skips_durability_for_metrics_keys(tmp_path):
    """Per-interval metric flushes must not grow the WAL or dirty the
    snapshot: only real KV mutations pay durability."""
    from ray_tpu.runtime.gcs import GcsServer

    gcs = GcsServer(persist_path=str(tmp_path / "gcs.json"))
    try:
        gcs._dirty.clear()
        seq0 = gcs._wal_seq
        gcs._handle(None, "kv_put", {"key": "metrics/m/x",
                                     "value": b"{}"})
        assert gcs._wal_seq == seq0, "metrics kv_put was WALed"
        assert not gcs._dirty.is_set(), "metrics kv_put dirtied snapshot"
        gcs._handle(None, "kv_put", {"key": "real_key", "value": b"v"})
        assert gcs._wal_seq > seq0 and gcs._dirty.is_set()
    finally:
        gcs.stop()


def test_gcs_prunes_stale_metrics_keys(tmp_path):
    """A dead process's frozen last snapshot is swept once its payload
    ts goes stale; fresh keys survive."""
    from ray_tpu.runtime.gcs import GcsServer

    gcs = GcsServer()
    try:
        now = time.time()
        gcs._metrics_kv_put(
            "metrics/m/dead",
            json.dumps({"ts": now - 600, "runtime": True}).encode())
        gcs._metrics_kv_put(
            "metrics/m/alive",
            json.dumps({"ts": now, "runtime": True}).encode())
        # a user metric (no runtime marker) has no ts keep-alive: a
        # once-set gauge from a live-but-idle process must NOT be swept
        gcs._metrics_kv_put("metrics/user_gauge/w1",
                            json.dumps({"ts": now - 600}).encode())
        pruned = gcs._prune_stale_metrics(now)
        assert pruned == 1
        with gcs._lock:
            assert "metrics/m/alive" in gcs._kv
            assert "metrics/m/dead" not in gcs._kv
            assert "metrics/user_gauge/w1" in gcs._kv
    finally:
        gcs.stop()


def test_list_metrics_gauge_max_aggregation(ray_start_regular):
    """Gauges merged across processes report both the sum (additive
    gauges) and the largest single-process reading (point-in-time)."""
    import ray_tpu
    from ray_tpu.experimental.state import list_metrics
    w = ray_tpu.runtime.core_worker.get_global_worker()
    for ident, v in (("p1", 4.0), ("p2", 1.0)):
        w.gcs.kv_put(f"metrics/tm_depth/{ident}", json.dumps({
            "type": "gauge", "description": "", "ts": time.time(),
            "values": {"{}": v}}).encode())
    row = next(r for r in list_metrics(prefix="tm_depth"))
    assert row["value"] == 5.0 and row["max"] == 4.0


def test_step_phase_bucket_boundaries_conformant():
    """The training-plane families use sub-ms-resolution boundaries
    (ms-scale steps: a healthy data_wait is tens of microseconds) that
    span to checkpoint-scale tens of seconds, strictly increasing, and
    render as conformant Prometheus histograms (the byte-scale-bucket
    precedent from the serve handoff families)."""
    from ray_tpu._private.runtime_metrics import (HistogramFamily,
                                                  prometheus_exposition)
    from ray_tpu._private.step_stats import STEP_PHASE_MS_BOUNDARIES

    b = STEP_PHASE_MS_BOUNDARIES
    assert b[0] <= 0.01, "sub-ms steps need sub-10us resolution at the low end"
    assert sum(1 for x in b if x < 1.0) >= 5, "too few sub-ms buckets"
    assert b[-1] >= 10000.0, "checkpoint phases reach tens of seconds"
    assert list(b) == sorted(set(b)), "boundaries must strictly increase"

    fam = HistogramFamily("tm_step_phase_ms", "phase",
                          tag_key="phase",
                          boundaries=STEP_PHASE_MS_BOUNDARIES)
    assert fam.boundaries == tuple(sorted(STEP_PHASE_MS_BOUNDARIES))
    # sub-ms observations land in DISTINCT buckets (the point of the
    # low-end resolution)
    fam.observe("data_wait", 0.02)
    fam.observe("data_wait", 0.2)
    fam.observe("data_wait", 40000.0)   # overflow
    payload = fam._payload()
    rec = payload["values"][json.dumps({"phase": "data_wait"})]
    assert len([k for k in rec["buckets"] if k != "+Inf"]) == 2
    assert rec["buckets"]["+Inf"] == 1
    text = prometheus_exposition(
        [("tm_step_phase_ms", "w", payload)])
    assert 'le="+Inf"' in text and "tm_step_phase_ms_count" in text
    # the registered runtime families carry the same boundaries (when
    # telemetry is enabled in this process they are real instruments)
    from ray_tpu._private import runtime_metrics as rtm
    from ray_tpu._private import step_stats as sst
    inst = rtm._instruments.get("ray_tpu_train_phase_ms")
    if inst is not None:
        assert inst.boundaries == tuple(sorted(STEP_PHASE_MS_BOUNDARIES))
        assert sst._M_PHASE_MS is inst


# ------------------------------------------------------- task_events fixes
def test_task_table_eviction_scans_past_live_head():
    """A live (non-terminal) task at the head of first-seen order must
    not block eviction of terminal tasks queued behind it (the
    eviction-stall satellite)."""
    from ray_tpu._private.config import CONFIG
    from ray_tpu._private.task_events import GcsTaskTable

    CONFIG.set("gcs_max_task_events", 10)
    try:
        table = GcsTaskTable()
        # live head, seen first
        table.put_events([{"task_id": "live-0", "state": "RUNNING",
                           "name": "head", "ts": time.time()}])
        # a wave of terminal tasks behind it
        for i in range(50):
            table.put_events([
                {"task_id": f"done-{i}", "state": "SUBMITTED",
                 "name": "t", "ts": time.time()},
                {"task_id": f"done-{i}", "state": "FINISHED",
                 "name": "t", "ts": time.time()},
            ])
        rows = table.list()
        assert len(rows) <= 10, (
            f"table grew to {len(rows)} records past the cap of 10")
        # the live head survived: live entries are spared, not evicted
        assert any(r["task_id"] == "live-0" for r in rows)
    finally:
        CONFIG.set("gcs_max_task_events", 100000)


def test_task_event_buffer_stop_joins_and_noops():
    """stop() joins the flush thread (no racing final flush) and a
    record() after stop is a no-op."""
    from ray_tpu._private.task_events import TaskEventBuffer

    calls = []

    class FakeGcs:
        def call(self, method, payload, timeout=None):
            calls.append(payload)

    buf = TaskEventBuffer(FakeGcs())
    buf.record("t1", "SUBMITTED", name="x")
    _wait_for(lambda: buf._thread is not None, msg="flush thread started")
    buf.stop()
    assert not buf._thread.is_alive(), "stop() must join the flush thread"
    flushed = sum(len(p["events"]) for p in calls)
    assert flushed == 1
    buf.record("t2", "SUBMITTED", name="y")   # after stop: dropped
    buf.flush()
    assert sum(len(p["events"]) for p in calls) == 1
    assert all(ev["task_id"] != "t2"
               for p in calls for ev in p["events"])


def test_task_table_event_list_bounded():
    """One chatty task (a long stream's per-yield instants) cannot grow
    its record's event list without bound."""
    from ray_tpu._private.task_events import GcsTaskTable

    table = GcsTaskTable()
    events = [{"task_id": "s1", "state": "STREAM_ITEM", "name": "gen",
               "ts": time.time() + i * 1e-6, "index": i}
              for i in range(2000)]
    table.put_events(events)
    rec = table.list()[0]
    assert len(rec["events"]) <= 512
    assert rec.get("events_truncated")
    # instants never become the record's lifecycle state
    table.put_events([{"task_id": "s1", "state": "RUNNING", "name": "gen",
                       "ts": time.time()}])
    rec = table.list()[0]
    assert rec["state"] == "RUNNING"


# ---------------------------------------------------------------------
# sixth plane: GCS metrics history (docs/observability.md)

def _mk_payload(v, ts=None):
    return json.dumps({"type": "gauge", "description": "t",
                       "values": {"{}": v},
                       "ts": time.time() if ts is None else ts,
                       "runtime": True}).encode()


def test_history_multi_resolution_downsampling():
    """Each ring seals the LAST write of a closed bucket (last-write-
    wins) and the live bucket surfaces as the series' pending value."""
    from ray_tpu._private.metrics_history import GcsMetricsHistoryTable

    t = GcsMetricsHistoryTable(resolutions=[(1.0, 10), (10.0, 10)])
    base = 1000.0
    for i in range(30):   # 10 writes/s for 3 seconds
        t.record("metrics/m/a", _mk_payload(i), now=base + i * 0.1)
    fine = t.query(name="m", resolution=1.0)
    # buckets 1000 and 1001 sealed with their last write (9, 19);
    # bucket 1002's last write (29) is the pending live point
    assert [p["values"]["{}"] for p in fine] == [9, 19, 29]
    coarse = t.query(name="m", resolution=10.0)
    # no 10s boundary crossed yet: pending only
    assert [p["values"]["{}"] for p in coarse] == [29]
    # crossing the 10s boundary seals the pending into the coarse ring
    t.record("metrics/m/a", _mk_payload(99), now=base + 10.5)
    coarse = t.query(name="m", resolution=10.0)
    assert [p["values"]["{}"] for p in coarse] == [29, 99]
    # since= filters by point timestamp
    late = t.query(name="m", resolution=1.0, since=base + 2.0)
    assert [p["values"]["{}"] for p in late] == [29, 99]


def test_history_ring_count_bound():
    """A ring never holds more than its configured slot count no matter
    how many buckets roll past it."""
    from ray_tpu._private.metrics_history import GcsMetricsHistoryTable

    t = GcsMetricsHistoryTable(resolutions=[(1.0, 5)],
                               max_bytes=10 * 1024 * 1024)
    for i in range(50):   # one write per 1s bucket -> 49 seals
        t.record("metrics/m/a", _mk_payload(i), now=2000.0 + i)
    s = t.series()[0]
    assert s["points"] == [5]
    assert t.stats()["dropped_points"] == 50 - 1 - 5


def test_history_series_cap_evicts_idlest():
    from ray_tpu._private.metrics_history import GcsMetricsHistoryTable

    t = GcsMetricsHistoryTable(resolutions=[(1.0, 10)], max_series=2)
    t.record("metrics/m/old", _mk_payload(1), now=1000.0)
    t.record("metrics/m/mid", _mk_payload(2), now=1001.0)
    t.record("metrics/m/new", _mk_payload(3), now=1002.0)
    keys = [s["key"] for s in t.series()]
    assert keys == ["metrics/m/mid", "metrics/m/new"]
    st = t.stats()
    assert st["series"] == 2 and st["evicted_series"] == 1


def test_history_byte_budget():
    """The byte budget holds under sustained ingest (oldest stored
    points dropped first), and the accounting the stats report matches
    what the table actually holds."""
    from ray_tpu._private.metrics_history import GcsMetricsHistoryTable

    payload = _mk_payload(1.0)
    budget = len(payload) * 20
    t = GcsMetricsHistoryTable(resolutions=[(1.0, 1000)],
                               max_series=1000, max_bytes=budget)
    for i in range(200):   # 50 buckets x 4 series, all sealed points
        t.record(f"metrics/m/s{i % 4}", _mk_payload(float(i)),
                 now=3000.0 + (i // 4))
    st = t.stats()
    assert st["bytes"] <= budget
    assert st["dropped_points"] > 0
    # recount from the table contents: stats must not drift from truth
    with t._lock:
        held = sum(len(raw) for s in t._series.values()
                   for ring in s["rings"] for _, _, raw in ring)
        held += sum(len(s["last_raw"]) for s in t._series.values())
    assert st["bytes"] == held


def test_history_staged_ingest_read_your_writes():
    """ingest() stages without folding; any reader drains first, so a
    write is visible to the query that follows it."""
    from ray_tpu._private.metrics_history import GcsMetricsHistoryTable

    t = GcsMetricsHistoryTable()
    t.ingest("metrics/m/a", _mk_payload(7.0))
    assert len(t._staged) == 1          # below the batch threshold
    pts = t.query(name="m")
    assert [p["values"]["{}"] for p in pts] == [7.0]
    assert len(t._staged) == 0
    # the batch threshold folds without a reader
    for _ in range(t._INGEST_BATCH):
        t.ingest("metrics/m/a", _mk_payload(8.0))
    assert len(t._staged) < t._INGEST_BATCH


def test_history_kill_switch(monkeypatch):
    """RAY_TPU_METRICS_HISTORY=0 beats the CONFIG flag: the GCS ingest
    path records nothing and the history stays empty."""
    from ray_tpu._private.config import CONFIG
    from ray_tpu.runtime.gcs import GcsServer

    monkeypatch.setenv("RAY_TPU_METRICS_HISTORY", "0")
    CONFIG.set("metrics_history_enabled", True)  # bump gen -> re-read env
    gcs = GcsServer()
    try:
        gcs._handle(None, "kv_put", {"key": "metrics/m/x",
                                     "value": _mk_payload(1.0)})
        assert gcs._handle(None, "metrics_history_stats", {})["series"] == 0
        # KV itself still works -- only the history fold is killed
        with gcs._lock:
            assert "metrics/m/x" in gcs._kv
        monkeypatch.delenv("RAY_TPU_METRICS_HISTORY")
        CONFIG.set("metrics_history_enabled", True)  # bump gen again
        gcs._handle(None, "kv_put", {"key": "metrics/m/x",
                                     "value": _mk_payload(2.0)})
        assert gcs._handle(None, "metrics_history_stats", {})["series"] == 1
    finally:
        gcs.stop()


def test_gcs_history_rpcs():
    """The GCS-side RPC surface: windowed query, stats, and the
    optional per-series index."""
    from ray_tpu.runtime.gcs import GcsServer

    gcs = GcsServer()
    try:
        for i in range(5):
            gcs._metrics_kv_put("metrics/ray_tpu_t/w1", _mk_payload(i))
            gcs._metrics_kv_put("metrics/ray_tpu_u/w1", _mk_payload(i * 10))
        pts = gcs._handle(None, "list_metrics_history",
                          {"name": "ray_tpu_t"})
        assert pts and all(p["name"] == "ray_tpu_t" for p in pts)
        assert pts[-1]["values"]["{}"] == 4   # newest sample visible
        st = gcs._handle(None, "metrics_history_stats", {"series": True})
        assert st["series"] == 2 and st["bytes"] > 0
        idx = {s["key"] for s in st["series_index"]}
        assert idx == {"metrics/ray_tpu_t/w1", "metrics/ray_tpu_u/w1"}
        assert st["resolutions"] == [[1.0, 120], [10.0, 180], [60.0, 120]]
    finally:
        gcs.stop()
