"""Serving front door (docs/serve_frontdoor.md): SSE streaming ingress,
prefix-affinity routing, SLO-driven pool re-roling.

Tier-1 smokes on the CPU-sized tiny model: the SSE bridge must be
token-exact against the handle-level stream, the router must pin
shared-prefix prompts to the advertising prefill replica (and the
engine must actually skip the re-prefill), and a forced re-role must
execute drain -> re-role -> rejoin with a closed ``rerole`` episode in
the recovery auditor.  The 10k-connection closed-loop harness rides as
@slow (benchmarks/serve_frontdoor.py carries the MICROBENCH row).
"""

import json
import threading
import time
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu import serve


def _init(**system_config):
    # record every trace: the smokes cross-link specific requests, so
    # the default 10% sampler would make them flaky
    system_config.setdefault("trace_sample_rate", 1.0)
    rt.init(num_cpus=4, object_store_memory=256 * 1024 * 1024,
            system_config=system_config)


def _shutdown():
    try:
        serve.shutdown()
    except Exception:
        pass
    rt.shutdown()


def _stream_all(handle, requests, timeout=300):
    """Drive N concurrent streams through a DisaggHandle; returns
    (tokens, summary, retries) per request, in order."""
    import asyncio

    async def one(req):
        toks, summary, retries = [], None, 0
        async for item in handle.stream(req):
            if "token" in item:
                toks.append(item["token"])
            elif "retry" in item:
                retries = item["retry"]
            else:
                summary = item
        return toks, summary, retries

    async def main():
        return await asyncio.gather(*[one(r) for r in requests])

    return asyncio.run(asyncio.wait_for(main(), timeout=timeout))


def _sse_events(resp):
    """Parse one SSE response body: [(event_name_or_None, data_dict)].
    The wire format is ``[event: name NL] data: json NL NL`` per frame
    (serve/frontdoor/sse.py format_event)."""
    out, event = [], None
    for raw in resp:
        line = raw.decode("utf-8").rstrip("\r\n")
        if line.startswith("event:"):
            event = line.split(":", 1)[1].strip()
        elif line.startswith("data:"):
            out.append((event, json.loads(line.split(":", 1)[1])))
            event = None
    return out


def _sse_post(url, req, timeout=240):
    """POST one LLM request, stream the SSE frames back."""
    r = urllib.request.Request(
        url, data=json.dumps(req).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith(
            "text/event-stream"), resp.headers["Content-Type"]
        return _sse_events(resp)


def _sse_tokens(events):
    toks = [d["token"] for ev, d in events
            if ev is None and "token" in d]
    done = [d for ev, d in events if ev == "done"]
    assert len(done) == 1, events
    return toks, done[0]


def test_sse_stream_token_exact():
    """The SSE front door is a faithful bridge: tokens streamed over
    HTTP (both the colocated ``/-/stream/{deployment}`` path and the
    disaggregated ``/-/disagg/{preset}`` path) are exactly the tokens
    the in-process handle streams, with the summary frame as an
    ``event: done`` and each connection's ingress root feeding the SLO
    plane with client-observed TTFT/TPOT."""
    port = 18272
    _init()
    try:
        serve.start(serve.HTTPOptions(port=port))
        # one app per path: colocated "llm-tiny" + a 1+1 disagg pair
        serve.run(serve.llm.build_app(preset="tiny", num_slots=4,
                                      max_concurrent_queries=32))
        serve.run(serve.llm.build_app(
            preset="tiny", disaggregated=True, num_replicas=1,
            prefill_replicas=1, num_slots=4, block_size=4, page_size=8,
            max_concurrent_queries=32))
        handle = serve.llm.disagg_handle("tiny")

        prompts = [[1, 2, 3], [7, 8, 9, 10, 11], [50, 60], [9] * 17]
        reqs = [{"prompt": p, "max_new_tokens": 6, "temperature": 0.0}
                for p in prompts]
        expect = {tuple(r["prompt"]): toks
                  for r, (toks, _, _) in zip(reqs,
                                             _stream_all(handle, reqs))}

        # --- disagg SSE: 4 concurrent connections
        outs = [None] * len(reqs)
        errs = []

        def fetch(i, path):
            try:
                outs[i] = _sse_post(
                    f"http://127.0.0.1:{port}{path}", reqs[i])
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=fetch, args=(i, "/-/disagg/tiny"))
                   for i in range(len(reqs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not errs, errs
        for req, events in zip(reqs, outs):
            toks, done = _sse_tokens(events)
            assert toks == expect[tuple(req["prompt"])], (req, toks)
            assert done["finish_reason"] == "length"
            assert done["num_tokens"] == 6

        # --- colocated SSE against the same expectations
        events = _sse_post(f"http://127.0.0.1:{port}/-/stream/llm-tiny",
                           reqs[0])
        toks, done = _sse_tokens(events)
        assert toks == expect[tuple(prompts[0])]
        assert done["finish_reason"] == "length"

        # a malformed body is a 400, not a wedged stream
        r = urllib.request.Request(
            f"http://127.0.0.1:{port}/-/disagg/tiny", data=b"not json",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(r, timeout=30)
        assert ei.value.code == 400

        # --- ingress roots feed the SLO plane: every SSE request above
        # closed a root on its route with client-observed latency
        from ray_tpu.experimental.state.api import trace_stats
        deadline = time.monotonic() + 60
        by_route = {}
        while time.monotonic() < deadline:
            by_route = trace_stats().get("slo_by_route") or {}
            dec = by_route.get("llm-tiny-decode") or {}
            col = by_route.get("llm-tiny") or {}
            if (dec.get("good", 0) + dec.get("violation", 0) >= 4
                    and col.get("good", 0) + col.get("violation", 0) >= 1):
                break
            time.sleep(0.5)
        dec = by_route.get("llm-tiny-decode") or {}
        assert dec.get("good", 0) + dec.get("violation", 0) >= 4, by_route
        col = by_route.get("llm-tiny") or {}
        assert col.get("good", 0) + col.get("violation", 0) >= 1, by_route
    finally:
        _shutdown()


def test_prefix_affinity_routing():
    """Shared-prefix prompts pin the prefill hop to the replica whose
    paged-KV cache already holds the prefix: the replica advertises its
    resident boundary digests up the load-publish path, the router's
    PrefixIndex routes on them, the ray_tpu_serve_prefix_hit family
    counts the outcome, and the engine's counters prove the hit path
    skipped the shared pages' prefill — with the streamed tokens still
    exactly the lone-generation reference."""
    import jax.numpy as jnp

    from ray_tpu.models.configs import get_config
    from ray_tpu.models.generate import Generator
    from ray_tpu.models.gpt import GPT
    from ray_tpu.serve.controller import SERVE_NAMESPACE
    from ray_tpu.serve.frontdoor.prefix import _M_PREFIX_HIT

    _init()
    try:
        serve.start()
        # 2 prefill replicas so affinity is a real routing decision
        # (p2c would spread the shared prefix across both); prefix
        # cache only on the prefill pool
        serve.run(serve.llm.build_app(
            preset="tiny", disaggregated=True, num_replicas=1,
            prefill_replicas=2, num_slots=4, block_size=4, page_size=8,
            max_concurrent_queries=32,
            prefill_server_kwargs={"prefix_cache_pages": 8}))
        handle = serve.llm.disagg_handle("tiny")

        shared = list(range(1, 17))          # 2 full 8-token pages
        warm = {"prompt": shared + [31], "max_new_tokens": 4,
                "temperature": 0.0}
        (toks, summary, _), = _stream_all(handle, [warm])
        assert summary["finish_reason"] == "length"

        # advertisement round trip: engine retains pages at slot-free ->
        # replica advertises on the next health-check pass -> controller
        # republishes on get_targets -> handle refresh feeds the index
        deadline = time.monotonic() + 90
        pinned = None
        while time.monotonic() < deadline and pinned is None:
            handle.prefill._refresh(force=True)
            pinned = handle.prefill.prefix_route(shared)
            if pinned is None:
                time.sleep(0.5)
        assert pinned is not None, "prefix advertisement never reached " \
            f"the router: {handle.prefill._prefix_index and handle.prefill._prefix_index.stats()}"

        hits0 = _M_PREFIX_HIT.get("hit").value
        reqs = [{"prompt": shared + [41 + i], "max_new_tokens": 4,
                 "temperature": 0.0} for i in range(4)]
        outs = _stream_all(handle, reqs)
        for (toks, summary, _) in outs:
            assert summary["finish_reason"] == "length"
            assert summary["num_tokens"] == 4

        # every routed prefill above consulted the index and hit
        assert _M_PREFIX_HIT.get("hit").value - hits0 >= 4

        # the pinned replica's ENGINE took the hits: its suffix prefill
        # skipped the 16 shared tokens each time (prefix_route returns
        # the full actor name, the same key the routing table uses)
        a = rt.get_actor(pinned, namespace=SERVE_NAMESPACE)
        s = rt.get(a.handle_request.remote("stats", (), {}), timeout=60)
        assert s["prefix_hits"] >= 4, s
        assert s["prefix_tokens_saved"] >= 4 * len(shared), s

        # numerics gate: the hit path (suffix prefill over retained
        # pages) must not change what gets generated
        cfg = get_config("tiny")
        model = GPT(cfg, decode=True)
        import jax
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 1), jnp.int32))["params"]
        lone = Generator(cfg, params)
        for i, (toks, _, _) in enumerate(outs):
            expect = [int(t) for t in lone.generate(
                jnp.asarray([shared + [41 + i]], jnp.int32),
                max_new_tokens=4, temperature=0.0)[0]]
            assert toks == expect, (i, toks, expect)
    finally:
        _shutdown()


def test_forced_rerole_episode_audited():
    """Controller-driven pool re-roling end to end: request_rerole
    drains the donor prefill replica, shifts pool targets, and the
    reconcile loop grows the decode pool — with the whole episode
    visible to the recovery auditor as a closed ``rerole`` episode
    cross-linked to a real ingress trace."""
    from ray_tpu.experimental import state
    from ray_tpu.serve.controller import CONTROLLER_NAME, SERVE_NAMESPACE

    _init()
    try:
        serve.start()
        serve.run(serve.llm.build_app(
            preset="tiny", disaggregated=True, num_replicas=1,
            prefill_replicas=2, num_slots=4, block_size=4, page_size=8,
            max_concurrent_queries=32))
        handle = serve.llm.disagg_handle("tiny")
        # traffic first: the episode should cross-link a real trace
        _stream_all(handle, [{"prompt": [3, 4, 5], "max_new_tokens": 4,
                              "temperature": 0.0}] * 2)
        deadline = time.monotonic() + 60
        traces = []
        while time.monotonic() < deadline and not traces:
            traces = state.list_traces(route="llm-tiny-decode", limit=5)
            if not traces:
                time.sleep(0.5)
        assert traces, "no ingress trace to cross-link"
        tid = traces[0]["trace_id"]

        controller = rt.get_actor(CONTROLLER_NAME,
                                  namespace=SERVE_NAMESPACE)
        ok = rt.get(controller.request_rerole.remote(
            "llm-tiny-prefill", "llm-tiny-decode", reason="slo",
            slo_kind="ttft", trace_id=tid), timeout=30)
        assert ok is True
        # one move in flight per controller: a concurrent request is
        # refused, not queued
        ok2 = rt.get(controller.request_rerole.remote(
            "llm-tiny-prefill", "llm-tiny-decode"), timeout=30)
        assert ok2 is False

        # drain -> re-role -> rejoin: prefill 2 -> 1, decode 1 -> 2
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            st = serve.status()
            if (len(st["llm-tiny-prefill"]["replicas"]) == 1
                    and st["llm-tiny-prefill"]["target_replicas"] == 1
                    and len(st["llm-tiny-decode"]["replicas"]) == 2):
                break
            time.sleep(0.5)
        else:
            raise AssertionError(f"re-role never converged: "
                                 f"{serve.status()}")

        # the auditor closed the episode (SERVE_REROLE ->
        # SERVE_REROLE_DONE) with the SLO verdict and the trace link
        deadline = time.monotonic() + 60
        eps = []
        while time.monotonic() < deadline and not eps:
            eps = state.list_recovery_episodes(kind="rerole",
                                               include_open=False)
            if not eps:
                time.sleep(0.5)
        assert eps, "auditor never closed the rerole episode"
        ep = eps[-1]
        assert ep["src"] == "llm-tiny-prefill"
        assert ep["dst"] == "llm-tiny-decode"
        assert ep["reason"] == "slo" and ep["slo_kind"] == "ttft"
        assert ep["trace_id"] == tid
        assert state.get_trace(tid) is not None   # link resolves
        assert ep["src_replicas"] == 1 and ep["dst_replicas"] == 2
        assert ep["latency_s"] > 0
        # default re-roling SLO (recovery_slo_rerole_s): 60 s
        assert ep["slo_s"] == 60.0
        assert ep["violation"] == (ep["latency_s"] > ep["slo_s"])

        # re-roled pools still serve: a stream through the reshaped
        # pair completes (the donor's drain never stranded a request)
        (toks, summary, _), = _stream_all(
            handle, [{"prompt": [8, 9], "max_new_tokens": 4,
                      "temperature": 0.0}])
        assert summary["finish_reason"] == "length"

        from conftest import record_recovery_row
        record_recovery_row({
            "name": "rerole", "latency_s": ep["latency_s"],
            "slo_s": ep["slo_s"], "violation": ep["violation"],
            "reference": "tests/test_serve_frontdoor.py::"
                         "test_forced_rerole_episode_audited"})
    finally:
        _shutdown()


@pytest.mark.slow
def test_serve_frontdoor_load_harness_10k():
    """The full 10k-connection closed-loop SSE harness
    (benchmarks/serve_frontdoor.py) with the MICROBENCH acceptance
    bars: zero stream errors, per-pool TTFT/TPOT SLO classification
    present, nonzero prefix-hit-rate on the bimodal shared-prefix mix.
    ~15 min; tier-1 runs the smokes above instead."""
    from benchmarks.serve_frontdoor import run_frontdoor

    rows = run_frontdoor(connections=10000, new_tokens=48,
                         duration_s=120.0)
    row = rows[-1]
    assert row["metric"] == "serve_frontdoor_closed_loop"
    assert row["errors"] == 0
    assert row["connections"] >= 10000
    assert row["prefix_hit_rate"] > 0, row
    slo = row["slo"]
    assert "llm-tiny-decode" in slo, slo
    verdicts = slo["llm-tiny-decode"]
    assert verdicts["good"] + verdicts["violation"] > 0
    assert row["handoff_saved_bytes"] > 0, row
