"""Control-plane RPC fast path: framing, dispatch, batching, reaping.

Covers the mechanisms docs/rpc_fastpath.md describes: scatter/gather
frame coalescing under concurrent writers, inline (fast-method) vs
pooled dispatch, deferred replies, batched ``push_tasks`` ordering per
lease, the inline-return size threshold, and timed-out-call reaping.
The transport-level suites run twice — fuzz off and with
``rpc_fuzz_ms`` schedule fuzz — because the fast path must not depend
on frames "usually" landing in a convenient order.
"""

import threading
import time

import pytest

from ray_tpu._private import rpc
from ray_tpu._private.config import CONFIG


@pytest.fixture(params=[0.0, 2.0], ids=["nofuzz", "fuzz"])
def fuzz(request):
    """Run the transport tests under both dispatch regimes: fuzz > 0
    also forces every fast method onto the pooled path."""
    CONFIG.set("rpc_fuzz_ms", request.param)
    yield request.param
    CONFIG.set("rpc_fuzz_ms", 0.0)


def _echo_server(fast=None):
    order = []
    olock = threading.Lock()

    def handler(conn, method, payload):
        with olock:
            order.append((method, payload))
        if method == "boom":
            raise ValueError("kaboom")
        if method == "slow":
            time.sleep(payload or 0.2)
            return "slept"
        if method == "deferred":
            d = rpc.Deferred()
            threading.Thread(target=lambda: (time.sleep(0.01),
                                             d.resolve(payload * 2)),
                             daemon=True).start()
            return d
        return payload

    srv = rpc.Server(handler, fast_methods=fast)
    return srv, order


def test_fuzz_cache_tracks_config_generation():
    """_maybe_fuzz caches the flag keyed on CONFIG.generation(): runtime
    overrides (ray_tpu.init system_config) must still take effect."""
    rpc._fuzz_ms_now()
    CONFIG.set("rpc_fuzz_ms", 7.5)
    try:
        assert rpc._fuzz_ms_now() == 7.5
        CONFIG.set("rpc_fuzz_ms", 0.0)
        assert rpc._fuzz_ms_now() == 0.0
    finally:
        CONFIG.set("rpc_fuzz_ms", 0.0)


def test_concurrent_writers_coalesce_without_corruption(fuzz):
    """Many threads writing frames (requests) on ONE connection: the
    write-side queue may coalesce any subset into single sendmsg calls;
    every frame must still arrive intact and every reply must route to
    its caller."""
    srv, _ = _echo_server()
    conn = rpc.connect(srv.address)
    try:
        errs = []

        def spam(base):
            try:
                payloads = [{"i": base + i, "blob": b"x" * (base % 7000)}
                            for i in range(50)]
                futs = [conn.call_async("echo", p) for p in payloads]
                for p, f in zip(payloads, futs):
                    assert f.result(30) == p
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=spam, args=(k * 1000,))
                   for k in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
    finally:
        conn.close()
        srv.stop()


def test_out_of_band_buffers_roundtrip(fuzz):
    """Protocol-5 buffer_callback payloads (numpy) ride the iovec out of
    band and reassemble exactly."""
    np = pytest.importorskip("numpy")
    srv, _ = _echo_server()
    conn = rpc.connect(srv.address)
    try:
        arr = np.arange(100_000, dtype=np.float32).reshape(100, 1000)
        out = conn.call("echo", {"a": arr, "b": b"tail"})
        assert (out["a"] == arr).all() and out["b"] == b"tail"
        # non-contiguous falls back to in-band pickling
        sl = arr[:, ::7]
        assert (conn.call("echo", sl) == sl).all()
    finally:
        conn.close()
        srv.stop()


def test_inline_vs_pooled_dispatch_ordering(fuzz):
    """Pooled handlers on one connection START in arrival order (FIFO
    pool fed by one reader); fast methods may run inline ahead of queued
    slow work but never corrupt replies.  Under fuzz the fast registry
    is bypassed (everything pooled) and results must be identical."""
    srv, order = _echo_server(fast={"fastping"})
    conn = rpc.connect(srv.address)
    try:
        slow_futs = [conn.call_async("echo", i) for i in range(20)]
        assert conn.call("fastping", "now", timeout=30) == "now"
        assert [f.result(30) for f in slow_futs] == list(range(20))
        echoes = [p for m, p in order if m == "echo"]
        if fuzz == 0:
            # the pool is FIFO fed by one reader: handler bodies start in
            # arrival order.  Under fuzz the pre-handler jitter shuffles
            # body START order on purpose — only completeness holds.
            assert echoes == list(range(20)), "pooled dispatch reordered"
        assert sorted(echoes) == list(range(20))
    finally:
        conn.close()
        srv.stop()


def test_deferred_reply_resolves_from_other_thread(fuzz):
    srv, _ = _echo_server(fast={"deferred"})
    conn = rpc.connect(srv.address)
    try:
        assert conn.call("deferred", 21, timeout=30) == 42
        futs = [conn.call_async("deferred", i) for i in range(10)]
        assert [f.result(30) for f in futs] == [2 * i for i in range(10)]
    finally:
        conn.close()
        srv.stop()


def test_remote_error_carries_cause(fuzz):
    srv, _ = _echo_server()
    conn = rpc.connect(srv.address)
    try:
        with pytest.raises(rpc.RemoteError) as ei:
            conn.call("boom")
        assert isinstance(ei.value.cause, ValueError)
    finally:
        conn.close()
        srv.stop()


def test_timed_out_call_is_reaped(fuzz):
    """A call abandoned on timeout must drop its in-flight future (the
    3.10 futures.TimeoutError != builtin TimeoutError trap) — and a late
    response for it must not blow up the reader."""
    srv, _ = _echo_server()
    conn = rpc.connect(srv.address)
    try:
        with pytest.raises(Exception) as ei:
            conn.call("slow", 0.5, timeout=0.01)
        assert "Timeout" in type(ei.value).__name__
        with conn._inflight_lock:
            assert not conn._inflight, "timed-out call leaked its future"
        # the late response arrives and is discarded; the conn still works
        time.sleep(0.7)
        assert conn.call("echo", "alive", timeout=30) == "alive"
    finally:
        conn.close()
        srv.stop()


def test_buffer_sink_receives_payload_in_place(fuzz):
    """A call's registered buffer sink gets the response's out-of-band
    payload recv_into'd straight into its destination view, and the
    deserialized reply references that same memory (the data plane's
    zero-copy landing, docs/object_transfer.md)."""
    import pickle

    blob = bytes(range(256)) * 16  # 4 KiB

    def handler(conn, method, payload):
        if method == "oob":
            return {"data": pickle.PickleBuffer(blob)}
        return {"data": blob}  # in band: the sink must NOT be used

    srv = rpc.Server(handler)
    conn = rpc.connect(srv.address)
    try:
        dest = bytearray(len(blob))
        hits = []

        def sink(lens):
            if len(lens) == 1 and lens[0] <= len(dest):
                hits.append(lens[0])
                return [memoryview(dest)[:lens[0]]]
            return None

        res = conn.call_async("oob", buffer_sink=sink).result(30)
        assert hits == [len(blob)]
        assert bytes(dest) == blob, "payload did not land in the sink"
        assert bytes(res["data"]) == blob
        assert not conn._sinks, "consumed sink must be unregistered"

        # an in-band reply never consults the sink but still drops the
        # registration (no leak)
        dest2 = bytearray(len(blob))
        res2 = conn.call_async(
            "inband",
            buffer_sink=lambda lens: [memoryview(dest2)[:lens[0]]]
        ).result(30)
        assert bytes(res2["data"]) == blob
        assert bytes(dest2) == bytes(len(blob)), "sink wrongly used"
        assert not conn._sinks
    finally:
        conn.close()
        srv.stop()


def test_discarded_sink_falls_back_to_fresh_storage(fuzz):
    """discard_sinks withdraws a destination before the reply lands: the
    reader must fall back to fresh storage and never touch the withdrawn
    view (the engine releases it right after)."""
    import pickle

    blob = b"q" * 1024
    gate = threading.Event()

    def handler(conn, method, payload):
        gate.wait(10)  # hold the reply until the sink is withdrawn
        return {"data": pickle.PickleBuffer(blob)}

    srv = rpc.Server(handler)
    conn = rpc.connect(srv.address)
    try:
        dest = bytearray(len(blob))
        fut = conn.call_async(
            "oob", buffer_sink=lambda lens: [memoryview(dest)[:lens[0]]])
        conn.discard_sinks([fut._rpc_msg_id])
        gate.set()
        res = fut.result(30)
        assert bytes(res["data"]) == blob
        assert bytes(dest) == bytes(len(blob)), \
            "withdrawn sink was written to"
    finally:
        conn.close()
        srv.stop()


def test_push_closes_connection_on_dead_socket(fuzz):
    """Satellite: push() on a dead socket must close the connection (so
    pubsub cleanup runs and later pushes fail fast) instead of silently
    raising forever."""
    srv, _ = _echo_server()
    conn = rpc.connect(srv.address)
    try:
        srv.stop()   # kills the server side of the socket
        # until the reader observes the EOF, pushes may legitimately land
        # in kernel buffers; once the connection is closed every push
        # must raise instead of silently dropping
        deadline = time.monotonic() + 30
        while not conn.closed and time.monotonic() < deadline:
            try:
                conn.push("note", b"x" * 4096)
            except ConnectionError:
                break
            time.sleep(0.005)
        assert conn.closed or time.monotonic() < deadline
        with pytest.raises(ConnectionError):
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                conn.push("note", b"x" * 4096)
                time.sleep(0.005)
        assert conn.closed
    finally:
        conn.close()
        srv.stop()


def test_accept_after_stop_is_closed():
    """Regression: a connection accepted concurrently with Server.stop()
    must not survive as a live unregistered reader.  stop() closes a
    snapshot of connections(); an accept that lands its _conns.add after
    that snapshot was never closed, and its reader then drained the
    client's pushes forever — test_push_closes_connection_on_dead_socket
    hung on exactly that interleaving.  _register_conn must refuse (and
    close) once stop() has run."""
    import socket as socket_mod

    srv, _ = _echo_server()
    lst = socket_mod.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)
    client = socket_mod.create_connection(lst.getsockname())
    accepted, _ = lst.accept()
    try:
        srv.stop()
        conn = rpc.Connection(accepted, handler=srv._handler,
                              on_close=srv._conn_closed)
        assert srv._register_conn(conn) is False
        assert conn.closed, "post-stop accept left a live reader"
        assert conn not in srv.connections()
    finally:
        client.close()
        lst.close()


# --------------------------------------------------------------------------
# batched push_tasks at the submitter level (scripted fake peers)
# --------------------------------------------------------------------------
class _FakePeer:
    def __init__(self, script):
        self.script = dict(script)
        self.calls = []
        self.lock = threading.Lock()
        self.server = rpc.Server(self._handle)
        self.address = self.server.address

    def _handle(self, conn, method, payload):
        with self.lock:
            self.calls.append((method, payload))
        fn = self.script.get(method)
        if fn is None:
            raise rpc.RpcError(f"unscripted method {method}")
        return fn(conn, payload)

    def called(self, method):
        with self.lock:
            return [p for m, p in self.calls if m == method]


def _make_owner(raylet_addr):
    from ray_tpu._private.ids import JobID
    from ray_tpu.runtime import core_worker as cw

    class Owner(cw.CoreWorker):
        def __init__(self):
            # the shared helper owns the full submitter field list, so
            # new fields added there can't drift from this harness
            self._init_submitter_state()
            self._raylet = rpc.connect(raylet_addr)
            self.job_id = JobID.from_random()
            self.replies = []
            self.errors = []
            self.done = threading.Condition()

        def _on_task_reply(self, spec, reply):
            with self.done:
                self.replies.append(spec["name"])
                self.done.notify_all()

        def _store_task_error(self, spec, error, error_code=None):
            with self.done:
                self.errors.append((spec["name"], error))
                self.done.notify_all()

        def _lease_was_oom_killed(self, lease):
            return False

        def submit(self, name, refs=False):
            spec = {"task_id": name.encode().ljust(16, b"0"), "name": name}
            if refs:
                spec["_refs"] = True
            self._enqueue_task("k", {"CPU": 1}, spec, 0)

        def wait_done(self, n, timeout=60):
            deadline = time.monotonic() + timeout
            with self.done:
                while len(self.replies) + len(self.errors) < n:
                    left = deadline - time.monotonic()
                    assert left > 0, (self.replies, self.errors)
                    self.done.wait(left)

        def close(self):
            self._shutdown.set()
            with self._sched_lock:
                self._sched_cv.notify_all()
            try:
                self._raylet.close()
            except Exception:
                pass

    return Owner()


def test_batched_push_tasks_order_and_ref_isolation(fuzz):
    """Specs coalesce into push_tasks frames in strict submission order,
    never exceed task_submit_batch_max per frame, and a ref-carrying
    spec always travels in a singleton frame."""
    gate = threading.Event()

    def push_tasks(conn, p):
        gate.wait(30)   # hold frame 1 so the rest of the queue coalesces
        return {"results": [{"ok": {"results": [{"name": s["name"]}]}}
                            for s in p["specs"]]}

    worker = _FakePeer({"push_tasks": push_tasks})
    raylet = _FakePeer({
        "lease_worker": lambda conn, p: {"lease_id": "l1", "worker_id": "w1",
                                         "address": list(worker.address)},
        "return_worker": lambda conn, p: {"ok": True}})
    o = _make_owner(raylet.address)
    try:
        names = [f"t{i:02d}" for i in range(10)]
        for i, n in enumerate(names):
            o.submit(n, refs=(i == 5))   # t05 must ride alone
        gate.set()
        o.wait_done(10)
        assert not o.errors, o.errors
        frames = [[s["name"] for s in p["specs"]]
                  for p in worker.called("push_tasks")]
        if fuzz == 0:
            # frames recorded in arrival order without fuzz; the fuzz
            # jitter shuffles handler START order, not frame contents
            flat = [n for f in frames for n in f]
            assert flat == names, f"submission order broken: {frames}"
        assert sorted(n for f in frames for n in f) == names
        # within a frame, specs are contiguous ascending submissions
        for f in frames:
            assert f == sorted(f) and \
                [int(n[1:]) for n in f] == list(range(int(f[0][1:]),
                                                      int(f[0][1:]) + len(f)))
        cap = CONFIG.task_submit_batch_max
        assert all(len(f) <= cap for f in frames)
        assert ["t05"] in frames, f"ref spec shared a frame: {frames}"
        # owner consumes frame acks in send order: completions surface in
        # submission order regardless of worker-side dispatch jitter
        assert o.replies == names
    finally:
        o.close()


def test_batched_push_tasks_early_results_stream(fuzz):
    """A fast task batched behind a slow one must resolve at its own
    finish time via the task_done push, not at the frame ack."""
    def push_tasks(conn, p):
        results = []
        for s in p["specs"]:
            if s["name"] == "slowtail":
                # the tail EXECUTES for a second before completing: its
                # task_done push and the frame ack both trail the head
                # by this much.  (The fake used to push the tail's
                # task_done BEFORE sleeping, so the head's "early"
                # assert raced the serial push loop by microseconds and
                # lost under box load — the two pushes must be
                # separated by the simulated execution, like a real
                # worker's.)
                time.sleep(1.0)
            res = {"ok": {"results": [{"name": s["name"]}]}}
            if len(p["specs"]) > 1:
                conn.push("task_done", {"task_id": s["task_id"],
                                        "res": res})
            results.append(res)
        return {"results": results}

    def lease_worker(conn, p):
        time.sleep(0.05)   # let both submissions queue -> one frame
        return {"lease_id": "l1", "worker_id": "w1",
                "address": list(worker.address)}

    worker = _FakePeer({"push_tasks": push_tasks})
    raylet = _FakePeer({"lease_worker": lease_worker,
                        "return_worker": lambda conn, p: {"ok": True}})
    o = _make_owner(raylet.address)
    try:
        o.submit("fasthead")
        o.submit("slowtail")
        t0 = time.monotonic()
        with o.done:
            while "fasthead" not in o.replies:
                assert time.monotonic() - t0 < 30
                o.done.wait(1.0)
            # state-based earliness: the head resolved while the frame's
            # tail (and its ack) was still half a second out
            assert "slowtail" not in o.replies
        o.wait_done(2)
        assert o.replies == ["fasthead", "slowtail"]
    finally:
        o.close()


def test_keepalive_does_not_collapse_fanout(fuzz):
    """A lease parked in keepalive absorbs a lone follow-up task, but a
    burst deeper than the parked capacity must still request more leases
    (the idle guard must not serialize parallel workloads onto one
    warm worker)."""
    def push_tasks(conn, p):
        time.sleep(0.05)   # slow worker: the burst outruns one lease
        return {"results": [{"ok": {"results": [{"name": s["name"]}]}}
                            for s in p["specs"]]}

    worker = _FakePeer({"push_tasks": push_tasks})
    nleases = [0]

    def lease_worker(conn, p):
        nleases[0] += 1
        return {"lease_id": f"l{nleases[0]}", "worker_id": f"w{nleases[0]}",
                "address": list(worker.address)}

    raylet = _FakePeer({"lease_worker": lease_worker,
                        "return_worker": lambda conn, p: {"ok": True}})
    o = _make_owner(raylet.address)
    try:
        o.submit("warm")
        o.wait_done(1)
        # the lease is now parked in keepalive; burst past its window
        for i in range(20):
            o.submit(f"b{i:02d}")
        o.wait_done(21)
        assert not o.errors, o.errors
        assert nleases[0] >= 2, \
            "burst during keepalive stayed on one lease (fan-out collapsed)"
    finally:
        o.close()


# --------------------------------------------------------------------------
# inline-return threshold (live cluster)
# --------------------------------------------------------------------------
def test_inline_return_threshold_boundary():
    """Returns at the threshold travel inline in the reply (owner holds
    the bytes); returns one byte over go through the store and come back
    as a location."""
    import ray_tpu
    from ray_tpu.runtime.core_worker import get_global_worker

    limit = 8 * 1024
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024,
                 system_config={"rpc_inline_return_max_bytes": limit})
    try:
        @ray_tpu.remote
        def blob(n):
            return b"z" * n

        # serialization adds a fixed header; stay clearly on each side
        small_ref = blob.remote(limit // 2)
        big_ref = blob.remote(4 * limit)
        assert ray_tpu.get(small_ref, timeout=60) == b"z" * (limit // 2)
        assert ray_tpu.get(big_ref, timeout=60) == b"z" * (4 * limit)
        w = get_global_worker()
        with w._owned_lock:
            small_entry = w._owned[small_ref.id]
            big_entry = w._owned[big_ref.id]
            assert small_entry.data is not None, "small return not inline"
            assert big_entry.data is None and big_entry.locations, \
                "big return did not go through the store"
    finally:
        ray_tpu.shutdown()
