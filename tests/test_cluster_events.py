"""Cluster event plane + failure flight recorder (docs/observability.md):
typed lifecycle events into the GCS table, retention bounds, crash
dossiers, dump_stacks, and the task-table synthetic-record bound."""

import json
import os
import time

import pytest

from ray_tpu._private import cluster_events as cev
from ray_tpu._private.config import CONFIG


# --------------------------------------------------------------- units
def test_event_table_retention_bounds():
    """Both retention gates hold: max event count (sharded rotation)
    and the max-bytes budget — the table can never grow unbounded."""
    table = cev.GcsClusterEventTable(max_events=64,
                                     max_bytes=1024 * 1024)
    dropped = table.put([{"type": "T", "node_id": f"n{i % 5}",
                          "message": f"m{i}"} for i in range(500)])
    st = table.stats()
    assert st["events"] <= 64
    assert dropped >= 500 - 64
    # byte budget: oversized payloads evict oldest-first until it fits
    table2 = cev.GcsClusterEventTable(max_events=10_000,
                                      max_bytes=8 * 1024)
    table2.put([{"type": "BIG", "node_id": f"n{i}",
                 "blob": "x" * 1024} for i in range(64)])
    assert table2.stats()["bytes"] <= 8 * 1024
    assert table2.stats()["events"] < 64
    # counts_by_type survives rotation (metrics_summary top-types view)
    assert table.counts_by_type()["T"] == 500


def test_event_table_filters():
    table = cev.GcsClusterEventTable(max_events=1000,
                                     max_bytes=1 << 20)
    table.put([
        {"type": "WORKER_EXIT", "severity": "ERROR", "node_id": "aaa111",
         "worker_id": "w1", "job_id": "j1", "message": "boom"},
        {"type": "WORKER_SPAWN", "severity": "INFO", "node_id": "aaa111",
         "worker_id": "w2", "job_id": "j1"},
        {"type": "OBJECT_SPILL", "severity": "DEBUG", "node_id": "bbb222"},
        {"type": "ACTOR_DEAD", "severity": "ERROR", "actor_id": "ac1",
         "node_id": "bbb222"},
    ])
    assert len(table.list(etype="WORKER_EXIT")) == 1
    assert len(table.list(severity="ERROR")) == 2
    # min_severity is a floor: DEBUG < INFO < WARNING < ERROR
    assert len(table.list(min_severity="INFO")) == 3
    assert len(table.list(node_id="aaa")) == 2      # prefix match
    assert len(table.list(actor_id="ac")) == 1
    assert len(table.list(worker_id="w1")) == 1
    assert len(table.list(job_id="j1")) == 2
    rows = table.list(limit=2)
    assert len(rows) == 2
    # sorted by ts: limit keeps the newest tail
    assert rows == sorted(rows, key=lambda e: e["ts"])


def test_recorder_ring_flight_and_ring_only(tmp_path):
    """ring_only events reach the ring + flight file but never the
    sink; the flight dump is atomic and readable post-mortem."""
    shipped = []
    flight = str(tmp_path / "logs" / cev.flight_file_name("deadbeef" * 4))
    os.makedirs(os.path.dirname(flight))
    rec = cev.EventRecorder(sink=lambda evs: shipped.extend(evs),
                            source="test", worker_id="deadbeef" * 4,
                            flight_path=flight)
    rec.emit("TASK_RUNNING", "crumb", ring_only=True, task_id="t1")
    rec.emit("WORKER_EXIT", "real", severity="ERROR")
    rec.flush()
    assert [e["type"] for e in shipped] == ["WORKER_EXIT"]
    ring = cev.read_flight_file(str(tmp_path), "deadbeef" * 4)
    assert [e["type"] for e in ring] == ["TASK_RUNNING", "WORKER_EXIT"]
    # ring is bounded
    for i in range(CONFIG.event_ring_size + 50):
        rec.emit("X", ring_only=True, i=i)
    assert len(rec.ring_snapshot()) <= CONFIG.event_ring_size
    rec.stop()
    # a sink failure re-queues the batch instead of dropping it
    boom = {"n": 0}

    def flaky(evs):
        boom["n"] += 1
        if boom["n"] == 1:
            raise ConnectionError("gcs away")
        shipped.extend(evs)

    rec2 = cev.EventRecorder(sink=flaky, source="test")
    rec2.emit("RETRY_ME")
    rec2.flush()
    rec2.flush()
    assert any(e["type"] == "RETRY_ME" for e in shipped)


def test_kill_switch_disables_recorder(monkeypatch):
    monkeypatch.setenv("RAY_TPU_EVENTS", "0")
    assert not cev.enabled()
    assert cev.configure(sink=lambda evs: None, source="test") is None
    cev.emit("ANYTHING")            # must be a cheap no-op, not a crash
    assert cev.ring_snapshot() == []
    monkeypatch.delenv("RAY_TPU_EVENTS")
    assert cev.enabled()


def test_task_table_bounds_synthetic_instant_records():
    """PR 8 whitelisted synthetic ``handoff-<object>`` records into the
    GcsTaskTable; they carry only instant markers (state never leaves
    ""), so the eviction scan must treat them as evictable — under a
    long-lived serve app they used to rotate forever and pin the table
    at 2x cap (regression, ISSUE 9 satellite)."""
    from ray_tpu._private.task_events import GcsTaskTable
    saved = CONFIG.copy_overrides()
    CONFIG.set("gcs_max_task_events", 32)
    try:
        table = GcsTaskTable()
        # one genuinely live task must survive the rotation
        table.put_events([{"task_id": "live-1", "state": "RUNNING",
                           "name": "t", "ts": time.time()}])
        for i in range(300):
            table.put_events([{
                "task_id": f"handoff-{i:08x}", "state": "HANDOFF",
                "name": "kv_handoff", "ts": time.time(),
                "stage": "export", "bytes": 1}])
        rows = table.list()
        assert len(rows) <= 32, f"table grew to {len(rows)}"
        assert any(r["task_id"] == "live-1" for r in rows), \
            "live task evicted while synthetic records were spared"
    finally:
        CONFIG.set_overrides(saved)


# --------------------------------------------------------- integration
def test_event_plane_end_to_end(ray_start_regular):
    """One cluster exercises the whole plane: worker spawn/exit events,
    a crash dossier retrievable from the propagated error, node health
    snapshots, dump_stacks on every process kind, and the summary
    sections."""
    import ray_tpu
    from ray_tpu.experimental import state
    from ray_tpu.runtime.core_worker import get_global_worker

    @ray_tpu.remote
    def warm():
        return os.getpid()

    # run a few tasks first so the worker's flight ring has breadcrumbs
    # and at least one flush interval passes before the death
    pid = ray_tpu.get(warm.remote(), timeout=60)
    for _ in range(3):
        ray_tpu.get(warm.remote(), timeout=60)
    time.sleep(1.2 * CONFIG.events_flush_interval_ms / 1000.0 + 0.3)

    @ray_tpu.remote(max_retries=0)
    def die():
        os._exit(13)

    with pytest.raises(ray_tpu.exceptions.WorkerCrashedError) as ei:
        ray_tpu.get(die.remote(), timeout=120)
    err = ei.value
    assert err.dossier_id, "WorkerCrashedError carries no dossier id"

    # events + dossier land asynchronously (flusher + harvest thread)
    deadline = time.monotonic() + 60
    dossier = None
    while time.monotonic() < deadline:
        exits = state.list_cluster_events(type="WORKER_EXIT")
        dossier = state.get_dossier(err.dossier_id)
        if exits and dossier is not None:
            break
        time.sleep(0.5)
    assert exits, "no WORKER_EXIT event reached the GCS table"
    assert dossier is not None, "no dossier for the dead worker"

    # the event names the dead worker and its node
    ev = next(e for e in exits if e["worker_id"] == err.dossier_id)
    assert ev["severity"] == "ERROR"
    assert ev["node_id"]
    # spawn events exist too, and filters compose
    assert state.list_cluster_events(type="WORKER_SPAWN",
                                     node_id=ev["node_id"][:8])
    assert all(e["severity"] == "ERROR"
               for e in state.list_cluster_events(severity="ERROR"))

    # dossier: identifies the process, carries ring + log tail sections
    assert dossier["worker_id"] == err.dossier_id
    assert dossier["kind"] == "worker"
    assert "log_tail" in dossier and "events" in dossier
    # the flight ring captured the warm tasks (the worker outlived a
    # flush interval); the dying task itself may or may not have made
    # the final dump
    assert any(e.get("type") == "TASK_RUNNING"
               for e in dossier["events"]), dossier["events"]
    text = err.debug_dossier()
    assert err.dossier_id[:12] in text or "crash dossier" in text

    # node health snapshots ride heartbeats into list_nodes
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        nodes = [n for n in state.list_nodes() if n.get("health")]
        if nodes:
            break
        time.sleep(0.25)
    assert nodes, "no node health snapshot arrived"
    h = nodes[0]["health"]
    assert {"mem_frac", "store_frac", "loop_lag_ms",
            "workers"} <= set(h)

    # dump_stacks answers on the GCS, the raylet, and a live worker
    worker = get_global_worker()
    gs = worker.gcs.call("dump_stacks", {"duration": 0.05}, timeout=30)
    assert gs["threads"] and isinstance(gs["folded"], dict)
    rs = worker._raylet.call("dump_stacks", {"duration": 0.05},
                             timeout=30)
    assert rs["threads"]
    # the warm worker died with the die() task (lease reuse): sample a
    # freshly-leased live one instead
    live_pid = ray_tpu.get(warm.remote(), timeout=60)
    ws = worker._raylet.call("dump_stacks",
                             {"pid": live_pid, "duration": 0.05},
                             timeout=30)
    assert ws["threads"], "worker dump_stacks forward failed"

    # single-screen summary covers the new plane
    summary = state.metrics_summary()
    assert "Cluster events" in summary
    assert "WORKER_EXIT" in summary
    assert "Node health" in summary

    # legacy ring API still works (PARITY: event.cc analog)
    worker.gcs.call("report_event", {
        "severity": "WARNING", "source": "test", "label": "UNIT",
        "message": "hello", "fields": {"k": 1}})
    legacy = worker.gcs.call("list_events", {"limit": 500})
    assert any(e["label"] == "UNIT" and e["fields"]["k"] == 1
               for e in legacy)


def test_actor_death_dossier(ray_start_regular):
    """rt.kill()'d actor: ActorDiedError carries the dead worker's
    dossier id and the dossier names the actor."""
    import ray_tpu
    from ray_tpu.experimental import state

    @ray_tpu.remote
    class Victim:
        def ping(self):
            return "pong"

    a = Victim.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
    time.sleep(1.2 * CONFIG.events_flush_interval_ms / 1000.0)
    ray_tpu.kill(a)
    # poll until the raylet's actor_failed (carrying the dead worker's
    # id) lands — a get racing it can see DEAD before the id is known
    deadline = time.monotonic() + 60
    err = None
    while time.monotonic() < deadline:
        try:
            ray_tpu.get(a.ping.remote(), timeout=30)
        except ray_tpu.exceptions.ActorDiedError as e:
            err = e
            if err.dossier_id:
                break
        except ray_tpu.exceptions.RayTpuError:
            pass
        time.sleep(0.3)
    assert err is not None, "kill never surfaced as ActorDiedError"
    assert err.dossier_id, "ActorDiedError carries no dossier id"
    deadline = time.monotonic() + 60
    dossier = None
    while time.monotonic() < deadline:
        dossier = state.get_dossier(err.dossier_id)
        if dossier is not None:
            break
        time.sleep(0.5)
    assert dossier is not None
    assert dossier["worker_id"] == err.dossier_id
    assert "ActorDied" in type(err).__name__
    assert "crash dossier" in err.debug_dossier()


def test_dossier_store_bounded(ray_start_regular):
    """The GCS dossier store is FIFO-bounded at gcs_max_dossiers."""
    from ray_tpu.runtime.core_worker import get_global_worker
    gcs = get_global_worker().gcs
    for i in range(CONFIG.gcs_max_dossiers + 20):
        gcs.call("put_dossier", {
            "dossier_id": f"unit-{i:04d}",
            "dossier": {"kind": "worker", "reason": "unit"}})
    listed = gcs.call("list_dossiers")
    assert len(listed) <= CONFIG.gcs_max_dossiers
    # newest survive, oldest rotated
    ids = {d["dossier_id"] for d in listed}
    assert f"unit-{CONFIG.gcs_max_dossiers + 19:04d}" in ids
    assert "unit-0000" not in ids


# ------------------------------------------------- recovery SLO auditor
# (sixth plane, docs/observability.md: the GCS folds the typed event
# stream into drain/failover/heal episodes with SLO classification)

def _ev(etype, ts, **fields):
    return dict(type=etype, ts=ts, **fields)


def test_auditor_drain_episode_matches_event_timestamps():
    """NODE_PREEMPTING -> NODE_DRAINED closes a drain episode whose
    latency is exactly the event-timestamp delta, with the evacuation
    ledger attached from the OBJECT_EVACUATED stream."""
    from ray_tpu._private.metrics_history import RecoveryAuditor

    a = RecoveryAuditor()
    t0 = 1000.0
    a.observe([
        _ev("NODE_PREEMPTING", t0, node_id="n1", grace_s=5.0,
            reason="spot"),
        _ev("OBJECT_EVACUATED", t0 + 0.5, node_id="n1", bytes=100),
        _ev("OBJECT_EVACUATED", t0 + 1.0, node_id="n1", bytes=200),
        _ev("NODE_DRAINED", t0 + 2.5, node_id="n1", evacuated=2,
            bytes=300, failed=0, duration_s=2.4),
    ])
    eps = a.list(kind="drain")
    assert len(eps) == 1
    ep = eps[0]
    assert not ep["open"] and ep["latency_s"] == 2.5
    assert ep["opening_type"] == "NODE_PREEMPTING"
    assert ep["closing_type"] == "NODE_DRAINED"
    assert ep["evacuated"] == 2 and ep["evacuated_bytes"] == 300
    # no explicit drain SLO configured: the advertised grace window is
    # the budget, and 2.5s < 5s is within it
    assert ep["slo_s"] == 5.0 and not ep["violation"]
    assert a.stats()["counts_by_kind"] == {"drain": 1}

    # blowing the grace window classifies as an SLO violation
    a.observe([
        _ev("NODE_PREEMPTING", t0 + 10, node_id="n2", grace_s=1.0),
        _ev("NODE_DRAINED", t0 + 13, node_id="n2", evacuated=0),
    ])
    ep2 = a.list(kind="drain")[-1]
    assert ep2["violation"] and ep2["latency_s"] == 3.0
    assert a.stats()["violations_by_kind"] == {"drain": 1}


def test_auditor_failover_anchors_on_first_failure_event():
    """The graceful path anchors time-to-failover at NODE_PREEMPTING
    (not the later NODE_DEAD), counts lost work, and closes the
    dangling drain as died-before-drained."""
    from ray_tpu._private.metrics_history import RecoveryAuditor

    a = RecoveryAuditor()
    t0 = 2000.0
    a.observe([
        _ev("NODE_PREEMPTING", t0, node_id="n1", grace_s=5.0),
        _ev("NODE_DEAD", t0 + 6.0, node_id="n1", actors_affected=2),
        _ev("TRAIN_GANG_RECOVERY", t0 + 14.0, experiment="exp",
            attempt=1, downtime_s=8.0, resumed_from_checkpoint=True,
            lost_steps=2, resume_step=5, last_step=7),
    ])
    fo = a.list(kind="failover")
    assert len(fo) == 1 and not fo[0]["open"]
    assert fo[0]["opening_type"] == "NODE_PREEMPTING"
    assert fo[0]["latency_s"] == 14.0       # anchored at the notice
    assert fo[0]["lost_steps"] == 2 and fo[0]["experiment"] == "exp"
    assert a.stats()["lost_steps"] == 2
    # the node died before reporting NODE_DRAINED: the drain episode
    # closed as a failure instead of dangling open forever
    dr = a.list(kind="drain")[0]
    assert not dr["open"] and dr["outcome"] == "died before drained"


def test_auditor_failover_without_failure_event_synthesizes_anchor():
    """A recovery with no observed node failure (worker-level crash)
    still yields an episode, anchored on the trainer's downtime."""
    from ray_tpu._private.metrics_history import RecoveryAuditor

    a = RecoveryAuditor()
    a.observe([_ev("TRAIN_GANG_RECOVERY", 3000.0, experiment="solo",
                   downtime_s=4.0, lost_steps=0)])
    eps = a.list(kind="failover")
    assert len(eps) == 1
    assert eps[0]["opening_type"] == "TRAIN_DOWNTIME"
    assert eps[0]["latency_s"] == 4.0
    assert eps[0]["key"] == "run:solo"


def test_auditor_heal_episode():
    """REPLICA_RETIRED -> AUTOSCALE measures serve pool healing."""
    from ray_tpu._private.metrics_history import RecoveryAuditor

    a = RecoveryAuditor()
    a.observe([
        _ev("REPLICA_RETIRED", 4000.0, deployment="d", replica="r1",
            reason="unhealthy"),
        _ev("REPLICA_RETIRED", 4001.0, deployment="d", replica="r2",
            reason="unhealthy"),
        _ev("AUTOSCALE", 4003.0, deployment="d", old_target=2,
            new_target=4, load=0.9),
    ])
    eps = a.list(kind="heal")
    assert len(eps) == 1
    ep = eps[0]
    assert ep["latency_s"] == 3.0 and ep["retired"] == 2
    assert ep["new_target"] == 4
    assert not ep["violation"]   # default heal SLO is 90s


def test_auditor_transfer_failover_counters():
    from ray_tpu._private.metrics_history import RecoveryAuditor

    a = RecoveryAuditor()
    a.observe([
        _ev("TRANSFER_FAILOVER", 5000.0, object_id="o1",
            outcome="restriped"),
        _ev("TRANSFER_FAILOVER", 5001.0, object_id="o2",
            outcome="restriped"),
        _ev("TRANSFER_FAILOVER", 5002.0, object_id="o3",
            outcome="lost"),
    ])
    st = a.stats()
    assert st["transfer_failovers"] == 3
    assert st["transfer_by_outcome"] == {"restriped": 2, "lost": 1}


def test_auditor_retention_bounds_and_rotation_survival():
    """Both retention gates hold (episode count and byte budget) and
    the per-kind totals survive rotation, like the event table's
    counts_by_type."""
    from ray_tpu._private.metrics_history import RecoveryAuditor

    a = RecoveryAuditor(max_episodes=8, max_bytes=1 << 20)
    for i in range(50):
        t = 6000.0 + i * 10
        a.observe([
            _ev("NODE_PREEMPTING", t, node_id=f"n{i}", grace_s=1.0),
            _ev("NODE_DRAINED", t + 2.0, node_id=f"n{i}", evacuated=0),
        ])
    st = a.stats()
    assert st["episodes"] <= 8 and st["dropped"] >= 42
    assert st["counts_by_kind"]["drain"] == 50       # survives rotation
    assert st["violations_by_kind"]["drain"] == 50   # 2s > 1s grace
    assert len(a.list(kind="drain", include_open=False)) <= 8

    # byte budget: padded episodes evict oldest-first until it fits
    b = RecoveryAuditor(max_episodes=10_000, max_bytes=4096)
    for i in range(40):
        t = 7000.0 + i * 10
        b.observe([
            _ev("NODE_PREEMPTING", t, node_id=f"m{i}", grace_s=5.0,
                reason="x" * 200),
            _ev("NODE_DRAINED", t + 1.0, node_id=f"m{i}", evacuated=0),
        ])
    st = b.stats()
    assert st["bytes"] <= 4096 and st["episodes"] < 40
    assert st["counts_by_kind"]["drain"] == 40


def test_doctor_report_names_episodes():
    """The doctor's findings name the auditor's episodes by id, rank
    ERROR above WARNING above INFO, and the text rendering carries the
    verdict."""
    from ray_tpu._private.metrics_history import (
        RecoveryAuditor, build_doctor_report, format_doctor_report)

    a = RecoveryAuditor()
    a.observe([
        _ev("NODE_PREEMPTING", 8000.0, node_id="n1", grace_s=1.0),
        _ev("NODE_DRAINED", 8003.0, node_id="n1", evacuated=1),
    ])
    ep = a.list(kind="drain")[0]
    report = build_doctor_report({
        "nodes": [{"node_id": "n1" * 12, "alive": False},
                  {"node_id": "n2" * 12, "alive": True}],
        "episodes": a.list(),
        "recovery_stats": a.stats(),
        "events": [{"type": "NODE_DEAD", "severity": "ERROR",
                    "ts": 8004.0, "message": "n1 dead"}],
    })
    assert not report["healthy"]
    assert report["counts"]["dead_nodes"] == 1
    assert report["counts"]["slo_violations"] == 1
    sevs = [f["severity"] for f in report["findings"]]
    assert sevs == sorted(sevs, key=["ERROR", "WARNING", "INFO"].index)
    text = format_doctor_report(report)
    assert "ray-tpu doctor" in text
    assert "ATTENTION NEEDED" in text
    assert ep["id"] in text      # the episode is named, e.g. drain-1

    healthy = build_doctor_report({"nodes": [{"node_id": "x", "alive":
                                              True}]})
    assert healthy["healthy"]
    assert "HEALTHY" in format_doctor_report(healthy)
