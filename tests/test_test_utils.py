"""The test-double layer itself (reference _private/test_utils.py —
SignalActor :704, Semaphore :725, wait_for_condition :461,
run_string_as_driver :329)."""

import time

import pytest

import ray_tpu
from ray_tpu._private.test_utils import (Semaphore, SignalActor,
                                         run_string_as_driver,
                                         wait_for_condition)


def test_signal_actor_rendezvous(ray_start_regular):
    sig = SignalActor.remote()

    @ray_tpu.remote
    def blocked(s):
        ray_tpu.get(s.wait.remote())
        return "released"

    ref = blocked.remote(sig)
    # the task is parked on the signal, not finished
    ready, pending = ray_tpu.wait([ref], timeout=1)
    assert pending == [ref]
    wait_for_condition(
        lambda: ray_tpu.get(sig.cur_num_waiters.remote(), timeout=30) == 1,
        timeout=60)
    ray_tpu.get(sig.send.remote(), timeout=30)
    assert ray_tpu.get(ref, timeout=60) == "released"


def test_semaphore_throttles(ray_start_regular):
    sem = Semaphore.remote(value=1)
    ray_tpu.get(sem.acquire.remote(), timeout=30)
    assert ray_tpu.get(sem.locked.remote(), timeout=30)
    ray_tpu.get(sem.release.remote(), timeout=30)
    assert not ray_tpu.get(sem.locked.remote(), timeout=30)


def test_wait_for_condition_surfaces_last_exception():
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise ValueError("not yet")
        return True

    wait_for_condition(flaky, timeout=10, retry_interval_ms=10)

    with pytest.raises(RuntimeError, match="always-broken"):
        def broken():
            raise ValueError("always-broken")
        wait_for_condition(broken, timeout=0.3, retry_interval_ms=50)


def test_run_string_as_driver_isolated(ray_start_regular):
    """A second driver process joins the same cluster and leaves again
    without disturbing this one."""
    from ray_tpu.runtime.core_worker import get_global_worker
    addr = get_global_worker().gcs._address
    out = run_string_as_driver(f"""
import ray_tpu
ray_tpu.init(address="{addr[0]}:{addr[1]}")

@ray_tpu.remote
def f():
    return "from-second-driver"

print(ray_tpu.get(f.remote(), timeout=60))
ray_tpu.shutdown()
""")
    assert "from-second-driver" in out

    @ray_tpu.remote
    def g():
        return 1

    assert ray_tpu.get(g.remote(), timeout=60) == 1
