// Native cluster-resource scheduler: fixed-point resources + hybrid policy.
//
// C++ analog of the reference's raylet scheduling core
// (/root/reference/src/ray/raylet/scheduling/cluster_resource_scheduler.h:45,
// policy/hybrid_scheduling_policy.h:48, fixed_point.h): resource quantities
// are int64 milli-units (exact arithmetic, no float drift when packing
// fractional CPUs), node views live in one flat table, and the hybrid policy
// prefers the local node until its utilization crosses a threshold, then
// spills to the top-k best-utilization feasible nodes deterministically.
//
// Exposed as a C ABI (ctypes-loaded from ray_tpu/_core/scheduler.py); the
// GCS actor scheduler uses it when built, with a pure-Python fallback
// mirroring the semantics (same test suite runs against both).
//
// Thread-safety: one mutex over the node table — scheduling decisions are
// O(nodes * resources) table scans, far from any contention concern at the
// control-plane rates involved.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

constexpr int64_t kMilli = 1000;  // fixed-point scale (fixed_point.h analog)

struct Node {
  // resource name -> milli-units
  std::map<std::string, int64_t> total;
  std::map<std::string, int64_t> available;
  bool alive = true;
};

struct Scheduler {
  std::mutex mu;
  std::map<std::string, Node> nodes;
  double spill_threshold = 0.5;  // hybrid_threshold (ray_config_def.h
                                 // scheduler_spread_threshold default)
  int top_k = 1;
};

// demand/capacity wire format: a flat array of (name, milli) pairs encoded
// as "name\0" strings + int64 array, kept simple: we parse a single packed
// buffer "name=milli;name=milli;..." to avoid multi-array ABI juggling.
std::map<std::string, int64_t> ParseDemand(const char* packed) {
  std::map<std::string, int64_t> out;
  if (packed == nullptr) return out;
  const char* p = packed;
  while (*p) {
    const char* eq = std::strchr(p, '=');
    if (!eq) break;
    const char* sep = std::strchr(eq + 1, ';');
    std::string name(p, eq - p);
    int64_t v = std::strtoll(eq + 1, nullptr, 10);
    out[name] = v;
    if (!sep) break;
    p = sep + 1;
  }
  return out;
}

bool Feasible(const Node& n, const std::map<std::string, int64_t>& demand,
              bool against_total) {
  const auto& cap = against_total ? n.total : n.available;
  for (const auto& [name, need] : demand) {
    if (need <= 0) continue;
    auto it = cap.find(name);
    if (it == cap.end() || it->second < need) return false;
  }
  return true;
}

// "critical resource utilization" after hypothetically placing the demand
// (hybrid_scheduling_policy.cc HybridPolicyWithFarthestNode scoring).
double Utilization(const Node& n, const std::map<std::string, int64_t>& demand) {
  double worst = 0.0;
  for (const auto& [name, tot] : n.total) {
    if (tot <= 0) continue;
    int64_t avail = 0;
    auto it = n.available.find(name);
    if (it != n.available.end()) avail = it->second;
    auto dit = demand.find(name);
    int64_t need = dit == demand.end() ? 0 : dit->second;
    double used = static_cast<double>(tot - avail + need);
    worst = std::max(worst, used / static_cast<double>(tot));
  }
  return worst;
}

}  // namespace

extern "C" {

void* sched_create(double spill_threshold, int top_k) {
  auto* s = new Scheduler();
  s->spill_threshold = spill_threshold;
  s->top_k = std::max(top_k, 1);
  return s;
}

void sched_destroy(void* h) { delete static_cast<Scheduler*>(h); }

void sched_update_node(void* h, const char* node_id, const char* total,
                       const char* available, int alive) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node& n = s->nodes[node_id];
  n.total = ParseDemand(total);
  n.available = ParseDemand(available);
  n.alive = alive != 0;
}

void sched_remove_node(void* h, const char* node_id) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  s->nodes.erase(node_id);
}

int64_t sched_num_nodes(void* h) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return static_cast<int64_t>(s->nodes.size());
}

// Pick the best node for `demand`. Returns 1 and writes the chosen node id
// into out (out_len bytes) on success; 0 if no feasible node. `local_id`
// may be empty. `spread` != 0 selects the spread policy (most-available
// first) instead of hybrid packing.
int sched_best_node(void* h, const char* demand_packed, const char* local_id,
                    int spread, int64_t seed, char* out, int64_t out_len) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto demand = ParseDemand(demand_packed);

  // local-first: if the local node is feasible and under the threshold,
  // keep the task here (hybrid policy's top preference).
  if (!spread && local_id != nullptr && *local_id) {
    auto it = s->nodes.find(local_id);
    if (it != s->nodes.end() && it->second.alive &&
        Feasible(it->second, demand, /*against_total=*/false) &&
        Utilization(it->second, demand) <= s->spill_threshold) {
      std::strncpy(out, local_id, out_len - 1);
      out[out_len - 1] = '\0';
      return 1;
    }
  }

  std::vector<std::pair<double, const std::string*>> scored;
  for (const auto& [id, n] : s->nodes) {
    if (!n.alive || !Feasible(n, demand, false)) continue;
    double u = Utilization(n, demand);
    // hybrid: lowest post-placement utilization wins (pack under the
    // threshold, spread above it); spread: most headroom first — same key.
    scored.emplace_back(u, &id);
  }
  if (scored.empty()) return 0;
  std::sort(scored.begin(), scored.end(),
            [](const auto& a, const auto& b) {
              if (a.first != b.first) return a.first < b.first;
              return *a.second < *b.second;  // deterministic tie-break
            });
  // deterministic rotation over the top-k equally-good candidates so
  // concurrent requests don't all pile onto one node
  int64_t k = std::min<int64_t>(s->top_k, scored.size());
  const std::string* chosen = scored[seed % k].second;
  std::strncpy(out, chosen->c_str(), out_len - 1);
  out[out_len - 1] = '\0';
  return 1;
}

// Feasibility check against *total* capacity — lets the GCS distinguish
// "pending, resources busy" from "infeasible until the cluster grows"
// (the autoscaler scales from pending demand, so neither fails fast).
int sched_feasible_anywhere(void* h, const char* demand_packed) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto demand = ParseDemand(demand_packed);
  for (const auto& [id, n] : s->nodes) {
    (void)id;
    if (n.alive && Feasible(n, demand, /*against_total=*/true)) return 1;
  }
  return 0;
}

}  // extern "C"
