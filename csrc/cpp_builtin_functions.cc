// Built-in cpp task functions shipped in the stock worker binary —
// the e2e test surface for the C++ task runtime (and a usage example
// for RAY_TPU_CPP_FUNCTION).
#include <unistd.h>

#include <numeric>
#include <stdexcept>

#include "cpp_functions.h"

namespace ray_tpu_cpp {

using pycodec::PyVal;

namespace {

PyVal add(const std::vector<PyVal>& args) {
  double acc = 0;
  bool any_float = false;
  int64_t iacc = 0;
  for (const auto& a : args) {
    if (a.kind == PyVal::INT) {
      iacc += a.i;
      acc += (double)a.i;
    } else if (a.kind == PyVal::FLOAT) {
      any_float = true;
      acc += a.f;
    } else {
      throw std::runtime_error("Add: numeric args only");
    }
  }
  return any_float ? PyVal::real(acc) : PyVal::integer(iacc);
}

PyVal concat(const std::vector<PyVal>& args) {
  std::string out;
  for (const auto& a : args) {
    if (a.kind != PyVal::STR) throw std::runtime_error("Concat: str args");
    out += a.s;
  }
  return PyVal::str(out);
}

PyVal fib(const std::vector<PyVal>& args) {
  if (args.size() != 1 || args[0].kind != PyVal::INT)
    throw std::runtime_error("Fib: one int arg");
  int64_t a = 0, b = 1;
  for (int64_t j = 0; j < args[0].i; ++j) {
    int64_t t = a + b;
    a = b;
    b = t;
  }
  return PyVal::integer(a);
}

PyVal echo(const std::vector<PyVal>& args) {
  PyVal out = PyVal::list(std::vector<PyVal>(args.begin(), args.end()));
  return out;
}

PyVal fail(const std::vector<PyVal>& args) {
  std::string msg = "cpp task failed deliberately";
  if (!args.empty() && args[0].kind == PyVal::STR) msg = args[0].s;
  throw std::runtime_error(msg);
}

PyVal blob(const std::vector<PyVal>& args) {
  // n bytes of fill — exercises the above-inline-threshold result path
  // (sealed into the shm store, {"location": ...} reply)
  if (args.empty() || args[0].kind != PyVal::INT)
    throw std::runtime_error("Blob: (n [, fill-str]) args");
  char fill = args.size() > 1 && !args[1].s.empty() ? args[1].s[0] : 'x';
  return PyVal::bytes(std::string((size_t)args[0].i, fill));
}

PyVal pid(const std::vector<PyVal>&) {
  // lets tests assert which PROCESS ran a task (language-pool isolation)
  return PyVal::integer((int64_t)::getpid());
}

PyVal minmax(const std::vector<PyVal>& args) {
  // two returns: exercise num_returns=2 from a cpp task
  if (args.empty()) throw std::runtime_error("MinMax: need args");
  int64_t lo = args[0].i, hi = args[0].i;
  for (const auto& a : args) {
    if (a.kind != PyVal::INT) throw std::runtime_error("MinMax: int args");
    if (a.i < lo) lo = a.i;
    if (a.i > hi) hi = a.i;
  }
  return PyVal::tuple({PyVal::integer(lo), PyVal::integer(hi)});
}

// ---------------------------------------------------------------- actors

struct CounterActor : CppActor {
  int64_t n = 0;
  explicit CounterActor(int64_t start) : n(start) {}
  // pid lets tests target THIS actor's process exactly (restart tests)
  PyVal call(const std::string& method,
             const std::vector<PyVal>& args) override {
    if (method == "inc") {
      n += args.empty() ? 1 : args[0].i;
      return PyVal::integer(n);
    }
    if (method == "total") return PyVal::integer(n);
    if (method == "pid") return PyVal::integer((int64_t)::getpid());
    if (method == "payload")  // big actor result -> store-object reply
      return PyVal::bytes(std::string((size_t)args.at(0).i, 'y'));
    if (method == "boom") throw std::runtime_error("counter exploded");
    throw std::runtime_error("CounterActor has no method '" + method + "'");
  }
};

struct KvActor : CppActor {
  std::vector<std::pair<std::string, PyVal>> entries;
  PyVal call(const std::string& method,
             const std::vector<PyVal>& args) override {
    if (method == "put") {
      if (args.size() != 2 || args[0].kind != PyVal::STR)
        throw std::runtime_error("put(key: str, value)");
      for (auto& kv : entries)
        if (kv.first == args[0].s) {
          kv.second = args[1];
          return PyVal::none();
        }
      entries.emplace_back(args[0].s, args[1]);
      return PyVal::none();
    }
    if (method == "get") {
      for (auto& kv : entries)
        if (kv.first == args.at(0).s) return kv.second;
      return PyVal::none();
    }
    if (method == "size") return PyVal::integer((int64_t)entries.size());
    throw std::runtime_error("KvActor has no method '" + method + "'");
  }
};

}  // namespace

void register_builtin_functions() {
  register_actor_class("Counter", [](const std::vector<PyVal>& args) {
    return std::unique_ptr<CppActor>(
        new CounterActor(args.empty() ? 0 : args[0].i));
  });
  register_actor_class("Kv", [](const std::vector<PyVal>&) {
    return std::unique_ptr<CppActor>(new KvActor());
  });
  register_function("Add", add);
  register_function("Concat", concat);
  register_function("Fib", fib);
  register_function("Echo", echo);
  register_function("Fail", fail);
  register_function("Blob", blob);
  register_function("Pid", pid);
  register_function("MinMax", minmax);
}

}  // namespace ray_tpu_cpp
