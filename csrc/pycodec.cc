// pycodec implementation — see pycodec.h for scope.
#include "pycodec.h"

#include <cstring>

namespace pycodec {

namespace {

// ---------------------------------------------------------------- repr
void repr_into(const PyVal& v, std::string* out) {
  char buf[64];
  switch (v.kind) {
    case PyVal::NONE: *out += "None"; break;
    case PyVal::BOOL: *out += v.b ? "True" : "False"; break;
    case PyVal::INT:
      snprintf(buf, sizeof buf, "%lld", (long long)v.i);
      *out += buf;
      break;
    case PyVal::FLOAT:
      snprintf(buf, sizeof buf, "%g", v.f);
      *out += buf;
      break;
    case PyVal::STR:
      *out += '\'';
      *out += v.s;
      *out += '\'';
      break;
    case PyVal::BYTES:
      *out += "b'";
      for (unsigned char c : v.s) {
        if (c >= 0x20 && c < 0x7f && c != '\'') {
          *out += (char)c;
        } else {
          snprintf(buf, sizeof buf, "\\x%02x", c);
          *out += buf;
        }
      }
      *out += '\'';
      break;
    case PyVal::LIST:
    case PyVal::TUPLE: {
      *out += v.kind == PyVal::LIST ? '[' : '(';
      for (size_t j = 0; j < v.items.size(); ++j) {
        if (j) *out += ", ";
        repr_into(v.items[j], out);
      }
      if (v.kind == PyVal::TUPLE && v.items.size() == 1) *out += ',';
      *out += v.kind == PyVal::LIST ? ']' : ')';
      break;
    }
    case PyVal::DICT: {
      *out += '{';
      for (size_t j = 0; j < v.map.size(); ++j) {
        if (j) *out += ", ";
        repr_into(v.map[j].first, out);
        *out += ": ";
        repr_into(v.map[j].second, out);
      }
      *out += '}';
      break;
    }
    case PyVal::OPAQUE: {
      *out += '<';
      *out += v.s;
      if (!v.items.empty()) {
        *out += '(';
        for (size_t j = 0; j < v.items.size(); ++j) {
          if (j) *out += ", ";
          repr_into(v.items[j], out);
        }
        *out += ')';
      }
      *out += '>';
      break;
    }
  }
}

// ------------------------------------------------------------- decoder
struct Reader {
  const unsigned char* p;
  const unsigned char* end;
  explicit Reader(const std::string& d)
      : p((const unsigned char*)d.data()),
        end((const unsigned char*)d.data() + d.size()) {}
  unsigned char u8() {
    if (p >= end) throw CodecError("pickle: truncated");
    return *p++;
  }
  const unsigned char* take(size_t n) {
    if ((size_t)(end - p) < n) throw CodecError("pickle: truncated");
    const unsigned char* q = p;
    p += n;
    return q;
  }
  uint16_t u16le() {
    const unsigned char* q = take(2);
    return (uint16_t)(q[0] | q[1] << 8);
  }
  uint32_t u32le() {
    const unsigned char* q = take(4);
    return (uint32_t)q[0] | (uint32_t)q[1] << 8 | (uint32_t)q[2] << 16 |
           (uint32_t)q[3] << 24;
  }
  uint64_t u64le() {
    uint64_t lo = u32le();
    uint64_t hi = u32le();
    return lo | hi << 32;
  }
};

constexpr int kMark = -1;  // sentinel index on the meta stack

struct Unpickler {
  // The stack and memo hold shared_ptrs to the SAME object: CPython
  // memoizes a container BEFORE populating it (EMPTY_LIST; MEMOIZE;
  // MARK ... APPENDS), so a BINGET alias must observe the later
  // population or shared references (e.g. Echo(x, x)) decode as empty
  // containers.  Embedding ops copy the (by then fully built) child by
  // value — correct for all acyclic data; cycles are out of scope (the
  // control plane never sends them) and surface as wrong-but-terminating
  // copies rather than infinite loops.
  using Ref = std::shared_ptr<PyVal>;
  Reader r;
  std::vector<Ref> stack;
  std::vector<size_t> marks;
  std::vector<Ref> memo;

  explicit Unpickler(const std::string& d) : r(d) {}

  void push(PyVal v) { stack.push_back(std::make_shared<PyVal>(std::move(v))); }
  Ref pop() {
    if (stack.empty()) throw CodecError("pickle: stack underflow");
    Ref v = std::move(stack.back());
    stack.pop_back();
    return v;
  }
  PyVal& top() {
    if (stack.empty()) throw CodecError("pickle: empty stack");
    return *stack.back();
  }
  std::vector<Ref> pop_to_mark() {
    if (marks.empty()) throw CodecError("pickle: no mark");
    size_t m = marks.back();
    marks.pop_back();
    std::vector<Ref> out(std::make_move_iterator(stack.begin() + m),
                         std::make_move_iterator(stack.end()));
    stack.resize(m);
    return out;
  }
  void memo_put(size_t idx) {
    if (stack.empty()) throw CodecError("pickle: memoize on empty stack");
    if (memo.size() <= idx) memo.resize(idx + 1);
    memo[idx] = stack.back();  // alias, not copy
  }

  PyVal run() {
    for (;;) {
      unsigned char op = r.u8();
      switch (op) {
        case 0x80: /* PROTO */ r.u8(); break;
        case 0x95: /* FRAME */ r.u64le(); break;
        case '.': /* STOP */
          if (stack.size() != 1)
            throw CodecError("pickle: bad final stack");
          return *stack.back();
        case 'N': push(PyVal::none()); break;
        case 0x88: push(PyVal::boolean(true)); break;
        case 0x89: push(PyVal::boolean(false)); break;
        case 'J': /* BININT, signed */
          push(PyVal::integer((int32_t)r.u32le()));
          break;
        case 'K': push(PyVal::integer(r.u8())); break;
        case 'M': push(PyVal::integer(r.u16le())); break;
        case 0x8a: { /* LONG1 */
          size_t n = r.u8();
          if (n > 8) throw CodecError("pickle: LONG1 too wide for int64");
          const unsigned char* q = r.take(n);
          uint64_t raw = 0;
          for (size_t j = 0; j < n; ++j) raw |= (uint64_t)q[j] << (8 * j);
          // sign-extend little-endian two's complement
          if (n > 0 && n < 8 && (q[n - 1] & 0x80))
            raw |= ~uint64_t(0) << (8 * n);
          push(PyVal::integer((int64_t)raw));
          break;
        }
        case 'G': { /* BINFLOAT, big-endian double */
          const unsigned char* q = r.take(8);
          uint64_t raw = 0;
          for (int j = 0; j < 8; ++j) raw = raw << 8 | q[j];
          double d;
          memcpy(&d, &raw, 8);
          push(PyVal::real(d));
          break;
        }
        case 0x8c: { /* SHORT_BINUNICODE */
          size_t n = r.u8();
          const unsigned char* q = r.take(n);
          push(PyVal::str(std::string((const char*)q, n)));
          break;
        }
        case 'X': { /* BINUNICODE */
          size_t n = r.u32le();
          const unsigned char* q = r.take(n);
          push(PyVal::str(std::string((const char*)q, n)));
          break;
        }
        case 0x8d: { /* BINUNICODE8 */
          size_t n = (size_t)r.u64le();
          const unsigned char* q = r.take(n);
          push(PyVal::str(std::string((const char*)q, n)));
          break;
        }
        case 'C': { /* SHORT_BINBYTES */
          size_t n = r.u8();
          const unsigned char* q = r.take(n);
          push(PyVal::bytes(std::string((const char*)q, n)));
          break;
        }
        case 'B': { /* BINBYTES */
          size_t n = r.u32le();
          const unsigned char* q = r.take(n);
          push(PyVal::bytes(std::string((const char*)q, n)));
          break;
        }
        case 0x8e: { /* BINBYTES8 */
          size_t n = (size_t)r.u64le();
          const unsigned char* q = r.take(n);
          push(PyVal::bytes(std::string((const char*)q, n)));
          break;
        }
        case ']': push(PyVal::list()); break;
        case ')': push(PyVal::tuple()); break;
        case '}': push(PyVal::dict()); break;
        case '(': marks.push_back(stack.size()); break;
        case 'a': { /* APPEND */
          Ref v = pop();
          if (top().kind != PyVal::LIST)
            throw CodecError("pickle: APPEND to non-list");
          top().items.push_back(*v);
          break;
        }
        case 'e': { /* APPENDS */
          std::vector<Ref> vs = pop_to_mark();
          if (top().kind != PyVal::LIST)
            throw CodecError("pickle: APPENDS to non-list");
          for (auto& v : vs) top().items.push_back(*v);
          break;
        }
        case 't': { /* TUPLE */
          std::vector<Ref> vs = pop_to_mark();
          std::vector<PyVal> items;
          items.reserve(vs.size());
          for (auto& v : vs) items.push_back(*v);
          push(PyVal::tuple(std::move(items)));
          break;
        }
        case 0x85: { /* TUPLE1 */
          Ref a = pop();
          push(PyVal::tuple({*a}));
          break;
        }
        case 0x86: { /* TUPLE2 */
          Ref b2 = pop(), a = pop();
          push(PyVal::tuple({*a, *b2}));
          break;
        }
        case 0x87: { /* TUPLE3 */
          Ref c = pop(), b2 = pop(), a = pop();
          push(PyVal::tuple({*a, *b2, *c}));
          break;
        }
        case 's': { /* SETITEM */
          Ref v = pop(), k = pop();
          if (top().kind != PyVal::DICT)
            throw CodecError("pickle: SETITEM on non-dict");
          top().map.emplace_back(*k, *v);
          break;
        }
        case 'u': { /* SETITEMS */
          std::vector<Ref> vs = pop_to_mark();
          if (vs.size() % 2)
            throw CodecError("pickle: SETITEMS odd count");
          if (top().kind != PyVal::DICT)
            throw CodecError("pickle: SETITEMS on non-dict");
          for (size_t j = 0; j < vs.size(); j += 2)
            top().map.emplace_back(*vs[j], *vs[j + 1]);
          break;
        }
        case 0x94: /* MEMOIZE */ memo_put(memo.size()); break;
        case 'q': /* BINPUT */ memo_put(r.u8()); break;
        case 'r': /* LONG_BINPUT */ memo_put(r.u32le()); break;
        case 'h': { /* BINGET */
          size_t idx = r.u8();
          if (idx >= memo.size()) throw CodecError("pickle: bad memo get");
          stack.push_back(memo[idx]);
          break;
        }
        case 'j': { /* LONG_BINGET */
          size_t idx = r.u32le();
          if (idx >= memo.size()) throw CodecError("pickle: bad memo get");
          stack.push_back(memo[idx]);
          break;
        }
        case 'c': { /* GLOBAL: two newline-terminated strings */
          std::string mod, name;
          for (unsigned char ch; (ch = r.u8()) != '\n';) mod += (char)ch;
          for (unsigned char ch; (ch = r.u8()) != '\n';) name += (char)ch;
          PyVal o;
          o.kind = PyVal::OPAQUE;
          o.s = mod + "." + name;
          push(std::move(o));
          break;
        }
        case 0x93: { /* STACK_GLOBAL */
          Ref name = pop(), mod = pop();
          PyVal o;
          o.kind = PyVal::OPAQUE;
          o.s = (mod->kind == PyVal::STR ? mod->s : "?") + "." +
                (name->kind == PyVal::STR ? name->s : "?");
          push(std::move(o));
          break;
        }
        case 'R':      /* REDUCE: callable(args) -> opaque keeping both */
        case 0x81: { /* NEWOBJ: cls.__new__(cls, *args) */
          PyVal args = *pop(), callable = *pop();
          // protocol-2 bytes: _codecs.encode(latin1_str, 'latin1') — map
          // the utf-8-carried code points (< 256 by construction) back
          if (callable.kind == PyVal::OPAQUE &&
              callable.s == "_codecs.encode" &&
              args.kind == PyVal::TUPLE && args.items.size() == 2 &&
              args.items[0].kind == PyVal::STR &&
              args.items[1].kind == PyVal::STR &&
              args.items[1].s == "latin1") {
            const std::string& u = args.items[0].s;
            std::string raw;
            raw.reserve(u.size());
            for (size_t j = 0; j < u.size();) {
              unsigned char c0 = u[j];
              if (c0 < 0x80) {
                raw += (char)c0;
                j += 1;
              } else {  // 2-byte utf-8 sequence for U+0080..U+00FF
                if (j + 1 >= u.size())
                  throw CodecError("pickle: bad latin1 payload");
                raw += (char)(((c0 & 0x1f) << 6) | (u[j + 1] & 0x3f));
                j += 2;
              }
            }
            push(PyVal::bytes(std::move(raw)));
            break;
          }
          // protocol-2 empty bytes: __builtin__.bytes() / builtins.bytes()
          if (callable.kind == PyVal::OPAQUE &&
              (callable.s == "__builtin__.bytes" ||
               callable.s == "builtins.bytes") &&
              args.kind == PyVal::TUPLE && args.items.empty()) {
            push(PyVal::bytes(""));
            break;
          }
          PyVal o;
          o.kind = PyVal::OPAQUE;
          o.s = callable.kind == PyVal::OPAQUE ? callable.s : "?";
          if (args.kind == PyVal::TUPLE) o.items = std::move(args.items);
          else o.items.push_back(std::move(args));
          push(std::move(o));
          break;
        }
        case 'b': { /* BUILD: obj.__setstate__(state) — keep the state */
          Ref state = pop();
          if (top().kind == PyVal::OPAQUE)
            top().items.push_back(*state);
          break;
        }
        case 0x8f: /* EMPTY_SET -> treat as list */
          push(PyVal::list());
          break;
        case 0x90: { /* ADDITEMS (set) */
          std::vector<Ref> vs = pop_to_mark();
          if (top().kind != PyVal::LIST)
            throw CodecError("pickle: ADDITEMS on non-set");
          for (auto& v : vs) top().items.push_back(*v);
          break;
        }
        default: {
          char msg[64];
          snprintf(msg, sizeof msg, "pickle: unsupported opcode 0x%02x", op);
          throw CodecError(msg);
        }
      }
    }
  }
};

// ------------------------------------------------------------- encoder
// RFC 3629: length of the valid UTF-8 sequence at p[i] (rejects
// overlongs, surrogates, and > U+10FFFF), or 0 when invalid.
size_t utf8_seq_len(const unsigned char* p, size_t n, size_t i) {
  unsigned char c = p[i];
  if (c < 0x80) return 1;
  if ((c & 0xe0) == 0xc0) {
    if (i + 1 >= n || (p[i + 1] & 0xc0) != 0x80 || c < 0xc2) return 0;
    return 2;
  }
  if ((c & 0xf0) == 0xe0) {
    if (i + 2 >= n || (p[i + 1] & 0xc0) != 0x80 ||
        (p[i + 2] & 0xc0) != 0x80)
      return 0;
    if (c == 0xe0 && p[i + 1] < 0xa0) return 0;   // overlong
    if (c == 0xed && p[i + 1] >= 0xa0) return 0;  // surrogate
    return 3;
  }
  if ((c & 0xf8) == 0xf0) {
    if (i + 3 >= n || (p[i + 1] & 0xc0) != 0x80 ||
        (p[i + 2] & 0xc0) != 0x80 || (p[i + 3] & 0xc0) != 0x80)
      return 0;
    if (c == 0xf0 && p[i + 1] < 0x90) return 0;  // overlong
    if (c > 0xf4 || (c == 0xf4 && p[i + 1] >= 0x90))
      return 0;  // > U+10FFFF
    return 4;
  }
  return 0;
}

bool is_valid_utf8(const std::string& s) {
  const unsigned char* p = (const unsigned char*)s.data();
  size_t n = s.size();
  for (size_t i = 0; i < n;) {
    size_t len = utf8_seq_len(p, n, i);
    if (!len) return false;
    i += len;
  }
  return true;
}

void dump_val(const PyVal& v, std::string* out) {
  char buf[16];
  switch (v.kind) {
    case PyVal::NONE: *out += 'N'; break;
    case PyVal::BOOL: *out += (char)(v.b ? 0x88 : 0x89); break;
    case PyVal::INT: {
      if (v.i >= 0 && v.i < 256) {
        *out += 'K';
        *out += (char)v.i;
      } else if (v.i >= INT32_MIN && v.i <= INT32_MAX) {
        *out += 'J';
        uint32_t u = (uint32_t)(int32_t)v.i;
        for (int j = 0; j < 4; ++j) *out += (char)(u >> (8 * j));
      } else { /* LONG1, 8-byte two's complement + sign pad rules */
        uint64_t u = (uint64_t)v.i;
        unsigned char le[9];
        size_t n = 0;
        for (; n < 8; ++n) le[n] = (unsigned char)(u >> (8 * n));
        // trim redundant sign bytes
        while (n > 1) {
          unsigned char top = le[n - 1], next = le[n - 2];
          if ((top == 0x00 && !(next & 0x80)) ||
              (top == 0xff && (next & 0x80)))
            --n;
          else
            break;
        }
        *out += (char)0x8a;
        *out += (char)n;
        out->append((const char*)le, n);
      }
      break;
    }
    case PyVal::FLOAT: {
      *out += 'G';
      uint64_t raw;
      memcpy(&raw, &v.f, 8);
      for (int j = 7; j >= 0; --j) *out += (char)(raw >> (8 * j));
      break;
    }
    case PyVal::STR: {
      // BINUNICODE carries raw UTF-8; an invalid sequence would only
      // surface as an opaque UnicodeDecodeError at the Python owner's
      // get(), far from the producing function. Fail here instead.
      if (!is_valid_utf8(v.s))
        throw CodecError(
            "non-UTF-8 str result: return bytes instead of str");
      *out += 'X';
      uint32_t n = (uint32_t)v.s.size();
      for (int j = 0; j < 4; ++j) *out += (char)(n >> (8 * j));
      *out += v.s;
      break;
    }
    case PyVal::BYTES: {
      *out += 'B';
      uint32_t n = (uint32_t)v.s.size();
      for (int j = 0; j < 4; ++j) *out += (char)(n >> (8 * j));
      *out += v.s;
      break;
    }
    case PyVal::LIST: {
      *out += ']';
      if (!v.items.empty()) {
        *out += '(';
        for (const auto& it : v.items) dump_val(it, out);
        *out += 'e';
      }
      break;
    }
    case PyVal::TUPLE: {
      if (v.items.empty()) {
        *out += ')';
      } else if (v.items.size() <= 3) {
        for (const auto& it : v.items) dump_val(it, out);
        *out += (char)(0x85 + v.items.size() - 1);
      } else {
        *out += '(';
        for (const auto& it : v.items) dump_val(it, out);
        *out += 't';
      }
      break;
    }
    case PyVal::DICT: {
      *out += '}';
      if (!v.map.empty()) {
        *out += '(';
        for (const auto& kv : v.map) {
          dump_val(kv.first, out);
          dump_val(kv.second, out);
        }
        *out += 'u';
      }
      break;
    }
    case PyVal::OPAQUE: {
      // GLOBAL(module, name) + args tuple + REDUCE: lets C++ construct
      // Python objects by qualified name — used for real exception
      // payloads (e.g. ray_tpu.exceptions.TaskError) in task replies
      size_t dot = v.s.rfind('.');
      if (dot == std::string::npos)
        throw CodecError("pickle: opaque value needs module.name: " + v.s);
      *out += 'c';
      *out += v.s.substr(0, dot);
      *out += '\n';
      *out += v.s.substr(dot + 1);
      *out += '\n';
      PyVal args = PyVal::tuple(v.items);
      dump_val(args, out);
      *out += 'R';
      break;
    }
  }
  (void)buf;
}

// ----------------------------------------------------- msgpack (tiny)
void mp_uint(uint64_t n, std::string* out) {
  if (n < 128) {
    *out += (char)n;
  } else if (n <= 0xffffffffu) {
    *out += (char)0xce;
    for (int j = 3; j >= 0; --j) *out += (char)(n >> (8 * j));
  } else {
    *out += (char)0xcf;
    for (int j = 7; j >= 0; --j) *out += (char)(n >> (8 * j));
  }
}
void mp_str(const std::string& s, std::string* out) {
  if (s.size() < 32) {
    *out += (char)(0xa0 | s.size());
  } else {
    *out += (char)0xd9;
    *out += (char)s.size();
  }
  *out += s;
}

}  // namespace

std::string PyVal::repr() const {
  std::string out;
  repr_into(*this, &out);
  return out;
}

PyVal pickle_loads(const std::string& data) {
  Unpickler u(data);
  (void)kMark;
  return u.run();
}

std::string sanitize_utf8(const std::string& s) {
  if (is_valid_utf8(s)) return s;
  const unsigned char* p = (const unsigned char*)s.data();
  size_t n = s.size();
  std::string out;
  out.reserve(n);
  for (size_t i = 0; i < n;) {
    size_t len = utf8_seq_len(p, n, i);
    if (len) {
      out.append(s, i, len);
      i += len;
    } else {
      out += "\xef\xbf\xbd";  // U+FFFD replacement character
      ++i;
    }
  }
  return out;
}

std::string pickle_dumps(const PyVal& v) {
  std::string out;
  out += (char)0x80;  // PROTO
  out += (char)3;     // bytes needs >= 3
  dump_val(v, &out);
  out += '.';
  return out;
}

std::string flat_serialize(const PyVal& v, int64_t error_type) {
  std::string payload = pickle_dumps(v);
  // msgpack {"n":0, "lens":[], "plen":N, "err":E}
  std::string meta;
  meta += (char)0x84;  // fixmap(4)
  mp_str("n", &meta);
  meta += (char)0x00;
  mp_str("lens", &meta);
  meta += (char)0x90;  // fixarray(0)
  mp_str("plen", &meta);
  mp_uint(payload.size(), &meta);
  mp_str("err", &meta);
  mp_uint((uint64_t)error_type, &meta);
  std::string out;
  uint32_t mlen = (uint32_t)meta.size();
  for (int j = 0; j < 4; ++j) out += (char)(mlen >> (8 * j));
  out += meta;
  out += payload;
  return out;
}

namespace {
// minimal msgpack reader for the meta dict written by serialization.py
struct MpReader {
  Reader r;
  explicit MpReader(const unsigned char* p, const unsigned char* end)
      : r("") {
    r.p = p;
    r.end = end;
  }
  uint64_t read_uint() {
    unsigned char t = r.u8();
    if (t < 0x80) return t;
    if (t == 0xcc) return r.u8();
    if (t == 0xcd) {
      const unsigned char* q = r.take(2);
      return (uint64_t)q[0] << 8 | q[1];
    }
    if (t == 0xce) {
      const unsigned char* q = r.take(4);
      return (uint64_t)q[0] << 24 | (uint64_t)q[1] << 16 |
             (uint64_t)q[2] << 8 | q[3];
    }
    if (t == 0xcf) {
      const unsigned char* q = r.take(8);
      uint64_t n = 0;
      for (int j = 0; j < 8; ++j) n = n << 8 | q[j];
      return n;
    }
    throw CodecError("msgpack: expected uint");
  }
  std::string read_str() {
    unsigned char t = r.u8();
    size_t n;
    if ((t & 0xe0) == 0xa0) n = t & 0x1f;
    else if (t == 0xd9) n = r.u8();
    else throw CodecError("msgpack: expected str");
    const unsigned char* q = r.take(n);
    return std::string((const char*)q, n);
  }
};
}  // namespace

PyVal flat_deserialize(const std::string& data, int64_t* error_type) {
  if (data.size() < 4) throw CodecError("flat: truncated header");
  uint32_t mlen = (uint32_t)(unsigned char)data[0] |
                  (uint32_t)(unsigned char)data[1] << 8 |
                  (uint32_t)(unsigned char)data[2] << 16 |
                  (uint32_t)(unsigned char)data[3] << 24;
  if (data.size() < 4 + mlen) throw CodecError("flat: truncated meta");
  MpReader mp((const unsigned char*)data.data() + 4,
              (const unsigned char*)data.data() + 4 + mlen);
  unsigned char t = mp.r.u8();
  if ((t & 0xf0) != 0x80) throw CodecError("flat: meta not a map");
  size_t pairs = t & 0x0f;
  uint64_t nbuf = 0, plen = 0, err = 0;
  for (size_t j = 0; j < pairs; ++j) {
    std::string key = mp.read_str();
    if (key == "lens") {
      unsigned char at = mp.r.u8();
      size_t n;
      if ((at & 0xf0) == 0x90) n = at & 0x0f;
      else if (at == 0xdc) { const unsigned char* q = mp.r.take(2);
                             n = (size_t)q[0] << 8 | q[1]; }
      else throw CodecError("flat: lens not array");
      for (size_t k = 0; k < n; ++k) mp.read_uint();
    } else {
      uint64_t val = mp.read_uint();
      if (key == "n") nbuf = val;
      else if (key == "plen") plen = val;
      else if (key == "err") err = val;
    }
  }
  if (nbuf != 0)
    throw CodecError("flat: payload has out-of-band buffers (numpy?) — "
                     "not representable C++-side");
  if (error_type) *error_type = (int64_t)err;
  if (data.size() < 4 + mlen + plen) throw CodecError("flat: truncated");
  return pickle_loads(data.substr(4 + mlen, plen));
}

}  // namespace pycodec
