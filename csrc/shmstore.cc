// Shared-memory immutable object store — one per node, created by the node
// daemon, attached by every worker/driver on the host.
//
// TPU-native analog of the reference's Plasma store
// (/root/reference/src/ray/object_manager/plasma/: ObjectStore /
// ObjectLifecycleManager / EvictionPolicy / dlmalloc shm allocator).  Design
// deltas from the reference, chosen for the TPU process model:
//   - The store lives in one mmap'd POSIX shm segment shared by all local
//     processes; no broker socket / fd passing (plasma's fling.cc) — clients
//     address objects by (offset, size) inside the common mapping, so a get
//     is a pointer, not an IPC round trip.
//   - Synchronization is a single process-shared robust pthread mutex in the
//     segment header plus a monotonically increasing seal counter clients can
//     poll/futex on.  (Plasma serializes through its event loop instead.)
//   - Allocation is a first-fit free list with coalescing; eviction is LRU
//     over sealed, unpinned objects (plasma: eviction_policy.h LRUCache).
//
// Object lifecycle: CREATED -> SEALED (immutable) -> deleted/evicted.
// Pins (get) protect sealed objects from eviction; creators hold an implicit
// pin until seal+release.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <pthread.h>

extern "C" {

static const uint64_t kMagic = 0x5241595450553031ULL;  // "RAYTPU01"
static const uint32_t kIdLen = 20;

enum ObjState : uint32_t { FREE_SLOT = 0, CREATED = 1, SEALED = 2 };

struct ObjEntry {
  uint8_t id[kIdLen];
  uint32_t state;
  uint64_t offset;
  uint64_t size;
  uint64_t meta;       // small user metadata word (e.g. error flag)
  int32_t pins;
  uint64_t lru_tick;
};

struct FreeBlock {
  uint64_t offset;
  uint64_t size;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;       // bytes of the data arena
  uint64_t data_start;     // offset of arena from segment base
  uint32_t table_size;     // number of ObjEntry slots
  uint32_t max_free;       // capacity of free list
  pthread_mutex_t mutex;
  uint64_t seal_count;     // bumped on every seal (clients poll this)
  uint64_t lru_clock;
  uint64_t bytes_in_use;
  uint64_t leaked_bytes;   // blocks lost when the free list overflowed
  uint32_t num_objects;
  uint32_t num_free;       // free-list entries
  // followed by: ObjEntry[table_size], FreeBlock[max_free], data arena
};

static ObjEntry* table_of(Header* h) {
  return reinterpret_cast<ObjEntry*>(reinterpret_cast<char*>(h) + sizeof(Header));
}
static FreeBlock* freelist_of(Header* h) {
  return reinterpret_cast<FreeBlock*>(
      reinterpret_cast<char*>(table_of(h)) + sizeof(ObjEntry) * h->table_size);
}

// ---------------------------------------------------------------------------
// init / attach
// ---------------------------------------------------------------------------

// Required segment size for a store of `capacity` data bytes.
uint64_t store_segment_size(uint64_t capacity, uint32_t table_size,
                            uint32_t max_free) {
  return sizeof(Header) + sizeof(ObjEntry) * table_size +
         sizeof(FreeBlock) * max_free + capacity;
}

// Initialize a zeroed mapping as a store. Returns 0 on success.
int store_init(void* base, uint64_t capacity, uint32_t table_size,
               uint32_t max_free) {
  Header* h = static_cast<Header*>(base);
  memset(h, 0, sizeof(Header));
  h->capacity = capacity;
  h->table_size = table_size;
  h->max_free = max_free;
  h->data_start = sizeof(Header) + sizeof(ObjEntry) * table_size +
                  sizeof(FreeBlock) * max_free;
  memset(table_of(h), 0, sizeof(ObjEntry) * table_size);
  FreeBlock* fl = freelist_of(h);
  fl[0].offset = h->data_start;
  fl[0].size = capacity;
  h->num_free = 1;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  if (pthread_mutex_init(&h->mutex, &attr) != 0) return -1;
  pthread_mutexattr_destroy(&attr);
  __sync_synchronize();
  h->magic = kMagic;
  return 0;
}

int store_validate(void* base) {
  Header* h = static_cast<Header*>(base);
  return h->magic == kMagic ? 0 : -1;
}

static int lock(Header* h) {
  int rc = pthread_mutex_lock(&h->mutex);
  if (rc == EOWNERDEAD) {
    // A holder died mid-operation; table stays usable (ops are idempotent
    // enough for our immutable objects), mark consistent and continue.
    pthread_mutex_consistent(&h->mutex);
    rc = 0;
  }
  return rc;
}
static void unlock(Header* h) { pthread_mutex_unlock(&h->mutex); }

// ---------------------------------------------------------------------------
// table / allocator helpers (mutex held)
// ---------------------------------------------------------------------------

static uint32_t hash_id(const uint8_t* id) {
  uint64_t x = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdLen; i++) { x ^= id[i]; x *= 1099511628211ULL; }
  return static_cast<uint32_t>(x);
}

static ObjEntry* find_entry(Header* h, const uint8_t* id, int for_insert) {
  ObjEntry* t = table_of(h);
  uint32_t n = h->table_size;
  uint32_t start = hash_id(id) % n;
  ObjEntry* first_free = nullptr;
  for (uint32_t probe = 0; probe < n; probe++) {
    ObjEntry* e = &t[(start + probe) % n];
    if (e->state == FREE_SLOT) {
      if (!first_free) first_free = e;
      // open addressing without tombstones: FREE ends the probe chain only
      // if we never delete mid-chain; we compact on delete (see erase).
      break;
    }
    if (memcmp(e->id, id, kIdLen) == 0) return e;
  }
  return for_insert ? first_free : nullptr;
}

// Robin-hood-free deletion: re-insert the tail of the probe cluster.
static void erase_entry(Header* h, ObjEntry* e) {
  ObjEntry* t = table_of(h);
  uint32_t n = h->table_size;
  uint32_t idx = static_cast<uint32_t>(e - t);
  e->state = FREE_SLOT;
  uint32_t i = (idx + 1) % n;
  while (t[i].state != FREE_SLOT) {
    ObjEntry moved = t[i];
    t[i].state = FREE_SLOT;
    ObjEntry* dst = find_entry(h, moved.id, 1);
    *dst = moved;
    i = (i + 1) % n;
  }
}

static int free_insert(Header* h, uint64_t offset, uint64_t size) {
  FreeBlock* fl = freelist_of(h);
  uint32_t n = h->num_free;
  // find insertion point (keep sorted by offset) and coalesce
  uint32_t i = 0;
  while (i < n && fl[i].offset < offset) i++;
  // coalesce with previous
  if (i > 0 && fl[i - 1].offset + fl[i - 1].size == offset) {
    fl[i - 1].size += size;
    if (i < n && fl[i - 1].offset + fl[i - 1].size == fl[i].offset) {
      fl[i - 1].size += fl[i].size;
      memmove(&fl[i], &fl[i + 1], (n - i - 1) * sizeof(FreeBlock));
      h->num_free--;
    }
    return 0;
  }
  // coalesce with next
  if (i < n && offset + size == fl[i].offset) {
    fl[i].offset = offset;
    fl[i].size += size;
    return 0;
  }
  if (n >= h->max_free) return -1;  // fragmented beyond free-list capacity
  memmove(&fl[i + 1], &fl[i], (n - i) * sizeof(FreeBlock));
  fl[i].offset = offset;
  fl[i].size = size;
  h->num_free++;
  return 0;
}

// free_insert that records un-recordable blocks instead of silently
// dropping them (free-list overflow under heavy fragmentation).
static void free_or_leak(Header* h, uint64_t offset, uint64_t size) {
  if (free_insert(h, offset, size) != 0) h->leaked_bytes += size;
}

static uint64_t alloc_block(Header* h, uint64_t size) {
  FreeBlock* fl = freelist_of(h);
  for (uint32_t i = 0; i < h->num_free; i++) {
    if (fl[i].size >= size) {
      uint64_t off = fl[i].offset;
      fl[i].offset += size;
      fl[i].size -= size;
      if (fl[i].size == 0) {
        memmove(&fl[i], &fl[i + 1], (h->num_free - i - 1) * sizeof(FreeBlock));
        h->num_free--;
      }
      return off;
    }
  }
  return 0;  // 0 is never a valid data offset (header lives there)
}

// Evict least-recently-used sealed unpinned objects until `needed` bytes can
// be allocated. Returns 1 if progress was made.
static int evict_lru(Header* h, uint64_t needed) {
  int evicted_any = 0;
  for (;;) {
    // check if an allocation of `needed` would now succeed
    FreeBlock* fl = freelist_of(h);
    for (uint32_t i = 0; i < h->num_free; i++)
      if (fl[i].size >= needed) return 1;
    // find LRU victim
    ObjEntry* t = table_of(h);
    ObjEntry* victim = nullptr;
    for (uint32_t i = 0; i < h->table_size; i++) {
      ObjEntry* e = &t[i];
      if (e->state == SEALED && e->pins == 0 &&
          (!victim || e->lru_tick < victim->lru_tick))
        victim = e;
    }
    if (!victim) return evicted_any;
    free_or_leak(h, victim->offset, victim->size);
    h->bytes_in_use -= victim->size;
    h->num_objects--;
    erase_entry(h, victim);
    evicted_any = 1;
  }
}

// ---------------------------------------------------------------------------
// public object API (all lock internally)
// ---------------------------------------------------------------------------

// rc: 0 ok; -1 exists; -2 out of memory; -3 table full.  allow_evict=0 keeps
// LRU eviction out of the allocation path: primary copies must be spilled to
// disk by the raylet (request_spill), never silently dropped — reference
// semantics where the raylet pins primaries and plasma only evicts
// secondary copies (local_object_manager.h).
long long store_create(void* base, const uint8_t* id, uint64_t size,
                       uint64_t meta, int allow_evict) {
  Header* h = static_cast<Header*>(base);
  if (size == 0) size = 1;
  if (lock(h) != 0) return -4;
  ObjEntry* existing = find_entry(h, id, 0);
  if (existing) { unlock(h); return -1; }
  uint64_t off = alloc_block(h, size);
  if (!off && allow_evict) {
    evict_lru(h, size);
    off = alloc_block(h, size);
  }
  if (!off) { unlock(h); return -2; }
  ObjEntry* e = find_entry(h, id, 1);
  if (!e) { free_or_leak(h, off, size); unlock(h); return -3; }
  memcpy(e->id, id, kIdLen);
  e->state = CREATED;
  e->offset = off;
  e->size = size;
  e->meta = meta;
  e->pins = 1;  // creator pin
  e->lru_tick = ++h->lru_clock;
  h->bytes_in_use += size;
  h->num_objects++;
  unlock(h);
  return static_cast<long long>(off);
}

int store_seal(void* base, const uint8_t* id) {
  Header* h = static_cast<Header*>(base);
  if (lock(h) != 0) return -4;
  ObjEntry* e = find_entry(h, id, 0);
  if (!e || e->state != CREATED) { unlock(h); return -1; }
  e->state = SEALED;
  e->pins -= 1;  // drop creator pin
  h->seal_count++;
  unlock(h);
  return 0;
}

// Sealed get: pins the object. out = {offset, size, meta}. rc 0 ok, -1 absent,
// -2 present but unsealed.
int store_get(void* base, const uint8_t* id, uint64_t* out) {
  Header* h = static_cast<Header*>(base);
  if (lock(h) != 0) return -4;
  ObjEntry* e = find_entry(h, id, 0);
  if (!e) { unlock(h); return -1; }
  if (e->state != SEALED) { unlock(h); return -2; }
  e->pins += 1;
  e->lru_tick = ++h->lru_clock;
  out[0] = e->offset;
  out[1] = e->size;
  out[2] = e->meta;
  unlock(h);
  return 0;
}

int store_release(void* base, const uint8_t* id) {
  Header* h = static_cast<Header*>(base);
  if (lock(h) != 0) return -4;
  ObjEntry* e = find_entry(h, id, 0);
  if (!e || e->pins <= 0) { unlock(h); return -1; }
  e->pins -= 1;
  unlock(h);
  return 0;
}

int store_contains(void* base, const uint8_t* id) {
  Header* h = static_cast<Header*>(base);
  if (lock(h) != 0) return -4;
  ObjEntry* e = find_entry(h, id, 0);
  int rc = (e && e->state == SEALED) ? 1 : 0;
  unlock(h);
  return rc;
}

// Delete a sealed object (refuses if pinned). rc 0 ok, -1 absent, -2 pinned.
int store_delete(void* base, const uint8_t* id) {
  Header* h = static_cast<Header*>(base);
  if (lock(h) != 0) return -4;
  ObjEntry* e = find_entry(h, id, 0);
  if (!e) { unlock(h); return -1; }
  if (e->pins > 0) { unlock(h); return -2; }
  free_or_leak(h, e->offset, e->size);
  h->bytes_in_use -= e->size;
  h->num_objects--;
  erase_entry(h, e);
  unlock(h);
  return 0;
}

// Abort an in-progress create (creator died / failed serialization).
int store_abort(void* base, const uint8_t* id) {
  Header* h = static_cast<Header*>(base);
  if (lock(h) != 0) return -4;
  ObjEntry* e = find_entry(h, id, 0);
  if (!e || e->state != CREATED) { unlock(h); return -1; }
  free_or_leak(h, e->offset, e->size);
  h->bytes_in_use -= e->size;
  h->num_objects--;
  erase_entry(h, e);
  unlock(h);
  return 0;
}

uint64_t store_seal_count(void* base) {
  return static_cast<Header*>(base)->seal_count;
}

// Enumerate sealed objects for the spill manager's victim selection
// (reference: LocalObjectManager::SpillObjectsOfSize walks the plasma
// eviction policy's LRU list, local_object_manager.h).  Packs up to
// `max_entries` records of [id (20B) | size u64 | lru_tick u64 | pins i32]
// = 40 bytes each into out_buf, LRU order not guaranteed (caller sorts by
// lru_tick).  Returns the number of entries written.
uint32_t store_list(void* base, uint8_t* out_buf, uint32_t max_entries) {
  Header* h = static_cast<Header*>(base);
  if (lock(h) != 0) return 0;
  ObjEntry* t = table_of(h);
  uint32_t written = 0;
  for (uint32_t i = 0; i < h->table_size && written < max_entries; i++) {
    ObjEntry* e = &t[i];
    if (e->state != SEALED) continue;
    uint8_t* rec = out_buf + written * 40;
    memcpy(rec, e->id, kIdLen);
    memcpy(rec + 20, &e->size, 8);
    memcpy(rec + 28, &e->lru_tick, 8);
    memcpy(rec + 36, &e->pins, 4);
    written++;
  }
  unlock(h);
  return written;
}

void store_stats(void* base, uint64_t* out) {
  Header* h = static_cast<Header*>(base);
  lock(h);
  out[0] = h->capacity;
  out[1] = h->bytes_in_use;
  out[2] = h->num_objects;
  out[3] = h->num_free;
  out[4] = h->leaked_bytes;
  unlock(h);
}

}  // extern "C"
