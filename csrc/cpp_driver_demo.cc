// Demo/e2e check for the C++ user API (cpp_api.h): joins a running
// cluster as a native driver, runs a handful of cpp tasks, verifies
// results, exits 0 on success.  Driven by tests/test_cpp_api.py.
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "cpp_api.h"

using pycodec::PyVal;

static const char* arg_value(int argc, char** argv, const char* flag) {
  for (int j = 1; j + 1 < argc; ++j)
    if (strcmp(argv[j], flag) == 0) return argv[j + 1];
  return nullptr;
}

int main(int argc, char** argv) {
  const char* rh = arg_value(argc, argv, "--raylet-host");
  const char* rp = arg_value(argc, argv, "--raylet-port");
  const char* gh = arg_value(argc, argv, "--gcs-host");
  const char* gp = arg_value(argc, argv, "--gcs-port");
  if (!rh || !rp || !gh || !gp) {
    fprintf(stderr, "usage: cpp_driver_demo --raylet-host H --raylet-port P"
                    " --gcs-host H --gcs-port P\n");
    return 2;
  }
  try {
    ray_tpu_cpp::Driver d(rh, atoi(rp), gh, atoi(gp));
    printf("joined cluster as job %s\n", d.job_id().c_str());

    PyVal sum = d.call("Add", {PyVal::integer(40), PyVal::integer(2)});
    printf("Add(40,2) = %s\n", sum.repr().c_str());
    if (sum.kind != PyVal::INT || sum.i != 42) return 1;

    PyVal fib = d.call("Fib", {PyVal::integer(30)});
    printf("Fib(30) = %s\n", fib.repr().c_str());
    if (fib.kind != PyVal::INT || fib.i != 832040) return 1;

    PyVal cat = d.call("Concat", {PyVal::str("c++ "), PyVal::str("driver")});
    printf("Concat = %s\n", cat.repr().c_str());
    if (cat.kind != PyVal::STR || cat.s != "c++ driver") return 1;

    bool raised = false;
    try {
      d.call("Fail", {PyVal::str("from-cpp-driver")});
    } catch (const ray_tpu_cpp::TaskFailure& e) {
      raised = strstr(e.what(), "from-cpp-driver") != nullptr;
      printf("failure surfaced: %s\n", e.what());
    }
    if (!raised) return 1;

    // actors from the native driver: stateful, ordered.  Fractional CPU
    // so the actor coexists with our held task lease on a 1-CPU node
    PyVal res = PyVal::dict();
    res.set("CPU", PyVal::real(0.25));
    ray_tpu_cpp::ActorClient counter =
        d.actor("Counter", {PyVal::integer(10)}, res);
    for (int j = 0; j < 3; ++j) {
      PyVal n = counter.call("inc", {});
      printf("counter.inc() = %s\n", n.repr().c_str());
      if (n.kind != PyVal::INT || n.i != 11 + j) return 1;
    }
    bool actor_err = false;
    try {
      counter.call("boom", {});
    } catch (const ray_tpu_cpp::TaskFailure& e) {
      actor_err = strstr(e.what(), "counter exploded") != nullptr;
    }
    PyVal total = counter.call("total", {});
    if (!actor_err || total.i != 13) return 1;  // error didn't kill it

    // store-located results: task + actor payloads above the inline
    // threshold come back via the raylet fetch path
    PyVal big = d.call("Blob", {PyVal::integer(500000), PyVal::str("q")});
    printf("Blob(500000) -> %zu bytes\n", big.s.size());
    if (big.kind != PyVal::BYTES || big.s.size() != 500000 ||
        big.s[0] != 'q')
      return 1;
    PyVal apay = counter.call("payload", {PyVal::integer(300000)});
    printf("actor payload -> %zu bytes\n", apay.s.size());
    if (apay.kind != PyVal::BYTES || apay.s.size() != 300000) return 1;
    d.kill_actor(counter);

    printf("CPP_DRIVER_OK\n");
    return 0;
  } catch (const std::exception& e) {
    fprintf(stderr, "cpp driver failed: %s\n", e.what());
    return 1;
  }
}
