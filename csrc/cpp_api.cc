// C++ driver implementation — see cpp_api.h.
#include "cpp_api.h"

#include <fcntl.h>
#include <unistd.h>

#include <cstring>

#include "rpcnet.h"

namespace ray_tpu_cpp {

using pycodec::PyVal;

namespace {

std::string random_bytes(size_t n) {
  std::string out(n, '\0');
  int fd = ::open("/dev/urandom", O_RDONLY);
  if (fd >= 0) {
    ssize_t got = ::read(fd, &out[0], n);
    ::close(fd);
    if ((size_t)got == n) return out;
  }
  for (size_t j = 0; j < n; ++j) out[j] = (char)(rand() & 0xff);
  return out;
}

std::string to_hex(const std::string& b) {
  static const char* digits = "0123456789abcdef";
  std::string out;
  out.reserve(b.size() * 2);
  for (unsigned char c : b) {
    out += digits[c >> 4];
    out += digits[c & 0xf];
  }
  return out;
}

}  // namespace

struct Driver::Impl {
  std::unique_ptr<rpcnet::Conn> gcs;
  std::unique_ptr<rpcnet::Conn> raylet;
  // after a spillback redirect, the raylet that actually granted the
  // lease — return_worker must go THERE or the remote worker leaks
  std::unique_ptr<rpcnet::Conn> granting;
  std::unique_ptr<rpcnet::Conn> worker;
  std::string job_id_hex;
  std::string sched_key;
  std::string lease_id, worker_id;

  rpcnet::Conn* lease_home() {
    return granting ? granting.get() : raylet.get();
  }
};

Driver::Driver(const std::string& raylet_host, int raylet_port,
               const std::string& gcs_host, int gcs_port)
    : impl_(new Impl) {
  impl_->job_id_hex = to_hex(random_bytes(16));
  job_id_ = impl_->job_id_hex;
  impl_->sched_key = impl_->job_id_hex.substr(0, 8) + "|CPU=1|lang=cpp";

  impl_->gcs.reset(rpcnet::Conn::connect(gcs_host, gcs_port));
  PyVal reg = PyVal::dict();
  reg.set("job_id", PyVal::str(impl_->job_id_hex));
  reg.set("entrypoint", PyVal::str("cpp-driver"));
  impl_->gcs->call("register_job", reg, 30.0);

  impl_->raylet.reset(rpcnet::Conn::connect(raylet_host, raylet_port));

  // lease one cpp worker, following spillback redirects like the Python
  // submitter (core_worker._lease_with_spillback, max 3 hops)
  PyVal payload = PyVal::dict();
  payload.set("key", PyVal::str(impl_->sched_key));
  PyVal res = PyVal::dict();
  res.set("CPU", PyVal::integer(1));
  payload.set("resources", std::move(res));
  payload.set("job_id", PyVal::str(impl_->job_id_hex));
  payload.set("env", PyVal::none());
  payload.set("language", PyVal::str("cpp"));

  PyVal grant;
  for (int hop = 0; hop < 3; ++hop) {
    PyVal p = payload;
    p.set("spillback", PyVal::integer(hop));
    grant = impl_->lease_home()->call("lease_worker", p, 60.0);
    const PyVal* retry = grant.get("retry_at");
    if (!retry) break;
    if (retry->items.size() != 2)
      throw TaskFailure("bad retry_at in lease grant");
    impl_->granting.reset(rpcnet::Conn::connect(retry->items[0].s,
                                                (int)retry->items[1].i));
  }
  const PyVal* lease = grant.get("lease_id");
  const PyVal* wid = grant.get("worker_id");
  const PyVal* addr = grant.get("address");
  if (!lease || !wid || !addr || addr->items.size() != 2)
    throw TaskFailure("bad lease grant: " + grant.repr());
  impl_->lease_id = lease->s;
  impl_->worker_id = wid->s;
  impl_->worker.reset(rpcnet::Conn::connect(addr->items[0].s,
                                            (int)addr->items[1].i));
}

Driver::~Driver() {
  if (!impl_) return;
  // return the lease so the worker goes back to the idle pool, then
  // finish the job (GCS reaps any leftover per-job state)
  try {
    if (impl_->lease_home() && !impl_->lease_id.empty()) {
      PyVal p = PyVal::dict();
      p.set("lease_id", PyVal::str(impl_->lease_id));
      p.set("worker_id", PyVal::str(impl_->worker_id));
      p.set("key", PyVal::str(impl_->sched_key));
      impl_->lease_home()->call("return_worker", p, 10.0);
    }
  } catch (...) {
  }
  try {
    if (impl_->gcs) {
      PyVal p = PyVal::dict();
      p.set("job_id", PyVal::str(impl_->job_id_hex));
      impl_->gcs->call("finish_job", p, 10.0);
    }
  } catch (...) {
  }
}

PyVal Driver::call(const std::string& fn_name,
                   const std::vector<PyVal>& args, double timeout_s) {
  // args blob shape = (args_tuple, kwargs_dict), core_worker._serialize_args
  PyVal packed = PyVal::tuple(
      {PyVal::tuple(std::vector<PyVal>(args.begin(), args.end())),
       PyVal::dict()});
  PyVal spec = PyVal::dict();
  spec.set("task_id", PyVal::bytes(random_bytes(16)));
  spec.set("fn_key", PyVal::str("cpp:" + fn_name));
  spec.set("args", PyVal::bytes(pycodec::pickle_dumps(packed)));
  spec.set("num_returns", PyVal::integer(1));
  PyVal owner = PyVal::list();
  owner.items.push_back(PyVal::str("127.0.0.1"));
  owner.items.push_back(PyVal::integer(0));
  spec.set("owner_addr", std::move(owner));
  spec.set("name", PyVal::str("cpp:" + fn_name));

  PyVal reply = impl_->worker->call("push_task", spec, timeout_s);
  const PyVal* results = reply.get("results");
  if (!results || results->items.empty())
    throw TaskFailure("empty task reply");
  const PyVal& one = results->items[0];
  const PyVal* data = one.get("data");
  if (!data || data->kind != PyVal::BYTES)
    throw TaskFailure("non-inline task result");
  int64_t err = 0;
  PyVal value = pycodec::flat_deserialize(data->s, &err);
  if (err) throw TaskFailure("task failed: " + value.repr());
  return value;
}

}  // namespace ray_tpu_cpp
