// C++ driver implementation — see cpp_api.h.
#include "cpp_api.h"

#include <fcntl.h>
#include <unistd.h>

#include <atomic>
#include <cstring>

#include "rpcnet.h"

namespace ray_tpu_cpp {

using pycodec::PyVal;

namespace {

std::string random_bytes(size_t n) {
  std::string out(n, '\0');
  int fd = ::open("/dev/urandom", O_RDONLY);
  if (fd >= 0) {
    ssize_t got = ::read(fd, &out[0], n);
    ::close(fd);
    if ((size_t)got == n) return out;
  }
  for (size_t j = 0; j < n; ++j) out[j] = (char)(rand() & 0xff);
  return out;
}

std::string to_hex(const std::string& b) {
  static const char* digits = "0123456789abcdef";
  std::string out;
  out.reserve(b.size() * 2);
  for (unsigned char c : b) {
    out += digits[c >> 4];
    out += digits[c & 0xf];
  }
  return out;
}

}  // namespace

// shared per-actor-handle state: all copies of an ActorClient draw seqs
// from the same counter on the same stream
struct ActorState {
  std::unique_ptr<rpcnet::Conn> conn;
  std::string stream;
  std::atomic<int64_t> next_seq{0};
  // for fetching store-located (non-inline) results via the raylet;
  // lazily connected, independent of the Driver's lifetime
  std::string raylet_host;
  int raylet_port = 0;
  std::unique_ptr<rpcnet::Conn> fetch_conn;
  std::mutex fetch_lock;
};

namespace {

// resolve one reply slot to the serialized flat bytes: inline "data",
// or a {"location": ...} store object fetched whole via the raylet's
// fetch_object RPC (raylet.py _rpc_fetch_object)
std::string resolve_slot(const PyVal& slot, const std::string& task_id,
                         rpcnet::Conn* raylet, double timeout_s) {
  const PyVal* data = slot.get("data");
  if (data && data->kind == PyVal::BYTES) return data->s;
  const PyVal* loc = slot.get("location");
  if (loc && raylet) {
    std::string oid = task_id;  // ObjectID: task id + BE u32 index 0
    oid.push_back('\0');
    oid.push_back('\0');
    oid.push_back('\0');
    oid.push_back('\0');
    PyVal q = PyVal::dict();
    q.set("object_id", PyVal::bytes(oid));
    PyVal out = raylet->call("fetch_object", q, timeout_s);
    const PyVal* d = out.get("data");
    if (d && d->kind == PyVal::BYTES) return d->s;
    throw TaskFailure("store fetch returned no data");
  }
  throw TaskFailure("unresolvable task result slot: " + slot.repr());
}

}  // namespace

struct Driver::Impl {
  std::unique_ptr<rpcnet::Conn> gcs;
  std::unique_ptr<rpcnet::Conn> raylet;
  // after a spillback redirect, the raylet that actually granted the
  // lease — return_worker must go THERE or the remote worker leaks
  std::unique_ptr<rpcnet::Conn> granting;
  std::unique_ptr<rpcnet::Conn> worker;
  std::string job_id_hex;
  std::string sched_key;
  std::string lease_id, worker_id;
  std::string raylet_host;
  int raylet_port = 0;

  rpcnet::Conn* lease_home() {
    return granting ? granting.get() : raylet.get();
  }
};

Driver::Driver(const std::string& raylet_host, int raylet_port,
               const std::string& gcs_host, int gcs_port)
    : impl_(new Impl) {
  impl_->job_id_hex = to_hex(random_bytes(16));
  job_id_ = impl_->job_id_hex;
  // fractional lease: the driver pins one worker for its whole lifetime,
  // and a full-CPU hold would starve actor placement on a 1-CPU node
  impl_->sched_key = impl_->job_id_hex.substr(0, 8) + "|CPU=0.5|lang=cpp";

  impl_->gcs.reset(rpcnet::Conn::connect(gcs_host, gcs_port));
  PyVal reg = PyVal::dict();
  reg.set("job_id", PyVal::str(impl_->job_id_hex));
  reg.set("entrypoint", PyVal::str("cpp-driver"));
  impl_->gcs->call("register_job", reg, 30.0);

  impl_->raylet.reset(rpcnet::Conn::connect(raylet_host, raylet_port));
  impl_->raylet_host = raylet_host;
  impl_->raylet_port = raylet_port;

  // lease one cpp worker, following spillback redirects like the Python
  // submitter (core_worker._lease_with_spillback, max 3 hops)
  PyVal payload = PyVal::dict();
  payload.set("key", PyVal::str(impl_->sched_key));
  PyVal res = PyVal::dict();
  res.set("CPU", PyVal::real(0.5));
  payload.set("resources", std::move(res));
  payload.set("job_id", PyVal::str(impl_->job_id_hex));
  payload.set("env", PyVal::none());
  payload.set("language", PyVal::str("cpp"));

  PyVal grant;
  for (int hop = 0; hop < 3; ++hop) {
    PyVal p = payload;
    p.set("spillback", PyVal::integer(hop));
    grant = impl_->lease_home()->call("lease_worker", p, 60.0);
    const PyVal* retry = grant.get("retry_at");
    if (!retry) break;
    if (retry->items.size() != 2)
      throw TaskFailure("bad retry_at in lease grant");
    impl_->granting.reset(rpcnet::Conn::connect(retry->items[0].s,
                                                (int)retry->items[1].i));
  }
  const PyVal* lease = grant.get("lease_id");
  const PyVal* wid = grant.get("worker_id");
  const PyVal* addr = grant.get("address");
  if (!lease || !wid || !addr || addr->items.size() != 2)
    throw TaskFailure("bad lease grant: " + grant.repr());
  impl_->lease_id = lease->s;
  impl_->worker_id = wid->s;
  impl_->worker.reset(rpcnet::Conn::connect(addr->items[0].s,
                                            (int)addr->items[1].i));
}

Driver::~Driver() {
  if (!impl_) return;
  // return the lease so the worker goes back to the idle pool, then
  // finish the job (GCS reaps any leftover per-job state)
  try {
    if (impl_->lease_home() && !impl_->lease_id.empty()) {
      PyVal p = PyVal::dict();
      p.set("lease_id", PyVal::str(impl_->lease_id));
      p.set("worker_id", PyVal::str(impl_->worker_id));
      p.set("key", PyVal::str(impl_->sched_key));
      impl_->lease_home()->call("return_worker", p, 10.0);
    }
  } catch (...) {
  }
  try {
    if (impl_->gcs) {
      PyVal p = PyVal::dict();
      p.set("job_id", PyVal::str(impl_->job_id_hex));
      impl_->gcs->call("finish_job", p, 10.0);
    }
  } catch (...) {
  }
}

ActorClient Driver::actor(const std::string& cls_name,
                          const std::vector<PyVal>& args,
                          const PyVal& resources, double timeout_s) {
  std::string aid_bytes = random_bytes(16);
  std::string actor_id_hex = to_hex(aid_bytes);
  // creation spec: the dict worker_main/cpp_worker expect inside
  // register_actor's spec bytes (core_worker.create_actor layout — the
  // spec's actor_id must be the same identity the GCS registers)
  PyVal args_blob = PyVal::tuple(
      {PyVal::tuple(std::vector<PyVal>(args.begin(), args.end())),
       PyVal::dict()});
  PyVal creation = PyVal::dict();
  creation.set("actor_id", PyVal::bytes(aid_bytes));
  creation.set("cls_key", PyVal::str("cpp:" + cls_name));
  creation.set("args", PyVal::bytes(pycodec::pickle_dumps(args_blob)));
  PyVal owner = PyVal::list();
  owner.items.push_back(PyVal::str("127.0.0.1"));
  owner.items.push_back(PyVal::integer(0));
  creation.set("owner_addr", std::move(owner));
  creation.set("max_concurrency", PyVal::none());
  creation.set("concurrency_groups", PyVal::dict());

  PyVal reg = PyVal::dict();
  reg.set("actor_id", PyVal::str(actor_id_hex));
  reg.set("job_id", PyVal::str(impl_->job_id_hex));
  reg.set("spec", PyVal::bytes(pycodec::pickle_dumps(creation)));
  reg.set("resources", resources);
  reg.set("max_restarts", PyVal::integer(0));
  reg.set("language", PyVal::str("cpp"));
  impl_->gcs->call("register_actor", reg, timeout_s);

  // poll the FSM until ALIVE (core_worker._resolve_actor analog)
  for (int tick = 0; tick < (int)(timeout_s / 0.1); ++tick) {
    PyVal q = PyVal::dict();
    q.set("actor_id", PyVal::str(actor_id_hex));
    PyVal info = impl_->gcs->call("get_actor", q, timeout_s);
    const PyVal* state = info.get("state");
    if (state && state->kind == PyVal::STR) {
      if (state->s == "DEAD") {
        const PyVal* cause = info.get("death_cause");
        throw TaskFailure("actor creation failed: " +
                          (cause ? cause->repr() : std::string("?")));
      }
      if (state->s == "ALIVE") {
        const PyVal* addr = info.get("address");
        if (addr && addr->items.size() == 2) {
          auto st = std::make_shared<ActorState>();
          st->conn.reset(rpcnet::Conn::connect(addr->items[0].s,
                                               (int)addr->items[1].i));
          st->stream = to_hex(random_bytes(8));
          st->raylet_host = impl_->raylet_host;
          st->raylet_port = impl_->raylet_port;
          ActorClient a;
          a.state_ = st;
          a.actor_id_ = actor_id_hex;
          return a;
        }
      }
    }
    usleep(100000);
  }
  throw TaskFailure("actor not ALIVE within timeout");
}

void Driver::kill_actor(const ActorClient& a) {
  PyVal p = PyVal::dict();
  p.set("actor_id", PyVal::str(a.actor_id()));
  impl_->gcs->call("kill_actor", p, 10.0);
}

PyVal ActorClient::call(const std::string& method,
                        const std::vector<PyVal>& args, double timeout_s) {
  auto* st = (ActorState*)state_.get();
  if (!st) throw TaskFailure("uninitialized ActorClient");
  PyVal packed = PyVal::tuple(
      {PyVal::tuple(std::vector<PyVal>(args.begin(), args.end())),
       PyVal::dict()});
  PyVal spec = PyVal::dict();
  spec.set("task_id", PyVal::bytes(random_bytes(16)));
  spec.set("actor_id", PyVal::str(actor_id_));
  spec.set("method", PyVal::str(method));
  spec.set("args", PyVal::bytes(pycodec::pickle_dumps(packed)));
  spec.set("num_returns", PyVal::integer(1));
  PyVal owner = PyVal::list();
  owner.items.push_back(PyVal::str("127.0.0.1"));
  owner.items.push_back(PyVal::integer(0));
  spec.set("owner_addr", std::move(owner));
  spec.set("name", PyVal::str(method));
  spec.set("seq", PyVal::integer(st->next_seq++));
  spec.set("stream", PyVal::str(st->stream));

  std::string task_id = spec.get("task_id")->s;
  PyVal reply = st->conn->call("actor_task", spec, timeout_s);
  const PyVal* results = reply.get("results");
  if (!results || results->items.empty())
    throw TaskFailure("empty actor reply");
  rpcnet::Conn* fetcher = nullptr;
  {
    std::lock_guard<std::mutex> g(st->fetch_lock);
    if (!st->fetch_conn && st->raylet_port)
      st->fetch_conn.reset(
          rpcnet::Conn::connect(st->raylet_host, st->raylet_port));
    fetcher = st->fetch_conn.get();
  }
  std::string flat =
      resolve_slot(results->items[0], task_id, fetcher, timeout_s);
  int64_t err = 0;
  PyVal value = pycodec::flat_deserialize(flat, &err);
  if (err) throw TaskFailure("actor call failed: " + value.repr());
  return value;
}

PyVal Driver::call(const std::string& fn_name,
                   const std::vector<PyVal>& args, double timeout_s) {
  // args blob shape = (args_tuple, kwargs_dict), core_worker._serialize_args
  PyVal packed = PyVal::tuple(
      {PyVal::tuple(std::vector<PyVal>(args.begin(), args.end())),
       PyVal::dict()});
  PyVal spec = PyVal::dict();
  spec.set("task_id", PyVal::bytes(random_bytes(16)));
  spec.set("fn_key", PyVal::str("cpp:" + fn_name));
  spec.set("args", PyVal::bytes(pycodec::pickle_dumps(packed)));
  spec.set("num_returns", PyVal::integer(1));
  PyVal owner = PyVal::list();
  owner.items.push_back(PyVal::str("127.0.0.1"));
  owner.items.push_back(PyVal::integer(0));
  spec.set("owner_addr", std::move(owner));
  spec.set("name", PyVal::str("cpp:" + fn_name));

  std::string task_id = spec.get("task_id")->s;
  PyVal reply = impl_->worker->call("push_task", spec, timeout_s);
  const PyVal* results = reply.get("results");
  if (!results || results->items.empty())
    throw TaskFailure("empty task reply");
  std::string flat = resolve_slot(results->items[0], task_id,
                                  impl_->raylet.get(), timeout_s);
  int64_t err = 0;
  PyVal value = pycodec::flat_deserialize(flat, &err);
  if (err) throw TaskFailure("task failed: " + value.repr());
  return value;
}

}  // namespace ray_tpu_cpp
