// rpcnet: C++ side of the control-plane RPC protocol.
//
// Wire-compatible with ray_tpu/_private/rpc.py — framed pickled 4-tuples
// (kind, msg_id, a, b) over TCP, full duplex: either side can issue
// requests; responses are matched by msg_id.  Frame layout (see
// docs/rpc_fastpath.md; kind/msg_id are duplicated in the header so the
// Python reader can route out-of-band buffers to a registered sink
// before unpickling — docs/object_transfer.md):
//   u32 pickle_len | u32 nbufs | u8 kind | u64 msg_id
//   | nbufs * u64 buf_len | pickle | bufs
// The C++ side always sends nbufs == 0 (pycodec pickles everything in
// band); inbound out-of-band buffers (protocol-5 numpy payloads) are not
// representable in pycodec, so such frames drop the connection — they
// never occur on cpp-bound traffic (task specs carry plain bytes).
// Used by the C++ worker runtime (cpp_worker.cc) and the C++ user API
// (the analog of the reference's cpp/ tree), with pycodec pickling.
//
// Concurrency model mirrors the Python layer: one reader thread per
// connection, each inbound request handled on its own thread (an owner
// pipelines task pushes on one connection; handling inline would
// head-of-line-block them), writes serialized by a mutex.
#pragma once

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <memory>
#include <cstring>
#include <functional>
#include <mutex>
#include <stdexcept>
#include <string>
#include <thread>
#include <unordered_map>

#include "pycodec.h"

namespace rpcnet {

using pycodec::PyVal;

struct RpcError : std::runtime_error {
  explicit RpcError(const std::string& m) : std::runtime_error(m) {}
};
struct RemoteError : RpcError {
  explicit RemoteError(const std::string& m) : RpcError(m) {}
};

namespace detail {
inline void send_all(int fd, const char* p, size_t n, std::mutex& wlock) {
  std::lock_guard<std::mutex> g(wlock);
  while (n) {
    ssize_t k = ::send(fd, p, n, MSG_NOSIGNAL);
    if (k <= 0) throw RpcError("send failed");
    p += k;
    n -= (size_t)k;
  }
}
inline bool recv_all(int fd, char* p, size_t n) {
  while (n) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k <= 0) return false;
    p += k;
    n -= (size_t)k;
  }
  return true;
}
}  // namespace detail

class Conn {
 public:
  // handler(method, payload) -> reply value; throw to send an error reply
  using Handler = std::function<PyVal(const std::string&, const PyVal&)>;
  using CloseFn = std::function<void()>;

  Conn(int fd, Handler handler = nullptr, CloseFn on_close = nullptr)
      : fd_(fd), handler_(std::move(handler)),
        on_close_(std::move(on_close)) {
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    reader_ = std::thread([this] { read_loop(); });
  }

  static Conn* connect(const std::string& host, int port,
                       Handler handler = nullptr,
                       CloseFn on_close = nullptr) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) throw RpcError("socket() failed");
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
      ::close(fd);
      throw RpcError("bad address " + host);
    }
    if (::connect(fd, (sockaddr*)&addr, sizeof addr) != 0) {
      ::close(fd);
      throw RpcError("connect to " + host + " failed");
    }
    return new Conn(fd, std::move(handler), std::move(on_close));
  }

  ~Conn() {
    close();
    if (reader_.joinable()) reader_.join();
  }

  PyVal call(const std::string& method, const PyVal& payload,
             double timeout_s = 60.0) {
    int64_t id = next_id_++;
    auto slot = std::make_shared<Slot>();
    {
      std::lock_guard<std::mutex> g(inflight_lock_);
      if (closed_) throw RpcError("connection closed");
      inflight_[id] = slot;
    }
    PyVal frame = PyVal::tuple(
        {PyVal::integer(0), PyVal::integer(id), PyVal::str(method),
         payload});
    send_frame(frame);
    std::unique_lock<std::mutex> lk(slot->m);
    if (!slot->cv.wait_for(lk, std::chrono::duration<double>(timeout_s),
                           [&] { return slot->done; })) {
      std::lock_guard<std::mutex> g(inflight_lock_);
      inflight_.erase(id);
      throw RpcError("rpc timeout: " + method);
    }
    if (!slot->ok) throw RemoteError(slot->err);
    return std::move(slot->value);
  }

  void push(const std::string& method, const PyVal& payload) {
    send_frame(PyVal::tuple({PyVal::integer(2), PyVal::integer(0),
                             PyVal::str(method), payload}));
  }

  void close() {
    bool was = closed_.exchange(true);
    if (!was) {
      ::shutdown(fd_, SHUT_RDWR);
      ::close(fd_);
      fail_inflight("connection closed");
    }
  }
  bool closed() const { return closed_; }

 private:
  struct Slot {
    std::mutex m;
    std::condition_variable cv;
    bool done = false, ok = false;
    PyVal value;
    std::string err;
  };

  static const size_t kHdrSize = 17;  // <IIBQ>, packed little-endian

  void send_frame(const PyVal& frame) {
    std::string data = pycodec::pickle_dumps(frame);
    char hdr[kHdrSize];
    uint32_t n = (uint32_t)data.size();
    uint64_t id = (uint64_t)frame.items[1].i;
    for (int j = 0; j < 4; ++j) hdr[j] = (char)(n >> (8 * j));
    for (int j = 4; j < 8; ++j) hdr[j] = 0;  // nbufs == 0: all in band
    hdr[8] = (char)frame.items[0].i;         // kind
    for (int j = 0; j < 8; ++j) hdr[9 + j] = (char)(id >> (8 * j));
    std::string buf(hdr, kHdrSize);
    buf += data;
    try {
      detail::send_all(fd_, buf.data(), buf.size(), wlock_);
    } catch (...) {
      close();
      throw;
    }
  }

  static uint32_t le32(const char* p) {
    return (uint32_t)(unsigned char)p[0] |
           (uint32_t)(unsigned char)p[1] << 8 |
           (uint32_t)(unsigned char)p[2] << 16 |
           (uint32_t)(unsigned char)p[3] << 24;
  }

  void read_loop() {
    for (;;) {
      char hdr[kHdrSize];
      if (!detail::recv_all(fd_, hdr, kHdrSize)) break;
      uint32_t n = le32(hdr);
      uint32_t nbufs = le32(hdr + 4);
      // hdr[8] (kind) and hdr[9..16] (msg_id) duplicate the pickled
      // tuple; the C++ side has no buffer sinks, so routing still uses
      // the tuple below
      if (n > (1u << 30) || nbufs > 0) {
        // out-of-band buffers are unrepresentable in pycodec (and never
        // sent on cpp-bound traffic); oversized headers mean a protocol
        // mismatch — drop the connection either way
        break;
      }
      std::string data(n, '\0');
      if (!detail::recv_all(fd_, &data[0], n)) break;
      PyVal frame;
      try {
        frame = pycodec::pickle_loads(data);
      } catch (const std::exception&) {
        break;  // protocol garbage: drop the connection
      }
      if (frame.kind != PyVal::TUPLE || frame.items.size() != 4) break;
      int64_t kind = frame.items[0].i;
      int64_t id = frame.items[1].i;
      if (kind == 0) {  // REQUEST
        std::string method =
            frame.items[2].kind == PyVal::STR ? frame.items[2].s : "";
        PyVal payload = std::move(frame.items[3]);
        std::thread([this, id, method, payload]() {
          handle_request(id, method, payload);
        }).detach();
      } else if (kind == 1) {  // RESPONSE
        std::shared_ptr<Slot> slot;
        {
          std::lock_guard<std::mutex> g(inflight_lock_);
          auto it = inflight_.find(id);
          if (it != inflight_.end()) {
            slot = it->second;
            inflight_.erase(it);
          }
        }
        if (slot) {
          std::lock_guard<std::mutex> lk(slot->m);
          slot->ok = frame.items[2].truthy();
          if (slot->ok)
            slot->value = std::move(frame.items[3]);
          else
            slot->err = frame.items[3].repr();
          slot->done = true;
          slot->cv.notify_all();
        }
      }
      // kind == 2 (PUSH): fire-and-forget notifications are not consumed
      // by C++ components yet; drop them
    }
    closed_ = true;
    fail_inflight("connection lost");
    if (on_close_) on_close_();
  }

  void handle_request(int64_t id, const std::string& method,
                      const PyVal& payload) {
    PyVal ok = PyVal::boolean(true);
    PyVal out;
    try {
      if (!handler_) throw RpcError("no handler");
      out = handler_(method, payload);
    } catch (const std::exception& e) {
      ok = PyVal::boolean(false);
      // the Python side pickles exception objects; we can only send a
      // string — rpc.RemoteError(repr(cause)) renders it faithfully.
      // Sanitized: a non-UTF-8 what() would make send_frame throw and
      // the reply would be silently dropped (caller hangs to timeout).
      out = PyVal::str(pycodec::sanitize_utf8(std::string(e.what())));
    }
    try {
      send_frame(PyVal::tuple(
          {PyVal::integer(1), PyVal::integer(id), ok, out}));
    } catch (...) {
      // peer gone; reader loop will notice
    }
  }

  void fail_inflight(const std::string& why) {
    std::unordered_map<int64_t, std::shared_ptr<Slot>> victims;
    {
      std::lock_guard<std::mutex> g(inflight_lock_);
      victims.swap(inflight_);
    }
    for (auto& kv : victims) {
      std::lock_guard<std::mutex> lk(kv.second->m);
      kv.second->ok = false;
      kv.second->err = why;
      kv.second->done = true;
      kv.second->cv.notify_all();
    }
  }

  int fd_;
  Handler handler_;
  CloseFn on_close_;
  std::mutex wlock_;
  std::atomic<int64_t> next_id_{1};
  std::mutex inflight_lock_;
  std::unordered_map<int64_t, std::shared_ptr<Slot>> inflight_;
  std::atomic<bool> closed_{false};
  std::thread reader_;
};

// Minimal listening server: accept loop, one Conn per client.
class Server {
 public:
  explicit Server(Conn::Handler handler, int port = 0)
      : handler_(std::move(handler)) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) throw RpcError("socket() failed");
    int one = 1;
    setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    // all interfaces: remote owners push tasks straight to workers, so a
    // loopback-only bind would strand cross-node actors/leases
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons((uint16_t)port);
    if (::bind(fd_, (sockaddr*)&addr, sizeof addr) != 0 ||
        ::listen(fd_, 128) != 0)
      throw RpcError("bind/listen failed");
    socklen_t len = sizeof addr;
    getsockname(fd_, (sockaddr*)&addr, &len);
    port_ = ntohs(addr.sin_port);
    acceptor_ = std::thread([this] { accept_loop(); });
  }

  int port() const { return port_; }

  ~Server() {
    ::shutdown(fd_, SHUT_RDWR);
    ::close(fd_);
    if (acceptor_.joinable()) acceptor_.join();
  }

 private:
  void accept_loop() {
    for (;;) {
      int cfd = ::accept(fd_, nullptr, nullptr);
      if (cfd < 0) return;
      // conns live until process exit (workers are short-lived processes;
      // a real teardown story belongs to the embedding runtime)
      new Conn(cfd, handler_);
    }
  }

  Conn::Handler handler_;
  int fd_ = -1;
  int port_ = 0;
  std::thread acceptor_;
};

}  // namespace rpcnet
