// The C++ task SDK surface: what user code includes to write cpp tasks.
//
// Analog of the reference's task registration macros
// (/root/reference/cpp/include/ray/api.h RAY_REMOTE): a function takes
// decoded PyVal args and returns a PyVal; RAY_TPU_CPP_FUNCTION registers
// it under a name callable from Python
// (cross_language.cpp_function("Name")) and from the C++ driver API.
// Users build their own worker binary by linking cpp_worker.cc with
// translation units that use this macro.
#pragma once

#include <functional>
#include <string>
#include <vector>

#include "pycodec.h"

namespace ray_tpu_cpp {

using TaskFn =
    std::function<pycodec::PyVal(const std::vector<pycodec::PyVal>&)>;

void register_function(const std::string& name, TaskFn fn);

// Built-in demo/test functions compiled into the stock cpp_worker
// (tests/test_cpp_api.py drives them end-to-end).
void register_builtin_functions();

struct Registrar {
  Registrar(const std::string& name, TaskFn fn) {
    register_function(name, std::move(fn));
  }
};

}  // namespace ray_tpu_cpp

#define RAY_TPU_CPP_FUNCTION(name, fn) \
  static ::ray_tpu_cpp::Registrar _ray_tpu_reg_##name(#name, fn)
