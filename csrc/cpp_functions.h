// The C++ task SDK surface: what user code includes to write cpp tasks.
//
// Analog of the reference's task registration macros
// (/root/reference/cpp/include/ray/api.h RAY_REMOTE): a function takes
// decoded PyVal args and returns a PyVal; RAY_TPU_CPP_FUNCTION registers
// it under a name callable from Python
// (cross_language.cpp_function("Name")) and from the C++ driver API.
// Users build their own worker binary by linking cpp_worker.cc with
// translation units that use this macro.
#pragma once

#include <functional>
#include <string>
#include <vector>

#include "pycodec.h"

#include <memory>

namespace ray_tpu_cpp {

using TaskFn =
    std::function<pycodec::PyVal(const std::vector<pycodec::PyVal>&)>;

void register_function(const std::string& name, TaskFn fn);

// A C++ actor: constructed once by its factory, then receives method
// calls in strict per-caller submission order (the actor queue
// guarantee).  Throwing from call() fails that task only, not the actor.
struct CppActor {
  virtual ~CppActor() = default;
  virtual pycodec::PyVal call(const std::string& method,
                              const std::vector<pycodec::PyVal>& args) = 0;
};

using ActorFactory = std::function<std::unique_ptr<CppActor>(
    const std::vector<pycodec::PyVal>&)>;

void register_actor_class(const std::string& name, ActorFactory factory);

// Built-in demo/test functions + actor classes compiled into the stock
// cpp_worker (tests/test_cpp_api.py drives them end-to-end).
void register_builtin_functions();

struct Registrar {
  Registrar(const std::string& name, TaskFn fn) {
    register_function(name, std::move(fn));
  }
};
struct ActorRegistrar {
  ActorRegistrar(const std::string& name, ActorFactory f) {
    register_actor_class(name, std::move(f));
  }
};

}  // namespace ray_tpu_cpp

#define RAY_TPU_CPP_FUNCTION(name, fn) \
  static ::ray_tpu_cpp::Registrar _ray_tpu_reg_##name(#name, fn)
#define RAY_TPU_CPP_ACTOR(name, factory) \
  static ::ray_tpu_cpp::ActorRegistrar _ray_tpu_areg_##name(#name, factory)
