// pycodec: a pickle/msgpack codec for the control-plane wire format.
//
// The framework's RPC layer frames length-prefixed pickled tuples
// (ray_tpu/_private/rpc.py), and object payloads use
// [u32 meta_len][msgpack meta][pickle payload] (_private/serialization.py).
// C++ components (the cpp worker runtime and the C++ user API — the analog
// of the reference's cpp/ tree, /root/reference/cpp/include/ray/api.h) need
// to speak both.  This codec covers the closed value set the control plane
// actually uses: None/bool/int/float/str/bytes/list/tuple/dict, plus an
// OPAQUE node for anything else (class refs, reduces) so error payloads can
// still be surfaced without a Python interpreter.
//
// Not a general unpickler by design: no framework object reconstruction,
// no extension registry, no cycles (the control plane never sends them).
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

namespace pycodec {

struct PyVal;
using PyValPtr = std::shared_ptr<PyVal>;

struct PyVal {
  enum Kind { NONE, BOOL, INT, FLOAT, STR, BYTES, LIST, TUPLE, DICT, OPAQUE };
  Kind kind = NONE;
  bool b = false;
  int64_t i = 0;
  double f = 0.0;
  std::string s;  // STR (utf-8) / BYTES; OPAQUE: "module.qualname"
  std::vector<PyVal> items;                      // LIST/TUPLE; OPAQUE: args
  std::vector<std::pair<PyVal, PyVal>> map;      // DICT

  static PyVal none() { return PyVal{}; }
  static PyVal boolean(bool v) { PyVal x; x.kind = BOOL; x.b = v; return x; }
  static PyVal integer(int64_t v) { PyVal x; x.kind = INT; x.i = v; return x; }
  static PyVal real(double v) { PyVal x; x.kind = FLOAT; x.f = v; return x; }
  static PyVal str(std::string v) {
    PyVal x; x.kind = STR; x.s = std::move(v); return x;
  }
  static PyVal bytes(std::string v) {
    PyVal x; x.kind = BYTES; x.s = std::move(v); return x;
  }
  static PyVal list(std::vector<PyVal> v = {}) {
    PyVal x; x.kind = LIST; x.items = std::move(v); return x;
  }
  static PyVal tuple(std::vector<PyVal> v = {}) {
    PyVal x; x.kind = TUPLE; x.items = std::move(v); return x;
  }
  static PyVal dict() { PyVal x; x.kind = DICT; return x; }

  void set(const std::string& key, PyVal value) {
    map.emplace_back(PyVal::str(key), std::move(value));
  }
  // dict lookup by string key; nullptr when absent
  const PyVal* get(const std::string& key) const {
    for (const auto& kv : map)
      if (kv.first.kind == STR && kv.first.s == key) return &kv.second;
    return nullptr;
  }
  bool truthy() const {
    switch (kind) {
      case NONE: return false;
      case BOOL: return b;
      case INT: return i != 0;
      case FLOAT: return f != 0.0;
      case STR: case BYTES: return !s.empty();
      case LIST: case TUPLE: return !items.empty();
      case DICT: return !map.empty();
      default: return true;
    }
  }
  // Pythonic repr for diagnostics/tests
  std::string repr() const;
};

struct CodecError : std::runtime_error {
  explicit CodecError(const std::string& m) : std::runtime_error(m) {}
};

// pickle.loads: accepts protocol 2..5 streams over the supported value set.
PyVal pickle_loads(const std::string& data);
// pickle.dumps(protocol=3): loadable by any Python 3.
std::string pickle_dumps(const PyVal& v);

// Object-payload flat format (serialization.py serialize/to_flat_bytes)
// with zero out-of-band buffers: [u32 meta_len][msgpack meta][payload].
std::string flat_serialize(const PyVal& v, int64_t error_type = 0);

// Replace invalid UTF-8 byte sequences with U+FFFD so the result always
// encodes as a pickle str.  Error paths MUST route messages through this
// (encoding a str raises CodecError on invalid UTF-8; an error path that
// itself throws would escape the executor and kill the worker).
std::string sanitize_utf8(const std::string& s);
// Inverse for inline results; throws CodecError if the payload carries
// out-of-band buffers (numpy et al. — not a C++-side value).
PyVal flat_deserialize(const std::string& data, int64_t* error_type);

}  // namespace pycodec
