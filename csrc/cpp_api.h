// The C++ user API: a native driver for the cluster.
//
// Analog of the reference's C++ API (/root/reference/cpp/include/ray/api.h
// ray::Init/Task(...).Remote(...).Get()): connect to a running cluster's
// raylet + GCS, lease C++ workers through the same lease protocol Python
// drivers use (core_worker._lease_with_spillback), push tasks, and read
// inline results.  pycodec::PyVal is the value currency on both sides.
//
//   ray_tpu_cpp::Driver d("127.0.0.1", raylet_port, "127.0.0.1", gcs_port);
//   PyVal out = d.call("Add", {PyVal::integer(1), PyVal::integer(2)});
//
// v1 scope matches the cpp worker: primitive by-value args/results,
// inline replies, no actors.  The driver keeps one leased worker per
// Driver object (serial dispatch) and returns it on destruction — the
// fan-out story belongs to the Python driver; this API is the
// "C++ program participates in the cluster" surface.
#pragma once

#include <memory>
#include <string>
#include <vector>

#include "pycodec.h"

namespace ray_tpu_cpp {

struct TaskFailure : std::runtime_error {
  explicit TaskFailure(const std::string& m) : std::runtime_error(m) {}
};

class Driver;

// Client handle on a C++ actor created by this driver.  Calls execute in
// submission order (the worker's seq-ordered actor queue).  Destroying
// the handle does NOT kill the actor; use Driver::kill_actor.
class ActorClient {
 public:
  pycodec::PyVal call(const std::string& method,
                      const std::vector<pycodec::PyVal>& args,
                      double timeout_s = 60.0);
  const std::string& actor_id() const { return actor_id_; }

 private:
  friend class Driver;
  ActorClient() = default;
  // conn + stream + seq live in ONE shared state so copies of a handle
  // keep drawing from the same sequence (colliding seqs would wedge the
  // worker's in-order queue); type-erased to keep rpcnet out of the header
  std::shared_ptr<void> state_;
  std::string actor_id_;
};

class Driver {
 public:
  Driver(const std::string& raylet_host, int raylet_port,
         const std::string& gcs_host, int gcs_port);
  ~Driver();

  // submit fn_name(args) to a leased cpp worker and wait for the result
  pycodec::PyVal call(const std::string& fn_name,
                      const std::vector<pycodec::PyVal>& args,
                      double timeout_s = 60.0);

  // create a C++ actor (RAY_TPU_CPP_ACTOR-registered class) and wait
  // until it is ALIVE; the GCS schedules it like any Python-created
  // actor.  resources defaults to {"CPU": 1} raylet-side; pass
  // fractional CPU to co-locate with held task leases on small nodes
  ActorClient actor(const std::string& cls_name,
                    const std::vector<pycodec::PyVal>& args,
                    const pycodec::PyVal& resources = pycodec::PyVal::dict(),
                    double timeout_s = 60.0);
  void kill_actor(const ActorClient& a);

  const std::string& job_id() const { return job_id_; }

 private:
  struct Impl;
  std::unique_ptr<Impl> impl_;
  std::string job_id_;
};

}  // namespace ray_tpu_cpp
