// cpp_worker: the C++ task-execution runtime.
//
// Analog of the reference's C++ worker half (/root/reference/cpp/ —
// api.h TaskExecutor + worker main): a worker process the raylet spawns
// for leases whose scheduling key carries language=cpp.  It speaks the
// same worker protocol as ray_tpu/runtime/worker_main.py — register with
// the raylet over a duplex RPC connection (fate-sharing on disconnect),
// serve push_task from owners, execute a registered C++ function, and
// reply with inline results in the serialization.py flat format.
//
// Functions are registered in a static registry by name; drivers invoke
// them via ray_tpu.cross_language.cpp_function("Name").remote(...)
// (the reference's cross_language.py:15 java_function analog) or from
// C++ via the user API in cpp_api.h.  v1 scope: by-value primitive
// args/results (no ObjectRef args, no actors, no dynamic returns).
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <string>

#include "cpp_functions.h"
#include "pycodec.h"
#include "rpcnet.h"

using pycodec::PyVal;

namespace {

std::map<std::string, ray_tpu_cpp::TaskFn>& registry() {
  static std::map<std::string, ray_tpu_cpp::TaskFn> r;
  return r;
}

// serialized-format helpers -------------------------------------------------

std::string make_error_payload(const std::string& task_name,
                               const std::string& message) {
  // a real ray_tpu.exceptions.TaskError(function_name, cause, tb) the
  // Python owner deserializes and raises unchanged
  PyVal cause;
  cause.kind = PyVal::OPAQUE;
  cause.s = "builtins.RuntimeError";
  cause.items.push_back(PyVal::str(message));
  PyVal err;
  err.kind = PyVal::OPAQUE;
  err.s = "ray_tpu.exceptions.TaskError";
  err.items.push_back(PyVal::str(task_name));
  err.items.push_back(std::move(cause));
  err.items.push_back(PyVal::str("(cpp worker)"));
  return pycodec::flat_serialize(err, /*error_type=ERROR_TASK*/ 1);
}

PyVal error_reply(const PyVal& spec, const std::string& message) {
  const PyVal* name = spec.get("name");
  const PyVal* nret = spec.get("num_returns");
  int64_t slots = 1;
  if (nret && nret->kind == PyVal::INT && nret->i > 1) slots = nret->i;
  std::string payload = make_error_payload(
      name && name->kind == PyVal::STR ? name->s : "cpp-task", message);
  PyVal results = PyVal::list();
  for (int64_t j = 0; j < slots; ++j) {
    PyVal one = PyVal::dict();
    one.set("data", PyVal::bytes(payload));
    one.set("error", PyVal::integer(1));
    results.items.push_back(std::move(one));
  }
  PyVal reply = PyVal::dict();
  reply.set("results", std::move(results));
  return reply;
}

PyVal execute_task(const PyVal& spec) {
  const PyVal* fn_key = spec.get("fn_key");
  if (!fn_key || fn_key->kind != PyVal::STR ||
      fn_key->s.rfind("cpp:", 0) != 0)
    return error_reply(spec, "cpp worker received a non-cpp fn_key");
  std::string name = fn_key->s.substr(4);
  auto it = registry().find(name);
  if (it == registry().end())
    return error_reply(spec, "no cpp function registered as '" + name +
                                 "' in this worker binary");
  const PyVal* blob = spec.get("args");
  if (!blob || blob->kind != PyVal::BYTES)
    return error_reply(spec, "missing args blob");
  PyVal packed;
  try {
    packed = pycodec::pickle_loads(blob->s);
  } catch (const std::exception& e) {
    return error_reply(spec, std::string("args not decodable C++-side "
                                         "(ObjectRef/numpy args are not "
                                         "supported by cpp tasks): ") +
                                 e.what());
  }
  // args blob = (args_tuple, kwargs_dict) — core_worker._serialize_args
  if (packed.kind != PyVal::TUPLE || packed.items.size() != 2)
    return error_reply(spec, "bad args blob shape");
  if (!packed.items[1].map.empty())
    return error_reply(spec, "cpp tasks take positional args only");
  std::vector<PyVal> args = std::move(packed.items[0].items);

  PyVal value;
  try {
    value = it->second(args);
  } catch (const std::exception& e) {
    return error_reply(spec, e.what());
  }

  const PyVal* nret = spec.get("num_returns");
  int64_t n = nret && nret->kind == PyVal::INT ? nret->i : 1;
  if (nret && nret->kind == PyVal::STR)
    return error_reply(spec, "num_returns='dynamic' unsupported for cpp");
  std::vector<PyVal> values;
  if (n == 1) {
    values.push_back(std::move(value));
  } else if (n == 0) {
    // nothing
  } else {
    if (value.kind != PyVal::TUPLE && value.kind != PyVal::LIST)
      return error_reply(spec, "task declared multiple returns but the "
                               "cpp function returned a scalar");
    if ((int64_t)value.items.size() != n)
      return error_reply(spec, "return count mismatch");
    values = std::move(value.items);
  }
  PyVal results = PyVal::list();
  for (auto& v : values) {
    PyVal one = PyVal::dict();
    try {
      one.set("data", PyVal::bytes(pycodec::flat_serialize(v)));
    } catch (const std::exception& e) {
      return error_reply(spec, std::string("unserializable result: ") +
                                   e.what());
    }
    results.items.push_back(std::move(one));
  }
  PyVal reply = PyVal::dict();
  reply.set("results", std::move(results));
  return reply;
}

// serial executor: the owner's retry accounting assumes this worker
// drains its FIFO one task at a time (core_worker._lease_worker_loop)
struct Executor {
  std::mutex m;
  std::condition_variable cv;
  std::deque<std::tuple<PyVal, PyVal*, std::condition_variable*, bool*>> q;

  PyVal run(const PyVal& spec) {
    PyVal out;
    std::condition_variable done_cv;
    bool done = false;
    {
      std::lock_guard<std::mutex> g(m);
      q.emplace_back(spec, &out, &done_cv, &done);
      cv.notify_one();
    }
    std::unique_lock<std::mutex> lk(m);
    done_cv.wait(lk, [&] { return done; });
    return out;
  }

  void loop() {
    for (;;) {
      std::tuple<PyVal, PyVal*, std::condition_variable*, bool*> item;
      {
        std::unique_lock<std::mutex> lk(m);
        cv.wait(lk, [&] { return !q.empty(); });
        item = std::move(q.front());
        q.pop_front();
      }
      PyVal out = execute_task(std::get<0>(item));
      {
        std::lock_guard<std::mutex> g(m);
        *std::get<1>(item) = std::move(out);
        *std::get<3>(item) = true;
        std::get<2>(item)->notify_all();
      }
    }
  }
};

Executor g_exec;

PyVal dispatch(const std::string& method, const PyVal& payload) {
  if (method == "push_task") return g_exec.run(payload);
  if (method == "kill") _exit(1);
  if (method == "ping") return PyVal::dict();
  if (method == "profile") {
    PyVal out = PyVal::dict();
    out.set("folded", PyVal::str("cpp_worker;native 1"));
    return out;
  }
  throw rpcnet::RpcError("cpp worker: unsupported method " + method);
}

const char* arg_value(int argc, char** argv, const char* flag) {
  for (int j = 1; j + 1 < argc; ++j)
    if (strcmp(argv[j], flag) == 0) return argv[j + 1];
  return nullptr;
}

}  // namespace

namespace ray_tpu_cpp {
void register_function(const std::string& name, TaskFn fn) {
  registry()[name] = std::move(fn);
}
}  // namespace ray_tpu_cpp

int main(int argc, char** argv) {
  const char* raylet_host = arg_value(argc, argv, "--raylet-host");
  const char* raylet_port = arg_value(argc, argv, "--raylet-port");
  const char* worker_id = arg_value(argc, argv, "--worker-id");
  if (!raylet_host || !raylet_port || !worker_id) {
    fprintf(stderr, "usage: cpp_worker --raylet-host H --raylet-port P "
                    "--worker-id ID [ignored worker_main flags]\n");
    return 2;
  }
  ray_tpu_cpp::register_builtin_functions();

  std::thread exec([&] { g_exec.loop(); });
  exec.detach();

  rpcnet::Server server(dispatch);

  // fate-share with the raylet exactly like worker_main.py:_raylet_gone
  rpcnet::Conn* raylet = rpcnet::Conn::connect(
      raylet_host, atoi(raylet_port), dispatch, [] {
        fprintf(stderr, "raylet connection lost; cpp worker exiting\n");
        _exit(1);
      });

  PyVal reg = PyVal::dict();
  reg.set("worker_id", PyVal::str(worker_id));
  PyVal addr = PyVal::list();
  addr.items.push_back(PyVal::str("127.0.0.1"));
  addr.items.push_back(PyVal::integer(server.port()));
  reg.set("address", std::move(addr));
  try {
    raylet->call("register_worker", reg, 30.0);
  } catch (const std::exception& e) {
    fprintf(stderr, "register_worker failed: %s\n", e.what());
    return 1;
  }
  fprintf(stderr, "cpp worker %s serving on port %d\n", worker_id,
          server.port());
  for (;;) pause();
}
