// cpp_worker: the C++ task-execution runtime.
//
// Analog of the reference's C++ worker half (/root/reference/cpp/ —
// api.h TaskExecutor + worker main): a worker process the raylet spawns
// for leases whose scheduling key carries language=cpp.  It speaks the
// same worker protocol as ray_tpu/runtime/worker_main.py — register with
// the raylet over a duplex RPC connection (fate-sharing on disconnect),
// serve push_task from owners, execute a registered C++ function, and
// reply with inline results in the serialization.py flat format.
//
// Functions are registered in a static registry by name; drivers invoke
// them via ray_tpu.cross_language.cpp_function("Name").remote(...)
// (the reference's cross_language.py:15 java_function analog) or from
// C++ via the user API in cpp_api.h.  v1 scope: by-value primitive
// args/results (no ObjectRef args, no actors, no dynamic returns).
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <string>

#include "cpp_functions.h"
#include "cpp_store.h"
#include "pycodec.h"
#include "rpcnet.h"

using pycodec::PyVal;

namespace {

std::map<std::string, ray_tpu_cpp::TaskFn>& registry() {
  static std::map<std::string, ray_tpu_cpp::TaskFn> r;
  return r;
}

std::map<std::string, ray_tpu_cpp::ActorFactory>& actor_registry() {
  static std::map<std::string, ray_tpu_cpp::ActorFactory> r;
  return r;
}

// actor state of this (dedicated) worker process
std::unique_ptr<ray_tpu_cpp::CppActor> g_actor;
std::string g_actor_id;
std::string g_gcs_host;
int g_gcs_port = 0;
// this node's cluster-visible host: the raylet we registered with lives
// on this machine, so its advertised host is ours too (worker_main's
// core.address analog — never loopback, or cross-node owners can't push)
std::string g_self_host = "127.0.0.1";
// local shm store for large results ({"location": node_id} replies)
ray_tpu_cpp::ShmStoreClient g_store;
std::string g_node_id_hex;

int64_t inline_max_bytes() {
  // default matches CONFIG.inline_object_max_bytes; the env var is the
  // standard flag-override channel (RAY_TPU_<NAME>)
  static int64_t v = [] {
    const char* e = getenv("RAY_TPU_INLINE_OBJECT_MAX_BYTES");
    return e ? atoll(e) : 100 * 1024;
  }();
  return v;
}

// ------------------------------------------------- borrowed-arg fetch
// ObjectRef args pickle as _rebuild_ref(id_bytes, (host, port)); the
// cpp worker resolves them through the same borrower protocol Python
// workers use: poll the owner's get_object (inline data or locations),
// then fetch located copies whole from that node's raylet
// (fetch_object).  Connections are cached per peer.
std::mutex g_peer_lock;
std::map<std::pair<std::string, int>, std::shared_ptr<rpcnet::Conn>>
    g_peer_conns;
std::map<std::string, std::pair<std::string, int>> g_node_addr_cache;
std::mutex g_gcs_lock;
std::unique_ptr<rpcnet::Conn> g_gcs_conn;

// Returned shared_ptr keeps the Conn alive for the caller even if a
// concurrent thread replaces the cache entry after a disconnect — the
// old object dies only when its last user finishes (throwing connects
// are never inserted, so a cached entry is never null).
std::shared_ptr<rpcnet::Conn> peer_conn(const std::string& host,
                                        int port) {
  auto key = std::make_pair(host, port);
  {
    std::lock_guard<std::mutex> g(g_peer_lock);
    auto it = g_peer_conns.find(key);
    if (it != g_peer_conns.end() && !it->second->closed())
      return it->second;
  }
  std::shared_ptr<rpcnet::Conn> fresh(
      rpcnet::Conn::connect(host, port));  // throws: nothing cached
  std::lock_guard<std::mutex> g(g_peer_lock);
  g_peer_conns[key] = fresh;
  return fresh;
}

// node_id hex -> (host, port); the table is cached like the Python
// borrower's node cache — one list_nodes per UNKNOWN node, never under
// the peer-connection lock
bool node_address(const std::string& node_id, std::string* host,
                  int* port) {
  {
    std::lock_guard<std::mutex> g(g_gcs_lock);
    auto it = g_node_addr_cache.find(node_id);
    if (it != g_node_addr_cache.end()) {
      *host = it->second.first;
      *port = it->second.second;
      return true;
    }
  }
  std::lock_guard<std::mutex> g(g_gcs_lock);
  if (!g_gcs_conn || g_gcs_conn->closed()) {
    if (g_gcs_host.empty()) return false;
    g_gcs_conn.reset(rpcnet::Conn::connect(g_gcs_host, g_gcs_port));
  }
  PyVal nodes = g_gcs_conn->call("list_nodes", PyVal::dict(), 10.0);
  bool found = false;
  for (const auto& n : nodes.items) {
    const PyVal* nid = n.get("node_id");
    const PyVal* addr = n.get("address");
    if (nid && nid->kind == PyVal::STR && addr &&
        addr->items.size() == 2) {
      g_node_addr_cache[nid->s] = {addr->items[0].s,
                                   (int)addr->items[1].i};
      if (nid->s == node_id) {
        *host = addr->items[0].s;
        *port = (int)addr->items[1].i;
        found = true;
      }
    }
  }
  return found;
}

// chunked whole-object read (fetch_object_chunk): a multi-GB promoted
// arg never occupies a multi-GB RPC frame (raylet chunk protocol)
constexpr int64_t kFetchChunk = 8 * 1024 * 1024;

// Returns false when the copy is absent at this location (evicted, or in
// the transient spill-restore window raylet documents as must-retry) —
// the caller then re-polls the owner, matching core_worker's
// absent->retry semantics (core_worker.py:871). Advances by the bytes
// actually received, never by the request size, and treats an empty
// chunk as absent so a short read can't yield a corrupt payload.
bool fetch_located(const std::string& id_bytes, const std::string& host,
                   int port, double timeout_s, std::string* out) {
  auto conn = peer_conn(host, port);
  out->clear();
  int64_t total = -1;
  int64_t off = 0;
  while (total < 0 || off < total) {
    PyVal q = PyVal::dict();
    q.set("object_id", PyVal::bytes(id_bytes));
    q.set("offset", PyVal::integer(off));
    q.set("length", PyVal::integer(kFetchChunk));
    PyVal r = conn->call("fetch_object_chunk", q, timeout_s);
    const PyVal* d = r.get("data");
    const PyVal* t = r.get("total");
    if (!d || d->kind != PyVal::BYTES || !t || t->kind != PyVal::INT)
      return false;  // copy gone at this node
    if (d->s.empty()) return false;  // empty chunk == absent
    total = t->i;
    out->append(d->s);
    off += (int64_t)d->s.size();
  }
  return (int64_t)out->size() == total;
}

PyVal resolve_ref_arg(const std::string& id_bytes,
                      const std::string& owner_host, int owner_port,
                      double timeout_s = 60.0) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(timeout_s);
  PyVal q = PyVal::dict();
  q.set("object_id", PyVal::bytes(id_bytes));
  q.set("timeout", PyVal::real(1.0));
  while (std::chrono::steady_clock::now() < deadline) {
    PyVal r = peer_conn(owner_host, owner_port)->call("get_object", q,
                                                      timeout_s);
    if (r.kind == PyVal::NONE) {
      usleep(10000);  // the owner is recovering/producing it: poll
      continue;
    }
    const PyVal* data = r.get("data");
    if (data && data->kind == PyVal::BYTES) {
      int64_t err = 0;
      PyVal v = pycodec::flat_deserialize(data->s, &err);
      if (err)
        throw std::runtime_error("dependency failed: " + v.repr());
      return v;
    }
    const PyVal* locs = r.get("locations");
    if (locs && !locs->items.empty()) {
      // try every reported location; a stale/evicted copy or a dead
      // node re-polls the owner instead of failing the task (the
      // Python borrower's retry semantics). Only a decoded
      // dependency-failure propagates out of this loop.
      for (const auto& loc : locs->items) {
        std::string host;
        int port = 0;
        if (loc.kind != PyVal::STR ||
            !node_address(loc.s, &host, &port))
          continue;
        std::string flat;
        bool have = false;
        try {
          have = fetch_located(id_bytes, host, port, timeout_s, &flat);
        } catch (const rpcnet::RpcError&) {
          have = false;  // node unreachable: try the next / re-poll
        }
        if (!have) continue;
        int64_t err = 0;
        PyVal v = pycodec::flat_deserialize(flat, &err);
        if (err)
          throw std::runtime_error("dependency failed: " + v.repr());
        return v;
      }
    }
    usleep(10000);
  }
  throw std::runtime_error("timed out resolving ObjectRef arg");
}

// an unpickled ObjectRef marker: OPAQUE _rebuild_ref(id, (host, port))
bool is_ref_marker(const PyVal& v) {
  return v.kind == PyVal::OPAQUE &&
         v.s.size() >= 12 &&
         v.s.compare(v.s.size() - 12, 12, "_rebuild_ref") == 0 &&
         v.items.size() == 2 && v.items[0].kind == PyVal::BYTES &&
         v.items[1].kind == PyVal::TUPLE &&
         v.items[1].items.size() == 2;
}

void resolve_ref_args(std::vector<PyVal>* args) {
  for (auto& a : *args) {
    if (is_ref_marker(a)) {
      a = resolve_ref_arg(a.items[0].s, a.items[1].items[0].s,
                          (int)a.items[1].items[1].i);
    }
  }
}

// one result slot: inline payload, or a sealed store object when the
// payload is big and the store is reachable (worker_main
// _package_results semantics)
PyVal package_slot(const std::string& task_id, int64_t index,
                   std::string payload) {  // by value: moved when inline
  PyVal one = PyVal::dict();
  if ((int64_t)payload.size() > inline_max_bytes() &&
      g_store.attached() && !g_node_id_hex.empty() &&
      task_id.size() == 16) {
    // ObjectID.for_task_return: 16-byte task id + big-endian u32 index
    uint8_t oid[20];
    memcpy(oid, task_id.data(), 16);
    oid[16] = (uint8_t)(index >> 24);
    oid[17] = (uint8_t)(index >> 16);
    oid[18] = (uint8_t)(index >> 8);
    oid[19] = (uint8_t)index;
    if (g_store.put(oid, payload)) {
      one.set("location", PyVal::str(g_node_id_hex));
      return one;
    }
    // store full: inline degradation is always correct, just bigger
  }
  one.set("data", PyVal::bytes(std::move(payload)));
  return one;
}

// serialized-format helpers -------------------------------------------------

std::string make_error_payload(const std::string& task_name,
                               const std::string& message) {
  // a real ray_tpu.exceptions.TaskError(function_name, cause, tb) the
  // Python owner deserializes and raises unchanged
  // sanitize: encoding a str raises CodecError on invalid UTF-8, and a
  // throw from the error path would escape the executor loop and kill
  // the worker (user e.what() may embed raw input bytes)
  PyVal cause;
  cause.kind = PyVal::OPAQUE;
  cause.s = "builtins.RuntimeError";
  cause.items.push_back(PyVal::str(pycodec::sanitize_utf8(message)));
  PyVal err;
  err.kind = PyVal::OPAQUE;
  err.s = "ray_tpu.exceptions.TaskError";
  err.items.push_back(PyVal::str(pycodec::sanitize_utf8(task_name)));
  err.items.push_back(std::move(cause));
  err.items.push_back(PyVal::str("(cpp worker)"));
  return pycodec::flat_serialize(err, /*error_type=ERROR_TASK*/ 1);
}

PyVal error_reply(const PyVal& spec, const std::string& message) {
  const PyVal* name = spec.get("name");
  const PyVal* nret = spec.get("num_returns");
  int64_t slots = 1;
  if (nret && nret->kind == PyVal::INT && nret->i > 1) slots = nret->i;
  std::string payload = make_error_payload(
      name && name->kind == PyVal::STR ? name->s : "cpp-task", message);
  PyVal results = PyVal::list();
  for (int64_t j = 0; j < slots; ++j) {
    PyVal one = PyVal::dict();
    one.set("data", PyVal::bytes(payload));
    one.set("error", PyVal::integer(1));
    results.items.push_back(std::move(one));
  }
  PyVal reply = PyVal::dict();
  reply.set("results", std::move(results));
  return reply;
}

PyVal execute_task(const PyVal& spec) {
  const PyVal* fn_key = spec.get("fn_key");
  if (!fn_key || fn_key->kind != PyVal::STR ||
      fn_key->s.rfind("cpp:", 0) != 0)
    return error_reply(spec, "cpp worker received a non-cpp fn_key");
  std::string name = fn_key->s.substr(4);
  auto it = registry().find(name);
  if (it == registry().end())
    return error_reply(spec, "no cpp function registered as '" + name +
                                 "' in this worker binary");
  const PyVal* blob = spec.get("args");
  if (!blob || blob->kind != PyVal::BYTES)
    return error_reply(spec, "missing args blob");
  PyVal packed;
  try {
    packed = pycodec::pickle_loads(blob->s);
  } catch (const std::exception& e) {
    return error_reply(spec, std::string("args not decodable C++-side "
                                         "(ObjectRef/numpy args are not "
                                         "supported by cpp tasks): ") +
                                 e.what());
  }
  // args blob = (args_tuple, kwargs_dict) — core_worker._serialize_args
  if (packed.kind != PyVal::TUPLE || packed.items.size() != 2)
    return error_reply(spec, "bad args blob shape");
  if (!packed.items[1].map.empty())
    return error_reply(spec, "cpp tasks take positional args only");
  std::vector<PyVal> args = std::move(packed.items[0].items);
  try {
    resolve_ref_args(&args);
  } catch (const std::exception& e) {
    return error_reply(spec, e.what());
  }

  PyVal value;
  try {
    value = it->second(args);
  } catch (const std::exception& e) {
    return error_reply(spec, e.what());
  }

  const PyVal* nret = spec.get("num_returns");
  int64_t n = nret && nret->kind == PyVal::INT ? nret->i : 1;
  if (nret && nret->kind == PyVal::STR)
    return error_reply(spec, "num_returns='dynamic' unsupported for cpp");
  std::vector<PyVal> values;
  if (n == 1) {
    values.push_back(std::move(value));
  } else if (n == 0) {
    // nothing
  } else {
    if (value.kind != PyVal::TUPLE && value.kind != PyVal::LIST)
      return error_reply(spec, "task declared multiple returns but the "
                               "cpp function returned a scalar");
    if ((int64_t)value.items.size() != n)
      return error_reply(spec, "return count mismatch");
    values = std::move(value.items);
  }
  const PyVal* tid = spec.get("task_id");
  std::string task_id =
      tid && tid->kind == PyVal::BYTES ? tid->s : std::string();
  PyVal results = PyVal::list();
  for (size_t i = 0; i < values.size(); ++i) {
    std::string payload;
    try {
      payload = pycodec::flat_serialize(values[i]);
    } catch (const std::exception& e) {
      return error_reply(spec, std::string("unserializable result: ") +
                                   e.what());
    }
    results.items.push_back(
        package_slot(task_id, (int64_t)i, std::move(payload)));
  }
  PyVal reply = PyVal::dict();
  reply.set("results", std::move(results));
  return reply;
}

PyVal execute_actor_task(const PyVal& spec) {
  const PyVal* method = spec.get("method");
  if (!method || method->kind != PyVal::STR)
    return error_reply(spec, "actor task without method");
  if (method->s == "__ray_terminate__") _exit(0);
  if (!g_actor)
    return error_reply(spec, "no actor constructed in this worker");
  const PyVal* blob = spec.get("args");
  PyVal packed;
  try {
    packed = pycodec::pickle_loads(blob ? blob->s : std::string());
  } catch (const std::exception& e) {
    return error_reply(spec, std::string("actor args not decodable "
                                         "C++-side: ") + e.what());
  }
  if (packed.kind != PyVal::TUPLE || packed.items.size() != 2 ||
      !packed.items[1].map.empty())
    return error_reply(spec, "cpp actors take positional args only");
  PyVal value;
  try {
    resolve_ref_args(&packed.items[0].items);
    value = g_actor->call(method->s, packed.items[0].items);
  } catch (const std::exception& e) {
    return error_reply(spec, e.what());
  }
  std::string payload;
  try {
    payload = pycodec::flat_serialize(value);
  } catch (const std::exception& e) {
    return error_reply(spec, std::string("unserializable result: ") +
                                 e.what());
  }
  const PyVal* tid = spec.get("task_id");
  PyVal results = PyVal::list();
  results.items.push_back(package_slot(
      tid && tid->kind == PyVal::BYTES ? tid->s : std::string(), 0,
      std::move(payload)));
  PyVal reply = PyVal::dict();
  reply.set("results", std::move(results));
  return reply;
}

PyVal create_actor(const PyVal& p) {
  const PyVal* aid = p.get("actor_id");
  const PyVal* spec_blob = p.get("spec");
  if (!aid || !spec_blob || spec_blob->kind != PyVal::BYTES)
    throw rpcnet::RpcError("bad create_actor payload");
  PyVal creation = pycodec::pickle_loads(spec_blob->s);
  const PyVal* cls_key = creation.get("cls_key");
  if (!cls_key || cls_key->kind != PyVal::STR ||
      cls_key->s.rfind("cpp:", 0) != 0)
    throw rpcnet::RpcError("cpp worker got a non-cpp actor class");
  std::string name = cls_key->s.substr(4);
  auto it = actor_registry().find(name);
  if (it == actor_registry().end())
    throw rpcnet::RpcError("no cpp actor class registered as '" + name +
                           "' in this worker binary");
  const PyVal* blob = creation.get("args");
  PyVal packed = pycodec::pickle_loads(
      blob && blob->kind == PyVal::BYTES ? blob->s : std::string());
  if (packed.kind != PyVal::TUPLE || packed.items.size() != 2)
    throw rpcnet::RpcError("bad actor creation args");
  // Constructor args may be top-level ObjectRefs (cross_language's
  // _guard_args allows them), exactly like plain task / actor-method
  // args: resolve the markers before the factory sees them.
  resolve_ref_args(&packed.items[0].items);
  g_actor = it->second(packed.items[0].items);
  g_actor_id = aid->s;
  return PyVal::dict();  // actor_ready is sent by the caller (main flow)
}

// Actor calls carry (stream, seq) and MUST execute in seq order per
// stream (worker_main._actor_streams analog): the handler thread parks
// its work in the stream buffer and the executor pops in-order.
struct ActorStreams {
  struct Stream {
    int64_t next = 0;
    std::map<int64_t, std::tuple<PyVal, PyVal*, bool*>> buf;
  };
  std::mutex m;
  std::condition_variable cv;       // executor wakeups
  std::condition_variable done_cv;  // handler-thread completions
  std::map<std::string, Stream> streams;

  PyVal run(const PyVal& spec) {
    const PyVal* seq = spec.get("seq");
    const PyVal* stream_id = spec.get("stream");
    PyVal out;
    bool done = false;
    {
      std::lock_guard<std::mutex> g(m);
      auto& st = streams[stream_id && stream_id->kind == PyVal::STR
                             ? stream_id->s
                             : std::string()];
      st.buf[seq ? seq->i : 0] = {spec, &out, &done};
      cv.notify_all();
    }
    std::unique_lock<std::mutex> lk(m);
    done_cv.wait(lk, [&] { return done; });
    return out;
  }

  void loop() {
    for (;;) {
      std::tuple<PyVal, PyVal*, bool*> work;
      bool got = false;
      {
        std::unique_lock<std::mutex> lk(m);
        cv.wait(lk, [&] {
          for (auto& kv : streams) {
            auto it = kv.second.buf.find(kv.second.next);
            if (it != kv.second.buf.end()) {
              work = std::move(it->second);
              kv.second.buf.erase(it);
              kv.second.next++;
              got = true;
              return true;
            }
          }
          return false;
        });
      }
      if (!got) continue;
      PyVal out = execute_actor_task(std::get<0>(work));
      {
        std::lock_guard<std::mutex> g(m);
        *std::get<1>(work) = std::move(out);
        *std::get<2>(work) = true;
        done_cv.notify_all();
      }
    }
  }
};

ActorStreams g_actor_streams;

// serial executor: the owner's retry accounting assumes this worker
// drains its FIFO one task at a time (core_worker._lease_worker_loop)
struct Executor {
  std::mutex m;
  std::condition_variable cv;
  std::deque<std::tuple<PyVal, PyVal*, std::condition_variable*, bool*>> q;

  PyVal run(const PyVal& spec) {
    PyVal out;
    std::condition_variable done_cv;
    bool done = false;
    {
      std::lock_guard<std::mutex> g(m);
      q.emplace_back(spec, &out, &done_cv, &done);
      cv.notify_one();
    }
    std::unique_lock<std::mutex> lk(m);
    done_cv.wait(lk, [&] { return done; });
    return out;
  }

  void loop() {
    for (;;) {
      std::tuple<PyVal, PyVal*, std::condition_variable*, bool*> item;
      {
        std::unique_lock<std::mutex> lk(m);
        cv.wait(lk, [&] { return !q.empty(); });
        item = std::move(q.front());
        q.pop_front();
      }
      PyVal out = execute_task(std::get<0>(item));
      {
        std::lock_guard<std::mutex> g(m);
        *std::get<1>(item) = std::move(out);
        *std::get<3>(item) = true;
        std::get<2>(item)->notify_all();
      }
    }
  }
};

Executor g_exec;
int g_server_port = 0;

void notify_actor_ready() {
  // dedicated conn; one-shot (the GCS also learns liveness via the
  // raylet's heartbeats — this just flips the FSM to ALIVE with our
  // address, like worker_main._create_actor's actor_ready call)
  std::unique_ptr<rpcnet::Conn> gcs(
      rpcnet::Conn::connect(g_gcs_host, g_gcs_port));
  PyVal p = PyVal::dict();
  p.set("actor_id", PyVal::str(g_actor_id));
  PyVal addr = PyVal::list();
  addr.items.push_back(PyVal::str(g_self_host));
  addr.items.push_back(PyVal::integer(g_server_port));
  p.set("address", std::move(addr));
  gcs->call("actor_ready", p, 30.0);
}

// Batched submission (core_worker._lease_worker_loop push_tasks frames):
// execute each spec in frame order on the serial executor and ack once
// with per-spec results.  A spec failure becomes a per-spec "err" entry
// so one bad task can't poison its frame-mates (the python worker's
// _run_queued_batch contract); no task_done streaming from C++ — the
// frame ack resolves everything.
PyVal run_task_batch(const PyVal& payload) {
  const PyVal* specs = payload.get("specs");
  if (!specs || (specs->kind != PyVal::LIST && specs->kind != PyVal::TUPLE))
    throw rpcnet::RpcError("push_tasks: missing specs");
  PyVal results = PyVal::list();
  for (const PyVal& spec : specs->items) {
    PyVal entry = PyVal::dict();
    try {
      entry.set("ok", g_exec.run(spec));
    } catch (const std::exception& e) {
      entry.set("err", PyVal::str(pycodec::sanitize_utf8(
          std::string(e.what()))));
    }
    results.items.push_back(std::move(entry));
  }
  PyVal out = PyVal::dict();
  out.set("results", std::move(results));
  return out;
}

PyVal dispatch(const std::string& method, const PyVal& payload) {
  if (method == "push_tasks") return run_task_batch(payload);
  if (method == "push_task") return g_exec.run(payload);
  if (method == "actor_task") return g_actor_streams.run(payload);
  if (method == "create_actor") {
    PyVal out = create_actor(payload);
    notify_actor_ready();
    return out;
  }
  if (method == "kill") _exit(1);
  if (method == "ping") return PyVal::dict();
  if (method == "profile") {
    PyVal out = PyVal::dict();
    out.set("folded", PyVal::str("cpp_worker;native 1"));
    return out;
  }
  throw rpcnet::RpcError("cpp worker: unsupported method " + method);
}

const char* arg_value(int argc, char** argv, const char* flag) {
  for (int j = 1; j + 1 < argc; ++j)
    if (strcmp(argv[j], flag) == 0) return argv[j + 1];
  return nullptr;
}

}  // namespace

namespace ray_tpu_cpp {
void register_function(const std::string& name, TaskFn fn) {
  registry()[name] = std::move(fn);
}
void register_actor_class(const std::string& name, ActorFactory f) {
  actor_registry()[name] = std::move(f);
}
}  // namespace ray_tpu_cpp

int main(int argc, char** argv) {
  const char* raylet_host = arg_value(argc, argv, "--raylet-host");
  const char* raylet_port = arg_value(argc, argv, "--raylet-port");
  const char* worker_id = arg_value(argc, argv, "--worker-id");
  const char* gcs_host = arg_value(argc, argv, "--gcs-host");
  const char* gcs_port = arg_value(argc, argv, "--gcs-port");
  if (!raylet_host || !raylet_port || !worker_id) {
    fprintf(stderr, "usage: cpp_worker --raylet-host H --raylet-port P "
                    "--worker-id ID [--gcs-host H --gcs-port P]\n");
    return 2;
  }
  if (gcs_host) g_gcs_host = gcs_host;
  if (gcs_port) g_gcs_port = atoi(gcs_port);
  g_self_host = raylet_host;
  const char* store_path = arg_value(argc, argv, "--store-path");
  const char* node_id = arg_value(argc, argv, "--node-id");
  if (node_id) g_node_id_hex = node_id;
  if (store_path && !g_store.attach(store_path))
    fprintf(stderr, "shm store attach failed (%s): large results will "
                    "ship inline\n", store_path);
  ray_tpu_cpp::register_builtin_functions();

  std::thread exec([&] { g_exec.loop(); });
  exec.detach();
  std::thread actor_exec([&] { g_actor_streams.loop(); });
  actor_exec.detach();

  rpcnet::Server server(dispatch);
  g_server_port = server.port();

  // fate-share with the raylet exactly like worker_main.py:_raylet_gone
  rpcnet::Conn* raylet = rpcnet::Conn::connect(
      raylet_host, atoi(raylet_port), dispatch, [] {
        fprintf(stderr, "raylet connection lost; cpp worker exiting\n");
        _exit(1);
      });

  PyVal reg = PyVal::dict();
  reg.set("worker_id", PyVal::str(worker_id));
  PyVal addr = PyVal::list();
  addr.items.push_back(PyVal::str(g_self_host));
  addr.items.push_back(PyVal::integer(server.port()));
  reg.set("address", std::move(addr));
  try {
    raylet->call("register_worker", reg, 30.0);
  } catch (const std::exception& e) {
    fprintf(stderr, "register_worker failed: %s\n", e.what());
    return 1;
  }
  fprintf(stderr, "cpp worker %s serving on port %d\n", worker_id,
          server.port());
  for (;;) pause();
}
