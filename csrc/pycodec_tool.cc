// Round-trip test harness for pycodec (driven by tests/test_cpp_api.py):
// stdin:  [u32 len][pickled value] ...
// stdout: per value, [u32 len][re-encoded pickle][u32 len][repr utf-8]
#include <cstdio>
#include <string>

#include "pycodec.h"

static bool read_exact(char* buf, size_t n) {
  return fread(buf, 1, n, stdin) == n;
}
static void write_block(const std::string& s) {
  uint32_t n = (uint32_t)s.size();
  char hdr[4] = {(char)n, (char)(n >> 8), (char)(n >> 16), (char)(n >> 24)};
  fwrite(hdr, 1, 4, stdout);
  fwrite(s.data(), 1, s.size(), stdout);
}

int main() {
  char hdr[4];
  while (read_exact(hdr, 4)) {
    uint32_t n = (uint32_t)(unsigned char)hdr[0] |
                 (uint32_t)(unsigned char)hdr[1] << 8 |
                 (uint32_t)(unsigned char)hdr[2] << 16 |
                 (uint32_t)(unsigned char)hdr[3] << 24;
    std::string data(n, '\0');
    if (!read_exact(&data[0], n)) return 1;
    try {
      pycodec::PyVal v = pycodec::pickle_loads(data);
      std::string enc;
      try {
        enc = pycodec::pickle_dumps(v);
      } catch (const std::exception&) {
        // opaque values (class refs etc.) decode for inspection but
        // cannot be re-encoded — report the repr alone
      }
      write_block(enc);
      write_block(v.repr());
    } catch (const std::exception& e) {
      write_block("");
      write_block(std::string("ERROR: ") + e.what());
    }
    fflush(stdout);
  }
  return 0;
}
