// cpp_store: the C++ worker's client for the node's shared-memory store.
//
// Attaches the same mmap segment csrc/shmstore.cc manages (the raylet
// creates it; Python workers attach via ctypes in
// ray_tpu/runtime/object_store.py) and writes sealed primary copies the
// same way store_put does (core_worker.py:577): create with
// allow_evict=0 — primaries are never LRU-evicted — copy the serialized
// flat bytes, seal.  Lets cpp tasks return results above the inline
// threshold as store objects instead of multi-MB RPC replies.
#pragma once

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <string>

extern "C" {
long long store_create(void* base, const uint8_t* id, uint64_t size,
                       uint64_t meta, int allow_evict);
int store_seal(void* base, const uint8_t* id);
}

namespace ray_tpu_cpp {

class ShmStoreClient {
 public:
  // attach an existing segment; false if absent/unreadable
  bool attach(const std::string& path) {
    int fd = ::open(path.c_str(), O_RDWR);
    if (fd < 0) return false;
    struct stat st{};
    if (fstat(fd, &st) != 0 || st.st_size <= 0) {
      ::close(fd);
      return false;
    }
    len_ = (size_t)st.st_size;
    base_ = ::mmap(nullptr, len_, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    ::close(fd);
    if (base_ == MAP_FAILED) {
      base_ = nullptr;
      return false;
    }
    return true;
  }

  bool attached() const { return base_ != nullptr; }

  // sealed primary copy of `data` under the 20-byte object id; false on
  // store-full (the caller degrades to an inline reply — the Python
  // worker's spill-request/fallback dance is not replicated here)
  bool put(const uint8_t id[20], const std::string& data) {
    if (!base_) return false;
    long long off = store_create(base_, id, data.size(), /*meta=*/0,
                                 /*allow_evict=*/0);
    if (off == -1) return true;  // already exists: a lost-reply retry
    // re-produced the same (task_id, index) — success like the Python
    // worker's FileExistsError path (core_worker.py store_put)
    if (off <= 0) return false;
    memcpy((char*)base_ + off, data.data(), data.size());
    return store_seal(base_, id) == 0;
  }

 private:
  void* base_ = nullptr;
  size_t len_ = 0;
};

}  // namespace ray_tpu_cpp
