"""Headline benchmark: flagship GPT train-step throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": tokens/sec/chip, "unit": "tokens/s",
   "vs_baseline": achieved_MFU / 0.35}

The reference commits no number for its Train north-star metric
(BASELINE.json "published" is empty), so ``vs_baseline`` is measured against
the north-star target itself: BASELINE.md's "GPT-J FSDP->GSPMD >= 35% MFU".
vs_baseline >= 1.0 means we meet/beat the target MFU on this chip.
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

PEAK_FLOPS = {
    # bf16 peak per chip
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
}


def _bench_one(cfg, batch, seq, steps, warmup, peak, *,
               optimizer=None, chunked=False):
    from ray_tpu._private import step_stats as sst
    from ray_tpu.models import GPT
    from ray_tpu.train.step import (OptimizerConfig, lm_loss_chunked_fn,
                                    make_sharded_train)
    from ray_tpu.parallel import build_mesh, MeshConfig

    n_params = cfg.num_params()
    # PaLM-style: 6N per token fwd+bwd + attention 12*L*d*S
    flops_per_token = 6 * n_params + 12 * cfg.n_layers * cfg.d_model * seq

    mesh = build_mesh(MeshConfig(data=-1))
    n_chips = mesh.size
    # goodput ledger (docs/observability.md training performance
    # plane): the same per-step clock the trainers drive, standalone
    # (no cluster, local-only ledger).  peak_flops covers the whole
    # mesh so the ledger MFU is per-chip-comparable with the hand
    # computation below.
    run = sst.start_run(
        f"bench-{getattr(cfg, 'name', 'gpt')}",
        flops_per_token=flops_per_token, peak_flops=peak * n_chips,
        tokens_per_step=batch * seq)
    clock = sst.step_clock()
    model = GPT(cfg, mesh=mesh)
    rng = np.random.default_rng(0)
    batch_data = {"tokens": jnp.asarray(
        rng.integers(0, cfg.vocab_size, (batch, seq + 1)), jnp.int32)}
    kwargs = {"loss_fn": lm_loss_chunked_fn} if chunked else {}
    init_fn, step_fn, _, _ = make_sharded_train(
        model, mesh,
        optimizer or OptimizerConfig(warmup_steps=10, decay_steps=1000),
        example_batch=batch_data, **kwargs)
    state = init_fn(jax.random.PRNGKey(0), batch_data)

    if run is not None:
        run.ledger.note_init_done()
    t_compile = time.perf_counter()
    for _ in range(warmup):
        state, metrics = step_fn(state, batch_data)
    # Fence via a device-to-host read: on the axon tunnel platform
    # block_until_ready returns early, a D2H copy forces the full chain.
    float(metrics["loss"])
    if run is not None:
        run.ledger.note_compile_ms((time.perf_counter() - t_compile) * 1e3)
    t0 = time.perf_counter()
    for i in range(steps):
        clock.begin()
        with clock.phase("host_dispatch"):
            state, metrics = step_fn(state, batch_data)
        if i == steps - 1:
            # the drain fence belongs to the LAST step's device_compute
            # so the ledger's productive window equals the timed window
            # (per-step fencing would serialize the device pipeline and
            # change the headline number)
            with clock.phase("device_compute"):
                final_loss = float(metrics["loss"])
        clock.end()
    dt = (time.perf_counter() - t0) / steps
    ledger = sst.end_run(run) or {}

    tokens_per_sec = batch * seq / dt / n_chips  # per chip
    mfu = flops_per_token * tokens_per_sec / peak
    out = {"tokens_s": round(tokens_per_sec, 1), "mfu": round(mfu, 4),
           "step_ms": round(dt * 1e3, 2), "params": n_params,
           "n_chips": n_chips, "final_loss": round(final_loss, 4)}
    if ledger:
        out.update({
            "goodput": ledger.get("goodput"),
            "ledger_mfu": ledger.get("mfu"),
            "init_ms": round(ledger.get("init_ms", 0.0), 1),
            "compile_ms": round(ledger.get("compile_ms", 0.0), 1),
            "phase_ms": ledger.get("phase_ms"),
        })
    return out


def _multichip_rows(timeout_s: float = 900.0):
    """The sharded-training headline legs (docs/train_sharded.md), in a
    fresh process: the simulated multi-device mesh needs
    ``XLA_FLAGS=--xla_force_host_platform_device_count`` pinned before
    first backend touch, and THIS process's backend is already live.
    Returns the child's JSON dict ({"multichip": ..., "pipeline": ...})
    or an error row — the headline must degrade, not die."""
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "benchmarks.sharded_train_bench"],
            capture_output=True, text=True, timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        for line in reversed(proc.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except ValueError:
                    continue
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return {"multichip": {
            "error": f"no JSON from sharded_train_bench (exit "
                     f"{proc.returncode}): {tail[-1] if tail else ''}"}}
    except Exception as e:  # noqa: BLE001 — degrade to an error row
        return {"multichip": {"error": f"{type(e).__name__}: {e}"}}


def main():
    from ray_tpu.models import get_config
    from ray_tpu.train.step import OptimizerConfig

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    kind = getattr(dev, "device_kind", "")
    peak = next((v for k, v in PEAK_FLOPS.items() if k in kind), 197e12)
    n_dev = len(jax.devices())

    if on_tpu:
        # measured sweep on v5e (16 GiB): batch 16 + remat beats batch 8
        # no-remat (47.7% vs 45.1% MFU); batch 32 needs the chunked head
        # and lands lower (44.3%) — the fp32 logits path at 16 wins.
        # Round-3 kernel sweep: flash block_q/block_k 1024/1024 beats the
        # old 256/256 by ~25% on attention fwd+bwd at these shapes
        # (gpt-small 49.1% -> 54.4% MFU, gpt-large 44.3% -> 48.6%).
        small = _bench_one(
            get_config("gpt-small", max_seq_len=1024, remat=True,
                       attention_impl="flash"),
            16 * n_dev, 1024, steps=20, warmup=3, peak=peak)
        # memory-lean path at 1B scale (north-star stepping stone): full
        # per-block remat + chunked CE head + adafactor + the hoisted
        # f32->bf16 param cast (train/step.py cast_params_once: one cast
        # per step instead of one per backward recompute) fits 1.07B
        # params on one 16 GiB chip at batch 10.  Round-4 sweep
        # (benchmarks/mfu_sweep.py): batch {4,6,8,12,16} x policy
        # {nothing, block_outs, dots, partial remat_layers} x CE chunk
        # {256,512,1024} all land 45.1-48.6% without the cast; with it,
        # nothing/b8 49.6%, nothing/b10 50.4% (b12 regresses: the bf16
        # copy eats the headroom).  Round-3 results still hold: xla
        # attention 37.5%, splash 23.6%, seq-2048@b4 worse; the in-tree
        # flash kernel with 1024-blocks wins.  Both models measure ~59%
        # raw hardware efficiency on their fwd pass — further MFU comes
        # from kernel work, not schedule knobs.
        import functools

        from ray_tpu.train.step import lm_loss_chunked_fn as _chunked
        import ray_tpu.train.step as _step_mod
        _orig_chunked = _step_mod.lm_loss_chunked_fn
        _step_mod.lm_loss_chunked_fn = functools.partial(
            _chunked, param_cast=jnp.bfloat16)
        try:
            large = _bench_one(
                get_config("gpt-large", max_seq_len=1024, remat=True,
                           remat_policy="nothing", attention_impl="flash"),
                10 * n_dev, 1024, steps=10, warmup=3, peak=peak,
                optimizer=OptimizerConfig(warmup_steps=10, decay_steps=1000,
                                          optimizer="adafactor"),
                chunked=True)
        finally:
            _step_mod.lm_loss_chunked_fn = _orig_chunked
        large.update({"config": "gpt-large", "optimizer": "adafactor",
                      "remat_policy": "nothing", "loss_head": "chunked_ce",
                      "param_cast": "bf16_once"})
    else:  # CI smoke fallback
        small = _bench_one(get_config("tiny"), 4 * n_dev, 128,
                           steps=5, warmup=1, peak=peak)
        large = None

    out = {
        "metric": "gpt_small_train_tokens_per_sec_per_chip",
        "value": small["tokens_s"],
        "unit": "tokens/s",
        "vs_baseline": round(small["mfu"] / 0.35, 4),
        "mfu": small["mfu"],
        "step_ms": small["step_ms"],
        "device": kind or dev.platform,
        "n_chips": small["n_chips"],
        "params": small["params"],
        "final_loss": small["final_loss"],
        # goodput ledger (docs/observability.md): the step-stats plane's
        # accounting of the same run — ledger_mfu must match `mfu`
        # (same flops arithmetic, clock-measured productive time)
        "goodput": small.get("goodput"),
        "ledger_mfu": small.get("ledger_mfu"),
        "init_ms": small.get("init_ms"),
        "compile_ms": small.get("compile_ms"),
        "phase_ms": small.get("phase_ms"),
    }
    if large is not None:
        out["large_model"] = large

    # multi-chip headline (docs/train_sharded.md): a gpt-large-family
    # gang on a simulated >= 4-device mesh — planner fsdp x tp layouts,
    # int8 backward-overlapped gradient sync — surviving one injected
    # mid-run slice preemption (``preempted: survived``, goodput/MFU
    # ledger as referee), plus a pp=2 MPMD pipeline row whose
    # per-microbatch submission cost is telemetry-asserted ~ 0.
    # RAY_TPU_BENCH_MULTICHIP=0 skips (the legs cost a few minutes).
    if os.environ.get("RAY_TPU_BENCH_MULTICHIP", "1").strip().lower() \
            not in ("0", "false", "no", "off"):
        rows = _multichip_rows()
        out["multichip"] = rows.get("multichip")
        if rows.get("pipeline") is not None:
            out["pipeline_mpmd"] = rows["pipeline"]
    print(json.dumps(out))


if __name__ == "__main__":
    main()
