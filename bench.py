"""Headline benchmark: flagship GPT train-step throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": tokens/sec/chip, "unit": "tokens/s",
   "vs_baseline": achieved_MFU / 0.35}

The reference commits no number for its Train north-star metric
(BASELINE.json "published" is empty), so ``vs_baseline`` is measured against
the north-star target itself: BASELINE.md's "GPT-J FSDP->GSPMD >= 35% MFU".
vs_baseline >= 1.0 means we meet/beat the target MFU on this chip.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

PEAK_FLOPS = {
    # bf16 peak per chip
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
}


def main():
    from ray_tpu.models import get_config, GPT
    from ray_tpu.train.step import OptimizerConfig, make_sharded_train
    from ray_tpu.parallel import build_mesh, MeshConfig

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    kind = getattr(dev, "device_kind", "")
    peak = next((v for k, v in PEAK_FLOPS.items() if k in kind), 197e12)

    n_dev = len(jax.devices())
    if on_tpu:
        # measured sweep on v5e (16 GiB): batch 16 + remat beats batch 8
        # no-remat (47.7% vs 45.1% MFU); batch 32 OOMs on fp32 logits
        batch, seq = 16 * n_dev, 1024
        cfg = get_config("gpt-small", max_seq_len=seq, remat=True,
                         attention_impl="flash")
        steps, warmup = 20, 3
    else:  # CI smoke fallback
        batch, seq = 4 * n_dev, 128
        cfg = get_config("tiny")
        steps, warmup = 5, 1

    mesh = build_mesh(MeshConfig(data=-1))
    model = GPT(cfg, mesh=mesh)
    rng = np.random.default_rng(0)
    batch_data = {"tokens": jnp.asarray(
        rng.integers(0, cfg.vocab_size, (batch, seq + 1)), jnp.int32)}
    init_fn, step_fn, _, _ = make_sharded_train(
        model, mesh, OptimizerConfig(warmup_steps=10, decay_steps=1000),
        example_batch=batch_data)
    state = init_fn(jax.random.PRNGKey(0), batch_data)

    for _ in range(warmup):
        state, metrics = step_fn(state, batch_data)
    # Fence via a device-to-host read: on the axon tunnel platform
    # block_until_ready returns early, a D2H copy forces the full chain.
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, batch_data)
    final_loss = float(metrics["loss"])
    dt = (time.perf_counter() - t0) / steps

    n_chips = mesh.size
    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step / dt / n_chips  # per chip
    n_params = cfg.num_params()
    # PaLM-style: 6N per token fwd+bwd + attention 12*L*d*S
    flops_per_token = 6 * n_params + 12 * cfg.n_layers * cfg.d_model * seq
    mfu = flops_per_token * tokens_per_sec / peak
    print(json.dumps({
        "metric": "gpt_small_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.35, 4),
        "mfu": round(mfu, 4),
        "step_ms": round(dt * 1e3, 2),
        "device": kind or dev.platform,
        "n_chips": n_chips,
        "params": n_params,
        "final_loss": round(final_loss, 4),
    }))


if __name__ == "__main__":
    main()
