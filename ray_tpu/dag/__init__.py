"""Lazy DAG authoring API.

Analog of /root/reference/python/ray/dag (DAGNode dag_node.py:23,
FunctionNode function_node.py:12, ClassNode class_node.py:16, InputNode
input_node.py:13): `.bind()` on remote functions/classes builds a lazy
graph; `.execute(input)` submits it as ray_tpu tasks/actors bottom-up.
Used by Workflow for durable execution.
"""

from ray_tpu.dag.dag_node import (ClassMethodNode, ClassNode, DAGNode,
                                  ExistingActorNode, FunctionNode, InputNode)


def __getattr__(name):
    # compiled-DAG types import the runtime; load them lazily so plain
    # graph authoring never pays for it
    if name in ("CompiledDAG", "CompiledDAGRef"):
        from ray_tpu.dag import compiled_dag
        return getattr(compiled_dag, name)
    raise AttributeError(name)


__all__ = ["DAGNode", "FunctionNode", "ClassNode", "ClassMethodNode",
           "ExistingActorNode", "InputNode", "CompiledDAG",
           "CompiledDAGRef"]
