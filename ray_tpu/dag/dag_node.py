"""DAG node types: build lazily, execute via tasks/actors.

Cf. reference python/ray/dag/dag_node.py:23 (_apply_recursive traversal),
function_node.py, class_node.py, input_node.py. Execution resolves
children depth-first, replacing nodes with ObjectRefs/actor handles, and
caches per-node results so diamond dependencies execute once.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional


class DAGNode:
    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs
        self._stable_uuid = uuid.uuid4().hex

    # ------------------------------------------------------------ traversal
    def _children(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def _resolve_args(self, cache: Dict[str, Any], input_value: Any):
        args = tuple(a._execute_recursive(cache, input_value)
                     if isinstance(a, DAGNode) else a
                     for a in self._bound_args)
        kwargs = {k: (v._execute_recursive(cache, input_value)
                      if isinstance(v, DAGNode) else v)
                  for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_recursive(self, cache: Dict[str, Any], input_value: Any):
        if self._stable_uuid not in cache:
            cache[self._stable_uuid] = self._execute_impl(cache, input_value)
        return cache[self._stable_uuid]

    def _execute_impl(self, cache, input_value):
        raise NotImplementedError

    # ------------------------------------------------------------ user API
    def execute(self, *input_values) -> Any:
        """Submit the whole DAG; returns the root's ObjectRef (or value)."""
        input_value = input_values[0] if input_values else None
        return self._execute_recursive({}, input_value)

    def experimental_compile(self, *, max_inflight: int = 2,
                             buffer_size_bytes: int = 1 << 20,
                             name: str = "", threaded_ops: bool = False):
        """Compile an actor-method-only graph into a ``CompiledDAG``:
        preallocated shm channels per edge + resident actor loops, so
        ``execute()`` pays zero per-call task submission (see
        dag/compiled_dag.py and docs/compiled_dag.md).

        ``threaded_ops=True`` gives each of an actor's ops its own
        resident thread instead of one serial per-actor loop: an actor
        appearing at several pipeline depths (e.g. forward AND backward
        of an MPMD stage) can then work on different execution indices
        concurrently — the 1F1B interleave.  Method execution stays
        serialized per actor (the worker's method mutex); only the
        channel waits overlap."""
        from ray_tpu.dag.compiled_dag import CompiledDAG
        return CompiledDAG(self, max_inflight=max_inflight,
                           buffer_size_bytes=buffer_size_bytes, name=name,
                           threaded_ops=threaded_ops)

    def walk(self) -> List["DAGNode"]:
        """All nodes, dependencies first, each once."""
        seen: set = set()
        order: List[DAGNode] = []

        def visit(node: DAGNode):
            if node._stable_uuid in seen:
                return
            seen.add(node._stable_uuid)
            for c in node._children():
                visit(c)
            order.append(node)

        visit(self)
        return order


class InputNode(DAGNode):
    """Placeholder for the runtime input (cf. reference input_node.py:13).

    Supports ``with InputNode() as x:`` authoring style.
    """

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def _execute_impl(self, cache, input_value):
        return input_value


class FunctionNode(DAGNode):
    def __init__(self, remote_function, args, kwargs, options=None):
        super().__init__(args, kwargs)
        self._remote_function = remote_function
        self._options = options or {}

    def _execute_impl(self, cache, input_value):
        args, kwargs = self._resolve_args(cache, input_value)
        fn = self._remote_function
        if self._options:
            fn = fn.options(**self._options)
        # upstream ObjectRefs pass through as-is: the executing worker
        # resolves ref args in-place (worker_main._resolve_args), so
        # intermediate results never round-trip through the driver
        return fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """A bound actor-class instantiation inside a DAG."""

    def __init__(self, actor_class, args, kwargs, options=None):
        super().__init__(args, kwargs)
        self._actor_class = actor_class
        self._options = options or {}

    def _execute_impl(self, cache, input_value):
        args, kwargs = self._resolve_args(cache, input_value)
        cls = self._actor_class
        if self._options:
            cls = cls.options(**self._options)
        return cls.remote(*args, **kwargs)

    def __getattr__(self, method_name: str):
        if method_name.startswith("_"):
            raise AttributeError(method_name)
        return _ClassMethodStub(self, method_name)


class _ClassMethodStub:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name,
                               args, kwargs)


class ExistingActorNode(DAGNode):
    """A live ActorHandle bound into a DAG (``handle.method.bind(...)``):
    unlike ClassNode, executing/compiling it never creates an actor —
    the graph runs against the caller's existing instance."""

    def __init__(self, handle):
        super().__init__((), {})
        self._handle = handle

    def _execute_impl(self, cache, input_value):
        return self._handle


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method_name: str,
                 args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method_name = method_name

    def _children(self):
        return super()._children() + [self._class_node]

    def _execute_impl(self, cache, input_value):
        handle = self._class_node._execute_recursive(cache, input_value)
        args, kwargs = self._resolve_args(cache, input_value)
        return getattr(handle, self._method_name).remote(*args, **kwargs)
