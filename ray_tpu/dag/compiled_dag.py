"""Compiled static DAG execution over preallocated shm channels.

``DAGNode.experimental_compile()`` takes an actor-method-only lazy graph
(ClassMethodNode over ClassNode / live ActorHandle bindings + one
InputNode) and turns every ``execute()`` into **zero task submissions**:

* compile time — validate the graph (single InputNode, acyclic, every
  method bound to a live actor), instantiate ClassNode actors once,
  preallocate one single-writer/multi-reader shm channel
  (experimental/channel.py) for the input, every edge and the output,
  and install a resident execution loop on each participating actor
  (``__ray_dag_install__`` over the existing pooled actor connection —
  runtime/worker_main.py).
* execute time — the driver serializes the input straight into the
  input channel's ring; each actor loop blocks on its input channels,
  runs the bound method, writes its output channel in place; the driver
  reads the output ring.  Slots are reused across executions, so 1k
  executes leave the store's ``bytes_in_use`` flat.

This is the dataflow shape MPMD pipeline parallelism needs (PAPERS.md
arXiv:2412.14374) and the low-latency repeated-execution regime the
original Ray task path leaves on the table (arXiv:1712.05889) — see
docs/compiled_dag.md for the protocol, limits and benchmarks
(benchmarks/compiled_dag_perf.py: >=5x lower per-execute latency than
the classic driver-mediated ``dag.execute()`` on a 3-stage chain).

Failure semantics: a user exception becomes an error item that flows
through the graph (downstream stages forward it without executing) and
re-raises at ``CompiledDAGRef.get()``; the DAG stays usable.  A dead
actor poisons every channel — in-flight and future calls raise
``DAGUnavailableError`` and the DAG can be recompiled cleanly.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu import exceptions as exc
from ray_tpu._private import runtime_metrics as rtm
from ray_tpu._private import serialization as ser
from ray_tpu._private.ids import TaskID
from ray_tpu.dag.dag_node import (ClassMethodNode, ClassNode, DAGNode,
                                  ExistingActorNode, FunctionNode, InputNode)
from ray_tpu.exceptions import (ChannelClosedError, ChannelTimeoutError,
                                DAGCompileError, DAGUnavailableError)
from ray_tpu.experimental.channel import (Channel, ChannelReader,
                                          ChannelWriter, channel_object_id,
                                          POISON_TEARDOWN,
                                          POISON_WORKER_DIED)

# ----------------------------------------------------------------- telemetry
# per-DAG execute latency (submit -> output item drained at the driver)
_M_DAG_EXEC = rtm.histogram_family(
    "ray_tpu_compiled_dag_execute_ms",
    "compiled-DAG execute() -> result latency at the driver (ms)",
    tag_key="dag")
_M_DAG_INFLIGHT = rtm.gauge(
    "ray_tpu_compiled_dag_inflight",
    "compiled-DAG executions in flight (submitted, not yet drained)",
    watermark=True)

# timeline: per-execution slices are recorded only for the first N
# executions of a DAG (same rationale as the streaming _STREAM_EVENT_CAP:
# a 1M-execute serving loop must not flood the bounded task table)
EXEC_EVENT_CAP = 256

# actor-liveness poll cadence while a get() is blocked (seconds)
_LIVENESS_PERIOD_S = 0.5

_DEFAULT_BUFFER_BYTES = 1 << 20


def _reject_nested_nodes(value, _seen: Optional[set] = None) -> None:
    """A DAGNode buried inside a container argument would be pickled as
    a constant and the stage would receive the node OBJECT instead of
    its runtime value — reject at compile instead of silently mis-wiring
    (top-level node args become channel reads; nested ones cannot)."""
    if isinstance(value, DAGNode):
        raise DAGCompileError(
            f"a {type(value).__name__} is nested inside a container "
            "argument of a compiled DAG; node arguments must be passed "
            "at the top level of args/kwargs so they become channel "
            "edges")
    if not isinstance(value, (dict, list, tuple, set, frozenset)):
        return
    if _seen is None:
        _seen = set()
    if id(value) in _seen:          # self-referencing container
        return
    _seen.add(id(value))
    for v in (value.values() if isinstance(value, dict) else value):
        _reject_nested_nodes(v, _seen)


def _exec_task_id(dag_id: str, idx: int) -> str:
    """Deterministic per-execution task id: the driver's SUBMITTED/
    FINISHED events and every actor's RUNNING slice land on the same
    timeline record without any per-execute wire traffic."""
    return TaskID(hashlib.sha1(
        f"{dag_id}:{idx}".encode()).digest()[:16]).hex()


def _exec_trace_id(dag_id: str, idx: int) -> str:
    return f"dag-{dag_id[:12]}:{idx}"


class _Op:
    """One ClassMethodNode scheduled onto an actor."""

    __slots__ = ("index", "node", "actor_node", "method", "args", "kwargs",
                 "out_channel_oid")

    def __init__(self, index: int, node: ClassMethodNode):
        self.index = index
        self.node = node
        self.actor_node = node._class_node
        self.method = node._method_name
        self.args: List[dict] = []      # install-payload descriptors
        self.kwargs: Dict[str, dict] = {}
        self.out_channel_oid = None


class CompiledDAGRef:
    """Result handle of one compiled execution.

    ``get()`` blocks for the output item (draining the output channel in
    execution order on behalf of every outstanding ref) and raises any
    exception the graph produced; a ref's value may be taken exactly
    once.  ``await ref`` works from asyncio (the blocking drain runs in
    the default executor)."""

    __slots__ = ("_dag", "_idx", "_taken")

    def __init__(self, dag: "CompiledDAG", idx: int):
        self._dag = dag
        self._idx = idx
        self._taken = False

    @property
    def execution_index(self) -> int:
        return self._idx

    def get(self, timeout: Optional[float] = None) -> Any:
        if self._taken:
            raise ValueError(
                "CompiledDAGRef result was already retrieved; a compiled "
                "execution's value can be taken once")
        value = self._dag._wait_result(self._idx, timeout)
        self._taken = True
        if isinstance(value, _ErrorResult):
            raise value.error
        return value

    def __del__(self):
        # fire-and-forget callers drop refs without get(): release the
        # buffered (or future) result so _results cannot grow unbounded
        if not self._taken:
            try:
                self._dag._abandon(self._idx)
            except Exception:
                pass

    def __await__(self):
        import asyncio
        loop = asyncio.get_running_loop()
        return (yield from loop.run_in_executor(
            None, self.get).__await__())

    def __repr__(self):
        return (f"CompiledDAGRef(dag={self._dag.dag_id[:8]}, "
                f"idx={self._idx})")


class _ErrorResult:
    """Internal: a drained output item that deserialized to an error."""

    __slots__ = ("error",)

    def __init__(self, error: BaseException):
        self.error = error


class CompiledDAG:
    """A compiled static graph; build via ``node.experimental_compile()``.

    Not thread-hostile: ``execute()`` and ``get()`` may be called from
    multiple threads; submission order defines execution order."""

    def __init__(self, root: DAGNode, *, max_inflight: int = 2,
                 buffer_size_bytes: int = _DEFAULT_BUFFER_BYTES,
                 name: str = "", threaded_ops: bool = False):
        from ray_tpu.runtime.core_worker import get_global_worker
        if max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        self._worker = get_global_worker()
        self._root = root
        self._max_inflight = int(max_inflight)
        self._threaded_ops = bool(threaded_ops)
        self._buffer_bytes = int(buffer_size_bytes)
        self.dag_id = hashlib.sha1(
            f"{id(self)}:{time.time_ns()}".encode()).hexdigest()
        self.name = name or f"dag-{self.dag_id[:8]}"

        # populated by _compile
        self._ops: List[_Op] = []
        self._input_channel: Optional[Channel] = None
        self._channels: List[Channel] = []
        self._actors: Dict[str, Any] = {}         # actor_id hex -> handle
        self._created_actor_ids: List[str] = []   # from ClassNodes: ours
        self._input_writer: Optional[ChannelWriter] = None
        self._out_reader: Optional[ChannelReader] = None

        # execution state
        self._cv = threading.Condition()
        self._next_idx = 0
        self._inflight = 0
        self._drained_idx = 0
        self._results: Dict[int, Any] = {}
        self._abandoned: set = set()     # idxs whose ref was dropped
        self._draining = False
        self._dead: Optional[BaseException] = None
        self._torn_down = False
        self._t0: Dict[int, float] = {}
        self._last_liveness = 0.0

        self._compile()

    # ------------------------------------------------------------- compile
    def _walk_validated(self) -> List[DAGNode]:
        """Topological order (dependencies first) with explicit cycle
        detection — ``DAGNode.walk`` assumes acyclicity, and compile must
        reject a hand-mutated cyclic graph instead of recursing forever."""
        order: List[DAGNode] = []
        done: set = set()
        in_progress: set = set()

        def visit(node: DAGNode, stack: list):
            uid = node._stable_uuid
            if uid in done:
                return
            if uid in in_progress:
                raise DAGCompileError(
                    "compiled DAGs must be acyclic; found a cycle through "
                    + " -> ".join(type(n).__name__ for n in stack))
            in_progress.add(uid)
            for child in node._children():
                visit(child, stack + [child])
            in_progress.discard(uid)
            done.add(uid)
            order.append(node)

        visit(self._root, [self._root])
        return order

    def _compile(self) -> None:
        nodes = self._walk_validated()
        if not isinstance(self._root, ClassMethodNode):
            raise DAGCompileError(
                "experimental_compile() requires the output node to be an "
                f"actor method call, got {type(self._root).__name__}")
        input_nodes = [n for n in nodes if isinstance(n, InputNode)]
        if len(input_nodes) > 1:
            raise DAGCompileError(
                f"compiled DAGs take a single InputNode; found "
                f"{len(input_nodes)}")
        if not input_nodes:
            raise DAGCompileError(
                "compiled DAGs require an InputNode (use `with InputNode() "
                "as inp:` and bind it into the graph)")
        for n in nodes:
            if isinstance(n, FunctionNode):
                raise DAGCompileError(
                    "compiled DAGs are actor-method only; task node "
                    f"{n._remote_function!r} cannot be compiled (wrap the "
                    "function in an actor)")
            if not isinstance(n, (InputNode, ClassNode, ExistingActorNode,
                                  ClassMethodNode)):
                raise DAGCompileError(
                    f"unsupported node type in compiled DAG: "
                    f"{type(n).__name__}")

        method_nodes = [n for n in nodes if isinstance(n, ClassMethodNode)]
        self._ops = [_Op(i, n) for i, n in enumerate(method_nodes)]
        op_by_uuid = {op.node._stable_uuid: op for op in self._ops}

        # instantiate ClassNode actors (once per compile; a recompile
        # after worker death gets fresh actors) and resolve liveness for
        # every participant — a dead bound actor fails compile here
        handle_cache: Dict[str, Any] = {}
        actor_of_op: Dict[int, str] = {}
        for op in self._ops:
            an = op.actor_node
            if isinstance(an, ExistingActorNode):
                handle = an._handle
                created = False
            elif isinstance(an, ClassNode):
                if an._stable_uuid not in handle_cache:
                    for a in list(an._bound_args) + \
                            list(an._bound_kwargs.values()):
                        if isinstance(a, DAGNode):
                            raise DAGCompileError(
                                "actor constructor arguments inside a "
                                "compiled DAG must be constants")
                    handle_cache[an._stable_uuid] = \
                        an._execute_recursive({}, None)
                    created = True
                else:
                    created = False
                handle = handle_cache[an._stable_uuid]
            else:
                raise DAGCompileError(
                    f"method bound to unsupported node "
                    f"{type(an).__name__}")
            aid = handle._actor_id.hex()
            try:
                self._worker._resolve_actor(aid)
            except exc.RayTpuError as e:
                raise DAGCompileError(
                    f"actor {aid[:8]} bound into the compiled DAG is not "
                    f"alive: {e}") from e
            self._actors[aid] = handle
            if created:
                self._created_actor_ids.append(aid)
            actor_of_op[op.index] = aid

        # channel planning: readers per producer (the input node and
        # every op), in deterministic order; the driver reads the root
        input_uuid = input_nodes[0]._stable_uuid
        readers: Dict[str, List[Tuple[str, int]]] = {"input": []}
        for op in self._ops:
            readers[f"op{op.index}"] = []

        def _chan_key(dep: DAGNode) -> Optional[str]:
            if isinstance(dep, InputNode):
                return "input"
            if isinstance(dep, ClassMethodNode):
                return f"op{op_by_uuid[dep._stable_uuid].index}"
            return None

        # per op: unique upstream channels -> local read-slot index
        op_reads: Dict[int, List[str]] = {}
        for op in self._ops:
            reads: List[str] = []

            def _descriptor(value, op=op, reads=reads):
                if isinstance(value, DAGNode):
                    key = _chan_key(value)
                    if key is None:
                        raise DAGCompileError(
                            f"cannot pass a {type(value).__name__} as a "
                            "method argument in a compiled DAG")
                    if key not in reads:
                        reads.append(key)
                        readers[key].append((f"op{op.index}", len(reads) - 1))
                    return {"t": "read", "i": reads.index(key)}
                _reject_nested_nodes(value)
                return {"t": "const", "v": value}

            op.args = [_descriptor(a) for a in op.node._bound_args]
            op.kwargs = {k: _descriptor(v)
                         for k, v in op.node._bound_kwargs.items()}
            op_reads[op.index] = reads
        root_key = f"op{op_by_uuid[self._root._stable_uuid].index}"
        readers[root_key].append(("driver", -1))
        if not readers["input"]:
            raise DAGCompileError(
                "the InputNode is not consumed by any compiled method; "
                "bind it into the graph or drop it")

        # allocate the channels in the driver's local shm segment
        chan_objs: Dict[str, Channel] = {}
        driver_reader_idx = None
        try:
            for key, consumer_list in readers.items():
                if not consumer_list:
                    raise DAGCompileError(
                        f"compiled op {key} has no consumers — only the "
                        "output node may be unconsumed")
                oid = channel_object_id(
                    f"{self.dag_id}:{key}".encode())
                chan_objs[key] = Channel.create(
                    self._worker.store, oid, nslots=self._max_inflight,
                    nreaders=len(consumer_list),
                    capacity=self._buffer_bytes)
                for ridx, (who, _slot) in enumerate(consumer_list):
                    if who == "driver":
                        driver_reader_idx = ridx
        except BaseException:
            for ch in chan_objs.values():
                ch.close()
                ch.delete()
            raise
        self._channels = list(chan_objs.values())
        self._input_channel = chan_objs["input"]
        self._input_writer = ChannelWriter(self._input_channel)
        self._out_reader = ChannelReader(chan_objs[root_key],
                                         driver_reader_idx)

        # install the resident loop on each actor (over the existing
        # pooled actor connection, i.e. the normal actor-task path)
        def _reader_index(key: str, op_index: int) -> int:
            for ridx, (who, _slot) in enumerate(readers[key]):
                if who == f"op{op_index}":
                    return ridx
            raise AssertionError(f"op{op_index} not registered on {key}")

        per_actor: Dict[str, List[dict]] = {}
        for op in self._ops:
            desc = {
                "method": op.method,
                "args": op.args,
                "kwargs": op.kwargs,
                "reads": [{"id": chan_objs[key].oid.binary(),
                           "reader": _reader_index(key, op.index)}
                          for key in op_reads[op.index]],
                "out": {"id": chan_objs[f"op{op.index}"].oid.binary()},
                "op_index": op.index,
            }
            per_actor.setdefault(actor_of_op[op.index], []).append(desc)

        # dunder methods bypass ActorHandle.__getattr__ (it rejects
        # underscore names); construct the ActorMethod directly — the
        # call still rides the actor's ordered pooled pipe
        from ray_tpu.actor import ActorMethod
        install_refs = []
        for aid, ops in per_actor.items():
            payload = {"dag_id": self.dag_id, "name": self.name,
                       "ops": ops, "event_cap": EXEC_EVENT_CAP,
                       "threaded_ops": self._threaded_ops,
                       # lets the resident loop watch for this driver's
                       # death and unwind instead of leaking forever on
                       # detached actors
                       "job_id": self._worker.job_id.hex()}
            handle = self._actors[aid]
            install_refs.append(
                (aid, ActorMethod(handle, "__ray_dag_install__")
                 .remote(payload)))
        try:
            for aid, ref in install_refs:
                self._worker.get([ref], timeout=60.0)
        except exc.RayTpuError as e:
            # full teardown, not just poisoning: releases the driver's
            # channel pins (else every failed compile strands
            # nchannels * nslots * capacity of un-evictable shm), stops
            # any loops that did install, and kills compile-created
            # actors
            self.teardown()
            raise DAGCompileError(
                f"installing the compiled loop on actor {aid[:8]} failed "
                f"(compiled DAGs require every actor on the driver's "
                f"node): {e}") from e

    # ------------------------------------------------------------- execute
    def execute(self, *input_values,
                timeout: Optional[float] = None) -> CompiledDAGRef:
        """Run the graph once with ``input_values[0]`` (or None): write
        the input into its channel and return a ref for the output.
        Blocks (backpressure) while ``max_inflight`` executions are
        outstanding."""
        if len(input_values) > 1:
            raise TypeError(
                "compiled DAGs take a single input value; pack multiple "
                "values into a tuple/dict")
        value = input_values[0] if input_values else None
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            drain_here = False
            with self._cv:
                self._raise_if_unavailable()
                if self._inflight < self._max_inflight:
                    idx = self._next_idx
                    self._next_idx += 1
                    self._inflight += 1
                    _M_DAG_INFLIGHT.set_max(self._inflight)
                    self._t0[idx] = rtm.now()
                    # the ring is sized to max_inflight, so with the
                    # inflight window held this write never blocks long;
                    # serialize inside the lock to keep ring order ==
                    # idx order
                    try:
                        self._input_writer.write(
                            value,
                            timeout=(None if deadline is None else
                                     max(0.1, deadline - time.monotonic())))
                    except ChannelClosedError as e:
                        self._inflight -= 1
                        raise self._fail_locked(
                            DAGUnavailableError(str(e))) from e
                    except ChannelTimeoutError as e:
                        # can't happen while the inflight window holds
                        # (the ring is sized to it) unless the graph is
                        # wedged; release the window slot we claimed
                        self._inflight -= 1
                        self._next_idx -= 1
                        self._t0.pop(idx, None)
                        raise exc.GetTimeoutError(str(e)) from e
                    except Exception:
                        # serialization failure (non-picklable input,
                        # payload over the slot capacity): nothing was
                        # published, so roll the claimed slot back — a
                        # leaked idx would permanently shift drain
                        # accounting and wedge the window.  Safe because
                        # _cv is held from claim to here, so no later
                        # idx exists yet.
                        self._inflight -= 1
                        self._next_idx -= 1
                        self._t0.pop(idx, None)
                        raise
                    break
                # window full (backpressure): pump the output channel
                # ourselves — a single-threaded submit loop must not
                # deadlock waiting for a get() that comes later; drained
                # results buffer in _results until their ref collects them
                if self._draining:
                    self._cv.wait(0.1)
                else:
                    self._draining = True
                    drain_here = True
            if drain_here:
                try:
                    self._drain_one(deadline)
                finally:
                    with self._cv:
                        self._draining = False
                        self._cv.notify_all()
            elif deadline is not None and time.monotonic() >= deadline:
                raise exc.GetTimeoutError(
                    f"execute() timed out with {self._inflight} executions "
                    f"in flight (max_inflight={self._max_inflight})")
        if idx < EXEC_EVENT_CAP:
            self._worker.events.record(
                _exec_task_id(self.dag_id, idx), "SUBMITTED",
                name=f"dag:{self.name}",
                trace_id=_exec_trace_id(self.dag_id, idx))
        return CompiledDAGRef(self, idx)

    # ------------------------------------------------------- result drain
    def _wait_result(self, idx: int, timeout: Optional[float]) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._cv:
                if idx in self._results:
                    return self._results.pop(idx)
                if self._dead is not None:
                    raise DAGUnavailableError(str(self._dead))
                if self._torn_down:
                    # teardown released the channel views; draining
                    # would touch freed memory
                    raise DAGUnavailableError(
                        f"compiled DAG {self.name} was torn down before "
                        f"execution {idx} was retrieved; recompile")
                if self._draining:
                    # another getter is pumping the output channel
                    self._cv.wait(0.1)
                    if deadline is not None and \
                            time.monotonic() >= deadline and \
                            idx not in self._results:
                        raise exc.GetTimeoutError(
                            f"compiled DAG execution {idx} not ready "
                            f"within the timeout")
                    continue
                self._draining = True
            try:
                self._drain_one(deadline)
            finally:
                with self._cv:
                    self._draining = False
                    self._cv.notify_all()

    def _drain_one(self, deadline: Optional[float]) -> None:
        """Read the next output item (execution order) into _results,
        interleaving actor-liveness checks so a mid-execution worker
        death surfaces as DAGUnavailableError instead of a hang."""
        while True:
            try:
                # clamp the poll slice to the caller's deadline so a
                # small get(timeout=) raises promptly instead of
                # overshooting by a full slice (or a liveness RPC)
                slice_s = 0.25
                if deadline is not None:
                    slice_s = min(slice_s, deadline - time.monotonic())
                    if slice_s <= 0:
                        raise exc.GetTimeoutError(
                            "timed out waiting for a compiled DAG result")
                payload, _flags = self._out_reader.read_raw(
                    timeout=slice_s)
                break
            except ChannelTimeoutError:
                if deadline is not None and time.monotonic() >= deadline:
                    raise exc.GetTimeoutError(
                        "timed out waiting for a compiled DAG result")
                self._check_liveness()
            except ChannelClosedError as e:
                with self._cv:
                    raise self._fail_locked(DAGUnavailableError(str(e)))
            except ValueError as e:
                # released channel view: teardown() gave up waiting for
                # this drain (e.g. it was parked in a slow liveness RPC)
                # and freed the channels under it
                with self._cv:
                    raise self._fail_locked(DAGUnavailableError(
                        f"compiled DAG {self.name} was torn down while a "
                        f"result drain was in flight")) from e
        try:
            value = ser.deserialize(payload)
        except Exception as e:  # noqa: BLE001 - error items re-raise here
            value = _ErrorResult(e)
        with self._cv:
            idx = self._drained_idx
            self._drained_idx += 1
            self._inflight -= 1
            if idx in self._abandoned:
                self._abandoned.discard(idx)   # ref dropped: no taker
            else:
                self._results[idx] = value
            t0 = self._t0.pop(idx, None)
            self._cv.notify_all()
        if t0 is not None:
            _M_DAG_EXEC.observe_since(self.name, t0)
        if idx < EXEC_EVENT_CAP:
            failed = isinstance(value, _ErrorResult)
            self._worker.events.record(
                _exec_task_id(self.dag_id, idx),
                "FAILED" if failed else "FINISHED",
                name=f"dag:{self.name}",
                trace_id=_exec_trace_id(self.dag_id, idx))

    def _abandon(self, idx: int) -> None:
        """A CompiledDAGRef was garbage-collected without get(): drop
        its buffered result, or mark the idx so the drain discards it.
        (Safe from __del__: the condition's lock is reentrant.)"""
        with self._cv:
            if self._results.pop(idx, None) is None and \
                    idx >= self._drained_idx:
                self._abandoned.add(idx)

    # ------------------------------------------------------------- failure
    def _raise_if_unavailable(self) -> None:
        if self._torn_down:
            raise DAGUnavailableError(
                f"compiled DAG {self.name} was torn down; recompile")
        if self._dead is not None:
            raise DAGUnavailableError(str(self._dead))

    def _fail_locked(self, error: BaseException) -> BaseException:
        """cv held: mark the DAG dead, poison every channel so blocked
        actor loops (and other driver threads) unwind."""
        if self._dead is None:
            self._dead = error
            for ch in self._channels:
                try:
                    ch.poison(POISON_WORKER_DIED)
                except Exception:
                    pass
        self._cv.notify_all()
        return error

    def _check_liveness(self) -> None:
        now = time.monotonic()
        if now - self._last_liveness < _LIVENESS_PERIOD_S:
            return
        self._last_liveness = now
        from ray_tpu.runtime.gcs import DEAD, RESTARTING
        for aid in self._actors:
            try:
                info = self._worker.gcs.call("get_actor",
                                             {"actor_id": aid}, timeout=5)
            except Exception:
                return      # GCS hiccup: keep waiting, not a death verdict
            # RESTARTING counts as lost too: the replacement worker has
            # no resident loop installed, so the compiled graph can
            # never complete — only a recompile restores it
            if info is None or info.get("state") in (DEAD, RESTARTING):
                with self._cv:
                    raise self._fail_locked(DAGUnavailableError(
                        f"actor {aid[:8]} participating in compiled DAG "
                        f"{self.name} died mid-execution; recompile to "
                        f"restore the graph"))

    # ------------------------------------------------------------ teardown
    def _teardown_channels(self, code: int) -> None:
        for ch in self._channels:
            try:
                ch.poison(code)
            except Exception:
                pass

    def teardown(self, kill_actors: Optional[bool] = None) -> None:
        """Stop the resident loops, free the channels, and (for actors
        this compile itself created from ClassNodes) kill the actors.
        Idempotent."""
        with self._cv:
            if self._torn_down:
                return
            self._torn_down = True
            self._cv.notify_all()
        self._teardown_channels(POISON_TEARDOWN)
        # let an in-flight drain unwind off the poisoned channels before
        # the views are released below — read_raw on a released
        # memoryview would crash instead of raising DAGUnavailableError
        with self._cv:
            deadline = time.monotonic() + 5.0
            while self._draining and time.monotonic() < deadline:
                self._cv.wait(0.1)
        from ray_tpu.actor import ActorMethod
        for aid, handle in self._actors.items():
            try:
                ref = ActorMethod(handle, "__ray_dag_teardown__").remote(
                    {"dag_id": self.dag_id})
                self._worker.get([ref], timeout=10.0)
            except Exception:
                pass            # dead/unreachable actor: poison suffices
        kill = self._created_actor_ids if kill_actors is None else (
            list(self._actors) if kill_actors else [])
        for aid in kill:
            try:
                self._worker.kill_actor(
                    self._actors[aid]._actor_id)
            except Exception:
                pass
        for ch in self._channels:
            ch.close()
            ch.delete()
        self._channels = []

    def __del__(self):
        try:
            if not self._torn_down and not self._worker._shutdown.is_set():
                self.teardown()
        except Exception:
            pass

    def __repr__(self):
        return (f"CompiledDAG({self.name}, ops={len(self._ops)}, "
                f"actors={len(self._actors)}, "
                f"max_inflight={self._max_inflight})")
