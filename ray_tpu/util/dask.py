"""Dask-on-ray_tpu scheduler: execute dask graphs as ray_tpu tasks.

Analog of /root/reference/python/ray/util/dask/scheduler.py
(``ray_dask_get``): a drop-in value for dask's ``scheduler=`` argument.
Dask task graphs are plain dicts (``{key: (fn, *args)}`` with keys
referencing other keys), so the SCHEDULER needs no dask import at all —
each graph task becomes one ``ray_tpu`` task whose ObjectRef feeds its
dependents, giving dask collections distributed execution, object-store
spilling, and lineage reconstruction for free.

With dask installed:  ``dask.compute(df, scheduler=ray_dask_get)``.
Without dask (this image): the executor is fully testable against
hand-written graphs in dask's documented tuple format
(tests/test_util_shims.py).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List

import ray_tpu

# dask task convention: a task is a tuple whose head is callable; a key
# reference is a (hashable) graph key; literals pass through.


def _is_task(x: Any) -> bool:
    return isinstance(x, tuple) and bool(x) and callable(x[0])


def _toposort(dsk: Dict) -> List:
    """Graph keys in dependency order (raises on cycles)."""
    deps = {k: _find_deps(v, dsk) for k, v in dsk.items()}
    out: List = []
    state = {}                   # key -> 1 visiting, 2 done

    def visit(k, stack):
        s = state.get(k)
        if s == 2:
            return
        if s == 1:
            raise ValueError(f"dask graph cycle through {k!r}")
        state[k] = 1
        for d in deps[k]:
            visit(d, stack)
        state[k] = 2
        out.append(k)

    for k in dsk:
        visit(k, [])
    return out


def _find_deps(v: Any, dsk: Dict) -> List:
    found: List = []

    def walk(x):
        if _is_task(x):
            for item in x[1:]:
                walk(item)
        elif isinstance(x, list):
            for item in x:
                walk(item)
        elif isinstance(x, dict):
            for item in x.values():
                walk(item)
        else:
            try:
                if x in dsk:
                    found.append(x)
            except TypeError:
                pass             # unhashable literal
    walk(v)
    return found


@ray_tpu.remote
def _dask_task(blob, *dep_values):
    """One graph task: rebuild the (possibly nested) call spec and
    evaluate it.  Dependencies ride as TOP-LEVEL ObjectRef args — the
    runtime resolves those to values before execution (nested refs
    would arrive unresolved, matching ray semantics)."""
    import cloudpickle
    spec = cloudpickle.loads(blob)

    def ev(x):
        if isinstance(x, _Dep):
            return dep_values[x.index]
        if _is_task(x):
            return x[0](*[ev(a) for a in x[1:]])
        if isinstance(x, list):
            return [ev(a) for a in x]
        if isinstance(x, dict):
            return {k: ev(v) for k, v in x.items()}
        return x
    return ev(spec)


class _Dep:
    """Placeholder for a graph-key reference inside a pickled spec."""

    def __init__(self, index: int):
        self.index = index


def _substitute(v: Any, dsk: Dict, dep_keys: List) -> Any:
    """Replace graph-key references with _Dep placeholders, recording
    the referenced keys in order (their ObjectRefs ride as a list arg,
    so the runtime stages/fetches them before the task runs)."""
    if _is_task(v):
        return tuple([v[0]] + [_substitute(a, dsk, dep_keys)
                               for a in v[1:]])
    if isinstance(v, list):
        return [_substitute(a, dsk, dep_keys) for a in v]
    if isinstance(v, dict):
        return {k: _substitute(a, dsk, dep_keys) for k, a in v.items()}
    try:
        if v in dsk:
            dep_keys.append(v)
            return _Dep(len(dep_keys) - 1)
    except TypeError:
        pass
    return v


def ray_dask_get(dsk: Dict, keys, **kwargs) -> Any:
    """Execute a dask graph on the cluster; pass as dask ``scheduler=``.

    ``keys`` may be a single key, a list, or nested lists (dask's
    convention for collections with partitions)."""
    import cloudpickle

    refs: Dict[Any, Any] = {}
    for k in _toposort(dsk):
        v = dsk[k]
        dep_keys: List = []
        spec = _substitute(v, dsk, dep_keys)
        if isinstance(spec, _Dep):          # pure alias: 'a': 'b'
            refs[k] = refs[dep_keys[0]]
            continue
        if not (_is_task(v) or isinstance(v, (list, dict))) \
                and not dep_keys:
            refs[k] = ray_tpu.put(v)        # literal node
            continue
        blob = cloudpickle.dumps(spec)
        refs[k] = _dask_task.remote(
            blob, *[refs[d] for d in dep_keys])

    def fetch(ks):
        if isinstance(ks, list):
            return [fetch(x) for x in ks]
        return ray_tpu.get(refs[ks])

    return fetch(keys if isinstance(keys, list) else [keys])[0] \
        if not isinstance(keys, list) else fetch(keys)


def enable_dask_on_ray() -> None:
    """Set ray_dask_get as dask's default scheduler (needs dask)."""
    import dask
    dask.config.set(scheduler=ray_dask_get)
