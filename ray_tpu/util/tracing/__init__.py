"""Distributed tracing hooks (SURVEY.md §5 tracing row)."""

from ray_tpu.util.tracing.tracing_helper import (  # noqa: F401
    span, get_trace_context, propagate_trace_context)

__all__ = ["span", "get_trace_context", "propagate_trace_context"]
