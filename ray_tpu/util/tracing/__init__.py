"""Distributed request tracing plane (docs/observability.md)."""

from ray_tpu.util.tracing.tracing_helper import (  # noqa: F401
    span, get_trace_context, propagate_trace_context, open_span,
    serve_ingress_root, finish_request, sampled, enabled)

__all__ = ["span", "get_trace_context", "propagate_trace_context",
           "open_span", "serve_ingress_root", "finish_request",
           "sampled", "enabled"]
