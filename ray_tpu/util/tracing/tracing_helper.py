"""Distributed request tracing plane (docs/observability.md).

The runtime's fifth observability plane: metrics say how fast, events
say what happened, the timeline shows each subsystem's slices, step
stats clock training — this module follows ONE request across process
boundaries.  A serve request traverses proxy -> router -> prefill
replica -> paged-KV handoff over the transfer plane -> decode replica;
each hop records a span carrying the same ``trace_id``, parent/child
linked, batched off the hot path into the GCS span table, so a p99 TTFT
regression points at a concrete trace whose spans show which hop (queue
wait, prefill, handoff pull, import wait, decode) ate the budget.

Pieces:

* **Context** — a ContextVar dict ``{trace_id, span_id, sampled}``.
  A ContextVar, not a thread-local: async-actor calls interleave on one
  event-loop thread and each asyncio Task must keep its own trace
  identity.  The context rides task specs (``spec["trace_ctx"]``,
  stamped at submission in core_worker.py), streaming-generator report
  RPCs (the reserved ``_trace_ctx`` payload key rpc.py installs around
  dispatch), and transfer-plane pulls.

* **Deterministic sampler** — ``sampled(trace_id)`` hashes the id's
  first 8 hex chars against ``CONFIG.trace_sample_rate``: a pure
  function of the id, so every process reaches the SAME decision with
  no coordination and no sampling flag can desync from its trace.
  Serve ingresses always open a root context (SLO accounting needs
  every request classified); span *recording* follows the sampler.
  Task/actor submissions with no active context draw one 32-bit random
  and only materialize a trace when it clears the rate — the unsampled
  hot-path cost is one ``getrandbits`` + compare.

* **SpanBuffer** — per-process bounded recorder + flusher thread
  (the step-stats/events flusher discipline: never an RPC on the hot
  path; sink failures re-queue bounded to one buffer's worth).
  Bound by ``CoreWorker.__init__`` like the event recorder.

* **GcsSpanTable** — trace-indexed span store, sharded like the event
  table, retention bounded by BOTH ``gcs_max_traces`` and a
  ``gcs_traces_max_bytes`` JSON-size budget plus a per-trace span cap.
  Root spans carry serve SLO fields (ttft/tpot vs targets) and an
  optional crash ``dossier_id`` cross-link (the table annotates the
  dossier with the trace id in return).  Queryable via
  ``experimental.state.list_traces()/get_trace()``, ``ray-tpu trace``/
  ``ray-tpu traces --slo-violations``, dashboard ``/api/traces``.

* **SLO accounting** — ``finish_request()`` classifies a completed
  serve request against ``CONFIG.serve_slo_ttft_ms`` /
  ``serve_slo_tpot_ms``, publishes
  ``ray_tpu_serve_slo_good/violation{pool,slo}`` counters (always, not
  just for sampled requests) and stamps the verdict + exemplar ids on
  the root span.

Kill switch: ``RAY_TPU_TRACING=0`` (or ``CONFIG.tracing_enabled=False``)
mirrors RAY_TPU_TELEMETRY / RAY_TPU_EVENTS — roots/spans degrade to
no-ops after one cached flag read, nothing is buffered or shipped.

User-level ``span()`` predates the plane (reference analog
/root/reference/python/ray/util/tracing/tracing_helper.py) and keeps
its contract: it always records a task-event slice for the timeline
(sampling governs only the span-table copy) and mirrors name,
attributes and error status onto an OpenTelemetry span when
opentelemetry happens to be importable.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional

from ray_tpu._private.config import CONFIG

try:  # pragma: no cover - image does not bundle opentelemetry
    from opentelemetry import trace as _otel_trace
    from opentelemetry.trace import Status as _OtelStatus
    from opentelemetry.trace import StatusCode as _OtelStatusCode
    _tracer = _otel_trace.get_tracer("ray_tpu")
except ImportError:
    _otel_trace = None
    _OtelStatus = None
    _OtelStatusCode = None
    _tracer = None

# a ContextVar, not threading.local: async-actor calls interleave on one
# event-loop thread, and each asyncio Task must keep its own trace context
# (a thread-local would let concurrent calls clobber each other's ids)
_ctx_var: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None)

# span status values
OK = "ok"
ERROR = "error"
# client walked away (disconnect, early close): neither an SLO success
# nor a service failure — excluded from both counters
CANCELLED = "cancelled"


def enabled() -> bool:
    """Kill switch: RAY_TPU_TRACING env wins, then the config flag."""
    raw = os.environ.get("RAY_TPU_TRACING")
    if raw is not None:
        return raw.strip().lower() not in ("0", "false", "no", "off")
    return CONFIG.tracing_enabled


# enabled() + the sampler threshold are read on every task submission:
# cache them keyed on the CONFIG override generation (the rpc._maybe_fuzz
# idiom) so the hot path pays a tuple compare, not an env read + lock
_flag_cache = (-1, False, 0)


def _flags() -> tuple:
    global _flag_cache
    gen = CONFIG.generation()
    cached = _flag_cache
    if cached[0] != gen:
        rate = min(1.0, max(0.0, CONFIG.trace_sample_rate))
        cached = (gen, enabled(), int(rate * 0x100000000))
        _flag_cache = cached
    return cached


def sampled(trace_id: str) -> bool:
    """Deterministic trace-id-hash sampling decision: a pure function of
    the id and the configured rate, so every process that sees this
    trace reaches the same verdict independently."""
    _gen, on, threshold = _flags()
    if not on:
        return False
    try:
        return int(trace_id[:8], 16) < threshold
    except (ValueError, TypeError):
        return False


# ids come from a Mersenne generator, not uuid4: uuid4 costs ~2us in
# isolation and 5-10us inside the live submit loop (os.urandom syscall +
# object churn), and a sampled task mints 3-4 ids across driver+worker —
# that alone was most of the plane's measured per-task cost.  Trace ids
# are correlation keys, not secrets; 128 random bits from MT are as
# collision-proof as uuid4's.  A module-LOCAL Random reseeded after
# fork, NOT the global generator: workers fork from a warm zygote
# (runtime/worker_zygote.py) with byte-identical RNG state, and without
# the reseed two workers would mint the SAME trace/span ids and merge
# unrelated requests into one trace record.
_id_rng = random.Random()
_rand = _id_rng.getrandbits
if hasattr(os, "register_at_fork"):  # pragma: no branch
    os.register_at_fork(after_in_child=_id_rng.seed)  # reseeds from urandom


def new_trace_id() -> str:
    return f"{_rand(128):032x}"


def new_span_id() -> str:
    return f"{_rand(64):016x}"


# ------------------------------------------------------------------ context
def get_trace_context() -> Dict[str, Any]:
    """Current trace/span ids, for propagation into submitted tasks."""
    ctx = _ctx_var.get()
    return dict(ctx) if ctx else {}


def current_context() -> Optional[dict]:
    """The raw context dict (no copy) — hot-path read for submitters."""
    return _ctx_var.get()


def propagate_trace_context(ctx: Optional[Dict[str, Any]]) -> None:
    """Install a parent context received with a task."""
    _ctx_var.set(dict(ctx) if ctx else None)


def install(ctx: Optional[dict]):
    """Set the context and return a token for ``uninstall`` (scoped
    installation around a routing/submit section)."""
    return _ctx_var.set(dict(ctx) if ctx else None)


def uninstall(token) -> None:
    _ctx_var.reset(token)


def bind_ctx(ctx: Optional[dict], fn: Callable, *args, **kwargs):
    """Wrap ``fn`` so it runs with ``ctx`` installed — for executor hops
    (``loop.run_in_executor`` does not carry ContextVars), where the
    serve layer moves blocking routing/pull work off the event loop."""
    def _run():
        token = _ctx_var.set(dict(ctx) if ctx else None)
        try:
            return fn(*args, **kwargs)
        finally:
            _ctx_var.reset(token)
    return _run


def maybe_sample_root() -> Optional[dict]:
    """Sampling gate for task/actor submission with no active context
    (core_worker.py): draw one 32-bit random; only when it clears the
    rate does a trace id materialize (its first 8 hex chars ARE the
    draw, so ``sampled()`` re-derives the same verdict anywhere)."""
    _gen, on, threshold = _flags()
    if not on or threshold <= 0:
        return None
    r = _rand(32)
    if r >= threshold:
        return None
    trace_id = f"{r:08x}{_rand(96):024x}"
    return {"trace_id": trace_id, "span_id": new_span_id(),
            "sampled": True}


def ctx_sampled(ctx: Optional[dict]) -> bool:
    """Is this context's trace being recorded?  Trusts the propagated
    flag when present (saves the hash), else re-derives from the id."""
    if not ctx:
        return False
    s = ctx.get("sampled")
    if s is None:
        return sampled(ctx.get("trace_id", ""))
    return bool(s)


# -------------------------------------------------------------------- spans
class Span:
    """One open span: fixed identity at open, attributes at end.

    ``end()`` records into the process's span buffer (no-op when the
    trace is unsampled or the plane is off) — never an RPC."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "kind",
                 "start", "_t0", "sampled", "attrs", "_ended")

    def __init__(self, name: str, kind: str = "user", *,
                 ctx: Optional[dict] = None, root: bool = False,
                 attrs: Optional[dict] = None):
        if root or not ctx:
            self.trace_id = (ctx or {}).get("trace_id") or new_trace_id()
            self.parent_id = (ctx or {}).get("span_id")
        else:
            self.trace_id = ctx["trace_id"]
            self.parent_id = ctx.get("span_id")
        self.span_id = new_span_id()
        self.name = name
        self.kind = kind
        self.start = time.time()
        self._t0 = time.perf_counter()
        self.sampled = ctx_sampled(ctx) if ctx else sampled(self.trace_id)
        self.attrs = dict(attrs) if attrs else None
        self._ended = False

    def ctx(self) -> dict:
        """The context children of this span should inherit."""
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "sampled": self.sampled}

    def set_attr(self, key: str, value: Any) -> None:
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    def end(self, status: str = OK, *, dur_ms: Optional[float] = None,
            **fields: Any) -> None:
        """Close and record.  Extra ``fields`` land as top-level span
        fields (root/SLO/dossier stamps); user attributes stay under
        ``attrs``.  Idempotent — a double end records once."""
        if self._ended or not self.sampled:
            self._ended = True
            return
        self._ended = True
        span = {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "name": self.name, "kind": self.kind, "start": self.start,
            "dur_ms": round(dur_ms if dur_ms is not None else
                            (time.perf_counter() - self._t0) * 1e3, 3),
            "status": status,
        }
        if self.parent_id:
            span["parent_id"] = self.parent_id
        if self.attrs:
            span["attrs"] = self.attrs
        for k, v in fields.items():
            if v is not None:
                span[k] = v
        record_span(span)


def open_span(name: str, kind: str = "user", *,
              ctx: Optional[dict] = None) -> Optional[Span]:
    """A child span of ``ctx`` (default: the current context) — or None
    when the trace is unsampled, so call sites stay one ``if`` cheap."""
    if ctx is None:
        ctx = _ctx_var.get()
    if not ctx_sampled(ctx):
        return None
    return Span(name, kind, ctx=ctx)


def instant_span(name: str, kind: str, *, ctx: Optional[dict] = None,
                 dur_ms: float = 0.0, **fields: Any) -> None:
    """Marker span recorded after the fact: zero duration by default
    (streaming per-yield items), or backdated by ``dur_ms`` for work
    whose cost was measured out-of-band (handoff export legs)."""
    sp = open_span(name, kind, ctx=ctx)
    if sp is not None:
        if dur_ms:
            sp.start -= dur_ms / 1e3
        sp.end(dur_ms=dur_ms, **fields)


# ------------------------------------------------------- per-process buffer
class SpanBuffer:
    """Bounded per-process span recorder + GCS flusher (the
    cluster-events flusher discipline: record() is one deque append
    under a short lock; the flusher batches to the sink; a sink failure
    re-queues bounded to one buffer's worth)."""

    def __init__(self, sink: Callable[[List[dict]], Any], *,
                 node_id: str = "", worker_id: str = "",
                 source: str = ""):
        self._sink = sink
        self._cap = max(64, CONFIG.trace_buffer_size)
        # stamped onto every span at record time (the EventRecorder
        # defaults idiom): which process/node a hop ran on is exactly
        # what a cross-process trace is for
        self._defaults = {k: v for k, v in
                          (("node_id", node_id), ("worker_id", worker_id),
                           ("source", source)) if v}
        self._unflushed: List[dict] = []
        self._dropped = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def record(self, span: dict) -> None:
        for k, v in self._defaults.items():
            span.setdefault(k, v)
        with self._lock:
            if len(self._unflushed) >= self._cap:
                self._dropped += 1
                return
            self._unflushed.append(span)
            if self._thread is None and not self._stop.is_set():
                self._thread = threading.Thread(
                    target=self._flush_loop, daemon=True,
                    name="trace-spans-flush")
                self._thread.start()

    def flush(self) -> None:
        with self._lock:
            batch, self._unflushed = self._unflushed, []
        if not batch:
            return
        try:
            self._sink(batch)
        except Exception:
            with self._lock:
                self._unflushed = (batch + self._unflushed)[-self._cap:]

    def _flush_loop(self) -> None:
        period = max(0.05, CONFIG.trace_flush_interval_ms / 1000.0)
        while not self._stop.wait(period):
            self.flush()
        self.flush()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=2.0)
        self.flush()


_buffer: Optional[SpanBuffer] = None
_buf_lock = threading.Lock()


def configure(sink: Optional[Callable[[List[dict]], Any]], *,
              node_id: str = "", worker_id: str = "",
              source: str = "") -> Optional[SpanBuffer]:
    """Bind this process's span buffer (CoreWorker.__init__, mirroring
    cluster_events.configure).  No-op returning None when disabled."""
    global _buffer, _flag_cache
    _flag_cache = (-1, False, 0)   # re-read env/config on rebind
    with _buf_lock:
        old, _buffer = _buffer, None
    if old is not None:
        old.stop()
    # raylint: disable=kill-switch -- configure() runs once per init(); span hot paths read the _flags() generation cache
    if sink is None or not enabled():
        return None
    buf = SpanBuffer(sink, node_id=node_id, worker_id=worker_id,
                     source=source)
    with _buf_lock:
        _buffer = buf
    return buf


def detach(buf: Optional[SpanBuffer] = None) -> None:
    """Unbind at owner shutdown; with ``buf`` given, only if it is still
    the active buffer (a newer owner's configure survives)."""
    global _buffer
    with _buf_lock:
        if buf is None or _buffer is buf:
            old, _buffer = _buffer, None
        else:
            old = None
    if old is not None:
        old.stop()


def record_span(span: dict) -> None:
    """Record one finished span (dropped when no buffer is bound)."""
    buf = _buffer
    if buf is not None:
        buf.record(span)


def flush_now() -> None:
    """Synchronous flush (tests / clean shutdown)."""
    buf = _buffer
    if buf is not None:
        buf.flush()


# -------------------------------------------------- serve ingress + SLO
def _slo_counters():
    # lazy: runtime_metrics import at module load would freeze the
    # kill-switch decision before the driver's env overrides land
    global _SLO_GOOD, _SLO_VIOL
    if _SLO_GOOD is None:
        from ray_tpu._private import runtime_metrics as rtm
        _SLO_GOOD = rtm.counter_family(
            "ray_tpu_serve_slo_good",
            "serve requests that met the SLO dimension",
            tag_keys=("pool", "slo"))
        _SLO_VIOL = rtm.counter_family(
            "ray_tpu_serve_slo_violation",
            "serve requests that violated the SLO dimension",
            tag_keys=("pool", "slo"))
    return _SLO_GOOD, _SLO_VIOL


_SLO_GOOD = None
_SLO_VIOL = None


def serve_ingress_root(name: str, *, route: str = "",
                       attrs: Optional[dict] = None) -> Optional[Span]:
    """Open a request root at a serve ingress (http proxy, deployment /
    disagg handle drivers).  Every request gets a root context (SLO
    accounting classifies all of them); whether its spans are recorded
    follows the deterministic sampler.  Returns None when the plane is
    off — callers guard with one ``if``."""
    _gen, on, _thr = _flags()
    if not on:
        return None
    sp = Span(name, "ingress", attrs=attrs)
    if route:
        sp.set_attr("route", route)
    return sp


def finish_request(root: Optional[Span], *, pool: str, route: str = "",
                   status: str = OK, ttft_s: Optional[float] = None,
                   tpot_s: Optional[float] = None,
                   num_tokens: Optional[int] = None,
                   dossier_id: Optional[str] = None,
                   error_type: Optional[str] = None) -> None:
    """Classify one completed serve request against the TTFT/TPOT
    targets, publish the SLO counters (every request — sampling only
    gates the span-table exemplar), and close the root span with the
    verdict so ``ray-tpu traces --slo-violations`` can point at it."""
    if root is None:
        return
    if not route:
        route = (root.attrs or {}).get("route", "")
    ttft_ms = None if ttft_s is None else ttft_s * 1e3
    tpot_ms = None if tpot_s is None else tpot_s * 1e3
    violated: List[str] = []
    slo_ok = None
    if status == OK:
        # only COMPLETED requests are latency-classified: an errored
        # request that died in 5ms must not count as "SLO good" — it
        # stays visible via status=error, the error counter dimension
        # and list_traces(status="error")
        good, viol = _slo_counters()
        if ttft_ms is not None:
            target = CONFIG.serve_slo_ttft_ms
            if target > 0 and ttft_ms > target:
                violated.append("ttft")
                viol.inc((pool, "ttft"))
            else:
                good.inc((pool, "ttft"))
        if tpot_ms is not None:
            target = CONFIG.serve_slo_tpot_ms
            if target > 0 and tpot_ms > target:
                violated.append("tpot")
                viol.inc((pool, "tpot"))
            else:
                good.inc((pool, "tpot"))
        if ttft_ms is not None or tpot_ms is not None:
            slo_ok = not violated
    elif status == ERROR:
        _good, viol = _slo_counters()
        viol.inc((pool, "error"))
    # CANCELLED: the client walked away — no counter either way, the
    # root still records with its status for list_traces(status=...)
    root.end(
        status, root=True, pool=pool, route=route or None,
        ttft_ms=None if ttft_ms is None else round(ttft_ms, 3),
        tpot_ms=None if tpot_ms is None else round(tpot_ms, 3),
        num_tokens=num_tokens,
        slo_violated=violated or None, slo_ok=slo_ok,
        dossier_id=dossier_id, error_type=error_type)


# ------------------------------------------------------------- user spans
@contextlib.contextmanager
def span(name: str, attributes: Optional[Dict] = None) -> Iterator[None]:
    """Record a named span around a block of worker/driver code.

    Contract (pre-plane, kept): always records a ``span:<name>``
    task-event pair for the timeline and joins/roots the ContextVar
    trace.  Plane addition: when the trace is sampled, the span also
    lands in the span table; when opentelemetry is importable, the
    OTel twin carries the attributes and error status too (not just
    the name)."""
    parent = get_trace_context()
    trace_id = parent.get("trace_id") or new_trace_id()
    span_id = new_span_id()
    is_sampled = (parent.get("sampled") if "sampled" in parent
                  else sampled(trace_id))
    _ctx_var.set({"trace_id": trace_id, "span_id": span_id,
                  "sampled": bool(is_sampled)})
    start = time.time()
    t0 = time.perf_counter()
    otel_cm = _tracer.start_as_current_span(name) if _tracer else None
    otel_span = otel_cm.__enter__() if otel_cm else None
    if otel_span is not None and attributes:
        # mirror user attributes onto the OTel twin (stringify values
        # OTel's attribute model would reject)
        try:
            for k, v in attributes.items():
                otel_span.set_attribute(
                    str(k), v if isinstance(v, (bool, int, float, str))
                    else str(v))
        except Exception:
            pass
    exc_info = (None, None, None)
    try:
        yield
    except BaseException as e:
        # capture only exceptions raised from the span body — sys.exc_info()
        # in the finally would also report an outer in-flight exception when
        # the span runs inside an except handler
        exc_info = (type(e), e, e.__traceback__)
        raise
    finally:
        if otel_span is not None and exc_info[0] is not None:
            # error status + exception event on the OTel side (was:
            # dropped — only the context manager's default handling)
            try:
                otel_span.record_exception(exc_info[1])
                if _OtelStatus is not None:
                    otel_span.set_status(
                        _OtelStatus(_OtelStatusCode.ERROR,
                                    str(exc_info[1])))
            except Exception:
                pass
        if otel_cm:
            otel_cm.__exit__(*exc_info)
        _ctx_var.set(parent or None)
        end = time.time()
        failed = exc_info[0] is not None
        if is_sampled:
            rec = {"trace_id": trace_id, "span_id": span_id,
                   "name": f"span:{name}", "kind": "user",
                   "start": start,
                   "dur_ms": round((time.perf_counter() - t0) * 1e3, 3),
                   "status": ERROR if failed else OK}
            if parent.get("span_id"):
                rec["parent_id"] = parent["span_id"]
            if attributes:
                rec["attrs"] = dict(attributes)
            if failed:
                rec["error_type"] = exc_info[0].__name__
            record_span(rec)
        from ray_tpu.runtime import core_worker as cw
        worker = cw._global_worker
        if worker is not None:
            # user attributes go under a single "attrs" key so they can
            # never collide with the record's own fields
            worker.events.record(
                span_id, "RUNNING", name=f"span:{name}", ts=start,
                trace_id=trace_id, attrs=dict(attributes or {}))
            end_state = "FAILED" if failed else "FINISHED"
            worker.events.record(
                span_id, end_state, name=f"span:{name}", ts=end,
                trace_id=trace_id)


# --------------------------------------------------------- GCS span table
class GcsSpanTable:
    """Trace-indexed span store on the GCS.

    Sharded by trace id (a trace's spans must colocate for get_trace);
    retention bounded three ways — trace count (``gcs_max_traces``),
    table-wide JSON byte budget (``gcs_traces_max_bytes``) and a
    per-trace span cap (``gcs_trace_max_spans``, first/last halves
    survive like the task table's event cap).  Root spans index SLO
    verdicts and keep per-route violation counts + worst-TTFT exemplars
    that survive rotation.  ``on_dossier_link`` is called for root
    spans carrying a ``dossier_id`` so the GCS can stamp the trace id
    onto the dossier (the reverse cross-link)."""

    NSHARDS = 8
    _EXEMPLARS = 5

    def __init__(self, max_traces: Optional[int] = None,
                 max_bytes: Optional[int] = None,
                 on_dossier_link: Optional[Callable[[str, str], None]]
                 = None):
        self.max_traces = max_traces or CONFIG.gcs_max_traces
        self.max_bytes = max_bytes or CONFIG.gcs_traces_max_bytes
        self.max_spans = CONFIG.gcs_trace_max_spans
        self._on_dossier_link = on_dossier_link
        self._per_shard = max(2, self.max_traces // self.NSHARDS)
        self._bytes_per_shard = max(4096, self.max_bytes // self.NSHARDS)
        self._shards = [dict() for _ in range(self.NSHARDS)]
        self._orders = [deque() for _ in range(self.NSHARDS)]
        self._shard_bytes = [0] * self.NSHARDS
        self._locks = [threading.Lock() for _ in range(self.NSHARDS)]
        self._stats_lock = threading.Lock()
        self._traces_seen = 0
        self._ingress_seen = 0   # serve request roots only
        self._spans_seen = 0
        self._dropped_traces = 0
        # route -> {"good": n, "violation": n, "exemplars": [(ttft, id)]}
        self._slo: Dict[str, dict] = {}

    def _shard_of(self, trace_id: str) -> int:
        try:
            return int(trace_id[:8], 16) % self.NSHARDS
        except (ValueError, TypeError):
            return 0

    @staticmethod
    def _size_of(span: dict) -> int:
        import json
        try:
            return len(json.dumps(span, default=str))
        except (TypeError, ValueError):
            return 256

    def put(self, spans: List[dict]) -> int:
        """Merge one flusher batch; returns traces dropped by
        rotation."""
        dropped = 0
        links: List[tuple] = []
        for span in spans:
            if not isinstance(span, dict):
                continue
            tid = span.get("trace_id")
            if not tid or not span.get("span_id"):
                continue
            size = self._size_of(span)
            i = self._shard_of(tid)
            with self._locks[i]:
                shard, order = self._shards[i], self._orders[i]
                rec = shard.get(tid)
                fresh = rec is None
                if fresh:
                    rec = {"trace_id": tid, "start": span.get("start", 0),
                           "last_ts": 0.0, "spans": [], "nbytes": 0,
                           "root": None}
                    shard[tid] = rec
                    order.append(tid)
                rec["last_ts"] = time.time()
                rec["start"] = min(rec["start"] or span.get("start", 0),
                                   span.get("start", 0))
                rec["spans"].append(span)
                rec["nbytes"] += size
                self._shard_bytes[i] += size
                if span.get("root"):
                    rec["root"] = span
                if len(rec["spans"]) > self.max_spans:
                    half = self.max_spans // 2
                    for victim in rec["spans"][half:-half]:
                        cut = self._size_of(victim)
                        rec["nbytes"] -= cut
                        self._shard_bytes[i] -= cut
                    rec["spans"] = (rec["spans"][:half] +
                                    rec["spans"][-half:])
                    rec["truncated"] = True
                # rotation: count bound then byte budget, oldest first
                evicted = 0
                while (len(shard) > self._per_shard
                       or self._shard_bytes[i] > self._bytes_per_shard) \
                        and len(order) > 1:
                    victim = order.popleft()
                    vrec = shard.pop(victim, None)
                    if vrec is not None:
                        self._shard_bytes[i] -= vrec["nbytes"]
                        evicted += 1
                dropped += evicted
            with self._stats_lock:
                self._spans_seen += 1
                if fresh:
                    self._traces_seen += 1
                self._dropped_traces += evicted
            if span.get("root"):
                # only serve ingress roots feed the SLO route index:
                # task-submission roots (kind "submit") would add one
                # empty slot per unique task name, forever
                if span.get("kind") == "ingress":
                    self._index_root(span)
                    with self._stats_lock:
                        self._ingress_seen += 1
                did = span.get("dossier_id")
                if did and self._on_dossier_link is not None:
                    links.append((did, tid))
        # dossier cross-links outside the shard locks (the GCS callback
        # takes its own table lock)
        for did, tid in links:
            try:
                self._on_dossier_link(did, tid)
            except Exception:
                pass
        return dropped

    _MAX_SLO_ROUTES = 256

    def _index_root(self, span: dict) -> None:
        route = str(span.get("route") or span.get("name") or "?")
        with self._stats_lock:
            if route not in self._slo and \
                    len(self._slo) >= self._MAX_SLO_ROUTES:
                # bounded like the shards: a per-request route pattern
                # must not grow GCS memory without bound
                route = "__other__"
            slot = self._slo.setdefault(
                route, {"good": 0, "violation": 0,
                        "ttft_violation": 0, "tpot_violation": 0,
                        "exemplars": []})
            if span.get("slo_ok") is False:
                slot["violation"] += 1
                # per-dimension counts: the re-roling policy needs to
                # know WHICH budget a route is burning (ttft -> the
                # prefill pool is starved, tpot -> decode is)
                for dim in span.get("slo_violated") or ():
                    k = f"{dim}_violation"
                    if k in slot:
                        slot[k] += 1
            elif span.get("slo_ok") is True:
                slot["good"] += 1
            ttft = span.get("ttft_ms")
            if ttft is not None:
                ex = slot["exemplars"]
                ex.append((float(ttft), span["trace_id"]))
                ex.sort(key=lambda t: -t[0])
                del ex[self._EXEMPLARS:]

    def list(self, *, slo_violations: bool = False,
             route: Optional[str] = None, status: Optional[str] = None,
             since: Optional[float] = None,
             limit: int = 100) -> List[dict]:
        """Trace directory rows (no span bodies), newest first."""
        out = []
        for i in range(self.NSHARDS):
            with self._locks[i]:
                for rec in self._shards[i].values():
                    root = rec.get("root") or {}
                    if slo_violations and root.get("slo_ok") is not False:
                        continue
                    if route and not str(
                            root.get("route") or "").startswith(route):
                        continue
                    if status and root.get("status") != status:
                        continue
                    if since and rec.get("start", 0) < since:
                        continue
                    out.append({
                        "trace_id": rec["trace_id"],
                        "start": rec.get("start"),
                        "nspans": len(rec["spans"]),
                        "name": root.get("name", ""),
                        "route": root.get("route", ""),
                        "pool": root.get("pool", ""),
                        "status": root.get("status", ""),
                        "dur_ms": root.get("dur_ms"),
                        "ttft_ms": root.get("ttft_ms"),
                        "tpot_ms": root.get("tpot_ms"),
                        "slo_ok": root.get("slo_ok"),
                        "slo_violated": root.get("slo_violated"),
                        "dossier_id": root.get("dossier_id"),
                    })
        out.sort(key=lambda r: r.get("start") or 0, reverse=True)
        return out[:max(0, int(limit))]

    def get(self, trace_id: str) -> Optional[dict]:
        """Full trace by id (prefix match accepted), spans sorted by
        start time."""
        if not trace_id:
            return None
        i = self._shard_of(trace_id)
        with self._locks[i]:
            rec = self._shards[i].get(trace_id)
        if rec is None and len(trace_id) >= 6:
            for j in range(self.NSHARDS):
                with self._locks[j]:
                    for tid, cand in self._shards[j].items():
                        if tid.startswith(trace_id):
                            rec = cand
                            break
                if rec is not None:
                    break
        if rec is None:
            return None
        i = self._shard_of(rec["trace_id"])
        with self._locks[i]:
            out = dict(rec)
            out["spans"] = sorted(rec["spans"],
                                  key=lambda s: s.get("start", 0))
        return out

    def stats(self) -> dict:
        retained = sum(len(s) for s in self._shards)
        spans = 0
        for i in range(self.NSHARDS):
            with self._locks[i]:
                spans += sum(len(r["spans"])
                             for r in self._shards[i].values())
        with self._stats_lock:
            slo = {route: {"good": s["good"],
                           "violation": s["violation"],
                           "ttft_violation": s.get("ttft_violation", 0),
                           "tpot_violation": s.get("tpot_violation", 0),
                           "exemplars": [
                               {"ttft_ms": t, "trace_id": tid}
                               for t, tid in s["exemplars"]]}
                   for route, s in self._slo.items()}
            return {"traces": retained, "spans": spans,
                    "bytes": sum(self._shard_bytes),
                    "traces_seen": self._traces_seen,
                    "ingress_seen": self._ingress_seen,
                    "spans_seen": self._spans_seen,
                    "dropped_traces": self._dropped_traces,
                    "max_traces": self.max_traces,
                    "max_bytes": self.max_bytes,
                    "slo_by_route": slo}
