"""Span helper: OpenTelemetry when installed, task-event spans otherwise.

Analog of /root/reference/python/ray/util/tracing/tracing_helper.py
(_OpenTelemetryProxy :33, _inject_tracing_into_function :324). The
reference wraps every remote call in an OTel span and propagates context
in task metadata. Here the core already records every task transition in
the GCS task table (our timeline source), so this module adds *user-level*
spans: with `span("preprocess")`, the block is recorded as a task event
and — if opentelemetry happens to be importable — mirrored to a real OTel
span as well.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
import uuid
from typing import Dict, Iterator, Optional

try:  # pragma: no cover - image does not bundle opentelemetry
    from opentelemetry import trace as _otel_trace
    _tracer = _otel_trace.get_tracer("ray_tpu")
except ImportError:
    _otel_trace = None
    _tracer = None

# a ContextVar, not threading.local: async-actor calls interleave on one
# event-loop thread, and each asyncio Task must keep its own trace context
# (a thread-local would let concurrent calls clobber each other's ids)
_ctx_var: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None)


def get_trace_context() -> Dict[str, str]:
    """Current trace/span ids, for propagation into submitted tasks."""
    ctx = _ctx_var.get()
    return dict(ctx) if ctx else {}


def propagate_trace_context(ctx: Optional[Dict[str, str]]) -> None:
    """Install a parent context received with a task."""
    _ctx_var.set(dict(ctx) if ctx else None)


@contextlib.contextmanager
def span(name: str, attributes: Optional[Dict] = None) -> Iterator[None]:
    """Record a named span around a block of worker/driver code."""
    parent = get_trace_context()
    trace_id = parent.get("trace_id") or uuid.uuid4().hex
    span_id = uuid.uuid4().hex[:16]
    _ctx_var.set({"trace_id": trace_id, "span_id": span_id})
    start = time.time()
    otel_cm = _tracer.start_as_current_span(name) if _tracer else None
    if otel_cm:
        otel_cm.__enter__()
    exc_info = (None, None, None)
    try:
        yield
    except BaseException as e:
        # capture only exceptions raised from the span body — sys.exc_info()
        # in the finally would also report an outer in-flight exception when
        # the span runs inside an except handler
        exc_info = (type(e), e, e.__traceback__)
        raise
    finally:
        if otel_cm:
            otel_cm.__exit__(*exc_info)
        _ctx_var.set(parent or None)
        end = time.time()
        from ray_tpu.runtime import core_worker as cw
        worker = cw._global_worker
        if worker is not None:
            # user attributes go under a single "attrs" key so they can
            # never collide with the record's own fields
            worker.events.record(
                span_id, "RUNNING", name=f"span:{name}", ts=start,
                trace_id=trace_id, attrs=dict(attributes or {}))
            end_state = "FAILED" if exc_info[0] is not None else "FINISHED"
            worker.events.record(
                span_id, end_state, name=f"span:{name}", ts=end,
                trace_id=trace_id)
