"""Collective communication on top of the cluster runtime.

Analog of /root/reference/python/ray/util/collective/collective.py
(init_collective_group :120, allreduce :258, reduce/broadcast/allgather/
reducescatter/send/recv/barrier :311-615).

Two planes (SURVEY.md §5 "distributed communication backend"):

- **ICI (in-graph)**: the hot path. TPU collectives are XLA ops compiled
  into jitted programs via ``pjit``/``shard_map`` over a Mesh — see
  :mod:`ray_tpu.util.collective.ici` for imperative-looking wrappers.
- **DCN (host)**: a ring collective group over host TCP for control-plane
  and cross-slice traffic, replacing the reference's Gloo/NCCL groups.
"""

from ray_tpu.util.collective.collective import (  # noqa: F401
    AsyncWork, ReduceOp, allgather, allreduce, allreduce_async, barrier,
    broadcast, destroy_collective_group, get_rank,
    get_collective_group_size, init_collective_group,
    is_group_initialized, recv, reduce, reducescatter, register_ici_mesh,
    send, wait_all)

__all__ = [
    "ReduceOp", "init_collective_group", "destroy_collective_group",
    "is_group_initialized", "get_rank", "get_collective_group_size",
    "allreduce", "allreduce_async", "AsyncWork", "wait_all",
    "register_ici_mesh", "allgather", "reducescatter", "broadcast",
    "reduce", "send", "recv", "barrier",
]
