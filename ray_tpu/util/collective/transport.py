"""Segment transports for the DCN collective data plane.

Two ways a pair of ranks exchanges tensor segments
(docs/collective.md):

* **TCP pull links** — receiver-driven: the consumer issues a ``take``
  request on a pooled duplex connection (``rpc.call_async``) carrying a
  buffer sink, so the reply's out-of-band payload is ``recv_into``-ed
  straight into the consumer's accumulator/staging/output buffer.  The
  producer side parks unfulfilled takes as :class:`rpc.Deferred`\\ s on a
  :class:`ServeBoard`; ``publish()`` resolves them with **stable**
  pickle-5 out-of-band frames (zero defensive copy; the ``on_sent``
  hook tracks drain so an op never returns while a peer could still
  read its buffers off the wire).
* **shm links** — same-node ranks exchange segments over
  single-writer/single-reader ring channels
  (:mod:`ray_tpu.experimental.channel`) on the node's shared-memory
  store segment: a send is one memcpy into the ring (queued on a local
  outbox when the ring is full — writes never block the op thread),
  and a recv deserializes ZERO-COPY straight out of the ring slot
  (ack deferred until the view is consumed).

Both present the same three-verb interface to the algorithms in
``collective.py``::

    link.publish(tag, arr, deadline)       # make a segment available
    h = link.request(tag, dest)            # announce intent to consume
    arr, in_place = link.wait(h, deadline) # blocking segment arrival

``in_place`` is True when the payload already landed in ``dest``
(TCP buffer-sink hit); shm reads return a ring-slot view the caller
consumes (reduces / copies into place) before the next link op.

:class:`ShmArena` is the third plane: single-node groups allreduce
through persistent store slabs with no per-segment protocol at all
(docs/collective.md).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu._private import rpc
from ray_tpu._private import runtime_metrics as rtm
from ray_tpu._private import serialization as ser
from ray_tpu._private.config import CONFIG
from ray_tpu._private.logging_utils import get_logger

logger = get_logger("collective")

# data-plane telemetry (docs/collective.md / docs/observability.md).
# The tcp/shm byte counters are the transport-selection ground truth:
# a same-node-only group must leave the TCP counter at exactly zero.
# hot-path kill-switch binding, the rpc.py idiom: one enabled() read at
# import; record sites whose ARGUMENT computation isn't free guard on
# this instead of paying an env read + config lock per segment
_TELEMETRY = rtm.enabled()
_M_TCP_BYTES = rtm.counter(
    "ray_tpu_collective_tcp_bytes_total",
    "collective segment payload bytes moved over TCP links")
_M_SHM_BYTES = rtm.counter(
    "ray_tpu_collective_shm_bytes_total",
    "collective segment payload bytes moved over same-node shm channels")
_M_STALL = rtm.gauge(
    "ray_tpu_collective_ring_stall_ms",
    "high-water time a collective op blocked waiting for one segment "
    "since the last flush (ring stall)", watermark=True)
_M_STALL_H = rtm.histogram(
    "ray_tpu_collective_seg_wait_ms",
    "per-segment blocking wait inside a collective op (ms)")
# codec-tagged wire accounting (docs/collective.md): every segment the
# ring engines publish increments wire_bytes under its codec label
# ("fp32" for the unquantized plane), and quantized segments credit the
# fp32-equivalent-minus-wire difference to bytes_saved — the counters
# the MICROBENCH 2x claim and metrics_summary's Collective block read.
_M_WIRE_BYTES = rtm.counter_family(
    "ray_tpu_collective_wire_bytes",
    "collective ring segment bytes published, by wire codec",
    tag_keys=("codec",))
_M_BYTES_SAVED = rtm.counter(
    "ray_tpu_collective_bytes_saved_total",
    "wire bytes saved by collective quantization (fp32-equivalent "
    "payload minus encoded payload)")


def count_wire(codec_name: str, wire_nbytes: int,
               raw_nbytes: int) -> None:
    """Wire-accounting hook for the ring engines (one call per
    published segment).  When ``collective_sim_dcn_mbps`` > 0 it also
    paces the publisher to that bandwidth — a debug/benchmark knob (the
    ``object_spill_slow_ms`` injection precedent) that models a
    bytes-limited DCN link on boxes whose loopback "wire" is really
    CPU: the sleep is proportional to the ENCODED bytes, so a wire
    codec's saving shows up as exactly the wall time a real
    bandwidth-limited link would give back."""
    mbps = CONFIG.collective_sim_dcn_mbps
    if mbps > 0:
        time.sleep(wire_nbytes / (mbps * 2**20))
    if not _TELEMETRY:
        return
    _M_WIRE_BYTES.inc((codec_name,), wire_nbytes)
    if raw_nbytes > wire_nbytes:
        _M_BYTES_SAVED.inc(raw_nbytes - wire_nbytes)

# a single-segment wait past this emits a COLLECTIVE_RING_STALL cluster
# event (docs/observability.md) — well above healthy segment times, far
# below the op timeout, so the event fires while the op can still be
# saved (or at least explains the timeout that follows)
_RING_STALL_EVENT_MS = 5000.0


def tag_seq(tag: str) -> Optional[int]:
    """Op sequence number embedded in a collective tag (``"<seq>:..."``);
    None for unsequenced tags (p2p)."""
    head, _, _ = tag.partition(":")
    try:
        return int(head)
    except ValueError:
        return None


def _remaining(deadline: Optional[float]) -> Optional[float]:
    if deadline is None:
        return None
    return max(0.001, deadline - time.monotonic())


class ServeBoard:
    """Rank-local registry of outgoing segments awaiting peer take
    requests (the producer half of a TCP pull link).

    ``publish`` and ``take`` meet in either order: an early take parks a
    :class:`rpc.Deferred` the publish resolves; an early publish stores
    the array for the take to collect.  Entries are keyed by
    ``(taker_rank, tag)``.  Resolutions ride **stable** frames — the
    published array must stay immutable until its frame drains to the
    socket, which :meth:`wait_clear` enforces before the op returns.

    Hygiene mirrors the mailbox fix (ISSUE 6): ``sweep_below`` drops
    entries of finished ops and *fails* parked takes for them, so a peer
    that timed out mid-op gets an error instead of a forever-parked
    request poisoning the next op that reuses the tag space.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._entries: Dict[Tuple[int, str], np.ndarray] = {}
        self._parked: Dict[Tuple[int, str], rpc.Deferred] = {}
        self._undrained = 0
        self._closed = False

    def _sent_one(self) -> None:
        with self._cv:
            self._undrained -= 1
            if self._undrained <= 0:
                self._cv.notify_all()

    def _resolve(self, d: rpc.Deferred, arr: np.ndarray) -> None:
        """Never called with the board lock held: resolving sends the
        reply frame, and a full socket may block that send — blocking
        while holding the lock would wedge every other taker/publisher
        (including the RPC readers servicing this very socket)."""
        d.resolve(arr, stable=True, on_sent=self._sent_one)
        if _TELEMETRY:
            _M_TCP_BYTES.inc(arr.nbytes)

    def publish(self, dst: int, tag: str, arr: np.ndarray) -> None:
        key = (dst, tag)
        with self._cv:
            if self._closed:
                raise RuntimeError("collective group destroyed")
            d = self._parked.pop(key, None)
            if d is not None:
                self._undrained += 1
            else:
                self._entries[key] = arr
                return
        self._resolve(d, arr)

    def take(self, src: int, tag: str) -> rpc.Deferred:
        """Server-handler side: returns the Deferred carrying the reply.
        Runs on the dispatch pool, NOT inline on the connection reader —
        an immediate resolution's reply send may block on a saturated
        socket, and a blocked reader would deadlock the full-duplex
        ring."""
        key = (src, tag)
        d = rpc.Deferred()
        old = None
        with self._cv:
            if self._closed:
                arr = None
                fail = rpc.RpcError("collective group destroyed")
            else:
                fail = None
                arr = self._entries.pop(key, None)
                if arr is not None:
                    self._undrained += 1
                else:
                    # one outstanding take per (src, tag): a duplicate
                    # (peer retry after timeout) supersedes the old
                    # parked request
                    old = self._parked.pop(key, None)
                    self._parked[key] = d
        if fail is not None:
            d.fail(fail)
        elif arr is not None:
            self._resolve(d, arr)
        if old is not None:
            old.fail(rpc.RpcError(f"take {tag!r} superseded"))
        return d

    def sweep_below(self, seq_floor: int) -> None:
        """Drop entries and fail parked takes whose tag belongs to an op
        older than ``seq_floor`` (the group's current op sequence)."""
        with self._cv:
            for key in [k for k in self._entries
                        if (tag_seq(k[1]) or seq_floor) < seq_floor]:
                del self._entries[key]
            stale = [k for k in self._parked
                     if (tag_seq(k[1]) or seq_floor) < seq_floor]
            parked = [self._parked.pop(k) for k in stale]
        for d in parked:
            d.fail(rpc.RpcError("stale collective take (op expired)"))

    def wait_clear(self, deadline: Optional[float]) -> None:
        """Block until every published entry has been taken AND every
        resolved reply frame has drained to the socket — after this the
        caller may mutate (or free) the buffers it published.  Raises
        TimeoutError if a peer never collects (it died mid-op)."""
        with self._cv:
            while self._entries or self._undrained > 0:
                t = _remaining(deadline)
                if t is not None and t <= 0:
                    raise TimeoutError(
                        f"collective op end: {len(self._entries)} "
                        f"published segments never taken and "
                        f"{self._undrained} reply frames undrained "
                        f"(peer dead or wedged)")
                self._cv.wait(min(t, 0.5) if t is not None else 0.5)

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._entries.clear()
            parked = list(self._parked.values())
            self._parked.clear()
            self._undrained = 0
            self._cv.notify_all()
        for d in parked:
            d.fail(rpc.RpcError("collective group destroyed"))


class TcpLink:
    """Pull link to one peer over a pooled duplex connection.

    ``publish`` lands on the *local* board (the peer pulls from us);
    ``request``/``wait`` pull from the peer's board, landing payloads
    through a buffer sink when a destination view is supplied.
    """

    kind = "tcp"

    def __init__(self, group, peer: int):
        self._group = group
        self._peer = peer

    def publish(self, tag: str, arr: np.ndarray,
                deadline: Optional[float] = None) -> None:
        self._group._board.publish(self._peer, tag, arr)

    @staticmethod
    def _make_sink(dest: memoryview, used: list):
        def sink(lens):
            if len(lens) == 1 and lens[0] == len(dest):
                used.append(lens[0])
                return [dest]
            return None  # unexpected shape: fresh storage fallback
        return sink

    def request(self, tag: str, dest: Optional[np.ndarray] = None):
        conn = self._group._conn_to(self._peer)
        payload = {"src": self._group.rank, "tag": tag}
        used: List[int] = []
        sink = None
        if dest is not None and dest.nbytes:
            sink = self._make_sink(dest.data.cast("B"), used)
        fut = conn.call_async("take", payload, buffer_sink=sink)
        return (fut, used)

    def wait(self, handle, deadline: Optional[float]
             ) -> Tuple[np.ndarray, bool]:
        fut, used = handle
        t0 = rtm.now()
        try:
            arr = fut.result(_remaining(deadline))
        except rpc.RemoteError as e:
            raise RuntimeError(
                f"collective take from rank {self._peer} failed: "
                f"{e}") from e
        except ConnectionError as e:
            # hard rank death detected at the transport: emit before
            # unwinding so the event table explains the op failure
            from ray_tpu._private import cluster_events as cev
            cev.emit(cev.COLLECTIVE_RANK_DEATH,
                     f"collective peer rank {self._peer} connection "
                     f"lost mid-op: {e}", severity="ERROR",
                     peer_rank=self._peer)
            raise ConnectionError(
                f"collective peer rank {self._peer} connection lost "
                f"mid-op: {e}") from e
        except Exception as e:
            raise TimeoutError(
                f"collective take from rank {self._peer} timed out "
                f"({e!r})") from e
        ms = (rtm.now() - t0) * 1000.0
        _M_STALL_H.observe(ms)
        _M_STALL.set_max(ms)
        if ms >= _RING_STALL_EVENT_MS:
            # a segment wait this long means the ring is limping (a
            # rank is starved or its link is saturated): one WARNING
            # event per offending wait, next to the stall watermark
            from ray_tpu._private import cluster_events as cev
            cev.emit(cev.COLLECTIVE_RING_STALL,
                     f"waited {ms:.0f}ms on a segment from rank "
                     f"{self._peer}", severity="WARNING",
                     peer_rank=self._peer, stall_ms=round(ms, 1))
        if not isinstance(arr, np.ndarray):
            raise RuntimeError(
                f"collective take from rank {self._peer} returned "
                f"{type(arr).__name__}")
        if _TELEMETRY:
            _M_TCP_BYTES.inc(arr.nbytes)
        return arr, bool(used)

    def finish_op(self, deadline: Optional[float] = None) -> None:
        pass  # reply-frame drain is tracked by the ServeBoard

    def close(self) -> None:
        pass  # pooled conns are owned by the group


class ShmLink:
    """Same-node pair transport over two single-writer/single-reader
    ring channels on the node's shared-memory store segment.

    The outgoing channel is created lazily on first ``publish`` (this
    rank is its single writer); the incoming one is attached lazily on
    first ``wait`` (created by the peer).  Channel object ids derive
    deterministically from (group, incarnation nonce, src, dst), so
    both sides rendezvous without any extra control traffic and a
    re-created group can never collide with a dead incarnation's rings.

    Reads are ZERO-COPY: ``wait`` deserializes straight out of the ring
    slot and defers the slot ack until the view has been consumed (the
    returned array is valid only until the next operation on this
    link — callers that retain it must copy).  A small stash reorders
    out-of-order tags (the ring is FIFO in the *writer's* publish
    order, which pipelining may interleave differently from the
    reader's wait order).

    Writes NEVER block the algorithm thread: a publish that finds the
    ring full queues the segment on a local outbox, which is pumped
    opportunistically during waits and drained (blocking) by
    ``finish_op``.  This is what makes the self-clocked pipelined ring
    deadlock-free — a rank blocked on ring credit would stop *reading*,
    and a cycle of such ranks wedges the whole group (observed at
    64 MiB / 1 MiB segments / 4 ranks before the outbox).
    """

    kind = "shm"

    def __init__(self, store, group_name: str, nonce: str, my_rank: int,
                 peer: int, *, capacity: int, nslots: int,
                 pump_all=None):
        from ray_tpu.experimental import channel as ch
        self._ch = ch
        self._store = store
        self._nonce = nonce
        self._group_name = group_name
        # pump EVERY shm link of the group, not just this one: the ring
        # publishes to the NEXT link while waits park on the PREV link,
        # so a wait that only pumped its own outbox would leave the
        # next-link's queued segments stranded (observed wedge: rank 3
        # parked on its prev with 21 segments outboxed to its next)
        self._pump_all = pump_all if pump_all is not None \
            else (lambda: self._pump_outbox())
        self.rank = my_rank
        self.peer = peer
        self._capacity = capacity
        self._nslots = nslots
        self._writer = None          # ChannelWriter (lazy create)
        self._reader = None          # ChannelReader (lazy attach)
        self._wchan = None
        self._rchan = None
        # out-of-order arrivals, FIFO per tag (p2p reuses tags); owned
        # copies, never ring views
        self._stash: Dict[str, deque] = {}
        self._outbox: deque = deque()    # (tag, arr) awaiting ring credit
        self._pending_ack = None         # deferred ack of the last wait
        self._lock = threading.Lock()

    def _oid(self, src: int, dst: int):
        seed = (f"collective:{self._group_name}:{self._nonce}:"
                f"{src}->{dst}").encode()
        return self._ch.channel_object_id(seed)

    def _ensure_writer(self):
        if self._writer is None:
            chan = self._ch.Channel.create(
                self._store, self._oid(self.rank, self.peer),
                nslots=self._nslots, nreaders=1, capacity=self._capacity)
            chan.spin_yields = 8  # see Channel.spin_yields: N ranks
            self._wchan = chan    # spinning starve the producing rank
            self._writer = self._ch.ChannelWriter(chan)
        return self._writer

    def _ensure_reader(self, deadline: Optional[float]):
        if self._reader is None:
            t = _remaining(deadline)
            chan = self._ch.Channel.attach(
                self._store, self._oid(self.peer, self.rank),
                timeout=t if t is not None else 30.0)
            chan.spin_yields = 8
            self._rchan = chan
            self._reader = self._ch.ChannelReader(chan, 0)
        return self._reader

    def _fire_ack(self) -> None:
        ack, self._pending_ack = self._pending_ack, None
        if ack is not None:
            ack()

    def _write_one(self, tag: str, arr: np.ndarray,
                   timeout: Optional[float]) -> None:
        self._writer.write((tag, arr), timeout=timeout)
        if _TELEMETRY:
            _M_SHM_BYTES.inc(arr.nbytes)

    def _pump_outbox(self) -> None:
        """Move queued segments into the ring while credit lasts; never
        blocks."""
        w = self._writer
        while self._outbox and w is not None and w.writable():
            tag, arr = self._outbox.popleft()
            self._write_one(tag, arr, timeout=0.001)

    def publish(self, tag: str, arr: np.ndarray,
                deadline: Optional[float] = None) -> None:
        """Non-blocking: a full ring queues the segment on the outbox
        (see class docstring — blocking here deadlocks the ring).  The
        caller promises ``arr`` stays valid until ``finish_op``."""
        self._ensure_writer()
        self._pump_outbox()
        if not self._outbox and self._writer.writable():
            self._write_one(tag, arr, timeout=0.001)
        else:
            self._outbox.append((tag, arr))

    def finish_op(self, deadline: Optional[float]) -> None:
        """Op-end drain: release the last read slot and push every
        outboxed segment.  Drains via the group-wide pump (a peer
        parked on one of our OTHER outboxes is what frees this ring,
        transitively) with short sleeps instead of one blocking write;
        a peer that consumed everything it needs leaves nothing here,
        so this converges unless the peer died — then the deadline
        fires."""
        with self._lock:
            self._fire_ack()
            while self._outbox:
                self._pump_all()
                if not self._outbox:
                    break
                t = _remaining(deadline)
                if t is not None and t <= 0.001:
                    raise TimeoutError(
                        f"collective shm drain to rank {self.peer} "
                        f"timed out with {len(self._outbox)} segments "
                        f"queued (peer dead or wedged)")
                time.sleep(0.002)

    def request(self, tag: str, dest: Optional[np.ndarray] = None):
        return tag  # shm reads are ordered pulls; nothing to pre-issue

    def wait(self, handle, deadline: Optional[float]
             ) -> Tuple[np.ndarray, bool]:
        """Returns (array, False).  The array may VIEW the ring slot:
        it is valid only until the next operation on this link — every
        caller consumes (reduces / copies) before touching the link
        again."""
        tag = handle
        with self._lock:
            self._fire_ack()
            self._pump_outbox()
            q = self._stash.get(tag)
            if q:
                arr = q.popleft()
                if not q:
                    del self._stash[tag]
                return arr, False
            r = self._ensure_reader(deadline)
            t0 = rtm.now()
            while True:
                # short read slices so the outbox keeps pumping while we
                # are parked: the peer may be waiting on a segment that
                # is sitting in OUR outbox
                t = _remaining(deadline)
                slice_t = 0.05 if t is None else min(0.05, t)
                try:
                    view, _flags, ack = r.read_zc(timeout=slice_t)
                except self._ch.ChannelTimeoutError:
                    self._pump_all()
                    if t is not None and t <= slice_t:
                        raise TimeoutError(
                            f"collective shm recv of {tag!r} from rank "
                            f"{self.peer} timed out")
                    continue
                got_tag, arr = ser.deserialize(view)
                if got_tag == tag:
                    self._pending_ack = ack
                    break
                # out-of-order: own the payload, release the slot
                self._stash.setdefault(got_tag, deque()).append(
                    np.array(arr, copy=True))
                ack()
            ms = (rtm.now() - t0) * 1000.0
        _M_STALL_H.observe(ms)
        _M_STALL.set_max(ms)
        return arr, False

    def consume_next(self, wanted, deadline: Optional[float]):
        """Arrival-order variant of ``wait``: returns ``(tag, arr)`` for
        the NEXT message whose tag is in ``wanted`` — zero-copy, no
        reorder-stash memcpy for in-window run-ahead.  Same view
        validity contract as ``wait``."""
        with self._lock:
            self._fire_ack()
            self._pump_outbox()
            for t in wanted:
                q = self._stash.get(t)
                if q:
                    arr = q.popleft()
                    if not q:
                        del self._stash[t]
                    return t, arr
            r = self._ensure_reader(deadline)
            t0 = rtm.now()
            while True:
                rem = _remaining(deadline)
                slice_t = 0.05 if rem is None else min(0.05, rem)
                try:
                    view, _flags, ack = r.read_zc(timeout=slice_t)
                except self._ch.ChannelTimeoutError:
                    self._pump_all()
                    if rem is not None and rem <= slice_t:
                        raise TimeoutError(
                            f"collective shm recv (any of "
                            f"{len(wanted)} tags) from rank "
                            f"{self.peer} timed out")
                    continue
                got_tag, arr = ser.deserialize(view)
                if got_tag in wanted:
                    self._pending_ack = ack
                    break
                # beyond-window run-ahead or p2p interleave: own it
                self._stash.setdefault(got_tag, deque()).append(
                    np.array(arr, copy=True))
                ack()
            ms = (rtm.now() - t0) * 1000.0
        _M_STALL_H.observe(ms)
        _M_STALL.set_max(ms)
        return got_tag, arr

    def drop_stashed_below(self, seq_floor: int) -> None:
        """Mailbox-style hygiene for the reorder stash."""
        with self._lock:
            self._fire_ack()
            for t in [t for t in self._stash
                      if (tag_seq(t) or seq_floor) < seq_floor]:
                del self._stash[t]

    def close(self) -> None:
        # poison FIRST, without the lock: a parked wait holds the lock
        # for its whole blocking loop, and the poison stamp is exactly
        # what makes it unwind — taking the lock first would block
        # destroy behind the op deadline
        for chan in (self._wchan, self._rchan):
            if chan is not None:
                try:
                    chan.poison(self._ch.POISON_TEARDOWN)
                except Exception:
                    pass
        with self._lock:   # waits out the unwinding parked op
            self._pending_ack = None
            self._outbox.clear()
            wchan, self._wchan = self._wchan, None
            rchan, self._rchan = self._rchan, None
            self._writer = self._reader = None
        for chan in (wchan, rchan):
            if chan is not None:
                try:
                    chan.close()
                except Exception:
                    pass
        if wchan is not None:
            wchan.delete()  # creator removes its own ring object


class ShmArena:
    """Node-local flat allreduce plane: when EVERY rank of a group
    lives on one node, the segmented ring is pure overhead — each rank
    instead writes its flat input ONCE into its persistent shared-
    memory slab, reduces its own chunk directly from all peers' mapped
    slabs into a shared result slab (single writer per region,
    channel-style sealed-then-mutated), and copies the finished result
    out.  Per-rank data movement is one input write + one chunk reduce
    + one result read, all at memory bandwidth with no per-segment
    protocol, which beats the shm ring ~2x on CPU-starved hosts
    (docs/collective.md).

    Slabs are PERSISTENT and reused across ops (keyed by rank and a
    power-of-two size bucket every rank derives identically from the
    tensor size): on this class of VM a first-touch tmpfs page fault
    runs ~80x slower than a warm write (the object_store_prefault
    rationale), so per-op object churn would pay cold faults on every
    single op.

    Synchronization rides a tiny control object (u64 poison + per-rank
    u64 input-ready / reduced / copied-out words, one writer each,
    x86-TSO publication ordering exactly like experimental/channel.py),
    counted by an ARENA-LOCAL op number (the group's op sequence also
    advances on non-arena ops).  The copied-out word is load-bearing:
    before touching any slab for op N, a rank waits until every peer
    copied op N-1's result out — without it, a fast rank's next input/
    region write races a lagging rank's result read (silent
    corruption; no test with driver-side barriers between ops would
    catch it, but back-to-back sync_gradients calls would hit it).
    """

    def __init__(self, store, group_name: str, nonce: str, rank: int,
                 ranks: List[int]):
        self._store = store
        self._group = group_name
        self._nonce = nonce
        self.rank = rank
        self._ranks = sorted(ranks)
        self._idx = self._ranks.index(rank)
        self._leader = self._ranks[0]
        self._ctl = None             # pinned memoryview of the control obj
        self._slabs: Dict[Tuple[int, int], Tuple[Any, memoryview]] = {}
        self._pending_delete: List[Any] = []
        self._op = 0                 # arena-local op number (all ranks
        self._closed = False         # call arena ops in the same order)

    def _oid(self, kind: str, a: int = 0, b: int = 0):
        from ray_tpu.experimental.channel import channel_object_id
        seed = (f"colarena:{self._group}:{self._nonce}:"
                f"{kind}:{a}:{b}").encode()
        return channel_object_id(seed)

    def _ensure_ctl(self, deadline: Optional[float]):
        if self._ctl is not None:
            return self._ctl
        oid = self._oid("ctl")
        size = 8 + 24 * len(self._ranks)
        if self.rank == self._leader:
            buf = self._store.create(oid, size, meta=0, allow_evict=False)
            buf[:size] = bytes(size)
            buf.release()
            self._store.seal(oid)
        t = _remaining(deadline)
        res = self._store.get(oid, timeout=t if t is not None else 30.0)
        if res is None:
            raise TimeoutError("collective shm arena: control object "
                               "never appeared (leader dead?)")
        self._ctl = res[0]
        return self._ctl

    def _slab(self, kind: str, r: int, bucket: int,
              deadline: Optional[float]) -> memoryview:
        """Attach (or create, if it is ours) the persistent slab for
        ``(kind, r, bucket)``; cached pinned view."""
        key_r = r if kind == "in" else -1
        cached = self._slabs.get((key_r, bucket))
        if cached is not None:
            return cached[1]
        oid = self._oid(kind, r, bucket)
        mine = (kind == "in" and r == self.rank) or \
               (kind == "res" and self.rank == self._leader)
        if mine:
            try:
                buf = self._store.create(oid, bucket, meta=0,
                                         allow_evict=False)
                buf.release()
                self._store.seal(oid)
            except FileExistsError:
                pass  # survived from an earlier attach cycle
            except Exception:
                # store too full for a slab (the capacity gate is
                # deterministic across ranks but blind to occupancy):
                # poison so PEERS parked on our words unwind in
                # seconds, not the op deadline; the group marks the
                # arena broken and falls back to the ring path
                self.poison()
                raise
            self._pending_delete_on_close(oid)
        t = _remaining(deadline)
        res = self._store.get(oid, timeout=t if t is not None else 60.0)
        if res is None:
            raise TimeoutError(
                f"collective shm arena: slab of rank {r} never "
                f"appeared (peer dead or its store create failed)")
        self._slabs[(key_r, bucket)] = (oid, res[0])
        return res[0]

    def _pending_delete_on_close(self, oid) -> None:
        if oid not in self._pending_delete:
            self._pending_delete.append(oid)

    def poison(self) -> None:
        """Stamp the control word so every parked arena wait unwinds
        promptly (destroy, or a rank's slab allocation failing)."""
        import struct
        if self._ctl is not None:
            try:
                struct.pack_into("<Q", self._ctl, 0, 1)
            except ValueError:
                pass

    def _poisoned(self) -> bool:
        import struct
        return (self._ctl is not None
                and struct.unpack_from("<Q", self._ctl, 0)[0] != 0)

    def _wait_word(self, word: int, seq: int,
                   deadline: Optional[float], what: str) -> None:
        import struct
        delay = 2e-5
        while struct.unpack_from("<Q", self._ctl, word)[0] < seq:
            if self._poisoned():
                raise RuntimeError(
                    "collective shm arena poisoned (group destroyed or "
                    "a rank's slab allocation failed) mid-op")
            t = _remaining(deadline)
            if t is not None and t <= 0.001:
                raise TimeoutError(
                    f"collective shm arena: {what} never ready for op "
                    f"{seq} (peer dead or wedged)")
            time.sleep(delay)
            delay = min(delay * 2, 0.002)

    def _in_word(self, idx: int) -> int:
        return 8 + 24 * idx

    def _red_word(self, idx: int) -> int:
        return 8 + 24 * idx + 8

    def _out_word(self, idx: int) -> int:
        return 8 + 24 * idx + 16

    @staticmethod
    def bucket_of(nbytes: int) -> int:
        b = 1 << 16
        while b < nbytes:
            b <<= 1
        return b

    def allreduce(self, src: np.ndarray, out: np.ndarray, reducer,
                  deadline: Optional[float]) -> None:
        """``src``: this rank's flat contiguous input (read only — no
        private working copy needed, saving one full heap copy per op);
        ``out``: flat destination the finished result lands in."""
        import struct
        ctl = self._ensure_ctl(deadline)
        m = len(self._ranks)
        self._op += 1
        seq = self._op
        bucket = self.bucket_of(src.nbytes)
        # 0. cross-op gate (see class docstring): every peer must have
        # finished COPYING the previous result out before any slab of
        # this op may be written — a peer's out-word implies its red
        # and in words, so this one wait covers input-slab reuse too
        for i in range(m):
            self._wait_word(self._out_word(i), seq - 1, deadline,
                            f"rank {self._ranks[i]} prev-op copy-out")
        # 1. write my input into my persistent slab, publish via seq word
        mine = self._slab("in", self.rank, bucket, deadline)
        np.copyto(np.frombuffer(mine, np.uint8, count=src.nbytes),
                  src.view(np.uint8))
        struct.pack_into("<Q", ctl, self._in_word(self._idx), seq)
        if _TELEMETRY:
            _M_SHM_BYTES.inc(src.nbytes)
        # 2. reduce MY chunk from every peer slab straight into the
        # shared result slab (single writer per region)
        res_np = np.frombuffer(self._slab("res", 0, bucket, deadline),
                               dtype=src.dtype, count=src.size)
        bounds = _chunk_bounds(src.size, m)
        a, b = bounds[self._idx]
        if b > a:
            np.copyto(res_np[a:b], src[a:b])
        t0 = rtm.now()
        for i, r in enumerate(self._ranks):
            if r == self.rank or b <= a:
                continue
            self._wait_word(self._in_word(i), seq, deadline,
                            f"rank {r} input")
            arr = np.frombuffer(self._slab("in", r, bucket, deadline),
                                dtype=src.dtype, count=src.size)
            reducer(res_np[a:b], arr[a:b], out=res_np[a:b])
        # 3. stamp my reduced word LAST (x86-TSO publication), then
        # copy each region out the moment its producer stamps — the
        # copy of early chunks overlaps the stragglers' reduces
        struct.pack_into("<Q", ctl, self._red_word(self._idx), seq)
        for i in range(m):
            self._wait_word(self._red_word(i), seq, deadline,
                            f"rank {self._ranks[i]} chunk")
            ca, cb = bounds[i]
            if cb > ca:
                np.copyto(out[ca:cb], res_np[ca:cb])
        # copied out: the slabs may be reused by the next op
        struct.pack_into("<Q", ctl, self._out_word(self._idx), seq)
        _M_STALL_H.observe((rtm.now() - t0) * 1000.0)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.poison()  # parked waiters unwind
        for oid, view in self._slabs.values():
            try:
                view.release()
                self._store.release(oid)
            except Exception:
                pass
        self._slabs.clear()
        if self._ctl is not None:
            try:
                self._ctl.release()
                self._store.release(self._oid("ctl"))
            except Exception:
                pass
            self._ctl = None
        if self.rank == self._leader:
            self._pending_delete_on_close(self._oid("ctl"))
        # best-effort: pinned-elsewhere slabs are freed when the last
        # participant closes (delete refuses while pinned)
        for oid in self._pending_delete:
            try:
                self._store.delete(oid)
            except Exception:
                pass
        self._pending_delete = []


def _chunk_bounds(nelem: int, m: int) -> List[Tuple[int, int]]:
    """np.array_split boundaries: m contiguous ranges covering nelem
    (identical on every rank; empty ranges when m > nelem).  The ONE
    definition both endpoints of every link segment by."""
    base, rem = divmod(nelem, m)
    bounds, off = [], 0
    for k in range(m):
        sz = base + (1 if k < rem else 0)
        bounds.append((off, off + sz))
        off += sz
    return bounds


class Window:
    """Sliding-window executor over ordered segment receives.

    ``push`` issues one request; once ``depth`` are outstanding it
    completes one (wait -> completion callback) before issuing more.
    Completion callbacks run on the calling thread — the per-segment
    chaining (reduce + publish of the next ring step) the pipelined
    ring is built from.

    TCP items complete in issue order (their replies land concurrently
    via the connection reader regardless, so head-blocking loses
    nothing, and the staging-slot rotation relies on it).  shm items
    complete in ARRIVAL order within their link: the ring is FIFO in
    the producer's publish order, which pipelining interleaves
    differently from our issue order — dispatching whatever arrives
    next consumes every message zero-copy instead of paying a
    reorder-stash memcpy per out-of-order segment.
    """

    def __init__(self, depth: int, deadline: Optional[float]):
        self.depth = max(1, depth)
        self.deadline = deadline
        self._tcp: deque = deque()       # (link, handle, done) FIFO
        self._shm: Dict[Any, Dict[str, Any]] = {}  # link -> {tag: done}
        self._order: deque = deque()     # None = tcp head, else shm link
        self._outstanding = 0

    def push(self, link, tag: str, dest: Optional[np.ndarray],
             done) -> None:
        while self._outstanding >= self.depth:
            self._complete_one()
        if isinstance(link, ShmLink):
            self._shm.setdefault(link, {})[tag] = done
            self._order.append(link)
        else:
            h = link.request(tag, dest)
            self._tcp.append((link, h, done))
            self._order.append(None)
        self._outstanding += 1

    def drain(self) -> None:
        while self._outstanding:
            self._complete_one()

    def _complete_one(self) -> None:
        ent = self._order.popleft()
        if ent is None:
            link, h, done = self._tcp.popleft()
            arr, in_place = link.wait(h, self.deadline)
            done(arr, in_place)
        else:
            cbs = self._shm[ent]
            tag, arr = ent.consume_next(cbs.keys(), self.deadline)
            done = cbs.pop(tag)
            done(arr, False)
        self._outstanding -= 1
