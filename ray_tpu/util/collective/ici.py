"""In-graph (ICI) collectives: XLA ops over a device mesh.

On TPU the intra-slice fabric is only reachable from inside compiled
programs — there is no host-initiated NCCL analog. These helpers wrap the
XLA collectives (`psum`, `all_gather`, `ppermute`, `psum_scatter`) in
`shard_map` over a :class:`jax.sharding.Mesh` so callers get an
imperative-looking API whose body compiles to ICI traffic.

This is the TPU replacement for the reference's NCCLGroup
(/root/reference/python/ray/util/collective/collective_group/
nccl_collective_group.py:127): the reference moves GPU tensors with NCCL
from the host; we stage arrays once and let XLA schedule the transfer.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# shard_map promotion shim (_shard_map vs jax.experimental.shard_map)
from ray_tpu._private.jax_compat import shard_map as _shard_map


def allreduce(x: jax.Array, mesh: Mesh, axis: str = "data") -> jax.Array:
    """Allreduce an array whose leading dim is sharded over ``axis``;
    every shard ends up holding the sum of all shards."""
    spec = P(axis)

    @functools.partial(_shard_map, mesh=mesh, check_vma=False, in_specs=spec, out_specs=spec)
    def _ar(shard):
        total = jax.lax.psum(shard.sum(axis=0, keepdims=True), axis)
        return jnp.broadcast_to(total, shard.shape)

    return jax.jit(_ar)(x)


def psum(x: jax.Array, mesh: Mesh, axis: str = "data") -> jax.Array:
    """Sum replicated-per-device values over the mesh axis; returns the
    reduced value replicated everywhere (classic gradient allreduce)."""

    @functools.partial(
        _shard_map, mesh=mesh, check_vma=False, in_specs=P(axis), out_specs=P())
    def _psum(shard):
        return jax.lax.psum(shard, axis)

    n = mesh.shape[axis]
    stacked = x if x.shape and x.shape[0] == n else \
        jnp.broadcast_to(x[None], (n,) + x.shape)
    return jax.jit(_psum)(stacked)


def all_gather(x: jax.Array, mesh: Mesh, axis: str = "data") -> jax.Array:
    """Gather shards along the leading dim onto every device."""
    @functools.partial(
        _shard_map, mesh=mesh, check_vma=False, in_specs=P(axis), out_specs=P())
    def _ag(shard):
        return jax.lax.all_gather(shard, axis, axis=0, tiled=True)

    return jax.jit(_ag)(x)


def reduce_scatter(x: jax.Array, mesh: Mesh,
                   axis: str = "data") -> jax.Array:
    """Treat each device's shard (leading dim 1 of an ``axis``-sharded
    array) as its contribution; elementwise-reduce the contributions and
    leave each device with its 1/N piece of the sum. The contribution size
    must be divisible by the axis size."""
    @functools.partial(
        _shard_map, mesh=mesh, check_vma=False, in_specs=P(axis),
        out_specs=P(axis))
    def _rs(shard):
        flat = shard.reshape((-1,))
        piece = jax.lax.psum_scatter(flat, axis, scatter_dimension=0,
                                     tiled=True)
        return piece[None]

    return jax.jit(_rs)(x)


def ppermute(x: jax.Array, mesh: Mesh, axis: str = "data",
             shift: int = 1) -> jax.Array:
    """Neighbor exchange around the ring (the building block of ring
    attention / pipeline transfers)."""
    n = mesh.shape[axis]
    perm = [(i, (i + shift) % n) for i in range(n)]

    @functools.partial(
        _shard_map, mesh=mesh, check_vma=False, in_specs=P(axis), out_specs=P(axis))
    def _pp(shard):
        return jax.lax.ppermute(shard, axis, perm)

    return jax.jit(_pp)(x)


def device_put_sharded(x, mesh: Mesh, axis: Optional[str] = "data"):
    """Stage a host array onto the mesh, sharded along the leading dim."""
    spec = P(axis) if axis else P()
    return jax.device_put(x, NamedSharding(mesh, spec))
