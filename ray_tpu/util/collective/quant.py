"""Wire codecs for the DCN collective plane (docs/collective.md).

EQuARX-style block-scaled int8 quantization (arXiv:2506.17615): each
wire segment is encoded as one int8 value per element plus one fp32
scale per ``block`` elements, cutting ring traffic ~4x for fp32
tensors (wire = n + 4*ceil(n/block) bytes vs 4n).  Accumulation stays
in the caller's fp32 master buffer — the codec only touches bytes that
cross a link, so numerics degrade by a bounded per-hop rounding error
instead of drifting with tensor magnitude.

Numerics contract (the bound the tier-1 gate asserts): one
encode/decode round trip perturbs each element by at most
``blockmax / 254`` (symmetric round-to-nearest over 255 int8 steps,
``blockmax`` = max |x| over the element's block).  A ring allreduce
re-encodes each partial sum once per reduce-scatter hop and encodes
the final value once for allgather (forwarded hops ship the encoded
bytes verbatim), so the end-to-end absolute error per element is
bounded by ``world_size * max_running_blockmax / 254`` — relative to
the reduced block max, roughly ``world_size / 254``.

The wire layout is self-describing to both endpoints WITHOUT a header:
every link pair derives identical segmentation (`_chunk_bounds` +
segment size), so the element count and dtype are known at decode time.

    [ fp32 scales: 4 * nblocks bytes | int8 payload: nelem bytes ]
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class Int8Codec:
    """Block-scaled symmetric int8 wire codec for float tensors."""

    name = "int8"

    def __init__(self, block: int = 256):
        self.block = max(1, int(block))

    def nblocks(self, nelem: int) -> int:
        return -(-nelem // self.block)

    def wire_nbytes(self, nelem: int) -> int:
        """Encoded size of an ``nelem``-element segment — deterministic,
        so the receiver can pre-size its staging buffer (TCP recv_into
        needs an exact-length sink)."""
        return 4 * self.nblocks(nelem) + nelem

    def encode(self, arr: np.ndarray) -> np.ndarray:
        """fp32/fp64 segment -> uint8 wire buffer (fresh array)."""
        n = arr.size
        nb = self.nblocks(n)
        pad = nb * self.block - n
        x = np.asarray(arr, np.float32).reshape(-1)
        if pad:
            x = np.concatenate([x, np.zeros(pad, np.float32)])
        blocks = x.reshape(nb, self.block)
        scale = np.max(np.abs(blocks), axis=1) / 127.0
        # all-zero blocks: scale 1.0 encodes/decodes exact zeros
        safe = np.where(scale > 0.0, scale, np.float32(1.0))
        q = np.rint(blocks / safe[:, None]).astype(np.int8)
        wire = np.empty(4 * nb + n, np.uint8)
        wire[:4 * nb] = safe.astype(np.float32).view(np.uint8)
        wire[4 * nb:] = q.reshape(-1)[:n].view(np.uint8)
        return wire

    def decode(self, wire: np.ndarray, nelem: int,
               dtype=np.float32,
               out: Optional[np.ndarray] = None) -> np.ndarray:
        """uint8 wire buffer -> ``nelem`` decoded elements.  ``wire``
        may view transport storage (shm ring slot / staging buffer):
        the result is always fresh (or ``out``), never a view."""
        nb = self.nblocks(nelem)
        w = np.asarray(wire, np.uint8).reshape(-1)
        scale = w[:4 * nb].view(np.float32)
        q = w[4 * nb:4 * nb + nelem].view(np.int8)
        pad = nb * self.block - nelem
        if pad:
            q = np.concatenate([q, np.zeros(pad, np.int8)])
        vals = (q.reshape(nb, self.block).astype(np.float32)
                * scale[:, None]).reshape(-1)[:nelem]
        if out is not None:
            np.copyto(out, vals.astype(dtype, copy=False))
            return out
        return vals.astype(dtype, copy=False)


_CODECS = {"int8": Int8Codec}


def get_codec(quantize: Optional[str], block: int):
    """Resolve a ``quantize=`` argument to a codec instance (None passes
    through — the fp32 plane is untouched)."""
    if quantize is None:
        return None
    cls = _CODECS.get(quantize)
    if cls is None:
        raise ValueError(
            f"unknown collective wire codec {quantize!r} "
            f"(supported: {sorted(_CODECS)})")
    return cls(block)
