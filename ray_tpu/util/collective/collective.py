"""Host-level (DCN) collective groups: ring allreduce & friends over TCP.

Design notes (vs the reference's NCCL/Gloo groups,
/root/reference/python/ray/util/collective/collective_group/):

- Rendezvous rides the GCS KV (the reference uses a named actor store):
  each rank publishes its listening address under
  ``collective/<group>/<rank>`` and polls for the full ring.
- allreduce/reducescatter/allgather use the bandwidth-optimal ring
  algorithm (2*(N-1) steps, each moving 1/N of the data), the same
  schedule NCCL uses — here over host sockets because on TPU the
  intra-slice fabric (ICI) is only reachable in-graph via XLA.
- Tensors are numpy arrays (JAX arrays are converted on the way in and
  returned as numpy; callers on the hot path should use in-graph
  collectives instead).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu._private import rpc
from ray_tpu.runtime.core_worker import get_global_worker


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


_REDUCERS = {
    ReduceOp.SUM: np.add,
    ReduceOp.PRODUCT: np.multiply,
    ReduceOp.MIN: np.minimum,
    ReduceOp.MAX: np.maximum,
}

_groups: Dict[str, "_Group"] = {}
_groups_lock = threading.Lock()


def _as_numpy(tensor: Any) -> np.ndarray:
    if isinstance(tensor, np.ndarray):
        return tensor
    return np.asarray(tensor)


class _Mailbox:
    """Incoming messages keyed by (src_rank, tag)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._msgs: Dict[Tuple[int, str], List[Any]] = {}

    def put(self, src: int, tag: str, payload: Any) -> None:
        with self._cv:
            self._msgs.setdefault((src, tag), []).append(payload)
            self._cv.notify_all()

    def get(self, src: int, tag: str, timeout: float) -> Any:
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                q = self._msgs.get((src, tag))
                if q:
                    msg = q.pop(0)
                    if not q:
                        del self._msgs[(src, tag)]
                    return msg
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"collective recv (src={src}, tag={tag}) timed out")
                self._cv.wait(remaining)


class _Group:
    def __init__(self, name: str, world_size: int, rank: int,
                 timeout: float = 60.0):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.timeout = timeout
        self._mailbox = _Mailbox()
        self._server = rpc.Server(self._handle)
        self._conns: Dict[int, rpc.Connection] = {}
        self._conns_lock = threading.Lock()
        self._seq = 0
        self._rendezvous()

    # ------------------------------------------------------------ plumbing
    def _handle(self, conn: rpc.Connection, method: str, p: Any) -> Any:
        if method == "msg":
            self._mailbox.put(p["src"], p["tag"], p["data"])
            return True
        raise rpc.RpcError(f"collective: unknown method {method}")

    def _rendezvous(self) -> None:
        import json
        gcs = get_global_worker().gcs
        key = f"collective/{self.name}/{self.rank}"
        gcs.kv_put(key, json.dumps(list(self._server.address)).encode())
        self._addrs: Dict[int, Tuple[str, int]] = {}
        deadline = time.monotonic() + self.timeout
        while len(self._addrs) < self.world_size:
            for r in range(self.world_size):
                if r in self._addrs:
                    continue
                raw = gcs.kv_get(f"collective/{self.name}/{r}")
                if raw is not None:
                    host, port = json.loads(raw.decode())
                    self._addrs[r] = (host, int(port))
            if len(self._addrs) < self.world_size:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"collective group {self.name!r}: only "
                        f"{len(self._addrs)}/{self.world_size} ranks showed")
                time.sleep(0.05)

    def _conn_to(self, peer: int) -> rpc.Connection:
        with self._conns_lock:
            conn = self._conns.get(peer)
            if conn is None or conn.closed:
                conn = rpc.connect(self._addrs[peer])
                self._conns[peer] = conn
            return conn

    def _send(self, peer: int, tag: str, data: Any) -> None:
        self._conn_to(peer).call(
            "msg", {"src": self.rank, "tag": tag, "data": data},
            timeout=self.timeout)

    def _recv(self, peer: int, tag: str) -> Any:
        return self._mailbox.get(peer, tag, self.timeout)

    def _next_tag(self, opname: str) -> str:
        # all ranks call collectives in the same order => same sequence
        self._seq += 1
        return f"{opname}:{self._seq}"

    # ---------------------------------------------------------- primitives
    def send(self, tensor: Any, dst: int, tag: str = "p2p") -> None:
        self._send(dst, tag, _as_numpy(tensor))

    def recv(self, src: int, tag: str = "p2p") -> np.ndarray:
        return self._recv(src, tag)

    def broadcast(self, tensor: Any, src: int) -> np.ndarray:
        tag = self._next_tag("bcast")
        if self.world_size == 1:
            return _as_numpy(tensor)
        # ring forward: src -> src+1 -> ... -> src-1
        if self.rank == src:
            out = _as_numpy(tensor)
        else:
            out = self._recv((self.rank - 1) % self.world_size, tag)
        nxt = (self.rank + 1) % self.world_size
        if nxt != src:
            self._send(nxt, tag, out)
        return out

    def allreduce(self, tensor: Any, op: str = ReduceOp.SUM) -> np.ndarray:
        """Ring allreduce: reduce-scatter then allgather, 2(N-1) steps."""
        x = _as_numpy(tensor)
        n = self.world_size
        if n == 1:
            return x.copy()
        tag = self._next_tag("ar")
        reducer = _REDUCERS[op]
        flat = x.reshape(-1).astype(x.dtype, copy=True)
        chunks = np.array_split(flat, n)
        nxt, prv = (self.rank + 1) % n, (self.rank - 1) % n
        # reduce-scatter: after N-1 steps, rank r owns the fully-reduced
        # chunk (r+1) % n
        for step in range(n - 1):
            send_idx = (self.rank - step) % n
            recv_idx = (self.rank - step - 1) % n
            self._send(nxt, f"{tag}:rs{step}", chunks[send_idx])
            incoming = self._recv(prv, f"{tag}:rs{step}")
            chunks[recv_idx] = reducer(chunks[recv_idx], incoming)
        # allgather: circulate the reduced chunks
        for step in range(n - 1):
            send_idx = (self.rank - step + 1) % n
            recv_idx = (self.rank - step) % n
            self._send(nxt, f"{tag}:ag{step}", chunks[send_idx])
            chunks[recv_idx] = self._recv(prv, f"{tag}:ag{step}")
        out = np.concatenate(chunks).reshape(x.shape)
        return out

    def reduce(self, tensor: Any, dst: int,
               op: str = ReduceOp.SUM) -> np.ndarray:
        """Reduce to ``dst`` (star gather; fine for control-plane sizes)."""
        x = _as_numpy(tensor)
        tag = self._next_tag("red")
        if self.world_size == 1:
            return x.copy()
        if self.rank == dst:
            acc = x.astype(x.dtype, copy=True)
            reducer = _REDUCERS[op]
            for r in range(self.world_size):
                if r == dst:
                    continue
                acc = reducer(acc, self._recv(r, tag))
            return acc
        self._send(dst, tag, x)
        return x

    def allgather(self, tensor: Any) -> List[np.ndarray]:
        x = _as_numpy(tensor)
        n = self.world_size
        if n == 1:
            return [x.copy()]
        tag = self._next_tag("allg")
        nxt, prv = (self.rank + 1) % n, (self.rank - 1) % n
        parts: List[Optional[np.ndarray]] = [None] * n
        parts[self.rank] = x
        for step in range(n - 1):
            send_idx = (self.rank - step) % n
            self._send(nxt, f"{tag}:{step}", parts[send_idx])
            recv_idx = (self.rank - step - 1) % n
            parts[recv_idx] = self._recv(prv, f"{tag}:{step}")
        return [p for p in parts]

    def reducescatter(self, tensor: Any,
                      op: str = ReduceOp.SUM) -> np.ndarray:
        """Each rank gets its reduced 1/N shard (ring reduce-scatter)."""
        x = _as_numpy(tensor)
        n = self.world_size
        if n == 1:
            return x.copy()
        tag = self._next_tag("rs")
        reducer = _REDUCERS[op]
        flat = x.reshape(-1).astype(x.dtype, copy=True)
        chunks = np.array_split(flat, n)
        nxt, prv = (self.rank + 1) % n, (self.rank - 1) % n
        # offset -1 vs allreduce's schedule so rank r finishes owning chunk
        # r (each rank gets *its own* reduced shard, matching allgather's
        # index==rank convention)
        for step in range(n - 1):
            send_idx = (self.rank - step - 1) % n
            recv_idx = (self.rank - step - 2) % n
            self._send(nxt, f"{tag}:{step}", chunks[send_idx])
            incoming = self._recv(prv, f"{tag}:{step}")
            chunks[recv_idx] = reducer(chunks[recv_idx], incoming)
        return chunks[self.rank]

    def barrier(self) -> None:
        self.allreduce(np.zeros(1, np.float32))

    def destroy(self) -> None:
        try:
            gcs = get_global_worker().gcs
            gcs.kv_del(f"collective/{self.name}/{self.rank}")
        except Exception:
            pass
        with self._conns_lock:
            for conn in self._conns.values():
                try:
                    conn.close()
                except Exception:
                    pass
            self._conns.clear()
        self._server.stop()


# -------------------------------------------------------------- public API
def init_collective_group(world_size: int, rank: int,
                          backend: str = "dcn",
                          group_name: str = "default",
                          timeout: float = 60.0) -> None:
    """Join a collective group. Every participating process calls this with
    its own rank; returns once the full ring has rendezvoused."""
    if backend not in ("dcn", "gloo", "ring"):
        raise ValueError(
            f"backend {backend!r} not supported; TPU in-graph collectives "
            "are compiled via pjit (see ray_tpu.util.collective.ici)")
    if not (0 <= rank < world_size):
        raise ValueError(f"rank {rank} out of range [0, {world_size})")
    with _groups_lock:
        if group_name in _groups:
            raise RuntimeError(f"group {group_name!r} already initialized")
    g = _Group(group_name, world_size, rank, timeout)
    with _groups_lock:
        _groups[group_name] = g


def _get(group_name: str) -> _Group:
    with _groups_lock:
        g = _groups.get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized")
    return g


def is_group_initialized(group_name: str = "default") -> bool:
    with _groups_lock:
        return group_name in _groups


def destroy_collective_group(group_name: str = "default") -> None:
    with _groups_lock:
        g = _groups.pop(group_name, None)
    if g is not None:
        g.destroy()


def get_rank(group_name: str = "default") -> int:
    return _get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _get(group_name).world_size


def allreduce(tensor: Any, group_name: str = "default",
              op: str = ReduceOp.SUM) -> np.ndarray:
    return _get(group_name).allreduce(tensor, op)


def reduce(tensor: Any, dst_rank: int = 0, group_name: str = "default",
           op: str = ReduceOp.SUM) -> np.ndarray:
    return _get(group_name).reduce(tensor, dst_rank, op)


def broadcast(tensor: Any, src_rank: int = 0,
              group_name: str = "default") -> np.ndarray:
    return _get(group_name).broadcast(tensor, src_rank)


def allgather(tensor: Any, group_name: str = "default") -> List[np.ndarray]:
    return _get(group_name).allgather(tensor)


def reducescatter(tensor: Any, group_name: str = "default",
                  op: str = ReduceOp.SUM) -> np.ndarray:
    return _get(group_name).reducescatter(tensor, op)


def send(tensor: Any, dst_rank: int, group_name: str = "default") -> None:
    _get(group_name).send(tensor, dst_rank)


def recv(src_rank: int, group_name: str = "default") -> np.ndarray:
    return _get(group_name).recv(src_rank)


def barrier(group_name: str = "default") -> None:
    _get(group_name).barrier()
